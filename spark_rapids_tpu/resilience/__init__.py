"""Stage-level fault domains (SURVEY.md §2.3, §5.3; PAPER.md §2.3).

The reference plugin survives production because every GPU stage runs
inside a retry/spill state machine and anything it cannot run routes back
to CPU Spark.  This package is the TPU port of that posture, generalized
beyond the OOM slice that memory/retry.py already covers:

  * classify.py — one taxonomy for every failure escaping a stage:
    device OOM (delegate to memory/retry.py spill+retry), deterministic
    (compile / lowering / unsupported — never retried), transient
    (bounded retry with exponential backoff + jitter), and propagate
    (semantic errors like ANSI overflow that must surface unchanged).
  * faults.py — the fault-injection harness (force_retry_oom generalized):
    inject a compile failure, a transient runtime error, or a poisoned
    output batch at any named operator, deterministically seeded.
  * breaker.py — a process-global circuit breaker keyed by (operator
    class, expression fingerprint): stages that failed deterministically
    are tagged to the CPU oracle at *plan* time for subsequent queries,
    with TTL + half-open probing so a fixed stage returns to TPU.
  * fallback.py — runtime per-stage CPU fallback: synthesize the failing
    operator's plan-node twin over its materialized TPU inputs and run it
    through cpu/oracle.py, then continue the query on TPU.
  * domain.py — the per-operator wrapper (installed by exec/base.py)
    that ties the four together around every execute_columnar iterator.
"""
from spark_rapids_tpu.resilience.classify import (
    DETERMINISTIC,
    DEVICE_OOM,
    PROPAGATE,
    TRANSIENT,
    classify_failure,
    exception_chain,
    is_device_oom,
)
from spark_rapids_tpu.resilience.faults import (
    InjectedCompileError,
    InjectedDecodeError,
    InjectedFileCorruption,
    InjectedTransientError,
    active_faults,
    clear_faults,
    inject_fault,
)
from spark_rapids_tpu.resilience.breaker import (
    get_breaker,
    reset_breaker,
)

__all__ = [
    "DETERMINISTIC", "DEVICE_OOM", "PROPAGATE", "TRANSIENT",
    "classify_failure", "exception_chain", "is_device_oom",
    "InjectedCompileError", "InjectedTransientError",
    "clear_faults", "inject_fault",
    "get_breaker", "reset_breaker",
]
