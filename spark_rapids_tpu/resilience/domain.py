"""The per-operator fault domain — installed around every exec iterator.

Reference analog: RmmRapidsRetryIterator wraps per-batch work in the retry
state machine (SURVEY.md §2.3); here the wrapper (hooked in by
``exec/base.py.__init_subclass__``) also owns the non-OOM failure classes:

  * DEVICE_OOM      -> spill everything unpinned and restart the operator,
                       bounded by spark.rapids.tpu.retry.maxAttempts
                       (delegating pressure release to memory/spill.py —
                       the same valve with_retry uses).
  * TRANSIENT       -> restart with exponential backoff + jitter, bounded
                       by spark.rapids.tpu.resilience.maxTransientRetries.
  * DETERMINISTIC   -> record the failure with the circuit breaker, then
                       run the stage's CPU twin via fallback.py and keep
                       the query going; re-raise when no twin exists (the
                       parent domain falls back at its granularity).
  * PROPAGATE       -> re-raise unchanged (ANSI errors are results).

Restarts replay the operator from scratch and fast-forward past batches
already yielded downstream — sound because stage programs are
deterministic functions of their (re-executed) inputs.  The CPU fallback
only engages before the first yield OR re-emits the full result when
nothing was yielded yet; a mid-stream deterministic failure after yields
re-raises (oracle row order is not guaranteed to match the device's, so
splicing rows would risk duplicates) — the session-level whole-query
fallback still catches it."""
from __future__ import annotations

import random
import time
from typing import Iterator

from spark_rapids_tpu.diagnostics import context as DIAG_CTX
from spark_rapids_tpu.resilience import classify as CL
from spark_rapids_tpu.resilience import faults


def _diag_event(kind: str, op_name: str, detail: str = "") -> None:
    """One ambient check; records a resilience event when a
    QueryDiagnostics recorder is active (ISSUE 3)."""
    rec = DIAG_CTX.RECORDER
    if rec is not None:
        rec.resilience(kind, op_name, detail)


def _confs():
    from spark_rapids_tpu.config import (
        RESILIENCE_BACKOFF_BASE_MS,
        RESILIENCE_BREAKER_THRESHOLD,
        RESILIENCE_ENABLED,
        RESILIENCE_MAX_TRANSIENT_RETRIES,
        RESILIENCE_RUNTIME_FALLBACK,
        RETRY_MAX_ATTEMPTS,
        get_conf,
    )

    c = get_conf()
    return {
        "enabled": bool(c.get(RESILIENCE_ENABLED)),
        "max_transient": int(c.get(RESILIENCE_MAX_TRANSIENT_RETRIES)),
        "backoff_ms": float(c.get(RESILIENCE_BACKOFF_BASE_MS)),
        "max_oom": int(c.get(RETRY_MAX_ATTEMPTS)),
        "fallback": bool(c.get(RESILIENCE_RUNTIME_FALLBACK)),
        "ansi": bool(c.ansi_enabled),
        "threshold": int(c.get(RESILIENCE_BREAKER_THRESHOLD)),
    }


def _backoff_sleep(base_ms: float, attempt: int) -> None:
    """base * 2^(attempt-1) + jitter in [0, base), capped at 2s.

    Cancellable (ISSUE 4): a backoff is a *blocked batch pull*, so it
    sleeps on the query's CancelToken — a deadline/cancel trip wakes it
    immediately and raises instead of holding the stage (and whatever it
    pinned) for the rest of the delay."""
    if base_ms <= 0:
        return
    delay = min(base_ms * (2 ** (attempt - 1)), 2000.0)
    delay += random.random() * base_ms
    from spark_rapids_tpu.lifecycle.context import current_token

    token = current_token()
    if token is not None:
        token.sleep_or_raise(delay / 1000.0)
    else:
        time.sleep(delay / 1000.0)


_KEY_UNSET = object()


def _breaker_key_of(op):
    """op_breaker_key, cached on the exec: the key is immutable per
    instance, and computing it means synthesizing the CPU twin plus
    hashing every expression — too heavy to redo on every operator
    completion once any breaker entry exists."""
    key = getattr(op, "_srt_breaker_key", _KEY_UNSET)
    if key is _KEY_UNSET:
        from spark_rapids_tpu.resilience.fallback import op_breaker_key

        key = op_breaker_key(op)
        op._srt_breaker_key = key
    return key


class ReplayMisalignment(Exception):
    """A restarted operator's batch boundaries no longer line up with the
    rows already delivered downstream (e.g. an OOM split on the first run
    changed batch sizes).  Splicing would drop or duplicate rows, so the
    domain re-raises to the session's whole-query fallback — and skips
    breaker recording, since the operator did not deterministically
    fail."""


def run_fault_domain(op, fn, args, kwargs) -> Iterator:
    """Drive ``fn(op, *args, **kwargs)`` (the operator's raw batch
    iterator) inside the fault domain."""
    from spark_rapids_tpu import perfcounters as PC
    from spark_rapids_tpu.resilience.breaker import get_breaker
    from spark_rapids_tpu.resilience.fallback import execute_fallback

    conf = _confs()
    name = op.node_name
    if not conf["enabled"]:
        # the injection hooks stay live so tests can demonstrate that a
        # disabled fault domain lets failures kill the query
        it = fn(op, *args, **kwargs)
        try:
            idx = 0
            while True:
                faults.check_fault(name, idx)
                try:
                    b = next(it)
                except StopIteration:
                    return
                yield faults.maybe_poison(name, idx, b)
                idx += 1
        finally:
            # the raw batch iterator need not be a generator (a source
            # exec may return a plain iterator with no close())
            _close_quietly(it)

    breaker = get_breaker()
    yielded = 0                 # batches already delivered downstream
    yielded_rows = 0            # rows already delivered downstream
    transient_used = 0
    oom_used = 0
    it = None
    try:
        while True:
            try:
                if it is None:
                    it = fn(op, *args, **kwargs)
                    # deterministic replay, accounted by ROWS: batch
                    # boundaries are not stable across restarts (an OOM
                    # split on the failed run changes batch sizes), so a
                    # misaligned boundary bails to the whole-query
                    # fallback instead of dropping/duplicating rows
                    replayed = 0
                    while replayed < yielded_rows:
                        try:
                            rb = next(it)
                        except StopIteration:
                            raise ReplayMisalignment(
                                f"{name}: restart replayed {replayed} of "
                                f"{yielded_rows} rows") from None
                        replayed += rb.num_rows
                        # the inner iterator re-counted this batch on the
                        # way out; it was already counted when first
                        # delivered downstream
                        op.metric("numOutputRows").add(-rb.num_rows)
                        op.metric("numOutputBatches").add(-1)
                    if replayed > yielded_rows:
                        raise ReplayMisalignment(
                            f"{name}: restart batch boundary overshot "
                            f"({replayed} > {yielded_rows} rows)")

                faults.check_fault(name, yielded)
                try:
                    b = next(it)
                except StopIteration:
                    if breaker.has_entries():
                        key = _breaker_key_of(op)
                        if key is not None:
                            breaker.record_success(key)
                    return
                b = faults.maybe_poison(name, yielded, b)
            except GeneratorExit:
                raise
            except Exception as e:
                kind = CL.classify_failure(e)
                if kind == CL.PROPAGATE:
                    raise
                # a child domain that already exhausted its own retry
                # budget tags the exception; retrying the whole subtree
                # here would reset the child's counter and multiply the
                # work exponentially with plan depth — treat as
                # deterministic instead
                exhausted = getattr(e, "_srt_retries_exhausted", False)
                if kind == CL.TRANSIENT and not exhausted \
                        and transient_used < conf["max_transient"]:
                    transient_used += 1
                    PC.bump("transient_retries")
                    op.metric("transientRetries").add(1)
                    _diag_event("transient_retry", name,
                                f"{type(e).__name__}: {e}")
                    _close_quietly(it)
                    it = None
                    _backoff_sleep(conf["backoff_ms"], transient_used)
                    continue
                if kind == CL.DEVICE_OOM and not exhausted \
                        and oom_used < conf["max_oom"]:
                    oom_used += 1
                    PC.bump("oom_restarts")
                    op.metric("retryCount").add(1)
                    _diag_event("oom_restart", name,
                                f"{type(e).__name__}: {e}")
                    from spark_rapids_tpu.memory.spill import (
                        get_spill_framework,
                    )

                    get_spill_framework().spill_device_pressure()
                    _close_quietly(it)
                    it = None
                    continue
                if kind in (CL.TRANSIENT, CL.DEVICE_OOM):
                    e._srt_retries_exhausted = True
                # deterministic (or retry budget exhausted): breaker +
                # runtime CPU fallback.  WORKER_LOST (ISSUE 14) takes
                # the same fallback path but NEVER indicts the
                # operator's breaker key — the distributed tier already
                # re-placed/re-drove what it could and quarantined the
                # worker's own per-worker entry; losing infrastructure
                # must not banish a healthy stage to CPU
                # WORKER_DEGRADED (ISSUE 20) is the same stance, one
                # notch softer: the straggler stays a member, so there
                # is even less reason to indict the operator
                if kind in (CL.WORKER_LOST, CL.WORKER_DEGRADED):
                    _diag_event(
                        "worker_lost" if kind == CL.WORKER_LOST
                        else "worker_degraded", name,
                        f"{type(e).__name__}: {e}")
                key = None if isinstance(e, ReplayMisalignment) \
                    or kind in (CL.WORKER_LOST, CL.WORKER_DEGRADED) \
                    else _breaker_key_of(op)
                if key is not None and not getattr(
                        e, "_srt_breaker_recorded", False):
                    tripped = breaker.record_failure(
                        key, conf["threshold"],
                        reason=f"{type(e).__name__}: {e}")
                    e._srt_breaker_recorded = True
                    if tripped:
                        PC.bump("breaker_trips")
                        op.metric("breakerTrips").add(1)
                        _diag_event("breaker_trip", name,
                                    f"{type(e).__name__}: {e}")
                if not conf["fallback"] or yielded:
                    raise
                try:
                    fb = execute_fallback(op, conf["ansi"])
                    out = list(fb)
                except LookupError:
                    raise e
                except Exception as oracle_err:
                    # the oracle agrees this fails; surface the ORIGINAL
                    # error so expected-error tests keep their match
                    raise e from oracle_err
                PC.bump("runtime_fallbacks")
                op.metric("runtimeFallbacks").add(1)
                _diag_event("runtime_fallback", name,
                            f"{type(e).__name__}: {e}")
                _close_quietly(it)
                it = None
                for b2 in out:
                    yield op._count_output(b2)
                return
            else:
                yielded += 1
                yielded_rows += b.num_rows
                yield b
    finally:
        _close_quietly(it)


def _close_quietly(it) -> None:
    if it is not None:
        try:
            it.close()
        # tpulint: disable=cancel-swallow (generator close on the unwind
        # path; the original exception is already propagating)
        except Exception:
            pass
