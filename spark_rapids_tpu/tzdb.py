"""Timezone transition tables — the GpuTimeZoneDB analog.

Reference analog: spark-rapids-jni ``timezones.cu`` + GpuTimeZoneDB
(SURVEY.md §2.5 Date/time): the reference loads the JVM's tz database into
GPU transition tables and resolves offsets with a device binary search.

TPU build: TZif files (RFC 8536) are parsed straight from
/usr/share/zoneinfo into two sorted int64 arrays per zone —

  * ``utc_instants`` / ``offsets``: offset in effect at a UTC instant
    (from_utc_timestamp) — searchsorted on the instant;
  * ``wall_starts`` / ``offsets``: offset chosen for a LOCAL wall time
    (to_utc_timestamp), with wall boundary t_i + max(off_before,
    off_after), which reproduces java.time's gap (shift forward) and
    overlap (earlier offset) resolution — the same rules Spark applies.

Tables upload once per zone (cached) and every row resolves with one
vectorized searchsorted — no per-row host work.
"""
from __future__ import annotations

import functools
import os
import struct
from typing import Optional, Tuple

import numpy as np

_TZPATHS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo", "/etc/zoneinfo")

_MIN_I64 = -(2**63)


class UnknownTimeZone(ValueError):
    pass


def _read_tzif(name: str) -> Tuple[np.ndarray, np.ndarray]:
    """-> (transition utc seconds int64[n], offsets seconds int64[n+1]);
    offsets[0] applies before the first transition."""
    if "/" in name and ".." in name:
        raise UnknownTimeZone(name)
    path = None
    for base in _TZPATHS:
        p = os.path.join(base, name)
        if os.path.isfile(p):
            path = p
            break
    if path is None:
        raise UnknownTimeZone(name)
    with open(path, "rb") as f:
        data = f.read()

    def parse_block(buf, pos, time_size, fmt):
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = struct.unpack_from(">6I", buf, pos + 20)
        pos += 44
        times = np.frombuffer(buf, dtype=fmt, count=timecnt,
                              offset=pos).astype(np.int64)
        pos += timecnt * time_size
        idxs = np.frombuffer(buf, dtype=np.uint8, count=timecnt, offset=pos)
        pos += timecnt
        ttinfo = []
        for k in range(typecnt):
            utoff, dst, ab = struct.unpack_from(">iBB", buf, pos + k * 6)
            ttinfo.append(utoff)
        pos += typecnt * 6 + charcnt + leapcnt * (time_size + 4)
        pos += isstdcnt + isutcnt
        return times, idxs, np.asarray(ttinfo, np.int64), pos

    if data[:4] != b"TZif":
        raise UnknownTimeZone(f"{name}: not a TZif file")
    version = data[4:5]
    times, idxs, ttinfo, pos = parse_block(data, 0, 4, ">i4")
    footer = b""
    if version in (b"2", b"3", b"4"):
        # the 64-bit block supersedes the 32-bit one
        times, idxs, ttinfo, end = parse_block(data, pos, 8, ">i8")
        footer = data[end:].strip(b"\n")
    if len(ttinfo) == 0:
        return (np.zeros(0, np.int64), np.zeros(1, np.int64))
    # offset BEFORE first transition: first ttinfo entry (RFC: first
    # standard-time entry; entry 0 is the common convention)
    first = ttinfo[0]
    offsets = np.concatenate([[first], ttinfo[idxs]]).astype(np.int64)
    times = times.astype(np.int64)
    # TZif tables usually stop ~2037; the POSIX footer rule governs the
    # open future — materialize it out to 2200 (java.time does the
    # equivalent with ZoneRules.getTransitionRules)
    ext = _extend_with_posix_rule(footer.decode("ascii", "ignore"),
                                  int(times[-1]) if len(times) else 0,
                                  int(offsets[-1]))
    if ext is not None:
        ft, fo = ext
        times = np.concatenate([times, ft])
        offsets = np.concatenate([offsets, fo])
    return times, offsets


def _parse_posix_offset(s: str, i: int):
    """[+|-]hh[:mm[:ss]] -> (seconds west-negative per POSIX -> we return
    the UTC offset in seconds, POSIX sign INVERTED), next index."""
    sign = 1
    if i < len(s) and s[i] in "+-":
        sign = -1 if s[i] == "-" else 1
        i += 1
    parts = [0, 0, 0]
    for k in range(3):
        j = i
        while j < len(s) and s[j].isdigit():
            j += 1
        if j == i:
            break
        parts[k] = int(s[i:j])
        i = j
        if i < len(s) and s[i] == ":":
            i += 1
        else:
            break
    secs = parts[0] * 3600 + parts[1] * 60 + parts[2]
    return -sign * secs, i  # POSIX: positive = west of UTC


def _days_in_month(y, m):
    import calendar

    return calendar.monthrange(y, m)[1]


def _rule_instant(year: int, rule: str, at: int, utoff: int) -> int:
    """POSIX Mm.w.d rule -> UTC seconds for that year's transition."""
    import datetime as _dt

    if rule.startswith("M"):
        m, w, d = (int(x) for x in rule[1:].split("."))
        # d-th day-of-week (0=Sunday) of week w (w=5: last)
        first = _dt.date(year, m, 1)
        dow_first = (first.weekday() + 1) % 7  # python Mon=0 -> Sun=0
        day = 1 + (d - dow_first) % 7 + (w - 1) * 7
        while day > _days_in_month(year, m):
            day -= 7
        local = _dt.datetime(year, m, day) + _dt.timedelta(seconds=at)
    elif rule.startswith("J"):
        n = int(rule[1:])  # 1..365, Feb 29 never counted
        local = (_dt.datetime(year, 1, 1)
                 + _dt.timedelta(days=n - 1, seconds=at))
        if n >= 60 and _days_in_month(year, 2) == 29:
            local += _dt.timedelta(days=1)
    else:
        n = int(rule)  # 0..365 incl leap day
        local = (_dt.datetime(year, 1, 1)
                 + _dt.timedelta(days=n, seconds=at))
    epoch = _dt.datetime(1970, 1, 1)
    return int((local - epoch).total_seconds()) - utoff


def _extend_with_posix_rule(footer: str, last_trans: int, last_off: int):
    """Materialize the footer rule's transitions for years after the table.

    Returns (times, offsets_after_each) or None for fixed-offset zones."""
    if not footer or "," not in footer:
        return None  # no DST rule: last offset holds forever
    try:
        head, start_rule, end_rule = footer.split(",")
        i = 0
        if head[i] == "<":
            i = head.index(">", i) + 1
        else:
            while i < len(head) and not (head[i].isdigit()
                                         or head[i] in "+-"):
                i += 1
        std_off, i = _parse_posix_offset(head, i)
        if i < len(head):
            j = i
            if head[j] == "<":
                j = head.index(">", j) + 1
            else:
                while j < len(head) and not (head[j].isdigit()
                                             or head[j] in "+-,"):
                    j += 1
            if j < len(head) and (head[j].isdigit() or head[j] in "+-"):
                dst_off, _ = _parse_posix_offset(head, j)
            else:
                dst_off = std_off + 3600
        else:
            dst_off = std_off + 3600

        def split_at(r, default=7200):
            if "/" in r:
                r, t = r.split("/")
                secs, _ = _parse_posix_offset(t, 0)
                return r, -secs  # parse returns inverted sign
            return r, default

        start_rule, start_at = split_at(start_rule)
        end_rule, end_at = split_at(end_rule)
        import datetime as _dt

        y0 = max(_dt.datetime.utcfromtimestamp(max(last_trans, 0)).year, 1970)
        times, offs = [], []
        for year in range(y0, 2201):
            s = _rule_instant(year, start_rule, start_at, std_off)
            e = _rule_instant(year, end_rule, end_at, dst_off)
            for t, o in sorted([(s, dst_off), (e, std_off)]):
                if t > last_trans:
                    times.append(t)
                    offs.append(o)
        return (np.asarray(times, np.int64), np.asarray(offs, np.int64))
    except (ValueError, IndexError):
        return None


@functools.lru_cache(maxsize=256)
def zone_tables(name: str):
    """-> dict of numpy tables for one zone (host side, cached)."""
    trans, offsets = _read_tzif(name)
    # utc lookup: instants with sentinel -inf
    utc_instants = np.concatenate([[_MIN_I64], trans])
    # wall lookup: boundary = transition + max(off_before, off_after)
    if len(trans):
        wall = trans + np.maximum(offsets[:-1], offsets[1:])
    else:
        wall = trans
    wall_starts = np.concatenate([[_MIN_I64], wall])
    return {
        "utc_instants": utc_instants,          # (n+1,) seconds
        "wall_starts": wall_starts,            # (n+1,) seconds
        "offsets": offsets,                    # (n+1,) seconds
    }


def is_known_zone(name: Optional[str]) -> bool:
    if not isinstance(name, str) or not name:
        return False
    try:
        zone_tables(name)
        return True
    except (UnknownTimeZone, OSError, ValueError, struct.error):
        return False


def offsets_for_instants_np(name: str, micros: np.ndarray) -> np.ndarray:
    """Offset (seconds) in effect at each UTC instant (numpy, oracle-free
    helper for IO paths)."""
    t = zone_tables(name)
    secs = np.floor_divide(micros, 1_000_000)
    idx = np.searchsorted(t["utc_instants"], secs, side="right") - 1
    return t["offsets"][np.clip(idx, 0, len(t["offsets"]) - 1)]
