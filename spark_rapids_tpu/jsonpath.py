"""JSON path engine — the host half of the JSON expression family.

Reference analog: spark-rapids-jni ``get_json_object.cu`` + Spark's
``JsonExpressions.scala`` (JsonPathParser / GetJsonObject evaluatePath).
The reference evaluates JSON paths in a CUDA kernel; the TPU build keeps
JSON on the host (SURVEY.md §2.10 item 10: "CSV/JSON parsers — host parse →
device") behind ``jax.pure_callback``, with a native C++ port of this exact
state machine in native/host_kernels.cpp for speed.

Semantics notes (documented TypeSig notes, mirroring the reference's own
get_json_object compatibility docs):
  * nested object/array results are whitespace-compacted from the source
    text; Spark (Jackson) re-serializes, which also normalizes string
    escapes — inputs with non-canonical escapes inside nested results may
    differ.
  * a terminal JSON ``null`` yields SQL NULL.
  * wildcard paths (``[*]``, ``.*``) are rejected at plan time (CPU
    fallback), like the reference transpiler-reject path for regex.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

_WS = b" \t\n\r"
_DELIM = b",}] \t\n\r"

PathStep = Union[str, int]


def parse_json_path(path) -> Optional[List[PathStep]]:
    """Parse a Spark JSON path into [key|index] steps.

    Returns None for an INVALID path (Spark: result is NULL for every row),
    raises UnsupportedJsonPath for wildcards (plan-time CPU fallback).
    """
    if not isinstance(path, str) or not path.startswith("$"):
        return None
    steps: List[PathStep] = []
    i, L = 1, len(path)
    while i < L:
        c = path[i]
        if c == ".":
            i += 1
            j = i
            while j < L and path[j] not in ".[":
                j += 1
            name = path[i:j]
            if not name:
                return None
            if name == "*":
                raise UnsupportedJsonPath("wildcard field .*")
            steps.append(name)
            i = j
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                return None
            inner = path[i + 1:j]
            if inner == "*":
                raise UnsupportedJsonPath("wildcard subscript [*]")
            if len(inner) >= 2 and inner[0] == "'" and inner[-1] == "'":
                steps.append(inner[1:-1])
            else:
                try:
                    steps.append(int(inner))
                except ValueError:
                    return None
                if steps[-1] < 0:
                    return None
            i = j + 1
        else:
            return None
    return steps


class UnsupportedJsonPath(Exception):
    """Wildcard (or otherwise un-accelerated) path: plan-time fallback."""


# ---------------------------------------------------------------------------
# Byte-level evaluator (ported verbatim to C++ in native/host_kernels.cpp)
# ---------------------------------------------------------------------------

def _skip_ws(b: bytes, i: int) -> int:
    L = len(b)
    while i < L and b[i] in _WS:
        i += 1
    return i


def _string_end(b: bytes, i: int) -> int:
    """b[i] == '\"'; index one past the closing quote, or -1."""
    L = len(b)
    i += 1
    while i < L:
        c = b[i]
        if c == 0x5C:  # backslash
            i += 2
            continue
        if c == 0x22:
            return i + 1
        i += 1
    return -1


_MAX_DEPTH = 256


def _skip_value(b: bytes, i: int, depth: int = 0) -> int:
    """Index one past the JSON value starting at (ws-skipped) i, or -1.

    VALIDATES as it goes (strings incl. escapes, scalars, structure):
    Spark's Jackson streaming fails on any malformed token it passes over,
    so a skip that merely bracket-matched would diverge on bad documents.
    """
    if depth > _MAX_DEPTH:
        return -1
    L = len(b)
    i = _skip_ws(b, i)
    if i >= L:
        return -1
    c = b[i]
    if c == 0x22:
        e = _string_end(b, i)
        if e < 0 or _unescape(b[i + 1:e - 1]) is None:
            return -1
        return e
    if c == 0x7B:  # {
        i = _skip_ws(b, i + 1)
        if i < L and b[i] == 0x7D:
            return i + 1
        while True:
            i = _skip_ws(b, i)
            if i >= L or b[i] != 0x22:
                return -1
            ke = _string_end(b, i)
            if ke < 0 or _unescape(b[i + 1:ke - 1]) is None:
                return -1
            i = _skip_ws(b, ke)
            if i >= L or b[i] != 0x3A:
                return -1
            e = _skip_value(b, i + 1, depth + 1)
            if e < 0:
                return -1
            i = _skip_ws(b, e)
            if i >= L:
                return -1
            if b[i] == 0x2C:
                i += 1
                continue
            if b[i] == 0x7D:
                return i + 1
            return -1
    if c == 0x5B:  # [
        i = _skip_ws(b, i + 1)
        if i < L and b[i] == 0x5D:
            return i + 1
        while True:
            e = _skip_value(b, i, depth + 1)
            if e < 0:
                return -1
            i = _skip_ws(b, e)
            if i >= L:
                return -1
            if b[i] == 0x2C:
                i += 1
                continue
            if b[i] == 0x5D:
                return i + 1
            return -1
    j = i
    while j < L and b[j] not in _DELIM:
        j += 1
    if j == i or not _valid_scalar(b[i:j]):
        return -1
    return j


def _unescape(raw: bytes) -> Optional[bytes]:
    """JSON string-body unescape (handles \\uXXXX incl. surrogate pairs)."""
    if 0x5C not in raw:
        return raw
    out = bytearray()
    i, L = 0, len(raw)
    while i < L:
        c = raw[i]
        if c != 0x5C:
            out.append(c)
            i += 1
            continue
        if i + 1 >= L:
            return None
        e = raw[i + 1]
        i += 2
        simple = {0x22: 0x22, 0x5C: 0x5C, 0x2F: 0x2F, 0x62: 8, 0x66: 12,
                  0x6E: 10, 0x72: 13, 0x74: 9}
        if e in simple:
            out.append(simple[e])
            continue
        if e != 0x75:  # u
            return None
        if i + 4 > L:
            return None
        try:
            cp = int(raw[i:i + 4], 16)
        except ValueError:
            return None
        i += 4
        if 0xD800 <= cp <= 0xDBFF and i + 6 <= L and raw[i:i + 2] == b"\\u":
            try:
                lo = int(raw[i + 2:i + 6], 16)
            except ValueError:
                lo = -1
            if 0xDC00 <= lo <= 0xDFFF:
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00)
                i += 6
        try:
            out += chr(cp).encode("utf-8")
        except (ValueError, UnicodeEncodeError):
            return None
    return bytes(out)


def _compact(raw: bytes) -> Optional[bytes]:
    """Strip whitespace outside strings (Jackson-compact analog)."""
    out = bytearray()
    i, L = 0, len(raw)
    while i < L:
        c = raw[i]
        if c == 0x22:
            e = _string_end(raw, i)
            if e < 0:
                return None
            out += raw[i:e]
            i = e
            continue
        if c in _WS:
            i += 1
            continue
        out.append(c)
        i += 1
    return bytes(out)


def _valid_scalar(raw: bytes) -> bool:
    if raw in (b"true", b"false", b"null"):
        return True
    # JSON number grammar
    i, L = 0, len(raw)
    if i < L and raw[i] == 0x2D:
        i += 1
    start = i
    while i < L and 0x30 <= raw[i] <= 0x39:
        i += 1
    if i == start:
        return False
    if i < L and raw[i] == 0x2E:
        i += 1
        start = i
        while i < L and 0x30 <= raw[i] <= 0x39:
            i += 1
        if i == start:
            return False
    if i < L and raw[i] in (0x65, 0x45):
        i += 1
        if i < L and raw[i] in (0x2B, 0x2D):
            i += 1
        start = i
        while i < L and 0x30 <= raw[i] <= 0x39:
            i += 1
        if i == start:
            return False
    return i == L


def _navigate(b: bytes, i: int, steps: List[PathStep],
              si: int) -> Optional[Tuple[int, int]]:
    """Span (start, end) of the value addressed by steps[si:], or None."""
    L = len(b)
    i = _skip_ws(b, i)
    if si == len(steps):
        e = _skip_value(b, i)
        if e < 0:
            return None
        return (i, e)
    if i >= L:
        return None
    step = steps[si]
    if isinstance(step, str):
        if b[i] != 0x7B:  # {
            return None
        i += 1
        while True:
            i = _skip_ws(b, i)
            if i >= L or b[i] == 0x7D:
                return None
            if b[i] != 0x22:
                return None
            ke = _string_end(b, i)
            if ke < 0:
                return None
            key = _unescape(b[i + 1:ke - 1])
            if key is None:
                return None
            i2 = _skip_ws(b, ke)
            if i2 >= L or b[i2] != 0x3A:  # :
                return None
            i2 += 1
            if key.decode("utf-8", "replace") == step:
                return _navigate(b, i2, steps, si + 1)
            e = _skip_value(b, i2)
            if e < 0:
                return None
            i = _skip_ws(b, e)
            if i >= L:
                return None
            if b[i] == 0x2C:  # ,
                i += 1
            elif b[i] != 0x7D:
                return None
    else:
        if b[i] != 0x5B:  # [
            return None
        i += 1
        for _ in range(step):
            i = _skip_ws(b, i)
            if i >= L or b[i] == 0x5D:
                return None
            e = _skip_value(b, i)
            if e < 0:
                return None
            i = _skip_ws(b, e)
            if i >= L or b[i] != 0x2C:
                return None
            i += 1
        i = _skip_ws(b, i)
        if i >= L or b[i] == 0x5D:
            return None
        return _navigate(b, i, steps, si + 1)


def get_json_object_bytes(doc: bytes,
                          steps: List[PathStep]) -> Optional[bytes]:
    """Evaluate path; result bytes or None (SQL NULL)."""
    span = _navigate(doc, 0, steps, 0)
    if span is None:
        return None
    return _terminal_bytes(doc, span[0], span[1])


def _terminal_bytes(doc: bytes, s: int, e: int) -> Optional[bytes]:
    """Extracted value span -> result bytes (string unescape / compact /
    raw scalar), or None for JSON null."""
    c = doc[s]
    if c == 0x22:
        return _unescape(doc[s + 1:e - 1])
    raw = doc[s:e]
    if c in (0x7B, 0x5B):
        return _compact(raw)
    if raw == b"null":
        return None
    if not _valid_scalar(raw):
        return None
    return raw


def json_tuple_bytes(doc: bytes,
                     keys: List[str]) -> List[Optional[bytes]]:
    """One top-level pass filling every requested key (Spark JsonTuple:
    a parse failure anywhere nulls the whole row; a later duplicate key
    overwrites an earlier one)."""
    out: List[Optional[bytes]] = [None] * len(keys)
    idx_of = {}
    for i, k in enumerate(keys):
        idx_of.setdefault(k, []).append(i)
    L = len(doc)
    i = _skip_ws(doc, 0)
    if i >= L or doc[i] != 0x7B:
        return out
    i += 1
    none_row = [None] * len(keys)
    while True:
        i = _skip_ws(doc, i)
        if i >= L:
            return list(none_row)
        if doc[i] == 0x7D:
            return out
        if doc[i] != 0x22:
            return list(none_row)
        ke = _string_end(doc, i)
        if ke < 0:
            return list(none_row)
        key = _unescape(doc[i + 1:ke - 1])
        if key is None:
            return list(none_row)
        i = _skip_ws(doc, ke)
        if i >= L or doc[i] != 0x3A:
            return list(none_row)
        i += 1
        vs = _skip_ws(doc, i)
        e = _skip_value(doc, vs)
        if e < 0:
            return list(none_row)
        slots = idx_of.get(key.decode("utf-8", "replace"))
        if slots:
            val = _terminal_bytes(doc, vs, e)
            for sl in slots:
                out[sl] = val
        i = _skip_ws(doc, e)
        if i >= L:
            return list(none_row)
        if doc[i] == 0x2C:
            i += 1
        elif doc[i] != 0x7D:
            return list(none_row)


def get_json_object_str(doc: str, path: str) -> Optional[str]:
    """Convenience wrapper (oracle cross-checks + doctests)."""
    try:
        steps = parse_json_path(path)
    except UnsupportedJsonPath:
        return None
    if steps is None:
        return None
    out = get_json_object_bytes(doc.encode("utf-8"), steps)
    return None if out is None else out.decode("utf-8", "replace")
