"""TpuSession / DataFrame — the user entry point.

Role: in the reference, users keep using SparkSession and the plugin hooks in
via ``spark.plugins=com.nvidia.spark.SQLPlugin`` (SURVEY.md §3.1).  This
standalone framework has no JVM, so TpuSession plays both roles: it builds
Catalyst-shaped physical plans from a PySpark-flavored DataFrame API
(select/filter/groupBy/join/orderBy...), plans aggregates two-phase around
exchanges exactly like Spark (partial -> shuffle -> final), and at collect()
time applies TpuOverrides (the ColumnarOverrideRules hook analog), executes
the rewritten plan, and returns rows.

``conf`` accepts the same ``spark.rapids.*`` keys as the reference;
``spark.rapids.sql.enabled=false`` runs everything on the CPU oracle — which
is precisely what the differential test harness does to get golden results.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple, Union

from spark_rapids_tpu import types as T
from spark_rapids_tpu.accounting import context as _ACCT_CTX
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.telemetry import context as _TEL_CTX
from spark_rapids_tpu.config import SHUFFLE_PARTITIONS, TpuConf
from spark_rapids_tpu.expr.base import (
    Alias,
    AttributeReference,
    Expression,
    col as _col,
    lit as _lit,
)
from spark_rapids_tpu.ops.sortkeys import SortSpec
from spark_rapids_tpu.plan import nodes as PN

ColumnLike = Union[str, Expression]


def _to_expr(c: ColumnLike) -> Expression:
    if isinstance(c, Expression):
        return c
    return _col(c)


_COMPILE_CACHE_APPLIED: Optional[str] = None     # last applied dir ("" = off)


def _apply_compile_cache(conf: "TpuConf") -> None:
    """Point XLA's persistent compile cache at the configured dir (VERDICT
    r4 Next #6: one cache authority for session/tests/tools/bench).
    jax.config is process-global; re-applied whenever a session resolves a
    DIFFERENT dir, so a later explicit conf is not silently ignored.  An
    empty/'0' dir opts out.  Falls back to ~/.cache when the configured dir
    cannot be created (e.g. a read-only install tree)."""
    global _COMPILE_CACHE_APPLIED
    from spark_rapids_tpu.config import COMPILE_CACHE_DIR, COMPILE_CACHE_DIR_V2

    # preferred spelling first (spark.rapids.tpu.compile.cacheDir); unset
    # falls back to the legacy key and its repo-local default
    cache_dir = conf.get(COMPILE_CACHE_DIR_V2)
    if cache_dir is None:
        cache_dir = conf.get(COMPILE_CACHE_DIR)
    if not cache_dir or cache_dir == "0":
        cache_dir = ""
    if cache_dir:
        # partition by backend + compile mode: XLA:CPU AOT artifacts are
        # machine-feature-specific, and the axon remote-compile relay
        # builds them for ITS host — loading those locally risks SIGILL
        # (observed "+prefer-no-scatter not supported" load warnings)
        try:
            import jax

            plat = jax.default_backend()
        except Exception:
            plat = "unknown"
        if os.environ.get("PALLAS_AXON_REMOTE_COMPILE"):
            plat += "-remote"
        cache_dir = os.path.join(cache_dir, plat)
    if _COMPILE_CACHE_APPLIED == cache_dir:
        return
    if not cache_dir:
        _COMPILE_CACHE_APPLIED = cache_dir
        return
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        # keep the backend partition in the fallback too (mixing AOT
        # artifacts across machines risks SIGILL); leave the sentinel
        # unset on total failure so a later fixed conf can still apply
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "spark_rapids_tpu",
            os.path.basename(cache_dir))
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            return
    _COMPILE_CACHE_APPLIED = cache_dir
    try:
        import jax

        from spark_rapids_tpu.compilecache import ensure_atomic_cache_put

        ensure_atomic_cache_put()
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


class TpuSession:
    def __init__(self, conf: Optional[Dict[str, str]] = None):
        self.conf = TpuConf(conf or {})
        _apply_compile_cache(self.conf)
        # Telemetry tier (ISSUE 7): the first enabling session builds the
        # process-global hub (metrics registry + sampler + flight
        # recorder + optional scrape endpoint); later sessions reuse it.
        from spark_rapids_tpu.telemetry import maybe_configure

        maybe_configure(self.conf)
        # Overload governor (ISSUE 13): the first session whose conf
        # enables spark.rapids.tpu.governor.enabled installs the
        # process-global pressure state machine; disabled (the default)
        # this is one conf read and the ambient slot stays None.
        from spark_rapids_tpu.governor import ensure_governor

        ensure_governor(self.conf)
        # Resource accounting (ISSUE 18): the first session enabling
        # spark.rapids.tpu.accounting.enabled installs the process-global
        # ledger registry; disabled (the default) the ambient slot stays
        # None and every spill-framework charge site is one attr check.
        from spark_rapids_tpu.accounting import maybe_configure as acct_configure

        acct_configure(self.conf)
        # Multi-tenant serving tier (ISSUE 19): the first session whose
        # conf enables spark.rapids.tpu.serving.enabled builds the tier
        # (fair-share scheduler installed into admission, the result-
        # fragment cache into its ambient slot).  Disabled (the
        # default): one conf read, the serving package never imports.
        from spark_rapids_tpu.config import SERVING_ENABLED

        if bool(self.conf.get(SERVING_ENABLED)):
            from spark_rapids_tpu.serving import ensure_serving

            ensure_serving(self.conf)

    @staticmethod
    def builder() -> "TpuSessionBuilder":
        return TpuSessionBuilder()

    def set_conf(self, key: str, value) -> "TpuSession":
        self.conf = self.conf.set(key, value)
        return self

    def progress(self, include_finished: bool = True) -> List[dict]:
        """Live multi-query progress snapshot (ISSUE 12): one dict per
        in-flight (and recently finished) lifecycle-managed query on
        this PROCESS — per-operator batches/rows/bytes, percent/ETA
        from the cost-model join, attributed background work, and stall
        state.  Empty when spark.rapids.tpu.progress.enabled never
        enabled a query.  The same payload the telemetry endpoint's
        /progress route serves (docs/progress.md)."""
        from spark_rapids_tpu.progress import snapshot

        return snapshot(include_finished)

    # -- data sources ---------------------------------------------------
    def create_dataframe(self, data, schema: T.StructType) -> "DataFrame":
        if isinstance(data, dict):
            cols = [HostColumn.from_pylist(data[f.name], f.dataType)
                    for f in schema.fields]
        else:  # rows
            cols = []
            for i, f in enumerate(schema.fields):
                cols.append(HostColumn.from_pylist(
                    [r[i] for r in data], f.dataType))
        return DataFrame(PN.LocalTableScan(cols, schema), self)

    createDataFrame = create_dataframe

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(PN.RangeNode(start, end, step), self)

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    @property
    def shuffle_partitions(self) -> int:
        return self.conf.get(SHUFFLE_PARTITIONS)

    def close(self, check_leaks: bool = True,
              drop_hot_cache: bool = True) -> List[str]:
        """Session shutdown (ISSUE 4 satellite): report — and then
        release — anything still held across the process singletons:
        unclosed non-persistent spillables, semaphore permits, live
        shuffle registrations.  Returns the leak report (empty for a
        well-behaved session); with spark.rapids.memory.debug the
        entries carry allocation stacks."""
        from spark_rapids_tpu.io.hot_cache import clear_hot_cache
        from spark_rapids_tpu.lifecycle import (
            leak_report_all,
            reset_leaked_state,
        )

        # hot-table cache entries are INTENTIONAL persistent spillables
        # while the process serves queries; like everything else this
        # method touches, the cache is a PROCESS singleton — shutdown
        # drops it so the leak report below (and the conftest session
        # gate) sees a clean framework.  A deployment closing one of
        # several live sessions passes drop_hot_cache=False to keep the
        # other sessions' warm tables.
        if drop_hot_cache:
            clear_hot_cache()
        leaks = leak_report_all() if check_leaks else []
        reset_leaked_state()
        # flush the telemetry JSONL sink so a shutdown-then-inspect
        # workflow sees every sampler tick; the hub itself is
        # process-global and keeps serving other live sessions
        # (telemetry.shutdown() stops it for good)
        from spark_rapids_tpu.telemetry import flush as _telemetry_flush

        _telemetry_flush()
        return leaks


class TpuSessionBuilder:
    def __init__(self):
        self._conf: Dict[str, str] = {}

    def config(self, key: str, value) -> "TpuSessionBuilder":
        self._conf[key] = value
        return self

    def get_or_create(self) -> TpuSession:
        return TpuSession(self._conf)

    getOrCreate = get_or_create


class DataFrameReader:
    def __init__(self, session: TpuSession):
        self.session = session
        self._options: Dict[str, str] = {}
        self._schema: Optional[T.StructType] = None

    def option(self, k, v) -> "DataFrameReader":
        self._options[k] = v
        return self

    def schema(self, s: T.StructType) -> "DataFrameReader":
        self._schema = s
        return self

    def _infer_schema(self, fmt: str, paths: List[str]) -> T.StructType:
        import os

        import pyarrow as pa

        if os.path.isdir(paths[0]):
            # hive-partitioned directory written by df.write.partitionBy
            import pyarrow.dataset as ds

            dset = ds.dataset(paths[0], format=fmt,
                              partitioning="hive",
                              exclude_invalid_files=True)
            arrow_schema = dset.schema
        elif fmt == "parquet":
            import pyarrow.parquet as pq

            arrow_schema = pq.read_schema(paths[0])
        elif fmt == "orc":
            import pyarrow.orc as paorc

            arrow_schema = paorc.ORCFile(paths[0]).schema
        elif fmt == "csv":
            import pyarrow.csv as pacsv

            arrow_schema = pacsv.read_csv(paths[0]).schema
        else:
            import pyarrow.json as pajson

            arrow_schema = pajson.read_json(paths[0]).schema
        fields = []
        for f in arrow_schema:
            fields.append(T.StructField(f.name, _arrow_to_sql(f.type),
                                        f.nullable))
        return T.StructType(fields)

    def parquet(self, *paths: str) -> "DataFrame":
        schema = self._schema or self._infer_schema("parquet", list(paths))
        return DataFrame(
            PN.FileSourceScan("parquet", list(paths), schema,
                              options=self._options), self.session)

    def csv(self, *paths: str) -> "DataFrame":
        opts = dict(self._options)
        if self._schema is None:
            # inference honors the reader's sep/header options (arrow
            # parse options), so the strict parse sees the same shape
            import pyarrow.csv as pacsv

            sep = str(opts.get("sep", opts.get("delimiter", ",")))
            headerless = str(opts.get("header", "")).lower() == "false"
            tbl = pacsv.read_csv(
                paths[0],
                read_options=pacsv.ReadOptions(
                    autogenerate_column_names=headerless),
                parse_options=pacsv.ParseOptions(delimiter=sep))
            fields = [T.StructField(f.name if not headerless
                                    else f"_c{i}",
                                    _arrow_to_sql(f.type), f.nullable)
                      for i, f in enumerate(tbl.schema)]
            schema = T.StructType(fields)
            # schema inference reads column names from the header line, so
            # the parse must consume it too (explicit schemas keep Spark's
            # header=false default)
            opts.setdefault("header", "true")
        else:
            schema = self._schema
        return DataFrame(
            PN.FileSourceScan("csv", list(paths), schema,
                              options=opts), self.session)

    def delta(self, path: str, version: Optional[int] = None) -> "DataFrame":
        from spark_rapids_tpu.delta import read_delta

        return read_delta(self.session, path, version)

    def iceberg(self, path: str,
                snapshot_id: Optional[int] = None) -> "DataFrame":
        from spark_rapids_tpu.io.iceberg import read_iceberg

        return read_iceberg(self.session, path, snapshot_id)

    def avro(self, *paths: str) -> "DataFrame":
        if self._schema is None:
            from spark_rapids_tpu.io.avro import (
                avro_schema_to_struct,
                read_avro_file,
            )

            schema = avro_schema_to_struct(read_avro_file(paths[0])[0])
        else:
            schema = self._schema
        return DataFrame(
            PN.FileSourceScan("avro", list(paths), schema,
                              options=self._options), self.session)

    def orc(self, *paths: str) -> "DataFrame":
        schema = self._schema or self._infer_schema("orc", list(paths))
        return DataFrame(
            PN.FileSourceScan("orc", list(paths), schema,
                              options=self._options), self.session)

    def json(self, *paths: str) -> "DataFrame":
        schema = self._schema or self._infer_schema("json", list(paths))
        return DataFrame(
            PN.FileSourceScan("json", list(paths), schema,
                              options=self._options), self.session)


def _arrow_to_sql(t) -> T.DataType:
    import pyarrow as pa

    if pa.types.is_boolean(t):
        return T.BOOLEAN
    if pa.types.is_int8(t):
        return T.BYTE
    if pa.types.is_int16(t):
        return T.SHORT
    if pa.types.is_int32(t):
        return T.INT
    if pa.types.is_int64(t):
        return T.LONG
    if pa.types.is_float32(t):
        return T.FLOAT
    if pa.types.is_float64(t):
        return T.DOUBLE
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return T.STRING
    if pa.types.is_date32(t):
        return T.DATE
    if pa.types.is_timestamp(t):
        return T.TIMESTAMP
    if pa.types.is_decimal(t):
        return T.DecimalType(t.precision, t.scale)
    raise TypeError(f"unsupported arrow type {t}")


class DataFrame:
    def __init__(self, plan: PN.SparkPlan, session: TpuSession):
        self.plan = plan
        self.session = session

    @property
    def schema(self) -> T.StructType:
        return self.plan.output

    @property
    def columns(self) -> List[str]:
        return self.schema.field_names()

    # -- transformations ------------------------------------------------
    def select(self, *cols: ColumnLike) -> "DataFrame":
        exprs = [_named(_to_expr(c).resolve(self.schema), i)
                 for i, c in enumerate(cols)]
        return DataFrame(PN.Project(exprs, self.plan), self.session)

    def with_column(self, name: str, e: Expression) -> "DataFrame":
        exprs = []
        for f in self.schema.fields:
            if f.name != name:
                exprs.append(Alias(_col(f.name).resolve(self.schema), f.name))
                exprs[-1].resolve(self.schema)
        newe = Alias(e.resolve(self.schema), name)
        newe.resolve(self.schema)
        exprs.append(newe)
        return DataFrame(PN.Project(exprs, self.plan), self.session)

    withColumn = with_column

    def filter(self, cond: Expression) -> "DataFrame":
        return DataFrame(
            PN.Filter(cond.resolve(self.schema), self.plan), self.session)

    where = filter

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(PN.Union([self.plan, other.plan]), self.session)

    def repartition(self, num_partitions: int,
                    *cols: ColumnLike) -> "DataFrame":
        """Dataset.repartition: hash exchange on ``cols`` (round-robin
        when none given).  Under mesh/ICI mode this lowers to the generic
        mesh all-to-all (exec/ici.TpuIciRepartitionExec)."""
        if cols:
            part = PN.HashPartitioning(
                [_to_expr(c).resolve(self.schema) for c in cols],
                num_partitions)
        else:
            part = PN.RoundRobinPartitioning(num_partitions)
        return DataFrame(PN.Exchange(part, self.plan), self.session)

    def group_by(self, *cols: ColumnLike) -> "GroupedData":
        return GroupedData(self, [_to_expr(c).resolve(self.schema)
                                  for c in cols])

    groupBy = group_by

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def cross_join(self, other: "DataFrame") -> "DataFrame":
        return self.join(other, None, "cross")

    def join(self, other: "DataFrame", on, how: str = "inner") -> "DataFrame":
        jt = {"inner": PN.JoinType.INNER, "left": PN.JoinType.LEFT_OUTER,
              "left_outer": PN.JoinType.LEFT_OUTER,
              "right": PN.JoinType.RIGHT_OUTER,
              "right_outer": PN.JoinType.RIGHT_OUTER,
              "outer": PN.JoinType.FULL_OUTER,
              "full": PN.JoinType.FULL_OUTER,
              "full_outer": PN.JoinType.FULL_OUTER,
              "left_semi": PN.JoinType.LEFT_SEMI, "semi": PN.JoinType.LEFT_SEMI,
              "left_anti": PN.JoinType.LEFT_ANTI, "anti": PN.JoinType.LEFT_ANTI,
              "cross": PN.JoinType.CROSS}[how.lower()]
        if isinstance(on, Expression):
            # non-equi condition -> broadcast nested loop join; the
            # condition resolves against the combined (left ++ right) schema
            combined = T.StructType(list(self.schema.fields)
                                    + list(other.schema.fields))
            cond = on.resolve(combined)
            node = PN.BroadcastNestedLoopJoin(
                self.plan, PN.BroadcastExchange(other.plan), jt, cond)
            return DataFrame(node, self.session)
        if isinstance(on, str):
            on = [on]
        lkeys = [_col(k).resolve(self.schema) for k in on] if on else []
        rkeys = [_col(k).resolve(other.schema) for k in on] if on else []
        np_ = self.session.shuffle_partitions
        if jt == PN.JoinType.CROSS:
            node = PN.SortMergeJoin(self.plan, other.plan, [], [], jt)
            return DataFrame(node, self.session)
        # broadcast if the right side is a small local/file scan
        if _is_broadcastable(other.plan, self.session.conf):
            node = PN.BroadcastHashJoin(
                self.plan, PN.BroadcastExchange(other.plan), lkeys, rkeys, jt)
            return DataFrame(node, self.session)
        lex = PN.Exchange(PN.HashPartitioning(lkeys, np_), self.plan)
        rex = PN.Exchange(PN.HashPartitioning(rkeys, np_), other.plan)
        node = PN.SortMergeJoin(lex, rex, lkeys, rkeys, jt)
        return DataFrame(node, self.session)

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        return DataFrame(PN.Sample(fraction, seed, self.plan), self.session)

    def order_by(self, *cols, ascending=None) -> "DataFrame":
        orders = []
        for i, c in enumerate(cols):
            if isinstance(c, tuple):
                e, spec = c
            else:
                asc = (ascending[i] if isinstance(ascending, (list, tuple))
                       else (ascending if ascending is not None else True))
                e = _to_expr(c)
                spec = SortSpec(ascending=asc, nulls_first=asc)
            orders.append((e.resolve(self.schema), spec))
        return DataFrame(PN.Sort(orders, True, self.plan), self.session)

    orderBy = order_by
    sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(PN.GlobalLimit(n, self.plan), self.session)

    def explode(self, column: ColumnLike, outer: bool = False,
                position: bool = False, out_name: str = "col") -> "DataFrame":
        """explode/posexplode an array column; retains the other columns
        (GpuGenerateExec analog)."""
        gen = _to_expr(column).resolve(self.schema)
        return DataFrame(PN.Generate(gen, self.plan, position=position,
                                     outer=outer, out_name=out_name),
                         self.session)

    def expand(self, projections) -> "DataFrame":
        """Emit one row per projection set per input row (GpuExpandExec;
        the rollup/cube building block).  ``projections`` is a list of
        same-length expression lists; output columns take names/types from
        the first set."""
        resolved = [[_to_expr(e).resolve(self.schema) for e in ps]
                    for ps in projections]
        schema = T.StructType([
            T.StructField(e.name, e.dataType, True) for e in resolved[0]])
        return DataFrame(PN.Expand(resolved, schema, self.plan), self.session)

    def stack(self, n: int, columns, names=None) -> "DataFrame":
        """stack(n, e1..ek): n rows of k//n columns per input row — planned
        as Expand with n projection sets (exact Spark semantics: short
        rows pad with NULL literals).  Reference analog: GpuGenerateExec's
        stack generator (GpuStack)."""
        from spark_rapids_tpu.expr.base import Literal

        exprs = [_to_expr(c).resolve(self.schema) for c in columns]
        k = len(exprs)
        per = (k + n - 1) // n
        names = names or [f"col{i}" for i in range(per)]
        projections = []
        for r in range(n):
            row = []
            for c in range(per):
                i = r * per + c
                if i < k:
                    row.append(exprs[i].alias(names[c]))
                else:
                    row.append(Literal(None, exprs[c].dataType)
                               .alias(names[c]))
            projections.append(row)
        resolved = [[e.resolve(self.schema) for e in ps]
                    for ps in projections]
        schema = T.StructType([
            T.StructField(e.name, e.dataType, True) for e in resolved[0]])
        return DataFrame(PN.Expand(resolved, schema, self.plan),
                         self.session)

    def cache(self) -> "DataFrame":
        """Materialize this DataFrame's batches on first action and reuse
        them (ParquetCachedBatchSerializer analog; device batches held as
        spillable handles)."""
        if isinstance(self.plan, PN.CachedRelation):
            return self
        return DataFrame(PN.CachedRelation(self.plan), self.session)

    persist = cache

    def unpersist(self) -> "DataFrame":
        if isinstance(self.plan, PN.CachedRelation):
            for handles in self.plan.cache_slot.values():
                if isinstance(handles, list):
                    for h in handles:
                        try:
                            h.close()
                        except Exception:
                            pass
            self.plan.cache_slot.clear()
        return self

    def window(self, functions: List[PN.WindowFunction],
               partition_by: Sequence[ColumnLike],
               order_by: Sequence, frame: str = "running") -> "DataFrame":
        pb = [_to_expr(c).resolve(self.schema) for c in partition_by]
        ob = []
        for c in order_by:
            if isinstance(c, tuple):
                e, spec = c
            else:
                e, spec = _to_expr(c), SortSpec()
            ob.append((e.resolve(self.schema), spec))
        fns = [f.resolve(self.schema) for f in functions]
        return DataFrame(PN.Window(fns, pb, ob, self.plan, frame),
                         self.session)

    # -- actions --------------------------------------------------------
    def _planned(self):
        """Apply TpuOverrides; the planned exec tree is cached per conf so
        repeated collects reuse compiled XLA programs (Spark likewise reuses
        a query's compiled stages across executions of the same plan)."""
        from spark_rapids_tpu.config import set_conf
        from spark_rapids_tpu.overrides import TpuOverrides

        conf = self.session.conf
        if not conf.sql_enabled:
            return self.plan, None
        # the execution-ambient conf (config.get_conf): exec nodes read
        # runtime knobs (skew split, groups-cap ladder) through it at
        # execute time, after plan construction has dropped conf refs.
        # Plan+execute run synchronously per collect, so the ambient conf
        # is stable for the query that set it; oracle (sql-disabled)
        # sessions never clobber it
        set_conf(conf)
        from spark_rapids_tpu.resilience.breaker import get_breaker

        # the breaker generation ticks on every planner-visible breaker
        # transition (trip / probe / close), so a plan cached before a
        # stage tripped is re-planned — and re-tagged to the oracle —
        # instead of re-failing on the TPU every collect.  Same rule for
        # the profiling advisory (ISSUE 8): editing/regenerating the
        # advisory file must re-tag cached plans, so its (path, mtime,
        # size) stamp is part of the key — gated on the conf so the
        # disabled path makes zero profiling-module calls
        advisory_key = None
        from spark_rapids_tpu.config import PROFILE_ADVISOR_ENABLED

        if conf.get(PROFILE_ADVISOR_ENABLED):
            from spark_rapids_tpu.profiling.advisor import advisory_state

            advisory_key = advisory_state(conf)
        cache_key = (get_breaker().generation, advisory_key) + tuple(
            sorted((k, str(v)) for k, v in conf.settings.items()))
        cached = getattr(self, "_plan_cache", None)
        if cached is not None and cached[0] == cache_key:
            return cached[1], cached[2]
        root, meta = TpuOverrides.apply(self.plan, conf)
        self._plan_cache = (cache_key, root, meta)
        return root, meta

    def collect(self) -> List[tuple]:
        # Query lifecycle (ISSUE 4): admission slot BEFORE planning, an
        # optional deadline armed by the watchdog, a CancelToken every
        # blocking layer observes, and guaranteed cleanup (semaphore
        # permits, tracked spillables, shuffle registrations) when the
        # exec tree unwinds — even mid-batch
        from spark_rapids_tpu.lifecycle import query_lifecycle

        with query_lifecycle(self.session.conf) as qctx:
            # Telemetry (ISSUE 7): lifecycle-managed queries run under
            # flight-recorder + SLO observation — a few dict appends and
            # one plan walk per QUERY.  The hub check is one ambient
            # attribute read; a telemetry-disabled session skips on the
            # conf alone (zero calls into telemetry modules — pinned by
            # tests/test_telemetry.py).
            hub = _TEL_CTX.HUB
            if hub is not None and qctx is not None:
                from spark_rapids_tpu.config import TELEMETRY_ENABLED

                if self.session.conf.get(TELEMETRY_ENABLED):
                    return hub.observed_collect(self, qctx)
            return self._collect_impl(qctx)

    def _collect_impl(self, qctx) -> List[tuple]:
        from spark_rapids_tpu.cpu.oracle import execute_cpu_plan
        from spark_rapids_tpu.exec.base import TpuExec
        from spark_rapids_tpu.exec.transitions import TpuColumnarToRowExec
        from spark_rapids_tpu.expr.misc import CURRENT_INPUT_FILE

        CURRENT_INPUT_FILE[0] = ""   # InputFileName: "" outside file scans
        root, _meta = self._planned()
        # Crash-consistent recovery (ISSUE 16): journal the planned
        # tree's identity so a reborn driver replanning the same query
        # can prove checkpoint fingerprints refer to the same plan.
        # Disabled (default): one conf read, zero journal-module calls
        # (pinned by tests/test_recovery.py).
        if qctx is not None:
            from spark_rapids_tpu.config import RECOVERY_ENABLED

            if bool(self.session.conf.get(RECOVERY_ENABLED)):
                from spark_rapids_tpu.lifecycle import journal as _jn

                try:
                    _jn.journal_plan(qctx, root, self.session.conf)
                # tpulint: disable=cancel-swallow (durability isolation:
                # the plan record is advisory; losing it weakens the
                # post-mortem, never the query)
                except Exception:
                    pass
        if isinstance(root, TpuExec):
            from spark_rapids_tpu.config import PROFILE_ENABLED
            from spark_rapids_tpu.exec.base import enable_operator_tracing

            enable_operator_tracing(
                root, bool(self.session.conf.get(PROFILE_ENABLED)))
            # Diagnostics (ISSUE 3): one QueryDiagnostics recorder spans
            # the window from AOT submission through execution — operator
            # spans, launch/sync/compile/resilience events, per-operator
            # counter attribution — flushed atomically to the configured
            # sinks on exit and kept on the DataFrame for
            # explain("analyze")
            from spark_rapids_tpu.config import PROFILE_DIR, ambient_conf
            from spark_rapids_tpu.diagnostics import query_scope

            # Profiling (ISSUE 8): with a calibration-store dir set, the
            # finished recorder's operator spans fold into the store and
            # the predicted-vs-actual record lands in the event log —
            # wired as the scope's finish hook so it runs after
            # finish() but before the sinks flush.  Unset (default):
            # one conf read, zero profiling-module calls (pinned by
            # tests/test_profiling.py).
            prof_dir = self.session.conf.get(PROFILE_DIR)
            on_finish = None
            # the prediction is threaded through this box, NOT stashed
            # on the cached (shared) plan root: a losing concurrent
            # collect of the same DataFrame must not clobber the
            # recorded query's prediction
            cost_box = {"pred": None}
            # Accounting (ISSUE 18): with the ledger registry installed
            # and a lifecycle context to own the bill, the finish hook
            # also joins + records the query's resource bill and runs
            # the regression sentinel.  Disabled: one ambient attr read.
            acct_on = _ACCT_CTX.LEDGERS is not None and qctx is not None
            if prof_dir or acct_on:
                _conf = self.session.conf

                def on_finish(diag, _conf=_conf, _box=cost_box,
                              _prof=bool(prof_dir), _acct=acct_on):
                    if _prof:
                        from spark_rapids_tpu.profiling import record_query

                        record_query(diag, _conf,
                                     prediction=_box["pred"])
                    if _acct:
                        from spark_rapids_tpu.accounting import record_bill

                        # AFTER record_query: a freshly folded operator
                        # calibration must not shift THIS query's
                        # sentinel baseline mid-flight (signatures merge
                        # on the same store but are read once here)
                        record_bill(diag, _conf)

            # Progress (ISSUE 12): lifecycle-managed queries register
            # with the process-global live tracker.  Disabled (default):
            # one conf read, zero progress-module calls (pinned by
            # tests/test_progress.py).
            prog_trk = None
            if qctx is not None:
                from spark_rapids_tpu.config import (
                    PROGRESS_ENABLED,
                    PROGRESS_MAX_FINISHED,
                )

                if self.session.conf.get(PROGRESS_ENABLED):
                    from spark_rapids_tpu.progress import ensure_tracker

                    prog_trk = ensure_tracker(int(
                        self.session.conf.get(PROGRESS_MAX_FINISHED)))
            scope = query_scope(self.session.conf, root,
                                on_finish=on_finish)
            try:
                # thread-local conf pin: concurrent collects each read
                # THEIR OWN session conf through config.get_conf() on
                # their own thread, instead of racing the process-global
                # ambient slot _planned() set (ISSUE 4: N queries with
                # different knobs must not clobber each other)
                with ambient_conf(self.session.conf), scope:
                    if scope.diag is not None and qctx is not None:
                        scope.diag.lifecycle(
                            "admitted", qctx.query_id,
                            qctx.admission_wait_ns)
                    # Plan-time cost model (ISSUE 8): predict each
                    # operator's wall/transfer from the calibration
                    # store BEFORE execution (cost_model_* counters land
                    # inside the recorder window and attribute to the
                    # query); the prediction is compared against the
                    # recorded actuals by the finish hook above
                    if prof_dir:
                        from spark_rapids_tpu.profiling import (
                            annotate_plan,
                        )

                        cost_box["pred"] = annotate_plan(
                            root, self.session.conf,
                            attributed=scope.diag is not None)
                    # Progress registration AFTER the cost model ran:
                    # the prediction joins per-operator predicted walls
                    # into percent-complete / ETA; without a store the
                    # tracker falls back to plan row estimates
                    if prog_trk is not None:
                        from spark_rapids_tpu.config import (
                            PROGRESS_STALL_MS,
                        )

                        prog_trk.register(
                            qctx, root,
                            stall_ms=float(self.session.conf.get(
                                PROGRESS_STALL_MS)),
                            prediction=cost_box["pred"],
                            diag_qid=(scope.diag.query_id
                                      if scope.diag is not None
                                      else None))
                        # live explain("analyze") key: while this
                        # collect is in flight, analyze renders the
                        # LIVE snapshot instead of the last post-hoc
                        # recorder
                        self._live_progress_qid = qctx.query_id
                    # progress finish must cover EVERYTHING after
                    # registration: a raise below (bad injection spec,
                    # semaphore conf parse) would otherwise leave a
                    # ghost "running" query in the tracker forever
                    _prog_status = "error"
                    try:
                        # Plan-time AOT pipeline (compilecache/aot.py):
                        # enumerate the stage programs this exec tree
                        # will need and compile them on the background
                        # pool NOW, so the first operator's first batch
                        # overlaps the compiles of everything
                        # downstream.  Idempotent per planned tree; a
                        # warm-up failure never reaches the query.
                        # AFTER progress registration: a compile
                        # finishing before register() would drop its
                        # background attribution on the floor.
                        from spark_rapids_tpu.compilecache import (
                            maybe_submit_aot,
                        )

                        maybe_submit_aot(root, self.session.conf)
                        # Admission control: the thread driving this
                        # query's iterator chain holds a TpuSemaphore
                        # permit while it touches the device (reference:
                        # GpuSemaphore.acquireIfNecessary at first
                        # batch).
                        from spark_rapids_tpu.memory import (
                            get_semaphore,
                            get_spill_framework,
                        )
                        from spark_rapids_tpu.memory.retry import (
                            force_retry_oom,
                            force_split_and_retry_oom,
                        )
                        from spark_rapids_tpu.config import (
                            TEST_RETRY_OOM_INJECTION_MODE,
                        )

                        get_spill_framework(self.session.conf)
                        inject = self.session.conf.get(
                            TEST_RETRY_OOM_INJECTION_MODE)
                        if inject and inject != "NONE":
                            kind, _, n = inject.partition(":")
                            if kind.upper() == "RETRY":
                                force_retry_oom(int(n or 1))
                            elif kind.upper() == "SPLIT":
                                force_split_and_retry_oom(int(n or 1))
                        # chaos injection (the force_retry_oom API
                        # generalized to compile/transient/poison faults
                        # at named operators); armed once per distinct
                        # spec, process-global like the fault list
                        from spark_rapids_tpu.config import (
                            RESILIENCE_TEST_INJECT,
                        )
                        from spark_rapids_tpu.resilience.faults import (
                            arm_conf_spec,
                        )

                        arm_conf_spec(self.session.conf.get(
                            RESILIENCE_TEST_INJECT))
                        from spark_rapids_tpu.config import (
                            SEMAPHORE_ACQUIRE_TIMEOUT_MS,
                        )

                        sem_timeout_ms = int(self.session.conf.get(
                            SEMAPHORE_ACQUIRE_TIMEOUT_MS))
                        sem = get_semaphore(
                            self.session.conf.concurrent_tpu_tasks)
                        try:
                            with sem.scope(
                                    timeout=(sem_timeout_ms / 1000.0
                                             if sem_timeout_ms > 0
                                             else None)):
                                host = TpuColumnarToRowExec(
                                    root).collect_host()
                        except Exception as e:
                            from spark_rapids_tpu.lifecycle.context import (
                                QueryCancelled,
                                QueryDeadlineExceeded,
                            )

                            if isinstance(e, QueryCancelled) \
                                    and scope.diag is not None:
                                scope.diag.lifecycle(
                                    "deadline_trip"
                                    if isinstance(e, QueryDeadlineExceeded)
                                    else "cancelled", str(e))
                            # the whole-query CPU re-run makes no batch
                            # pulls: exempt it from stall detection so
                            # the frozen clock is not read as a wedge
                            if prog_trk is not None:
                                prog_trk.mark_untracked(qctx.query_id)
                            host = self._query_fallback(e)
                        _prog_status = "ok"
                    except BaseException as _pe:
                        _prog_status = type(_pe).__name__
                        raise
                    finally:
                        # progress finish INSIDE the diagnostics scope:
                        # the summary event must land before query_end.
                        # Compare-and-clear the live-explain key: a
                        # concurrent collect of the same DataFrame may
                        # have overwritten it with ITS query id
                        if prog_trk is not None:
                            if getattr(self, "_live_progress_qid",
                                       None) == qctx.query_id:
                                self._live_progress_qid = None
                            prog_trk.finish_query(qctx.query_id,
                                                  _prog_status)
            finally:
                # None when this collect ran unrecorded; assigned on the
                # FAILURE path too — explain("analyze") must not report a
                # stale previous query's diagnostics as if they described
                # the latest (failed) execution
                self._last_diag = scope.diag
            lists = [h.to_pylist() for h in host]
            return list(zip(*lists)) if lists else []
        # full-oracle runs pin the session conf thread-locally too: the
        # oracle file scan reads the per-file tolerance confs (ISSUE 5)
        # through config.get_conf(), which must see THIS session's
        # settings, not the process-global slot
        from spark_rapids_tpu.config import ambient_conf

        with ambient_conf(self.session.conf):
            cols, n = execute_cpu_plan(
                root, ansi=self.session.conf.ansi_enabled)
        lists = [c.to_pylist() for c in cols]
        return list(zip(*lists)) if lists else []

    def _query_fallback(self, exc: Exception):
        """Whole-query oracle fallback of last resort: a deterministic
        failure that escaped every stage-level fault domain (e.g. a stage
        with no CPU twin, or a mid-stream failure after yields) re-runs
        the ORIGINAL logical plan on the CPU oracle — the runtime analog
        of spark.rapids.sql.enabled=false.  Semantic errors (ANSI,
        FAILFAST) and recoverable classes re-raise unchanged; if the
        oracle also fails, the original device error stays primary."""
        from spark_rapids_tpu import perfcounters as PC
        from spark_rapids_tpu.config import (
            RESILIENCE_ENABLED,
            RESILIENCE_RUNTIME_FALLBACK,
        )
        from spark_rapids_tpu.cpu.oracle import execute_cpu_plan
        from spark_rapids_tpu.resilience.classify import (
            DETERMINISTIC,
            classify_failure,
        )

        conf = self.session.conf
        if not (conf.get(RESILIENCE_ENABLED)
                and conf.get(RESILIENCE_RUNTIME_FALLBACK)):
            raise exc
        # a transient/OOM failure whose retry budget a stage domain
        # already exhausted is as good as deterministic here — retrying
        # the whole query would re-derive the same exhaustion
        if classify_failure(exc) != DETERMINISTIC \
                and not getattr(exc, "_srt_retries_exhausted", False):
            raise exc
        try:
            cols, _n = execute_cpu_plan(self.plan,
                                        ansi=conf.ansi_enabled)
        except Exception as oracle_err:
            raise exc from oracle_err
        PC.bump("query_fallbacks")
        from spark_rapids_tpu.diagnostics import context as DIAG_CTX

        rec = DIAG_CTX.RECORDER
        if rec is not None:
            rec.resilience("query_fallback", "collect",
                           f"{type(exc).__name__}: {exc}")
        return [c.to_host() for c in cols]

    def to_pydict(self) -> Dict[str, list]:
        rows = self.collect()
        names = self.columns
        return {n: [r[i] for r in rows] for i, n in enumerate(names)}

    def count(self) -> int:
        rows = self.agg(("count_star", None, "count")).collect()
        return int(rows[0][0]) if rows else 0

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    def metrics_report(self) -> str:
        """Per-operator metrics CUMULATIVE across every execution of this
        DataFrame's cached plan (run collect() first) — the Spark SQL UI
        metrics analog, which likewise accumulates across a query's
        tasks."""
        root, _ = self._planned()
        from spark_rapids_tpu.exec.base import TpuExec

        if isinstance(root, TpuExec):
            return root.metrics_report()
        return "(plan ran on the CPU oracle; no TPU metrics)"

    def explain(self, mode: str = "formatted") -> str:
        """``mode="analyze"``: re-print the plan tree annotated with each
        node's metrics, attributed counter deltas, compile-cache hits,
        and fallback status from the LAST collect() (requires
        spark.rapids.tpu.diagnostics.enabled for the counter columns;
        falls back to metrics-only otherwise) — the diagnostics analog of
        Spark's AQE ``explain`` with runtime statistics.

        ``mode="cost"``: annotate the plan with the profiling cost
        model's PRE-execution predictions — per-operator wall / transfer
        bytes / confidence from the calibration store
        (spark.rapids.tpu.profile.dir), plus predicted-vs-actual when
        the last collect was diagnosed (docs/profiling.md)."""
        from spark_rapids_tpu.exec.base import TpuExec

        if mode == "cost":
            from spark_rapids_tpu.profiling import explain_cost

            return explain_cost(self)
        if mode == "analyze":
            # Live introspection (ISSUE 12): while a collect of this
            # DataFrame is in flight, analyze renders the LIVE progress
            # snapshot (operator table, pct/ETA, background work)
            # instead of the last finished recorder — checked BEFORE
            # _planned() so an explain from another thread never
            # touches plan state mid-collect
            qid = getattr(self, "_live_progress_qid", None)
            if qid is not None:
                from spark_rapids_tpu.progress import (
                    render_snapshot,
                    snapshot_for,
                )

                snap = snapshot_for(qid)
                if snap is not None and snap["status"] == "running":
                    return ("live progress (query in flight — see "
                            "docs/progress.md):\n" + render_snapshot(snap))
        root, meta = self._planned()
        if mode == "analyze":
            if not isinstance(root, TpuExec):
                return "(plan ran on the CPU oracle; no TPU metrics)"
            from spark_rapids_tpu.config import METRICS_LEVEL
            from spark_rapids_tpu.diagnostics.report import analyze_tree

            return analyze_tree(root, getattr(self, "_last_diag", None),
                                meta,
                                self.session.conf.get(METRICS_LEVEL))
        s = root.pretty() if isinstance(root, TpuExec) else root.pretty()
        if meta is not None:
            fb = meta.explain(only_fallback=True)
            if fb:
                s += "\nFallback reasons:\n" + fb
        return s


class DataFrameWriter:
    """df.write API (DataFrameWriter analog); executes the write command
    through the plan rewrite so GPU-vs-CPU write placement follows the same
    tagging rules as reads."""

    def __init__(self, df: DataFrame):
        self.df = df
        self._mode = "overwrite"
        self._partition_by: List[str] = []
        self._options: Dict[str, str] = {}

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def option(self, k, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def _run(self, fmt: str, path: str) -> None:
        node = PN.InsertIntoHadoopFsRelation(
            fmt, path, self.df.plan, self._partition_by, self._mode,
            self._options)
        DataFrame(node, self.df.session).collect()

    def parquet(self, path: str) -> None:
        self._run("parquet", path)

    def orc(self, path: str) -> None:
        self._run("orc", path)

    def csv(self, path: str) -> None:
        self._run("csv", path)

    def json(self, path: str) -> None:
        self._run("json", path)

    def iceberg(self, path: str) -> None:
        from spark_rapids_tpu.io.iceberg import write_iceberg

        mode = {"error": "error", "errorifexists": "error"}.get(
            self._mode, self._mode)
        write_iceberg(self.df, path, mode=mode,
                      partition_by=self._partition_by)

    def delta(self, path: str) -> None:
        from spark_rapids_tpu.delta import write_delta

        mode = {"overwrite": "overwrite", "append": "append",
                "error": "error", "errorifexists": "error",
                "ignore": "ignore"}.get(self._mode, self._mode)
        write_delta(self.df, path, mode=mode,
                    partition_by=self._partition_by)


def _estimated_plan_bytes(plan: PN.SparkPlan):
    """Size estimate for broadcast decisions; None = unknown (never
    broadcast).  LocalTableScan: exact host bytes; FileSourceScan: file
    sizes on disk (the stats Spark reads from the file system)."""
    if isinstance(plan, PN.LocalTableScan):
        total = 0
        for h in plan.host_columns:
            if h.chars is not None:
                total += int(h.lengths.sum()) + 4 * h.num_rows
            elif h.data is not None:
                total += h.data.nbytes
            total += h.num_rows  # validity
        return total
    if isinstance(plan, PN.FileSourceScan):
        import os

        try:
            return sum(os.path.getsize(p) for p in plan.paths)
        except OSError:
            return None
    if isinstance(plan, (PN.Project, PN.Filter, PN.GlobalLimit,
                         PN.LocalLimit, PN.CachedRelation)):
        # narrow nodes: bounded by the child (filters/limits only shrink)
        return _estimated_plan_bytes(plan.children[0])
    return None


def _is_broadcastable(plan: PN.SparkPlan, conf) -> bool:
    """spark.sql.autoBroadcastJoinThreshold applied to the size estimate
    (reference: GpuBroadcastHashJoin selection; fixes VERDICT r1 weak #5 —
    a 10-row file scan now broadcasts instead of shuffling both sides)."""
    from spark_rapids_tpu.config import AUTO_BROADCAST_JOIN_THRESHOLD

    threshold = conf.get(AUTO_BROADCAST_JOIN_THRESHOLD)
    if threshold < 0:
        return False
    est = _estimated_plan_bytes(plan)
    return est is not None and est <= threshold


def _named(e: Expression, i: int) -> Expression:
    return e


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[Expression]):
        self.df = df
        self.keys = keys

    def agg(self, *aggs) -> "DataFrame":
        """aggs: tuples (func, column-or-None, result_name) or
        AggregateExpression.  count_distinct/sum_distinct expand to a
        two-level aggregation at plan time (dedup on (keys, expr), then
        aggregate) — Spark's single-distinct-column rewrite — so both the
        TPU path and the oracle execute the same plan."""
        specs = list(aggs)
        distinct = [a for a in specs if isinstance(a, tuple)
                    and a[0] in ("count_distinct", "sum_distinct")]
        if distinct:
            if len(distinct) != len(specs):
                raise NotImplementedError(
                    "mixing distinct and non-distinct aggregates is not "
                    "supported yet")
            children = {str(a[1]) for a in distinct}
            if len(children) != 1:
                raise NotImplementedError(
                    "distinct aggregates over multiple columns are not "
                    "supported yet")
            schema = self.df.schema
            dcol = _to_expr(distinct[0][1]).resolve(schema)
            dedup = GroupedData(self.df, self.keys + [dcol]).agg()
            outer_keys = [k.name for k in self.keys]
            outer = [(a[0].replace("_distinct", ""), dcol.name, a[2])
                     for a in distinct]
            return dedup.group_by(*outer_keys).agg(*outer) if outer_keys \
                else dedup.agg(*outer)
        collect = [a for a in specs
                   if (isinstance(a, tuple)
                       and a[0] in PN.SINGLE_PHASE_FUNCS)
                   or (isinstance(a, PN.AggregateExpression)
                       and a.func in PN.SINGLE_PHASE_FUNCS)]
        if collect:
            # single-phase plan: co-locate each key's rows with a hash
            # exchange, then ONE COMPLETE-mode aggregate builds the arrays
            # (partial/final would need array-buffer merges)
            schema = self.df.schema
            aexprs = []
            for a in specs:
                if isinstance(a, PN.AggregateExpression):
                    aexprs.append(a.resolve(schema))
                    continue
                func, child, name = a
                ce = _to_expr(child) if child is not None else None
                aexprs.append(PN.AggregateExpression(
                    func, ce, name).resolve(schema))
            if self.keys:
                ex = PN.Exchange(
                    PN.HashPartitioning(self.keys,
                                        self.df.session.shuffle_partitions),
                    self.df.plan)
            else:
                ex = PN.Exchange(PN.SinglePartitioning(), self.df.plan)
            comp = PN.HashAggregate(self.keys, aexprs,
                                    PN.AggregateMode.COMPLETE, ex)
            return DataFrame(comp, self.df.session)
        schema = self.df.schema
        aexprs: List[PN.AggregateExpression] = []
        for a in aggs:
            if isinstance(a, PN.AggregateExpression):
                aexprs.append(a.resolve(schema))
            else:
                func, child, name = a
                ce = _to_expr(child) if child is not None else None
                aexprs.append(PN.AggregateExpression(
                    func, ce, name).resolve(schema))
        np_ = self.df.session.shuffle_partitions
        partial = PN.HashAggregate(self.keys, aexprs,
                                   PN.AggregateMode.PARTIAL, self.df.plan)
        if self.keys:
            # re-key the exchange + final agg on the partial output
            pschema = partial.output
            fkeys = [AttributeReference(g.name).resolve(pschema)
                     for g in self.keys]
            ex = PN.Exchange(PN.HashPartitioning(fkeys, np_), partial)
        else:
            fkeys = []
            ex = PN.Exchange(PN.SinglePartitioning(), partial)
        final_aggs = [PN.AggregateExpression(a.func, a.child, a.result_name,
                                             a.result_type,
                                             child2=a.child2, args=a.args)
                      for a in aexprs]
        final = PN.HashAggregate(fkeys, final_aggs,
                                 PN.AggregateMode.FINAL, ex)
        return DataFrame(final, self.df.session)


# convenience re-exports (pyspark.sql.functions flavored)
col = _col
lit = _lit


def sum_(c: ColumnLike, name: str = "sum") -> Tuple[str, ColumnLike, str]:
    return ("sum", c, name)


def count_(c: Optional[ColumnLike] = None, name: str = "count"):
    return ("count", c, name) if c is not None else ("count_star", None, name)


def count_distinct_(c: ColumnLike, name: str = "count_distinct"):
    return ("count_distinct", c, name)


def sum_distinct_(c: ColumnLike, name: str = "sum_distinct"):
    return ("sum_distinct", c, name)


def collect_list_(c: ColumnLike, name: str = "collect_list"):
    return ("collect_list", c, name)


def collect_set_(c: ColumnLike, name: str = "collect_set"):
    return ("collect_set", c, name)


def min_(c: ColumnLike, name: str = "min"):
    return ("min", c, name)


def max_(c: ColumnLike, name: str = "max"):
    return ("max", c, name)


def avg_(c: ColumnLike, name: str = "avg"):
    return ("avg", c, name)


def rlike_(c: ColumnLike, pattern: str):
    from spark_rapids_tpu.expr.strings import RLike

    return RLike(_to_expr(c), _lit(pattern))


def hash_(*cols: ColumnLike):
    from spark_rapids_tpu.expr.hashexprs import Murmur3Hash

    return Murmur3Hash([_to_expr(c) for c in cols])


def xxhash64_(*cols: ColumnLike):
    from spark_rapids_tpu.expr.hashexprs import XxHash64

    return XxHash64([_to_expr(c) for c in cols])


def stddev_(c: ColumnLike, name: str = "stddev"):
    return ("stddev_samp", c, name)


def stddev_pop_(c: ColumnLike, name: str = "stddev_pop"):
    return ("stddev_pop", c, name)


def variance_(c: ColumnLike, name: str = "variance"):
    return ("var_samp", c, name)


def var_pop_(c: ColumnLike, name: str = "var_pop"):
    return ("var_pop", c, name)


def count_if_(c: ColumnLike, name: str = "count_if"):
    return ("count_if", c, name)


def skewness_(c: ColumnLike, name: str = "skewness"):
    return ("skewness", c, name)


def kurtosis_(c: ColumnLike, name: str = "kurtosis"):
    return ("kurtosis", c, name)


def bool_and_(c: ColumnLike, name: str = "bool_and"):
    return PN.AggregateExpression("bool_and", _to_expr(c), name)


def bool_or_(c: ColumnLike, name: str = "bool_or"):
    return PN.AggregateExpression("bool_or", _to_expr(c), name)


def bit_and_(c: ColumnLike, name: str = "bit_and"):
    return PN.AggregateExpression("bit_and", _to_expr(c), name)


def bit_or_(c: ColumnLike, name: str = "bit_or"):
    return PN.AggregateExpression("bit_or", _to_expr(c), name)


def bit_xor_(c: ColumnLike, name: str = "bit_xor"):
    return PN.AggregateExpression("bit_xor", _to_expr(c), name)


def any_value_(c: ColumnLike, name: str = "any_value"):
    return PN.AggregateExpression("any_value", _to_expr(c), name)


def median_(c: ColumnLike, name: str = "median"):
    return PN.AggregateExpression("median", _to_expr(c), name)


def _regr(func):
    def helper(y: ColumnLike, x: ColumnLike, name: str = None):
        return PN.AggregateExpression(func, _to_expr(y), name or func,
                                      child2=_to_expr(x))
    helper.__name__ = func + "_"
    return helper


regr_count_ = _regr("regr_count")
regr_avgx_ = _regr("regr_avgx")
regr_avgy_ = _regr("regr_avgy")
regr_sxx_ = _regr("regr_sxx")
regr_syy_ = _regr("regr_syy")
regr_sxy_ = _regr("regr_sxy")
regr_slope_ = _regr("regr_slope")
regr_intercept_ = _regr("regr_intercept")
regr_r2_ = _regr("regr_r2")


def corr_(x: ColumnLike, y: ColumnLike, name: str = "corr"):
    return PN.AggregateExpression("corr", _to_expr(x), name,
                                  child2=_to_expr(y))


def covar_pop_(x: ColumnLike, y: ColumnLike, name: str = "covar_pop"):
    return PN.AggregateExpression("covar_pop", _to_expr(x), name,
                                  child2=_to_expr(y))


def covar_samp_(x: ColumnLike, y: ColumnLike, name: str = "covar_samp"):
    return PN.AggregateExpression("covar_samp", _to_expr(x), name,
                                  child2=_to_expr(y))


def percentile_(c: ColumnLike, percentage: float, name: str = "percentile"):
    return PN.AggregateExpression("percentile", _to_expr(c), name,
                                  args=(float(percentage),))


def approx_percentile_(c: ColumnLike, percentage: float,
                       accuracy: int = 10000,
                       name: str = "approx_percentile"):
    return PN.AggregateExpression("approx_percentile", _to_expr(c), name,
                                  args=(float(percentage), int(accuracy)))


def approx_count_distinct_(c: ColumnLike,
                           name: str = "approx_count_distinct"):
    return ("approx_count_distinct", c, name)


def bloom_filter_agg_(c: ColumnLike, name: str = "bloom_filter_agg",
                      num_items: int = 4096, num_bits: int = 65536):
    return PN.AggregateExpression("bloom_filter_agg", _to_expr(c), name,
                                  args=(int(num_items), int(num_bits)))
