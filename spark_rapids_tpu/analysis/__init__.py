"""tpulint — the project-invariant static analyzer (ISSUE 9).

Eight PRs accumulated load-bearing concurrency and accounting
invariants — lock-guarded ``perfcounters.bump()``, PROPAGATE-classified
cancellation that must never be swallowed, ``sync_event``-accounted host
syncs, the semaphore-before-spill lock order, the registered
conf/counter/event vocabularies — all enforced only at runtime, so a
regression surfaced as a flaky stress failure instead of a CI error.
This package turns them into machine-checked gates: one ``ast.parse``
per file, every rule's visitors multiplexed over that single tree walk,
structured findings (file:line + rule id + fix hint), a
``# tpulint: disable=<rule>`` pragma for justified exceptions, and a
JSON baseline for grandfathered findings.

Two tiers of rules:

* Tier A — project-invariant lints (:mod:`rules_invariants`):
  counter-write discipline, cancellation-swallow detection, unaccounted
  host syncs, conf-vocabulary resolution, thread-unsafe module state,
  unlocked read-modify-writes; plus the conf/counter/event doc-drift
  checks folded in from ``tools/check_counters.py``
  (:mod:`rules_docs`).
* Tier B — a lockset-based race/deadlock detector
  (:mod:`rules_lockset`): per-class dominant-lock inference with
  mixed-guard write detection, and the inter-lock acquisition-order
  graph with cycle detection (the static twin of the runtime guard in
  ``memory/semaphore.py``).

Entry points: :func:`run_paths` (importable API, used by the tier-1
gate in ``tests/test_lint.py``) and ``tools/lint.py`` (CLI with
``--baseline`` / ``--json`` / ``--fail-on-new``).
"""
from __future__ import annotations

from spark_rapids_tpu.analysis.core import (
    Baseline,
    Finding,
    run_paths,
    to_json,
)

__all__ = ["Finding", "Baseline", "run_paths", "to_json"]
