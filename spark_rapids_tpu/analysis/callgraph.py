"""Interprocedural call graph rooted at the jit boundary.

tracelint's foundation: every ``jax.jit`` / ``tpu_jit`` / ``pallas_call``
/ ``shard_map`` / ``cached_jit_program`` site roots a **traced region** —
the referenced function plus everything reachable from it through calls
the resolver can bind (module-local names through nested scopes,
``self``-methods through the class table and its base chain, imported
names through the per-file alias map — the same resolution vocabulary
``rules_lockset`` uses, extended with nested-``def`` scoping and
lambda-default following for the ``lambda _fn=fn: tpu_jit(_fn)`` idiom).

On top of the region the builder runs a **shallow taint** analysis:
every parameter of a root function is a traced value; taint propagates
through arithmetic/comparison operators, subscripts, attribute loads,
``jnp.``/``jax.``/``lax.``/``pl.`` calls, and tuple packing/unpacking —
and deliberately NOT through constructor calls, comprehensions, or
user-function returns.  That asymmetry is the point: the trace rules
that consume the taint (``rules_trace``) must never storm false
positives, so the taint under-approximates and the region rules
(conf reads, side effects) carry the recall.  Limits are documented in
docs/static_analysis.md.

One parse per file still holds: the builder only reads ``ctx.tree``
objects the engine already parsed.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.core import FileCtx

# wrappers whose FIRST function-valued argument becomes a trace root
JIT_WRAPPERS = frozenset(("jit", "tpu_jit", "pallas_call", "shard_map"))
# registry entry point: cached_jit_program(key_parts, builder) traces arg 1
BUILDER_WRAPPERS = {"cached_jit_program": 1}
# jax.lax higher-order combinators: function-valued args join the caller's
# region (they only ever run under an enclosing trace)
HOF_FN_ARGS = {
    "fori_loop": (2,), "while_loop": (0, 1), "scan": (0,),
    "cond": (1, 2), "switch": (1, 2, 3, 4, 5), "map": (0,),
    "vmap": (0,), "custom_vjp": (0,), "checkpoint": (0,), "remat": (0,),
}
# attribute-chain roots whose calls return traced values for taint
ARRAY_NAMESPACES = frozenset(("jnp", "jax", "lax", "pl", "plgpu"))
# attribute loads that yield STATIC metadata even on a traced value:
# tracer shape/dtype are Python values (branching on them is legal and
# resolves at trace time), and the columnar containers' schema fields
# (is_string/width/capacity/dtype) are host metadata by construction
STATIC_ATTRS = frozenset((
    "shape", "ndim", "dtype", "size", "nbytes", "capacity", "width",
    "is_string", "is_array", "is_struct", "is_string_array",
    "is_dec128", "is_128", "fields", "names", "aval", "weak_type",
))


def _root_name(expr: ast.AST) -> str:
    """Leftmost Name of an attribute/call chain (``jnp.ops.x`` -> jnp)."""
    while isinstance(expr, (ast.Attribute, ast.Subscript, ast.Call)):
        expr = (expr.value if isinstance(expr, (ast.Attribute,
                                                ast.Subscript))
                else expr.func)
    return expr.id if isinstance(expr, ast.Name) else ""


def _trailing(expr: ast.AST) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


class FuncInfo:
    __slots__ = ("key", "rel", "qual", "node", "params", "ctx",
                 "owner_class", "scope", "defaulted", "call_bindings")

    def __init__(self, key: str, rel: str, qual: str, node: ast.AST,
                 params: List[str], ctx: FileCtx,
                 owner_class: str, scope: Tuple[str, ...],
                 defaulted: Optional[Set[str]] = None):
        self.key = key
        self.rel = rel
        self.qual = qual
        self.node = node
        self.params = params        # ordered positional-or-kw names
        self.ctx = ctx
        self.owner_class = owner_class   # innermost enclosing class, ""
        self.scope = scope               # qual path segments
        self.defaulted = defaulted or set()  # params carrying a default
        # local name -> (callee desc, tuple index|None) for bindings of
        # the `fn, aux = self._stage_fn(...)` form — lets a jit site on
        # `fn` follow the callee's `return fn, aux` to the nested def
        self.call_bindings: Dict[str, Tuple[Tuple, Optional[int]]] = {}

    def receiver_params(self) -> int:
        """1 when calls through ``self``/``cls`` skip the first param."""
        return 1 if self.params[:1] in (["self"], ["cls"]) else 0


class RootSite:
    """Where a traced region is rooted: the jit/pallas/builder call."""

    __slots__ = ("rel", "line", "kind", "owner_class", "scope")

    def __init__(self, rel: str, line: int, kind: str,
                 owner_class: str, scope: Tuple[str, ...]):
        self.rel = rel
        self.line = line
        self.kind = kind
        self.owner_class = owner_class
        self.scope = scope


class _CallRec:
    __slots__ = ("desc", "node", "args", "keywords")

    def __init__(self, desc, node: ast.Call):
        self.desc = desc
        self.node = node
        self.args = node.args
        self.keywords = node.keywords


class CallGraph:
    """Repo-wide function table + call edges + traced-region state."""

    def __init__(self):
        self.funcs: Dict[str, FuncInfo] = {}
        self.calls: Dict[str, List[_CallRec]] = {}
        # per-file import alias maps:
        #   alias -> ("mod", "a/b")      plain `import a.b as alias`
        #   alias -> ("from", "a/b", "name")  `from a.b import name`
        self.aliases: Dict[str, Dict[str, Tuple]] = {}
        # (rel, ClassName) -> list of base descriptors (raw AST exprs)
        self.class_bases: Dict[Tuple[str, str], List[ast.AST]] = {}
        self.jit_sites: List[Tuple[FileCtx, ast.Call, str, ast.AST,
                                   Tuple[str, ...], str]] = []
        self._site_seen: Set[Tuple[str, int, int]] = set()
        # results of finalize()
        self.traced: Dict[str, RootSite] = {}
        self.tainted_params: Dict[str, Set[str]] = {}
        self.resolved_calls: Dict[str, List[Tuple[str, _CallRec]]] = {}
        self._finalized = False

    # ------------------------------------------------------------------
    # per-file scan
    # ------------------------------------------------------------------
    def scan_file(self, ctx: FileCtx) -> None:
        amap = self.aliases.setdefault(ctx.rel, {})
        scanner = _FileScanner(self, ctx, amap)
        scanner.visit_body(ctx.tree.body, scope=(), owner_class="")

    def _add_func(self, ctx: FileCtx, node, scope: Tuple[str, ...],
                  owner_class: str) -> FuncInfo:
        if isinstance(node, ast.Lambda):
            name = f"<lambda:{node.lineno}>"
            args = node.args
        else:
            name = node.name
            args = node.args
        qual = ".".join(scope + (name,))
        params = ([a.arg for a in args.posonlyargs]
                  + [a.arg for a in args.args]
                  + [a.arg for a in args.kwonlyargs])
        if args.vararg is not None:
            params.append(args.vararg.arg)
        if args.kwarg is not None:
            params.append(args.kwarg.arg)
        positional = ([a.arg for a in args.posonlyargs]
                      + [a.arg for a in args.args])
        defaulted = set(positional[len(positional)
                                   - len(args.defaults):]
                        if args.defaults else ())
        defaulted |= {a.arg for a, d in zip(args.kwonlyargs,
                                            args.kw_defaults)
                      if d is not None}
        key = f"{ctx.rel}::{qual}"
        info = FuncInfo(key, ctx.rel, qual, node, params, ctx,
                        owner_class, scope + (name,), defaulted)
        self.funcs[key] = info
        return info

    # ------------------------------------------------------------------
    # resolution
    # ------------------------------------------------------------------
    def _lookup_scoped(self, rel: str, scope: Tuple[str, ...],
                       name: str) -> Optional[str]:
        """Innermost-out lookup of a function ``name`` visible at
        ``scope`` in file ``rel`` (nested defs included)."""
        for i in range(len(scope), -1, -1):
            qual = ".".join(scope[:i] + (name,))
            key = f"{rel}::{qual}"
            if key in self.funcs:
                return key
        return None

    def _class_chain(self, rel: str, cls: str,
                     _seen=None) -> List[Tuple[str, str]]:
        """(rel, class) plus base classes, depth-first, repo-resolved."""
        _seen = _seen if _seen is not None else set()
        if (rel, cls) in _seen:
            return []
        _seen.add((rel, cls))
        out = [(rel, cls)]
        for base in self.class_bases.get((rel, cls), ()):  # raw exprs
            bname = _trailing(base)
            if not bname:
                continue
            if (rel, bname) in self.class_bases:
                out.extend(self._class_chain(rel, bname, _seen))
                continue
            # imported base: follow the from-import alias
            tgt = self.aliases.get(rel, {}).get(bname)
            if tgt is not None and tgt[0] == "from":
                brel = tgt[1] + ".py"
                for (frel, fcls) in self.class_bases:
                    if frel.endswith(brel) and fcls == tgt[2]:
                        out.extend(self._class_chain(frel, fcls, _seen))
                        break
        return out

    def _lookup_method(self, rel: str, cls: str,
                       attr: str) -> Optional[str]:
        for (crel, cname) in self._class_chain(rel, cls):
            key = f"{crel}::{cname}.{attr}"
            if key in self.funcs:
                return key
        return None

    def resolve(self, desc) -> Optional[str]:
        """Bind a call/function-reference descriptor to a function key."""
        kind = desc[0]
        if kind == "name":
            _, rel, scope, name = desc
            key = self._lookup_scoped(rel, scope, name)
            if key is not None:
                return key
            tgt = self.aliases.get(rel, {}).get(name)
            if tgt is not None and tgt[0] == "from":
                frel, fname = tgt[1] + ".py", tgt[2]
                for k in self.funcs:
                    krel, qual = k.split("::", 1)
                    if krel.endswith(frel) and qual == fname:
                        return k
            return None
        if kind == "self":
            _, rel, cls, attr = desc
            return self._lookup_method(rel, cls, attr)
        if kind == "alias":
            _, rel, alias, attr = desc
            tgt = self.aliases.get(rel, {}).get(alias)
            if tgt is None or tgt[0] != "mod":
                return None
            frel = tgt[1] + ".py"
            for k in self.funcs:
                krel, qual = k.split("::", 1)
                if krel.endswith(frel) and qual == attr:
                    return k
            return None
        if kind == "objattr":
            # method reference through an untyped object: resolve in the
            # current class chain first, else a same-file unique match
            _, rel, cls, attr = desc
            if cls:
                key = self._lookup_method(rel, cls, attr)
                if key is not None:
                    return key
            hits = [f"{rel}::{cname}.{attr}"
                    for (crel, cname) in self.class_bases
                    if crel == rel
                    and f"{rel}::{cname}.{attr}" in self.funcs]
            if len(set(hits)) == 1:
                return hits[0]
            return None
        return None

    # ------------------------------------------------------------------
    # finalize: traced regions + taint fixpoint
    # ------------------------------------------------------------------
    def finalize(self) -> None:
        if self._finalized:
            return
        self._finalized = True
        for caller, recs in self.calls.items():
            lst = []
            for rec in recs:
                callee = self.resolve(rec.desc)
                if callee is not None and callee != caller:
                    lst.append((callee, rec))
            if lst:
                self.resolved_calls[caller] = lst

        work: List[str] = []
        for (ctx, call, kind, fn_expr, scope, owner) in self.jit_sites:
            keys = []
            key = self._resolve_fn_expr(ctx, fn_expr, scope, owner)
            if key is not None:
                keys.append(key)
            else:
                keys.extend(self._param_fed_roots(ctx, fn_expr, scope))
            static = _partial_bound(fn_expr)
            for key in keys:
                site = RootSite(ctx.rel, call.lineno, kind, owner, scope)
                if key not in self.traced:
                    self.traced[key] = site
                info = self.funcs[key]
                # defaulted params of a ROOT function are closure
                # constants (the `def fn(cols, n, _b=groups_cap)`
                # idiom): jax traces only arguments actually passed,
                # and the jit-boundary call is invisible to the
                # resolver — taint reaching a defaulted param through a
                # resolved INTERIOR call still applies.  Same for
                # params bound by a `partial(kernel, bw=bw)` wrapper.
                seed = set(info.params) - info.defaulted
                if static is not None:
                    names, npos = static
                    seed -= names | set(
                        info.params[info.receiver_params():][:npos])
                grew = self._taint_params(key, seed)
                if key not in work or grew:
                    work.append(key)

        # BFS/fixpoint: propagate region membership + param taint
        while work:
            key = work.pop()
            info = self.funcs.get(key)
            if info is None:
                continue
            root = self.traced[key]
            local = self.local_taint(key)
            for callee, rec in self.resolved_calls.get(key, ()):
                cinfo = self.funcs.get(callee)
                if cinfo is None:
                    continue
                newly = callee not in self.traced
                if newly:
                    self.traced[callee] = root
                tainted = set()
                # calls through self/cls skip the receiver param, so
                # positional args align one slot later
                off = (cinfo.receiver_params()
                       if rec.desc[0] in ("self", "objattr") else 0)
                for i, arg in enumerate(rec.args):
                    if i + off < len(cinfo.params) and self.expr_tainted(
                            arg, local):
                        tainted.add(cinfo.params[i + off])
                for kw in rec.keywords:
                    if kw.arg and kw.arg in cinfo.params \
                            and self.expr_tainted(kw.value, local):
                        tainted.add(kw.arg)
                grew = self._taint_params(callee, tainted)
                if newly or grew:
                    work.append(callee)
            # HOF fn-args join the region with fully-tainted params
            for hof_key in self._hof_fn_refs(info):
                if hof_key in self.funcs:
                    newly = hof_key not in self.traced
                    if newly:
                        self.traced[hof_key] = root
                    grew = self._taint_params(
                        hof_key, set(self.funcs[hof_key].params))
                    if newly or grew:
                        work.append(hof_key)

    def _param_fed_roots(self, ctx: FileCtx, fn_expr: ast.AST,
                         scope: Tuple[str, ...]) -> List[str]:
        """A jit site over a PARAM of its enclosing function (the
        ``_cached_jit(self, attr, kind, builder)`` shape): resolve the
        actual builder expressions at every resolved caller."""
        if not (isinstance(fn_expr, ast.Name) and scope):
            return []
        enc_key = f"{ctx.rel}::" + ".".join(scope)
        enc = self.funcs.get(enc_key)
        if enc is None or fn_expr.id not in enc.params:
            return []
        pos = enc.params.index(fn_expr.id)
        out = []
        for caller in sorted(self.resolved_calls):
            for callee, rec in self.resolved_calls[caller]:
                if callee != enc_key:
                    continue
                cinfo = self.funcs.get(caller)
                if cinfo is None:
                    continue
                # the caller's call is itself a registered jit site
                # (`tpu_jit(...)` resolved into the tpu_jit WRAPPER's
                # own `jax.jit(fn)`): the lexical site already rooted
                # it, with better partial/lambda context
                if (cinfo.rel, rec.node.lineno,
                        rec.node.col_offset) in self._site_seen:
                    continue
                arg = None
                apos = pos - (enc.receiver_params()
                              if rec.desc[0] in ("self", "objattr")
                              else 0)
                if 0 <= apos < len(rec.args):
                    arg = rec.args[apos]
                else:
                    for kw in rec.keywords:
                        if kw.arg == fn_expr.id:
                            arg = kw.value
                if arg is not None:
                    key = self._resolve_fn_expr(cinfo.ctx, arg,
                                                cinfo.scope,
                                                cinfo.owner_class)
                    if key is not None:
                        out.append(key)
        return out

    def _taint_params(self, key: str, params: Set[str]) -> bool:
        # `self`/`cls` are never traced arrays — a method's receiver is
        # plan-node state, and tainting it would mark every attribute
        # read (self.mode, self.grouping) as a traced value
        cur = self.tainted_params.setdefault(key, set())
        before = len(cur)
        cur |= params - {"self", "cls"}
        return len(cur) > before

    def _resolve_fn_expr(self, ctx: FileCtx, expr: ast.AST,
                         scope: Tuple[str, ...],
                         owner: str, _depth: int = 0) -> Optional[str]:
        """Bind the function-valued argument of a jit site to a key."""
        if _depth > 8:
            return None
        if isinstance(expr, ast.Lambda):
            return f"{ctx.rel}::" + ".".join(
                scope + (f"<lambda:{expr.lineno}>",))
        if isinstance(expr, ast.Call):
            name = _trailing(expr.func)
            # tpu_jit(pl.pallas_call(kernel, ...)) — unwrap one level;
            # partial(kernel, bw=bw) binds closure constants only
            if name in JIT_WRAPPERS and expr.args:
                return self._resolve_fn_expr(ctx, expr.args[0], scope,
                                             owner, _depth + 1)
            if name == "partial" and expr.args:
                return self._resolve_fn_expr(ctx, expr.args[0], scope,
                                             owner, _depth + 1)
            # kernel RETURNED by a resolvable callee:
            # `tpu_jit(self._chain_fn(...))` — follow `return fn`
            desc = self._fn_desc(ctx, expr.func, scope, owner)
            callee = self.resolve(desc) if desc is not None else None
            if callee is not None:
                return self._returned_fn_key(callee, None, _depth + 1)
            return None
        desc = self._fn_desc(ctx, expr, scope, owner)
        key = self.resolve(desc) if desc is not None else None
        if key is not None:
            return key
        # name bound from a resolvable call in an enclosing function:
        # `fn, aux = self._stage_fn(...); ... tpu_jit(fn)` — follow the
        # callee's `return fn, aux` through the tuple index
        if isinstance(expr, ast.Name):
            for i in range(len(scope), 0, -1):
                enc = self.funcs.get(
                    f"{ctx.rel}::" + ".".join(scope[:i]))
                if enc is None:
                    continue
                bound = enc.call_bindings.get(expr.id)
                if bound is None:
                    continue
                callee = self.resolve(bound[0])
                if callee is not None:
                    return self._returned_fn_key(callee, bound[1],
                                                 _depth + 1)
        return None

    def _returned_fn_key(self, callee: str, index: Optional[int],
                         _depth: int) -> Optional[str]:
        """The function key ``callee`` returns (element ``index`` of a
        returned tuple, or the bare return value)."""
        info = self.funcs.get(callee)
        if info is None:
            return None
        if isinstance(info.node, ast.Lambda):
            values = [info.node.body]
        else:
            values = [st.value
                      for st in _own_statements(info.node.body)
                      if isinstance(st, ast.Return)
                      and st.value is not None]
        for v in values:
            if index is not None:
                if not (isinstance(v, (ast.Tuple, ast.List))
                        and index < len(v.elts)):
                    continue
                v = v.elts[index]
            key = self._resolve_fn_expr(info.ctx, v, info.scope,
                                        info.owner_class, _depth)
            if key is not None and key != callee:
                return key
        return None

    def _fn_desc(self, ctx: FileCtx, expr: ast.AST,
                 scope: Tuple[str, ...], owner: str):
        if isinstance(expr, ast.Name):
            return ("name", ctx.rel, scope, expr.id)
        if isinstance(expr, ast.Attribute):
            v = expr.value
            if isinstance(v, ast.Name):
                if v.id == "self" and owner:
                    return ("self", ctx.rel, owner, expr.attr)
                if v.id in self.aliases.get(ctx.rel, {}):
                    return ("alias", ctx.rel, v.id, expr.attr)
                return ("objattr", ctx.rel, owner, expr.attr)
            # chained value (self.detached_for_trace()._fn, clone._fn...)
            return ("objattr", ctx.rel, owner, expr.attr)
        return None

    def _hof_fn_refs(self, info: FuncInfo) -> List[str]:
        """Function keys referenced as fn-args of jax.lax HOF calls in
        ``info``'s body."""
        out = []
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            idxs = HOF_FN_ARGS.get(_trailing(node.func))
            if idxs is None:
                continue
            if _root_name(node.func) not in ARRAY_NAMESPACES \
                    and _trailing(node.func) not in ("vmap",):
                continue
            for i in idxs:
                if i < len(node.args):
                    # info.scope (not [:-1]): the fn arg is an
                    # expression INSIDE the function, so its own nested
                    # defs/lambdas are visible — the common
                    # `def body(...): ...; lax.fori_loop(0, n, body, x)`
                    # idiom
                    key = self._resolve_fn_expr(
                        info.ctx, node.args[i], info.scope,
                        info.owner_class)
                    if key is not None:
                        out.append(key)
        return out

    # ------------------------------------------------------------------
    # shallow taint
    # ------------------------------------------------------------------
    def local_taint(self, key: str) -> Set[str]:
        """Names holding traced values inside function ``key``, given
        its tainted parameters — the shallow-propagation fixpoint."""
        info = self.funcs.get(key)
        if info is None:
            return set()
        tainted = set(self.tainted_params.get(key, ()))
        body = getattr(info.node, "body", [])
        if isinstance(info.node, ast.Lambda):
            return tainted
        stmts = list(_own_statements(body))
        changed = True
        while changed:
            changed = False
            for st in stmts:
                targets: List[ast.AST] = []
                value = None
                if isinstance(st, ast.Assign):
                    targets, value = st.targets, st.value
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    targets, value = [st.target], st.value
                elif isinstance(st, ast.AugAssign):
                    targets, value = [st.target], st.value
                elif isinstance(st, ast.For):
                    targets, value = [st.target], st.iter
                elif isinstance(st, ast.NamedExpr):
                    targets, value = [st.target], st.value
                if value is None or not self.expr_tainted(value, tainted):
                    continue
                for t in targets:
                    for n in _target_names(t):
                        if n not in tainted:
                            tainted.add(n)
                            changed = True
        return tainted

    def expr_tainted(self, expr: ast.AST, tainted: Set[str]) -> bool:
        """Shallow: does ``expr`` propagate a traced value?  (See module
        docstring for the deliberate under-approximation.)"""
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(expr.value, tainted)
        if isinstance(expr, (ast.Subscript, ast.Starred)):
            return self.expr_tainted(expr.value, tainted)
        if isinstance(expr, ast.BinOp):
            return (self.expr_tainted(expr.left, tainted)
                    or self.expr_tainted(expr.right, tainted))
        if isinstance(expr, ast.UnaryOp):
            return self.expr_tainted(expr.operand, tainted)
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_tainted(v, tainted) for v in expr.values)
        if isinstance(expr, ast.Compare):
            # `x is None` / `x is not None` is an IDENTITY check — the
            # standard optional-traced-arg pattern resolves at trace
            # time from the call signature, not from the value
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in expr.ops):
                return False
            return (self.expr_tainted(expr.left, tainted)
                    or any(self.expr_tainted(c, tainted)
                           for c in expr.comparators))
        if isinstance(expr, ast.IfExp):
            return (self.expr_tainted(expr.body, tainted)
                    or self.expr_tainted(expr.orelse, tainted))
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e, tainted) for e in expr.elts)
        if isinstance(expr, ast.Call):
            if _root_name(expr.func) in ARRAY_NAMESPACES:
                return (any(self.expr_tainted(a, tainted)
                            for a in expr.args)
                        or any(self.expr_tainted(kw.value, tainted)
                               for kw in expr.keywords)
                        # jnp methods ON a tainted chain (x.at[...].set)
                        or self.expr_tainted(expr.func, tainted))
            # method call on a tainted object keeps the taint
            # (col.data.astype(...), x.reshape(...))
            if isinstance(expr.func, ast.Attribute) \
                    and self.expr_tainted(expr.func.value, tainted):
                return True
            return False
        return False


def _partial_bound(expr: ast.AST) -> Optional[Tuple[Set[str], int]]:
    """(keyword names, positional count) a ``partial(...)`` wrapper on
    the jit site's fn expression binds — those params are closure
    constants, not traced values.  None when no partial is involved."""
    while isinstance(expr, ast.Call) \
            and _trailing(expr.func) in JIT_WRAPPERS and expr.args:
        expr = expr.args[0]
    if isinstance(expr, ast.Call) and _trailing(expr.func) == "partial":
        return ({kw.arg for kw in expr.keywords if kw.arg},
                max(len(expr.args) - 1, 0))
    return None


def _target_names(t: ast.AST):
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, ast.Starred):
        yield from _target_names(t.value)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _target_names(e)


def _shallow_exprs(stmt: ast.AST):
    """Expression nodes belonging to ONE statement: stops at nested
    statements (they get their own ``_visit``) and at lambda boundaries
    (a registered lambda's body is scanned by ``_scan_calls``)."""
    stack = [c for c in ast.iter_child_nodes(stmt)
             if not isinstance(c, ast.stmt)]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, ast.Lambda):
            continue
        stack.extend(c for c in ast.iter_child_nodes(n)
                     if not isinstance(c, ast.stmt))


def _own_statements(body):
    """Every statement of a function body EXCLUDING nested function /
    class bodies (those are separate call-graph nodes)."""
    stack = list(body)
    while stack:
        st = stack.pop()
        yield st
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.stmt):
                stack.append(child)
            elif isinstance(child, ast.NamedExpr):
                yield child


def own_body_nodes(node: ast.AST):
    """Every AST node lexically inside a function, EXCLUDING nested
    function/class/lambda bodies — the traversal the trace rules use so
    one finding never double-reports from both a helper and its
    enclosing builder."""
    for st in (node.body if isinstance(node.body, list) else [node.body]):
        stack = [st]
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))


class _FileScanner:
    """Recursive one-pass scan of one file: functions (nested included),
    classes + bases, import aliases, call records, jit sites."""

    def __init__(self, graph: CallGraph, ctx: FileCtx, amap: Dict):
        self.graph = graph
        self.ctx = ctx
        self.amap = amap

    def visit_body(self, body, scope: Tuple[str, ...],
                   owner_class: str) -> None:
        for node in body:
            self._visit(node, scope, owner_class)

    def _visit(self, node: ast.AST, scope: Tuple[str, ...],
               owner_class: str) -> None:
        g, ctx = self.graph, self.ctx
        if isinstance(node, ast.Import):
            for a in node.names:
                self.amap[a.asname or a.name.split(".")[0]] = (
                    "mod", a.name.replace(".", "/"))
            return
        if isinstance(node, ast.ImportFrom):
            mod = (node.module or "").replace(".", "/")
            for a in node.names:
                self.amap[a.asname or a.name] = ("from", mod, a.name)
            return
        if isinstance(node, ast.ClassDef):
            g.class_bases[(ctx.rel, node.name)] = list(node.bases)
            self.visit_body(node.body, scope + (node.name,), node.name)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = g._add_func(ctx, node, scope, owner_class)
            self._maybe_decorator_site(node, info)
            self._scan_calls(info)
            self.visit_body(node.body, info.scope, owner_class)
            return
        # any other statement: register lambdas / jit sites among its
        # OWN expressions, then recurse into nested statements — a def
        # inside an `if`/`try`/`with` body is still a call-graph node
        # (the `_GATHER_JITS` memo-miss pattern builds kernels there)
        for sub in _shallow_exprs(node):
            if isinstance(sub, ast.Lambda):
                info = g._add_func(ctx, sub, scope, owner_class)
                self._scan_calls(info)
            elif isinstance(sub, ast.Call):
                self._maybe_jit_site(sub, scope, owner_class)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._visit(child, scope, owner_class)

    def _scan_calls(self, info: FuncInfo) -> None:
        g, ctx = self.graph, self.ctx
        node = info.node
        # follow `lambda _fn=fn: ...` defaults: a Name default aliases
        # the enclosing binding, so rewrite param -> target at jit sites
        defaults_map = {}
        if isinstance(node, ast.Lambda):
            args = node.args.args
            dflts = node.args.defaults
            for a, d in zip(args[len(args) - len(dflts):], dflts):
                if isinstance(d, ast.Name):
                    defaults_map[a.arg] = d.id
        body_iter = (own_body_nodes(node)
                     if not isinstance(node, ast.Lambda)
                     else ast.walk(node.body))
        for sub in body_iter:
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call):
                bdesc = g._fn_desc(ctx, sub.value.func, info.scope[:-1],
                                   info.owner_class)
                if bdesc is not None:
                    if bdesc[0] == "name":
                        bdesc = ("name", ctx.rel, info.scope, bdesc[3])
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            info.call_bindings[t.id] = (bdesc, None)
                        elif isinstance(t, (ast.Tuple, ast.List)):
                            for ti, te in enumerate(t.elts):
                                if isinstance(te, ast.Name):
                                    info.call_bindings[te.id] = (bdesc,
                                                                 ti)
            if not isinstance(sub, ast.Call):
                continue
            self._maybe_jit_site(sub, info.scope, info.owner_class,
                                 defaults_map)
            desc = g._fn_desc(ctx, sub.func, info.scope[:-1],
                              info.owner_class)
            if desc is not None:
                if desc[0] == "name":
                    # call resolution sees names visible INSIDE the
                    # function (its own nested defs included)
                    desc = ("name", ctx.rel, info.scope, desc[3])
                g.calls.setdefault(info.key, []).append(
                    _CallRec(desc, sub))

    def _maybe_decorator_site(self, node, info: FuncInfo) -> None:
        """``@tpu_jit`` / ``@jax.jit`` / ``@partial(jax.jit, ...)``
        decorators root a traced region at the decorated function."""
        for dec in node.decorator_list:
            target = dec
            if isinstance(dec, ast.Call):
                name = _trailing(dec.func)
                if name == "partial" and dec.args:
                    target = dec.args[0]
                else:
                    target = dec.func
            if _trailing(target) in JIT_WRAPPERS:
                fn_ref = ast.copy_location(
                    ast.Name(id=(node.name
                                 if not isinstance(node, ast.Lambda)
                                 else ""), ctx=ast.Load()), node)
                # only .lineno is read off the site node downstream, so
                # the decorator expression itself serves as the site
                self.graph.jit_sites.append(
                    (self.ctx, dec, _trailing(target), fn_ref,
                     info.scope[:-1], info.owner_class))
                return

    def _maybe_jit_site(self, call: ast.Call, scope: Tuple[str, ...],
                        owner_class: str, defaults_map=None) -> None:
        name = _trailing(call.func)
        fn_expr = None
        kind = name
        if name in JIT_WRAPPERS and call.args:
            fn_expr = call.args[0]
        elif name in BUILDER_WRAPPERS:
            idx = BUILDER_WRAPPERS[name]
            if idx < len(call.args):
                fn_expr = call.args[idx]
        if fn_expr is None:
            return
        # the tree is visited both by the enclosing def's call scan and
        # by the statement walk — first registration wins (it is the one
        # with lambda-default context)
        site_key = (self.ctx.rel, call.lineno, call.col_offset)
        if site_key in self.graph._site_seen:
            return
        self.graph._site_seen.add(site_key)
        if defaults_map and isinstance(fn_expr, ast.Name) \
                and fn_expr.id in defaults_map:
            # lambda-default alias: resolve the outer binding instead,
            # in the scope ENCLOSING the lambda
            fn_expr = ast.copy_location(
                ast.Name(id=defaults_map[fn_expr.id], ctx=ast.Load()),
                fn_expr)
            scope = scope[:-1]
        self.graph.jit_sites.append(
            (self.ctx, call, kind, fn_expr, scope, owner_class))


class CallGraphRule:
    """Pseudo-rule that builds the shared CallGraph during prescan and
    finalizes it before the trace rules' ``end_run`` — register it
    FIRST in the rule list; it reports nothing itself."""

    id = "_callgraph"
    node_types = ()

    def __init__(self):
        self.graph = CallGraph()

    def prescan(self, ctx: FileCtx) -> None:
        self.graph.scan_file(ctx)

    def end_run(self, engine) -> None:
        self.graph.finalize()
