"""Tier B — lockset-based race/deadlock detection.

* ``lock-mixed-guard`` — per class, collect which ``with self._lock``
  blocks guard each attribute's writes; an attribute written both
  under and outside its dominant lock is a data race (the unguarded
  write can interleave with a guarded reader/writer).  ``__init__``
  writes are excluded (construction is single-threaded) and methods
  suffixed ``_locked`` are treated as guarded by contract (the
  caller-holds-lock convention memory/spill.py uses).

* ``lock-order`` — build the inter-lock acquisition-order graph from
  (a) lexical ``with A: ... with B:`` nesting and (b) calls made while
  holding a lock to functions whose (transitive) bodies acquire other
  locks, then flag cycles.  This statically pins the ordering the
  runtime guard in ``memory/semaphore.py`` only checks dynamically —
  semaphore BEFORE spill, always.  Device-semaphore acquisition is
  recognized non-lexically too: ``acquire_if_necessary(...)`` /
  ``.scope()`` calls map to the ``<device-semaphore>`` pseudo-lock, so
  acquiring the semaphore while lexically holding any other lock
  contributes an edge.

Known limits (documented in docs/static_analysis.md): lock identities
resolve within a file (module locks) or class (``self`` locks); calls
through untyped objects (``obj.method()`` where ``obj`` is a local)
are not resolved; ``with sem.scope():`` regions DO contribute outgoing
semaphore->X edges (the walker tracks the pseudo-lock on a separate
acquisition stack), but a permit held between bare ``acquire``/
``release`` CALLS is not a lexical region and contributes none.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.analysis.core import (
    SEMAPHORE_CALLS,
    SEMAPHORE_LOCK,
    Engine,
    FileCtx,
    Walk,
    _is_semaphore_acquire,
)
from spark_rapids_tpu.analysis.rules_invariants import (
    MUTATORS,
    _trailing_name,
)


# ---------------------------------------------------------------------------
# lock-mixed-guard
# ---------------------------------------------------------------------------

class LockMixedGuardRule:
    id = "lock-mixed-guard"
    node_types = (ast.Assign, ast.AugAssign, ast.Delete, ast.Call)
    HINT = ("take the class's lock around the unguarded write (or move "
            "it into a `*_locked` method whose callers hold the lock)")

    def begin_file(self, ctx: FileCtx) -> None:
        # (class, attr) -> list of (guarded, lock_or_None, qual, node)
        self._writes: Dict[Tuple[str, str],
                           List[Tuple[bool, Optional[str], str,
                                      ast.AST]]] = {}

    def _self_attr(self, t: ast.AST) -> Optional[str]:
        """self.X or self.X[...] target -> attr name."""
        if isinstance(t, ast.Subscript):
            t = t.value
        if (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"):
            return t.attr
        return None

    def _record(self, walk: Walk, attr: str, node: ast.AST) -> None:
        cls = walk.current_class
        if not cls or not walk.func_stack:
            return
        locks = walk.ctx.class_locks.get(cls)
        if not locks or attr in locks:
            return
        in_init = any(f == "__init__" for f in walk.func_stack)
        if in_init:
            return
        by_contract = any(f.endswith("_locked") for f in walk.func_stack)
        held = walk.held_locks()
        guarded = bool(held) or by_contract
        lock = held[-1] if held else ("<caller-held>" if by_contract
                                      else None)
        self._writes.setdefault((cls, attr), []).append(
            (guarded, lock, walk.qualname(), node))

    def visit(self, node: ast.AST, walk: Walk) -> None:
        if isinstance(node, (ast.Assign, ast.Delete)):
            for t in node.targets:
                attr = self._self_attr(t)
                if attr is not None:
                    self._record(walk, attr, node)
        elif isinstance(node, ast.AugAssign):
            attr = self._self_attr(node.target)
            if attr is not None:
                self._record(walk, attr, node)
        elif isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr in MUTATORS
                    and isinstance(fn.value, ast.Attribute)
                    and isinstance(fn.value.value, ast.Name)
                    and fn.value.value.id == "self"):
                self._record(walk, fn.value.attr, node)

    def end_file(self, walk: Walk) -> None:
        for (cls, attr) in sorted(self._writes):
            sites = self._writes[(cls, attr)]
            guarded = [s for s in sites if s[0]]
            unguarded = [s for s in sites if not s[0]]
            if not guarded or not unguarded:
                continue
            # dominant lock: the most common guarding lock identity
            counts: Dict[str, int] = {}
            for _, lock, _, _ in guarded:
                if lock is not None:
                    counts[lock] = counts.get(lock, 0) + 1
            dominant = (sorted(counts, key=lambda k: (-counts[k], k))[0]
                        if counts else "<caller-held>")
            short = dominant.split("::")[-1]
            for _, _, qual, node in sorted(
                    unguarded, key=lambda s: (s[3].lineno,
                                              s[3].col_offset)):
                walk.engine.report(
                    walk.ctx, self.id, node.lineno, node.col_offset,
                    f"attribute '{attr}' of {cls} is written under its "
                    f"dominant lock {short} at {len(guarded)} site(s) "
                    f"but UNGUARDED here", self.HINT, qual)


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

class LockOrderRule:
    id = "lock-order"
    node_types = (ast.With, ast.Call, ast.Import, ast.ImportFrom)
    HINT = ("pick one global acquisition order (the runtime's is "
            "semaphore -> spill -> leaf locks) and re-nest the "
            "inverted site to match it")

    def __init__(self):
        # func key "rel::Qual.name" -> set of lock ids acquired lexically
        self._acquires: Dict[str, Set[str]] = {}
        # func key -> list of unresolved callee descriptors
        self._calls: Dict[str, List[Tuple]] = {}
        # observed ordered pairs: (A, B) -> (rel, line) first/min site
        self._edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        # per-file import alias -> module rel path ("a/b.py")
        self._aliases: Dict[str, Dict[str, str]] = {}

    # -- helpers ---------------------------------------------------------
    def _func_key(self, walk: Walk) -> Optional[str]:
        if not walk.func_stack:
            return None
        return f"{walk.ctx.rel}::{walk.qualname()}"

    def _edge(self, a: str, b: str, rel: str, line: int) -> None:
        if a == b:
            return
        site = self._edges.get((a, b))
        if site is None or (rel, line) < site:
            self._edges[(a, b)] = (rel, line)

    def _with_locks(self, node: ast.With, walk: Walk) -> List[str]:
        out = []
        for item in node.items:
            ident = walk.lock_identity(item.context_expr)
            if ident is None and _is_semaphore_acquire(
                    item.context_expr):
                ident = SEMAPHORE_LOCK
            if ident is not None:
                out.append(ident)
        return out

    # -- visits ----------------------------------------------------------
    def visit(self, node: ast.AST, walk: Walk) -> None:
        rel = walk.ctx.rel
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            amap = self._aliases.setdefault(rel, {})
            if isinstance(node, ast.Import):
                for a in node.names:
                    amap[a.asname or a.name.split(".")[0]] = \
                        a.name.replace(".", "/") + ".py"
            else:
                mod = (node.module or "").replace(".", "/")
                for a in node.names:
                    amap[a.asname or a.name] = f"{mod}/{a.name}.py"
            return
        key = self._func_key(walk)
        if isinstance(node, ast.With):
            new_locks = self._with_locks(node, walk)
            held = list(walk.held_acquires())
            for b in new_locks:
                for a in held:
                    self._edge(a, b, rel, node.lineno)
                held.append(b)
                if key is not None:
                    self._acquires.setdefault(key, set()).add(b)
            return
        if isinstance(node, ast.Call):
            name = _trailing_name(node.func)
            held = walk.held_acquires()
            if name in SEMAPHORE_CALLS and held:
                for a in held:
                    self._edge(a, SEMAPHORE_LOCK, rel, node.lineno)
            if key is None:
                return
            fn = node.func
            desc: Optional[Tuple] = None
            if isinstance(fn, ast.Name):
                desc = ("mod", rel, fn.id)
            elif isinstance(fn, ast.Attribute):
                if (isinstance(fn.value, ast.Name)
                        and fn.value.id == "self" and walk.current_class):
                    desc = ("self", rel, walk.current_class, fn.attr)
                elif isinstance(fn.value, ast.Name):
                    desc = ("alias", rel, fn.value.id, fn.attr)
            if desc is not None:
                self._calls.setdefault(key, []).append(
                    (desc, tuple(held), node.lineno))

    # -- cross-file resolution + cycle detection -------------------------
    def _resolve(self, desc: Tuple,
                 funcs: Set[str]) -> Optional[str]:
        kind = desc[0]
        if kind == "mod":
            k = f"{desc[1]}::{desc[2]}"
            return k if k in funcs else None
        if kind == "self":
            k = f"{desc[1]}::{desc[2]}.{desc[3]}"
            return k if k in funcs else None
        if kind == "alias":
            rel, alias, attr = desc[1], desc[2], desc[3]
            target = self._aliases.get(rel, {}).get(alias)
            if target is None:
                return None
            for cand in funcs:
                frel, qual = cand.split("::", 1)
                if frel.endswith(target) and qual.split(".")[-1] == attr \
                        and "." not in qual:
                    return cand
            return None
        return None

    def end_run(self, engine: Engine) -> None:
        funcs = set(self._acquires) | set(self._calls)
        # resolve call descriptors once, then propagate acquire sets to
        # a fixpoint over the call graph (bounded by lock-set growth)
        call_graph: Dict[str, List[Tuple[str, Tuple[str, ...], int,
                                         str]]] = {}
        for caller, calls in self._calls.items():
            rel = caller.split("::", 1)[0]
            for desc, held, line in calls:
                callee = self._resolve(desc, funcs)
                if callee is not None:
                    call_graph.setdefault(caller, []).append(
                        (callee, held, line, rel))
        trans: Dict[str, Set[str]] = {
            k: set(v) for k, v in self._acquires.items()}
        changed = True
        while changed:
            changed = False
            for caller, edges in call_graph.items():
                acc = trans.setdefault(caller, set())
                for callee, _, _, _ in edges:
                    extra = trans.get(callee, ())
                    for lk in extra:
                        if lk not in acc:
                            acc.add(lk)
                            changed = True
        # held-across-call edges
        for caller, edges in call_graph.items():
            for callee, held, line, rel in edges:
                for b in trans.get(callee, ()):
                    for a in held:
                        self._edge(a, b, rel, line)
        # cycle detection over the order graph
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self._edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for cycle in _find_cycles(graph):
            sites = []
            for i, a in enumerate(cycle):
                b = cycle[(i + 1) % len(cycle)]
                rel, line = self._edges.get((a, b), ("?", 0))
                sites.append(f"{_short(a)}->{_short(b)} at {rel}:{line}")
            first_rel, first_line = self._edges.get(
                (cycle[0], cycle[1 % len(cycle)]), ("<repo>", 1))
            ctx = engine.ctx_for(first_rel)
            engine.report(
                ctx, self.id, first_line, 0,
                "lock acquisition-order cycle (deadlock under "
                "concurrency): " + "; ".join(sites), self.HINT,
                "lock-order-graph")


def _short(lock: str) -> str:
    return lock.split("::")[-1] if "::" in lock else lock


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components of size >= 2 (plus 2-cycles),
    each canonicalized to start at its smallest lock id — deterministic
    across runs."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (the repo tree nests deep enough that a
        # recursive walk could hit the interpreter limit)
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) >= 2:
                    sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    out = []
    for comp in sccs:
        start = min(comp)
        # order the cycle deterministically: smallest id first, then
        # follow edges greedily (smallest next) within the component
        comp_set = set(comp)
        ordered = [start]
        cur = start
        while True:
            nxts = sorted(n for n in graph.get(cur, ())
                          if n in comp_set and n not in ordered)
            if not nxts:
                break
            cur = nxts[0]
            ordered.append(cur)
        out.append(ordered)
    out.sort()
    return out
