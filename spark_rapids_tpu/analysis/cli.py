"""CLI entry for tpulint (invoked via ``tools/lint.py``).

Exit-code contract (pinned by tests/test_lint.py):

* 0 — no findings beyond the (empty-or-justified) baseline
* 1 — at least one non-baselined finding (``--fail-on-new`` makes the
  intent explicit; it is also the default behavior)
* 2 — bad invocation / unreadable baseline / unknown ``--rules`` id

``--json`` and ``--sarif`` emit deterministic output (sorted findings,
sorted keys, no timestamps): two runs over an unchanged tree are
byte-identical.  ``--rules a,b`` scopes a run to the named rules;
``--prune-baseline`` rewrites the baseline dropping entries that no
longer fire; the stale-entry count prints on every run.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from spark_rapids_tpu.analysis.core import (
    Baseline,
    all_rule_ids,
    default_rules,
    run_paths,
    to_json,
    to_sarif,
)

DEFAULT_BASELINE = "tools/lint_baseline.json"


def main(argv: Optional[List[str]] = None,
         repo_root: Optional[str] = None) -> int:
    root = os.path.abspath(repo_root or os.getcwd())
    ap = argparse.ArgumentParser(
        prog="lint.py",
        description="tpulint: AST invariant linter + lockset "
                    "race/deadlock detector")
    ap.add_argument("paths", nargs="*",
                    default=["spark_rapids_tpu", "tools"],
                    help="files/directories to analyze "
                         "(default: spark_rapids_tpu tools)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline JSON of grandfathered findings "
                         f"(default: {DEFAULT_BASELINE} when present)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as deterministic JSON")
    ap.add_argument("--sarif", metavar="OUT",
                    help="write NEW findings (both tiers) as "
                         "deterministic SARIF 2.1.0 to OUT")
    ap.add_argument("--rules", metavar="A,B",
                    help="scope the run to the named rule ids "
                         "(comma-separated)")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="exit 1 on findings not in the baseline "
                         "(explicit form of the default)")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write the current NEW findings as a baseline "
                         "skeleton (justifications must be filled in)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline file dropping entries "
                         "that no longer fire")
    ap.add_argument("--no-docs-rule", action="store_true",
                    help="skip the repo-level doc-drift rule (fixture "
                         "trees have no docs/)")
    args = ap.parse_args(argv)

    # user-supplied relative paths resolve against the CALLER's cwd;
    # only the built-in defaults anchor at the repo root
    defaults = ap.get_default("paths")
    paths = [os.path.join(root, p) if args.paths is defaults
             else os.path.abspath(p)
             for p in args.paths]
    for p in paths:
        if not os.path.exists(p):
            print(f"lint.py: no such path: {p}", file=sys.stderr)
            return 2

    baseline_path = args.baseline
    if baseline_path is None:
        cand = os.path.join(root, DEFAULT_BASELINE)
        baseline_path = cand if os.path.exists(cand) else None
    baseline = Baseline()
    if baseline_path is not None:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"lint.py: cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    only = None
    if args.rules:
        only = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = set(all_rule_ids(include_docs=True))
        unknown = only - known
        if unknown:
            print(f"lint.py: unknown rule id(s): "
                  f"{', '.join(sorted(unknown))} (known: "
                  f"{', '.join(sorted(known))})", file=sys.stderr)
            return 2

    findings = run_paths(
        paths, root,
        rules=default_rules(include_docs=not args.no_docs_rule,
                            only=only))
    new, stale = baseline.split(findings)
    # staleness is only meaningful for files (and, under --rules,
    # rules) this run actually looked at — a scoped run must not
    # report out-of-scope entries as stale
    scope_rels = [os.path.relpath(p, root).replace(os.sep, "/")
                  for p in paths]
    stale = [e for e in stale
             if any(e.get("file", "") == r
                    or e.get("file", "").startswith(r.rstrip("/") + "/")
                    for r in scope_rels)
             and (only is None or e.get("rule") in only)]

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            f.write(Baseline.render_entries(new))
        print(f"wrote {len(new)} baseline entries to "
              f"{args.write_baseline} — fill in the justifications",
              file=sys.stderr)

    if args.prune_baseline:
        if baseline_path is None:
            print("lint.py: --prune-baseline needs a baseline file",
                  file=sys.stderr)
            return 2
        stale_keys = {(e["rule"], e["file"], e.get("context", ""),
                       e["message"]) for e in stale}
        kept = [e for e in baseline.entries
                if (e["rule"], e["file"], e.get("context", ""),
                    e["message"]) not in stale_keys]
        with open(baseline_path, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"entries": sorted(
                    kept, key=lambda e: (e["rule"], e["file"],
                                         e.get("context", ""),
                                         e["message"]))},
                indent=2, sort_keys=True) + "\n")
        print(f"lint.py: pruned {len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'} from "
              f"{baseline_path}", file=sys.stderr)
        # the pruned entries are gone from the file — the always-on
        # stale count below must describe the post-prune state
        stale = []

    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as f:
            f.write(to_sarif(new, all_rule_ids(
                include_docs=not args.no_docs_rule)))

    if args.json:
        sys.stdout.write(to_json(new))
    else:
        for f in new:
            print(f.render())
        n_base = len(findings) - len(new)
        summary = (f"tpulint: {len(new)} finding(s)"
                   + (f" ({n_base} baselined)" if n_base else ""))
        print(summary if new or n_base else "tpulint: clean")
    # the stale count prints on EVERY run so a shrinking baseline is
    # visible without --prune-baseline
    print(f"lint.py: {len(stale)} stale baseline "
          f"entr{'y' if len(stale) == 1 else 'ies'}", file=sys.stderr)
    for e in stale:
        print(f"lint.py: stale baseline entry (no longer fires): "
              f"{e['rule']} in {e['file']}: {e['message']}",
              file=sys.stderr)

    return 1 if new else 0
