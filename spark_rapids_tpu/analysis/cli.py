"""CLI entry for tpulint (invoked via ``tools/lint.py``).

Exit-code contract (pinned by tests/test_lint.py):

* 0 — no findings beyond the (empty-or-justified) baseline
* 1 — at least one non-baselined finding (``--fail-on-new`` makes the
  intent explicit; it is also the default behavior)
* 2 — bad invocation / unreadable baseline

``--json`` emits deterministic JSON (sorted findings, sorted keys, no
timestamps): two runs over an unchanged tree are byte-identical.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from spark_rapids_tpu.analysis.core import (
    Baseline,
    default_rules,
    run_paths,
    to_json,
)

DEFAULT_BASELINE = "tools/lint_baseline.json"


def main(argv: Optional[List[str]] = None,
         repo_root: Optional[str] = None) -> int:
    root = os.path.abspath(repo_root or os.getcwd())
    ap = argparse.ArgumentParser(
        prog="lint.py",
        description="tpulint: AST invariant linter + lockset "
                    "race/deadlock detector")
    ap.add_argument("paths", nargs="*",
                    default=["spark_rapids_tpu", "tools"],
                    help="files/directories to analyze "
                         "(default: spark_rapids_tpu tools)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline JSON of grandfathered findings "
                         f"(default: {DEFAULT_BASELINE} when present)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as deterministic JSON")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="exit 1 on findings not in the baseline "
                         "(explicit form of the default)")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write the current NEW findings as a baseline "
                         "skeleton (justifications must be filled in)")
    ap.add_argument("--no-docs-rule", action="store_true",
                    help="skip the repo-level doc-drift rule (fixture "
                         "trees have no docs/)")
    args = ap.parse_args(argv)

    # user-supplied relative paths resolve against the CALLER's cwd;
    # only the built-in defaults anchor at the repo root
    defaults = ap.get_default("paths")
    paths = [os.path.join(root, p) if args.paths is defaults
             else os.path.abspath(p)
             for p in args.paths]
    for p in paths:
        if not os.path.exists(p):
            print(f"lint.py: no such path: {p}", file=sys.stderr)
            return 2

    baseline_path = args.baseline
    if baseline_path is None:
        cand = os.path.join(root, DEFAULT_BASELINE)
        baseline_path = cand if os.path.exists(cand) else None
    baseline = Baseline()
    if baseline_path is not None:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"lint.py: cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    findings = run_paths(
        paths, root,
        rules=default_rules(include_docs=not args.no_docs_rule))
    new, stale = baseline.split(findings)
    # staleness is only meaningful for files this run actually looked
    # at — a scoped run must not report out-of-scope entries as stale
    scope_rels = [os.path.relpath(p, root).replace(os.sep, "/")
                  for p in paths]
    stale = [e for e in stale
             if any(e.get("file", "") == r
                    or e.get("file", "").startswith(r.rstrip("/") + "/")
                    for r in scope_rels)]

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            f.write(Baseline.render_entries(new))
        print(f"wrote {len(new)} baseline entries to "
              f"{args.write_baseline} — fill in the justifications",
              file=sys.stderr)

    if args.json:
        sys.stdout.write(to_json(new))
    else:
        for f in new:
            print(f.render())
        n_base = len(findings) - len(new)
        summary = (f"tpulint: {len(new)} finding(s)"
                   + (f" ({n_base} baselined)" if n_base else ""))
        print(summary if new or n_base else "tpulint: clean")
    for e in stale:
        print(f"lint.py: stale baseline entry (no longer fires): "
              f"{e['rule']} in {e['file']}: {e['message']}",
              file=sys.stderr)

    return 1 if new else 0
