"""Analyzer engine — one parse per file, rule visitors multiplexed.

The engine owns everything rule-agnostic: file discovery, parsing
(exactly once per file — rules never re-parse), the shared tree walk
with scope/lock-context bookkeeping, ``# tpulint: disable=<rule>``
pragma suppression, the JSON baseline for grandfathered findings, and
deterministic ordering/serialization of findings (two runs over the
same tree produce byte-identical JSON — pinned by the tier-1 gate).

Rule protocol (see :mod:`rules_invariants` / :mod:`rules_lockset`):

* ``node_types`` — AST classes the rule wants; the engine's single walk
  dispatches each matching node to ``visit(node, walk)``.
* ``prescan(ctx)`` — optional first pass over every file (used by the
  conf-vocabulary rule to collect declarations before judging reads).
* ``begin_file(ctx)`` / ``end_file(walk)`` — per-file aggregation.
* ``end_run(engine)`` — cross-file analyses (the lock-order graph).

Findings are reported through the walker/engine so suppression and
identity stay uniform: a finding's baseline identity is
``(rule, file, context, message)`` — deliberately line-free, so a
grandfathered finding survives unrelated edits above it.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

PRAGMA_PREFIX = "tpulint:"

# threading constructors whose result is a mutual-exclusion object; a
# `with` over one of these is a critical section the lockset rules track
LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One structured finding: file:line + rule id + message + fix hint.

    ``context`` is the enclosing ``Class.method`` / function qualname
    (empty at module level) and is part of the baseline identity so the
    match survives line drift."""

    file: str          # repo-relative posix path
    line: int
    col: int
    rule: str
    message: str
    hint: str = ""
    context: str = ""

    @property
    def identity(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.file, self.context, self.message)

    def to_dict(self) -> Dict[str, object]:
        return {"file": self.file, "line": self.line, "col": self.col,
                "rule": self.rule, "context": self.context,
                "message": self.message, "hint": self.hint}

    def render(self) -> str:
        ctx = f" [{self.context}]" if self.context else ""
        hint = f"\n    hint: {self.hint}" if self.hint else ""
        return (f"{self.file}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}{ctx}{hint}")


def to_json(findings: Sequence[Finding]) -> str:
    """Deterministic serialization: sorted findings, sorted keys, no
    timestamps — byte-identical across runs over an unchanged tree."""
    return json.dumps([f.to_dict() for f in sorted(findings)],
                      indent=2, sort_keys=True) + "\n"


def to_sarif(findings: Sequence[Finding],
             rule_ids: Sequence[str]) -> str:
    """SARIF 2.1.0 for CI/editor annotations.  Deterministic like
    ``to_json``: sorted results and rule metadata, no timestamps or
    absolute paths — byte-identical across runs over an unchanged
    tree."""
    results = []
    for f in sorted(findings):
        msg = f.message + (f" [{f.context}]" if f.context else "")
        if f.hint:
            msg += f"\nhint: {f.hint}"
        results.append({
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": msg},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.file},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        })
    sarif = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri":
                    "docs/static_analysis.md",
                "rules": [{"id": rid} for rid in sorted(set(rule_ids))],
            }},
            "results": results,
        }],
    }
    return json.dumps(sarif, indent=2, sort_keys=True) + "\n"


class Baseline:
    """Grandfathered findings.  Every entry MUST carry a non-empty
    ``justification`` — the shipped baseline is empty-or-justified by
    construction, and the loader enforces it."""

    def __init__(self, entries: Optional[List[Dict[str, str]]] = None):
        self.entries = entries or []
        self._keys: Set[Tuple[str, str, str, str]] = set()
        for i, e in enumerate(self.entries):
            if not str(e.get("justification", "")).strip():
                raise ValueError(
                    f"baseline entry #{i} ({e.get('rule')} in "
                    f"{e.get('file')}) has no justification — every "
                    f"grandfathered finding must say why it is benign")
            self._keys.add((e["rule"], e["file"], e.get("context", ""),
                            e["message"]))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return cls(data.get("entries", []))

    def matches(self, f: Finding) -> bool:
        return f.identity in self._keys

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Dict[str, str]]]:
        """(new findings not in the baseline, stale unmatched entries)."""
        new = [f for f in findings if not self.matches(f)]
        seen = {f.identity for f in findings}
        stale = [e for e in self.entries
                 if (e["rule"], e["file"], e.get("context", ""),
                     e["message"]) not in seen]
        return new, stale

    @staticmethod
    def render_entries(findings: Sequence[Finding],
                       justification: str = "FIXME: justify") -> str:
        """``--write-baseline`` payload for the given findings."""
        return json.dumps(
            {"entries": [{"rule": f.rule, "file": f.file,
                          "context": f.context, "message": f.message,
                          "justification": justification}
                         for f in sorted(findings)]},
            indent=2, sort_keys=True) + "\n"


# ---------------------------------------------------------------------------
# pragma parsing
# ---------------------------------------------------------------------------

def parse_pragmas(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """``# tpulint: disable=rule1,rule2`` comments.

    Returns (line -> suppressed rule set, file-wide suppressed set from
    ``# tpulint: disable-file=...``).  Comment tokens only — a pragma
    inside a string literal does not suppress anything."""
    per_line: Dict[int, Set[str]] = {}
    whole_file: Set[str] = set()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            text = tok.string.lstrip("#").strip()
            if not text.startswith(PRAGMA_PREFIX):
                continue
            body = text[len(PRAGMA_PREFIX):].strip()
            for directive, sink in (("disable-file=", "file"),
                                    ("disable=", "line")):
                if body.startswith(directive):
                    # everything after the first whitespace is a free-
                    # form justification: `# tpulint: disable=r (why)`
                    spec = body[len(directive):].split(None, 1)[0]
                    rules = {r.strip() for r in spec.split(",")
                             if r.strip()}
                    if sink == "file":
                        whole_file |= rules
                    else:
                        per_line.setdefault(tok.start[0], set()).update(
                            rules)
    except tokenize.TokenError:
        pass
    return per_line, whole_file


# ---------------------------------------------------------------------------
# per-file context + shared prepass facts
# ---------------------------------------------------------------------------

def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else "")
    return name in LOCK_CTORS


class FileCtx:
    """Everything the rules may ask about one file: the single parsed
    tree, pragma maps, and the lock-declaration prepass facts."""

    def __init__(self, path: str, rel: str, source: str, tree: ast.Module):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = tree
        self.line_pragmas, self.file_pragmas = parse_pragmas(source)
        # module-level lock names: _lock = threading.Lock()
        self.module_locks: Set[str] = set()
        for st in tree.body:
            if (isinstance(st, ast.Assign) and _is_lock_ctor(st.value)):
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
            elif (isinstance(st, ast.AnnAssign) and st.value is not None
                  and _is_lock_ctor(st.value)
                  and isinstance(st.target, ast.Name)):
                self.module_locks.add(st.target.id)
        # per-class self-lock attrs: self._lock = threading.Lock()
        self.class_locks: Dict[str, Set[str]] = {}
        for st in ast.walk(tree):
            if not isinstance(st, ast.ClassDef):
                continue
            attrs: Set[str] = set()
            for sub in ast.walk(st):
                if (isinstance(sub, ast.Assign)
                        and _is_lock_ctor(sub.value)):
                    for t in sub.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            attrs.add(t.attr)
            if attrs:
                self.class_locks[st.name] = attrs

    def suppressed(self, rule: str, line: int) -> bool:
        """Pragma on the finding's line, or anywhere in the contiguous
        comment block directly above it (multi-line justifications)."""
        if rule in self.file_pragmas or "all" in self.file_pragmas:
            return True

        def hit(ln: int) -> bool:
            rules = self.line_pragmas.get(ln)
            return bool(rules and (rule in rules or "all" in rules))

        if hit(line):
            return True
        lines = self.source.splitlines()
        ln = line - 1
        while ln >= 1 and ln <= len(lines):
            stripped = lines[ln - 1].strip()
            if not stripped.startswith("#"):
                break
            if hit(ln):
                return True
            ln -= 1
        return False


# ---------------------------------------------------------------------------
# the multiplexed walker
# ---------------------------------------------------------------------------

class Walk:
    """One traversal of one file's tree, shared by every rule.

    Maintains the scope stack (class/function nesting), the active
    lock-context stack (resolved identities of ``with`` locks currently
    held lexically), and whether the walk is inside a
    ``with sync_event():`` region."""

    def __init__(self, engine: "Engine", ctx: FileCtx,
                 dispatch: Dict[type, List[object]]):
        self.engine = engine
        self.ctx = ctx
        self._dispatch = dispatch
        self.class_stack: List[str] = []
        self.func_stack: List[str] = []
        self.lock_stack: List[str] = []      # resolved MUTEX identities
        # acquisition-order stack: the mutexes PLUS non-mutex ordered
        # resources (the device semaphore via `with sem.scope():`).
        # Separate from lock_stack on purpose — holding a semaphore
        # permit orders lock acquisition but guards no attribute state.
        self.acquire_stack: List[str] = []
        self.sync_depth = 0                  # nested sync_event withs

    # -- state queries ---------------------------------------------------
    @property
    def current_class(self) -> str:
        return self.class_stack[-1] if self.class_stack else ""

    def qualname(self) -> str:
        parts = self.class_stack + self.func_stack
        return ".".join(parts)

    def held_locks(self) -> Tuple[str, ...]:
        """Mutexes held lexically (guard semantics)."""
        return tuple(self.lock_stack)

    def held_acquires(self) -> Tuple[str, ...]:
        """Ordered resources held lexically: mutexes + the device
        semaphore (ordering semantics, for the lock-order rule)."""
        return tuple(self.acquire_stack)

    def in_sync_event(self) -> bool:
        return self.sync_depth > 0

    def lock_identity(self, expr: ast.AST) -> Optional[str]:
        """Resolve a ``with`` context expression to a lock identity, or
        None when it is not a known lock.  Identities:
        ``<rel>::<name>`` for module-level locks, ``<rel>::<Class>.
        <attr>`` for self-locks."""
        if isinstance(expr, ast.Name):
            if expr.id in self.ctx.module_locks:
                return f"{self.ctx.rel}::{expr.id}"
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)):
            if expr.value.id == "self" and self.current_class:
                if expr.attr in self.ctx.class_locks.get(
                        self.current_class, ()):
                    return (f"{self.ctx.rel}::"
                            f"{self.current_class}.{expr.attr}")
            return None
        return None

    # -- reporting -------------------------------------------------------
    def report(self, rule: str, node: ast.AST, message: str,
               hint: str = "", context: Optional[str] = None) -> None:
        self.engine.report(self.ctx, rule,
                           getattr(node, "lineno", 1),
                           getattr(node, "col_offset", 0),
                           message, hint,
                           self.qualname() if context is None else context)

    # -- traversal -------------------------------------------------------
    def run(self) -> None:
        self._visit(self.ctx.tree)

    def _visit(self, node: ast.AST) -> None:
        rules = self._dispatch.get(type(node))
        if rules:
            for r in rules:
                r.visit(node, self)
        if isinstance(node, ast.ClassDef):
            self.class_stack.append(node.name)
            self._generic(node)
            self.class_stack.pop()
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.func_stack.append(node.name)
            self._generic(node)
            self.func_stack.pop()
        elif isinstance(node, ast.With):
            pushed_locks = 0
            pushed_acq = 0
            pushed_sync = 0
            for item in node.items:
                ident = self.lock_identity(item.context_expr)
                if ident is not None:
                    self.lock_stack.append(ident)
                    pushed_locks += 1
                    self.acquire_stack.append(ident)
                    pushed_acq += 1
                elif _is_semaphore_acquire(item.context_expr):
                    # `with sem.scope():` — orders later acquisitions
                    # but guards nothing (acquire_stack only)
                    self.acquire_stack.append(SEMAPHORE_LOCK)
                    pushed_acq += 1
                elif _is_sync_event(item.context_expr):
                    self.sync_depth += 1
                    pushed_sync += 1
            self._generic(node)
            for _ in range(pushed_locks):
                self.lock_stack.pop()
            for _ in range(pushed_acq):
                self.acquire_stack.pop()
            self.sync_depth -= pushed_sync
        else:
            self._generic(node)

    def _generic(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            self._visit(child)


# the device-semaphore pseudo-lock: `sem.scope()` / `acquire_if_
# necessary()` acquire it without a lexical `with <mutex>`; the
# lock-order rule needs it as a graph node (semaphore BEFORE spill)
SEMAPHORE_LOCK = "<device-semaphore>"
SEMAPHORE_CALLS = frozenset(("acquire_if_necessary", "scope"))


def _is_semaphore_acquire(expr: ast.AST) -> bool:
    return (isinstance(expr, ast.Call)
            and (expr.func.attr if isinstance(expr.func, ast.Attribute)
                 else expr.func.id if isinstance(expr.func, ast.Name)
                 else "") in SEMAPHORE_CALLS)


def _is_sync_event(expr: ast.AST) -> bool:
    """``with sync_event():`` / ``with PC.sync_event():`` — the
    accounted-host-sync region perfcounters exposes."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = (expr.id if isinstance(expr, ast.Name)
            else expr.attr if isinstance(expr, ast.Attribute) else "")
    return name == "sync_event"


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

class Engine:
    def __init__(self, repo_root: str, rules: Sequence[object]):
        self.repo_root = os.path.abspath(repo_root)
        self.rules = list(rules)
        self.findings: List[Finding] = []
        self._ctxs: List[FileCtx] = []

    def report(self, ctx: Optional[FileCtx], rule: str, line: int,
               col: int, message: str, hint: str = "",
               context: str = "") -> None:
        if ctx is not None and ctx.suppressed(rule, line):
            return
        rel = ctx.rel if ctx is not None else "<repo>"
        self.findings.append(Finding(rel, line, col, rule, message, hint,
                                     context))

    def ctx_for(self, rel: str) -> Optional[FileCtx]:
        for c in self._ctxs:
            if c.rel == rel:
                return c
        return None

    def run(self, paths: Sequence[str]) -> List[Finding]:
        files = sorted(_collect_files(paths))
        for path in files:
            rel = os.path.relpath(path, self.repo_root).replace(os.sep,
                                                                "/")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError, ValueError) as e:
                self.findings.append(Finding(
                    rel, 1, 0, "parse-error",
                    f"could not parse: {type(e).__name__}: {e}",
                    "fix the syntax error; nothing else was checked"))
                continue
            self._ctxs.append(FileCtx(path, rel, source, tree))
        # phase 0: run-level setup (e.g. repo-wide vocabulary, so a
        # SCOPED run still judges against the full declaration set)
        for rule in self.rules:
            begin_run = getattr(rule, "begin_run", None)
            if begin_run is not None:
                begin_run(self)
        # phase 1: prescan (vocabulary collection etc.)
        for rule in self.rules:
            prescan = getattr(rule, "prescan", None)
            if prescan is not None:
                for ctx in self._ctxs:
                    prescan(ctx)
        # phase 2: the single multiplexed walk per file
        dispatch: Dict[type, List[object]] = {}
        for rule in self.rules:
            for nt in getattr(rule, "node_types", ()):
                dispatch.setdefault(nt, []).append(rule)
        for ctx in self._ctxs:
            for rule in self.rules:
                begin = getattr(rule, "begin_file", None)
                if begin is not None:
                    begin(ctx)
            walk = Walk(self, ctx, dispatch)
            walk.run()
            for rule in self.rules:
                end = getattr(rule, "end_file", None)
                if end is not None:
                    end(walk)
        # phase 3: cross-file analyses
        for rule in self.rules:
            end_run = getattr(rule, "end_run", None)
            if end_run is not None:
                end_run(self)
        self.findings.sort()
        return self.findings


def _collect_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def default_rules(include_docs: bool = True,
                  only: Optional[Set[str]] = None) -> List[object]:
    """The full rule set: tiers A/B (invariants + lockset), the
    tracelint tier C (trace-safety over jitted regions), and the
    repo-level doc-drift rule.  ``only`` scopes to the named rule ids
    (the shared call-graph builder rides along whenever any tier-C rule
    is requested)."""
    from spark_rapids_tpu.analysis import rules_invariants as RI
    from spark_rapids_tpu.analysis import rules_lockset as RL
    from spark_rapids_tpu.analysis import rules_trace as RT

    rules: List[object] = [
        RI.CounterWriteRule(),
        RI.CancelSwallowRule(),
        RI.UnaccountedSyncRule(),
        RI.ConfVocabularyRule(),
        RI.ModuleStateRule(),
        RI.UnlockedRmwRule(),
        RL.LockMixedGuardRule(),
        RL.LockOrderRule(),
    ]
    rules.extend(RT.trace_rules())
    if include_docs:
        from spark_rapids_tpu.analysis import rules_docs as RD

        rules.append(RD.DocDriftRule())
    if only is not None:
        keep = [r for r in rules if getattr(r, "id", "") in only]
        # tier-C rules consume the shared builder — keep it FIRST
        if any(getattr(r, "id", "") in TRACE_RULE_IDS for r in keep):
            builder = next(r for r in rules if r.id == "_callgraph")
            if builder not in keep:
                keep.insert(0, builder)
        rules = keep
    return rules


# tier-C rule ids (the tracelint tier) — used by --rules scoping and
# the doc-drift vocabulary check
TRACE_RULE_IDS = frozenset((
    "trace-conf-read", "trace-side-effect", "trace-host-sync",
    "trace-branch", "trace-closure-state", "trace-split-sync",
    "retrace-key",
))


def all_rule_ids(include_docs: bool = True) -> List[str]:
    """Every user-facing rule id in the default set, sorted."""
    return sorted(getattr(r, "id") for r in default_rules(include_docs)
                  if not getattr(r, "id", "").startswith("_"))


def run_paths(paths: Sequence[str], repo_root: str,
              rules: Optional[Sequence[object]] = None,
              include_docs: bool = False) -> List[Finding]:
    """Analyze ``paths`` (files or directories); returns sorted
    findings.  ``include_docs`` adds the repo-level doc-drift rule —
    only meaningful when analyzing the real repo (it imports the conf
    registry and reads docs/)."""
    engine = Engine(repo_root,
                    default_rules(include_docs) if rules is None
                    else rules)
    return engine.run(paths)
