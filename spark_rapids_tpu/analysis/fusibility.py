"""Fusion-safety classification — tracelint's answer to "which kernels
can be inlined into a larger traced region?"

The ROADMAP's whole-plan-fusion item needs a static answer per exec:
collapsing a pipeline-able subtree into ONE jitted program means every
member kernel's body runs under a shared trace, so anything that is
merely *suspicious* standalone (a trace-time side effect rescued by a
per-exec aux store, a host sync that happens to sit at a program
boundary) becomes *wrong* when inlined.  This module replays the
tracelint region rules over every traced kernel — pragma suppression
deliberately IGNORED, because a justified standalone exception is still
a fusion blocker — and rolls the verdicts up:

* ``fusable`` — the kernel body is pure traced compute; inline freely.
* ``fusable-with-rewrite(<reason>)`` — inlinable after a mechanical
  rewrite (hoist the conf read to build time, move the side effect to
  the dispatch wrapper, make the trace-time aux travel with the fused
  executable).
* ``unfusable(<reason>)`` — Python control flow or host syncs on traced
  values (the trace would freeze or concretize), or no jitted kernel at
  all (host-side batch plumbing).

The manifest is keyed by the ``plan_key`` operator-class identity —
``resilience.breaker.plan_key(plan)[0]``, the same ``op_class`` the
PR 8 calibration store and ``tools/qualify.py`` use — so the fusion
planner and the qualification report can join it directly.  A second
section keys by exec CLASS for the execs that exist only at runtime
(fused stages, ICI shuffles, transitions).  Output is deterministic:
two runs over an unchanged tree are byte-identical (pinned by
``tests/test_lint.py``).
"""
from __future__ import annotations

import ast
import json
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.analysis.callgraph import CallGraphRule, _trailing
from spark_rapids_tpu.analysis.core import Engine

MANIFEST_VERSION = 1

# shared sentinel entry for execs with no jitted kernel anywhere in
# their class chain
_HOST_ONLY = {
    "classification":
        "unfusable(no-jitted-kernel: host-side batch plumbing)",
    "kernels": {},
}

# classification severity order: worst wins in roll-ups
_SEVERITY = {"fusable": 0, "fusable-with-rewrite": 1, "unfusable": 2}

# per-rule fusion verdicts: (class, reason) — reasons are stable text,
# part of the byte-identical manifest
_RULE_VERDICTS = {
    "trace-host-sync": ("unfusable", "host sync on a traced value"),
    "trace-branch": ("unfusable",
                     "Python control flow on a traced value"),
    "trace-conf-read": ("fusable-with-rewrite",
                        "conf read must hoist to build time"),
    "trace-side-effect": ("fusable-with-rewrite",
                          "side effect must hoist to the call site"),
    "trace-closure-state": ("fusable-with-rewrite",
                            "trace-time aux must travel with the fused "
                            "executable"),
}


class _Capture:
    """Reporter shim: collects raw rule verdicts, no pragma/baseline
    filtering — a justified standalone exception still blocks fusion."""

    def __init__(self):
        self.by_fn: Dict[str, List[str]] = {}

    def report(self, ctx, rule, line, col, message, hint="",
               context="") -> None:
        key = f"{ctx.rel}::{context}"
        self.by_fn.setdefault(key, []).append(rule)


def _region_rules(cg: CallGraphRule):
    from spark_rapids_tpu.analysis import rules_trace as RT

    return [RT.TraceConfReadRule(cg), RT.TraceSideEffectRule(cg),
            RT.TraceHostSyncRule(cg), RT.TraceBranchRule(cg),
            RT.TraceClosureStateRule(cg)]


def _convert_map(engine: Engine) -> Dict[str, List[str]]:
    """plan-class name -> exec-class names, parsed statically from the
    ``isinstance(plan, PN.X)`` branches of ``overrides._convert_node``
    (and the module body around it) so the mapping cannot drift from
    the code that does the converting."""
    out: Dict[str, List[str]] = {}
    for ctx in engine._ctxs:
        if not ctx.rel.endswith("overrides/overrides.py"):
            continue
        fn = None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "_convert_node":
                fn = node
                break
        if fn is None:
            continue
        for st in ast.walk(fn):
            if not isinstance(st, ast.If):
                continue
            plans = _isinstance_plan_classes(st.test)
            if not plans:
                continue
            execs = sorted({
                _trailing(c.func) for c in ast.walk(st)
                if isinstance(c, ast.Call)
                and _is_exec_ctor(_trailing(c.func))})
            for p in plans:
                if execs:
                    cur = out.setdefault(p, [])
                    cur.extend(e for e in execs if e not in cur)
    return out


def _isinstance_plan_classes(test: ast.AST) -> List[str]:
    for c in ast.walk(test):
        if isinstance(c, ast.Call) and _trailing(c.func) == "isinstance" \
                and len(c.args) == 2:
            second = c.args[1]
            names = (second.elts if isinstance(second, ast.Tuple)
                     else [second])
            return [_trailing(n) for n in names if _trailing(n)]
    return []


def _is_exec_ctor(name: str) -> bool:
    return name.startswith("Tpu") and name.endswith("Exec")


def _worst(classes: List[str]) -> str:
    if not classes:
        return "unfusable(no-jitted-kernel: host-side batch plumbing)"
    return max(sorted(classes),
               key=lambda c: _SEVERITY[c.split("(", 1)[0]])


def build_manifest(repo_root: str,
                   paths: Optional[List[str]] = None) -> dict:
    """The fusion-safety manifest for the repo at ``repo_root``."""
    import os

    cg = CallGraphRule()
    # only the callgraph pseudo-rule runs in the engine (prescan builds
    # the graph, its end_run finalizes it); the region rules run below
    # through the raw capture — pragma/baseline filtering deliberately
    # bypassed, and no wasted pragma-filtered engine pass
    engine = Engine(repo_root, [cg])
    scan = paths or [os.path.join(repo_root, "spark_rapids_tpu")]
    engine.run(scan)
    g = cg.graph

    cap = _Capture()
    rules = _region_rules(cg)
    for key in sorted(g.traced):
        info = g.funcs.get(key)
        if info is None:
            continue
        for rule in rules:
            rule.check(cap, info, g.traced[key], g)

    # kernel verdicts, grouped by the ROOT site's owning exec class
    kernels_by_class: Dict[str, Dict[str, dict]] = {}
    for key in sorted(g.traced):
        info = g.funcs.get(key)
        if info is None:
            continue
        root = g.traced[key]
        owner = root.owner_class or info.owner_class
        if not owner:
            continue
        fired = sorted(set(cap.by_fn.get(f"{info.rel}::{info.qual}",
                                         ())))
        pairs = [(r, _RULE_VERDICTS[r]) for r in fired
                 if r in _RULE_VERDICTS]
        if pairs:
            cls = _worst(sorted(f"{c}({reason})"
                                for _, (c, reason) in pairs))
            reasons = sorted(f"{reason} [{r}]"
                             for r, (_, reason) in pairs)
        else:
            cls, reasons = "fusable", []
        kernels_by_class.setdefault(owner, {})[info.qual] = {
            "classification": cls,
            "reasons": reasons,
            "root": f"{root.rel}:{root.kind}",
        }

    exec_entries: Dict[str, dict] = {}
    for cls_name in sorted(kernels_by_class):
        kernels = kernels_by_class[cls_name]
        exec_entries[cls_name] = {
            "classification": _worst(
                [k["classification"] for k in kernels.values()]),
            "kernels": dict(sorted(kernels.items())),
        }

    # subclass execs inherit their base's kernels (TpuProjectExec runs
    # TpuStageExec's stage program; the join execs share _BaseTpuJoin's)
    base_names: Dict[str, List[str]] = {}
    for (rel, cls), bases in g.class_bases.items():
        base_names.setdefault(cls, []).extend(
            _trailing(b) for b in bases if _trailing(b))

    def entry_for(cls_name: str, _seen=None) -> dict:
        _seen = _seen if _seen is not None else set()
        if cls_name in _seen:
            return _HOST_ONLY
        _seen.add(cls_name)
        if cls_name in exec_entries:
            return exec_entries[cls_name]
        for base in base_names.get(cls_name, ()):
            e = entry_for(base, _seen)
            if e is not _HOST_ONLY:
                return e
        return _HOST_ONLY

    convert = _convert_map(engine)
    try:
        from spark_rapids_tpu.overrides.overrides import EXECS

        plan_classes = sorted(c.__name__ for c in EXECS)
    except Exception:
        plan_classes = sorted(convert)

    operators: Dict[str, dict] = {}
    for op in plan_classes:
        execs = convert.get(op, [])
        mapped = {e: entry_for(e) for e in execs}
        if mapped:
            cls = _worst([m["classification"] for m in mapped.values()])
        else:
            cls = ("unfusable(no-device-exec: converts outside the "
                   "traced kernel set)")
        operators[op] = {
            "classification": cls,
            "execs": {e: m["classification"]
                      for e, m in sorted(mapped.items())},
        }

    return {
        "version": MANIFEST_VERSION,
        "identity": ("op_class — resilience.breaker.plan_key(plan)[0], "
                     "the calibration-store operator class"),
        "operators": operators,
        "execs": exec_entries,
    }


def manifest_json(manifest: dict) -> str:
    """Deterministic serialization: sorted keys, no timestamps."""
    return json.dumps(manifest, indent=2, sort_keys=True) + "\n"
