"""Doc-drift rule — the conf/counter/event vocabulary checks folded in
from ``tools/check_counters.py`` (which remains as a thin CLI shim so
existing invocations and the pytest mirrors keep working).

Unlike the AST rules this one introspects the RUNTIME registries
(``perfcounters.COUNTERS``, the typed conf ``_REGISTRY``, the
diagnostics ``EVENT_SCHEMA``) and cross-checks the docs tree, so it
only runs against the real repo (``tools/lint.py`` default; fixture
runs exclude it).  Message strings are kept byte-compatible with the
old checker — tests assert on them.
"""
from __future__ import annotations

import os
from typing import List

from spark_rapids_tpu.analysis.core import Engine, Finding


def doc_drift_problems(repo_root: str) -> List[str]:
    """Every drift problem as a human-readable string (the legacy
    ``check_counters.check()`` contract)."""
    from spark_rapids_tpu import perfcounters as PC
    from spark_rapids_tpu.config import _REGISTRY
    from spark_rapids_tpu.diagnostics.recorder import EVENT_SCHEMA

    problems = []

    def read(name):
        path = os.path.join(repo_root, "docs", name)
        try:
            with open(path) as f:
                return f.read()
        except OSError:
            problems.append(f"missing docs file: docs/{name}")
            return ""

    diag_md = read("diagnostics.md")
    configs_md = read("configs.md")

    for key in sorted(PC.COUNTERS):
        # backtick-delimited: a bare substring test is vacuous for
        # counter names that are ordinary words ("compiles")
        if f"`{key}`" not in diag_md:
            problems.append(
                f"perf counter '{key}' is not documented (backticked) in "
                f"docs/diagnostics.md")
    if hasattr(PC, "ALIASES"):
        problems.append(
            "perfcounters.ALIASES still exists — the one-release "
            "camelCase compat window closed in ISSUE 7")

    diag_confs = [k for k in _REGISTRY
                  if k.startswith("spark.rapids.tpu.diagnostics.")]
    if not diag_confs:
        problems.append("no spark.rapids.tpu.diagnostics.* confs "
                        "registered")
    for key in sorted(diag_confs):
        if key not in diag_md:
            problems.append(
                f"conf '{key}' is not documented in docs/diagnostics.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")

    for ev in sorted(EVENT_SCHEMA):
        if f"`{ev}`" not in diag_md:
            problems.append(
                f"event type '{ev}' is not documented in "
                f"docs/diagnostics.md")

    # query lifecycle (ISSUE 4): confs + counters must be documented in
    # docs/concurrency.md (and confs in the regenerated configs.md)
    conc_md = read("concurrency.md")
    life_confs = [k for k in _REGISTRY
                  if k == "spark.rapids.tpu.concurrentQueries"
                  or k.startswith(("spark.rapids.tpu.admission.",
                                   "spark.rapids.tpu.query.",
                                   "spark.rapids.tpu.semaphore."))]
    if not life_confs:
        problems.append("no query-lifecycle confs registered")
    for key in sorted(life_confs):
        if f"`{key}`" not in conc_md:
            problems.append(
                f"conf '{key}' is not documented in docs/concurrency.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("queries_admitted", "queries_rejected",
                "queries_cancelled", "deadline_trips",
                "admission_wait_ns"):
        if key not in PC.COUNTERS:
            problems.append(f"lifecycle counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in conc_md:
            problems.append(
                f"lifecycle counter '{key}' is not documented in "
                f"docs/concurrency.md")

    # I/O fault domain (ISSUE 5): tolerance confs + counters must be
    # documented in docs/io_resilience.md (and confs in configs.md)
    io_md = read("io_resilience.md")
    io_confs = [k for k in _REGISTRY
                if k.startswith(("spark.sql.files.ignore",
                                 "spark.rapids.tpu.files."))]
    if not io_confs:
        problems.append("no I/O fault-tolerance confs registered")
    for key in sorted(io_confs):
        if f"`{key}`" not in io_md:
            problems.append(
                f"conf '{key}' is not documented in "
                f"docs/io_resilience.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("files_skipped_corrupt", "files_skipped_missing",
                "file_decoder_fallbacks"):
        if key not in PC.COUNTERS:
            problems.append(f"I/O counter '{key}' is not registered in "
                            f"perfcounters.COUNTERS")
        if f"`{key}`" not in io_md:
            problems.append(
                f"I/O counter '{key}' is not documented in "
                f"docs/io_resilience.md")
    if "io_fault" not in EVENT_SCHEMA:
        problems.append("diagnostics event type 'io_fault' is not "
                        "registered in EVENT_SCHEMA")

    # transport-aware scan pipeline (ISSUE 6): confs + counters must be
    # documented in docs/scan_pipeline.md (and confs in configs.md)
    scan_md = read("scan_pipeline.md")
    scan_confs = [k for k in _REGISTRY
                  if k.startswith(("spark.rapids.tpu.scan.",
                                   "spark.rapids.sql.format.parquet."
                                   "transfer."))]
    if not scan_confs:
        problems.append("no scan-pipeline confs registered")
    for key in sorted(scan_confs):
        if f"`{key}`" not in scan_md:
            problems.append(
                f"conf '{key}' is not documented in "
                f"docs/scan_pipeline.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("bytes_h2d_logical", "scan_transfer_ns",
                "pages_device_decompressed", "chunk_decode_fallbacks",
                "bytes_h2d_overlapped", "prefetch_stall_ns",
                "hot_cache_hits", "hot_cache_misses",
                "hot_cache_evictions"):
        if key not in PC.COUNTERS:
            problems.append(f"scan counter '{key}' is not registered "
                            f"in perfcounters.COUNTERS")
        if f"`{key}`" not in scan_md:
            problems.append(
                f"scan counter '{key}' is not documented in "
                f"docs/scan_pipeline.md")
    if "scan_prefetch" not in EVENT_SCHEMA:
        problems.append("diagnostics event type 'scan_prefetch' is not "
                        "registered in EVENT_SCHEMA")

    # telemetry tier (ISSUE 7): confs + counters + the sampler's gauge
    # vocabulary must be documented in docs/observability.md (and confs
    # in the regenerated configs.md)
    obs_md = read("observability.md")
    tel_confs = [k for k in _REGISTRY
                 if k.startswith("spark.rapids.tpu.telemetry.")]
    if not tel_confs:
        problems.append("no spark.rapids.tpu.telemetry.* confs "
                        "registered")
    for key in sorted(tel_confs):
        if f"`{key}`" not in obs_md:
            problems.append(
                f"conf '{key}' is not documented in "
                f"docs/observability.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("slo_violations", "postmortem_dumps"):
        if key not in PC.COUNTERS:
            problems.append(f"telemetry counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in obs_md:
            problems.append(
                f"telemetry counter '{key}' is not documented in "
                f"docs/observability.md")
    for gauge in ("admission_running", "admission_queued",
                  "active_queries", "hbm_pool_bytes", "hbm_used_bytes",
                  "hbm_occupancy", "hot_cache_hit_rate",
                  "compile_cache_hit_rate", "compile_registry_programs",
                  "query_latency_p95_ms"):
        if f"`{gauge}`" not in obs_md:
            problems.append(
                f"sampler gauge '{gauge}' is not documented in "
                f"docs/observability.md")

    # profile-driven cost model (ISSUE 8): confs + counters + the
    # cost_model event + the advisory/telemetry vocabulary must be
    # documented in docs/profiling.md (and confs in configs.md)
    prof_md = read("profiling.md")
    prof_confs = [k for k in _REGISTRY
                  if k.startswith("spark.rapids.tpu.profile.")]
    if not prof_confs:
        problems.append("no spark.rapids.tpu.profile.* confs registered")
    for key in sorted(prof_confs):
        if f"`{key}`" not in prof_md:
            problems.append(
                f"conf '{key}' is not documented in docs/profiling.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("cost_model_hits", "cost_model_misses",
                "cost_model_predicted_wall_ns",
                "cost_model_matched_actual_wall_ns",
                "advisor_plan_fallbacks"):
        if key not in PC.COUNTERS:
            problems.append(f"profiling counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in prof_md:
            problems.append(
                f"profiling counter '{key}' is not documented in "
                f"docs/profiling.md")
    if "cost_model" not in EVENT_SCHEMA:
        problems.append("diagnostics event type 'cost_model' is not "
                        "registered in EVENT_SCHEMA")
    for field in ("op_class", "fp"):
        if field not in EVENT_SCHEMA.get("operator", []):
            problems.append(
                f"operator event field '{field}' (the calibration "
                f"identity) is missing from EVENT_SCHEMA")
    for gauge in ("cost_model_predicted_wall_ms",
                  "cost_model_matched_actual_wall_ms",
                  "cost_model_hit_rate", "cost_model_prediction_error"):
        if f"`{gauge}`" not in prof_md:
            problems.append(
                f"profiling telemetry gauge '{gauge}' is not "
                f"documented in docs/profiling.md")
    # the advisory file vocabulary the plan-time consult depends on
    for word in ("`route`", "`device`", "`native`", "`cpu`",
                 "`fallback-heavy`", "`sync-bound`", "`transport-bound`",
                 "advisory.json", "calibration.json"):
        if word not in prof_md:
            problems.append(
                f"advisory/store vocabulary {word} is not documented "
                f"in docs/profiling.md")

    # out-of-core exchange + ICI shuffle (ISSUE 10): confs + counters +
    # the ici_shuffle event must be documented in docs/out_of_core.md
    # (and confs in the regenerated configs.md)
    ooc_md = read("out_of_core.md")
    ooc_confs = [k for k in _REGISTRY
                 if k.startswith(("spark.rapids.tpu.exchange.",
                                  "spark.rapids.tpu.ici."))]
    if not ooc_confs:
        problems.append("no spark.rapids.tpu.exchange.* / "
                        "spark.rapids.tpu.ici.* confs registered")
    for key in sorted(ooc_confs):
        if f"`{key}`" not in ooc_md:
            problems.append(
                f"conf '{key}' is not documented in "
                f"docs/out_of_core.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("exchange_partitions_planned", "exchange_partition_ns",
                "exchange_spill_ns", "exchange_host_blocks",
                "exchange_host_block_bytes", "partitions_coalesced",
                "ici_epochs", "ici_rows_exchanged", "ici_bytes_moved",
                "ici_shuffle_ns"):
        if key not in PC.COUNTERS:
            problems.append(f"out-of-core counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in ooc_md:
            problems.append(
                f"out-of-core counter '{key}' is not documented in "
                f"docs/out_of_core.md")
    if "ici_shuffle" not in EVENT_SCHEMA:
        problems.append("diagnostics event type 'ici_shuffle' is not "
                        "registered in EVENT_SCHEMA")

    # live progress (ISSUE 12): confs + counters + the query_stall /
    # progress event vocabulary + the sampler's aggregate gauges + the
    # history-server tooling must be documented in docs/progress.md
    # (and confs in the regenerated configs.md)
    prog_md = read("progress.md")
    prog_confs = [k for k in _REGISTRY
                  if k.startswith("spark.rapids.tpu.progress.")]
    if not prog_confs:
        problems.append("no spark.rapids.tpu.progress.* confs "
                        "registered")
    for key in sorted(prog_confs):
        if f"`{key}`" not in prog_md:
            problems.append(
                f"conf '{key}' is not documented in docs/progress.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("stalls_detected", "progress_snapshots"):
        if key not in PC.COUNTERS:
            problems.append(f"progress counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in prog_md:
            problems.append(
                f"progress counter '{key}' is not documented in "
                f"docs/progress.md")
    for ev in ("query_stall", "progress"):
        if ev not in EVENT_SCHEMA:
            problems.append(f"diagnostics event type '{ev}' is not "
                            f"registered in EVENT_SCHEMA")
    for gauge in ("progress_queries_running", "progress_min_pct",
                  "progress_median_pct", "progress_stalled"):
        if f"`{gauge}`" not in prog_md:
            problems.append(
                f"progress sampler gauge '{gauge}' is not documented "
                f"in docs/progress.md")
    for word in ("history.py", "`/progress`", "`aot_compile`",
                 "`scan_prefetch`", "`shuffle_write`", "`--stalls`",
                 "progressOverhead"):
        if word not in prog_md:
            problems.append(
                f"progress surface vocabulary {word} is not "
                f"documented in docs/progress.md")

    # overload governor (ISSUE 13): confs + counters + the sampler
    # gauges + the governor event + the stress/chaos driver vocabulary
    # must be documented in docs/overload.md (and confs in configs.md)
    ovl_md = read("overload.md")
    gov_confs = [k for k in _REGISTRY
                 if k.startswith("spark.rapids.tpu.governor.")]
    if not gov_confs:
        problems.append("no spark.rapids.tpu.governor.* confs "
                        "registered")
    for key in sorted(gov_confs):
        if f"`{key}`" not in ovl_md:
            problems.append(
                f"conf '{key}' is not documented in docs/overload.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("governor_transitions", "queries_shed",
                "preempt_pauses", "degraded_batches",
                "oom_retry_preempts", "oom_retry_splits"):
        if key not in PC.COUNTERS:
            problems.append(f"governor counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in ovl_md:
            problems.append(
                f"governor counter '{key}' is not documented in "
                f"docs/overload.md")
    if "governor" not in EVENT_SCHEMA:
        problems.append("diagnostics event type 'governor' is not "
                        "registered in EVENT_SCHEMA")
    for gauge in ("governor_state", "governor_pressure"):
        if f"`{gauge}`" not in ovl_md:
            problems.append(
                f"governor sampler gauge '{gauge}' is not documented "
                f"in docs/overload.md")
    for word in ("`--overload`", "`--pressure`", "`retry_after_ms`",
                 "`queue_depth`", "`pressure_state`", "`governor_red`",
                 "`QueryRejected`", "run_stress.py", "run_chaos.py",
                 "bench_gate.py"):
        if word not in ovl_md:
            problems.append(
                f"governor surface vocabulary {word} is not "
                f"documented in docs/overload.md")

    # distributed cross-host tier (ISSUE 14): confs + counters + the
    # sampler gauges + the distributed event + the chaos/bench surface
    # vocabulary must be documented in docs/distributed.md (confs in
    # configs.md, counters ALSO in diagnostics.md via the global check)
    dist_md = read("distributed.md")
    dist_confs = [k for k in _REGISTRY
                  if k.startswith("spark.rapids.tpu.distributed.")]
    if not dist_confs:
        problems.append("no spark.rapids.tpu.distributed.* confs "
                        "registered")
    for key in sorted(dist_confs):
        if f"`{key}`" not in dist_md:
            problems.append(
                f"conf '{key}' is not documented in "
                f"docs/distributed.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("workers_joined", "worker_lost",
                "worker_heartbeat_misses", "partitions_replayed",
                "dist_blocks_shipped", "dist_block_bytes"):
        if key not in PC.COUNTERS:
            problems.append(f"distributed counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in dist_md:
            problems.append(
                f"distributed counter '{key}' is not documented in "
                f"docs/distributed.md")
    if "distributed" not in EVENT_SCHEMA:
        problems.append("diagnostics event type 'distributed' is not "
                        "registered in EVENT_SCHEMA")
    for gauge in ("dist_workers_live", "dist_workers_quarantined",
                  "dist_replacement_backlog"):
        if f"`{gauge}`" not in dist_md:
            problems.append(
                f"distributed sampler gauge '{gauge}' is not "
                f"documented in docs/distributed.md")
    for word in ("`--worker-kill`", "`WorkerLost`", "QUARANTINED",
                 "`worker_lost`", "`partition_replayed`", "rung4_dist",
                 "`TKD1`", "`TKU2`", "`ProtocolCorruption`",
                 "run_chaos.py", "bench_gate", "lineage"):
        if word not in dist_md:
            problems.append(
                f"distributed surface vocabulary {word} is not "
                f"documented in docs/distributed.md")

    # cluster observability (ISSUE 15): the worker-local counter
    # vocabulary, the federation gauges, the trace-id contract and the
    # merged-bundle/trace surfaces must be documented in
    # docs/cluster_observability.md (worker counters are NOT
    # perfcounters.COUNTERS — they live in the worker process — so the
    # global diagnostics.md check cannot see them)
    from spark_rapids_tpu.distributed.worker import WORKER_COUNTER_KEYS

    cluster_md = read("cluster_observability.md")
    for key in WORKER_COUNTER_KEYS:
        if f"`{key}`" not in cluster_md:
            problems.append(
                f"worker-local counter '{key}' is not documented in "
                f"docs/cluster_observability.md")
    for gauge in ("dist_blocks_unacked",):
        if f"`{gauge}`" not in cluster_md:
            problems.append(
                f"cluster-observability gauge '{gauge}' is not "
                f"documented in docs/cluster_observability.md")
    for ev in ("worker_telemetry", "worker_span"):
        if ev not in EVENT_SCHEMA:
            problems.append(f"diagnostics event type '{ev}' is not "
                            f"registered in EVENT_SCHEMA")
        if f"`{ev}`" not in cluster_md:
            problems.append(
                f"cluster-observability event '{ev}' is not "
                f"documented in docs/cluster_observability.md")
    if "trace_id" not in EVENT_SCHEMA.get("query_start", []):
        problems.append(
            "query_start event is missing the 'trace_id' field (the "
            "cluster trace contract)")
    for key in ("dist_worker_dumps", "dist_worker_spans_merged"):
        if key not in PC.COUNTERS:
            problems.append(f"cluster-observability counter '{key}' is "
                            f"not registered in perfcounters.COUNTERS")
        if f"`{key}`" not in cluster_md:
            problems.append(
                f"cluster-observability counter '{key}' is not "
                f"documented in docs/cluster_observability.md")
    for word in ("trace id", "`trace`", "`span`", "`dump`",
                 "clock offset", "heartbeat", "piggyback",
                 "`worker=`", "history.py", "`/cluster`",
                 "`--telemetry-out`", "`--workers`",
                 "traceOverheadPct", "`redrive`", "Perfetto",
                 "worker_diagnostics", "mint_trace_id"):
        if word not in cluster_md:
            problems.append(
                f"cluster-observability vocabulary {word} is not "
                f"documented in docs/cluster_observability.md")
    for name, md in (("distributed.md", dist_md),
                     ("observability.md", obs_md)):
        if "cluster_observability.md" not in md:
            problems.append(
                f"docs/{name} does not cross-link "
                f"docs/cluster_observability.md")

    # crash-consistent recovery (ISSUE 16): confs + counters + the
    # recovery event + the journal/checkpoint/lease surface vocabulary
    # must be documented in docs/recovery.md (confs in configs.md,
    # counters ALSO in diagnostics.md via the global check)
    rec_md = read("recovery.md")
    rec_confs = [k for k in _REGISTRY
                 if k.startswith("spark.rapids.tpu.recovery.")]
    if not rec_confs:
        problems.append("no spark.rapids.tpu.recovery.* confs "
                        "registered")
    for key in sorted(rec_confs):
        if f"`{key}`" not in rec_md:
            problems.append(
                f"conf '{key}' is not documented in docs/recovery.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("journal_records_written", "stages_recovered",
                "queries_resumed", "journal_recovery_discards",
                "recovery_leases_expired"):
        if key not in PC.COUNTERS:
            problems.append(f"recovery counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in rec_md:
            problems.append(
                f"recovery counter '{key}' is not documented in "
                f"docs/recovery.md")
    if "recovery" not in EVENT_SCHEMA:
        problems.append("diagnostics event type 'recovery' is not "
                        "registered in EVENT_SCHEMA")
    for word in ("`TKJ1`", "`journal.wal`", "`journal.replay`",
                 "`coordinator.endpoint`", "MANIFEST.json",
                 "`completed`", "`resumable`", "`abandoned`",
                 "`--driver-kill`", "re-HELLO", "lease",
                 "`stage_committed`", "`stage_recovered`",
                 "`driver_crash`", "run_chaos.py", "rung5_recovery",
                 "journalOverheadPct"):
        if word not in rec_md:
            problems.append(
                f"recovery surface vocabulary {word} is not "
                f"documented in docs/recovery.md")
    for name, md in (("distributed.md", dist_md),
                     ("concurrency.md", conc_md)):
        if "recovery.md" not in md:
            problems.append(
                f"docs/{name} does not cross-link docs/recovery.md")

    # tracelint (ISSUE 11): every lint rule id and the fusibility
    # manifest vocabulary must be documented in docs/static_analysis.md
    from spark_rapids_tpu.analysis.core import all_rule_ids

    sa_md = read("static_analysis.md")
    for rid in all_rule_ids(include_docs=True):
        if f"`{rid}`" not in sa_md:
            problems.append(
                f"lint rule '{rid}' is not documented in "
                f"docs/static_analysis.md")
    for word in ("`fusable`", "`fusable-with-rewrite`", "`unfusable`",
                 "`op_class`", "fusibility.py", "`--sarif`",
                 "`--prune-baseline`", "`--rules`"):
        if word not in sa_md:
            problems.append(
                f"tracelint/fusibility vocabulary {word} is not "
                f"documented in docs/static_analysis.md")

    # whole-plan fusion (ISSUE 17): confs + counters + the runtime
    # dispatch / fusion / bench-gate surface vocabulary must be
    # documented in docs/whole_plan_fusion.md (confs in configs.md,
    # counters ALSO in diagnostics.md via the global check), and the
    # docs the pass's machinery rides on must cross-link it
    fus_md = read("whole_plan_fusion.md")
    fus_confs = [k for k in _REGISTRY
                 if k.startswith("spark.rapids.tpu.fusion.")]
    if not fus_confs:
        problems.append("no spark.rapids.tpu.fusion.* confs registered")
    for key in sorted(fus_confs):
        if f"`{key}`" not in fus_md:
            problems.append(
                f"conf '{key}' is not documented in "
                f"docs/whole_plan_fusion.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("subtrees_fused", "collect_shrinks_elided"):
        if key not in PC.COUNTERS:
            problems.append(f"fusion counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in fus_md:
            problems.append(
                f"fusion counter '{key}' is not documented in "
                f"docs/whole_plan_fusion.md")
    for word in ("`CONCERNS`", "`fusion_segment()`", "`PipelineSegment`",
                 "`MANIFEST_ELIGIBLE`", "`tools/fusibility_manifest.json`",
                 "`fusable-with-rewrite`", "trace-time aux", "`--check`",
                 "`predicted_intermediate_bytes`",
                 "`nProgramsLaunched`", "`nHostSyncs`",
                 "splits at the predicted boundary", "TpuFusedPipeline["):
        if word not in fus_md:
            problems.append(
                f"whole-plan-fusion surface vocabulary {word} is not "
                f"documented in docs/whole_plan_fusion.md")
    for name, md in (("out_of_core.md", read("out_of_core.md")),
                     ("static_analysis.md", sa_md),
                     ("profiling.md", read("profiling.md"))):
        if "whole_plan_fusion.md" not in md:
            problems.append(
                f"docs/{name} does not cross-link "
                f"docs/whole_plan_fusion.md")

    # per-query resource accounting + regression sentinel (ISSUE 18):
    # confs + counters + the bill gauges + the resource_bill/regression
    # events + the bill/sentinel surface vocabulary must be documented
    # in docs/accounting.md (confs in configs.md, counters ALSO in
    # diagnostics.md via the global check), and the observability docs
    # the layer rides on must cross-link it
    acct_md = read("accounting.md")
    acct_confs = [k for k in _REGISTRY
                  if k.startswith("spark.rapids.tpu.accounting.")]
    if not acct_confs:
        problems.append("no spark.rapids.tpu.accounting.* confs "
                        "registered")
    for key in sorted(acct_confs):
        if f"`{key}`" not in acct_md:
            problems.append(
                f"conf '{key}' is not documented in docs/accounting.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("acct_device_bytes_charged", "acct_device_bytes_released",
                "acct_spill_bytes_host", "acct_spill_bytes_disk",
                "acct_bytes_restored", "bills_settled",
                "perf_regressions_flagged"):
        if key not in PC.COUNTERS:
            problems.append(f"accounting counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in acct_md:
            problems.append(
                f"accounting counter '{key}' is not documented in "
                f"docs/accounting.md")
    for ev in ("resource_bill", "regression"):
        if ev not in EVENT_SCHEMA:
            problems.append(f"diagnostics event type '{ev}' is not "
                            f"registered in EVENT_SCHEMA")
        if f"`{ev}`" not in acct_md:
            problems.append(
                f"accounting event '{ev}' is not documented in "
                f"docs/accounting.md")
    for gauge in ("bill_device_peak_bytes", "bill_device_byte_seconds",
                  "bill_spilled_bytes"):
        if f"`{gauge}`" not in acct_md:
            problems.append(
                f"accounting bill gauge '{gauge}' is not documented "
                f"in docs/accounting.md")
    for word in ("device-byte-seconds", "`(unowned)`", "`--bills`",
                 "`residual_bytes`", "`perf_regression`",
                 "`devicePeakBytes`", "`deviceByteSeconds`",
                 "`spilledBytes`", "accountingOverhead", "bench_gate",
                 "history.py", "`df.cache()`", "plan-signature"):
        if word not in acct_md:
            problems.append(
                f"accounting surface vocabulary {word} is not "
                f"documented in docs/accounting.md")
    for name, md in (("observability.md", obs_md),
                     ("profiling.md", read("profiling.md")),
                     ("overload.md", ovl_md)):
        if "accounting.md" not in md:
            problems.append(
                f"docs/{name} does not cross-link docs/accounting.md")

    # multi-tenant serving tier (ISSUE 19): confs + counters + the
    # sampler gauges + the session/fair-share/result-cache/warm-start
    # surface vocabulary must be documented in docs/serving.md (confs
    # in configs.md, counters ALSO in diagnostics.md via the global
    # check), and the docs the tier composes over must cross-link it
    srv_md = read("serving.md")
    srv_confs = [k for k in _REGISTRY
                 if k.startswith("spark.rapids.tpu.serving.")]
    if not srv_confs:
        problems.append("no spark.rapids.tpu.serving.* confs registered")
    for key in sorted(srv_confs):
        if f"`{key}`" not in srv_md:
            problems.append(
                f"conf '{key}' is not documented in docs/serving.md")
        if f"`{key}`" not in configs_md:
            problems.append(
                f"conf '{key}' missing from docs/configs.md — re-run "
                f"python docs/gen_docs.py")
    for key in ("serving_sessions_opened", "serving_sessions_closed",
                "fair_share_admissions", "result_cache_hits",
                "result_cache_misses", "result_cache_evictions",
                "tenant_sheds", "tenant_preempts"):
        if key not in PC.COUNTERS:
            problems.append(f"serving counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in srv_md:
            problems.append(
                f"serving counter '{key}' is not documented in "
                f"docs/serving.md")
    for gauge in ("serving_tenants_active", "serving_queue_depth",
                  "serving_running", "result_cache_entries",
                  "result_cache_bytes"):
        if f"`{gauge}`" not in srv_md:
            problems.append(
                f"serving sampler gauge '{gauge}' is not documented "
                f"in docs/serving.md")
    for word in ("fair-share", "`retry_after_ms`", "`QueryRejected`",
                 "`tenant=<name>`", "`drop_tenant`", "warm_cache.py",
                 "`--serve`", "`--serving`", "`--trace`",
                 "work-conserving", "half-life",
                 "`result_plan_key`", "`shutdown_serving()`",
                 "starved", "bench_gate", "`close()`"):
        if word not in srv_md:
            problems.append(
                f"serving surface vocabulary {word} is not documented "
                f"in docs/serving.md")
    for name, md in (("concurrency.md", conc_md),
                     ("overload.md", ovl_md),
                     ("observability.md", obs_md)):
        if "serving.md" not in md:
            problems.append(
                f"docs/{name} does not cross-link docs/serving.md")

    # gray-failure resilience (ISSUE 20): the hedging/DEGRADED counters
    # + sampler gauges + the soft-deadline/netchaos surface vocabulary
    # must be documented in docs/distributed.md (confs are covered by
    # the ISSUE 14 prefix loop above, counters ALSO in diagnostics.md
    # via the global check), and the failure taxonomy in
    # docs/resilience.md must carry the workerDegraded class
    for key in ("fetch_hedges", "hedges_won", "workers_degraded",
                "speculative_redrives"):
        if key not in PC.COUNTERS:
            problems.append(f"gray-failure counter '{key}' is not "
                            f"registered in perfcounters.COUNTERS")
        if f"`{key}`" not in dist_md:
            problems.append(
                f"gray-failure counter '{key}' is not documented in "
                f"docs/distributed.md")
    for gauge in ("dist_workers_degraded", "dist_fleet_lat_p95_ms"):
        if f"`{gauge}`" not in dist_md:
            problems.append(
                f"gray-failure sampler gauge '{gauge}' is not "
                f"documented in docs/distributed.md")
    for word in ("DEGRADED", "soft deadline", "hedge", "`--net`",
                 "netchaos", "`worker_degraded`", "`worker_promoted`",
                 "`WorkerDegraded`", "`ProtocolDesync`",
                 "first-complete-wins", "p95", "fleet median",
                 "test_gray_failure"):
        if word not in dist_md:
            problems.append(
                f"gray-failure surface vocabulary {word} is not "
                f"documented in docs/distributed.md")
    res_md = read("resilience.md")
    for word in ("`workerDegraded`", "`WorkerDegraded`", "`--net`",
                 "netchaos"):
        if word not in res_md:
            problems.append(
                f"gray-failure taxonomy vocabulary {word} is not "
                f"documented in docs/resilience.md")
    return problems


def _docs_file_of(problem: str) -> str:
    """Best-effort anchor: the docs file the message names, else the
    shim (registry-side problems)."""
    for tok in problem.split():
        tok = tok.rstrip(".,;:)")
        if tok.startswith("docs/") and tok.endswith(".md"):
            return tok
    return "tools/check_counters.py"


class DocDriftRule:
    """Repo-level rule: runs once per analysis, not per file."""

    id = "doc-drift"
    node_types = ()
    HINT = ("update the named docs file (and re-run python "
            "docs/gen_docs.py for configs.md) so the registered "
            "vocabulary and the documentation stay in sync")

    def end_run(self, engine: Engine) -> None:
        for problem in doc_drift_problems(engine.repo_root):
            engine.findings.append(Finding(
                _docs_file_of(problem), 1, 0, self.id, problem,
                self.HINT, "doc-drift"))
