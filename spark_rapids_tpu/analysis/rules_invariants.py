"""Tier A — project-invariant lints.

Each rule encodes an invariant a prior PR introduced and until now only
enforced at runtime (see docs/static_analysis.md for the catalogue and
the PR that owns each invariant):

* ``counter-write``    — every ``perfcounters.COUNTERS`` mutation goes
  through ``bump()``/``bump_unattributed()`` (or holds the counter
  lock inside perfcounters.py itself).  PR 1 made unguarded increments
  a lost-update bug; this makes them a CI error.
* ``cancel-swallow``   — a broad ``except Exception`` / bare ``except``
  in the cancellation-observing packages must re-raise or classify:
  ``QueryCancelled`` / ``QueryDeadlineExceeded`` are PROPAGATE-class
  (PR 4) and a handler that absorbs them turns a cancelled query into
  a wrong answer.
* ``unaccounted-sync`` — ``jax.device_get`` / ``.block_until_ready()``
  on exec/scan/shuffle hot paths must run inside ``sync_event`` (or
  ``sync_get``) so ``host_syncs`` counts LOGICAL round trips (PR 3).
* ``conf-vocabulary``  — every literal ``spark.*`` key at a conf
  get/set site must be declared via the typed ``conf(...)`` builder
  (the AST-resolved half of the old grep in check_counters.py).
* ``module-state``     — module-level mutable containers / singletons
  mutated from two or more functions need a module lock.
* ``unlocked-rmw``     — ``self.x += n`` outside any lock in a class
  that guards other state with a lock is a non-atomic
  read-modify-write (three bytecodes; CPython switches threads
  between them — the exact bug class perfcounters.bump() documents).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.analysis.core import FileCtx, Walk, _collect_files

# dict/set/list mutator method names that change the container in place
MUTATORS = frozenset((
    "append", "appendleft", "add", "update", "insert", "extend",
    "remove", "discard", "pop", "popitem", "popleft", "clear",
    "setdefault", "move_to_end", "sort", "reverse",
))

CONTAINER_CTORS = frozenset((
    "dict", "list", "set", "deque", "OrderedDict", "defaultdict",
    "Counter",
))


def _in_scoped_dirs(rel: str, segments: Tuple[str, ...]) -> bool:
    parts = rel.split("/")
    return any(seg in parts for seg in segments)


def _trailing_name(expr: ast.AST) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


def _is_container_ctor(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return _trailing_name(node.func) in CONTAINER_CTORS
    return False


# ---------------------------------------------------------------------------
# counter-write
# ---------------------------------------------------------------------------

class CounterWriteRule:
    """Any mutation of ``COUNTERS`` outside ``perfcounters.py``'s
    lock-guarded helpers loses updates under concurrency."""

    id = "counter-write"
    node_types = (ast.Assign, ast.AugAssign, ast.Delete, ast.Call)
    HINT = ("route the increment through perfcounters.bump() / "
            "bump_unattributed(); direct writes race and skip "
            "diagnostics attribution")

    @staticmethod
    def _is_counters(expr: ast.AST) -> bool:
        return _trailing_name(expr) == "COUNTERS"

    def _targets(self, node: ast.AST) -> List[ast.AST]:
        if isinstance(node, ast.Assign):
            return list(node.targets)
        if isinstance(node, ast.AugAssign):
            return [node.target]
        if isinstance(node, ast.Delete):
            return list(node.targets)
        return []

    def visit(self, node: ast.AST, walk: Walk) -> None:
        ctx = walk.ctx
        in_perfcounters = ctx.rel.endswith("perfcounters.py")
        hits: List[Tuple[ast.AST, str]] = []
        for t in self._targets(node):
            if isinstance(t, ast.Subscript) and self._is_counters(t.value):
                hits.append((node, "COUNTERS[...] write"))
            elif (self._is_counters(t) and walk.func_stack):
                hits.append((node, "COUNTERS rebound"))
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and self._is_counters(fn.value)
                    and fn.attr in MUTATORS):
                hits.append((node, f"COUNTERS.{fn.attr}() call"))
        for hit_node, what in hits:
            if in_perfcounters:
                # inside the owning module a write is legal only under
                # the counter lock (bump / reset / _CountingJit)
                if any(lk.endswith("::_LOCK") for lk in walk.held_locks()):
                    continue
            walk.report(self.id, hit_node,
                        f"{what} bypasses bump() — perfcounters.COUNTERS "
                        f"may only be mutated under the counter lock",
                        self.HINT)


# ---------------------------------------------------------------------------
# cancel-swallow
# ---------------------------------------------------------------------------

class CancelSwallowRule:
    """Broad excepts in the cancellation-observing packages must
    re-raise or classify; otherwise a tripped CancelToken's
    ``QueryCancelled`` dies in the handler and the query keeps running
    (or returns partial data)."""

    id = "cancel-swallow"
    node_types = (ast.Try,)
    SCOPED = ("exec", "lifecycle", "resilience", "io", "shuffle")
    # a handler that consults the failure taxonomy is explicitly
    # classifying; resilience/classify.py routes PROPAGATE back out
    CLASSIFIERS = frozenset((
        "classify_failure", "exception_chain", "is_device_oom",
        "to_scan_fault", "handle_scan_error",
    ))
    # only types that actually CATCH a raised QueryCancelled count as
    # interception: QueryCancelled itself or a superclass.  The
    # subclass QueryDeadlineExceeded and the sibling QueryRejected
    # intercept nothing — a QueryCancelled sails past those clauses
    # into the broad handler.
    CANCEL_TYPES = frozenset((
        "QueryCancelled", "BaseException", "Exception", "RuntimeError",
    ))
    HINT = ("re-raise PROPAGATE failures: classify via "
            "resilience.classify.classify_failure (or catch "
            "QueryCancelled first) so a tripped CancelToken unwinds")

    def _broad(self, h: ast.ExceptHandler) -> bool:
        if h.type is None:
            return True
        types = (h.type.elts if isinstance(h.type, ast.Tuple)
                 else [h.type])
        return any(_trailing_name(t) in ("Exception", "BaseException")
                   for t in types)

    def _names_cancel(self, h: ast.ExceptHandler) -> bool:
        if h.type is None:
            return False
        types = (h.type.elts if isinstance(h.type, ast.Tuple)
                 else [h.type])
        return any(_trailing_name(t) in self.CANCEL_TYPES for t in types)

    def _handler_ok(self, h: ast.ExceptHandler) -> bool:
        for sub in ast.walk(h):
            if isinstance(sub, ast.Raise):
                return True
            if (isinstance(sub, ast.Call)
                    and _trailing_name(sub.func) in self.CLASSIFIERS):
                return True
        return False

    def visit(self, node: ast.Try, walk: Walk) -> None:
        if not _in_scoped_dirs(walk.ctx.rel, self.SCOPED):
            return
        # cancel_handled tracks EARLIER clauses only: a handler must not
        # exempt itself by naming BaseException (a swallowing
        # `except BaseException:` behind a narrow clause is exactly the
        # bug this rule exists for)
        cancel_handled = False
        for h in node.handlers:
            if self._broad(h) and not cancel_handled \
                    and not self._handler_ok(h):
                what = ("bare except:" if h.type is None
                        else f"except {ast.unparse(h.type)}")
                walk.report(self.id, h,
                            f"{what} can swallow QueryCancelled/"
                            f"QueryDeadlineExceeded without re-raise or "
                            f"classification", self.HINT)
            if self._names_cancel(h):
                cancel_handled = True


# ---------------------------------------------------------------------------
# unaccounted-sync
# ---------------------------------------------------------------------------

class UnaccountedSyncRule:
    """Device->host materializations on hot paths must be routed
    through ``sync_event`` so ``host_syncs`` counts LOGICAL round trips
    (a pytree fetch is ONE trip, not one per leaf — perfcounters
    docstring).  ``np.asarray``-on-device cannot be resolved statically
    (host arrays share the spelling) and is deliberately out of scope."""

    id = "unaccounted-sync"
    node_types = (ast.Call,)
    SCOPED = ("exec", "io", "shuffle")
    HINT = ("wrap in `with sync_event():` or use perfcounters.sync_get "
            "for a pytree — one logical host round trip, exact "
            "host_syncs accounting")

    def visit(self, node: ast.Call, walk: Walk) -> None:
        if not _in_scoped_dirs(walk.ctx.rel, self.SCOPED):
            return
        name = _trailing_name(node.func)
        if name not in ("device_get", "block_until_ready"):
            return
        if walk.in_sync_event():
            return
        if "sync_get" in walk.func_stack:
            return
        walk.report(self.id, node,
                    f"{name}() outside sync_event: each materialized "
                    f"leaf counts a separate host_syncs round trip",
                    self.HINT)


# ---------------------------------------------------------------------------
# conf-vocabulary
# ---------------------------------------------------------------------------

class ConfVocabularyRule:
    """Every literal ``spark.*`` key read/written at a conf-get site
    must be a key the typed registry declares via ``conf("...")`` —
    typos silently fall back to defaults otherwise."""

    id = "conf-vocabulary"
    node_types = (ast.Call, ast.Subscript)
    # per-op kill switches and similar families are registered with
    # dynamically-built keys; literal members of the family are legal
    DYNAMIC_PREFIXES = (
        "spark.rapids.sql.expression.",
        "spark.rapids.sql.exec.",
    )
    HINT = ("declare the key with the conf(\"...\") builder in the "
            "owning module (or fix the typo) — unregistered keys "
            "silently read their hardcoded fallback")

    def __init__(self):
        self.vocab: Set[str] = set()

    # -- phase 0: repo-wide declarations --------------------------------
    def begin_run(self, engine) -> None:
        """A SCOPED run (`tools/lint.py some/dir`) must still know every
        key the repo declares, or correct reads of out-of-scope
        declarations become false positives.  Declarations are simple
        string literals, so a regex sweep (no extra AST parses) over the
        source tree is exact enough."""
        import re

        pat = re.compile(r"""conf\(\s*['"]([^'"]+)['"]""")
        for sub in ("spark_rapids_tpu", "tools"):
            root = os.path.join(engine.repo_root, sub)
            if not os.path.isdir(root):
                continue
            for path in _collect_files([root]):
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        self.vocab.update(pat.findall(f.read()))
                except OSError:
                    continue

    # -- phase 1: collect declarations (covers fixture trees whose
    # repo_root has no spark_rapids_tpu/) -------------------------------
    def prescan(self, ctx: FileCtx) -> None:
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and _trailing_name(node.func) == "conf"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                self.vocab.add(node.args[0].value)

    # -- phase 2: judge get/set sites -----------------------------------
    def _check_key(self, key: str) -> bool:
        if key in self.vocab:
            return True
        return any(key.startswith(p) for p in self.DYNAMIC_PREFIXES)

    def _report(self, walk: Walk, node: ast.AST, key: str,
                site: str) -> None:
        walk.report(self.id, node,
                    f"conf key '{key}' at a {site} site is not declared "
                    f"in the typed registry", self.HINT)

    def visit(self, node: ast.AST, walk: Walk) -> None:
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in ("get", "set", "unset")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                key = node.args[0].value
                if (key.startswith(("spark.rapids.", "spark.sql."))
                        and not self._check_key(key)):
                    self._report(walk, node, key, f".{fn.attr}()")
        elif isinstance(node, ast.Subscript):
            sl = node.slice
            if (isinstance(sl, ast.Constant) and isinstance(sl.value, str)
                    and sl.value.startswith(("spark.rapids.",
                                             "spark.sql."))
                    and not self._check_key(sl.value)):
                self._report(walk, node, sl.value, "subscript")


# ---------------------------------------------------------------------------
# module-state
# ---------------------------------------------------------------------------

class ModuleStateRule:
    """Module-level mutable containers (and ``global``-rebound
    singletons) mutated from two or more functions without a module
    lock in scope: the classic unguarded-shared-state race."""

    id = "module-state"
    node_types = (ast.Assign, ast.AugAssign, ast.Delete, ast.Call,
                  ast.Global)
    HINT = ("guard every mutation with a module-level threading.Lock "
            "(`with _lock:`) — or make the state per-instance")

    def begin_file(self, ctx: FileCtx) -> None:
        self._containers: Set[str] = set()
        self._module_names: Set[str] = set()
        for st in ctx.tree.body:
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(st, ast.Assign):
                targets, value = st.targets, st.value
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                targets, value = [st.target], st.value
            for t in targets:
                if isinstance(t, ast.Name):
                    self._module_names.add(t.id)
                    if _is_container_ctor(value):
                        self._containers.add(t.id)
        # name -> list of (func_qualname, guarded, node)
        self._sites: Dict[str, List[Tuple[str, bool, ast.AST]]] = {}
        self._globals_in_func: Dict[str, Set[str]] = {}

    def _record(self, walk: Walk, name: str, node: ast.AST) -> None:
        if not walk.func_stack:
            return                       # module-level init is fine
        self._sites.setdefault(name, []).append(
            (walk.qualname(), bool(walk.held_locks()), node))

    def visit(self, node: ast.AST, walk: Walk) -> None:
        if isinstance(node, ast.Global):
            if walk.func_stack:
                self._globals_in_func.setdefault(
                    walk.qualname(), set()).update(node.names)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (node.targets if isinstance(node, (ast.Assign,
                                                         ast.Delete))
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in self._containers):
                    self._record(walk, t.value.id, node)
                elif (isinstance(t, ast.Name)
                        and t.id in self._module_names
                        and t.id in self._globals_in_func.get(
                            walk.qualname(), ())):
                    self._record(walk, t.id, node)
        elif isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in self._containers
                    and fn.attr in MUTATORS):
                self._record(walk, fn.value.id, node)

    def end_file(self, walk: Walk) -> None:
        for name in sorted(self._sites):
            sites = self._sites[name]
            funcs = {q for q, _, _ in sites}
            if len(funcs) < 2:
                continue
            unguarded = [(q, n) for q, g, n in sites if not g]
            if not unguarded:
                continue
            unguarded.sort(key=lambda s: (s[1].lineno, s[1].col_offset))
            q, node = unguarded[0]
            walk.engine.report(
                walk.ctx, self.id, node.lineno, node.col_offset,
                f"module-level mutable state '{name}' is mutated from "
                f"{len(funcs)} functions with at least one write "
                f"outside any module lock", self.HINT, q)


# ---------------------------------------------------------------------------
# unlocked-rmw
# ---------------------------------------------------------------------------

class UnlockedRmwRule:
    """``self.x += n`` in a lock-guarded class, outside the lock:
    load/add/store is three bytecodes and concurrent increments lose
    updates (the exact race perfcounters.bump() exists to prevent)."""

    id = "unlocked-rmw"
    node_types = (ast.AugAssign,)
    HINT = ("perform the increment inside `with self._lock:` (or the "
            "class's guarding lock); a method suffixed `_locked` "
            "documents caller-holds-lock and is exempt")

    def visit(self, node: ast.AugAssign, walk: Walk) -> None:
        cls = walk.current_class
        if not cls or cls not in walk.ctx.class_locks:
            return
        t = node.target
        if not (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"):
            return
        if not walk.func_stack:
            return
        if any(f == "__init__" or f.endswith("_locked")
               for f in walk.func_stack):
            return
        if walk.held_locks():
            return
        walk.report(self.id, node,
                    f"read-modify-write of self.{t.attr} outside any "
                    f"lock in lock-guarded class {cls} — concurrent "
                    f"increments lose updates", self.HINT)
