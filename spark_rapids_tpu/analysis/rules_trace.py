"""Tier C — trace-safety rules over every jitted region (tracelint).

Consumes the interprocedural :mod:`callgraph`: every function reachable
from a ``jax.jit`` / ``tpu_jit`` / ``pallas_call`` / ``shard_map`` /
``cached_jit_program`` site is a **traced region**, and its parameters
carry a shallow traced-value taint.  The rules encode the bug classes
the jit boundary actually produced in this repo:

* ``trace-conf-read``     — ``get_conf()`` (or an ambient-conf helper)
  inside traced code: the value BAKES into the trace at compile time,
  and a session changing the setting keeps executing the stale program
  unless the key happens to be in the program fingerprint.  Hoist the
  read to build time and make it part of the cache key (``conf_fp``).
* ``trace-side-effect``   — counter bumps, diagnostics/telemetry
  recording, lock acquisition, or wall-clock reads inside traced code:
  they run ONCE at trace time (so counts/timings lie) and never again
  on cache hits.
* ``trace-host-sync``     — ``float()``/``int()``/``bool()`` /
  ``.item()``/``.tolist()``/``np.asarray`` on a traced value, or
  ``device_get``/``block_until_ready`` anywhere in traced code: a
  concretization error at trace time on TPU, a hidden device round
  trip when the same helper runs eagerly.
* ``trace-branch``        — Python ``if``/``while`` on a traced value:
  the branch freezes at trace time (or raises
  ``TracerBoolConversionError``); use ``jnp.where``/``lax.cond``.
* ``trace-closure-state`` — traced code reading (by subscript) or
  mutating a mutable container captured from an enclosing scope: the
  state is baked at trace time and silently stale on every cache hit
  (the ``offset_holder``/``msgs_store`` pattern — legal only with a
  justifying pragma, because the aux must travel WITH the executable).
* ``retrace-key``         — unstable Python values feeding a program
  cache key (``fingerprint``/``cached_program``/``cached_jit_program``
  key parts): f-strings, ``id()``/``hash()``/``repr()``, wall-clock /
  random / pid reads, and set displays (repr order is PYTHONHASHSEED-
  dependent, so a persisted AOT key misses across processes).

Taint limits (shallow, deliberately under-approximating — see
docs/static_analysis.md): constructor calls, comprehensions, and
non-``jnp``/``jax`` user-function returns do NOT propagate taint, so
``trace-host-sync``/``trace-branch`` trade recall for a near-zero
false-positive rate; the region rules (conf/side-effect) need no taint
and carry the recall.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.analysis.callgraph import (
    ARRAY_NAMESPACES,
    CallGraph,
    CallGraphRule,
    FuncInfo,
    RootSite,
    _root_name,
    _target_names,
    _trailing,
    own_body_nodes,
)
from spark_rapids_tpu.analysis.core import Engine
from spark_rapids_tpu.analysis.rules_invariants import MUTATORS

CONF_READERS = frozenset(("get_conf", "ambient_conf", "current_conf"))
COUNTER_CALLS = frozenset(("bump", "bump_unattributed", "count_h2d"))
DIAG_CALLS = frozenset(("record_event", "cache_event", "add_event",
                        "observe", "record", "record_many", "launch",
                        "d2h"))
CLOCK_CALLS = frozenset(("perf_counter", "perf_counter_ns", "monotonic",
                         "monotonic_ns", "process_time", "time_ns"))
LOCK_CALLS = frozenset(("acquire", "release"))
SYNC_CALLS = frozenset(("device_get", "block_until_ready"))
UNSTABLE_KEY_CALLS = frozenset((
    "id", "hash", "repr", "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns", "uuid1", "uuid4", "getpid",
    "get_ident", "random", "randint", "randrange", "token_hex",
    "getrandbits",
))


def _provenance(root: RootSite) -> str:
    """Line-free root description — part of the finding message, so it
    must survive unrelated edits (baseline identity)."""
    where = root.owner_class or root.rel
    return f"traced via {root.kind} in {where}"


class _TraceRegionRule:
    """Base: iterate every traced function once, deterministically."""

    node_types = ()

    def __init__(self, cg: CallGraphRule):
        self._cg = cg

    def end_run(self, engine: Engine) -> None:
        g = self._cg.graph
        g.finalize()
        for key in sorted(g.traced):
            info = g.funcs.get(key)
            if info is None:
                continue
            self.check(engine, info, g.traced[key], g)

    def check(self, engine: Engine, info: FuncInfo, root: RootSite,
              g: CallGraph) -> None:
        raise NotImplementedError


class TraceConfReadRule(_TraceRegionRule):
    """Conf reads bake at trace time — the stale-ambient-conf class."""

    id = "trace-conf-read"
    HINT = ("read the conf at BUILD time (outside the traced function), "
            "pass the value in as a closure constant, and include it in "
            "the program key (conf_fp already fingerprints the ambient "
            "settings)")

    def check(self, engine, info, root, g):
        for node in own_body_nodes(info.node):
            if isinstance(node, ast.Call) \
                    and _trailing(node.func) in CONF_READERS:
                engine.report(
                    info.ctx, self.id, node.lineno, node.col_offset,
                    f"conf read ({_trailing(node.func)}) inside traced "
                    f"code ({_provenance(root)}) bakes the setting into "
                    f"the compiled program", self.HINT, info.qual)


class TraceSideEffectRule(_TraceRegionRule):
    """Side effects inside a trace run once at trace time, then never
    again on cache hits — counters lie, locks guard nothing."""

    id = "trace-side-effect"
    HINT = ("hoist the side effect out of the traced function (wrap the "
            "CALL site, not the trace); counters/telemetry belong in "
            "the dispatch wrapper, locks around the jit call")

    def _lock_ident(self, info: FuncInfo, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Name) \
                and expr.id in info.ctx.module_locks:
            return expr.id
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and info.owner_class
                and expr.attr in info.ctx.class_locks.get(
                    info.owner_class, ())):
            return expr.attr
        return None

    def check(self, engine, info, root, g):
        for node in own_body_nodes(info.node):
            what = None
            if isinstance(node, ast.Call):
                name = _trailing(node.func)
                if name in COUNTER_CALLS:
                    what = f"counter write {name}()"
                elif name in DIAG_CALLS:
                    what = f"diagnostics/telemetry call {name}()"
                elif name in CLOCK_CALLS:
                    what = f"wall-clock read {name}()"
                elif name in LOCK_CALLS:
                    what = f"lock {name}()"
                elif name == "print":
                    what = "print()"
                elif (isinstance(node.func, ast.Attribute)
                        and _trailing(node.func.value) == "COUNTERS"
                        and name in MUTATORS):
                    what = f"COUNTERS.{name}()"
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and _trailing(t.value) == "COUNTERS":
                        what = "COUNTERS[...] write"
            elif isinstance(node, ast.With):
                for item in node.items:
                    lk = self._lock_ident(info, item.context_expr)
                    if lk is not None:
                        what = f"lock acquisition `with {lk}:`"
            if what is not None:
                engine.report(
                    info.ctx, self.id, node.lineno, node.col_offset,
                    f"{what} inside traced code ({_provenance(root)}) "
                    f"runs at trace time only — never on cache hits",
                    self.HINT, info.qual)


class TraceHostSyncRule(_TraceRegionRule):
    """Implicit host syncs on traced values: trace-time concretization
    errors on TPU, hidden device round trips on eager twins."""

    id = "trace-host-sync"
    HINT = ("keep the value on device (jnp ops) or return it and "
            "materialize OUTSIDE the traced function under "
            "`with sync_event():`")

    def check(self, engine, info, root, g):
        local = g.local_taint(info.key)
        for node in own_body_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            what = None
            fn = node.func
            name = _trailing(fn)
            if name in SYNC_CALLS:
                what = f"{name}()"
            elif (isinstance(fn, ast.Name)
                    and fn.id in ("float", "int", "bool") and node.args
                    and g.expr_tainted(node.args[0], local)):
                what = f"{fn.id}() on a traced value"
            elif (name in ("item", "tolist")
                    and isinstance(fn, ast.Attribute)
                    and g.expr_tainted(fn.value, local)):
                what = f".{name}() on a traced value"
            elif (name in ("asarray", "array")
                    and _root_name(fn) in ("np", "numpy") and node.args
                    and g.expr_tainted(node.args[0], local)):
                what = f"np.{name}() on a traced value"
            if what is not None:
                engine.report(
                    info.ctx, self.id, node.lineno, node.col_offset,
                    f"implicit host sync: {what} inside traced code "
                    f"({_provenance(root)})", self.HINT, info.qual)


class TraceBranchRule(_TraceRegionRule):
    """Python control flow on traced values freezes at trace time."""

    id = "trace-branch"
    HINT = ("replace with jnp.where / jax.lax.cond / a masked "
            "computation — Python control flow evaluates ONCE at trace "
            "time, not per element or per call")

    def check(self, engine, info, root, g):
        local = g.local_taint(info.key)
        for node in own_body_nodes(info.node):
            if isinstance(node, (ast.If, ast.While)) \
                    and g.expr_tainted(node.test, local):
                kw = "if" if isinstance(node, ast.If) else "while"
                engine.report(
                    info.ctx, self.id, node.lineno, node.col_offset,
                    f"Python `{kw}` on a traced value inside traced "
                    f"code ({_provenance(root)})", self.HINT, info.qual)


class TraceClosureStateRule(_TraceRegionRule):
    """Mutable enclosing-scope state read/written from traced code is
    baked at trace time and stale on every cache hit."""

    id = "trace-closure-state"
    HINT = ("pass the value as a traced argument (or a static key part) "
            "instead of closing over mutable state; a deliberate "
            "trace-time aux store (the msgs_store pattern) needs a "
            "justifying pragma and must travel WITH the executable")

    def _bindings(self, info: FuncInfo) -> Set[str]:
        node = info.node
        if isinstance(node, ast.Lambda):
            return set(info.params)
        bound = set(info.params)
        for sub in own_body_nodes(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                                ast.For, ast.NamedExpr)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    for n in _target_names(t):
                        bound.add(n)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(sub.name)
            elif isinstance(sub, ast.withitem) and sub.optional_vars:
                for n in _target_names(sub.optional_vars):
                    bound.add(n)
            elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                for a in sub.names:
                    bound.add(a.asname or a.name.split(".")[0])
            elif isinstance(sub, ast.comprehension):
                for n in _target_names(sub.target):
                    bound.add(n)
        return bound

    def _enclosing_bindings(self, info: FuncInfo,
                            g: CallGraph) -> Set[str]:
        """Names bound in lexically enclosing FUNCTIONS (not module
        scope — module-level state is rules_invariants' domain)."""
        out: Set[str] = set()
        scope = info.scope[:-1]
        while scope:
            key = f"{info.rel}::" + ".".join(scope)
            enc = g.funcs.get(key)
            if enc is not None:
                out |= self._bindings(enc)
            scope = scope[:-1]
        return out

    def check(self, engine, info, root, g):
        bound = self._bindings(info)
        closure = self._enclosing_bindings(info, g) - bound
        if not closure:
            return

        def is_closure_name(expr) -> Optional[str]:
            return (expr.id if isinstance(expr, ast.Name)
                    and expr.id in closure else None)

        for node in own_body_nodes(info.node):
            what = None
            if isinstance(node, ast.Call):
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and fn.attr in MUTATORS):
                    n = is_closure_name(fn.value)
                    if n:
                        what = f"mutates closure container '{n}' " \
                               f"(.{fn.attr}())"
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        n = is_closure_name(t.value)
                        if n:
                            what = f"writes closure container '{n}[...]'"
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load):
                n = is_closure_name(node.value)
                if n:
                    what = (f"reads closure container '{n}[...]' — the "
                            f"value bakes at trace time")
            if what is not None:
                engine.report(
                    info.ctx, self.id, node.lineno, node.col_offset,
                    f"{what} inside traced code ({_provenance(root)})",
                    self.HINT, info.qual)


# ---------------------------------------------------------------------------
# trace-split-sync — N round trips where one sync_get suffices
# ---------------------------------------------------------------------------

def _chain_repr(expr: ast.AST) -> str:
    """``self._jit`` / ``cache`` as a stable string, "" if not a plain
    name/attribute chain."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return ""
    parts.append(expr.id)
    return ".".join(reversed(parts))


def _contains_jit_call(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and (
                _trailing(n.func) in ("jit", "tpu_jit",
                                      "cached_jit_program")):
            return True
        if isinstance(n, ast.Attribute) and n.attr == "jitted":
            return True
    return False


class TraceSplitSyncRule:
    """Materializing the components of ONE jitted program result as
    separate ``int()``/``float()``/``bool()``/``.item()`` calls outside
    ``sync_event`` is N device round trips where one ``sync_get`` is a
    single logical sync — the per-column-host-syncs bug class (PR 9's
    serializer fix) recurring at the jit boundary.  Fires on two or
    more split materializations of one result, or any materialization
    of a per-element loop over a result."""

    id = "trace-split-sync"
    node_types = (ast.Assign, ast.Call, ast.For)
    HINT = ("fetch the whole result in ONE logical round trip: "
            "`host = sync_get((count,) + tuple(flags))` — then branch "
            "on the host values")
    MATERIALIZERS = frozenset(("int", "float", "bool"))
    METHOD_MATS = frozenset(("item", "tolist"))

    def begin_file(self, ctx) -> None:
        # flat per-file maps: closure reads (`run` over `_build`'s
        # `jitted`) resolve naturally; rebinding overwrites
        self._containers: Set[str] = set()
        self._callables: Set[str] = set()
        self._groups: Dict[str, Tuple[int, int]] = {}
        self._loop_names: Set[str] = set()
        # group id -> [(node, loop_derived, qual)]
        self._mats: Dict[Tuple[int, int], List] = {}

    def _bind(self, targets: List[ast.AST], value: ast.AST,
              node: ast.AST) -> None:
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        tuple_targets = [t for t in targets
                         if isinstance(t, (ast.Tuple, ast.List))]
        # container store: self._jit[key] = (tpu_jit(fn), msgs)
        for t in targets:
            if isinstance(t, ast.Subscript) and _contains_jit_call(value):
                rep = _chain_repr(t.value)
                if rep:
                    self._containers.add(rep)
        is_jit = _contains_jit_call(value)
        from_container = (isinstance(value, ast.Subscript)
                          and _chain_repr(value.value)
                          in self._containers)
        is_result = (isinstance(value, ast.Call)
                     and isinstance(value.func, ast.Name)
                     and value.func.id in self._callables)
        gid = (node.lineno, node.col_offset)
        for name in names:
            self._clear(name)
            if is_jit or from_container:
                self._callables.add(name)
            elif is_result:
                self._groups[name] = gid
        for tt in tuple_targets:
            elts = [e for e in tt.elts]
            vals = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(elts) else None)
            for i, e in enumerate(elts):
                if not isinstance(e, ast.Name):
                    continue
                self._clear(e.id)
                ev = vals[i] if vals is not None else None
                if ev is not None and _contains_jit_call(ev):
                    self._callables.add(e.id)
                elif vals is None and (is_jit or from_container) \
                        and i == 0:
                    # `jitted, aux = self._jit[key]` — the callable is
                    # the first element by the store-site convention
                    self._callables.add(e.id)
                elif vals is None and is_result:
                    self._groups[e.id] = gid

    def _clear(self, name: str) -> None:
        self._callables.discard(name)
        self._groups.pop(name, None)
        self._loop_names.discard(name)

    def visit(self, node: ast.AST, walk) -> None:
        if isinstance(node, ast.Assign):
            self._bind(list(node.targets), node.value, node)
            return
        if isinstance(node, ast.For):
            src = None
            for n in ast.walk(node.iter):
                if isinstance(n, ast.Name) and n.id in self._groups:
                    src = self._groups[n.id]
                    break
            if src is not None:
                for t in ([node.target]
                          if isinstance(node.target, ast.Name)
                          else getattr(node.target, "elts", [])):
                    if isinstance(t, ast.Name):
                        self._groups[t.id] = src
                        self._loop_names.add(t.id)
            return
        # Call: a materialization of a grouped name?
        fn = node.func
        name = _trailing(fn)
        arg = None
        if isinstance(fn, ast.Name) and name in self.MATERIALIZERS \
                and node.args and isinstance(node.args[0], ast.Name):
            arg = node.args[0].id
        elif name in self.METHOD_MATS and isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name):
            arg = fn.value.id
        if arg is None or arg not in self._groups:
            return
        if walk.in_sync_event():
            return               # one accounted logical region
        self._mats.setdefault(self._groups[arg], []).append(
            (node, arg in self._loop_names, walk.qualname()))

    def end_file(self, walk) -> None:
        for gid in sorted(self._mats):
            mats = self._mats[gid]
            loops = [m for m in mats if m[1]]
            if len(mats) < 2 and not loops:
                continue
            node, in_loop, qual = mats[0]
            what = ("per-element loop materialization"
                    if loops else
                    f"{len(mats)} split host materializations")
            walk.engine.report(
                walk.ctx, self.id, node.lineno, node.col_offset,
                f"{what} of one jitted program result outside "
                f"sync_event — each is a device round trip per batch",
                self.HINT, qual)


# ---------------------------------------------------------------------------
# retrace-key — interprocedural backward slice from key-part sinks
# ---------------------------------------------------------------------------

class RetraceKeyRule:
    """Unstable Python values feeding a program cache key: every
    spurious difference is a retrace (minutes of XLA work), every
    cross-process instability defeats the persistent AOT cache, and an
    ``id()`` can be REUSED after GC — aliasing two different programs
    to one key is silent wrong-answer territory.

    Key material is sliced BACKWARD from the sinks through the call
    graph (bounded depth): a local name follows its assignment, a
    param follows every resolved caller's argument, and a call follows
    the callee's return expressions — so an unstable value laundered
    through a helper (``_agg_tag`` returning ``("id", id(agg))``) is
    still caught at its construction site."""

    id = "retrace-key"
    node_types = ()
    KEY_SINKS = {"fingerprint": None,        # every arg is key material
                 "cached_program": 0, "cached_jit_program": 0}
    HINT = ("feed the key stable, order-independent values: sorted "
            "tuples of primitives; never f-strings of objects, "
            "id()/hash()/repr(), clocks, randomness, or raw set reprs "
            "(set order is PYTHONHASHSEED-dependent across processes)")
    MAX_HOPS = 4

    def __init__(self, cg: CallGraphRule):
        self._cg = cg

    def end_run(self, engine: Engine) -> None:
        g = self._cg.graph
        g.finalize()
        self._reported: Set[Tuple[str, int, int]] = set()
        for key in sorted(g.funcs):
            info = g.funcs[key]
            body = (own_body_nodes(info.node)
                    if not isinstance(info.node, ast.Lambda)
                    else ast.walk(info.node.body))
            for node in body:
                if not isinstance(node, ast.Call):
                    continue
                idx = self.KEY_SINKS.get(_trailing(node.func), -1)
                if idx == -1:
                    continue
                exprs = (list(node.args) if idx is None else
                         [node.args[idx]] if idx < len(node.args)
                         else [])
                for e in exprs:
                    self._slice(engine, g, info, e, self.MAX_HOPS)

    def _slice(self, engine: Engine, g: CallGraph, info: FuncInfo,
               expr: ast.AST, hops: int) -> None:
        """Scan ``expr`` (evaluated inside ``info``) for unstable
        constructs, following names/params/calls up to ``hops``."""
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Call):
                cname = _trailing(n.func)
                if cname == "sorted":
                    continue     # sorted(...) stabilizes its subtree
                if cname in UNSTABLE_KEY_CALLS and not (
                        cname == "repr" and n.args
                        and isinstance(n.args[0], ast.Constant)):
                    self._report(engine, info, n,
                                 f"unstable call {cname}() in program "
                                 f"key parts")
                elif cname in ("set", "frozenset"):
                    self._report(engine, info, n,
                                 f"{cname}() in program key parts: repr "
                                 f"order is hash-dependent")
                elif hops > 0:
                    # follow into the callee's returns: a helper that
                    # RETURNS key material is part of the key
                    desc = g._fn_desc(info.ctx, n.func, info.scope[:-1],
                                      info.owner_class)
                    if desc is not None and desc[0] == "name":
                        # names resolve against the function's OWN
                        # scope (nested defs included), like call recs
                        desc = ("name", info.rel, info.scope, desc[3])
                    callee = (g.resolve(desc) if desc is not None
                              else None)
                    if callee is not None:
                        self._slice_returns(engine, g, callee, hops - 1)
            elif isinstance(n, ast.JoinedStr):
                if any(isinstance(v, ast.FormattedValue)
                       for v in n.values):
                    self._report(engine, info, n,
                                 "f-string in program key parts bakes "
                                 "object reprs into the fingerprint")
                continue         # don't descend into formatted values
            elif isinstance(n, (ast.Set, ast.SetComp)):
                self._report(engine, info, n,
                             "set display in program key parts: repr "
                             "order is hash-dependent")
            elif isinstance(n, ast.Name) and hops > 0:
                self._slice_name(engine, g, info, n.id, hops - 1)
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _slice_name(self, engine: Engine, g: CallGraph, info: FuncInfo,
                    name: str, hops: int) -> None:
        """A name in key material: follow its local assignment, or —
        when it is a parameter — every resolved caller's argument."""
        node = info.node
        if not isinstance(node, ast.Lambda):
            for st in own_body_nodes(node):
                if isinstance(st, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in st.targets):
                    self._slice(engine, g, info, st.value, hops)
        if name not in info.params:
            return
        pos = info.params.index(name)
        for caller in sorted(g.resolved_calls):
            for callee, rec in g.resolved_calls[caller]:
                if callee != info.key:
                    continue
                cinfo = g.funcs.get(caller)
                if cinfo is None:
                    continue
                apos = pos - (info.receiver_params()
                              if rec.desc[0] in ("self", "objattr")
                              else 0)
                if 0 <= apos < len(rec.args):
                    self._slice(engine, g, cinfo, rec.args[apos], hops)
                else:
                    for kw in rec.keywords:
                        if kw.arg == name:
                            self._slice(engine, g, cinfo, kw.value,
                                        hops)

    def _slice_returns(self, engine: Engine, g: CallGraph, callee: str,
                       hops: int) -> None:
        info = g.funcs.get(callee)
        if info is None:
            return
        if isinstance(info.node, ast.Lambda):
            self._slice(engine, g, info, info.node.body, hops)
            return
        for st in own_body_nodes(info.node):
            if isinstance(st, ast.Return) and st.value is not None:
                self._slice(engine, g, info, st.value, hops)

    def _report(self, engine: Engine, info: FuncInfo, node: ast.AST,
                msg: str) -> None:
        # the sink implementations are the canonicalization boundary:
        # fingerprint()'s own repr-of-vetted-parts is the digest
        # MECHANISM, not key material
        if info.qual.split(".")[-1] in self.KEY_SINKS:
            return
        # one finding per construction site even when the value feeds
        # several sinks (helper return + direct use)
        site = (info.rel, node.lineno, node.col_offset)
        if site in self._reported:
            return
        self._reported.add(site)
        engine.report(info.ctx, self.id, node.lineno, node.col_offset,
                      msg, self.HINT, info.qual)


def trace_rules() -> List[object]:
    """The tracelint tier: shared call-graph builder + its consumers +
    the per-file split-sync rule.  The builder must stay FIRST."""
    cg = CallGraphRule()
    return [cg,
            TraceConfReadRule(cg),
            TraceSideEffectRule(cg),
            TraceHostSyncRule(cg),
            TraceBranchRule(cg),
            TraceClosureStateRule(cg),
            TraceSplitSyncRule(),
            RetraceKeyRule(cg)]
