from spark_rapids_tpu.cpu.oracle import CpuCol, execute_cpu_plan  # noqa: F401
