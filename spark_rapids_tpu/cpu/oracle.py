"""The CPU oracle — an independent CPU implementation of plans + expressions.

Role (SURVEY.md §4 "key insight"): the reference's correctness net runs every
query twice — with the plugin on (GPU) and off (CPU Spark) — and asserts
equal results.  Standalone, we have no CPU Spark, so this module *is* the
"CPU Spark": a second, deliberately different implementation —

  * decimals: arbitrary-precision Python ints (vs device int64 unscaled)
  * strings: Python str objects (vs device padded byte matrices)
  * dates/timestamps: Python datetime arithmetic in the handlers
    (vs device civil-calendar bit math)
  * group-by/join: dict-based hashing (vs device lax.sort + segments)

so that agreement between the two paths is meaningful evidence.  It is also
the *fallback executor*: plan nodes tagged willNotWorkOnTpu run here, exactly
as untagged nodes stay on CPU Spark in the reference.
"""
from __future__ import annotations

import dataclasses
import datetime as pydt
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.expr import base as E
from spark_rapids_tpu.expr import arithmetic as A
from spark_rapids_tpu.expr import cast as C
from spark_rapids_tpu.expr import conditional as CO
from spark_rapids_tpu.expr import datetime as DT
from spark_rapids_tpu.expr import mathfuncs as M
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr import strings as S
from spark_rapids_tpu.plan import nodes as PN


@dataclasses.dataclass
class CpuCol:
    """values: object ndarray for string/decimal; typed ndarray otherwise.
    validity: bool ndarray."""

    dtype: T.DataType
    values: np.ndarray
    validity: np.ndarray

    @property
    def n(self):
        return len(self.validity)

    @staticmethod
    def from_objs(objs, dt: T.DataType) -> "CpuCol":
        """Build from python objects in STORAGE representation (None = null)."""
        n = len(objs)
        validity = np.array([o is not None for o in objs], np.bool_)
        if isinstance(dt, (T.StringType, T.DecimalType, T.ArrayType,
                           T.StructType, T.MapType)):
            vals = np.empty(n, object)
            for i, o in enumerate(objs):
                vals[i] = o
            return CpuCol(dt, vals, validity)
        data = np.zeros(n, T.storage_dtype(dt))
        for i, o in enumerate(objs):
            if o is not None:
                data[i] = o
        return CpuCol(dt, data, validity)

    @staticmethod
    def from_host(h: HostColumn) -> "CpuCol":
        if isinstance(h.dtype, T.MapType):
            kcol = CpuCol.from_host(h.children[0])
            vcol = CpuCol.from_host(h.children[1])
            vals = np.empty(h.num_rows, object)
            for i in range(h.num_rows):
                vals[i] = (dict(zip(kcol.row(i), vcol.row(i)))
                           if h.validity[i] else None)
            return CpuCol(h.dtype, vals, h.validity.copy())
        if h.is_struct:
            kids = [CpuCol.from_host(c) for c in h.children]
            vals = np.empty(h.num_rows, object)
            for i in range(h.num_rows):
                vals[i] = (tuple(k.row(i) for k in kids)
                           if h.validity[i] else None)
            return CpuCol(h.dtype, vals, h.validity.copy())
        if h.is_string_array:
            lists = h.to_pylist()
            vals = np.empty(h.num_rows, object)
            for i, v in enumerate(lists):
                vals[i] = v
            return CpuCol(h.dtype, vals, h.validity.copy())
        if h.is_array:
            elem_t = h.dtype.elementType
            vals = []
            for i in range(h.num_rows):
                if not h.validity[i]:
                    vals.append(None)
                    continue
                ln = int(h.lengths[i])
                row = CpuCol.from_host(HostColumn(
                    elem_t, h.elem_valid[i, :ln], data=h.data[i, :ln]))
                vals.append([row.row(j) for j in range(ln)])
            out = np.empty(h.num_rows, object)
            for i, v in enumerate(vals):
                out[i] = v
            return CpuCol(h.dtype, out, h.validity.copy())
        if h.is_string:
            vals = np.array(
                [bytes(h.chars[i, : h.lengths[i]]).decode("utf-8", "replace")
                 if h.validity[i] else None
                 for i in range(h.num_rows)], dtype=object)
            return CpuCol(h.dtype, vals, h.validity.copy())
        if isinstance(h.dtype, T.DecimalType):
            if h.dtype.is_128:
                from spark_rapids_tpu.expr.decimal128 import to_py

                vals = np.array(
                    [to_py(int(h.data[i, 0]), int(h.data[i, 1]))
                     for i in range(h.num_rows)], dtype=object)
            else:
                # tolist() gives PYTHON ints (np.int64 elements would wrap
                # on >64-bit products); np.array over the list is C-speed
                vals = np.empty(h.num_rows, object)
                vals[:] = h.data.tolist()
            return CpuCol(h.dtype, vals, h.validity.copy())
        return CpuCol(h.dtype, h.data.copy(), h.validity.copy())

    def to_host(self) -> HostColumn:
        n = self.n
        if isinstance(self.dtype, T.MapType):
            keys = [list(self.values[i].keys())
                    if self.validity[i] and self.values[i] is not None
                    else None for i in range(n)]
            vals = [list(self.values[i].values())
                    if self.validity[i] and self.values[i] is not None
                    else None for i in range(n)]
            kcol = CpuCol.from_objs(
                keys, T.ArrayType(self.dtype.keyType, containsNull=False))
            vcol = CpuCol.from_objs(vals, T.ArrayType(self.dtype.valueType))
            return HostColumn(self.dtype, self.validity.copy(),
                              children=[kcol.to_host(), vcol.to_host()])
        if isinstance(self.dtype, T.StructType):
            kids = []
            for k, f in enumerate(self.dtype.fields):
                fv = [self.values[i][k]
                      if self.validity[i] and self.values[i] is not None
                      else None for i in range(n)]
                kids.append(CpuCol.from_objs(fv, f.dataType).to_host())
            return HostColumn(self.dtype, self.validity.copy(), children=kids)
        if isinstance(self.dtype, T.ArrayType) and isinstance(
                self.dtype.elementType, T.StringType):
            rows = [list(self.values[i]) if self.validity[i]
                    and self.values[i] is not None else None
                    for i in range(n)]
            h = HostColumn.from_pylist(rows, self.dtype)
            h.validity = self.validity.copy()
            return h
        if isinstance(self.dtype, T.ArrayType):
            elem_t = self.dtype.elementType
            width = max((len(v) for v in self.values if v is not None),
                        default=1) or 1
            data = np.zeros((n, width), T.storage_dtype(elem_t))
            ev = np.zeros((n, width), np.bool_)
            lengths = np.zeros(n, np.int32)
            for i in range(n):
                v = self.values[i]
                if not self.validity[i] or v is None:
                    continue
                lengths[i] = len(v)
                eh = HostColumn.from_pylist(list(v), elem_t)
                data[i, :len(v)] = eh.data
                ev[i, :len(v)] = eh.validity
            return HostColumn(self.dtype, self.validity.copy(), data=data,
                              lengths=lengths, elem_valid=ev)
        if isinstance(self.dtype, T.StringType):
            strs = [self.values[i] if self.validity[i] else None
                    for i in range(n)]
            h = HostColumn.from_pylist(strs, T.STRING)
            h.validity = self.validity.copy()
            return h
        if isinstance(self.dtype, T.DecimalType):
            if self.dtype.is_128:
                from spark_rapids_tpu.expr.decimal128 import limbs_of

                data = np.zeros((n, 2), np.int64)
                for i in range(n):
                    if self.validity[i]:
                        data[i, 0], data[i, 1] = limbs_of(int(self.values[i]))
                return HostColumn(self.dtype, self.validity.copy(), data=data)
            data = np.zeros(n, np.int64)
            for i in range(n):
                if self.validity[i]:
                    v = int(self.values[i])
                    # clamp into int64 (oracle may exceed; device would null)
                    data[i] = max(min(v, 2 ** 63 - 1), -(2 ** 63))
            return HostColumn(self.dtype, self.validity.copy(), data=data)
        return HostColumn(self.dtype, self.validity.copy(),
                          data=np.asarray(self.values))

    def row(self, i):
        return self.values[i] if self.validity[i] else None

    def to_pylist(self):
        """Lossless python values (decimals keep arbitrary precision —
        HostColumn's int64 storage would clamp precision>18)."""
        import datetime as _dt
        from decimal import Decimal as _Dec

        out = []
        for i in range(self.n):
            if not self.validity[i]:
                out.append(None)
            elif isinstance(self.dtype, T.ArrayType):
                v = self.values[i]
                ev = np.array([e is not None for e in v], np.bool_)
                vals = np.empty(len(v), object)
                for j, e in enumerate(v):
                    vals[j] = e
                out.append(CpuCol(self.dtype.elementType, vals,
                                  ev).to_pylist())
            elif isinstance(self.dtype, T.StructType):
                v = self.values[i]
                out.append(tuple(
                    CpuCol.from_objs([v[k]], f.dataType).to_pylist()[0]
                    for k, f in enumerate(self.dtype.fields)))
            elif isinstance(self.dtype, T.MapType):
                d = self.values[i]
                ks = CpuCol.from_objs(list(d.keys()),
                                      self.dtype.keyType).to_pylist()
                vs = CpuCol.from_objs(list(d.values()),
                                      self.dtype.valueType).to_pylist()
                out.append(dict(zip(ks, vs)))
            elif isinstance(self.dtype, T.DecimalType):
                out.append(_Dec(int(self.values[i])).scaleb(-self.dtype.scale))
            elif isinstance(self.dtype, T.DateType):
                out.append(_dt.date(1970, 1, 1)
                           + _dt.timedelta(days=int(self.values[i])))
            elif isinstance(self.dtype, T.TimestampType):
                out.append(_dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
                           + _dt.timedelta(microseconds=int(self.values[i])))
            elif isinstance(self.dtype, T.BooleanType):
                out.append(bool(self.values[i]))
            elif isinstance(self.dtype, (T.FloatType, T.DoubleType)):
                out.append(float(self.values[i]))
            elif isinstance(self.dtype, T.StringType):
                out.append(self.values[i])
            else:
                out.append(int(self.values[i]))
        return out


CpuBatch = List[CpuCol]  # plus schema carried by plan


# ===========================================================================
# Expression interpreter
# ===========================================================================

def eval_expr(e: E.Expression, cols: CpuBatch, n: int, ansi: bool = False) -> CpuCol:
    h = _HANDLERS.get(type(e).__name__)
    if h is None:
        raise NotImplementedError(f"oracle: {type(e).__name__}")
    return h(e, cols, n, ansi)


def _kids(e, cols, n, ansi):
    return [eval_expr(c, cols, n, ansi) for c in e.children]


def _null_prop_validity(kids: List[CpuCol]) -> np.ndarray:
    v = kids[0].validity.copy()
    for k in kids[1:]:
        v &= k.validity
    return v


def _h_bound(e: E.BoundReference, cols, n, ansi):
    return cols[e.ordinal]


def _h_literal(e: E.Literal, cols, n, ansi):
    dt = e._dataType
    if e.value is None:
        if isinstance(dt, (T.StringType, T.DecimalType)):
            return CpuCol(dt, np.array([None] * n, dtype=object),
                          np.zeros(n, np.bool_))
        sdt = T.storage_dtype(dt) if not isinstance(dt, T.NullType) else np.int32
        return CpuCol(dt, np.zeros(n, sdt), np.zeros(n, np.bool_))
    if isinstance(dt, T.StringType):
        return CpuCol(dt, np.array([e.value] * n, dtype=object),
                      np.ones(n, np.bool_))
    if isinstance(dt, T.DecimalType):
        return CpuCol(dt, np.array([e.storage_value()] * n, dtype=object),
                      np.ones(n, np.bool_))
    return CpuCol(dt, np.full(n, e.storage_value(), T.storage_dtype(dt)),
                  np.ones(n, np.bool_))


def _h_alias(e, cols, n, ansi):
    return eval_expr(e.children[0], cols, n, ansi)


# -- arithmetic -------------------------------------------------------------

_JMIN = {T.ByteType: -(2**7), T.ShortType: -(2**15), T.IntegerType: -(2**31),
         T.LongType: -(2**63)}
_JRANGE = {T.ByteType: 2**8, T.ShortType: 2**16, T.IntegerType: 2**32,
           T.LongType: 2**64}


def _java_wrap(vals, dt) -> np.ndarray:
    """Wrap arbitrary python ints into the Java type (independent of numpy
    overflow behavior)."""
    lo, rng = _JMIN[type(dt)], _JRANGE[type(dt)]
    out = np.zeros(len(vals), T.storage_dtype(dt))
    for i, v in enumerate(vals):
        out[i] = ((int(v) - lo) % rng) + lo
    return out


def _dec_check(vals, validity, dt: T.DecimalType, ansi, op):
    bound = 10 ** dt.precision
    safe = np.where(validity, vals, 0)
    in_bounds = np.asarray(safe < bound, np.bool_) & np.asarray(
        safe > -bound, np.bool_)
    bad = validity & ~in_bounds
    if bad.any():
        if ansi:
            raise E.SparkArithmeticException(f"decimal {op} overflow (ANSI)")
        return validity & in_bounds
    return validity.copy() if hasattr(validity, "copy") else validity


def _h_binarith(e: A.BinaryArithmetic, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    validity = l.validity & r.validity
    dt = e.dataType
    name = type(e).__name__
    if isinstance(dt, T.DecimalType):
        lt, rt = e.left.dataType, e.right.dataType
        if name in ("Add", "Subtract", "Multiply"):
            # vectorized object-int arithmetic (the hot TPC-H shapes)
            a = np.where(validity, l.values, 0)
            b = np.where(validity, r.values, 0)
            if name in ("Add", "Subtract"):
                a = a * (10 ** (dt.scale - lt.scale))
                b = b * (10 ** (dt.scale - rt.scale))
                out = a + b if name == "Add" else a - b
            else:
                out = a * b
            validity = _dec_check(out, validity, dt, ansi, name.lower())
            return CpuCol(dt, out, validity)
        out = np.zeros(n, dtype=object)
        for i in range(n):
            if not validity[i]:
                out[i] = 0
                continue
            a, b = int(l.values[i]), int(r.values[i])
            if name == "Divide":
                if b == 0:
                    if ansi:
                        raise E.SparkArithmeticException("division by zero (ANSI)")
                    validity[i] = False
                    out[i] = 0
                else:
                    from decimal import Decimal, ROUND_HALF_UP, localcontext

                    with localcontext() as lctx:
                        lctx.prec = 78
                        q = (Decimal(a).scaleb(-lt.scale)
                             / Decimal(b).scaleb(-rt.scale))
                        out[i] = int(q.scaleb(dt.scale).quantize(
                            Decimal(1), rounding=ROUND_HALF_UP))
            elif name in ("Remainder", "Pmod"):
                if b == 0:
                    if ansi:
                        raise E.SparkArithmeticException("division by zero (ANSI)")
                    validity[i] = False
                    out[i] = 0
                else:
                    sa = a * 10 ** (dt.scale - lt.scale)
                    sb = b * 10 ** (dt.scale - rt.scale)
                    m = abs(sa) % abs(sb)
                    out[i] = m * (1 if sa >= 0 else -1) if name == "Remainder" \
                        else (sa % abs(sb))
            else:
                raise NotImplementedError(name)
        validity = _dec_check(out, validity, dt, ansi, name.lower())
        return CpuCol(dt, out, validity)
    if dt.is_integral:
        out_py = []
        la, ra = l.values, r.values
        for i in range(n):
            if not validity[i]:
                out_py.append(0)
                continue
            a, b = int(la[i]), int(ra[i])
            if name == "Add":
                v = a + b
            elif name == "Subtract":
                v = a - b
            elif name == "Multiply":
                v = a * b
            elif name == "Remainder":
                if b == 0:
                    if ansi:
                        raise E.SparkArithmeticException("division by zero (ANSI)")
                    validity[i] = False
                    v = 0
                else:
                    v = int(math.fmod(a, b))
            elif name == "Pmod":
                if b == 0:
                    if ansi:
                        raise E.SparkArithmeticException("division by zero (ANSI)")
                    validity[i] = False
                    v = 0
                else:
                    # Spark: r = a % n (truncated); r < 0 -> (r + n) % n
                    v = int(math.fmod(a, b))
                    if v < 0:
                        v = int(math.fmod(v + b, b))
            elif name == "IntegralDivide":
                if b == 0:
                    if ansi:
                        raise E.SparkArithmeticException("division by zero (ANSI)")
                    validity[i] = False
                    v = 0
                else:
                    v = int(a / b) if abs(a) < 2**52 and abs(b) < 2**52 else \
                        abs(a) // abs(b) * (1 if (a < 0) == (b < 0) else -1)
            else:
                raise NotImplementedError(name)
            lo, rng = _JMIN[type(dt)], _JRANGE[type(dt)]
            wrapped = ((v - lo) % rng) + lo
            if ansi and wrapped != v:
                raise E.SparkArithmeticException(f"{name} overflow (ANSI)")
            out_py.append(wrapped)
        return CpuCol(dt, np.array(out_py, T.storage_dtype(dt)), validity)
    # floating point
    la = l.values.astype(np.float64)
    ra = r.values.astype(np.float64)
    with np.errstate(all="ignore"):
        if name == "Add":
            out = la + ra
        elif name == "Subtract":
            out = la - ra
        elif name == "Multiply":
            out = la * ra
        elif name == "Divide":
            zero = ra == 0.0
            if ansi and bool((zero & validity).any()):
                raise E.SparkArithmeticException("division by zero (ANSI)")
            validity = validity & ~zero
            out = np.where(zero, np.nan, la / np.where(zero, 1.0, ra))
        elif name in ("Remainder", "Pmod"):
            zero = ra == 0.0
            validity = validity & ~zero
            out = np.fmod(la, np.where(zero, 1.0, ra))
            if name == "Pmod":
                safe = np.where(zero, 1.0, ra)
                out = np.where(out < 0, np.fmod(out + safe, safe), out)
        else:
            raise NotImplementedError(name)
    return CpuCol(e.dataType, out.astype(T.storage_dtype(e.dataType)), validity)


def _h_unaryminus(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    dt = e.dataType
    if isinstance(dt, T.DecimalType):
        return CpuCol(dt, np.array([-int(v) for v in c.values], object),
                      c.validity.copy())
    if dt.is_integral:
        return CpuCol(dt, _java_wrap([-int(v) for v in c.values], dt),
                      c.validity.copy())
    return CpuCol(dt, -c.values, c.validity.copy())


def _h_abs(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    dt = e.dataType
    if isinstance(dt, T.DecimalType):
        return CpuCol(dt, np.array([abs(int(v)) for v in c.values], object),
                      c.validity.copy())
    if dt.is_integral:
        return CpuCol(dt, _java_wrap([abs(int(v)) for v in c.values], dt),
                      c.validity.copy())
    return CpuCol(dt, np.abs(c.values), c.validity.copy())


# -- predicates -------------------------------------------------------------

def _cmp_rows(l: CpuCol, r: CpuCol, dt: T.DataType):
    """elementwise python compare -> int array (-1,0,1)."""
    if isinstance(dt, T.DecimalType):
        # vectorized object-int compare (nulls neutralized; validity masks
        # the result downstream)
        a = np.where(l.validity, l.values, 0)
        b = np.where(r.validity, r.values, 0)
        return np.asarray(a > b, np.int32) - np.asarray(a < b, np.int32)
    out = np.zeros(l.n, np.int32)
    for i in range(l.n):
        a, b = l.values[i], r.values[i]
        if isinstance(dt, T.StringType):
            ab, bb = a.encode() if a is not None else b"", \
                b.encode() if b is not None else b""
            out[i] = (ab > bb) - (ab < bb)
        else:
            out[i] = (a > b) - (a < b)
    return out


def _h_comparison(e: P.BinaryComparison, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    validity = l.validity & r.validity
    name = type(e).__name__
    ct = e.left.dataType
    if isinstance(ct, (T.StringType, T.DecimalType)):
        cmpv = _cmp_rows(l, r, ct)
        data = {"EqualTo": cmpv == 0, "LessThan": cmpv < 0,
                "LessThanOrEqual": cmpv <= 0, "GreaterThan": cmpv > 0,
                "GreaterThanOrEqual": cmpv >= 0}[name]
    else:
        with np.errstate(invalid="ignore"):
            data = {"EqualTo": l.values == r.values,
                    "LessThan": l.values < r.values,
                    "LessThanOrEqual": l.values <= r.values,
                    "GreaterThan": l.values > r.values,
                    "GreaterThanOrEqual": l.values >= r.values}[name]
    return CpuCol(T.BOOLEAN, np.asarray(data, np.bool_), validity)


def _h_nullsafe_eq(e, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    ct = e.left.dataType
    if isinstance(ct, (T.StringType, T.DecimalType)):
        eq = _cmp_rows(l, r, ct) == 0
    else:
        eq = l.values == r.values
    data = (l.validity & r.validity & eq) | (~l.validity & ~r.validity)
    return CpuCol(T.BOOLEAN, data, np.ones(n, np.bool_))


def _h_and(e, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    lt = l.validity & l.values.astype(bool)
    lf = l.validity & ~l.values.astype(bool)
    rt = r.validity & r.values.astype(bool)
    rf = r.validity & ~r.values.astype(bool)
    data = lt & rt
    validity = (l.validity & r.validity) | lf | rf
    return CpuCol(T.BOOLEAN, data, validity)


def _h_or(e, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    lt = l.validity & l.values.astype(bool)
    rt = r.validity & r.values.astype(bool)
    data = lt | rt
    validity = (l.validity & r.validity) | lt | rt
    return CpuCol(T.BOOLEAN, data, validity)


def _h_not(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    return CpuCol(T.BOOLEAN, ~c.values.astype(bool), c.validity.copy())


def _h_isnull(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    return CpuCol(T.BOOLEAN, ~c.validity, np.ones(n, np.bool_))


def _h_isnotnull(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    return CpuCol(T.BOOLEAN, c.validity.copy(), np.ones(n, np.bool_))


def _h_isnan(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    data = np.zeros(n, np.bool_)
    m = c.validity
    data[m] = np.isnan(c.values[m].astype(np.float64))
    return CpuCol(T.BOOLEAN, data, np.ones(n, np.bool_))


def _h_in(e: P.In, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    v, cands = kids[0], kids[1:]
    data = np.zeros(n, np.bool_)
    any_null_cand = any(not bool(c.validity.all()) for c in cands)
    for c in cands:
        if not c.validity.any():
            continue
        if isinstance(e.children[0].dataType, (T.StringType, T.DecimalType)):
            eq = np.array([v.values[i] == c.values[i] for i in range(n)])
        else:
            eq = v.values == c.values
        data |= eq & c.validity
    validity = v.validity.copy()
    if any_null_cand:
        validity &= data
    return CpuCol(T.BOOLEAN, data, validity)


# -- conditionals -----------------------------------------------------------

def _select(pred_data, pred_valid, a: CpuCol, b: CpuCol, dt) -> CpuCol:
    take_a = pred_data.astype(bool) & pred_valid
    if a.values.dtype == object or b.values.dtype == object:
        vals = np.array([a.values[i] if take_a[i] else b.values[i]
                         for i in range(len(take_a))], dtype=object)
    else:
        vals = np.where(take_a, a.values, b.values)
    validity = np.where(take_a, a.validity, b.validity)
    return CpuCol(dt, vals, validity.astype(np.bool_))


def _h_if(e, cols, n, ansi):
    p, a, b = _kids(e, cols, n, ansi)
    return _select(p.values, p.validity, a, b, e.dataType)


def _h_casewhen(e: CO.CaseWhen, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    nb = (len(e.children) - (1 if e.has_else else 0)) // 2
    if e.has_else:
        acc = kids[-1]
    else:
        acc = _h_literal(E.Literal(None, e.dataType), cols, n, ansi)
    for i in reversed(range(nb)):
        cond, val = kids[2 * i], kids[2 * i + 1]
        acc = _select(cond.values, cond.validity, val, acc, e.dataType)
    return acc


def _h_coalesce(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    acc = kids[-1]
    for c in reversed(kids[:-1]):
        acc = _select(c.validity, np.ones(n, np.bool_), c, acc, e.dataType)
    return acc


def _h_nanvl(e, cols, n, ansi):
    a, b = _kids(e, cols, n, ansi)
    is_nan = np.zeros(n, np.bool_)
    m = a.validity
    is_nan[m] = np.isnan(a.values[m].astype(np.float64))
    return _select(~is_nan, np.ones(n, np.bool_), a, b, e.dataType)


def _h_greatest(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    mx = type(e).__name__ == "Greatest"
    out_vals = []
    out_valid = np.zeros(n, np.bool_)

    def rank(v):
        # NaN strictly greatest; strings by bytes
        if isinstance(v, str):
            return (0, v.encode())
        if isinstance(v, float) and math.isnan(v):
            return (1, 0.0)
        return (0, float(v))

    for i in range(n):
        vals = [k.values[i] for k in kids if k.validity[i]]
        if not vals:
            out_vals.append(0 if kids[0].values.dtype != object else None)
            continue
        out_valid[i] = True
        out_vals.append((max if mx else min)(vals, key=rank))
    dtype = object if kids[0].values.dtype == object else kids[0].values.dtype
    return CpuCol(e.dataType, np.array(out_vals, dtype=dtype), out_valid)


# -- cast -------------------------------------------------------------------

def _h_cast(e: C.Cast, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    src, dst = e.child.dataType, e.to
    ansi = ansi or e.ansi_override
    if src == dst:
        return c
    if isinstance(src, T.DecimalType) and isinstance(dst, T.DecimalType):
        # vectorized integer rescale (comparison coercion makes this hot)
        vals = np.where(c.validity, c.values, 0)
        diff = dst.scale - src.scale
        widens = (dst.precision - dst.scale >= src.precision - src.scale
                  and diff >= 0)
        if diff == 0:
            if widens:  # pure widening: values cannot overflow
                return CpuCol(dst, vals, c.validity.copy())
            out = vals
        elif diff > 0:
            out = vals * (10 ** diff)
        else:
            den = 10 ** (-diff)
            q = vals // den               # floor
            rem = vals - q * den
            neg = np.asarray(vals < 0, np.bool_)
            q = q + np.asarray(neg & np.asarray(rem != 0, np.bool_),
                               np.int64)  # -> trunc toward zero
            rem2 = np.abs(vals - q * den)
            q = q + np.where(np.asarray(2 * rem2 >= den, np.bool_)
                             & np.asarray(rem2 != 0, np.bool_),
                             np.where(neg, -1, 1), 0)  # HALF_UP
            out = q
        validity = _dec_check(out, c.validity, dst, ansi, "cast")
        return CpuCol(dst, out, validity)
    out_vals: list = []
    out_valid = c.validity.copy()
    for i in range(n):
        if not c.validity[i]:
            out_vals.append(None)
            continue
        try:
            out_vals.append(_cast_one(c.values[i], src, dst, ansi))
        except _CastNull:
            if ansi:
                raise E.SparkArithmeticException(
                    f"invalid cast {src}->{dst} (ANSI)")
            out_vals.append(None)
            out_valid[i] = False
    if isinstance(dst, (T.StringType, T.DecimalType)):
        vals = np.array([v if v is not None else None for v in out_vals],
                        dtype=object)
    else:
        sdt = T.storage_dtype(dst)
        vals = np.array([v if v is not None else 0 for v in out_vals],
                        dtype=sdt)
    return CpuCol(dst, vals, out_valid)


class _CastNull(Exception):
    pass


_TS_TIME_RE = None


def _civil_days_py(y, m, d):
    """Hinnant days-from-civil (python ints; years beyond 9999 fine)."""
    yy = y - (1 if m <= 2 else 0)
    era = (yy if yy >= 0 else yy - 399) // 400
    yoe = yy - era * 400
    mp = m + (-3 if m > 2 else 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _civil_valid_py(y, m, d):
    if not (1 <= m <= 12 and d >= 1 and 1 <= y <= 9999):
        return False
    if m == 12:
        ml = _civil_days_py(y + 1, 1, 1) - _civil_days_py(y, 12, 1)
    else:
        ml = _civil_days_py(y, m + 1, 1) - _civil_days_py(y, m, 1)
    return d <= ml


def _parse_civil_py(s):
    """Oracle twin of cast._parse_civil_string: returns (days, tail) or
    None.  Grammar: [y]yyyy[-[m]m[-[d]d<tail>]]."""
    import re as _re

    m = _re.match(r"^(\d{4,6})(?:-(\d{1,2})(?:-(\d{1,2})(.*))?)?$", s,
                  _re.S)
    if not m:
        return None
    y = int(m.group(1))
    mo = int(m.group(2)) if m.group(2) else 1
    d = int(m.group(3)) if m.group(3) else 1
    tail = m.group(4) if m.group(4) is not None else ""
    if not _civil_valid_py(y, mo, d):
        return None
    return _civil_days_py(y, mo, d), tail, m.group(3) is not None


def _str_to_date_py(sv):
    r = _parse_civil_py(str(sv).strip())
    if r is None:
        return None
    days, tail, had_day = r
    if tail and not (had_day and tail[0] in " T"):
        return None
    return days


_TS_TAIL_RE = None


def _str_to_ts_py(sv):
    """Oracle twin of cast._string_to_timestamp (same documented subset)."""
    import re as _re

    global _TS_TAIL_RE
    if _TS_TAIL_RE is None:
        _TS_TAIL_RE = _re.compile(
            r"^[ T](\d{1,2})(?::(\d{1,2})(?::(\d{1,2})"
            r"(?:\.(\d{1,9}))?)?)?"
            r"(Z|z|[+-](?:\d{4}|\d{1,2}(?::\d{2})?))?$")
    r = _parse_civil_py(str(sv).strip())
    if r is None:
        return None
    days, tail, _ = r
    micros = days * 86_400_000_000
    if not tail:
        return micros
    m = _TS_TAIL_RE.match(tail)
    if not m:
        return None
    h = int(m.group(1))
    mi = int(m.group(2)) if m.group(2) else 0
    s = int(m.group(3)) if m.group(3) else 0
    if h > 23 or mi > 59 or s > 59:
        return None
    frac = m.group(4) or ""
    frac_us = (int(frac) * 10 ** (6 - len(frac)) if len(frac) <= 6
               else int(frac) // 10 ** (len(frac) - 6)) if frac else 0
    off = 0
    tz = m.group(5)
    if tz and tz not in ("Z", "z"):
        sign = 1 if tz[0] == "+" else -1
        body = tz[1:]
        if ":" in body:
            hh, mm = body.split(":")
        elif len(body) == 4:
            hh, mm = body[:2], body[2:]
        else:
            hh, mm = body, "0"
        hh, mm = int(hh), int(mm)
        if hh > 18 or mm > 59 or hh * 60 + mm > 18 * 60:
            return None
        off = sign * (hh * 3600 + mm * 60)
    return (micros + h * 3_600_000_000 + mi * 60_000_000 + s * 1_000_000
            + frac_us - off * 1_000_000)


def _cast_one(v, src: T.DataType, dst: T.DataType, ansi: bool):
    import decimal as pydec

    def is_int(t):
        return t.is_integral

    if isinstance(dst, T.BooleanType):
        if isinstance(src, T.StringType):
            s = str(v).strip().lower()
            if s in ("true", "t", "yes", "y", "1"):
                return True
            if s in ("false", "f", "no", "n", "0"):
                return False
            raise _CastNull
        return v != 0
    if isinstance(dst, T.StringType):
        if isinstance(src, T.BooleanType):
            return "true" if v else "false"
        if isinstance(src, T.DecimalType):
            d = pydec.Decimal(int(v)).scaleb(-src.scale)
            return f"{d:.{src.scale}f}" if src.scale > 0 else str(int(v))
        if isinstance(src, T.DateType):
            return (pydt.date(1970, 1, 1) + pydt.timedelta(days=int(v))).isoformat()
        if isinstance(src, T.TimestampType):
            ts = pydt.datetime(1970, 1, 1) + pydt.timedelta(microseconds=int(v))
            base = ts.strftime("%Y-%m-%d %H:%M:%S")
            if ts.microsecond:
                frac = f"{ts.microsecond:06d}".rstrip("0")
                return f"{base}.{frac}"
            return base
        if isinstance(src, (T.FloatType, T.DoubleType)):
            from spark_rapids_tpu.expr.cast import java_fp_to_string

            return java_fp_to_string(float(v), isinstance(src, T.FloatType))
        return str(int(v))
    if is_int(dst):
        if isinstance(src, T.StringType):
            s = str(v).strip()
            if not s or not s.lstrip("+-").isdigit() or len(s.lstrip("+-")) > 19:
                raise _CastNull
            val = int(s)
        elif isinstance(src, (T.FloatType, T.DoubleType)):
            f = float(v)
            if math.isnan(f):
                val = 0
            elif f >= 2 ** 63:      # Java (long) saturates
                val = 2 ** 63 - 1
            elif f <= -(2 ** 63):
                val = -(2 ** 63)
            else:
                val = int(f)
        elif isinstance(src, T.DecimalType):
            val = int(pydec.Decimal(int(v)).scaleb(-src.scale)
                      .to_integral_value(rounding=pydec.ROUND_DOWN))
        elif isinstance(src, T.TimestampType):
            val = int(v) // 1_000_000 if int(v) >= 0 or int(v) % 1_000_000 == 0 \
                else int(v) // 1_000_000
        else:
            val = int(v)
        lo, rng = _JMIN[type(dst)], _JRANGE[type(dst)]
        wrapped = ((val - lo) % rng) + lo
        if isinstance(src, T.StringType) and wrapped != val:
            raise _CastNull
        if isinstance(src, T.DecimalType) and wrapped != val:
            raise _CastNull
        return wrapped
    if isinstance(dst, (T.FloatType, T.DoubleType)):
        if isinstance(src, T.StringType):
            from spark_rapids_tpu.expr.cast import spark_string_to_double

            f = spark_string_to_double(str(v))
            if f is None:
                raise _CastNull
            return f
        if isinstance(src, T.DecimalType):
            return float(pydec.Decimal(int(v)).scaleb(-src.scale))
        return float(v)
    if isinstance(dst, T.DecimalType):
        if isinstance(src, T.DecimalType):
            d = pydec.Decimal(int(v)).scaleb(-src.scale)
        elif isinstance(src, (T.FloatType, T.DoubleType)):
            f = float(v)
            if math.isnan(f) or math.isinf(f):
                raise _CastNull
            d = pydec.Decimal(f)
        else:
            d = pydec.Decimal(int(v))
        scaled = int(d.scaleb(dst.scale).quantize(
            pydec.Decimal(1), rounding=pydec.ROUND_HALF_UP))
        if abs(scaled) >= 10 ** dst.precision:
            raise _CastNull
        return scaled
    if isinstance(dst, T.DateType):
        if isinstance(src, T.StringType):
            days = _str_to_date_py(v)
            if days is None:
                raise _CastNull
            return days
        if isinstance(src, T.TimestampType):
            return int(v) // 86_400_000_000
        raise _CastNull
    if isinstance(dst, T.TimestampType):
        if isinstance(src, T.DateType):
            return int(v) * 86_400_000_000
        if isinstance(src, T.StringType):
            micros = _str_to_ts_py(v)
            if micros is None:
                raise _CastNull
            return micros
        if is_int(src):
            return int(v) * 1_000_000
        raise _CastNull
    raise NotImplementedError(f"oracle cast {src}->{dst}")


# -- math -------------------------------------------------------------------

def _h_unary_math(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    x = c.values.astype(np.float64)
    name = type(e).__name__
    validity = c.validity.copy()
    with np.errstate(all="ignore"):
        if name == "Sqrt":
            out = np.sqrt(np.where(x < 0, np.nan, x))
        elif name == "Exp":
            out = np.exp(x)
        elif name == "Log":
            bad = x <= 0
            validity &= ~bad
            out = np.log(np.where(bad, 1.0, x))
        elif name == "Log10":
            bad = x <= 0
            validity &= ~bad
            out = np.log10(np.where(bad, 1.0, x))
        elif name in ("Sin", "Cos", "Tan", "Asin", "Acos", "Atan",
                      "Sinh", "Cosh", "Tanh", "Asinh", "Acosh", "Atanh",
                      "Cbrt", "Expm1"):
            out = getattr(np, {"Sin": "sin", "Cos": "cos", "Tan": "tan",
                               "Asin": "arcsin", "Acos": "arccos",
                               "Atan": "arctan", "Sinh": "sinh",
                               "Cosh": "cosh", "Tanh": "tanh",
                               "Asinh": "arcsinh", "Acosh": "arccosh",
                               "Atanh": "arctanh", "Cbrt": "cbrt",
                               "Expm1": "expm1"}[name])(x)
        elif name == "Log2":
            bad = x <= 0
            validity &= ~bad
            out = np.log2(np.where(bad, 1.0, x))
        elif name == "Log1p":
            bad = x <= -1.0
            validity &= ~bad
            out = np.log1p(np.where(bad, 0.0, x))
        elif name == "Rint":
            out = np.round(x)  # numpy round is half-to-even == Math.rint
        elif name == "Cot":
            out = 1.0 / np.tan(x)
        elif name == "Csc":
            out = 1.0 / np.sin(x)
        elif name == "Sec":
            out = 1.0 / np.cos(x)
        elif name == "ToDegrees":
            out = np.degrees(x)
        elif name == "ToRadians":
            out = np.radians(x)
        elif name == "Signum":
            out = np.sign(x)
        else:
            raise NotImplementedError(name)
    return CpuCol(T.DOUBLE, out, validity)


def _h_binary_math(e, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    a = l.values.astype(np.float64)
    b = r.values.astype(np.float64)
    name = type(e).__name__
    validity = l.validity & r.validity
    with np.errstate(all="ignore"):
        if name == "Atan2":
            out = np.arctan2(a, b)
        elif name == "Hypot":
            out = np.hypot(a, b)
        elif name == "Logarithm":
            bad = (b <= 0) | (a <= 0) | (a == 1.0)
            validity = validity & ~bad
            out = np.log(np.where(b <= 0, 1.0, b)) / np.log(
                np.where((a <= 0) | (a == 1.0), 2.0, a))
        else:
            raise NotImplementedError(name)
    return CpuCol(T.DOUBLE, out, validity)


def _h_bitwise(e, cols, n, ansi):
    name = type(e).__name__
    if name == "BitwiseNot":
        (c,) = _kids(e, cols, n, ansi)
        return CpuCol(e.dataType, ~c.values, c.validity.copy())
    l, r = _kids(e, cols, n, ansi)
    validity = l.validity & r.validity
    if name in ("BitwiseAnd", "BitwiseOr", "BitwiseXor"):
        fn = {"BitwiseAnd": np.bitwise_and, "BitwiseOr": np.bitwise_or,
              "BitwiseXor": np.bitwise_xor}[name]
        return CpuCol(e.dataType, fn(l.values, r.values), validity)
    # shifts: Java masks the amount to the value width
    width_mask = 63 if isinstance(e.dataType, T.LongType) else 31
    amt = (r.values.astype(np.int64) & width_mask).astype(l.values.dtype)
    if name == "ShiftLeft":
        out = l.values << amt
    elif name == "ShiftRight":
        out = l.values >> amt
    else:  # ShiftRightUnsigned
        udt = np.uint64 if l.values.dtype == np.int64 else np.uint32
        out = (l.values.view(udt) >> amt.view(udt)).view(l.values.dtype)
    return CpuCol(e.dataType, out, validity)


def _h_pow(e, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    with np.errstate(all="ignore"):
        out = np.power(l.values.astype(np.float64), r.values.astype(np.float64))
    return CpuCol(T.DOUBLE, out, l.validity & r.validity)


def _h_floorceil(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    ct = e.child.dataType
    is_ceil = type(e).__name__ == "Ceil"
    if ct.is_integral:
        return c
    if isinstance(ct, T.DecimalType):
        import decimal as pydec

        r = pydec.ROUND_CEILING if is_ceil else pydec.ROUND_FLOOR
        vals = np.array([int(pydec.Decimal(int(v)).scaleb(-ct.scale)
                             .to_integral_value(rounding=r))
                         for v in c.values], dtype=object)
        return CpuCol(e.dataType, vals, c.validity.copy())
    f = np.ceil if is_ceil else np.floor
    return CpuCol(T.LONG, f(c.values.astype(np.float64)).astype(np.int64),
                  c.validity.copy())


def _h_round(e, cols, n, ansi):
    c, s = _kids(e, cols, n, ansi)
    ct = e.children[0].dataType
    if isinstance(ct, T.DecimalType):
        import decimal as pydec

        dt: T.DecimalType = e.dataType
        vals = np.array(
            [int(pydec.Decimal(int(v)).scaleb(-ct.scale).scaleb(dt.scale)
                 .quantize(pydec.Decimal(1), rounding=pydec.ROUND_HALF_UP))
             for v in c.values], dtype=object)
        return CpuCol(dt, vals, c.validity.copy())
    if ct.is_integral:
        return c
    out = np.zeros(n, np.float64)
    for i in range(n):
        if c.validity[i]:
            import decimal as pydec

            d = pydec.Decimal(repr(float(c.values[i]))).quantize(
                pydec.Decimal(1).scaleb(-int(s.values[i])),
                rounding=pydec.ROUND_HALF_UP)
            out[i] = float(d)
    return CpuCol(e.dataType, out, c.validity & s.validity)


# -- strings ----------------------------------------------------------------

def _str_rows(c: CpuCol):
    return c.values


def _h_length(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.array([len(v) if v is not None else 0 for v in c.values],
                   np.int32)
    return CpuCol(T.INT, out, c.validity.copy())


def _h_upperlower(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    up = type(e).__name__ == "Upper"
    # ASCII-only to match device (documented incompat for non-ASCII)
    def tx(s):
        return "".join(
            chr(ord(ch) - 32) if up and "a" <= ch <= "z" else
            chr(ord(ch) + 32) if not up and "A" <= ch <= "Z" else ch
            for ch in s)

    out = np.array([tx(v) if v is not None else None for v in c.values],
                   object)
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_substring(e, cols, n, ansi):
    c, p, ln = _kids(e, cols, n, ansi)
    out = []
    validity = c.validity & p.validity & ln.validity
    for i in range(n):
        if not validity[i]:
            out.append(None)
            continue
        s = c.values[i]
        pos, want = int(p.values[i]), int(ln.values[i])
        b = s.encode()
        # Spark substringSQL: window computed on unclamped start
        if pos > 0:
            start = pos - 1
        elif pos < 0:
            start = len(b) + pos
        else:
            start = 0
        end = start + max(want, 0)
        seg = b[max(start, 0): max(end, 0)]
        out.append(seg.decode("utf-8", "replace"))
    return CpuCol(T.STRING, np.array(out, object), validity)


def _h_concat(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    validity = _null_prop_validity(kids)
    out = []
    for i in range(n):
        out.append("".join(k.values[i] for k in kids) if validity[i] else None)
    return CpuCol(T.STRING, np.array(out, object), validity)


def _h_startswith(e, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    validity = l.validity & r.validity
    name = type(e).__name__
    out = np.zeros(n, np.bool_)
    for i in range(n):
        if validity[i]:
            s, t = l.values[i], r.values[i]
            out[i] = (s.startswith(t) if name == "StartsWith"
                      else s.endswith(t) if name == "EndsWith"
                      else t in s)
    return CpuCol(T.BOOLEAN, out, validity)


def _h_trim(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.array([v.strip(" ") if v is not None else None
                    for v in c.values], object)
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_like(e: S.Like, cols, n, ansi):
    import re

    from spark_rapids_tpu.regex.transpiler import like_to_regex

    l, _ = _kids(e, cols, n, ansi)
    rx = re.compile(like_to_regex(e.right.value))
    out = np.array([bool(rx.fullmatch(v)) if v is not None else False
                    for v in l.values], np.bool_)
    return CpuCol(T.BOOLEAN, out, l.validity.copy())


def _java_regex_to_python(pat: str) -> str:
    """Adjust Java-vs-Python differences for the supported subset:
    Java `.` excludes \\r too; Java `$` also matches before a final \\r /
    \\r\\n.  Walks the pattern skipping escapes and char classes."""
    out = []
    i = 0
    in_class = False
    while i < len(pat):
        c = pat[i]
        if c == "\\" and i + 1 < len(pat):
            out.append(pat[i:i + 2])
            i += 2
            continue
        if in_class:
            if c == "]":
                in_class = False
            out.append(c)
        elif c == "[":
            in_class = True
            out.append(c)
        elif c == ".":
            out.append(r"[^\n\r]")
        elif c == "$":
            out.append(r"(?=(?:\r\n|\n|\r)?\Z)")
        else:
            out.append(c)
        i += 1
    return "".join(out)


def _h_rlike(e, cols, n, ansi):
    import re

    l, _ = _kids(e, cols, n, ansi)
    rx = re.compile(_java_regex_to_python(e.right.value))
    out = np.array([bool(rx.search(v)) if v is not None else False
                    for v in l.values], np.bool_)
    return CpuCol(T.BOOLEAN, out, l.validity.copy())


# -- datetime ---------------------------------------------------------------

def _date_of(c: CpuCol, dtype):
    if isinstance(dtype, T.TimestampType):
        return [pydt.date(1970, 1, 1)
                + pydt.timedelta(days=int(v) // 86_400_000_000)
                for v in c.values]
    return [pydt.date(1970, 1, 1) + pydt.timedelta(days=int(v))
            for v in c.values]


def _h_datefield(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    dates = _date_of(c, e.child.dataType)
    name = type(e).__name__
    out = np.zeros(n, np.int32)
    for i in range(n):
        if not c.validity[i]:
            continue
        d = dates[i]
        if name == "Year":
            out[i] = d.year
        elif name == "Month":
            out[i] = d.month
        elif name == "DayOfMonth":
            out[i] = d.day
        elif name == "DayOfWeek":
            out[i] = d.isoweekday() % 7 + 1
        elif name == "DayOfYear":
            out[i] = d.timetuple().tm_yday
        elif name == "Quarter":
            out[i] = (d.month - 1) // 3 + 1
        else:
            raise NotImplementedError(name)
    return CpuCol(T.INT, out, c.validity.copy())


def _h_lastday(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    import calendar

    dates = _date_of(c, e.child.dataType)
    out = np.zeros(n, np.int32)
    for i in range(n):
        if c.validity[i]:
            d = dates[i]
            last = d.replace(day=calendar.monthrange(d.year, d.month)[1])
            out[i] = (last - pydt.date(1970, 1, 1)).days
    return CpuCol(T.DATE, out, c.validity.copy())


def _h_timefield(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    name = type(e).__name__
    out = np.zeros(n, np.int32)
    for i in range(n):
        if c.validity[i]:
            ts = (pydt.datetime(1970, 1, 1)
                  + pydt.timedelta(microseconds=int(c.values[i])))
            out[i] = {"Hour": ts.hour, "Minute": ts.minute,
                      "Second": ts.second}[name]
    return CpuCol(T.INT, out, c.validity.copy())


def _h_dateadd(e, cols, n, ansi):
    d, k = _kids(e, cols, n, ansi)
    sign = -1 if type(e).__name__ == "DateSub" else 1
    out = (d.values.astype(np.int64)
           + sign * k.values.astype(np.int64)).astype(np.int32)
    return CpuCol(T.DATE, out, d.validity & k.validity)


def _h_datediff(e, cols, n, ansi):
    a, b = _kids(e, cols, n, ansi)
    return CpuCol(T.INT, (a.values.astype(np.int64)
                          - b.values.astype(np.int64)).astype(np.int32),
                  a.validity & b.validity)


def _h_unixts(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    if isinstance(e.child.dataType, T.DateType):
        out = c.values.astype(np.int64) * 86_400
    else:
        out = np.array([int(v) // 1_000_000 for v in c.values], np.int64)
    return CpuCol(T.LONG, out, c.validity.copy())


def _h_weekofyear(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    dates = _date_of(c, e.child.dataType)
    out = np.zeros(n, np.int32)
    for i in range(n):
        if c.validity[i]:
            out[i] = dates[i].isocalendar()[1]
    return CpuCol(T.INT, out, c.validity.copy())


def _h_addmonths(e, cols, n, ansi):
    import calendar

    d, k = _kids(e, cols, n, ansi)
    dates = _date_of(d, e.children[0].dataType)
    out = np.zeros(n, np.int32)
    validity = d.validity & k.validity
    for i in range(n):
        if not validity[i]:
            continue
        dt = dates[i]
        total = dt.year * 12 + dt.month - 1 + int(k.values[i])
        y, m = total // 12, total % 12 + 1
        day = min(dt.day, calendar.monthrange(y, m)[1])
        out[i] = (pydt.date(y, m, day) - pydt.date(1970, 1, 1)).days
    return CpuCol(T.DATE, out, validity)


def _h_monthsbetween(e, cols, n, ansi):
    import calendar

    a, b = _kids(e, cols, n, ansi)
    validity = a.validity & b.validity
    out = np.zeros(n, np.float64)

    def parts(col_, dt):
        if isinstance(dt, T.TimestampType):
            tss = [pydt.datetime(1970, 1, 1)
                   + pydt.timedelta(microseconds=int(v)) for v in col_.values]
        else:
            tss = [pydt.datetime(1970, 1, 1)
                   + pydt.timedelta(days=int(v)) for v in col_.values]
        return tss

    ta = parts(a, e.children[0].dataType)
    tb = parts(b, e.children[1].dataType)
    for i in range(n):
        if not validity[i]:
            continue
        x, y = ta[i], tb[i]
        months = (x.year - y.year) * 12 + (x.month - y.month)
        x_end = x.day == calendar.monthrange(x.year, x.month)[1]
        y_end = y.day == calendar.monthrange(y.year, y.month)[1]
        secs_x = x.hour * 3600 + x.minute * 60 + x.second + x.microsecond / 1e6
        secs_y = y.hour * 3600 + y.minute * 60 + y.second + y.microsecond / 1e6
        # Spark: equal day-of-month (or both month ends) -> whole months,
        # time of day ignored
        if (x_end and y_end) or x.day == y.day:
            v = float(months)
        else:
            v = months + ((x.day - y.day) * 86400.0 + secs_x - secs_y) \
                / (31.0 * 86400.0)
        if getattr(e, "round_off", True):
            v = float(np.round(v * 1e8) / 1e8)
        out[i] = v
    return CpuCol(T.DOUBLE, out, validity)


def _h_truncdate(e, cols, n, ansi):
    c = eval_expr(e.children[0], cols, n, ansi)
    from spark_rapids_tpu.expr.datetime import TruncDate as _TD

    fmt = e.children[1]
    unit = _TD._FMTS.get(str(fmt.value).lower()) \
        if getattr(fmt, "value", None) is not None else None
    dates = _date_of(c, e.children[0].dataType)
    out = np.zeros(n, np.int32)
    validity = c.validity.copy()
    for i in range(n):
        if not c.validity[i]:
            continue
        d = dates[i]
        if unit == "year":
            t = d.replace(month=1, day=1)
        elif unit == "quarter":
            t = d.replace(month=(d.month - 1) // 3 * 3 + 1, day=1)
        elif unit == "month":
            t = d.replace(day=1)
        elif unit == "week":
            t = d - pydt.timedelta(days=d.weekday())
        else:
            validity[i] = False
            continue
        out[i] = (t - pydt.date(1970, 1, 1)).days
    return CpuCol(T.DATE, out, validity)


def _h_nextday(e, cols, n, ansi):
    c = eval_expr(e.children[0], cols, n, ansi)
    from spark_rapids_tpu.expr.datetime import NextDay as _ND

    lit_ = e.children[1]
    target = _ND._DOW.get(str(lit_.value).strip().lower()) \
        if getattr(lit_, "value", None) is not None else None
    dates = _date_of(c, e.children[0].dataType)
    out = np.zeros(n, np.int32)
    validity = c.validity.copy()
    for i in range(n):
        if not c.validity[i]:
            continue
        if target is None:
            validity[i] = False
            continue
        d = dates[i]
        dow = d.isoweekday() % 7     # Sunday=0
        delta = (target - dow) % 7 or 7
        out[i] = (d - pydt.date(1970, 1, 1)).days + delta
    return CpuCol(T.DATE, out, validity)


def _py_civil_from_days(z: int):
    """Howard Hinnant civil-from-days (pure ints: no datetime range cap)."""
    z += 719468
    era = (z if z >= 0 else z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + 3 if mp < 10 else mp - 9
    return y + (1 if m <= 2 else 0), m, d


_DOW_ABBR = ["Sun", "Mon", "Tue", "Wed", "Thu", "Fri", "Sat"]
_DOW_FULL = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
_MON_ABBR = ["Jan", "Feb", "Mar", "Apr", "May", "Jun", "Jul", "Aug", "Sep",
             "Oct", "Nov", "Dec"]
_MON_FULL = ["January", "February", "March", "April", "May", "June", "July",
             "August", "September", "October", "November", "December"]
_ORACLE_FMT_TOKENS = ("yyyy", "MMMM", "MMM", "MM", "dd", "DD", "HH", "mm",
                      "ss", "EEEE", "EEE", "a")


def _oracle_format_micros(micros: int, fmt: str) -> str:
    """Render with pure integer civil math (Java patterns, UTC)."""
    days, rem = divmod(micros, 86_400_000_000)
    y, mo, d = _py_civil_from_days(days)
    h = rem // 3_600_000_000
    mi = (rem // 60_000_000) % 60
    s = (rem // 1_000_000) % 60
    dow = (days + 4) % 7
    out = []
    i = 0
    while i < len(fmt):
        for t in _ORACLE_FMT_TOKENS:
            if fmt.startswith(t, i):
                if t == "yyyy":
                    out.append(f"{y:04d}")
                elif t == "MM":
                    out.append(f"{mo:02d}")
                elif t == "MMM":
                    out.append(_MON_ABBR[mo - 1])
                elif t == "MMMM":
                    out.append(_MON_FULL[mo - 1])
                elif t == "dd":
                    out.append(f"{d:02d}")
                elif t == "DD":
                    out.append(f"{_day_of_year(y, mo, d):03d}")
                elif t == "HH":
                    out.append(f"{h:02d}")
                elif t == "mm":
                    out.append(f"{mi:02d}")
                elif t == "ss":
                    out.append(f"{s:02d}")
                elif t == "EEE":
                    out.append(_DOW_ABBR[dow])
                elif t == "EEEE":
                    out.append(_DOW_FULL[dow])
                elif t == "a":
                    out.append("AM" if h < 12 else "PM")
                i += len(t)
                break
        else:
            ch = fmt[i]
            if ch.isalpha():
                raise NotImplementedError(f"oracle time format letter {ch!r}")
            out.append(ch)
            i += 1
    return "".join(out)


_MDAYS = [31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31]


def _day_of_year(y: int, m: int, d: int) -> int:
    leap = (y % 4 == 0 and y % 100 != 0) or y % 400 == 0
    return sum(_MDAYS[: m - 1]) + (1 if leap and m > 2 else 0) + d


def _h_format_time(e, cols, n, ansi):
    c = eval_expr(e.children[0], cols, n, ansi)
    fmt = str(e.children[1].value)
    name = type(e).__name__
    out = np.empty(n, object)
    for i in range(n):
        if not c.validity[i]:
            out[i] = None
            continue
        if name == "FromUnixTime":
            # Java sec * MICROS_PER_SECOND wraps silently (long multiply)
            micros = int(c.values[i]) * 1_000_000
            micros = (micros + 2 ** 63) % 2 ** 64 - 2 ** 63
        elif isinstance(e.children[0].dataType, T.DateType):
            micros = int(c.values[i]) * 86_400_000_000
        else:
            micros = int(c.values[i])
        out[i] = _oracle_format_micros(micros, fmt)
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_size(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.array([len(v) if c.validity[i] and v is not None else -1
                    for i, v in enumerate(c.values)], np.int32)
    return CpuCol(T.INT, out, np.ones(n, np.bool_))


def _arr_index(e, cols, n, ansi, one_based):
    a, k = _kids(e, cols, n, ansi)
    et = e.dataType
    out_vals = []
    validity = a.validity & k.validity
    for i in range(n):
        if not validity[i]:
            out_vals.append(None)
            continue
        v = a.values[i]
        idx = int(k.values[i])
        if one_based:
            if idx == 0:
                out_vals.append(None)
                validity[i] = False
                continue
            idx = idx - 1 if idx > 0 else len(v) + idx
        if not (0 <= idx < len(v)) or v[idx] is None:
            out_vals.append(None)
            validity[i] = False
        else:
            out_vals.append(v[idx])
    if isinstance(et, T.StringType):
        arr = np.empty(n, object)
        for i, x in enumerate(out_vals):
            arr[i] = x
        return CpuCol(et, arr, validity)
    arr = np.array([x if x is not None else 0 for x in out_vals],
                   T.storage_dtype(et))
    return CpuCol(et, arr, validity)


def _h_get_array_item(e, cols, n, ansi):
    return _arr_index(e, cols, n, ansi, one_based=False)


def _h_element_at(e, cols, n, ansi):
    if isinstance(e.children[0]._dataType, T.MapType):
        return _h_get_map_value(e, cols, n, ansi)
    return _arr_index(e, cols, n, ansi, one_based=True)


def _h_array_contains(e, cols, n, ansi):
    a, v = _kids(e, cols, n, ansi)
    out = np.zeros(n, np.bool_)
    validity = a.validity & v.validity
    for i in range(n):
        if not validity[i]:
            continue
        arr = a.values[i]
        found = any(x is not None and x == v.values[i] for x in arr)
        out[i] = found
        if not found and any(x is None for x in arr):
            validity[i] = False
    return CpuCol(T.BOOLEAN, out, validity)


def _h_create_array(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        vals[i] = [k.row(i) for k in kids]
    return CpuCol(e.dataType, vals, np.ones(n, np.bool_))


def _h_array_minmax(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    mx = type(e).__name__ == "ArrayMax"
    et = e.dataType
    out_vals = []
    validity = c.validity.copy()
    for i in range(n):
        if not c.validity[i]:
            out_vals.append(None)
            continue
        xs = [x for x in c.values[i] if x is not None]
        if not xs:
            out_vals.append(None)
            validity[i] = False
        else:
            out_vals.append(max(xs) if mx else min(xs))
    arr = np.array([x if x is not None else 0 for x in out_vals],
                   T.storage_dtype(et))
    return CpuCol(et, arr, validity)


def _h_udf(e, cols, n, ansi):
    """Row-based UDF evaluation — the CPU truth (reference: the original
    Scala UDF body that RapidsUDF accelerates)."""
    kids = _kids(e, cols, n, ansi)
    out_vals = []
    validity = np.ones(n, np.bool_)
    from spark_rapids_tpu.udf_compiler import F, _wants_namespace

    wants_f = _wants_namespace(e.fn)
    if getattr(e, "vectorized", False):
        # pandas-style: whole columns in storage representation (mirrors
        # UserDefinedExpression._eval_python's vectorized branch)
        ins = []
        for k in kids:
            if k.values.dtype == object:
                ins.append(np.array(k.to_pylist(), dtype=object))
            else:
                ins.append(k.values)
        res = np.asarray(e.fn(*ins))
        mask = np.ones(n, np.bool_)
        for k in kids:
            mask &= k.validity
        out_vals = [res[i].item() if mask[i] else None for i in range(n)]
        validity = mask.copy()
        for i in range(n):
            if out_vals[i] is None:
                validity[i] = False
        dt = e.dataType
        return _udf_results_to_col(out_vals, validity, dt, n)
    # python UDFs receive CONVERTED python values (dates as datetime.date,
    # decimals as Decimal, plain python ints — NOT numpy storage scalars),
    # exactly like pyspark and the device arrow-eval path
    pylists = [k.to_pylist() for k in kids]
    for i in range(n):
        args = [p[i] for p in pylists]
        v = e.fn(*args, F) if wants_f else e.fn(*args)
        v = _clamp_udf_result(v, e.dataType)
        if v is None:
            validity[i] = False
        out_vals.append(v)
    dt = e.dataType
    return _udf_results_to_col(out_vals, validity, dt, n)


_INT_BOUNDS = {T.ByteType: 2**7, T.ShortType: 2**15, T.IntegerType: 2**31,
               T.LongType: 2**63}


def _clamp_udf_result(v, dt):
    """Results outside the declared type's range become NULL (pyspark's
    serializer behavior)."""
    bound = _INT_BOUNDS.get(type(dt))
    if bound is not None and v is not None:
        if not isinstance(v, int) or not (-bound <= v < bound):
            return None
    return v


def _udf_results_to_col(out_vals, validity, dt, n):
    out_vals = [_clamp_udf_result(v, dt) for v in out_vals]
    for i, v in enumerate(out_vals):
        if v is None:
            validity[i] = False
    if isinstance(dt, (T.StringType, T.DecimalType)):
        arr = np.array([v if v is not None else None for v in out_vals],
                       object)
    else:
        arr = np.array([v if v is not None else 0 for v in out_vals],
                       T.storage_dtype(dt))
    return CpuCol(dt, arr, validity)


def _java_replacement_to_python(r: str) -> str:
    """Java replacement -> python re template: $n -> \\n (group ref),
    \\$ -> literal $, literal backslashes doubled."""
    out = []
    i = 0
    while i < len(r):
        ch = r[i]
        if ch == "\\" and i + 1 < len(r):
            nxt = r[i + 1]
            out.append("$" if nxt == "$" else "\\\\" + nxt)
            i += 2
        elif ch == "$" and i + 1 < len(r) and r[i + 1].isdigit():
            out.append("\\" + r[i + 1])
            i += 2
        elif ch == "\\":
            out.append("\\\\")
            i += 1
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _h_regexp_replace(e, cols, n, ansi):
    import re as _re

    c = eval_expr(e.children[0], cols, n, ansi)
    pat = _re.compile(_java_regex_to_python(str(e.children[1].value)))
    repl = _java_replacement_to_python(str(e.children[2].value))
    out = np.array([pat.sub(repl, v) if v is not None else None
                    for v in c.values], object)
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_regexp_extract(e, cols, n, ansi):
    import re as _re

    c = eval_expr(e.children[0], cols, n, ansi)
    pat = _re.compile(_java_regex_to_python(str(e.children[1].value)))
    idx = int(e.children[2].value)
    out = []
    for v in c.values:
        if v is None:
            out.append(None)
            continue
        m = pat.search(v)
        out.append((m.group(idx) or "") if m else "")
    return CpuCol(T.STRING, np.array(out, object), c.validity.copy())


def _h_octetbit(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    mult = 8 if type(e).__name__ == "BitLength" else 1
    out = np.array([len(v.encode("utf-8")) * mult if v is not None else 0
                    for v in c.values], np.int32)
    return CpuCol(T.INT, out, c.validity.copy())


def _h_leftright(e, cols, n, ansi):
    s, k = _kids(e, cols, n, ansi)
    left = type(e).__name__ == "StringLeft"
    validity = s.validity & k.validity
    out = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            out[i] = None
            continue
        v = s.values[i]
        kk = int(k.values[i])
        if kk <= 0:
            out[i] = ""
        else:
            out[i] = v[:kk] if left else v[-kk:] if kk <= len(v) else v
    return CpuCol(T.STRING, out, validity)


def _h_substring_index(e, cols, n, ansi):
    s, d, k = _kids(e, cols, n, ansi)
    validity = s.validity & d.validity & k.validity
    out = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            out[i] = None
            continue
        v, delim, cnt = s.values[i], d.values[i], int(k.values[i])
        if cnt == 0 or not delim:
            out[i] = ""
            continue
        if cnt > 0:
            pos = 0
            found = 0
            while found < cnt:
                j = v.find(delim, pos)
                if j < 0:
                    break
                found += 1
                pos = j + len(delim)
            out[i] = v if found < cnt else v[: pos - len(delim)]
        else:
            pos = len(v)
            found = 0
            while found < -cnt:
                j = v.rfind(delim, 0, pos)
                if j < 0:
                    break
                found += 1
                pos = j
            out[i] = v if found < -cnt else v[pos + len(delim):]
    return CpuCol(T.STRING, out, validity)


# -- string breadth ---------------------------------------------------------

def _h_reverse(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.array([v[::-1] if v is not None else None for v in c.values],
                   object)
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_initcap(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)

    def tx(s):
        if s is None:
            return None
        out = []
        prev_space = True
        for ch in s:
            if prev_space and "a" <= ch <= "z":
                out.append(chr(ord(ch) - 32))
            elif not prev_space and "A" <= ch <= "Z":
                out.append(chr(ord(ch) + 32))
            else:
                out.append(ch)
            prev_space = ch == " "
        return "".join(out)

    return CpuCol(T.STRING, np.array([tx(v) for v in c.values], object),
                  c.validity.copy())


def _h_ascii(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.array([(ord(v[0]) if v else 0)
                    if v is not None else 0 for v in c.values], np.int32)
    return CpuCol(T.INT, out, c.validity.copy())


def _h_chr(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)

    def tx(v):
        if v is None:
            return None
        lv = int(v)
        if lv < 0:
            return ""
        return chr(lv % 256)

    return CpuCol(T.STRING, np.array([tx(v) for v in c.values], object),
                  c.validity.copy())


def _h_replace(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    c, se, re_ = kids
    validity = _null_prop_validity(kids)
    out = []
    for i in range(n):
        if not validity[i]:
            out.append(None)
            continue
        s, search, rep = c.values[i], se.values[i], re_.values[i]
        out.append(s if search == "" else s.replace(search, rep))
    return CpuCol(T.STRING, np.array(out, object), validity)


def _h_translate(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    c, f, t = kids
    validity = _null_prop_validity(kids)
    out = []
    for i in range(n):
        if not validity[i]:
            out.append(None)
            continue
        frm, to = f.values[i], t.values[i]
        table = {}
        for j, ch in enumerate(frm):
            if ch not in table:
                table[ch] = to[j] if j < len(to) else None
        out.append("".join(table.get(ch, ch) for ch in c.values[i]
                           if table.get(ch, ch) is not None))
    return CpuCol(T.STRING, np.array(out, object), validity)


def _h_instr(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    s, sub = kids
    validity = _null_prop_validity(kids)
    out = np.array([(s.values[i].find(sub.values[i]) + 1)
                    if validity[i] else 0 for i in range(n)], np.int32)
    return CpuCol(T.INT, out, validity)


def _h_locate(e, cols, n, ansi):
    sub, s, st = _kids(e, cols, n, ansi)
    validity = s.validity & sub.validity
    out = np.zeros(n, np.int32)
    for i in range(n):
        if not validity[i]:
            continue
        if not st.validity[i] or int(st.values[i]) < 1:
            out[i] = 0  # Spark: null start or start < 1 -> 0, stays valid
            continue
        frm = int(st.values[i]) - 1
        if sub.values[i] == "":
            out[i] = 1  # UTF8String.indexOf("") is 0 regardless of start
        else:
            out[i] = s.values[i].find(sub.values[i], frm) + 1
    return CpuCol(T.INT, out, validity)


def _pad_str(s, target, pad, left):
    if target <= 0:
        return ""
    if len(s) >= target:
        return s[:target]
    need = target - len(s)
    fill = (pad * (need // len(pad) + 1))[:need] if pad else ""
    return (fill + s) if left else (s + fill)


def _h_pad(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    c, ln, p = kids
    validity = _null_prop_validity(kids)
    left = type(e).__name__ == "StringLPad"
    out = [(_pad_str(c.values[i], int(ln.values[i]), p.values[i], left)
            if validity[i] else None) for i in range(n)]
    return CpuCol(T.STRING, np.array(out, object), validity)


def _h_repeat(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    c, r = kids
    validity = _null_prop_validity(kids)
    out = [(c.values[i] * max(int(r.values[i]), 0)
            if validity[i] else None) for i in range(n)]
    return CpuCol(T.STRING, np.array(out, object), validity)


def _h_concat_ws(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    sep = kids[0]
    out = []
    for i in range(n):
        if not sep.validity[i]:  # Spark: null separator -> NULL result
            out.append(None)
            continue
        pieces = [c.values[i] for c in kids[1:] if c.validity[i]]
        out.append(sep.values[i].join(pieces))
    return CpuCol(T.STRING, np.array(out, object), sep.validity.copy())


# -- hash functions (exact ports of Spark Murmur3_x86_32 / XXH64) -----------

_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF


def _mm3_mix_k1(k1):
    k1 = (k1 * 0xCC9E2D51) & _M32
    k1 = ((k1 << 15) | (k1 >> 17)) & _M32
    return (k1 * 0x1B873593) & _M32


def _mm3_mix_h1(h1, k1):
    h1 ^= k1
    h1 = ((h1 << 13) | (h1 >> 19)) & _M32
    return (h1 * 5 + 0xE6546B64) & _M32


def _mm3_fmix(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M32
    return h1 ^ (h1 >> 16)


def _mm3_update(kind, x, seed):
    if kind == "int":
        return _mm3_fmix(_mm3_mix_h1(seed, _mm3_mix_k1(x & _M32)), 4)
    if kind == "long":
        x &= _M64
        h = _mm3_mix_h1(seed, _mm3_mix_k1(x & _M32))
        h = _mm3_mix_h1(h, _mm3_mix_k1(x >> 32))
        return _mm3_fmix(h, 8)
    bs = x
    h = seed
    aligned = (len(bs) // 4) * 4
    for i in range(0, aligned, 4):
        block = bs[i] | bs[i + 1] << 8 | bs[i + 2] << 16 | bs[i + 3] << 24
        h = _mm3_mix_h1(h, _mm3_mix_k1(block))
    for i in range(aligned, len(bs)):
        b = bs[i]
        sb = b if b < 128 else b | 0xFFFFFF00
        h = _mm3_mix_h1(h, _mm3_mix_k1(sb))
    return _mm3_fmix(h, len(bs))


_XP1 = 0x9E3779B185EBCA87
_XP2 = 0xC2B2AE3D27D4EB4F
_XP3 = 0x165667B19E3779F9
_XP4 = 0x85EBCA77C2B2AE63
_XP5 = 0x27D4EB2F165667C5


def _xrotl(x, r):
    return ((x << r) | (x >> (64 - r))) & _M64


def _xfmix(h):
    h ^= h >> 33
    h = (h * _XP2) & _M64
    h ^= h >> 29
    h = (h * _XP3) & _M64
    return h ^ (h >> 32)


def _xxh_update(kind, x, seed):
    if kind == "int":
        h = (seed + _XP5 + 4) & _M64
        h ^= ((x & _M32) * _XP1) & _M64
        h = (_xrotl(h, 23) * _XP2 + _XP3) & _M64
        return _xfmix(h)
    if kind == "long":
        x &= _M64
        h = (seed + _XP5 + 8) & _M64
        h ^= (_xrotl((x * _XP2) & _M64, 31) * _XP1) & _M64
        h = (_xrotl(h, 27) * _XP1 + _XP4) & _M64
        return _xfmix(h)
    bs = x
    n = len(bs)
    if n >= 32:
        v1 = (seed + _XP1 + _XP2) & _M64
        v2 = (seed + _XP2) & _M64
        v3 = seed & _M64
        v4 = (seed - _XP1) & _M64
        o = 0
        while o <= n - 32:
            vs = []
            for j, v in enumerate((v1, v2, v3, v4)):
                k = int.from_bytes(bs[o + 8 * j:o + 8 * j + 8], "little")
                vs.append((_xrotl((v + k * _XP2) & _M64, 31) * _XP1) & _M64)
            v1, v2, v3, v4 = vs
            o += 32
        h = (_xrotl(v1, 1) + _xrotl(v2, 7) + _xrotl(v3, 12)
             + _xrotl(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h = ((h ^ (_xrotl((v * _XP2) & _M64, 31) * _XP1 & _M64))
                 * _XP1 + _XP4) & _M64
    else:
        h = (seed + _XP5) & _M64
        o = 0
    h = (h + n) & _M64
    while o <= n - 8:
        k = int.from_bytes(bs[o:o + 8], "little")
        h = (_xrotl(h ^ ((_xrotl((k * _XP2) & _M64, 31) * _XP1) & _M64), 27)
             * _XP1 + _XP4) & _M64
        o += 8
    if o <= n - 4:
        k = int.from_bytes(bs[o:o + 4], "little")
        h = (_xrotl(h ^ ((k * _XP1) & _M64), 23) * _XP2 + _XP3) & _M64
        o += 4
    while o < n:
        h = (_xrotl(h ^ ((bs[o] * _XP5) & _M64), 11) * _XP1) & _M64
        o += 1
    return _xfmix(h)


def _hash_input(dt: T.DataType, v):
    """-> (kind, value) matching Spark HashExpression's per-type encoding."""
    if isinstance(dt, T.StringType):
        return "bytes", v.encode("utf-8")
    if isinstance(dt, T.FloatType):
        f = np.float32(v)
        if f == 0.0:
            f = np.float32(0.0)
        bits = (0x7FC00000 if np.isnan(f)
                else int(f.view(np.int32)))
        return "int", bits
    if isinstance(dt, T.DoubleType):
        d = np.float64(v)
        if d == 0.0:
            d = np.float64(0.0)
        bits = (0x7FF8000000000000 if np.isnan(d)
                else int(d.view(np.int64)))
        return "long", bits
    if isinstance(dt, (T.LongType, T.TimestampType, T.DecimalType)):
        return "long", int(v)
    if isinstance(dt, T.BooleanType):
        return "int", 1 if v else 0
    return "int", int(v)  # byte/short/int/date


def _h_hashexpr(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    xx = type(e).__name__ == "XxHash64"
    out = np.zeros(n, np.int64 if xx else np.int32)
    for i in range(n):
        h = e.seed & (_M64 if xx else _M32)
        for c in kids:
            if not c.validity[i]:
                continue
            kind, x = _hash_input(c.dtype, c.values[i])
            h = _xxh_update(kind, x, h) if xx else _mm3_update(kind, x, h)
        if xx:
            out[i] = h - (1 << 64) if h >= (1 << 63) else h
        else:
            out[i] = h - (1 << 32) if h >= (1 << 31) else h
    return CpuCol(e.dataType, out, np.ones(n, np.bool_))


def _h_utc_shift(e, cols, n, ansi):
    """from/to_utc_timestamp via python zoneinfo — independent of the
    device path's raw TZif tables."""
    import datetime as pydt
    from zoneinfo import ZoneInfo

    ts, tzc = _kids(e, cols, n, ansi)
    to_utc = type(e).__name__ == "ToUTCTimestamp"
    validity = ts.validity & tzc.validity
    out = np.zeros(n, np.int64)
    zi_cache = {}
    for i in range(n):
        if not validity[i]:
            continue
        tz = tzc.values[i]
        zi = zi_cache.get(tz)
        if zi is None:
            zi = zi_cache[tz] = ZoneInfo(tz)
        us = int(ts.values[i])
        if to_utc:
            wall = (pydt.datetime(1970, 1, 1)
                    + pydt.timedelta(microseconds=us))
            off = wall.replace(tzinfo=zi, fold=0).utcoffset()
        else:
            inst = pydt.datetime.fromtimestamp(us // 1_000_000,
                                               tz=pydt.timezone.utc)
            # astimezone: offset AT THE INSTANT (tzinfo.utcoffset(dt)
            # alone would treat dt's fields as wall time)
            off = inst.astimezone(zi).utcoffset()
        shift = int(off.total_seconds()) * 1_000_000
        out[i] = us - shift if to_utc else us + shift
    return CpuCol(T.TIMESTAMP, out, validity)


# -- misc breadth: digests, encodings, url, soundex, ids ---------------------

def _str_map_handler(fn):
    def h(e, cols, n, ansi):
        kids = _kids(e, cols, n, ansi)
        s = kids[0]
        out = np.empty(n, object)
        validity = _null_prop_validity(kids)
        for i in range(n):
            if validity[i]:
                out[i] = fn(e, s.values[i], [k.values[i] for k in kids[1:]])
                if out[i] is None:
                    validity[i] = False
        return CpuCol.from_objs(list(out), T.STRING)

    return h


def _o_md5(e, s, _):
    import hashlib

    return hashlib.md5(s.encode()).hexdigest()


def _o_sha1(e, s, _):
    import hashlib

    return hashlib.sha1(s.encode()).hexdigest()


def _o_sha2(e, s, extra):
    import hashlib

    algo = {0: "sha256", 224: "sha224", 256: "sha256", 384: "sha384",
            512: "sha512"}.get(int(extra[0]) if extra[0] is not None
                               else -1)
    if algo is None:
        return None
    return getattr(hashlib, algo)(s.encode()).hexdigest()


def _h_crc32(e, cols, n, ansi):
    import zlib

    (s,) = _kids(e, cols, n, ansi)
    out = np.zeros(n, np.int64)
    for i in range(n):
        if s.validity[i]:
            out[i] = zlib.crc32(s.values[i].encode())
    return CpuCol(T.LONG, out, s.validity.copy())


def _o_base64(e, s, _):
    import base64 as b64

    return b64.b64encode(s.encode()).decode()


def _o_unbase64(e, s, _):
    import base64 as b64

    try:
        return b64.b64decode(s.encode(), validate=False).decode(
            "utf-8", "replace")
    except Exception:
        return None


def _o_encode(e, s, extra):
    try:
        return s.encode(str(extra[0]).lower()).decode("utf-8", "replace")
    except (UnicodeError, LookupError, TypeError):
        return None


def _o_decode(e, s, extra):
    try:
        return s.encode("utf-8").decode(str(extra[0]).lower())
    except (UnicodeError, LookupError, TypeError):
        return None


def _h_hex(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    for i in range(n):
        if not c.validity[i]:
            continue
        if isinstance(c.dtype, T.StringType):
            out[i] = c.values[i].encode().hex().upper()
        else:
            out[i] = format(int(c.values[i]) & 0xFFFFFFFFFFFFFFFF, "X")
    return CpuCol.from_objs(list(out), T.STRING)


def _o_unhex(e, s, _):
    if len(s) % 2:
        s = "0" + s
    try:
        return bytes.fromhex(s).decode("utf-8", "replace")
    except ValueError:
        return None


def _h_bin(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    for i in range(n):
        if c.validity[i]:
            out[i] = format(int(c.values[i]) & 0xFFFFFFFFFFFFFFFF, "b")
    return CpuCol.from_objs(list(out), T.STRING)


def _o_conv(e, s, extra):
    from spark_rapids_tpu.expr.misc import _conv_str

    if extra[0] is None or extra[1] is None:
        return None
    return _conv_str(s, int(extra[0]), int(extra[1]))


def _h_format_number(e, cols, n, ansi):
    import decimal as pydec

    c, d = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    validity = c.validity & d.validity
    for i in range(n):
        if not validity[i]:
            continue
        dd = int(d.values[i])
        if dd < 0:
            validity[i] = False
            continue
        if isinstance(c.dtype, T.DecimalType):
            v = pydec.Decimal(int(c.values[i])).scaleb(-c.dtype.scale)
        elif isinstance(c.dtype, (T.FloatType, T.DoubleType)):
            fv = float(c.values[i])
            if math.isnan(fv) or math.isinf(fv):
                # Java DecimalFormat renders the NaN / infinity glyphs
                out[i] = ("NaN" if math.isnan(fv)
                          else ("∞" if fv > 0 else "-∞"))
                continue
            v = pydec.Decimal(repr(fv))
        else:
            v = pydec.Decimal(int(c.values[i]))
        with pydec.localcontext() as lctx:
            lctx.prec = 400  # 1e308 doubles need headroom to quantize
            q = v.quantize(pydec.Decimal(1).scaleb(-dd),
                           rounding=pydec.ROUND_HALF_EVEN)
        out[i] = f"{q:,.{dd}f}"
    col = CpuCol.from_objs(list(out), T.STRING)
    col.validity &= validity
    return col


def _o_parse_url(e, s, extra):
    from spark_rapids_tpu.expr.misc import _URL_PARTS, _parse_url_part

    part = extra[0] if extra else None
    key = extra[1] if len(extra) > 1 else None
    if part not in _URL_PARTS:
        return None
    return _parse_url_part(s, part, key)


def _o_soundex(e, s, _):
    from spark_rapids_tpu.expr.misc import _soundex_str

    return _soundex_str(s)


def _h_levenshtein(e, cols, n, ansi):
    a, b = _kids(e, cols, n, ansi)
    validity = a.validity & b.validity
    out = np.zeros(n, np.int32)
    for i in range(n):
        if not validity[i]:
            continue
        x, y = a.values[i].encode(), b.values[i].encode()
        prev = list(range(len(y) + 1))
        for ii, cx in enumerate(x, 1):
            cur = [ii]
            for jj, cy in enumerate(y, 1):
                cur.append(min(prev[jj] + 1, cur[-1] + 1,
                               prev[jj - 1] + (cx != cy)))
            prev = cur
        out[i] = prev[-1]
    return CpuCol(T.INT, out, validity)


def _h_mono_id(e, cols, n, ansi):
    return CpuCol(T.LONG, np.arange(n, dtype=np.int64),
                  np.ones(n, np.bool_))


def _h_partition_id(e, cols, n, ansi):
    return CpuCol(T.INT, np.zeros(n, np.int32), np.ones(n, np.bool_))


def _h_rand(e, cols, n, ansi):
    # same splitmix64 spec as the device path (a PRNG stream is a spec,
    # not semantics to cross-check; NOT Spark's XORShiftRandom)
    from spark_rapids_tpu.expr.misc import Rand as _DevRand

    z = _DevRand._u64_for_rows(e.seed, 0, n)
    vals = (z >> np.uint64(11)).astype(np.float64) / float(1 << 53)
    return CpuCol(T.DOUBLE, vals, np.ones(n, np.bool_))


def _h_raise_error(e, cols, n, ansi):
    (m,) = _kids(e, cols, n, ansi)
    for i in range(n):
        if m.validity[i]:
            raise RuntimeError(f"raise_error: {m.values[i]}")
    return CpuCol(T.NULL, np.zeros(n, np.int32), np.zeros(n, np.bool_))


def _h_bloom_might_contain(e, cols, n, ansi):
    bloom, v = _kids(e, cols, n, ansi)
    import math as _math

    k = max(1, round(e.num_bits / e.num_items * _math.log(2)))
    out = np.zeros(n, np.bool_)
    validity = bloom.validity & v.validity
    for i in range(n):
        if not validity[i]:
            continue
        words = bloom.values[i]
        h1 = _wrap64(_oracle_xxh64(v.dtype, v.values[i], 42))
        h2 = _wrap64(_oracle_xxh64(v.dtype, v.values[i], 77))
        hit = True
        for j in range(k):
            bit = _wrap64(h1 + j * h2) % e.num_bits
            if not (int(words[bit // 64]) >> (bit % 64)) & 1:
                hit = False
                break
        out[i] = hit
    return CpuCol(T.BOOLEAN, out, validity)


def _h_string_split(e, cols, n, ansi):
    import re as _re

    kids = _kids(e, cols, n, ansi)
    s = kids[0]
    pat = e._pattern
    limit = e._limit
    try:
        rx = _re.compile(_java_regex_to_python(pat)) if pat else None
    except _re.error:
        rx = None
    vals = np.empty(n, object)
    validity = s.validity.copy()
    from spark_rapids_tpu.expr.strings import _java_split

    for i in range(n):
        if not validity[i] or rx is None:
            validity[i] = False
            continue
        vals[i] = _java_split(rx, s.values[i], limit)
    return CpuCol(e.dataType, vals, validity)


def _h_array_join(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    arr, delim = kids[0], kids[1]
    rep = kids[2] if len(kids) > 2 else None
    validity = arr.validity & delim.validity
    out = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            continue
        r = rep.row(i) if rep is not None else None
        parts = [e2 if e2 is not None else r for e2 in arr.values[i]]
        out[i] = delim.values[i].join(p for p in parts if p is not None)
    return CpuCol.from_objs(list(out), T.STRING)


# -- collection breadth ------------------------------------------------------

def _nan_eq(a, b):
    """SQL set-op equality incl. NaN == NaN."""
    import math

    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
    return a == b


def _null_aware_eq(a, b):
    if a is None or b is None:
        return a is None and b is None
    return _nan_eq(a, b)


def _h_array_position(e, cols, n, ansi):
    a, v = _kids(e, cols, n, ansi)
    validity = a.validity & v.validity
    out = np.zeros(n, np.int64)
    for i in range(n):
        if not validity[i]:
            continue
        for j, x in enumerate(a.values[i]):
            if x is not None and _nan_eq(x, v.values[i]):
                out[i] = j + 1
                break
    return CpuCol(T.LONG, out, validity)


def _h_array_remove(e, cols, n, ansi):
    a, v = _kids(e, cols, n, ansi)
    validity = a.validity & v.validity
    vals = np.empty(n, object)
    for i in range(n):
        if validity[i]:
            vals[i] = [x for x in a.values[i]
                       if x is None or not _nan_eq(x, v.values[i])]
    return CpuCol(e.dataType, vals, validity)


def _distinct_list(xs):
    out = []
    for x in xs:
        if not any(_null_aware_eq(x, y) for y in out):
            out.append(x)
    return out


def _h_array_distinct(e, cols, n, ansi):
    (a,) = _kids(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if a.validity[i]:
            vals[i] = _distinct_list(a.values[i])
    return CpuCol(e.dataType, vals, a.validity.copy())


def _h_arrays_overlap(e, cols, n, ansi):
    a, b = _kids(e, cols, n, ansi)
    validity = a.validity & b.validity
    out = np.zeros(n, np.bool_)
    for i in range(n):
        if not validity[i]:
            continue
        xs, ys = a.values[i], b.values[i]
        hit = any(x is not None and any(
            y is not None and _nan_eq(x, y) for y in ys) for x in xs)
        out[i] = hit
        if (not hit and xs and ys
                and (any(x is None for x in xs)
                     or any(y is None for y in ys))):
            validity[i] = False
    return CpuCol(T.BOOLEAN, out, validity)


def _h_array_union(e, cols, n, ansi):
    a, b = _kids(e, cols, n, ansi)
    validity = a.validity & b.validity
    vals = np.empty(n, object)
    for i in range(n):
        if validity[i]:
            vals[i] = _distinct_list(list(a.values[i]) + list(b.values[i]))
    return CpuCol(e.dataType, vals, validity)


def _h_array_intersect(e, cols, n, ansi):
    a, b = _kids(e, cols, n, ansi)
    validity = a.validity & b.validity
    vals = np.empty(n, object)
    for i in range(n):
        if validity[i]:
            vals[i] = [x for x in _distinct_list(a.values[i])
                       if any(_null_aware_eq(x, y) for y in b.values[i])]
    return CpuCol(e.dataType, vals, validity)


def _h_array_except(e, cols, n, ansi):
    a, b = _kids(e, cols, n, ansi)
    validity = a.validity & b.validity
    vals = np.empty(n, object)
    for i in range(n):
        if validity[i]:
            vals[i] = [x for x in _distinct_list(a.values[i])
                       if not any(_null_aware_eq(x, y)
                                  for y in b.values[i])]
    return CpuCol(e.dataType, vals, validity)


def _h_slice(e, cols, n, ansi):
    a, st, ln = _kids(e, cols, n, ansi)
    validity = a.validity & st.validity & ln.validity
    vals = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            continue
        s, k = int(st.values[i]), int(ln.values[i])
        if s == 0:
            raise RuntimeError(
                "Unexpected value for start in function slice: SQL array "
                "indices start at 1.")
        if k < 0:
            raise RuntimeError(
                "Unexpected value for length in function slice: length "
                "must be greater than or equal to 0.")
        xs = a.values[i]
        start0 = s - 1 if s > 0 else len(xs) + s
        vals[i] = [] if start0 < 0 else xs[start0:start0 + k]
    return CpuCol(e.dataType, vals, validity)


def _h_sort_array(e, cols, n, ansi):
    import math

    a, _ = _kids(e, cols, n, ansi)
    asc = True
    if isinstance(e.children[1], E.Literal):
        asc = bool(e.children[1].value)
    vals = np.empty(n, object)

    def key(x):
        if isinstance(x, float) and math.isnan(x):
            return (1, 0.0)  # NaN greatest (Spark)
        return (0, x)

    for i in range(n):
        if a.validity[i]:
            xs = a.values[i]
            nulls = [x for x in xs if x is None]
            rest = sorted((x for x in xs if x is not None), key=key,
                          reverse=not asc)
            vals[i] = (nulls + rest) if asc else (rest + nulls)
    return CpuCol(e.dataType, vals, a.validity.copy())


def _h_array_repeat(e, cols, n, ansi):
    v, k = _kids(e, cols, n, ansi)
    vals = np.empty(n, object)
    validity = k.validity.copy()
    for i in range(n):
        if validity[i]:
            count = max(int(k.values[i]), 0)
            vals[i] = [v.row(i)] * count
    return CpuCol(e.dataType, vals, validity)


def _h_sequence(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    validity = _null_prop_validity(kids)
    vals = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            continue
        start, stop = int(kids[0].values[i]), int(kids[1].values[i])
        if len(kids) > 2:
            step = int(kids[2].values[i])
        else:
            step = 1 if stop >= start else -1
        if step == 0 or (stop > start and step < 0) or \
                (stop < start and step > 0):
            raise RuntimeError("Illegal sequence boundaries")
        count = (stop - start) // step + 1
        vals[i] = [start + j * step for j in range(count)]
    return CpuCol(e.dataType, vals, validity)


def _h_create_map(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        d = {}
        for k in range(0, len(kids), 2):
            key = kids[k].row(i)
            if key is None:
                raise RuntimeError("Cannot use null as map key")
            if any(_nan_eq(key, existing) for existing in d):
                raise RuntimeError("Duplicate map key was found")
            d[key] = kids[k + 1].row(i)
        vals[i] = d
    return CpuCol(e.dataType, vals, np.ones(n, np.bool_))


def _h_map_keys(e, cols, n, ansi):
    (m,) = _kids(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if m.validity[i]:
            vals[i] = list(m.values[i].keys())
    return CpuCol(e.dataType, vals, m.validity.copy())


def _h_map_values(e, cols, n, ansi):
    (m,) = _kids(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if m.validity[i]:
            vals[i] = list(m.values[i].values())
    return CpuCol(e.dataType, vals, m.validity.copy())


def _h_get_map_value(e, cols, n, ansi):
    m, k = _kids(e, cols, n, ansi)
    validity = m.validity & k.validity
    objs = []
    for i in range(n):
        if not validity[i]:
            objs.append(None)
            continue
        hit = None
        for key, val in m.values[i].items():
            if _nan_eq(key, k.values[i]):
                hit = val
                break
        objs.append(hit)
    return CpuCol.from_objs(objs, e.dataType)


# -- higher-order functions ---------------------------------------------------

def _hof_flatten(e, cols, n, ansi):
    """Evaluate the lambda body over a flattened (row, element) batch."""
    a = eval_expr(e.children[0], cols, n, ansi)
    idx, elems = [], []
    for i in range(n):
        if a.validity[i] and a.values[i] is not None:
            for x in a.values[i]:
                idx.append(i)
                elems.append(x)
    m = len(idx)
    et = e.children[0]._dataType.elementType
    outer = [CpuCol(c.dtype, c.values[idx], c.validity[idx]) for c in cols]
    elem_col = CpuCol.from_objs(elems, et)
    # null elements stay null values (validity False) but rows exist
    res = eval_expr(e.body, outer + [elem_col], m, ansi)
    per_row = [[] for _ in range(n)]
    for k, i in enumerate(idx):
        per_row[i].append(res.row(k))
    return a, per_row


def _h_array_transform(e, cols, n, ansi):
    a, per_row = _hof_flatten(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if a.validity[i]:
            vals[i] = per_row[i]
    return CpuCol(e.dataType, vals, a.validity.copy())


def _h_array_filter(e, cols, n, ansi):
    a, per_row = _hof_flatten(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if a.validity[i]:
            vals[i] = [x for x, keep in zip(a.values[i], per_row[i])
                       if keep is not None and bool(keep)]
    return CpuCol(e.dataType, vals, a.validity.copy())


def _h_array_exists(e, cols, n, ansi):
    a, per_row = _hof_flatten(e, cols, n, ansi)
    out = np.zeros(n, np.bool_)
    validity = a.validity.copy()
    for i in range(n):
        if not a.validity[i]:
            continue
        preds = per_row[i]
        any_true = any(bool(p) for p in preds if p is not None)
        any_null = any(p is None for p in preds)
        out[i] = any_true
        if not any_true and any_null:
            validity[i] = False
    return CpuCol(T.BOOLEAN, out, validity)


def _h_array_forall(e, cols, n, ansi):
    a, per_row = _hof_flatten(e, cols, n, ansi)
    out = np.zeros(n, np.bool_)
    validity = a.validity.copy()
    for i in range(n):
        if not a.validity[i]:
            continue
        preds = per_row[i]
        any_false = any(not bool(p) for p in preds if p is not None)
        any_null = any(p is None for p in preds)
        out[i] = not any_false
        if not any_false and any_null:
            validity[i] = False
    return CpuCol(T.BOOLEAN, out, validity)


def _h_array_aggregate(e, cols, n, ansi):
    a = eval_expr(e.children[0], cols, n, ansi)
    acc = eval_expr(e.children[1], cols, n, ansi)
    maxw = max((len(v) for v in a.values
                if v is not None), default=0)
    for j in range(maxw):
        elems = [a.values[i][j]
                 if (a.validity[i] and a.values[i] is not None
                     and j < len(a.values[i])) else None
                 for i in range(n)]
        elem_col = CpuCol.from_objs(elems, e.children[0]._dataType.elementType)
        merged = eval_expr(e.merge, cols + [acc, elem_col], n, ansi)
        take = np.array([a.validity[i] and a.values[i] is not None
                         and j < len(a.values[i]) for i in range(n)])
        new_vals = acc.values.copy()
        new_valid = acc.validity.copy()
        for i in range(n):
            if take[i]:
                new_vals[i] = merged.values[i]
                new_valid[i] = merged.validity[i]
        acc = CpuCol(merged.dtype, new_vals, new_valid)
    if e.finish is not None:
        acc = eval_expr(e.finish, cols + [acc], n, ansi)
    return CpuCol(acc.dtype, acc.values, acc.validity & a.validity)


# -- JSON + struct expressions ----------------------------------------------
# Independent of the device path: json-module based (the device engine is a
# byte-level state machine in jsonpath.py / native C++), so differential
# tests exercise two implementations.

class _RawNum(str):
    """Number token with its raw source text preserved."""


_JSON_MISSING = object()


def _oracle_parse_json_path(path):
    import re

    if not isinstance(path, str) or not path.startswith("$"):
        return None
    token = re.compile(r"\.([^.\[]+)|\[\s*'([^']*)'\s*\]|\[(\d+)\]")
    out, i = [], 1
    while i < len(path):
        m = token.match(path, i)
        if not m:
            return None
        if m.group(1) is not None:
            if m.group(1) == "*":
                raise NotImplementedError("oracle: wildcard JSON path")
            out.append(m.group(1))
        elif m.group(2) is not None:
            out.append(m.group(2))
        else:
            out.append(int(m.group(3)))
        i = m.end()
    return out


def _oracle_json_loads(s: str):
    import json as _json

    def _reject(_):
        raise ValueError("non-standard constant")

    return _json.loads(s, parse_int=_RawNum, parse_float=_RawNum,
                       parse_constant=_reject)


def _oracle_json_ser(v) -> str:
    import json as _json

    if isinstance(v, _RawNum):
        return str(v)
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, str):
        return _json.dumps(v, ensure_ascii=False)
    if isinstance(v, list):
        return "[" + ",".join(_oracle_json_ser(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(
            _json.dumps(k, ensure_ascii=False) + ":" + _oracle_json_ser(x)
            for k, x in v.items()) + "}"
    return _json.dumps(v)


def _oracle_get_json_object(doc, path):
    if doc is None or path is None:
        return None
    steps = _oracle_parse_json_path(path)
    if steps is None:
        return None
    try:
        cur = _oracle_json_loads(doc)
    except ValueError:
        return None
    for s in steps:
        if isinstance(s, str):
            if not isinstance(cur, dict) or s not in cur:
                return None
            cur = cur[s]
        else:
            if not isinstance(cur, list) or s >= len(cur):
                return None
            cur = cur[s]
    if cur is None:
        return None
    if isinstance(cur, _RawNum):
        return str(cur)
    if cur is True:
        return "true"
    if cur is False:
        return "false"
    if isinstance(cur, str):
        return cur
    return _oracle_json_ser(cur)


def _h_get_json_object(e, cols, n, ansi):
    s, p = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    for i in range(n):
        try:
            out[i] = _oracle_get_json_object(s.row(i), p.row(i))
        except NotImplementedError:
            out[i] = None
        except RecursionError:
            out[i] = None
    return CpuCol.from_objs(list(out), T.STRING)


def _h_json_tuple(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    s = kids[0]
    vals = []
    for i in range(n):
        row = []
        doc = s.row(i)
        for k in kids[1:]:
            key = k.row(i)
            if doc is None or key is None:
                row.append(None)
                continue
            try:
                parsed = _oracle_json_loads(doc)
            except ValueError:
                row.append(None)
                continue
            v = parsed.get(key, _JSON_MISSING) if isinstance(
                parsed, dict) else _JSON_MISSING
            if v is _JSON_MISSING or v is None:
                row.append(None)
            elif isinstance(v, _RawNum):
                row.append(str(v))
            elif v is True:
                row.append("true")
            elif v is False:
                row.append("false")
            elif isinstance(v, str):
                row.append(v)
            else:
                row.append(_oracle_json_ser(v))
        vals.append(tuple(row))
    return CpuCol.from_objs(vals, e.dataType)


def _h_json_to_structs(e, cols, n, ansi):
    import json as _json

    s = _kids(e, cols, n, ansi)[0]
    fields = e.schema.fields
    vals = []
    for i in range(n):
        doc = s.row(i)
        if doc is None:
            vals.append(None)
            continue
        try:
            parsed = _json.loads(doc)
        except ValueError:
            parsed = None
        row = []
        if not isinstance(parsed, dict):
            row = [None] * len(fields)
        else:
            for f in fields:
                v = parsed.get(f.name)
                ok, sv = _oracle_convert_json_field(v, f.dataType)
                if not ok:
                    row = [None] * len(fields)
                    break
                row.append(sv)
        vals.append(tuple(row))
    return CpuCol.from_objs(vals, e.schema)


def _oracle_convert_json_field(v, dt):
    # from_json field conversion is DELIBERATELY shared with the device
    # path (expr/jsonexprs.convert_json_field): both sides parse with the
    # stdlib json module, so a separate copy would only invite silent
    # divergence, not independent verification.  The pinned expectations in
    # test_spark_semantics.py are the guard against a shared
    # misunderstanding of Spark's PERMISSIVE rules.
    from spark_rapids_tpu.expr.jsonexprs import convert_json_field

    ok, sv = convert_json_field(v, dt)
    if ok and sv is not None and isinstance(dt, T.FloatType):
        sv = np.float32(sv)
    return ok, sv


def _h_structs_to_json(e, cols, n, ansi):
    import json as _json

    s = _kids(e, cols, n, ansi)[0]
    fields = e.children[0].dataType.fields
    out = []
    for i in range(n):
        v = s.row(i)
        if v is None:
            out.append(None)
            continue
        parts = []
        for k, f in enumerate(fields):
            fv = v[k]
            if fv is None:
                continue
            key = _json.dumps(f.name, ensure_ascii=False)
            if isinstance(f.dataType, T.StringType):
                parts.append(f"{key}:{_json.dumps(fv, ensure_ascii=False)}")
            elif isinstance(f.dataType, T.BooleanType):
                parts.append(f"{key}:{'true' if fv else 'false'}")
            elif isinstance(f.dataType, (T.FloatType, T.DoubleType)):
                parts.append(f"{key}:{_json.dumps(float(fv))}")
            else:
                parts.append(f"{key}:{int(fv)}")
        out.append("{" + ",".join(parts) + "}")
    return CpuCol.from_objs(out, T.STRING)


def _h_get_struct_field(e, cols, n, ansi):
    s = _kids(e, cols, n, ansi)[0]
    k = e._field_ordinal
    ft = e.dataType
    objs = [s.values[i][k]
            if s.validity[i] and s.values[i] is not None else None
            for i in range(n)]
    return CpuCol.from_objs(objs, ft)


def _h_create_named_struct(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    vals = [tuple(k.row(i) for k in kids) for i in range(n)]
    out = CpuCol.from_objs(vals, e.dataType)
    out.validity[:] = True
    return out


def _map_hof_flatten(e, cols, n, ansi):
    """Evaluate a (k, v) lambda body over a flattened map-entry batch."""
    m = eval_expr(e.children[0], cols, n, ansi)
    idx, ks, vs = [], [], []
    for i in range(n):
        if m.validity[i] and m.values[i] is not None:
            for k, v in m.values[i].items():
                idx.append(i)
                ks.append(k)
                vs.append(v)
    cnt = len(idx)
    mt = e.children[0]._dataType
    outer = [CpuCol(c.dtype, c.values[idx], c.validity[idx]) for c in cols]
    kcol = CpuCol.from_objs(ks, mt.keyType)
    vcol = CpuCol.from_objs(vs, mt.valueType)
    res = eval_expr(e.body, outer + [kcol, vcol], cnt, ansi)
    per_row = [[] for _ in range(n)]
    for k, i in enumerate(idx):
        per_row[i].append(res.row(k))
    return m, per_row


def _h_transform_keys(e, cols, n, ansi):
    m, per_row = _map_hof_flatten(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if not m.validity[i]:
            continue
        d = {}
        for nk, v in zip(per_row[i], m.values[i].values()):
            if nk is None:
                raise RuntimeError("Cannot use null as map key")
            if any(_nan_eq(nk, ex) for ex in d):
                raise RuntimeError("Duplicate map key was found")
            d[nk] = v
        vals[i] = d
    return CpuCol(e.dataType, vals, m.validity.copy())


def _h_transform_values(e, cols, n, ansi):
    m, per_row = _map_hof_flatten(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if m.validity[i]:
            vals[i] = dict(zip(m.values[i].keys(), per_row[i]))
    return CpuCol(e.dataType, vals, m.validity.copy())


def _h_map_filter(e, cols, n, ansi):
    m, per_row = _map_hof_flatten(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if m.validity[i]:
            vals[i] = {k: v for (k, v), keep
                       in zip(m.values[i].items(), per_row[i])
                       if keep is not None and bool(keep)}
    return CpuCol(e.dataType, vals, m.validity.copy())


def _h_zip_with(e, cols, n, ansi):
    a = eval_expr(e.children[0], cols, n, ansi)
    b = eval_expr(e.children[1], cols, n, ansi)
    idx, xs, ys = [], [], []
    for i in range(n):
        if a.validity[i] and b.validity[i]:
            la = a.values[i] or []
            lb = b.values[i] or []
            for j in range(max(len(la), len(lb))):
                idx.append(i)
                xs.append(la[j] if j < len(la) else None)
                ys.append(lb[j] if j < len(lb) else None)
    cnt = len(idx)
    outer = [CpuCol(c.dtype, c.values[idx], c.validity[idx]) for c in cols]
    xcol = CpuCol.from_objs(xs, e.children[0]._dataType.elementType)
    ycol = CpuCol.from_objs(ys, e.children[1]._dataType.elementType)
    res = eval_expr(e.body, outer + [xcol, ycol], cnt, ansi)
    per_row = [[] for _ in range(n)]
    for k, i in enumerate(idx):
        per_row[i].append(res.row(k))
    vals = np.empty(n, object)
    validity = a.validity & b.validity
    for i in range(n):
        if validity[i]:
            vals[i] = per_row[i]
    return CpuCol(e.dataType, vals, validity)


def _h_map_from_arrays(e, cols, n, ansi):
    ka, va = _kids(e, cols, n, ansi)
    validity = ka.validity & va.validity
    vals = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            continue
        ks = ka.values[i] or []
        vs = va.values[i] or []
        if len(ks) != len(vs):
            raise RuntimeError(
                "key and value arrays must have the same length")
        d = {}
        for k, v in zip(ks, vs):
            if k is None:
                raise RuntimeError("Cannot use null as map key")
            if any(_nan_eq(k, ex) for ex in d):
                raise RuntimeError("Duplicate map key was found")
            d[k] = v
        vals[i] = d
    return CpuCol(e.dataType, vals, validity)


def _h_map_concat(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    validity = _null_prop_validity(kids)
    vals = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            continue
        d = {}
        for m in kids:
            for k, v in (m.values[i] or {}).items():
                if any(_nan_eq(k, ex) for ex in d):
                    raise RuntimeError("Duplicate map key was found")
                d[k] = v
        vals[i] = d
    return CpuCol(e.dataType, vals, validity)


def _h_map_contains_key(e, cols, n, ansi):
    m, key = _kids(e, cols, n, ansi)
    validity = m.validity & key.validity
    out = np.zeros(n, np.bool_)
    for i in range(n):
        if validity[i]:
            out[i] = any(_nan_eq(key.row(i), k)
                         for k in (m.values[i] or {}))
    return CpuCol(T.BOOLEAN, out, validity)


def _h_array_compact(e, cols, n, ansi):
    (a,) = _kids(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if a.validity[i]:
            vals[i] = [x for x in (a.values[i] or []) if x is not None]
    return CpuCol(e.dataType, vals, a.validity.copy())


def _h_array_append(e, cols, n, ansi):
    a, x = _kids(e, cols, n, ansi)
    prepend = type(e).__name__ == "ArrayPrepend"
    vals = np.empty(n, object)
    for i in range(n):
        if a.validity[i]:
            base = list(a.values[i] or [])
            vals[i] = ([x.row(i)] + base if prepend
                       else base + [x.row(i)])
    return CpuCol(e.dataType, vals, a.validity.copy())


def _h_make_date(e, cols, n, ansi):
    y, m, d = _kids(e, cols, n, ansi)
    validity = y.validity & m.validity & d.validity
    out = np.zeros(n, np.int32)
    for i in range(n):
        if not validity[i]:
            continue
        try:
            yy, mm, dd = int(y.values[i]), int(m.values[i]), int(d.values[i])
            if not (1 <= yy <= 9999):
                raise ValueError
            out[i] = (pydt.date(yy, mm, dd) - pydt.date(1970, 1, 1)).days
        except (ValueError, OverflowError):
            if ansi:
                raise RuntimeError("invalid date in make_date (ANSI)")
            validity[i] = False
    return CpuCol(T.DATE, out, validity)


def _h_make_timestamp(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    validity = _null_prop_validity(kids)
    y, m, d, h, mi, s = kids
    st = e.children[5].dataType
    out = np.zeros(n, np.int64)
    for i in range(n):
        if not validity[i]:
            continue
        try:
            yy, mm, dd = int(y.values[i]), int(m.values[i]), int(d.values[i])
            hh, mmin = int(h.values[i]), int(mi.values[i])
            if isinstance(st, T.DecimalType):
                micros_in_sec = int(s.values[i]) * (10 ** (6 - st.scale))
            elif isinstance(st, (T.FloatType, T.DoubleType)):
                micros_in_sec = int(round(float(s.values[i]) * 1e6))
            else:
                micros_in_sec = int(s.values[i]) * 1_000_000
            if not (1 <= yy <= 9999 and 0 <= hh <= 23 and 0 <= mmin <= 59
                    and 0 <= micros_in_sec <= 60_000_000):
                raise ValueError
            days = (pydt.date(yy, mm, dd) - pydt.date(1970, 1, 1)).days
            out[i] = (days * 86_400_000_000 + hh * 3_600_000_000
                      + mmin * 60_000_000 + micros_in_sec)
        except (ValueError, OverflowError):
            if ansi:
                raise RuntimeError("invalid timestamp in make_timestamp (ANSI)")
            validity[i] = False
    return CpuCol(T.TIMESTAMP, out, validity)


def _h_current(e, cols, n, ansi):
    if type(e).__name__ == "CurrentDate":
        return CpuCol(T.DATE,
                      np.full(n, e.captured_micros // 86_400_000_000,
                              np.int32), np.ones(n, np.bool_))
    return CpuCol(T.TIMESTAMP, np.full(n, e.captured_micros, np.int64),
                  np.ones(n, np.bool_))


def _h_timestamp_units(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    name = type(e).__name__
    validity = c.validity.copy()
    st = e.child.dataType
    out = np.zeros(n, np.int64)
    for i in range(n):
        if not validity[i]:
            continue
        v = c.values[i]
        if name == "TimestampSeconds":
            if isinstance(st, (T.FloatType, T.DoubleType)):
                f = float(v) * 1e6
                if not (math.isfinite(f) and abs(f) < 2.0 ** 63):
                    validity[i] = False
                    continue
                out[i] = int(round(f))
            elif not -9223372036854 <= int(v) <= 9223372036854:
                if ansi:
                    raise RuntimeError("timestamp_seconds overflow (ANSI)")
                validity[i] = False
            else:
                out[i] = int(v) * 1_000_000
        elif name == "TimestampMillis":
            if not -9223372036854775 <= int(v) <= 9223372036854775:
                validity[i] = False
            else:
                out[i] = int(v) * 1_000
        else:
            out[i] = int(v)
    return CpuCol(T.TIMESTAMP, out, validity)


def _h_unix_units(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    name = type(e).__name__
    div = {"UnixSeconds": 1_000_000, "UnixMillis": 1_000,
           "UnixMicros": 1}[name]
    out = np.array([int(v) // div for v in
                    np.where(c.validity, c.values, 0)], np.int64)
    return CpuCol(T.LONG, out, c.validity.copy())


def _h_unix_date(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    name = type(e).__name__
    dt = T.INT if name == "UnixDate" else T.DATE
    return CpuCol(dt, c.values.astype(np.int32), c.validity.copy())


def _h_weekday(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    days = (c.values.astype(np.int64) if isinstance(e.child.dataType,
                                                    T.DateType)
            else c.values.astype(np.int64) // 86_400_000_000)
    return CpuCol(T.INT, ((days + 3) % 7).astype(np.int32),
                  c.validity.copy())


def _h_to_date_ts(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    want_date = type(e).__name__ == "ToDate"
    ct = e.child.dataType
    validity = c.validity.copy()
    out = np.zeros(n, np.int32 if want_date else np.int64)
    for i in range(n):
        if not validity[i]:
            continue
        v = c.values[i]
        if isinstance(ct, T.DateType):
            out[i] = int(v) if want_date else int(v) * 86_400_000_000
        elif isinstance(ct, T.TimestampType):
            out[i] = int(v) // 86_400_000_000 if want_date else int(v)
        else:
            r = _str_to_date_py(v) if want_date else _str_to_ts_py(v)
            if r is None:
                validity[i] = False
            else:
                out[i] = r
    return CpuCol(T.DATE if want_date else T.TIMESTAMP, out, validity)


def _h_regexp_extract_all(e, cols, n, ansi):
    import re as _re

    c = eval_expr(e.children[0], cols, n, ansi)
    pat = _re.compile(_java_regex_to_python(str(e.children[1].value)))
    out = np.empty(n, object)
    for i in range(n):
        v = c.values[i]
        if v is not None and c.validity[i]:
            out[i] = [m for m in pat.findall(v) if m != ""]
    return CpuCol(e.dataType, out, c.validity.copy())


def _h_overlay(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    s, r, p, ln = kids
    validity = _null_prop_validity(kids)
    out = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            continue
        sv, rv = str(s.values[i]), str(r.values[i])
        pos0 = int(p.values[i]) - 1
        replen = int(ln.values[i])
        if replen < 0:
            replen = len(rv)
        pre = sv[:max(pos0, 0)][:len(sv)]
        tail = sv[min(max(pos0 + replen, 0), len(sv)):]
        out[i] = pre + rv + tail
    return CpuCol(T.STRING, out, validity)


def _h_find_in_set(e, cols, n, ansi):
    s, lst = _kids(e, cols, n, ansi)
    validity = s.validity & lst.validity
    out = np.zeros(n, np.int32)
    for i in range(n):
        if not validity[i]:
            continue
        sv = str(s.values[i])
        if "," in sv:
            out[i] = 0
            continue
        parts = str(lst.values[i]).split(",")
        out[i] = parts.index(sv) + 1 if sv in parts else 0
    return CpuCol(T.INT, out, validity)


def _h_elt(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    idx = kids[0]
    out = np.empty(n, object)
    validity = np.zeros(n, np.bool_)
    for i in range(n):
        if not idx.validity[i]:
            continue
        k = int(idx.values[i])
        if 1 <= k <= len(kids) - 1 and kids[k].validity[i]:
            out[i] = kids[k].values[i]
            validity[i] = True
    return CpuCol(T.STRING, out, validity)


def _h_space(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    for i in range(n):
        if c.validity[i]:
            out[i] = " " * max(int(c.values[i]), 0)
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_ltrim_rtrim(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    left = type(e).__name__ == "StringTrimLeft"
    out = np.empty(n, object)
    for i in range(n):
        if c.validity[i]:
            v = str(c.values[i])
            out[i] = v.lstrip(" ") if left else v.rstrip(" ")
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_bround(e, cols, n, ansi):
    c, s = _kids(e, cols, n, ansi)
    ct = e.children[0].dataType
    if ct.is_integral:
        return c
    out = np.zeros(n, np.float64)
    validity = c.validity & s.validity
    for i in range(n):
        if validity[i]:
            sc = 10.0 ** int(s.values[i])
            out[i] = np.round(float(c.values[i]) * sc) / sc
    return CpuCol(e.dataType, out, validity)


def _h_width_bucket(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    validity = _null_prop_validity(kids)
    out = np.zeros(n, np.int64)
    for i in range(n):
        if not validity[i]:
            continue
        v, lo, hi = (float(kids[j].values[i]) for j in range(3))
        nb = int(kids[3].values[i])
        if nb <= 0 or not all(math.isfinite(x) for x in (v, lo, hi)) \
                or lo == hi:
            validity[i] = False
            continue
        if lo < hi:
            if v < lo:
                out[i] = 0
            elif v >= hi:
                out[i] = nb + 1
            else:
                out[i] = int((v - lo) / ((hi - lo) / nb)) + 1
        else:
            if v > lo:
                out[i] = 0
            elif v <= hi:
                out[i] = nb + 1
            else:
                out[i] = int((lo - v) / ((lo - hi) / nb)) + 1
        out[i] = min(max(out[i], 0), nb + 1)
    return CpuCol(T.LONG, out, validity)


def _h_factorial(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.zeros(n, np.int64)
    validity = c.validity.copy()
    for i in range(n):
        if validity[i]:
            v = int(c.values[i])
            if 0 <= v <= 20:
                out[i] = math.factorial(v)
            else:
                validity[i] = False
    return CpuCol(T.LONG, out, validity)


def _h_bit_count(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    ct = e.child.dataType
    out = np.zeros(n, np.int32)
    for i in range(n):
        if not c.validity[i]:
            continue
        if isinstance(ct, T.BooleanType):
            out[i] = 1 if c.values[i] else 0
        else:
            # Java widens (sign-extends) before Long.bitCount
            out[i] = bin(int(c.values[i]) & ((1 << 64) - 1)).count("1")
    return CpuCol(T.INT, out, c.validity.copy())


def _h_nvl2(e, cols, n, ansi):
    a, b, c = _kids(e, cols, n, ansi)
    vals = np.where(a.validity, b.values, c.values)
    validity = np.where(a.validity, b.validity, c.validity)
    return CpuCol(e.dataType, vals, validity.astype(np.bool_))


def _h_nullif(e, cols, n, ansi):
    a, b = _kids(e, cols, n, ansi)
    validity = a.validity.copy()
    for i in range(n):
        if a.validity[i] and b.validity[i] \
                and _nan_eq(a.values[i], b.values[i]):
            validity[i] = False
    return CpuCol(e.dataType, a.values.copy(), validity)


def _h_trunc_timestamp(e, cols, n, ansi):
    from spark_rapids_tpu.expr.datetime import TruncTimestamp as _TT

    fmt_c, c = _kids(e, cols, n, ansi)
    unit = str(e.children[0].value).lower() \
        if getattr(e.children[0], "value", None) is not None else ""
    out = np.zeros(n, np.int64)
    validity = c.validity.copy()
    US_DAY = 86_400_000_000
    for i in range(n):
        if not validity[i]:
            continue
        micros = int(c.values[i])
        if unit in _TT._TIME:
            q = _TT._TIME[unit]
            out[i] = (micros // q) * q
        elif unit in _TT._DAY_FMTS:
            days = micros // US_DAY
            dt0 = pydt.date(1970, 1, 1) + pydt.timedelta(days=days)
            u = _TT._DAY_FMTS[unit]
            if u == "year":
                d2 = dt0.replace(month=1, day=1)
            elif u == "quarter":
                d2 = dt0.replace(month=(dt0.month - 1) // 3 * 3 + 1, day=1)
            elif u == "month":
                d2 = dt0.replace(day=1)
            else:
                d2 = dt0 - pydt.timedelta(days=dt0.weekday())
            out[i] = (d2 - pydt.date(1970, 1, 1)).days * US_DAY
        else:
            validity[i] = False
    return CpuCol(T.TIMESTAMP, out, validity)


def _h_timestamp_add(e, cols, n, ansi):
    from spark_rapids_tpu.expr.datetime import TimestampAdd as _TA

    k, c = _kids(e, cols, n, ansi)
    validity = k.validity & c.validity
    out = np.zeros(n, np.int64)
    US_DAY = 86_400_000_000
    for i in range(n):
        if not validity[i]:
            continue
        micros = int(c.values[i])
        kk = int(k.values[i])
        if e.unit in _TA._FIXED:
            out[i] = micros + kk * _TA._FIXED[e.unit]
            continue
        mult = {"month": 1, "quarter": 3, "year": 12}.get(e.unit)
        if mult is None:
            validity[i] = False
            continue
        days = micros // US_DAY
        tod = micros - days * US_DAY
        d0 = pydt.date(1970, 1, 1) + pydt.timedelta(days=days)
        tot = d0.year * 12 + (d0.month - 1) + kk * mult
        ny, nm = tot // 12, tot % 12 + 1
        import calendar

        nd = min(d0.day, calendar.monthrange(ny, nm)[1])
        out[i] = ((pydt.date(ny, nm, nd) - pydt.date(1970, 1, 1)).days
                  * US_DAY + tod)
    return CpuCol(T.TIMESTAMP, out, validity)


def _h_timestamp_diff(e, cols, n, ansi):
    from spark_rapids_tpu.expr.datetime import TimestampAdd as _TA

    a, b = _kids(e, cols, n, ansi)
    validity = a.validity & b.validity
    out = np.zeros(n, np.int64)
    US_DAY = 86_400_000_000
    for i in range(n):
        if not validity[i]:
            continue
        s, t = int(a.values[i]), int(b.values[i])
        fixed = _TA._FIXED.get(e.unit)
        if fixed is not None:
            d = t - s
            out[i] = d // fixed if d >= 0 else -((-d) // fixed)
            continue
        mult = {"month": 1, "quarter": 3, "year": 12}.get(e.unit)
        if mult is None:
            validity[i] = False
            continue
        sd, ed = s // US_DAY, t // US_DAY
        d1 = pydt.date(1970, 1, 1) + pydt.timedelta(days=sd)
        d2 = pydt.date(1970, 1, 1) + pydt.timedelta(days=ed)
        months = (d2.year * 12 + d2.month) - (d1.year * 12 + d1.month)
        stod, etod = s - sd * US_DAY, t - ed * US_DAY
        fwd = t >= s
        short = ((d2.day < d1.day or (d2.day == d1.day and etod < stod))
                 if fwd else
                 (d2.day > d1.day or (d2.day == d1.day and etod > stod)))
        months += (-1 if short and fwd else (1 if short and not fwd else 0))
        out[i] = months // mult if months >= 0 else -((-months) // mult)
    return CpuCol(T.LONG, out, validity)


def _h_convert_timezone(e, cols, n, ansi):
    from spark_rapids_tpu.tzdb import zone_tables

    (c,) = _kids(e, cols, n, ansi)
    tsrc = zone_tables(e.source_tz)
    ttgt = zone_tables(e.target_tz)
    out = np.zeros(n, np.int64)
    for i in range(n):
        if not c.validity[i]:
            continue
        micros = int(c.values[i])
        secs = micros // 1_000_000
        j = np.searchsorted(tsrc["wall_starts"], secs, side="right") - 1
        off1 = int(tsrc["offsets"][max(min(j, len(tsrc["offsets"]) - 1), 0)])
        utc = micros - off1 * 1_000_000
        us = utc // 1_000_000
        j2 = np.searchsorted(ttgt["utc_instants"], us, side="right") - 1
        off2 = int(ttgt["offsets"][max(min(j2, len(ttgt["offsets"]) - 1), 0)])
        out[i] = utc + off2 * 1_000_000
    return CpuCol(T.TIMESTAMP, out, c.validity.copy())


def _h_month_day_name(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    days = _date_of(c, e.child.dataType)
    months = ("Jan", "Feb", "Mar", "Apr", "May", "Jun",
              "Jul", "Aug", "Sep", "Oct", "Nov", "Dec")
    dows = ("Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun")
    out = np.empty(n, object)
    for i in range(n):
        if not c.validity[i]:
            continue
        if type(e).__name__ == "MonthName":
            out[i] = months[days[i].month - 1]
        else:
            out[i] = dows[days[i].weekday()]
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_date_part(e, cols, n, ansi):
    if e._inner is None:
        return CpuCol(T.INT, np.zeros(n, np.int32), np.zeros(n, np.bool_))
    return eval_expr(e._inner, cols, n, ansi)


def _h_url_codec(e, cols, n, ansi):
    from urllib.parse import quote_plus, unquote_plus
    import re as _re

    (c,) = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    validity = c.validity.copy()
    enc = type(e).__name__ == "UrlEncode"
    for i in range(n):
        if not validity[i]:
            continue
        s = str(c.values[i])
        if enc:
            out[i] = quote_plus(s)
        else:
            if _re.search(r"%(?![0-9A-Fa-f]{2})", s):
                validity[i] = False
                continue
            out[i] = unquote_plus(s)
    return CpuCol(T.STRING, out, validity)


def _h_json_array_length(e, cols, n, ansi):
    import json as _json

    (c,) = _kids(e, cols, n, ansi)
    out = np.zeros(n, np.int32)
    validity = np.zeros(n, np.bool_)
    for i in range(n):
        if not c.validity[i]:
            continue
        try:
            v = _json.loads(str(c.values[i]))
        except ValueError:
            continue
        if isinstance(v, list):
            out[i] = len(v)
            validity[i] = True
    return CpuCol(T.INT, out, validity)


def _h_json_object_keys(e, cols, n, ansi):
    import json as _json

    (c,) = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    validity = np.zeros(n, np.bool_)
    for i in range(n):
        if not c.validity[i]:
            continue
        try:
            v = _json.loads(str(c.values[i]))
        except ValueError:
            continue
        if isinstance(v, dict):
            out[i] = [str(k)[:e.KEY_WIDTH] for k in list(v)[:e.MAX_KEYS]]
            validity[i] = True
    return CpuCol(e.dataType, out, validity)


def _h_format_string(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    fmt = str(e.children[0].value)
    pyfmt = fmt.replace("%%", "\x00")
    out = np.empty(n, object)
    validity = np.zeros(n, np.bool_)
    for i in range(n):
        row = []
        null = False
        for k, ce in zip(kids[1:], e.children[1:]):
            if not k.validity[i]:
                null = True
                break
            v = k.values[i]
            if isinstance(ce.dataType, (T.FloatType, T.DoubleType)):
                row.append(float(v))
            elif isinstance(ce.dataType, T.StringType):
                row.append(str(v))
            else:
                row.append(int(v))
        if null:
            continue
        try:
            out[i] = (pyfmt % tuple(row)).replace("\x00", "%")
            validity[i] = True
        except (TypeError, ValueError):
            continue
    return CpuCol(T.STRING, out, validity)


def _h_uuid(e, cols, n, ansi):
    base = np.uint64((e.seed * 0x9E3779B97F4A7C15 + 0xA5A5A5A5)
                     & 0xFFFFFFFFFFFFFFFF)
    out = np.empty(n, object)
    with np.errstate(over="ignore"):
        for i in range(n):
            def mix(z):
                z = np.uint64(z + np.uint64(0x9E3779B97F4A7C15))
                z = np.uint64((z ^ (z >> np.uint64(30)))
                              * np.uint64(0xBF58476D1CE4E5B9))
                z = np.uint64((z ^ (z >> np.uint64(27)))
                              * np.uint64(0x94D049BB133111EB))
                return np.uint64(z ^ (z >> np.uint64(31)))

            hi = int(mix(base + np.uint64(i * 2)))
            lo = int(mix(base + np.uint64(i * 2 + 1)))
            hi = (hi & 0xFFFFFFFFFFFF0FFF) | 0x4000
            lo = (lo & 0x3FFFFFFFFFFFFFFF) | (1 << 63)
            s = f"{hi:016x}{lo:016x}"
            out[i] = f"{s[:8]}-{s[8:12]}-{s[12:16]}-{s[16:20]}-{s[20:]}"
    return CpuCol(T.STRING, out, np.ones(n, np.bool_))


def _h_pi_e(e, cols, n, ansi):
    v = math.pi if type(e).__name__ == "Pi" else math.e
    return CpuCol(T.DOUBLE, np.full(n, v, np.float64),
                  np.ones(n, np.bool_))


def _h_mask(e, cols, n, ansi):
    (c,) = [eval_expr(e.children[0], cols, n, ansi)]

    def rep_of(i):
        v = getattr(e.children[i], "value", None)
        return None if v is None else str(v)[0]

    up, lo, dg, ot = rep_of(1), rep_of(2), rep_of(3), rep_of(4)
    out = np.empty(n, object)
    for i in range(n):
        if not c.validity[i]:
            continue
        res = []
        for ch in str(c.values[i]):
            if "A" <= ch <= "Z":
                res.append(up if up is not None else ch)
            elif "a" <= ch <= "z":
                res.append(lo if lo is not None else ch)
            elif "0" <= ch <= "9":
                res.append(dg if dg is not None else ch)
            else:
                res.append(ot if ot is not None else ch)
        out[i] = "".join(res)
    return CpuCol(T.STRING, out, c.validity.copy())


def _h_ilike(e, cols, n, ansi):
    import re

    from spark_rapids_tpu.regex.transpiler import like_to_regex

    l, _ = _kids(e, cols, n, ansi)
    rx = re.compile(like_to_regex(str(e.right.value).lower()))
    out = np.array(
        [bool(rx.fullmatch("".join(
            chr(ord(ch) + 32) if "A" <= ch <= "Z" else ch for ch in v)))
         if v is not None else False for v in l.values], np.bool_)
    return CpuCol(T.BOOLEAN, out, l.validity.copy())


def _h_regexp_span(e, cols, n, ansi):
    import re as _re

    c = eval_expr(e.children[0], cols, n, ansi)
    pat = _re.compile(_java_regex_to_python(str(e.children[1].value)))
    name = type(e).__name__
    def nonempty_matches(v):
        # full matches (not group contents), skipping zero-length hits —
        # the device greedy span scan's non-overlapping leftmost contract
        return [m for m in pat.finditer(v) if m.group(0) != ""]

    if name == "RegExpCount":
        out = np.array([len(nonempty_matches(v)) if v is not None else 0
                        for v in c.values], np.int32)
        return CpuCol(T.INT, out, c.validity.copy())
    if name == "RegExpInStr":
        out = np.zeros(n, np.int32)
        for i, v in enumerate(c.values):
            if v is None or not c.validity[i]:
                continue
            ms = nonempty_matches(v)
            out[i] = (ms[0].start() + 1) if ms else 0
        return CpuCol(T.INT, out, c.validity.copy())
    out = np.empty(n, object)
    validity = c.validity.copy()
    for i, v in enumerate(c.values):
        if v is None or not validity[i]:
            validity[i] = False
            continue
        ms = nonempty_matches(v)
        if ms:
            out[i] = ms[0].group(0)
        else:
            validity[i] = False
    return CpuCol(T.STRING, out, validity)


def _h_split_part(e, cols, n, ansi):
    s, d, k = _kids(e, cols, n, ansi)
    delim = str(e.children[1].value)
    validity = s.validity & d.validity & k.validity
    out = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            continue
        parts = str(s.values[i]).split(delim)
        want = int(k.values[i])
        if want < 0:
            want = len(parts) + want + 1
        out[i] = parts[want - 1] if 1 <= want <= len(parts) else ""
    return CpuCol(T.STRING, out, validity)


def _h_get(e, cols, n, ansi):
    a, idx = _kids(e, cols, n, ansi)
    validity = a.validity & idx.validity
    out = np.empty(n, object)
    ok = np.zeros(n, np.bool_)
    for i in range(n):
        if not validity[i]:
            continue
        arr = a.values[i] or []
        j = int(idx.values[i])
        if 0 <= j < len(arr) and arr[j] is not None:
            out[i] = arr[j]
            ok[i] = True
    return CpuCol.from_objs(
        [out[i] if ok[i] else None for i in range(n)], e.dataType)


def _h_array_size(e, cols, n, ansi):
    (a,) = _kids(e, cols, n, ansi)
    out = np.array([len(a.values[i]) if a.validity[i]
                    and a.values[i] is not None else 0
                    for i in range(n)], np.int32)
    return CpuCol(T.INT, out, a.validity.copy())




def _h_hive_hash(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)

    def one(c, i):
        if not c.validity[i]:
            return 0
        v = c.values[i]
        dt = c.dtype
        if isinstance(dt, T.BooleanType):
            return 1 if v else 0
        if isinstance(dt, T.LongType):
            u = int(v) & _M64
            return _to_i32((u ^ (u >> 32)) & _M32)
        if isinstance(dt, T.FloatType):
            import struct

            f = np.float32(v)
            bits = struct.unpack("<i", struct.pack("<f", float(f)))[0]
            if math.isnan(float(f)):
                bits = 0x7FC00000
            return _to_i32(bits & _M32)
        if isinstance(dt, T.DoubleType):
            import struct

            bits = struct.unpack("<q", struct.pack("<d", float(v)))[0]
            if math.isnan(float(v)):
                bits = 0x7FF8000000000000
            u = bits & _M64
            return _to_i32((u ^ (u >> 32)) & _M32)
        if isinstance(dt, T.StringType):
            h = 0
            for b in str(v).encode("utf-8"):
                sb = b - 256 if b >= 128 else b   # Java signed bytes
                h = (h * 31 + sb) & _M32
            return _to_i32(h)
        return _to_i32(int(v) & _M32)

    out = np.zeros(n, np.int32)
    for i in range(n):
        h = 0
        for c in kids:
            h = (h * 31 + one(c, i)) & _M32
        out[i] = _to_i32(h)
    return CpuCol(T.INT, out, np.ones(n, np.bool_))


def _to_i32(u):
    return u - (1 << 32) if u >= (1 << 31) else u


def _h_array_insert(e, cols, n, ansi):
    arr, _p, item = _kids(e, cols, n, ansi)
    pos = int(e.pos_literal)
    vals = np.empty(n, object)
    validity = arr.validity.copy()
    for i in range(n):
        if not arr.validity[i]:
            continue
        a = list(arr.values[i])
        v = item.row(i)
        L = len(a)
        if pos > 0:
            idx = pos - 1
            if idx >= L:
                vals[i] = a + [None] * (idx - L) + [v]
            else:
                vals[i] = a[:idx] + [v] + a[idx:]
        else:
            # Spark 3.5 default: -1 appends (0-based position L + pos + 1)
            idx = L + pos + 1
            if idx < 0:
                vals[i] = [v] + [None] * (-idx) + a
            else:
                vals[i] = a[:idx] + [v] + a[idx:]
    return CpuCol(e.dataType, vals, validity)


def _h_flatten(e, cols, n, ansi):
    vals = np.empty(n, object)
    validity = np.ones(n, np.bool_)
    if getattr(e, "_absorbed", False):
        members = [eval_expr(m, cols, n, ansi) for m in e.children]
        for i in range(n):
            if any(not m.validity[i] for m in members):
                validity[i] = False
                continue
            out = []
            for m in members:
                out.extend(m.values[i])
            vals[i] = out
        return CpuCol(e.dataType, vals, validity)
    # general array<array> child (CPU-only shape): a null inner array
    # nulls the whole result, matching Spark flatten
    (c,) = _kids(e, cols, n, ansi)
    for i in range(n):
        if not c.validity[i]:
            validity[i] = False
            continue
        out = []
        bad = False
        for sub in c.values[i]:
            if sub is None:
                bad = True
                break
            out.extend(sub)
        if bad:
            validity[i] = False
        else:
            vals[i] = out
    return CpuCol(e.dataType, vals, validity)


def _h_str_to_map(e, cols, n, ansi):
    import re as _re

    kids = _kids(e, cols, n, ansi)
    rp = _re.compile(_java_regex_to_python(e._pair))
    rk = _re.compile(_java_regex_to_python(e._kv))
    vals = np.empty(n, object)
    validity = kids[0].validity.copy()
    for i in range(n):
        if not validity[i]:
            continue
        m = {}
        for entry in rp.split(str(kids[0].values[i])):
            parts = rk.split(entry, maxsplit=1)
            if parts[0] in m:
                raise RuntimeError("Duplicate map key was found")
            m[parts[0]] = parts[1] if len(parts) > 1 else None
        vals[i] = m
    return CpuCol(e.dataType, vals, validity)


def _h_schema_of_json(e, cols, n, ansi):
    s = e._folded()
    return CpuCol(T.STRING, np.array([s] * n, object),
                  np.ones(n, np.bool_))


def _h_xpath(e, cols, n, ansi):
    from spark_rapids_tpu.expr.xpath import xpath_eval

    kids = _kids(e, cols, n, ansi)
    path = e._path()
    vals = np.empty(n, object)
    validity = np.zeros(n, np.bool_)
    for i in range(n):
        v = kids[0].row(i)
        res = e._convert(xpath_eval(v, path)) if path is not None else None
        if res is not None:
            vals[i] = res
            validity[i] = True
    return CpuCol(e.dataType, vals, validity)




def _h_try_arith(e, cols, n, ansi):
    """try_add/subtract/multiply/divide: the ANSI op with per-row
    errors-as-null (twin of arithmetic._TryMixin)."""
    base = type(e).__name__[3:]
    l, r = _kids(e, cols, n, ansi)
    dt = e.dataType
    validity = (l.validity & r.validity).copy()
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        a = np.where(validity, l.values.astype(np.float64), 0.0)
        b = np.where(validity, r.values.astype(np.float64), 1.0)
        if base == "Divide":
            zero = b == 0.0
            validity &= ~zero
            out = a / np.where(zero, 1.0, b)
        elif base == "Add":
            out = a + b
        elif base == "Subtract":
            out = a - b
        else:
            out = a * b
        return CpuCol(dt, out.astype(T.storage_dtype(dt)), validity)
    if isinstance(dt, T.DecimalType):
        lt, rt = e.left.dataType, e.right.dataType
        out = np.zeros(n, object)
        for i in range(n):
            if not validity[i]:
                out[i] = 0
                continue
            a, b = int(l.values[i]), int(r.values[i])
            if base in ("Add", "Subtract"):
                sa = a * 10 ** (dt.scale - lt.scale)
                sb = b * 10 ** (dt.scale - rt.scale)
                v = sa + sb if base == "Add" else sa - sb
            elif base == "Multiply":
                v = a * b
            else:
                if b == 0:
                    validity[i] = False
                    out[i] = 0
                    continue
                from decimal import ROUND_HALF_UP, Decimal, localcontext

                with localcontext() as lc:
                    lc.prec = 78
                    q = (Decimal(a).scaleb(-lt.scale)
                         / Decimal(b).scaleb(-rt.scale))
                    v = int(q.scaleb(dt.scale).quantize(
                        Decimal(1), rounding=ROUND_HALF_UP))
            if abs(v) >= 10 ** dt.precision:
                validity[i] = False
                v = 0
            out[i] = v
        return CpuCol(dt, out, validity)
    out = np.zeros(n, T.storage_dtype(dt))
    lo, rng = _JMIN[type(dt)], _JRANGE[type(dt)]
    for i in range(n):
        if not validity[i]:
            continue
        a, b = int(l.values[i]), int(r.values[i])
        v = a + b if base == "Add" else a - b if base == "Subtract" \
            else a * b
        wrapped = ((v - lo) % rng) + lo
        if wrapped != v:
            validity[i] = False
        else:
            out[i] = v
    return CpuCol(dt, out, validity)


def _h_bit_get(e, cols, n, ansi):
    l, r = _kids(e, cols, n, ansi)
    bits = {T.ByteType: 8, T.ShortType: 16, T.IntegerType: 32,
            T.LongType: 64}[type(e.left.dataType)]
    validity = l.validity & r.validity
    out = np.zeros(n, np.int8)
    for i in range(n):
        if not validity[i]:
            continue
        pos = int(r.values[i])
        if pos < 0 or pos >= bits:
            raise RuntimeError(
                f"Invalid bit position: must be in [0, {bits})")
        out[i] = (int(l.values[i]) >> pos) & 1
    return CpuCol(T.BYTE, out, validity)


def _h_assert_true(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    for i in range(n):
        if not (c.validity[i] and bool(c.values[i])):
            raise RuntimeError(
                f"'{e.child.sql_string()}' is not true!")
    return CpuCol(T.NullType(), np.zeros(n, np.int8),
                  np.zeros(n, np.bool_))


def _h_typeof(e, cols, n, ansi):
    s = e.child.dataType.simpleString
    return CpuCol(T.STRING, np.array([s] * n, object),
                  np.ones(n, np.bool_))





def _h_map_entries(e, cols, n, ansi):
    (m,) = _kids(e, cols, n, ansi)
    vals = np.empty(n, object)
    for i in range(n):
        if m.validity[i]:
            vals[i] = [tuple(kv) for kv in m.values[i].items()]
    return CpuCol(e.dataType, vals, m.validity.copy())


def _h_arrays_zip(e, cols, n, ansi):
    kids = _kids(e, cols, n, ansi)
    validity = _null_prop_validity(kids)
    vals = np.empty(n, object)
    for i in range(n):
        if not validity[i]:
            continue
        arrs = [k.values[i] for k in kids]
        ln = max((len(a) for a in arrs), default=0)
        vals[i] = [tuple(a[j] if j < len(a) else None for a in arrs)
                   for j in range(ln)]
    return CpuCol(e.dataType, vals, validity)


def _h_map_zip_with(e, cols, n, ansi):
    m1 = eval_expr(e.children[0], cols, n, ansi)
    m2 = eval_expr(e.children[1], cols, n, ansi)
    idx, ks, v1s, v2s = [], [], [], []
    validity = m1.validity & m2.validity
    for i in range(n):
        if not validity[i]:
            continue
        d1 = m1.values[i] or {}
        d2 = m2.values[i] or {}
        keys = list(d1.keys()) + [k for k in d2 if k not in d1]
        for k in keys:
            idx.append(i)
            ks.append(k)
            v1s.append(d1.get(k))
            v2s.append(d2.get(k))
    cnt = len(idx)
    outer = [CpuCol(c.dtype, c.values[idx], c.validity[idx]) for c in cols]
    m1t = e.children[0]._dataType
    m2t = e.children[1]._dataType
    kcol = CpuCol.from_objs(ks, m1t.keyType)
    c1 = CpuCol.from_objs(v1s, m1t.valueType)
    c2 = CpuCol.from_objs(v2s, m2t.valueType)
    res = eval_expr(e.body, outer + [kcol, c1, c2], cnt, ansi)
    per_row = [{} for _ in range(n)]
    for k, i in enumerate(idx):
        per_row[i][ks[k]] = res.row(k)
    vals = np.empty(n, object)
    for i in range(n):
        if validity[i]:
            vals[i] = per_row[i]
    return CpuCol(e.dataType, vals, validity)


_HANDLERS = {
    "BoundReference": _h_bound,
    "Literal": _h_literal,
    "Alias": _h_alias,
    "Add": _h_binarith, "Subtract": _h_binarith, "Multiply": _h_binarith,
    "Divide": _h_binarith, "IntegralDivide": _h_binarith,
    "Remainder": _h_binarith, "Pmod": _h_binarith,
    "UnaryMinus": _h_unaryminus, "Abs": _h_abs,
    "EqualTo": _h_comparison, "LessThan": _h_comparison,
    "LessThanOrEqual": _h_comparison, "GreaterThan": _h_comparison,
    "GreaterThanOrEqual": _h_comparison, "EqualNullSafe": _h_nullsafe_eq,
    "And": _h_and, "Or": _h_or, "Not": _h_not,
    "IsNull": _h_isnull, "IsNotNull": _h_isnotnull, "IsNaN": _h_isnan,
    "In": _h_in,
    "If": _h_if, "CaseWhen": _h_casewhen, "Coalesce": _h_coalesce,
    "Nvl": _h_coalesce, "NaNvl": _h_nanvl,
    "Greatest": _h_greatest, "Least": _h_greatest,
    "Cast": _h_cast,
    "Sqrt": _h_unary_math, "Exp": _h_unary_math, "Log": _h_unary_math,
    "Log10": _h_unary_math, "Sin": _h_unary_math, "Cos": _h_unary_math,
    "Tan": _h_unary_math, "Asin": _h_unary_math, "Acos": _h_unary_math,
    "Atan": _h_unary_math, "Signum": _h_unary_math,
    "Sinh": _h_unary_math, "Cosh": _h_unary_math, "Tanh": _h_unary_math,
    "Asinh": _h_unary_math, "Acosh": _h_unary_math, "Atanh": _h_unary_math,
    "Cbrt": _h_unary_math, "Log2": _h_unary_math, "Log1p": _h_unary_math,
    "Expm1": _h_unary_math, "Rint": _h_unary_math, "Cot": _h_unary_math,
    "Csc": _h_unary_math, "Sec": _h_unary_math,
    "ToDegrees": _h_unary_math, "ToRadians": _h_unary_math,
    "Atan2": _h_binary_math, "Hypot": _h_binary_math,
    "Logarithm": _h_binary_math,
    "BitwiseAnd": _h_bitwise, "BitwiseOr": _h_bitwise,
    "BitwiseXor": _h_bitwise, "BitwiseNot": _h_bitwise,
    "ShiftLeft": _h_bitwise, "ShiftRight": _h_bitwise,
    "ShiftRightUnsigned": _h_bitwise,
    "Pow": _h_pow, "Floor": _h_floorceil, "Ceil": _h_floorceil,
    "Round": _h_round,
    "Length": _h_length, "Upper": _h_upperlower, "Lower": _h_upperlower,
    "Substring": _h_substring, "Concat": _h_concat,
    "StartsWith": _h_startswith, "EndsWith": _h_startswith,
    "Contains": _h_startswith, "StringTrim": _h_trim, "Like": _h_like,
    "RLike": _h_rlike,
    "Year": _h_datefield, "Month": _h_datefield, "DayOfMonth": _h_datefield,
    "DayOfWeek": _h_datefield, "DayOfYear": _h_datefield,
    "Quarter": _h_datefield, "LastDay": _h_lastday,
    "WeekOfYear": _h_weekofyear, "AddMonths": _h_addmonths,
    "MonthsBetween": _h_monthsbetween, "TruncDate": _h_truncdate,
    "NextDay": _h_nextday, "FromUnixTime": _h_format_time,
    "DateFormat": _h_format_time,
    "Hour": _h_timefield, "Minute": _h_timefield, "Second": _h_timefield,
    "DateAdd": _h_dateadd, "DateSub": _h_dateadd, "DateDiff": _h_datediff,
    "UnixTimestamp": _h_unixts, "ToUnixTimestamp": _h_unixts,
    "MakeDate": _h_make_date, "MakeTimestamp": _h_make_timestamp,
    "CurrentDate": _h_current, "CurrentTimestamp": _h_current,
    "TimestampSeconds": _h_timestamp_units,
    "TimestampMillis": _h_timestamp_units,
    "TimestampMicros": _h_timestamp_units,
    "UnixSeconds": _h_unix_units, "UnixMillis": _h_unix_units,
    "UnixMicros": _h_unix_units,
    "UnixDate": _h_unix_date, "DateFromUnixDate": _h_unix_date,
    "WeekDay": _h_weekday,
    "ToDate": _h_to_date_ts, "ToTimestamp": _h_to_date_ts,
    "TruncTimestamp": _h_trunc_timestamp,
    "TimestampAdd": _h_timestamp_add, "TimestampDiff": _h_timestamp_diff,
    "ConvertTimezone": _h_convert_timezone,
    "MonthName": _h_month_day_name, "DayName": _h_month_day_name,
    "LocalTimestamp": _h_current, "DatePart": _h_date_part,
    "UrlEncode": _h_url_codec, "UrlDecode": _h_url_codec,
    "JsonArrayLength": _h_json_array_length,
    "JsonObjectKeys": _h_json_object_keys,
    "FormatString": _h_format_string, "Uuid": _h_uuid,
    "Pi": _h_pi_e, "EulerNumber": _h_pi_e,
    "Mask": _h_mask, "ILike": _h_ilike,
    "RegExpCount": _h_regexp_span, "RegExpInStr": _h_regexp_span,
    "RegExpSubStr": _h_regexp_span, "SplitPart": _h_split_part,
    "Get": _h_get, "ArraySize": _h_array_size,
    "Murmur3Hash": _h_hashexpr, "XxHash64": _h_hashexpr,
    "HiveHash": _h_hive_hash,
    "TryAdd": _h_try_arith, "TrySubtract": _h_try_arith,
    "TryMultiply": _h_try_arith, "TryDivide": _h_try_arith,
    "BitGet": _h_bit_get, "AssertTrue": _h_assert_true,
    "TypeOf": _h_typeof,
    "ArrayInsert": _h_array_insert,
    "Flatten": _h_flatten,
    "StrToMap": _h_str_to_map,
    "SchemaOfJson": _h_schema_of_json,
    "XPathList": _h_xpath, "XPathString": _h_xpath,
    "XPathBoolean": _h_xpath, "XPathShort": _h_xpath,
    "XPathInt": _h_xpath, "XPathLong": _h_xpath,
    "XPathFloat": _h_xpath, "XPathDouble": _h_xpath,
    "Reverse": _h_reverse, "InitCap": _h_initcap, "Ascii": _h_ascii,
    "Chr": _h_chr, "StringReplace": _h_replace,
    "StringTranslate": _h_translate, "StringInstr": _h_instr,
    "StringLocate": _h_locate, "StringLPad": _h_pad, "StringRPad": _h_pad,
    "StringRepeat": _h_repeat, "ConcatWs": _h_concat_ws,
    "OctetLength": _h_octetbit, "BitLength": _h_octetbit,
    "UserDefinedExpression": _h_udf,
    "Size": _h_size, "GetArrayItem": _h_get_array_item,
    "ElementAt": _h_element_at, "ArrayContains": _h_array_contains,
    "CreateArray": _h_create_array, "ArrayMin": _h_array_minmax,
    "ArrayMax": _h_array_minmax,
    "StringLeft": _h_leftright, "StringRight": _h_leftright,
    "SubstringIndex": _h_substring_index,
    "StringSplit": _h_string_split,
    "ArrayJoin": _h_array_join,
    "RegExpReplace": _h_regexp_replace,
    "RegExpExtract": _h_regexp_extract,
    "RegExpExtractAll": _h_regexp_extract_all,
    "Overlay": _h_overlay, "FindInSet": _h_find_in_set, "Elt": _h_elt,
    "StringSpace": _h_space,
    "StringTrimLeft": _h_ltrim_rtrim, "StringTrimRight": _h_ltrim_rtrim,
    "BRound": _h_bround, "WidthBucket": _h_width_bucket,
    "Factorial": _h_factorial, "BitwiseCount": _h_bit_count,
    "Nvl2": _h_nvl2, "NullIf": _h_nullif,
    "GetJsonObject": _h_get_json_object,
    "JsonTuple": _h_json_tuple,
    "JsonToStructs": _h_json_to_structs,
    "StructsToJson": _h_structs_to_json,
    "GetStructField": _h_get_struct_field,
    "CreateNamedStruct": _h_create_named_struct,
    "ArrayPosition": _h_array_position,
    "ArrayRemove": _h_array_remove,
    "ArrayDistinct": _h_array_distinct,
    "ArraysOverlap": _h_arrays_overlap,
    "ArrayUnion": _h_array_union,
    "ArrayIntersect": _h_array_intersect,
    "ArrayExcept": _h_array_except,
    "Slice": _h_slice,
    "SortArray": _h_sort_array,
    "ArrayRepeat": _h_array_repeat,
    "Sequence": _h_sequence,
    "CreateMap": _h_create_map,
    "MapKeys": _h_map_keys,
    "MapValues": _h_map_values,
    "GetMapValue": _h_get_map_value,
    "BloomFilterMightContain": _h_bloom_might_contain,
    "FromUTCTimestamp": _h_utc_shift,
    "ToUTCTimestamp": _h_utc_shift,
    "Md5": _str_map_handler(_o_md5),
    "Sha1": _str_map_handler(_o_sha1),
    "Sha2": _str_map_handler(_o_sha2),
    "Crc32": _h_crc32,
    "Base64": _str_map_handler(_o_base64),
    "UnBase64": _str_map_handler(_o_unbase64),
    "Encode": _str_map_handler(_o_encode),
    "Decode": _str_map_handler(_o_decode),
    "Hex": _h_hex,
    "Unhex": _str_map_handler(_o_unhex),
    "Bin": _h_bin,
    "Conv": _str_map_handler(_o_conv),
    "FormatNumber": _h_format_number,
    "ParseUrl": _str_map_handler(_o_parse_url),
    "Soundex": _str_map_handler(_o_soundex),
    "Levenshtein": _h_levenshtein,
    "MonotonicallyIncreasingID": _h_mono_id,
    "SparkPartitionID": _h_partition_id,
    "Rand": _h_rand,
    "RaiseError": _h_raise_error,
    "ArrayTransform": _h_array_transform,
    "TransformKeys": _h_transform_keys,
    "TransformValues": _h_transform_values,
    "MapFilter": _h_map_filter,
    "ZipWith": _h_zip_with,
    "MapZipWith": _h_map_zip_with,
    "MapEntries": _h_map_entries,
    "ArraysZip": _h_arrays_zip,
    "MapFromArrays": _h_map_from_arrays,
    "MapConcat": _h_map_concat,
    "MapContainsKey": _h_map_contains_key,
    "ArrayCompact": _h_array_compact,
    "ArrayAppend": _h_array_append,
    "ArrayPrepend": _h_array_append,
    "ArrayFilter": _h_array_filter,
    "ArrayExists": _h_array_exists,
    "ArrayForAll": _h_array_forall,
    "ArrayAggregate": _h_array_aggregate,
}


# ===========================================================================
# Plan executor
# ===========================================================================

def execute_cpu_plan(plan: PN.SparkPlan, ansi: bool = False) -> Tuple[CpuBatch, int]:
    """Execute a plan tree fully on CPU.  Returns (columns, num_rows)."""
    if hasattr(plan, "materialize_cpu"):
        # TpuMaterializedScan: columnar->row boundary under a CPU node
        return plan.materialize_cpu()
    name = type(plan).__name__
    if isinstance(plan, PN.LocalTableScan):
        cols = [CpuCol.from_host(h) for h in plan.host_columns]
        n = cols[0].n if cols else 0
        return cols, n
    if isinstance(plan, PN.FileSourceScan):
        return _cpu_file_scan(plan)
    if isinstance(plan, PN.CachedRelation):
        cached = plan.cache_slot.get("cpu")
        if cached is None:
            cached = execute_cpu_plan(plan.child, ansi)
            plan.cache_slot["cpu"] = cached
        return cached
    if isinstance(plan, PN.RangeNode):
        vals = np.arange(plan.start, plan.end, plan.step, dtype=np.int64)
        return [CpuCol(T.LONG, vals, np.ones(len(vals), np.bool_))], len(vals)
    if isinstance(plan, PN.Generate):
        return _cpu_generate(plan, ansi)
    if isinstance(plan, PN.Expand):
        cols, n = execute_cpu_plan(plan.child, ansi)
        pieces = [[eval_expr(e, cols, n, ansi) for e in ps]
                  for ps in plan.projections]
        merged = []
        for ci in range(len(plan.projections[0])):
            vals = np.concatenate([p[ci].values for p in pieces])
            valid = np.concatenate([p[ci].validity for p in pieces])
            merged.append(CpuCol(pieces[0][ci].dtype, vals, valid))
        return merged, n * len(plan.projections)
    if isinstance(plan, PN.BroadcastNestedLoopJoin):
        return _cpu_bnlj(plan, ansi)
    if isinstance(plan, PN.Sample):
        from spark_rapids_tpu.expr.misc import Rand as _DevRand

        cols, n = execute_cpu_plan(plan.children[0], ansi)
        z = _DevRand._u64_for_rows(plan.seed, 0, n)
        u = (z >> np.uint64(11)).astype(np.float64) / float(1 << 53)
        keep = u < plan.fraction
        idx = np.nonzero(keep)[0]
        return [CpuCol(c.dtype, c.values[idx], c.validity[idx])
                for c in cols], len(idx)
    if isinstance(plan, PN.Project):
        cols, n = execute_cpu_plan(plan.child, ansi)
        return [eval_expr(e, cols, n, ansi) for e in plan.exprs], n
    if isinstance(plan, PN.Filter):
        cols, n = execute_cpu_plan(plan.child, ansi)
        pred = eval_expr(plan.condition, cols, n, ansi)
        keep = pred.values.astype(bool) & pred.validity
        out = [CpuCol(c.dtype, c.values[keep], c.validity[keep]) for c in cols]
        return out, int(keep.sum())
    if isinstance(plan, PN.HashAggregate):
        return _cpu_aggregate(plan, ansi)
    if isinstance(plan, (PN.SortMergeJoin, PN.ShuffledHashJoin,
                         PN.BroadcastHashJoin)):
        return _cpu_join(plan, ansi)
    if isinstance(plan, PN.Sort):
        return _cpu_sort(plan, ansi)
    if isinstance(plan, PN.Window):
        return _cpu_window(plan, ansi)
    if isinstance(plan, (PN.GlobalLimit, PN.LocalLimit)):
        cols, n = execute_cpu_plan(plan.children[0], ansi)
        k = min(plan.n, n)
        return [CpuCol(c.dtype, c.values[:k], c.validity[:k]) for c in cols], k
    if isinstance(plan, PN.Union):
        parts = [execute_cpu_plan(c, ansi) for c in plan.children]
        ncols = len(parts[0][0])
        out = []
        for ci in range(ncols):
            vals = np.concatenate([p[0][ci].values for p in parts])
            valid = np.concatenate([p[0][ci].validity for p in parts])
            out.append(CpuCol(parts[0][0][ci].dtype, vals, valid))
        return out, sum(p[1] for p in parts)
    if isinstance(plan, (PN.Exchange, PN.BroadcastExchange)):
        return execute_cpu_plan(plan.children[0], ansi)
    if isinstance(plan, PN.InsertIntoHadoopFsRelation):
        from spark_rapids_tpu.io.writer import cpu_write

        cpu_write(plan, ansi)
        return [], 0
    raise NotImplementedError(f"oracle plan node {name}")


def _cpu_file_scan(plan: PN.FileSourceScan):
    import pyarrow.parquet as pq
    import pyarrow.csv as pacsv

    import os

    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.io import faults as IOF

    # the oracle honors the SAME per-file tolerance confs as the TPU
    # scan (differential runs must read the same surviving file set);
    # skips here bump no counters and write no quarantine — only the
    # device scan's accounting is the product surface
    conf = get_conf()
    tol = IOF.scan_tolerance(conf)

    def read_one(p):
        if os.path.isdir(p):
            import pyarrow.dataset as ds

            return ds.dataset(
                p, format=plan.fmt, partitioning="hive",
                exclude_invalid_files=True).to_table(
                columns=[f.name for f in plan.output.fields])
        if plan.fmt == "parquet":
            from spark_rapids_tpu.io.scan import read_parquet_file

            return read_parquet_file(
                p, [f.name for f in plan.output.fields])
        if plan.fmt == "orc":
            import pyarrow.orc as paorc

            return paorc.ORCFile(p).read(
                columns=[f.name for f in plan.output.fields])
        if plan.fmt in ("csv", "json"):
            import pyarrow as pa

            from spark_rapids_tpu.io.text import (read_csv_spark,
                                                  read_json_spark)

            rd = read_csv_spark if plan.fmt == "csv" else read_json_spark
            tcols, _ = rd(p, plan.output, plan.options)
            return pa.table(
                {f.name: c.to_arrow()
                 for f, c in zip(plan.output.fields, tcols)})
        if plan.fmt == "avro":
            import pyarrow as pa

            from spark_rapids_tpu.io.avro import read_avro_columns

            acols, astruct = read_avro_columns(p, plan.output)
            return pa.table(
                {f.name: c.to_arrow()
                 for f, c in zip(astruct.fields, acols)})
        raise NotImplementedError(plan.fmt)

    tables = []
    for p in plan.paths:
        try:
            with IOF.file_context(p, plan.fmt, "cpu-oracle"):
                tables.append(read_one(p))
        except Exception as e:
            IOF.handle_scan_error(e, p, plan.fmt, "cpu-oracle", tol,
                                  conf, count_skips=False)
    import pyarrow as pa

    if not tables:
        cols = [CpuCol.from_host(HostColumn.from_pylist([], f.dataType))
                for f in plan.output.fields]
        return cols, 0
    tbl = pa.concat_tables(tables)
    cols = []
    for f in plan.output.fields:
        h = HostColumn.from_arrow(tbl.column(f.name), f.dataType)
        cols.append(CpuCol.from_host(h))
    return cols, tbl.num_rows


def _group_key(cols: List[CpuCol], i: int):
    out = []
    for c in cols:
        if not c.validity[i]:
            out.append(("\0NULL",))
        else:
            v = c.values[i]
            if isinstance(v, float) and math.isnan(v):
                out.append(("\0NAN",))
            else:
                out.append(v)
    return tuple(out)


def _cpu_aggregate(plan: PN.HashAggregate, ansi: bool):
    cols, n = execute_cpu_plan(plan.child, ansi)
    gcols = [eval_expr(g, cols, n, ansi) for g in plan.grouping]
    mode = plan.mode
    child_names = plan.child.output.field_names()
    if mode == PN.AggregateMode.FINAL:
        # inputs are partial buffers from the child by name
        acols = []
        for a in plan.aggregates:
            if a.func == "avg":
                acols.append((cols[child_names.index(a.result_name + "_sum")],
                              cols[child_names.index(a.result_name + "_count")]))
            elif a.func in PN.MOMENT_BUFFERS:
                acols.append(tuple(
                    cols[child_names.index(a.result_name + s)]
                    for s in PN.MOMENT_BUFFERS[a.func]))
            elif a.func == "approx_count_distinct":
                acols.append(cols[child_names.index(a.result_name + "_hll")])
            else:
                nm = a.result_name
                acols.append(cols[child_names.index(nm)])
    else:
        acols = []
        for a in plan.aggregates:
            if a.child is None:
                acols.append(None)
            elif a.child2 is not None:
                acols.append((eval_expr(a.child, cols, n, ansi),
                              eval_expr(a.child2, cols, n, ansi)))
            else:
                acols.append(eval_expr(a.child, cols, n, ansi))
    groups: Dict[tuple, int] = {}
    order: List[tuple] = []
    rows_per_group: List[List[int]] = []
    if gcols:
        for i in range(n):
            k = _group_key(gcols, i)
            gi = groups.get(k)
            if gi is None:
                gi = len(order)
                groups[k] = gi
                order.append(k)
                rows_per_group.append([])
            rows_per_group[gi].append(i)
        ng = len(order)
    else:
        ng = 1
        rows_per_group = [list(range(n))]
    out_cols: List[CpuCol] = []
    for ki, g in enumerate(plan.grouping):
        vals = []
        valid = np.ones(ng, np.bool_)
        for gi in range(ng):
            i = rows_per_group[gi][0]
            if gcols[ki].validity[i]:
                vals.append(gcols[ki].values[i])
            else:
                vals.append(None)
                valid[gi] = False
        dtype = (object if gcols[ki].values.dtype == object
                 else gcols[ki].values.dtype)
        arr = np.array([v if v is not None else
                        (None if dtype == object else 0) for v in vals],
                       dtype=dtype)
        out_cols.append(CpuCol(g.dataType, arr, valid))
    for a, ac, f in zip(plan.aggregates, acols,
                        plan.output.fields[len(plan.grouping):]
                        if mode != PN.AggregateMode.PARTIAL else
                        _partial_field_groups(plan)):
        if mode == PN.AggregateMode.PARTIAL:
            for c in _agg_partial(a, ac, rows_per_group, f):
                out_cols.append(c)
        elif mode == PN.AggregateMode.FINAL:
            out_cols.append(_agg_final(a, ac, rows_per_group))
        else:
            vals, valid = _agg_one(a, ac, rows_per_group, ansi)
            out_cols.append(CpuCol(a.result_type, vals, valid))
    return out_cols, ng


def _partial_field_groups(plan: PN.HashAggregate):
    """Yield the output field (or field pair for avg) per aggregate."""
    fields = plan.output.fields[len(plan.grouping):]
    i = 0
    for a in plan.aggregates:
        if a.func == "avg":
            yield (fields[i], fields[i + 1])
            i += 2
        elif a.func in PN.MOMENT_BUFFERS:
            k = len(PN.MOMENT_BUFFERS[a.func])
            yield tuple(fields[i:i + k])
            i += k
        else:
            yield (fields[i],)
            i += 1


# -- moment/covariance/HLL/bloom helpers (spec-mirrors of the device path;
# hashing goes through the oracle's OWN xxhash64) -----------------------------

_HLL_P = PN.HLL_DEFAULT_P


def _oracle_xxh64(dtype, value, seed: int) -> int:
    kind, x = _hash_input(dtype, value)
    h = _xxh_update(kind, x, seed & _M64)
    return h & _M64


def _scaled_floats(ac: CpuCol, idxs) -> List[float]:
    scale = (10.0 ** -ac.dtype.scale
             if isinstance(ac.dtype, T.DecimalType) else 1.0)
    return [float(ac.values[i]) * scale for i in idxs if ac.validity[i]]


def _moment_stats(xs: List[float]):
    n = len(xs)
    if n == 0:
        return 0.0, 0.0, 0.0, 0.0, 0.0
    m = sum(xs) / n
    m2 = sum((x - m) ** 2 for x in xs)
    m3 = sum((x - m) ** 3 for x in xs)
    m4 = sum((x - m) ** 4 for x in xs)
    return float(n), m, m2, m3, m4


def _cov_stats(pairs):
    n = len(pairs)
    if n == 0:
        return 0.0, 0.0, 0.0, 0.0, 0.0, 0.0
    xa = sum(x for x, _ in pairs) / n
    ya = sum(y for _, y in pairs) / n
    ck = sum((x - xa) * (y - ya) for x, y in pairs)
    xm2 = sum((x - xa) ** 2 for x, _ in pairs)
    ym2 = sum((y - ya) ** 2 for _, y in pairs)
    return float(n), xa, ya, ck, xm2, ym2


def _cov_pairs(ac, idxs):
    xc, yc = ac
    xs = _scaled_floats_map(xc)
    ys = _scaled_floats_map(yc)
    return [(xs(i), ys(i)) for i in idxs
            if xc.validity[i] and yc.validity[i]]


def _scaled_floats_map(c: CpuCol):
    scale = (10.0 ** -c.dtype.scale
             if isinstance(c.dtype, T.DecimalType) else 1.0)
    return lambda i: float(c.values[i]) * scale


def _finalize_moment(func: str, n, m2, m3, m4):
    """-> (value, valid); Spark nullOnDivideByZero semantics."""
    if n <= 0 or m2 == 0.0:
        return 0.0, False
    if func == "skewness":
        return math.sqrt(n) * m3 / (m2 ** 1.5), True
    return n * m4 / (m2 * m2) - 3.0, True


def _finalize_cov(func: str, n, ck, xm2, ym2):
    if n <= 0:
        return 0.0, False
    if func == "corr":
        denom = math.sqrt(xm2 * ym2)
        if denom == 0.0:
            return float("nan"), True
        return ck / denom, True
    if func == "covar_pop":
        return ck / n, True
    if n <= 1:
        return 0.0, False
    return ck / (n - 1.0), True


def _hll_regs(ac: CpuCol, idxs) -> List[int]:
    p = _HLL_P
    m = 1 << p
    regs = [0] * m
    for i in idxs:
        if not ac.validity[i]:
            continue
        h = _oracle_xxh64(ac.dtype, ac.values[i], 42)
        idx = h >> (64 - p)
        w = (h << p) & _M64
        clz = 64 - w.bit_length()
        rank = min(clz + 1, 65 - p)
        regs[idx] = max(regs[idx], rank)
    return regs


def _hll_estimate(regs: List[int]) -> int:
    m = len(regs)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv = sum(2.0 ** -r for r in regs)
    raw = alpha * m * m / inv
    zeros = regs.count(0)
    if raw <= 2.5 * m and zeros > 0:
        est = m * math.log(m / zeros)
    else:
        est = raw
    return int(round(est))


def _wrap64(x: int) -> int:
    return ((x + 2**63) % 2**64) - 2**63


def _bloom_words(ac: CpuCol, idxs, num_items: int, num_bits: int):
    words = [0] * (num_bits // 64)
    k = max(1, round(num_bits / num_items * math.log(2)))
    for i in idxs:
        if not ac.validity[i]:
            continue
        h1 = _wrap64(_oracle_xxh64(ac.dtype, ac.values[i], 42))
        h2 = _wrap64(_oracle_xxh64(ac.dtype, ac.values[i], 77))
        for j in range(k):
            bit = _wrap64(h1 + j * h2) % num_bits
            words[bit // 64] |= 1 << (bit % 64)
    return [_wrap64(w) for w in words]


def _percentile_sorted(ac: CpuCol, idxs):
    vals = [(ac.values[i]) for i in idxs if ac.validity[i]]
    return sorted(vals, key=lambda v: (isinstance(v, float)
                                       and math.isnan(v), v))


def _agg_partial(a: PN.AggregateExpression, ac: Optional[CpuCol],
                 rows_per_group, fields):
    ng = len(rows_per_group)
    if a.func == "avg":
        sum_f, cnt_f = fields
        sums, cnts = [], []
        valid = np.ones(ng, np.bool_)
        dec = isinstance(sum_f.dataType, T.DecimalType)
        for gi in range(ng):
            idxs = [i for i in rows_per_group[gi] if ac.validity[i]]
            cnts.append(len(idxs))
            if not idxs:
                sums.append(None)
                valid[gi] = False
            elif dec:
                sums.append(sum(int(ac.values[i]) for i in idxs))
            else:
                sums.append(float(np.sum(np.array(
                    [ac.values[i] for i in idxs], np.float64))))
        svals = (np.array([s if s is not None else 0 for s in sums],
                          dtype=object if dec else np.float64))
        yield CpuCol(sum_f.dataType, svals, valid)
        yield CpuCol(cnt_f.dataType, np.array(cnts, np.int64),
                     np.ones(ng, np.bool_))
        return
    if a.func in PN.MOMENT_BUFFERS:
        suffixes = PN.MOMENT_BUFFERS[a.func]
        bufs = [[] for _ in suffixes]
        mvalid = np.ones(ng, np.bool_)
        for gi in range(ng):
            if a.func in PN.COVARIANCE_FUNCS \
                    or a.func in PN.REGR_FUNCS:
                pair_ac = ((ac[1], ac[0])
                           if a.func in PN.REGR_FUNCS else ac)
                pairs = _cov_pairs(pair_ac, rows_per_group[gi])
                stats = _cov_stats(pairs)
                nvals = stats[0]
            else:
                xs = _scaled_floats(ac, rows_per_group[gi])
                n_, m, m2, m3, m4 = _moment_stats(xs)
                stats = {"_n": n_, "_avg": m, "_m2": m2, "_m3": m3,
                         "_m4": m4}
                stats = tuple(stats[s] for s in suffixes)
                nvals = n_
            if nvals == 0:
                mvalid[gi] = False
            for b, v in zip(bufs, stats):
                b.append(v)
        for si, (s, f) in enumerate(zip(suffixes, fields)):
            valid = np.ones(ng, np.bool_) if s == "_n" else mvalid
            yield CpuCol(f.dataType, np.array(bufs[si], np.float64),
                         valid.copy())
        return
    if a.func == "approx_count_distinct":
        (f,) = fields
        vals = np.empty(ng, object)
        for gi in range(ng):
            vals[gi] = _hll_regs(ac, rows_per_group[gi])
        yield CpuCol(f.dataType, vals, np.ones(ng, np.bool_))
        return
    # count/sum/min/max/first/last/count_if partials share the final shape
    vals, valid = _agg_one(a, ac, rows_per_group, False)
    (f,) = fields
    yield CpuCol(f.dataType, vals, valid)


def _agg_final(a: PN.AggregateExpression, ac, rows_per_group) -> CpuCol:
    """Merge partial buffers (collect_* never reaches FINAL — the planner
    builds it single-phase COMPLETE)."""
    ng = len(rows_per_group)
    if a.func == "avg":
        sc, cc = ac
        dec = isinstance(a.result_type, T.DecimalType)
        out, valid = [], np.ones(ng, np.bool_)
        for gi in range(ng):
            idxs = rows_per_group[gi]
            total_cnt = sum(int(cc.values[i]) for i in idxs if cc.validity[i])
            if total_cnt == 0:
                out.append(None)
                valid[gi] = False
                continue
            if dec:
                import decimal as pydec

                rt: T.DecimalType = a.result_type
                s = sum(int(sc.values[i]) for i in idxs if sc.validity[i])
                in_scale = rt.scale - 4
                with pydec.localcontext() as lctx:
                    lctx.prec = 78
                    q = pydec.Decimal(s).scaleb(-in_scale) / total_cnt
                    out.append(int(q.scaleb(rt.scale).quantize(
                        pydec.Decimal(1), rounding=pydec.ROUND_HALF_UP)))
            else:
                s = sum(float(sc.values[i]) for i in idxs if sc.validity[i])
                out.append(s / total_cnt)
        if dec:
            return CpuCol(a.result_type, np.array(out, object), valid)
        return CpuCol(a.result_type,
                      np.array([v if v is not None else 0 for v in out],
                               np.float64), valid)
    if a.func in PN.VARIANCE_FUNCS:
        cn, ca, cm = ac
        out = np.zeros(ng, np.float64)
        valid = np.ones(ng, np.bool_)
        for gi in range(ng):
            idxs = [i for i in rows_per_group[gi]
                    if cn.validity[i] and float(cn.values[i]) > 0]
            ntot = sum(float(cn.values[i]) for i in idxs)
            if ntot == 0:
                valid[gi] = False
                continue
            mean = sum(float(cn.values[i]) * float(ca.values[i])
                       for i in idxs) / ntot
            m2 = sum(float(cm.values[i])
                     + float(cn.values[i]) * (float(ca.values[i]) - mean) ** 2
                     for i in idxs)
            v, ok = _finalize_variance(a.func, ntot, m2)
            out[gi] = v
            valid[gi] = ok
        return CpuCol(a.result_type, out, valid)
    if a.func in PN.HIGHER_MOMENT_FUNCS:
        cn, ca, cm2, cm3 = ac[:4]
        cm4 = ac[4] if len(ac) > 4 else None
        out = np.zeros(ng, np.float64)
        valid = np.ones(ng, np.bool_)
        for gi in range(ng):
            idxs = [i for i in rows_per_group[gi]
                    if cn.validity[i] and float(cn.values[i]) > 0]
            ntot = sum(float(cn.values[i]) for i in idxs)
            if ntot == 0:
                valid[gi] = False
                continue
            mean = sum(float(cn.values[i]) * float(ca.values[i])
                       for i in idxs) / ntot
            m2 = m3 = m4 = 0.0
            for i in idxs:
                ni = float(cn.values[i])
                di = float(ca.values[i]) - mean
                m2i = float(cm2.values[i])
                m3i = float(cm3.values[i])
                m2 += m2i + ni * di * di
                m3 += m3i + 3.0 * m2i * di + ni * di ** 3
                if cm4 is not None:
                    m4 += (float(cm4.values[i]) + 4.0 * m3i * di
                           + 6.0 * m2i * di * di + ni * di ** 4)
            v, ok = _finalize_moment(a.func, ntot, m2, m3, m4)
            out[gi] = v
            valid[gi] = ok
        return CpuCol(a.result_type, out, valid)
    if a.func in PN.COVARIANCE_FUNCS or a.func in PN.REGR_FUNCS:
        cn, cx, cy, cc = ac[:4]
        is_regr = a.func in PN.REGR_FUNCS
        is_corr = a.func == "corr" or is_regr
        out = np.zeros(ng, np.float64)
        valid = np.ones(ng, np.bool_)
        for gi in range(ng):
            idxs = [i for i in rows_per_group[gi]
                    if cn.validity[i] and float(cn.values[i]) > 0]
            ntot = sum(float(cn.values[i]) for i in idxs)
            if ntot == 0:
                valid[gi] = False
                continue
            xavg = sum(float(cn.values[i]) * float(cx.values[i])
                       for i in idxs) / ntot
            yavg = sum(float(cn.values[i]) * float(cy.values[i])
                       for i in idxs) / ntot
            ck = xm2 = ym2 = 0.0
            for i in idxs:
                ni = float(cn.values[i])
                dxi = float(cx.values[i]) - xavg
                dyi = float(cy.values[i]) - yavg
                ck += float(cc.values[i]) + ni * dxi * dyi
                if is_corr:
                    xm2 += float(ac[4].values[i]) + ni * dxi * dxi
                    ym2 += float(ac[5].values[i]) + ni * dyi * dyi
            if is_regr:
                v, ok = _finalize_regr(a.func, ntot, xavg, yavg, ck,
                                       xm2, ym2)
            else:
                v, ok = _finalize_cov(a.func, ntot, ck, xm2, ym2)
            out[gi] = v
            valid[gi] = ok
        if a.func == "regr_count":
            return CpuCol(T.LONG, out.astype(np.int64),
                          np.ones(ng, np.bool_))
        return CpuCol(a.result_type, out, valid)
    if a.func == "approx_count_distinct":
        out = np.zeros(ng, np.int64)
        for gi in range(ng):
            m = 1 << _HLL_P
            merged = [0] * m
            for i in rows_per_group[gi]:
                if not ac.validity[i]:
                    continue
                regs = ac.values[i]
                for j in range(m):
                    if regs[j] > merged[j]:
                        merged[j] = regs[j]
            out[gi] = _hll_estimate(merged)
        return CpuCol(a.result_type, out, np.ones(ng, np.bool_))
    merge_func = {"count": "sum", "count_star": "sum", "sum": "sum",
                  "min": "min", "max": "max", "first": "first",
                  "last": "last", "count_if": "sum",
                  "bool_and": "min", "bool_or": "max",
                  "any_value": "first", "bit_and": "bit_and",
                  "bit_or": "bit_or", "bit_xor": "bit_xor"}[a.func]
    merged = PN.AggregateExpression(merge_func, None, a.result_name,
                                    a.result_type)
    vals, valid = _agg_one(merged, ac, rows_per_group, False)
    if a.func in ("count", "count_star", "count_if"):
        valid = np.ones(ng, np.bool_)
        vals = np.array([v if valid[i] else 0 for i, v in enumerate(vals)],
                        np.int64)
    return CpuCol(a.result_type, vals, valid)


def _finalize_variance(func: str, n: float, m2: float):
    """-> (value, is_valid).  Spark CentralMomentAgg semantics with the
    default nullOnDivideByZero (samp of a single row -> NULL)."""
    den = n if func.endswith("_pop") else n - 1.0
    if den <= 0:
        return 0.0, False
    v = m2 / den
    return (v if func.startswith("var") else math.sqrt(v)), True


def _finalize_regr(func, n, xa, ya, ck, xm2, ym2):
    """-> (value, valid); Spark regr_* null/zero semantics."""
    if func == "regr_count":
        return float(n), True
    if n <= 0:
        return 0.0, False
    if func == "regr_avgx":
        return xa, True
    if func == "regr_avgy":
        return ya, True
    if func == "regr_sxx":
        return xm2, True
    if func == "regr_syy":
        return ym2, True
    if func == "regr_sxy":
        return ck, True
    if xm2 == 0.0:
        return 0.0, False
    slope = ck / xm2
    if func == "regr_slope":
        return slope, True
    if func == "regr_intercept":
        return ya - slope * xa, True
    if ym2 == 0.0:
        return 1.0, True
    return (ck * ck) / (xm2 * ym2), True


def _agg_one(a: PN.AggregateExpression, ac: Optional[CpuCol],
             rows_per_group, ansi):
    ng = len(rows_per_group)
    func = a.func
    if func == "any_value":
        func = "first"
    if func in ("bool_and", "bool_or"):
        func = "min" if func == "bool_and" else "max"
    if func == "count_star":
        return (np.array([len(r) for r in rows_per_group], np.int64),
                np.ones(ng, np.bool_))
    if func in ("collect_list", "collect_set"):
        vals = np.empty(ng, object)
        for gi in range(ng):
            xs = [ac.row(i) for i in rows_per_group[gi] if ac.validity[i]]
            if func == "collect_set":
                # NaN == NaN for set membership (Spark total order); output
                # ascending with NaN last, matching the TPU kernel's keys
                has_nan = any(isinstance(x, float) and math.isnan(x)
                              for x in xs)
                rest = sorted({x for x in xs
                               if not (isinstance(x, float)
                                       and math.isnan(x))})
                xs = rest + ([float("nan")] if has_nan else [])
            vals[gi] = xs
        return vals, np.ones(ng, np.bool_)
    if func == "bloom_filter_agg":
        vals = np.empty(ng, object)
        for gi in range(ng):
            vals[gi] = _bloom_words(ac, rows_per_group[gi],
                                    int(a.args[0]), int(a.args[1]))
        return vals, np.ones(ng, np.bool_)
    out = []
    valid = np.ones(ng, np.bool_)
    dec = isinstance(a.result_type, T.DecimalType)
    if isinstance(ac, tuple):  # covariance/regr family: two inputs
        is_regr = func in PN.REGR_FUNCS
        for gi in range(ng):
            # regr_f(y, x): the independent x is the SECOND argument
            pair_ac = (ac[1], ac[0]) if is_regr else ac
            pairs = _cov_pairs(pair_ac, rows_per_group[gi])
            n_, xa, ya, ck, xm2, ym2 = _cov_stats(pairs)
            if is_regr:
                v, ok = _finalize_regr(func, n_, xa, ya, ck, xm2, ym2)
            else:
                v, ok = _finalize_cov(func, n_, ck, xm2, ym2)
            out.append(v if ok else None)
            valid[gi] = ok
        if func == "regr_count":
            return (np.array([int(v) if v is not None else 0
                              for v in out], np.int64), valid)
        return (np.array([v if v is not None else 0.0 for v in out],
                         np.float64), valid)
    for gi in range(ng):
        idxs = [i for i in rows_per_group[gi] if ac.validity[i]]
        if func == "count":
            out.append(len(idxs))
            continue
        if func == "count_if":
            out.append(sum(1 for i in idxs if bool(ac.values[i])))
            continue
        if func == "approx_count_distinct":
            out.append(_hll_estimate(_hll_regs(ac, rows_per_group[gi])))
            continue
        if func in ("first", "last"):
            # Spark First/Last default ignoreNulls=false: nulls count
            all_rows = rows_per_group[gi]
            i = all_rows[0] if func == "first" else all_rows[-1]
            if ac.validity[i]:
                out.append(ac.values[i])
            else:
                out.append(None)
                valid[gi] = False
            continue
        if not idxs:
            out.append(None)
            valid[gi] = False
            continue
        vs = [ac.values[i] for i in idxs]
        if func == "sum":
            out.append(sum(int(v) for v in vs) if dec or
                       isinstance(a.result_type, T.LongType)
                       else float(np.sum(np.array(vs, np.float64))))
        elif func == "min":
            out.append(_minmax(vs, ac.dtype, mx=False))
        elif func == "max":
            out.append(_minmax(vs, ac.dtype, mx=True))
        elif func == "avg":
            if isinstance(ac.dtype, T.DecimalType):
                import decimal as pydec

                s = sum(int(v) for v in vs)
                rt: T.DecimalType = a.result_type
                q = (pydec.Decimal(s).scaleb(-ac.dtype.scale)
                     / pydec.Decimal(len(vs)))
                out.append(int(q.scaleb(rt.scale).quantize(
                    pydec.Decimal(1), rounding=pydec.ROUND_HALF_UP)))
            else:
                out.append(float(np.mean(np.array(vs, np.float64))))
        elif func == "first":
            out.append(vs[0])
        elif func == "last":
            out.append(vs[-1])
        elif func in PN.VARIANCE_FUNCS:
            vscale = (10.0 ** -ac.dtype.scale
                      if isinstance(ac.dtype, T.DecimalType) else 1.0)
            xs = [float(v) * vscale for v in vs]
            m = sum(xs) / len(xs)
            m2 = sum((x - m) ** 2 for x in xs)
            v, ok = _finalize_variance(func, float(len(xs)), m2)
            if ok:
                out.append(v)
            else:
                out.append(None)
                valid[gi] = False
        elif func in PN.HIGHER_MOMENT_FUNCS:
            xs = _scaled_floats(ac, idxs)
            n_, m, m2, m3, m4 = _moment_stats(xs)
            v, ok = _finalize_moment(func, n_, m2, m3, m4)
            if ok:
                out.append(v)
            else:
                out.append(None)
                valid[gi] = False
        elif func in ("percentile", "median"):
            xs = _percentile_sorted(ac, idxs)
            if not xs:
                out.append(None)
                valid[gi] = False
                continue
            pscale = (10.0 ** -ac.dtype.scale
                      if isinstance(ac.dtype, T.DecimalType) else 1.0)
            p = 0.5 if func == "median" else float(a.args[0])
            r = p * (len(xs) - 1)
            lo, hi = int(math.floor(r)), int(math.ceil(r))
            frac = r - lo
            out.append((float(xs[lo]) * (1 - frac)
                        + float(xs[hi]) * frac) * pscale)
        elif func == "approx_percentile":
            xs = _percentile_sorted(ac, idxs)
            if not xs:
                out.append(None)
                valid[gi] = False
                continue
            p = float(a.args[0])
            out.append(xs[int(math.floor(p * (len(xs) - 1)))])
        elif func in ("bit_and", "bit_or", "bit_xor"):
            acc = -1 if func == "bit_and" else 0
            for i in idxs:
                v = int(ac.values[i])
                acc = acc & v if func == "bit_and" else (
                    acc | v if func == "bit_or" else acc ^ v)
            out.append(acc)
        else:
            raise NotImplementedError(func)
    if dec or isinstance(a.result_type, T.StringType):
        vals = np.array([v if v is not None else None for v in out], object)
    else:
        sdt = T.storage_dtype(a.result_type)
        if a.result_type.is_integral:
            # Spark sum(long) wraps silently in non-ANSI mode (Java +)
            out = [((int(v) + 2 ** 63) % 2 ** 64) - 2 ** 63
                   if v is not None else None for v in out]
        vals = np.array([v if v is not None else 0 for v in out], sdt)
    return vals, valid


def _minmax(vs, dtype, mx):
    if isinstance(dtype, T.StringType):
        key = lambda s: s.encode()
        return (max if mx else min)(vs, key=key)
    fv = [v for v in vs]
    floats = [v for v in fv if isinstance(v, float)]
    if floats and any(math.isnan(v) for v in floats):
        # Spark: NaN is greater than everything
        non_nan = [v for v in fv if not (isinstance(v, float) and math.isnan(v))]
        if mx:
            return math.nan
        return min(non_nan) if non_nan else math.nan
    return (max if mx else min)(fv)


def _join_key(cols: List[CpuCol], i: int):
    parts = []
    for c in cols:
        if not c.validity[i]:
            return None  # null keys never match
        v = c.values[i]
        if isinstance(v, float) and math.isnan(v):
            v = ("\0NAN",)
        parts.append(v)
    return tuple(parts)


def _cpu_join(plan: PN._BaseJoin, ansi: bool):
    lcols, ln = execute_cpu_plan(plan.left, ansi)
    rcols, rn = execute_cpu_plan(plan.right, ansi)
    lkeys = [eval_expr(k, lcols, ln, ansi) for k in plan.left_keys]
    rkeys = [eval_expr(k, rcols, rn, ansi) for k in plan.right_keys]
    build: Dict[tuple, List[int]] = {}
    for j in range(rn):
        k = _join_key(rkeys, j)
        if k is not None:
            build.setdefault(k, []).append(j)
    jt = plan.join_type
    pairs: List[Tuple[int, Optional[int]]] = []
    matched_right = np.zeros(rn, np.bool_)
    for i in range(ln):
        k = _join_key(lkeys, i)
        matches = build.get(k, []) if k is not None else []
        if jt == PN.JoinType.LEFT_SEMI:
            if matches:
                pairs.append((i, None))
            continue
        if jt == PN.JoinType.LEFT_ANTI:
            if not matches:
                pairs.append((i, None))
            continue
        if matches:
            for j in matches:
                pairs.append((i, j))
                matched_right[j] = True
        elif jt in (PN.JoinType.LEFT_OUTER, PN.JoinType.FULL_OUTER):
            pairs.append((i, None))
    if jt in (PN.JoinType.RIGHT_OUTER, PN.JoinType.FULL_OUTER):
        if jt == PN.JoinType.RIGHT_OUTER:
            # keep matched pairs plus unmatched right
            pass
        for j in range(rn):
            if not matched_right[j]:
                pairs.append((None, j))
        if jt == PN.JoinType.RIGHT_OUTER:
            pairs = [(i, j) for (i, j) in pairs if j is not None]
    # apply residual condition on joined rows (inner-style filter)
    out_cols = _materialize_join(plan, lcols, rcols, pairs, jt)
    nrows = len(pairs)
    if plan.condition is not None and jt == PN.JoinType.INNER:
        pred = eval_expr(plan.condition, out_cols, nrows, ansi)
        keep = pred.values.astype(bool) & pred.validity
        out_cols = [CpuCol(c.dtype, c.values[keep], c.validity[keep])
                    for c in out_cols]
        nrows = int(keep.sum())
    return out_cols, nrows


def _materialize_join(plan, lcols, rcols, pairs, jt):
    def take(cols, idxs):
        out = []
        for c in cols:
            vals = np.array(
                [c.values[i] if i is not None else
                 (None if c.values.dtype == object else 0)
                 for i in idxs],
                dtype=c.values.dtype if c.values.dtype == object else
                c.values.dtype)
            valid = np.array([c.validity[i] if i is not None else False
                              for i in idxs], np.bool_)
            out.append(CpuCol(c.dtype, vals, valid))
        return out

    li = [p[0] for p in pairs]
    out = take(lcols, li)
    if jt not in (PN.JoinType.LEFT_SEMI, PN.JoinType.LEFT_ANTI):
        ri = [p[1] for p in pairs]
        out += take(rcols, ri)
    return out


def _sort_key_fn(c: CpuCol, spec):
    def key(i):
        if not c.validity[i]:
            return (0 if spec.nulls_first else 2, 0, 0)
        v = c.values[i]
        if isinstance(v, str):
            b = v.encode()
            if not spec.ascending:
                # desc for bytes: invert and terminate so prefixes sort after
                b = bytes(255 - x for x in b) + b"\xff"
                return (1, b, 0)
            return (1, b, 0)
        if isinstance(v, float) and math.isnan(v):
            # NaN is strictly greatest (above +inf)
            return ((1, math.inf, 1) if spec.ascending
                    else (1, -math.inf, -1))
        v2 = float(v) if not isinstance(v, int) else v
        return (1, -v2 if not spec.ascending else v2, 0)

    return key


def _cpu_sort(plan: PN.Sort, ansi: bool):
    cols, n = execute_cpu_plan(plan.child, ansi)
    kcols = [eval_expr(e, cols, n, ansi) for e, _ in plan.orders]
    idx = list(range(n))
    # stable multi-key: sort by last key first
    for (e, spec), kc in reversed(list(zip(plan.orders, kcols))):
        keyf = _sort_key_fn(kc, spec)
        idx.sort(key=keyf)
    take = np.array(idx, np.int64) if n else np.zeros(0, np.int64)
    out = [CpuCol(c.dtype, c.values[take], c.validity[take]) for c in cols]
    return out, n


def _cpu_generate(plan: PN.Generate, ansi: bool):
    cols, n = execute_cpu_plan(plan.child, ansi)
    arr = eval_expr(plan.gen_expr, cols, n, ansi)
    rows = []           # (src_row, pos or None, value, value_valid)
    for i in range(n):
        v = arr.values[i] if arr.validity[i] else None
        if v is None or len(v) == 0:
            if plan.outer:
                rows.append((i, None, None, False))
            continue
        for k, e in enumerate(v):
            rows.append((i, k, e, e is not None))
    m = len(rows)
    out = []
    for c in cols:
        if c.values.dtype == object:
            # np.array() would collapse equal-length lists into a 2-D array
            vals = np.empty(m, object)
            for j, r in enumerate(rows):
                vals[j] = c.values[r[0]]
        else:
            vals = np.array([c.values[r[0]] for r in rows],
                            dtype=c.values.dtype)
        valid = np.array([c.validity[r[0]] for r in rows], np.bool_)
        out.append(CpuCol(c.dtype, vals, valid))
    if plan.position:
        out.append(CpuCol(T.INT, np.array(
            [r[1] if r[1] is not None else 0 for r in rows], np.int32),
            np.array([r[1] is not None for r in rows], np.bool_)))
    et = plan.gen_expr.dataType.elementType
    evalid = np.array([r[3] for r in rows], np.bool_)
    if isinstance(et, T.StringType):
        evals = np.empty(m, object)
        for j, r in enumerate(rows):
            evals[j] = r[2] if r[3] else None
    else:
        evals = np.array([r[2] if r[3] else 0 for r in rows],
                         T.storage_dtype(et))
    out.append(CpuCol(et, evals, evalid))
    return out, m


def _cpu_bnlj(plan, ansi: bool):
    lcols, nl = execute_cpu_plan(plan.left, ansi)
    rcols, nr = execute_cpu_plan(plan.right, ansi)
    jt = plan.join_type
    # expand all pairs, evaluate the condition on the pair table
    li = np.repeat(np.arange(nl), max(nr, 1)) if nr else np.array([], np.int64)
    ri = np.tile(np.arange(max(nr, 1)), nl) if nr else np.array([], np.int64)
    pair_cols = [CpuCol(c.dtype, c.values[li], c.validity[li])
                 for c in lcols] +                 [CpuCol(c.dtype, c.values[ri], c.validity[ri])
                 for c in rcols] if nr else []
    npairs = nl * nr
    if plan.condition is not None and npairs:
        pred = eval_expr(plan.condition, pair_cols, npairs, ansi)
        ok = pred.values.astype(bool) & pred.validity
    else:
        ok = np.ones(npairs, np.bool_)
    matched_left = np.zeros(nl, np.bool_)
    if npairs:
        for i in range(npairs):
            if ok[i]:
                matched_left[li[i]] = True
    if jt in (PN.JoinType.LEFT_SEMI, PN.JoinType.LEFT_ANTI):
        keep = matched_left if jt == PN.JoinType.LEFT_SEMI else ~matched_left
        idx = np.nonzero(keep)[0]
        return [CpuCol(c.dtype, c.values[idx], c.validity[idx])
                for c in lcols], len(idx)
    sel = np.nonzero(ok)[0] if npairs else np.array([], np.int64)
    out = [CpuCol(c.dtype, c.values[li[sel]], c.validity[li[sel]])
           for c in lcols] +           [CpuCol(c.dtype, c.values[ri[sel]], c.validity[ri[sel]])
           for c in rcols]
    m = len(sel)
    if jt == PN.JoinType.LEFT_OUTER:
        um = np.nonzero(~matched_left)[0]
        if len(um):
            for ci, c in enumerate(lcols):
                out[ci] = CpuCol(c.dtype,
                                 np.concatenate([out[ci].values,
                                                 c.values[um]]),
                                 np.concatenate([out[ci].validity,
                                                 c.validity[um]]))
            for ci, c in enumerate(rcols):
                k = len(lcols) + ci
                pad_vals = np.zeros(len(um), dtype=c.values.dtype) \
                    if c.values.dtype != object else np.array(
                        [None] * len(um), object)
                out[k] = CpuCol(c.dtype,
                                np.concatenate([out[k].values, pad_vals]),
                                np.concatenate([out[k].validity,
                                                np.zeros(len(um),
                                                         np.bool_)]))
            m += len(um)
    return out, m


def _order_peer_key(ocols, i):
    """Order-key tuple for peer/rank comparison; NaN maps to a sentinel so
    NaN rows peer with each other (Spark: NaN == NaN in ordering — plain
    tuple equality would make every NaN its own peer group)."""
    out = []
    for oc in ocols:
        v = oc.row(i)
        if isinstance(v, (float, np.floating)) and math.isnan(v):
            v = "__nan__"
        out.append(v)
    return tuple(out)


def _cpu_window(plan: PN.Window, ansi: bool):
    cols, n = execute_cpu_plan(plan.child, ansi)
    pcols = [eval_expr(e, cols, n, ansi) for e in plan.partition_by]
    ocols = [eval_expr(e, cols, n, ansi) for e, _ in plan.order_by]
    # partition rows
    parts: Dict[tuple, List[int]] = {}
    for i in range(n):
        k = _group_key(pcols, i) if pcols else ()
        parts.setdefault(k, []).append(i)
    # order within partition
    for k, idxs in parts.items():
        for (e, spec), oc in reversed(list(zip(plan.order_by, ocols))):
            keyf = _sort_key_fn(oc, spec)
            idxs.sort(key=keyf)
    out_cols = list(cols)
    for wf in plan.functions:
        ac = (eval_expr(wf.child, cols, n, ansi)
              if wf.child is not None else None)
        vals = [None] * n
        valid = np.ones(n, np.bool_)
        for k, idxs in parts.items():
            if wf.func == "row_number":
                for r, i in enumerate(idxs):
                    vals[i] = r + 1
            elif wf.func in ("rank", "dense_rank"):
                rank = 0
                dense = 0
                prev = object()
                for r, i in enumerate(idxs):
                    cur = _order_peer_key(ocols, i)
                    if cur != prev:
                        rank = r + 1
                        dense += 1
                        prev = cur
                    vals[i] = rank if wf.func == "rank" else dense
            elif wf.func == "percent_rank":
                prev = object()
                rank = 0
                nr = len(idxs)
                for r, i in enumerate(idxs):
                    cur = _order_peer_key(ocols, i)
                    if cur != prev:
                        rank = r + 1
                        prev = cur
                    vals[i] = ((rank - 1) / (nr - 1)) if nr > 1 else 0.0
            elif wf.func == "cume_dist":
                nr = len(idxs)
                keys = [_order_peer_key(ocols, i) for i in idxs]
                for r, i in enumerate(idxs):
                    last = r
                    while last + 1 < nr and keys[last + 1] == keys[r]:
                        last += 1
                    vals[i] = (last + 1) / nr
            elif wf.func == "ntile":
                nb = max(int(wf.buckets), 1)
                nr = len(idxs)
                q, rem = divmod(nr, nb)
                for r, i in enumerate(idxs):
                    big = rem * (q + 1)
                    vals[i] = (r // (q + 1) if r < big
                               else rem + (r - big) // max(q, 1)) + 1
            elif wf.func in ("lead", "lag"):
                off = int(wf.offset) * (1 if wf.func == "lead" else -1)
                for r, i in enumerate(idxs):
                    j = r + off
                    if 0 <= j < len(idxs):
                        src = idxs[j]
                        if ac.validity[src]:
                            vals[i] = ac.values[src]
                        else:
                            vals[i] = None
                            valid[i] = False
                    elif wf.default is not None:
                        from spark_rapids_tpu.expr.base import Literal

                        vals[i] = Literal(wf.default,
                                          wf.result_type).storage_value()
                    else:
                        vals[i] = None
                        valid[i] = False
            elif wf.func in ("first_value", "last_value"):
                for r, i in enumerate(idxs):
                    sel = _frame_rows(plan, idxs, r, ocols)
                    order = sel if wf.func == "first_value" \
                        else list(reversed(sel))
                    vals[i] = None
                    valid[i] = False
                    for j in order:
                        if wf.ignore_nulls and not ac.validity[j]:
                            continue
                        if ac.validity[j]:
                            vals[i] = ac.values[j]
                            valid[i] = True
                        break
            elif wf.func in ("sum", "count", "avg", "min", "max",
                             "var_pop", "var_samp", "stddev_pop",
                             "stddev_samp"):
                # incremental/shared accumulators for the linear frames;
                # per-row _frame_rows only for peer/bounded frames (the
                # oracle is the production CPU fallback — O(n^2) frame
                # rebuilds would melt large partitions)
                if plan.frame == "running":
                    acc: List = []
                    for i in idxs:
                        if ac.validity[i]:
                            acc.append(ac.values[i])
                        vals[i] = _wagg(wf, acc, valid, i)
                elif plan.frame == "unbounded":
                    acc = [ac.values[i] for i in idxs if ac.validity[i]]
                    for i in idxs:
                        vals[i] = _wagg(wf, acc, valid, i)
                else:
                    for r, i in enumerate(idxs):
                        sel = _frame_rows(plan, idxs, r, ocols)
                        acc = [ac.values[j] for j in sel
                               if ac.validity[j]]
                        vals[i] = _wagg(wf, acc, valid, i)
            else:
                raise NotImplementedError(wf.func)
        if isinstance(wf.result_type, (T.DecimalType, T.StringType)):
            arr = np.array(vals, object)
        else:
            arr = np.array([v if v is not None else 0 for v in vals],
                           T.storage_dtype(wf.result_type))
        out_cols.append(CpuCol(wf.result_type, arr, valid))
    return out_cols, n


def _frame_rows(plan: PN.Window, idxs, r, ocols):
    """Row indices in the window frame of sorted-position ``r``
    (frame forms per plan.nodes.normalize_frame)."""
    fr = plan.frame
    nr = len(idxs)
    if fr == "running":
        return idxs[:r + 1]
    if fr == "unbounded":
        return idxs
    if fr == "range_running":
        # peers (equal order keys, nulls peer with nulls) are included
        kr = _order_peer_key(ocols, idxs[r])
        last = r
        while last + 1 < nr and \
                _order_peer_key(ocols, idxs[last + 1]) == kr:
            last += 1
        return idxs[:last + 1]
    if fr[0] == "rows":
        lo = max(0, r - int(fr[1]))
        hi = min(nr, r + int(fr[2]) + 1)
        return idxs[lo:hi]
    # ("range", lo, hi) over the single (numeric) order key.  "PRECEDING"
    # means towards the partition start, so the value-space bounds flip for
    # descending order.  Null order keys frame only their null peers.
    lo_off, hi_off = fr[1], fr[2]
    ov = ocols[0]
    i = idxs[r]
    if not ov.validity[i]:
        return [j for j in idxs if not ov.validity[j]]
    asc = plan.order_by[0][1].ascending
    v = ov.values[i]
    if isinstance(v, (float, np.floating)) and math.isnan(v):
        # NaN order keys frame their NaN peers (Spark: NaN == NaN in
        # ordering; NaN ± offset comparisons would otherwise all be False)
        return [j for j in idxs
                if ov.validity[j]
                and isinstance(ov.values[j], (float, np.floating))
                and math.isnan(ov.values[j])]
    if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
        # exact python-int arithmetic: np.int64 boundaries would wrap at
        # the extremes (the device side saturates, which is equivalent)
        v = int(v)
        lo_v = v - int(lo_off) if asc else v - int(hi_off)
        hi_v = v + int(hi_off) if asc else v + int(lo_off)
        return [j for j in idxs
                if ov.validity[j] and lo_v <= int(ov.values[j]) <= hi_v]
    lo_v = v - lo_off if asc else v - hi_off
    hi_v = v + hi_off if asc else v + lo_off
    return [j for j in idxs
            if ov.validity[j] and lo_v <= ov.values[j] <= hi_v]


def _wagg(wf, acc, valid, i):
    if wf.func == "count":
        return len(acc)
    if wf.func in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        xs = [float(v) for v in acc]
        n = len(xs)
        den = n if wf.func.endswith("pop") else n - 1
        if den <= 0:  # Spark nullOnDivideByZero: samp of n<=1 -> NULL
            valid[i] = False
            return None
        mean = sum(xs) / n
        m2 = sum((x - mean) ** 2 for x in xs)
        var = m2 / den
        return var if wf.func.startswith("var") else math.sqrt(var)
    if not acc:
        valid[i] = False
        return None
    if wf.func == "sum":
        return sum(acc) if not isinstance(acc[0], float) else float(sum(acc))
    if wf.func == "avg":
        return float(sum(float(v) for v in acc)) / len(acc)
    floats = isinstance(acc[0], float) or isinstance(acc[0], np.floating)
    if wf.func == "min":
        if floats:
            # Spark total order: NaN is the GREATEST value — min prefers
            # any non-NaN (python min() is positional on NaN)
            non_nan = [v for v in acc if not math.isnan(v)]
            return min(non_nan) if non_nan else float("nan")
        return min(acc)
    if wf.func == "max":
        if floats:
            if any(math.isnan(float(v)) for v in acc):
                return float("nan")
            return max(acc)
        return max(acc)
    raise NotImplementedError(wf.func)


# -- round-5 breadth: luhn/binary/bitmap/number-format/xml/avro/etc ----------

def _h_luhn(e, cols, n, ansi):
    (s,) = _kids(e, cols, n, ansi)
    out = np.zeros(n, np.bool_)
    for i in range(n):
        if not s.validity[i]:
            continue
        t = s.values[i]
        if not t or not t.isdigit():
            continue
        total = 0
        for j, ch in enumerate(reversed(t)):
            d = ord(ch) - 48
            if j % 2 == 1:
                d *= 2
                if d > 9:
                    d -= 9
            total += d
        out[i] = total % 10 == 0
    return CpuCol(T.BOOLEAN, out, s.validity.copy())


def _h_empty2null(e, cols, n, ansi):
    (s,) = _kids(e, cols, n, ansi)
    validity = s.validity & np.array(
        [bool(v) for v in s.values], np.bool_)
    return CpuCol(T.STRING, s.values.copy(), validity)


def _h_unary_positive(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    return c


def _h_to_binary(e, cols, n, ansi):
    import base64 as b64

    kids = _kids(e, cols, n, ansi)
    s = kids[0]
    fmt = e._fmt
    out = np.empty(n, object)
    validity = s.validity.copy()
    bad = np.zeros(n, np.bool_)
    for i in range(n):
        if not validity[i]:
            out[i] = None
            continue
        t = s.values[i]
        if fmt in ("utf-8", "utf8"):
            out[i] = t
            continue
        try:
            if fmt == "hex":
                if not all(c2 in "0123456789abcdefABCDEF" for c2 in t):
                    raise ValueError
                tt = ("0" + t) if len(t) % 2 else t
                out[i] = bytes.fromhex(tt).decode("utf-8", "replace")
            else:
                out[i] = b64.b64decode(t.encode(), validate=True).decode(
                    "utf-8", "replace")
        except Exception:
            out[i] = None
            validity[i] = False
            bad[i] = True
    if not e._try and ansi and bad.any():
        raise E.SparkArithmeticException(
            f"to_binary: malformed {fmt} input")
    return CpuCol.from_objs(list(out), T.STRING)


def _h_bitmap_bit_position(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    v = c.values.astype(np.int64)
    adj = np.where(v > 0, v - 1, v)
    pos = np.remainder(adj, 32768)
    return CpuCol(T.LONG, pos.astype(np.int64), c.validity.copy())


def _h_bitmap_bucket_number(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    v = c.values.astype(np.int64)
    adj = np.where(v > 0, v - 1, v)
    b = np.floor_divide(adj, 32768)
    b = np.where(v > 0, b + 1, b)
    return CpuCol(T.LONG, b.astype(np.int64), c.validity.copy())


def _h_bitmap_count(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)
    out = np.zeros(n, np.int64)
    for i in range(n):
        if c.validity[i] and c.values[i] is not None:
            out[i] = sum(bin(b).count("1")
                         for b in c.values[i].encode("utf-8", "replace"))
    return CpuCol(T.LONG, out, c.validity.copy())


def _h_randn(e, cols, n, ansi):
    from spark_rapids_tpu.expr.base import Literal as _L

    seed = 0
    ch = e.child
    if isinstance(ch, _L) and ch.value is not None:
        seed = int(ch.value)
    idx = np.arange(n, dtype=np.uint64)

    def unit(salt):
        z = idx * np.uint64(0x9E3779B97F4A7C15) + np.uint64(salt)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z = z ^ (z >> np.uint64(31))
        return (z >> np.uint64(11)).astype(np.float64) / float(1 << 53)

    u1 = unit((seed * 2654435769 + 1) % (1 << 64))
    u2 = unit((seed * 2654435769 + 2) % (1 << 64))
    r = np.sqrt(-2.0 * np.log(np.maximum(u1, 1e-300)))
    out = r * np.cos(2.0 * np.pi * u2)
    return CpuCol(T.DOUBLE, out, np.ones(n, np.bool_))


def _h_sentences(e, cols, n, ansi):
    import re as _re

    (s,) = _kids(e, cols, n, ansi)[:1]
    out = np.empty(n, object)
    for i in range(n):
        if not s.validity[i]:
            out[i] = None
            continue
        sents = [x for x in _re.split(r"[.!?]+", s.values[i]) if x.strip()]
        out[i] = [[w for w in _re.split(r"[^\w']+", x) if w]
                  for x in sents]
    return CpuCol(e.dataType, out, s.validity.copy())


def _h_try_element_at(e, cols, n, ansi):
    return _h_element_at(e, cols, n, ansi)


def _h_cardinality(e, cols, n, ansi):
    (a,) = _kids(e, cols, n, ansi)
    out = np.zeros(n, np.int32)
    for i in range(n):
        if a.validity[i] and a.values[i] is not None:
            out[i] = len(a.values[i])
    return CpuCol(T.INT, out, a.validity.copy())


def _h_map_from_entries(e, cols, n, ansi):
    (a,) = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    validity = a.validity.copy()
    for i in range(n):
        if not validity[i]:
            continue
        entries = a.values[i]
        m = {}
        for kv in entries:
            if kv is None:
                validity[i] = False
                break
            k, v = (kv if isinstance(kv, tuple) else tuple(kv))
            if k is None:
                raise E.SparkArithmeticException(
                    "Cannot use null as map key")
            if k in m:
                raise E.SparkArithmeticException(
                    "Duplicate map key was found")
            m[k] = v
        else:
            out[i] = m
    return CpuCol(e.dataType, out, validity)


def _h_map_sort(e, cols, n, ansi):
    (m,) = _kids(e, cols, n, ansi)
    out = np.empty(n, object)
    for i in range(n):
        if m.validity[i] and m.values[i] is not None:
            out[i] = dict(sorted(m.values[i].items()))
    return CpuCol(e.dataType, out, m.validity.copy())


def _h_shuffle(e, cols, n, ansi):
    (a,) = _kids(e, cols, n, ansi)
    seed = getattr(e, "_seed", 0)
    out = np.empty(n, object)
    for i in range(n):
        if not a.validity[i] or a.values[i] is None:
            continue
        arr = list(a.values[i])
        w = len(arr)
        ranks = []
        np.seterr(over="ignore")     # uint64 mix wraps by design
        for j in range(w):
            idx = np.uint64(i) * np.uint64(1 << 17) + np.uint64(j)
            z = idx * np.uint64(0x9E3779B97F4A7C15) + np.uint64(
                (seed * 2654435769 + 11) % (1 << 64))
            z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            ranks.append(np.int64(z ^ (z >> np.uint64(31))))
        order = sorted(range(w), key=lambda j: ranks[j])
        np.seterr(over="warn")
        out[i] = [arr[j] for j in order]
    return CpuCol(e.dataType, out, a.validity.copy())


def _h_parse_to_date(e, cols, n, ansi):
    inner = type(e).__mro__  # noqa: F841  (delegation below)
    from spark_rapids_tpu.expr.datetime import ToDate as _TD, \
        ToTimestamp as _TT

    name = type(e).__name__
    d = (_TD if name == "ParseToDate" else _TT)(e.children[0])
    d._resolve_type()
    return eval_expr(d, cols, n, ansi if name != "TryToTimestamp" else False)


def _h_to_number(e, cols, n, ansi):
    import re as _re
    from decimal import Decimal as _D

    kids = _kids(e, cols, n, ansi)
    s = kids[0]
    spec = e._spec
    scale = spec["scale"]
    out = np.empty(n, object)
    validity = s.validity.copy()
    for i in range(n):
        if not validity[i]:
            out[i] = None
            continue
        t = s.values[i].strip()
        sign = ""
        if spec["sign"] == "S_START" and t[:1] in "+-":
            sign, t = t[0], t[1:]
        if spec["currency"]:
            if not t.startswith("$"):
                out[i] = None
                validity[i] = False
                continue
            t = t[1:]
        if spec["sign"] == "S_END" and t[-1:] in "+-":
            sign, t = t[-1], t[:-1]
        elif spec["sign"] == "MI" and t.endswith("-"):
            sign, t = "-", t[:-1]
        fr = r"(?:\.([0-9]{0,%d}))?" % scale if scale else "()?"
        pat = (r"^([0-9][0-9,]*)?" if spec["grouping"]
               else r"^([0-9]+)?") + fr + "$"
        m2 = _re.match(pat, t)
        if not m2 or (not (m2.group(1) or "") and not (m2.group(2) or "")):
            out[i] = None
            validity[i] = False
            continue
        digits = (m2.group(1) or "").replace(",", "")
        fpart = (m2.group(2) or "")
        if len(digits.lstrip("0")) > spec["int_digits"]:
            out[i] = None
            validity[i] = False
            continue
        unscaled = int((digits or "0") + fpart.ljust(scale, "0"))
        if sign == "-":
            unscaled = -unscaled
        out[i] = unscaled     # CpuCol decimal storage = unscaled int
    if not e._try and ansi:
        bad = s.validity & ~validity
        if bad.any():
            raise E.SparkArithmeticException(
                "to_number: input does not match the format")
    return CpuCol.from_objs(
        [None if v is None else v for v in out], e.dataType)


def _h_to_character(e, cols, n, ansi):
    from decimal import Decimal as _D

    kids = _kids(e, cols, n, ansi)
    c = kids[0]
    spec = e._spec
    scale = spec["scale"]
    in_dt = e.children[0]._dataType
    out = np.empty(n, object)
    validity = c.validity.copy()
    for i in range(n):
        if not validity[i]:
            out[i] = None
            continue
        v = c.values[i]
        in_scale = in_dt.scale if isinstance(in_dt, T.DecimalType) else 0
        v = (v if isinstance(v, _D)
             else _D(int(v)).scaleb(-in_scale))
        q = v.quantize(_D(1).scaleb(-scale)) if scale else v.quantize(_D(1))
        neg = q < 0
        digits = format(abs(q), "f")
        ipart, _, fpart = digits.partition(".")
        if len(ipart.lstrip("0") or "") > spec["int_digits"]:
            out[i] = "#" * (spec["precision"] + (1 if scale else 0))
            continue
        if spec["grouping"]:
            rev = ipart[::-1]
            ipart = ",".join(rev[j:j + 3]
                             for j in range(0, len(rev), 3))[::-1]
        s2 = ipart + (("." + fpart.ljust(scale, "0")) if scale else "")
        if spec["currency"]:
            s2 = "$" + s2
        if spec["sign"] == "S_START":
            s2 = ("-" if neg else "+") + s2
        elif spec["sign"] == "S_END":
            s2 = s2 + ("-" if neg else "+")
        elif spec["sign"] == "MI":
            s2 = s2 + ("-" if neg else " ")
        elif neg:
            s2 = "-" + s2
        out[i] = s2
    return CpuCol.from_objs(list(out), T.STRING)


def _h_input_file_name(e, cols, n, ansi):
    from spark_rapids_tpu.expr.misc import CURRENT_INPUT_FILE

    path = getattr(cols, "input_file", None)
    if path is None:
        path = CURRENT_INPUT_FILE[0]
    return CpuCol.from_objs([path or ""] * n, T.STRING)


def _h_from_avro(e, cols, n, ansi):
    from spark_rapids_tpu.io.avro import _Reader, _decode_value

    (c,) = _kids(e, cols, n, ansi)[:1]
    st = e.dataType
    out = np.empty(n, object)
    validity = c.validity.copy()
    for i in range(n):
        if not validity[i]:
            continue
        try:
            r = _Reader(c.values[i].encode("latin-1", "replace")
                        if isinstance(c.values[i], str) else c.values[i])
            rec = _decode_value(r, e._avro_schema)
            out[i] = tuple(rec.get(f.name) for f in st.fields)
        except Exception:
            validity[i] = False
    return CpuCol(st, out, validity)


def _h_to_avro(e, cols, n, ansi):
    from spark_rapids_tpu.io.avro import _encode_value

    (c,) = _kids(e, cols, n, ansi)[:1]
    st = e.children[0]._dataType
    out = np.empty(n, object)
    for i in range(n):
        if not c.validity[i]:
            continue
        row = c.values[i]
        rec = {f.name: (row[j] if not isinstance(row, dict)
                        else row.get(f.name))
               for j, f in enumerate(st.fields)}
        buf = bytearray()
        _encode_value(buf, e._avro_schema, rec)
        out[i] = bytes(buf).decode("latin-1")
    return CpuCol.from_objs(list(out), T.STRING)


def _h_from_xml(e, cols, n, ansi):
    import xml.etree.ElementTree as _ET

    (c,) = _kids(e, cols, n, ansi)[:1]
    st = e.schema
    out = np.empty(n, object)
    validity = c.validity.copy()
    from spark_rapids_tpu.expr.jsonexprs import convert_json_field as _cjf
    for i in range(n):
        if not validity[i]:
            continue
        try:
            root = _ET.fromstring(c.values[i])
        except _ET.ParseError:
            out[i] = tuple([None] * len(st.fields))
            continue
        vals = []
        for f in st.fields:
            el = root.find(f.name)
            txt = None if el is None else (el.text or "")
            if txt is None:
                vals.append(None)
                continue
            sv = txt
            if not isinstance(f.dataType, T.StringType):
                try:
                    if isinstance(f.dataType, T.BooleanType):
                        sv = txt.strip().lower() == "true"
                    elif isinstance(f.dataType, (T.FloatType, T.DoubleType)):
                        sv = float(txt)
                    else:
                        sv = int(txt.strip())
                except ValueError:
                    vals = [None] * len(st.fields)
                    break
            ok, sv = _cjf(sv, f.dataType)
            if not ok:
                vals = [None] * len(st.fields)
                break
            vals.append(sv)
        out[i] = tuple(vals)
    return CpuCol(st, out, validity)


def _h_to_xml(e, cols, n, ansi):
    (c,) = _kids(e, cols, n, ansi)[:1]
    st = e.children[0]._dataType
    out = np.empty(n, object)

    def esc(s):
        return (s.replace("&", "&amp;").replace("<", "&lt;")
                .replace(">", "&gt;"))

    for i in range(n):
        if not c.validity[i]:
            continue
        row = c.values[i]
        body = []
        for j, f in enumerate(st.fields):
            v = row[j] if not isinstance(row, dict) else row.get(f.name)
            if v is None:
                continue
            if isinstance(f.dataType, T.StringType):
                sv = esc(str(v))
            elif isinstance(f.dataType, T.BooleanType):
                sv = "true" if v else "false"
            elif isinstance(f.dataType, (T.FloatType, T.DoubleType)):
                sv = repr(float(v))
            else:
                sv = str(int(v))
            body.append(f"<{f.name}>{sv}</{f.name}>")
        out[i] = "<row>" + "".join(body) + "</row>"
    return CpuCol.from_objs(list(out), T.STRING)


_HANDLERS.update({
    "Luhn": _h_luhn,
    "Empty2Null": _h_empty2null,
    "UnaryPositive": _h_unary_positive,
    "ToBinary": _h_to_binary, "TryToBinary": _h_to_binary,
    "BitmapBitPosition": _h_bitmap_bit_position,
    "BitmapBucketNumber": _h_bitmap_bucket_number,
    "BitmapCount": _h_bitmap_count,
    "Randn": _h_randn,
    "Sentences": _h_sentences,
    "TryElementAt": _h_try_element_at,
    "Cardinality": _h_cardinality,
    "MapFromEntries": _h_map_from_entries,
    "MapSort": _h_map_sort,
    "Shuffle": _h_shuffle,
    "ParseToDate": _h_parse_to_date,
    "ParseToTimestamp": _h_parse_to_date,
    "TryToTimestamp": _h_parse_to_date,
    "ToNumber": _h_to_number, "TryToNumber": _h_to_number,
    "ToCharacter": _h_to_character,
    "InputFileName": _h_input_file_name,
    "AvroDataToCatalyst": _h_from_avro,
    "CatalystDataToAvro": _h_to_avro,
    "XmlToStructs": _h_from_xml,
    "StructsToXml": _h_to_xml,
})


def _h_extract(e, cols, n, ansi):
    from spark_rapids_tpu.expr.datetime import _EXTRACT_FIELDS
    from spark_rapids_tpu.expr.base import Literal as _L

    f = e.children[0]
    name = str(f.value).lower() if isinstance(f, _L) else None
    cls = _EXTRACT_FIELDS.get(name)
    if cls is None:
        if name == "epoch":
            (src_col,) = [eval_expr(e.children[1], cols, n, ansi)]
            out = np.zeros(n, np.int64)
            for i in range(n):
                if src_col.validity[i]:
                    v = int(src_col.values[i])
                    # date days -> seconds; timestamps are micros
                    if isinstance(e.children[1]._dataType, T.DateType):
                        out[i] = v * 86400
                    else:
                        out[i] = v // 1_000_000
            return CpuCol(T.LONG, out, src_col.validity.copy())
        raise NotImplementedError(f"oracle extract field {name!r}")
    d = cls(e.children[1])
    d._resolve_type()
    return eval_expr(d, cols, n, ansi)


_HANDLERS["Extract"] = _h_extract
