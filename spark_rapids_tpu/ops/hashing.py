"""Spark-compatible hash functions, vectorized.

Reference analog: spark-rapids-jni murmur_hash.cu / xxhash64.cu backing
GpuMurmur3Hash (hash partitioning MUST produce Spark's exact partition ids so
CPU and TPU stages can interoperate) and GpuXxHash64.

All arithmetic in uint32/uint64 with natural wraparound; per-row, fully
vectorized; string hashing unrolls over the (static) char-matrix width the
way Spark's Murmur3_x86_32.hashUnsafeBytes walks bytes: 4-byte little-endian
blocks, then each trailing byte as its own block.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn

# python ints, NOT jnp arrays: module-level jax arrays become lifted
# jit constants that leak as foreign tracers into shard_map programs
_C1 = 0xCC9E2D51
_C2 = 0x1B873593


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl32(k1, 15)
    return k1 * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def _hash_int_block(seed_u32, block_u32, length):
    return _fmix(_mix_h1(seed_u32, _mix_k1(block_u32)), length)


def murmur3_column(c: DeviceColumn, seed: jax.Array) -> jax.Array:
    """Per-row murmur3 chained onto ``seed`` (uint32).  Null rows pass the
    seed through unchanged — exactly Spark's HashExpression behavior."""
    dt = c.dtype
    if c.is_string:
        h = _murmur3_string(c, seed)
    elif isinstance(dt, (T.FloatType,)):
        f = c.data.astype(jnp.float32)
        f = jnp.where(f == 0.0, jnp.float32(0.0), f)  # -0.0 -> 0.0
        as_u32 = f.view(jnp.int32).astype(jnp.uint32)
        # Java Float.floatToIntBits canonicalizes every NaN
        as_u32 = jnp.where(jnp.isnan(f), jnp.uint32(0x7FC00000), as_u32)
        h = _hash_int_block(seed, as_u32, 4)
    elif isinstance(dt, (T.DoubleType,)):
        d = c.data.astype(jnp.float64)
        d = jnp.where(d == 0.0, jnp.float64(0.0), d)
        bits = d.view(jnp.int64).astype(jnp.uint64)
        bits = jnp.where(jnp.isnan(d), jnp.uint64(0x7FF8000000000000), bits)
        h = _hash_long(seed, bits)
    elif isinstance(dt, (T.LongType, T.TimestampType)):
        h = _hash_long(seed, c.data.astype(jnp.int64).view(jnp.uint64)
                       if c.data.dtype == jnp.int64
                       else c.data.astype(jnp.uint64))
    elif isinstance(dt, T.DecimalType):
        # Spark hashes precision<=18 decimals as their unscaled long;
        # larger ones as the minimal BigInteger byte array — fail loudly
        # until that path exists (HashExpression.hash in Spark).
        if dt.precision > 18:
            raise NotImplementedError(
                "murmur3 of decimal precision > 18 requires the BigInteger "
                "byte-array path")
        h = _hash_long(seed, c.data.astype(jnp.int64).astype(jnp.uint64))
    elif isinstance(dt, T.BooleanType):
        h = _hash_int_block(seed, c.data.astype(jnp.uint32), 4)
    else:  # byte/short/int/date hash as int
        h = _hash_int_block(seed, c.data.astype(jnp.int32).astype(jnp.uint32), 4)
    return jnp.where(c.validity, h, seed)


def _hash_long(seed, bits_u64):
    low = (bits_u64 & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    high = (bits_u64 >> 32).astype(jnp.uint32)
    h = _mix_h1(seed, _mix_k1(low))
    h = _mix_h1(h, _mix_k1(high))
    return _fmix(h, 8)


def _murmur3_string(c: DeviceColumn, seed: jax.Array) -> jax.Array:
    w = c.width
    n = c.capacity
    h = jnp.broadcast_to(seed, (n,)).astype(jnp.uint32)
    lengths = c.lengths
    aligned = (lengths // 4) * 4
    nblocks = w // 4
    ch = c.chars.astype(jnp.uint32)
    for b in range(nblocks + 1):
        base = b * 4
        if base + 4 <= w:
            block = (ch[:, base]
                     | (ch[:, base + 1] << 8)
                     | (ch[:, base + 2] << 16)
                     | (ch[:, base + 3] << 24))
            use = (base + 4) <= aligned
            h = jnp.where(use, _mix_h1(h, _mix_k1(block)), h)
    # tail bytes, each as its own signed-byte block (Spark hashUnsafeBytes)
    for t in range(min(3, w)):
        idx = aligned + t
        in_tail = idx < lengths
        byte = jnp.take_along_axis(
            ch, jnp.clip(idx, 0, w - 1)[:, None], axis=1)[:, 0]
        sbyte = jnp.where(byte > 127, byte | jnp.uint32(0xFFFFFF00), byte)
        h = jnp.where(in_tail, _mix_h1(h, _mix_k1(sbyte)), h)
    return _fmix_len(h, lengths)


def _fmix_len(h1, lengths):
    h1 = h1 ^ lengths.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def murmur3_columns(cols: List[DeviceColumn], seed: int = 42) -> jax.Array:
    """Spark Murmur3Hash(cols): chain column hashes starting at seed."""
    n = cols[0].capacity
    h = jnp.full((n,), jnp.uint32(seed))
    for c in cols:
        h = murmur3_column(c, h)
    return h.astype(jnp.int32)


def spark_partition_ids(cols: List[DeviceColumn], num_partitions: int,
                        seed: int = 42) -> jax.Array:
    """GpuHashPartitioning: pmod(murmur3(keys), numPartitions).

    Sub-partitioned joins pass a different seed so bucket assignment is
    decorrelated from the upstream exchange's partitioning (reference:
    GpuSubPartitionHashJoin's distinct hash seed)."""
    h = murmur3_columns(cols, seed=seed)
    p = h % jnp.int32(num_partitions)
    return jnp.where(p < 0, p + num_partitions, p)


# ---------------------------------------------------------------------------
# XXH64 (Spark's XxHash64, seed-chained per column like murmur3 above).
# Reference analog: spark-rapids-jni xxhash64.cu backing GpuXxHash64.
# ---------------------------------------------------------------------------
_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _u64(x: int):
    """In-trace uint64 constant: primes >= 2^63 overflow jax's weak-int
    scalar path, and module-level jnp arrays leak across traces — so each
    trace materializes its own constant."""
    return jnp.uint64(x)


def _rotl64(x, r):
    return (x << r) | (x >> (64 - r))


def _xxh_fmix(h):
    h = h ^ (h >> 33)
    h = h * _u64(_P2)
    h = h ^ (h >> 29)
    h = h * _P3
    return h ^ (h >> 32)


def _xxh_int(value_i32, seed_u64):
    h = seed_u64 + _P5 + jnp.uint64(4)
    # Spark's XXH64.hashInt: i & 0xFFFFFFFFL — the 32-bit pattern zero-
    # extended.  An astype chain (int8/int16 -> int32 -> uint32 ->
    # uint64) is NOT safe here: XLA's algebraic simplifier folds the
    # converts into one signed int8->uint64 convert under jit, sign-
    # extending negative bytes/shorts into the high 32 bits (eager and
    # jit disagreed; seed xxhash64 byte/short failures).  The explicit
    # mask survives any convert folding.
    u = value_i32.astype(jnp.int64).astype(jnp.uint64) & _u64(0xFFFFFFFF)
    h = h ^ (u * _u64(_P1))
    h = _rotl64(h, 23) * _u64(_P2) + _P3
    return _xxh_fmix(h)


def _xxh_long(value_u64, seed_u64):
    h = seed_u64 + _P5 + jnp.uint64(8)
    h = h ^ (_rotl64(value_u64 * _u64(_P2), 31) * _u64(_P1))
    h = _rotl64(h, 27) * _u64(_P1) + _u64(_P4)
    return _xxh_fmix(h)


def _gather_byte(ch_u64, idx, width):
    """ch_u64: (n, w) uint64 byte matrix; idx: (n,) positions (clipped)."""
    return jnp.take_along_axis(
        ch_u64, jnp.clip(idx, 0, max(width - 1, 0))[:, None], axis=1)[:, 0]


def _le_chunk(ch_u64, base, nbytes, width):
    """Little-endian nbytes chunk starting at per-row ``base`` offsets."""
    v = jnp.zeros(ch_u64.shape[0], jnp.uint64)
    for t in range(nbytes):
        v = v | (_gather_byte(ch_u64, base + t, width) << (8 * t))
    return v


def _xxh_string(c: DeviceColumn, seed: jax.Array) -> jax.Array:
    """Vectorized XXH64.hashUnsafeBytes over the padded char matrix."""
    n, w = c.capacity, c.width
    ch = c.chars.astype(jnp.uint64)
    lengths = c.lengths.astype(jnp.int32)
    len64 = lengths.astype(jnp.uint64)
    long_path = lengths >= 32
    nstripes = lengths // 32  # do-while stripes == floor(len/32)
    v1 = seed + _u64(_P1) + _u64(_P2)
    v2 = seed + _u64(_P2)
    v3 = seed
    v4 = seed - _u64(_P1)
    for b in range(w // 32):
        active = b < nstripes
        for j, v in enumerate((v1, v2, v3, v4)):
            base = 32 * b + 8 * j
            k = jnp.zeros(n, jnp.uint64)
            for t in range(8):  # static offsets -> plain column slices
                k = k | (ch[:, base + t] << (8 * t))
            nv = _rotl64(v + k * _u64(_P2), 31) * _u64(_P1)
            if j == 0:
                v1 = jnp.where(active, nv, v1)
            elif j == 1:
                v2 = jnp.where(active, nv, v2)
            elif j == 2:
                v3 = jnp.where(active, nv, v3)
            else:
                v4 = jnp.where(active, nv, v4)
    merged = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12)
              + _rotl64(v4, 18))
    for v in (v1, v2, v3, v4):
        merged = (merged ^ (_rotl64(v * _u64(_P2), 31) * _u64(_P1))) * _u64(_P1) + _u64(_P4)
    h = jnp.where(long_path, merged, seed + _P5)
    h = h + len64
    base = nstripes * 32
    rem = lengths - base
    # up to three 8-byte tail chunks
    for j in range(3):
        active = (j + 1) * 8 <= rem
        k = _le_chunk(ch, base + 8 * j, 8, w)
        nh = _rotl64(h ^ (_rotl64(k * _u64(_P2), 31) * _u64(_P1)), 27) * _u64(_P1) + _u64(_P4)
        h = jnp.where(active, nh, h)
    o4 = base + (rem // 8) * 8
    rem4 = lengths - o4
    active4 = rem4 >= 4
    k4 = _le_chunk(ch, o4, 4, w)
    h = jnp.where(active4, _rotl64(h ^ (k4 * _u64(_P1)), 23) * _u64(_P2) + _P3, h)
    ob = o4 + jnp.where(active4, 4, 0)
    for t in range(3):
        idx = ob + t
        active = idx < lengths
        byte = _gather_byte(ch, idx, w)
        h = jnp.where(active, _rotl64(h ^ (byte * _P5), 11) * _u64(_P1), h)
    return _xxh_fmix(h)


_CANON_NAN32 = 0x7FC00000
_CANON_NAN64 = 0x7FF8000000000000


def xxhash64_column(c: DeviceColumn, seed: jax.Array) -> jax.Array:
    """Per-row xxhash64 chained onto ``seed`` (uint64); null rows pass the
    seed through (Spark HashExpression)."""
    dt = c.dtype
    if c.is_string:
        h = _xxh_string(c, seed)
    elif isinstance(dt, T.FloatType):
        f = c.data.astype(jnp.float32)
        f = jnp.where(f == 0.0, jnp.float32(0.0), f)
        bits = f.view(jnp.int32)
        bits = jnp.where(jnp.isnan(f), jnp.int32(_CANON_NAN32), bits)
        h = _xxh_int(bits, seed)
    elif isinstance(dt, T.DoubleType):
        d = c.data.astype(jnp.float64)
        d = jnp.where(d == 0.0, jnp.float64(0.0), d)
        bits = d.view(jnp.int64).astype(jnp.uint64)
        bits = jnp.where(jnp.isnan(d), _CANON_NAN64, bits)
        h = _xxh_long(bits, seed)
    elif isinstance(dt, (T.LongType, T.TimestampType)) or (
            isinstance(dt, T.DecimalType) and dt.precision <= 18):
        h = _xxh_long(c.data.astype(jnp.int64).view(jnp.uint64), seed)
    elif isinstance(dt, T.DecimalType):
        # Spark hashes precision>18 decimals as the minimal BigInteger
        # byte array, not the unscaled long (same as murmur3 above).
        raise NotImplementedError(
            "xxhash64 of decimal precision > 18 requires the BigInteger "
            "byte-array path")
    elif isinstance(dt, T.BooleanType):
        h = _xxh_int(c.data.astype(jnp.int32), seed)
    else:  # byte/short/int/date
        h = _xxh_int(c.data.astype(jnp.int32), seed)
    return jnp.where(c.validity, h, seed)


def xxhash64_columns(cols: List[DeviceColumn], seed: int = 42) -> jax.Array:
    n = cols[0].capacity
    h = jnp.full((n,), jnp.uint64(seed))
    for c in cols:
        h = xxhash64_column(c, h)
    return h.view(jnp.int64)


# ---------------------------------------------------------------------------
# HiveHash.  Reference analog: spark-rapids-jni hive_hash.cu backing
# GpuHiveHash (SURVEY.md §2.5 Hash/misc).  Semantics: Spark's HiveHash
# expression — h = 31*h + colHash per child (int32 wraparound), null -> 0;
# string = byte-polynomial hash, long = (v ^ (v >>> 32)).
# ---------------------------------------------------------------------------

def hive_hash_column(c: DeviceColumn) -> jax.Array:
    """Per-row Hive hash of one column (int32), null rows -> 0."""
    dt = c.dtype
    if c.is_string:
        h = _hive_hash_string(c)
    elif isinstance(dt, T.BooleanType):
        h = c.data.astype(jnp.int32)
    elif isinstance(dt, (T.LongType, T.TimestampType)):
        v = c.data.astype(jnp.int64)
        h = (v ^ jax.lax.shift_right_logical(
            v, jnp.int64(32))).astype(jnp.int32)
    elif isinstance(dt, T.FloatType):
        f = c.data.astype(jnp.float32)
        bits = f.view(jnp.int32)
        bits = jnp.where(jnp.isnan(f), jnp.int32(0x7FC00000), bits)
        h = bits
    elif isinstance(dt, T.DoubleType):
        d = c.data.astype(jnp.float64)
        bits = d.view(jnp.int64)
        bits = jnp.where(jnp.isnan(d),
                         jnp.int64(0x7FF8000000000000), bits)
        h = (bits ^ jax.lax.shift_right_logical(
            bits, jnp.int64(32))).astype(jnp.int32)
    else:  # byte/short/int/date
        h = c.data.astype(jnp.int32)
    return jnp.where(c.validity, h, jnp.int32(0))


def _hive_hash_string(c: DeviceColumn) -> jax.Array:
    """h = 31*h + byte over the row's UTF-8 bytes (chunked fori_loop —
    O(1) compile size at any width bucket)."""
    # Java HiveHasher reads SIGNED bytes; chars are stored unsigned
    chars = c.chars.astype(jnp.int32)
    chars = jnp.where(chars >= 128, chars - 256, chars)
    w = chars.shape[1] if chars.ndim == 2 else 1
    lens = c.lengths.astype(jnp.int32)
    cap = chars.shape[0]
    pow31 = jnp.int32(31)

    def body(i, h):
        byte = chars[:, i]
        inside = i < lens
        return jnp.where(inside, h * pow31 + byte, h)

    h0 = jnp.zeros(cap, jnp.int32)
    if w == 0:
        return h0
    return jax.lax.fori_loop(0, w, body, h0)


def hive_hash_columns(cols: List[DeviceColumn]) -> jax.Array:
    """HiveHash(c1..cn): h = 31*h + hash(ci), starting at 0."""
    n = cols[0].capacity
    h = jnp.zeros(n, jnp.int32)
    for c in cols:
        h = h * jnp.int32(31) + hive_hash_column(c)
    return h
