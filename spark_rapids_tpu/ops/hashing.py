"""Spark-compatible hash functions, vectorized.

Reference analog: spark-rapids-jni murmur_hash.cu / xxhash64.cu backing
GpuMurmur3Hash (hash partitioning MUST produce Spark's exact partition ids so
CPU and TPU stages can interoperate) and GpuXxHash64.

All arithmetic in uint32/uint64 with natural wraparound; per-row, fully
vectorized; string hashing unrolls over the (static) char-matrix width the
way Spark's Murmur3_x86_32.hashUnsafeBytes walks bytes: 4-byte little-endian
blocks, then each trailing byte as its own block.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn

_C1 = jnp.uint32(0xCC9E2D51)
_C2 = jnp.uint32(0x1B873593)


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl32(k1, 15)
    return k1 * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def _hash_int_block(seed_u32, block_u32, length):
    return _fmix(_mix_h1(seed_u32, _mix_k1(block_u32)), length)


def murmur3_column(c: DeviceColumn, seed: jax.Array) -> jax.Array:
    """Per-row murmur3 chained onto ``seed`` (uint32).  Null rows pass the
    seed through unchanged — exactly Spark's HashExpression behavior."""
    dt = c.dtype
    if c.is_string:
        h = _murmur3_string(c, seed)
    elif isinstance(dt, (T.FloatType,)):
        bits = c.data.astype(jnp.float32)
        bits = jnp.where(bits == 0.0, jnp.float32(0.0), bits)  # -0.0 -> 0.0
        as_u32 = bits.view(jnp.int32).astype(jnp.uint32)
        h = _hash_int_block(seed, as_u32, 4)
    elif isinstance(dt, (T.DoubleType,)):
        d = c.data.astype(jnp.float64)
        d = jnp.where(d == 0.0, jnp.float64(0.0), d)
        bits = d.view(jnp.int64).astype(jnp.uint64)
        h = _hash_long(seed, bits)
    elif isinstance(dt, (T.LongType, T.TimestampType)) or (
            isinstance(dt, T.DecimalType) and dt.precision > 18):
        h = _hash_long(seed, c.data.astype(jnp.int64).view(jnp.uint64)
                       if c.data.dtype == jnp.int64
                       else c.data.astype(jnp.uint64))
    elif isinstance(dt, T.DecimalType):
        # Spark hashes small decimals as their unscaled long
        h = _hash_long(seed, c.data.astype(jnp.int64).astype(jnp.uint64))
    elif isinstance(dt, T.BooleanType):
        h = _hash_int_block(seed, c.data.astype(jnp.uint32), 4)
    else:  # byte/short/int/date hash as int
        h = _hash_int_block(seed, c.data.astype(jnp.int32).astype(jnp.uint32), 4)
    return jnp.where(c.validity, h, seed)


def _hash_long(seed, bits_u64):
    low = (bits_u64 & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    high = (bits_u64 >> 32).astype(jnp.uint32)
    h = _mix_h1(seed, _mix_k1(low))
    h = _mix_h1(h, _mix_k1(high))
    return _fmix(h, 8)


def _murmur3_string(c: DeviceColumn, seed: jax.Array) -> jax.Array:
    w = c.width
    n = c.capacity
    h = jnp.broadcast_to(seed, (n,)).astype(jnp.uint32)
    lengths = c.lengths
    aligned = (lengths // 4) * 4
    nblocks = w // 4
    ch = c.chars.astype(jnp.uint32)
    for b in range(nblocks + 1):
        base = b * 4
        if base + 4 <= w:
            block = (ch[:, base]
                     | (ch[:, base + 1] << 8)
                     | (ch[:, base + 2] << 16)
                     | (ch[:, base + 3] << 24))
            use = (base + 4) <= aligned
            h = jnp.where(use, _mix_h1(h, _mix_k1(block)), h)
    # tail bytes, each as its own signed-byte block (Spark hashUnsafeBytes)
    for t in range(min(3, w)):
        idx = aligned + t
        in_tail = idx < lengths
        byte = jnp.take_along_axis(
            ch, jnp.clip(idx, 0, w - 1)[:, None], axis=1)[:, 0]
        sbyte = jnp.where(byte > 127, byte | jnp.uint32(0xFFFFFF00), byte)
        h = jnp.where(in_tail, _mix_h1(h, _mix_k1(sbyte)), h)
    return _fmix_len(h, lengths)


def _fmix_len(h1, lengths):
    h1 = h1 ^ lengths.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def murmur3_columns(cols: List[DeviceColumn], seed: int = 42) -> jax.Array:
    """Spark Murmur3Hash(cols): chain column hashes starting at seed."""
    n = cols[0].capacity
    h = jnp.full((n,), jnp.uint32(seed))
    for c in cols:
        h = murmur3_column(c, h)
    return h.astype(jnp.int32)


def spark_partition_ids(cols: List[DeviceColumn], num_partitions: int) -> jax.Array:
    """GpuHashPartitioning: pmod(murmur3(keys), numPartitions)."""
    h = murmur3_columns(cols, seed=42)
    p = h % jnp.int32(num_partitions)
    return jnp.where(p < 0, p + num_partitions, p)
