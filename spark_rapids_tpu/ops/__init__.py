"""Device kernels (jnp/XLA today, Pallas where profiling justifies it).

This package is the L0 of the framework — the TPU equivalent of libcudf's
kernel layer (SURVEY.md §2.10).  Everything here is shape-static, traceable,
and designed around sort-based algorithms: on a machine whose strengths are
the MXU/VPU and whose weakness is device-wide atomics, `lax.sort` + segment
scans replace cuDF's hash tables (hash groupby, hash join) — same semantics,
different algorithm, as SURVEY.md §7 prescribes.
"""
from spark_rapids_tpu.ops.filterops import compact_columns  # noqa: F401
from spark_rapids_tpu.ops.sortkeys import pack_sort_keys  # noqa: F401
