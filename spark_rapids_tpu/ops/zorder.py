"""Z-order (Morton) interleaving — the zorder.cu analog.

Reference analog: spark-rapids-jni ``zorder.cu`` (GpuInterleaveBits +
GpuHilbertLongIndex) powering Delta OPTIMIZE ZORDER BY (SURVEY.md §2.5
Hash/misc, §2.8 Delta).

TPU design: each key column is rank-normalized to uint32 (order-preserving
per type: ints biased, floats via the total-order bit trick, strings by
their first 4 big-endian bytes), then bits interleave into k 32-bit planes
packed as int64 key words — all dense vector ops; the actual clustering is
the engine's regular sort over those words.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.ops.sortkeys import _float_total_order


def _rank_u32(c: DeviceColumn) -> jax.Array:
    """Order-preserving uint32 surrogate per row (nulls smallest)."""
    dt = c.dtype
    if c.is_string:
        w = min(c.width, 4)
        acc = jnp.zeros(c.capacity, jnp.uint32)
        for i in range(4):
            byte = (c.chars[:, i].astype(jnp.uint32)
                    if i < w else jnp.zeros(c.capacity, jnp.uint32))
            inb = (i < c.lengths).astype(jnp.uint32)
            acc = (acc << 8) | (byte * inb)
        ranked = acc
    elif isinstance(dt, (T.FloatType, T.DoubleType)):
        bits = jax.lax.bitcast_convert_type(
            c.data.astype(jnp.float64), jnp.int64)
        bits = jnp.where(jnp.isnan(c.data.astype(jnp.float64)),
                         jnp.int64(0x7FF8000000000000), bits)
        key = _float_total_order(bits)
        ranked = ((key >> 32) + jnp.int64(1 << 31)).astype(jnp.uint32)
    else:
        v = c.data.astype(jnp.int64)
        wide = isinstance(dt, (T.LongType, T.TimestampType)) or (
            isinstance(dt, T.DecimalType))
        if wide:
            # top 32 bits of the sign-biased 64-bit value
            ranked = ((v >> jnp.int64(32))
                      + jnp.int64(1 << 31)).astype(jnp.uint32)
        else:
            ranked = (v + jnp.int64(1 << 31)).astype(jnp.uint32)
    # nulls first: shift valid ranks up by 1 (saturating) is unnecessary —
    # zero out null ranks (ties with real zeros only smear clustering)
    return jnp.where(c.validity, ranked, 0)


def interleave_bits(cols: List[DeviceColumn]) -> List[jax.Array]:
    """-> list of int64 sort-key words, most-significant first.

    k columns × 32 bits = 32*k interleaved bits, packed big-endian into
    ceil(32k/64) words (the cuDF interleave_bits returns a byte list; key
    words feed our lax.sort directly)."""
    k = len(cols)
    ranks = [_rank_u32(c) for c in cols]
    total_bits = 32 * k
    nwords = (total_bits + 63) // 64
    cap = cols[0].capacity
    words = [jnp.zeros(cap, jnp.int64) for _ in range(nwords)]
    # bit b (0 = most significant) = bit (31 - b//k) of column (b % k)
    for b in range(total_bits):
        col_i = b % k
        src_bit = 31 - (b // k)
        bit = (ranks[col_i] >> jnp.uint32(src_bit)) & jnp.uint32(1)
        w_i = b // 64
        dst = 63 - (b % 64)
        words[w_i] = words[w_i] | (bit.astype(jnp.int64) << jnp.int64(dst))
    return words
