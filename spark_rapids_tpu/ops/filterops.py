"""Stream compaction — the cuDF `apply_boolean_mask` / `copy_if_else` analog.

Reference analog: libcudf stream compaction consumed by GpuFilterExec
(SURVEY.md §2.10 item 5).  TPU design: compaction is a cumsum + scatter
(O(n), no sort).  The kept-row count comes back as a device scalar; the
caller syncs it to host once per stage output (not per op) — whole-stage
fusion keeps intermediate counts on device.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.column import DeviceColumn


def compact_columns(mask: jax.Array,
                    cols: List[DeviceColumn]) -> Tuple[List[DeviceColumn], jax.Array]:
    """Move rows where ``mask`` is True to the front, preserving order.

    Returns (compacted columns, kept-count device scalar).  Rows past the
    count hold garbage (masked by validity=False).
    """
    n = mask.shape[0]
    positions = jnp.cumsum(mask.astype(jnp.int32)) - 1
    count = jnp.where(n > 0, positions[-1] + 1, 0).astype(jnp.int32)
    # rows not kept scatter out of bounds -> dropped
    scatter_idx = jnp.where(mask, positions, n)

    def _compact(c: DeviceColumn) -> DeviceColumn:
        validity = jnp.zeros_like(c.validity).at[scatter_idx].set(
            c.validity, mode="drop")
        if c.is_string_array:
            chars = jnp.zeros_like(c.chars).at[scatter_idx].set(
                c.chars, mode="drop")
            elens = jnp.zeros_like(c.data).at[scatter_idx].set(
                c.data, mode="drop")
            lengths = jnp.zeros_like(c.lengths).at[scatter_idx].set(
                c.lengths, mode="drop")
            ev = jnp.zeros_like(c.elem_valid).at[scatter_idx].set(
                c.elem_valid, mode="drop")
            return DeviceColumn(c.dtype, validity, chars=chars, data=elens,
                                lengths=lengths, elem_valid=ev)
        if c.is_string:
            chars = jnp.zeros_like(c.chars).at[scatter_idx].set(
                c.chars, mode="drop")
            lengths = jnp.zeros_like(c.lengths).at[scatter_idx].set(
                c.lengths, mode="drop")
            return DeviceColumn(c.dtype, validity, chars=chars,
                                lengths=lengths)
        if c.is_array:
            data = jnp.zeros_like(c.data).at[scatter_idx].set(
                c.data, mode="drop")
            lengths = jnp.zeros_like(c.lengths).at[scatter_idx].set(
                c.lengths, mode="drop")
            ev = jnp.zeros_like(c.elem_valid).at[scatter_idx].set(
                c.elem_valid, mode="drop")
            return DeviceColumn(c.dtype, validity, data=data,
                                lengths=lengths, elem_valid=ev)
        if c.is_struct:
            lengths = None
            if c.lengths is not None:   # entries layout (array<struct>)
                lengths = jnp.zeros_like(c.lengths).at[scatter_idx].set(
                    c.lengths, mode="drop")
            return DeviceColumn(c.dtype, validity, lengths=lengths,
                                children=tuple(_compact(k)
                                               for k in c.children))
        data = jnp.zeros_like(c.data).at[scatter_idx].set(
            c.data, mode="drop")
        return DeviceColumn(c.dtype, validity, data=data)

    return [_compact(c) for c in cols], count


def gather_columns(indices: jax.Array, valid_out: jax.Array,
                   cols: List[DeviceColumn]) -> List[DeviceColumn]:
    """Row gather (the JoinGatherer primitive): out[i] = col[indices[i]],
    with rows where ``valid_out`` is False nulled (used for outer joins)."""
    n = cols[0].capacity if cols else 0
    safe = jnp.clip(indices, 0, max(n - 1, 0))

    def _gather(c: DeviceColumn) -> DeviceColumn:
        validity = c.validity[safe] & valid_out
        if c.is_string_array:
            return DeviceColumn(c.dtype, validity, chars=c.chars[safe],
                                data=c.data[safe], lengths=c.lengths[safe],
                                elem_valid=c.elem_valid[safe])
        if c.is_string:
            return DeviceColumn(c.dtype, validity, chars=c.chars[safe],
                                lengths=c.lengths[safe])
        if c.is_array:
            return DeviceColumn(c.dtype, validity, data=c.data[safe],
                                lengths=c.lengths[safe],
                                elem_valid=c.elem_valid[safe])
        if c.is_struct:
            return DeviceColumn(c.dtype, validity,
                                children=tuple(_gather(k)
                                               for k in c.children))
        return DeviceColumn(c.dtype, validity, data=c.data[safe])

    return [_gather(c) for c in cols]
