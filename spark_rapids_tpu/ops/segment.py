"""Segmented reductions/scans — the TPU groupby/window primitive.

Reference analog: cuDF's hash `Table.groupBy().aggregate()` kernels
(SURVEY.md §2.10 item 2).  TPU-first: group-by is sort-based — rows sorted by
key, equal-key runs become segments, and `jax.ops.segment_*` performs the
reduction in one pass.  Null/NaN semantics follow Spark SQL:

  * aggregates skip nulls; a group with zero valid inputs yields null
    (except count, which yields 0);
  * float min/max treat NaN as the greatest value (Spark total order).
"""
from __future__ import annotations

import threading
from typing import Tuple

import jax
import jax.numpy as jnp


def _starts_of(seg_ids):
    return jnp.concatenate([jnp.ones(1, jnp.bool_),
                            seg_ids[1:] != seg_ids[:-1]])


class SegBounds:
    """Boundary form of a SORTED segment-id array, bounded to B segments:
    ``starts[g]``/``ends[g]`` delimit segment g's row range.  B*log(cap)
    tiny gathers (binary search) replace every full-width scatter in the
    bounded aggregation path — on the v5e, a cap-wide scatter-add costs
    ~1.7s at 20M rows while cumsum+B-gathers cost ~90ms (round-5
    calibration)."""

    __slots__ = ("starts", "ends", "num")

    def __init__(self, seg_ids, num: int):
        gids = jnp.arange(num, dtype=seg_ids.dtype)
        self.starts = jnp.searchsorted(seg_ids, gids, side="left")
        self.ends = jnp.searchsorted(seg_ids, gids, side="right")
        self.num = num

    def gather_last(self, arr, fill):
        """arr value at each segment's last row (fill for empty)."""
        cap = arr.shape[0]
        idx = jnp.clip(self.ends - 1, 0, cap - 1)
        return jnp.where(self.ends > self.starts, arr[idx],
                         jnp.asarray(fill, arr.dtype))

    def gather_first(self, arr, fill):
        cap = arr.shape[0]
        idx = jnp.clip(self.starts, 0, cap - 1)
        return jnp.where(self.ends > self.starts, arr[idx],
                         jnp.asarray(fill, arr.dtype))

    def csum_diff(self, contrib):
        """Per-segment sum of contrib via one cumsum + 2B gathers.
        Exact for integers (wrap cancels); callers keep floats on the
        scatter path (global-magnitude cancellation)."""
        cs = jnp.cumsum(contrib)
        cap = contrib.shape[0]
        hi = cs[jnp.clip(self.ends - 1, 0, cap - 1)]
        lo_idx = self.starts - 1
        lo = jnp.where(lo_idx >= 0, cs[jnp.clip(lo_idx, 0, cap - 1)],
                       jnp.zeros((), cs.dtype))
        return jnp.where(self.ends > self.starts, hi - lo,
                         jnp.zeros((), cs.dtype))

    def counts(self, validity):
        return self.csum_diff(validity.astype(jnp.int64))


_BOUNDS_TLS = threading.local()


def _bounds_stack() -> list:
    st = getattr(_BOUNDS_TLS, "stack", None)
    if st is None:
        st = _BOUNDS_TLS.stack = []
    return st


class bounds_scope:
    """Trace-scoped bounded-segments mode: inside the scope, every
    segment primitive called with ``num_segments == bounds.num`` takes the
    boundary form instead of a full-width scatter.  Installed by the
    aggregate's bounded program builder around its evaluation so the ~40
    SEG call sites need no signature change.  The ambient stack is
    PER-THREAD: tracing is synchronous on its own thread, but concurrent
    collects and the AOT compile pool trace on different threads at the
    same time, and one query's bounds must never leak into another's
    trace (found by tpulint's module-state rule, ISSUE 9)."""

    def __init__(self, b: "SegBounds"):
        self.b = b

    def __enter__(self):
        _bounds_stack().append(self.b)
        return self.b

    def __exit__(self, *a):
        _bounds_stack().pop()


def _active_bounds(num_segments: int, bounds):
    if bounds is not None:
        return bounds
    st = _bounds_stack()
    if st and st[-1].num == num_segments:
        return st[-1]
    return None


def _scatter_at(rows_mask, seg_ids, values, num_segments: int, fill):
    """values at flagged rows -> their segment's slot (one scatter-set;
    flagged rows are one-per-segment so indices are distinct)."""
    idx = jnp.where(rows_mask, seg_ids, num_segments).astype(jnp.int32)
    return jnp.full(num_segments, fill, values.dtype).at[idx].set(
        values, mode="drop")


def seg_sum(values, validity, seg_ids, num_segments: int, bounds=None):
    bounds = _active_bounds(num_segments, bounds)
    contrib = jnp.where(validity, values, jnp.zeros_like(values))
    if num_segments == 1:
        # global reduction: plain tree-reduce, no scatter
        return (jnp.sum(contrib, keepdims=True),
                jnp.sum(validity.astype(jnp.int64), keepdims=True) > 0)
    if bounds is not None and not jnp.issubdtype(values.dtype,
                                                 jnp.floating):
        # integer/decimal: cumsum-diff is exact (wrap cancels); floats
        # keep the scatter (cumsum-diff cancels across segments)
        return bounds.csum_diff(contrib), bounds.counts(validity) > 0
    s = jax.ops.segment_sum(contrib, seg_ids, num_segments=num_segments)
    cnt = jax.ops.segment_sum(validity.astype(jnp.int64), seg_ids,
                              num_segments=num_segments)
    return s, cnt > 0


def seg_count(validity, seg_ids, num_segments: int, bounds=None):
    bounds = _active_bounds(num_segments, bounds)
    if num_segments == 1:
        return jnp.sum(validity.astype(jnp.int64), keepdims=True)
    if bounds is not None:
        return bounds.counts(validity)
    return jax.ops.segment_sum(validity.astype(jnp.int64), seg_ids,
                               num_segments=num_segments)


def _seg_min_raw(v, seg_ids, num_segments: int, bounds=None):
    """Sorted-run min: re-sort within segments by value, pick segment
    starts, scatter to slots.  segment_min's scatter measured ~480ms at
    2M on TPU while sorts are near-free; associative_scan alternatives
    cost ~20s of XLA compile EACH (the round-4 compile hang), so this is
    the compile-cheap AND runtime-cheap form.  With bounds, the end
    scatter becomes B gathers at segment starts."""
    bounds = _active_bounds(num_segments, bounds)
    if num_segments == 1:
        return jnp.min(v, keepdims=True)
    fill = (jnp.asarray(jnp.inf, v.dtype)
            if jnp.issubdtype(v.dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(v.dtype).max, v.dtype))
    sv = jax.lax.sort((seg_ids, v), num_keys=2)[1]
    if bounds is not None:
        return bounds.gather_first(sv, fill)
    return _scatter_at(_starts_of(seg_ids), seg_ids, sv, num_segments,
                       fill)


def _seg_max_raw(v, seg_ids, num_segments: int, bounds=None):
    bounds = _active_bounds(num_segments, bounds)
    if num_segments == 1:
        return jnp.max(v, keepdims=True)
    fill = (jnp.asarray(-jnp.inf, v.dtype)
            if jnp.issubdtype(v.dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(v.dtype).min, v.dtype))
    sv = jax.lax.sort((seg_ids, v), num_keys=2)[1]
    if bounds is not None:
        return bounds.gather_last(sv, fill)
    starts = _starts_of(seg_ids)
    is_end = jnp.concatenate([starts[1:], jnp.ones(1, jnp.bool_)])
    return _scatter_at(is_end, seg_ids, sv, num_segments, fill)


def _seg_isum(v, seg_ids, num_segments: int, bounds=None):
    bounds = _active_bounds(num_segments, bounds)
    if num_segments == 1:
        return jnp.sum(v, keepdims=True)
    if bounds is not None:
        return bounds.csum_diff(v.astype(jnp.int64)).astype(v.dtype)
    return jax.ops.segment_sum(v, seg_ids, num_segments=num_segments)


def seg_min(values, validity, seg_ids, num_segments: int, is_float: bool,
            bounds=None):
    if is_float:
        nan = jnp.isnan(values)
        big = jnp.asarray(jnp.inf, values.dtype)
        v = jnp.where(validity & ~nan, values, big)
        m = _seg_min_raw(v, seg_ids, num_segments, bounds)
        valid_nonnan = _seg_isum(
            (validity & ~nan).astype(jnp.int32), seg_ids, num_segments,
            bounds) > 0
        any_valid = _seg_isum(
            validity.astype(jnp.int32), seg_ids, num_segments, bounds) > 0
        # all-NaN group -> NaN (NaN is greatest, min falls back to NaN
        # only when nothing else exists)
        m = jnp.where(valid_nonnan, m, jnp.asarray(jnp.nan, values.dtype))
        return m, any_valid
    if values.dtype == jnp.bool_:
        v = jnp.where(validity, values, True)
        m = _seg_min_raw(v.astype(jnp.int32), seg_ids,
                         num_segments, bounds).astype(jnp.bool_)
    else:
        big = jnp.asarray(jnp.iinfo(values.dtype).max, values.dtype)
        v = jnp.where(validity, values, big)
        m = _seg_min_raw(v, seg_ids, num_segments, bounds)
    any_valid = _seg_isum(validity.astype(jnp.int32), seg_ids,
                          num_segments, bounds) > 0
    return m, any_valid


def seg_max(values, validity, seg_ids, num_segments: int, is_float: bool,
            bounds=None):
    if is_float:
        nan = jnp.isnan(values)
        small = jnp.asarray(-jnp.inf, values.dtype)
        v = jnp.where(validity & ~nan, values, small)
        m = _seg_max_raw(v, seg_ids, num_segments, bounds)
        has_nan = _seg_isum(
            (validity & nan).astype(jnp.int32), seg_ids, num_segments,
            bounds) > 0
        any_valid = _seg_isum(
            validity.astype(jnp.int32), seg_ids, num_segments, bounds) > 0
        m = jnp.where(has_nan, jnp.asarray(jnp.nan, values.dtype), m)
        return m, any_valid
    if values.dtype == jnp.bool_:
        v = jnp.where(validity, values, False)
        m = _seg_max_raw(v.astype(jnp.int32), seg_ids,
                         num_segments, bounds).astype(jnp.bool_)
    else:
        small = jnp.asarray(jnp.iinfo(values.dtype).min, values.dtype)
        v = jnp.where(validity, values, small)
        m = _seg_max_raw(v, seg_ids, num_segments, bounds)
    any_valid = _seg_isum(validity.astype(jnp.int32), seg_ids,
                          num_segments, bounds) > 0
    return m, any_valid


def seg_first_index(seg_ids, row_mask, num_segments: int, bounds=None):
    """Index of the first row of each segment (for group-key extraction):
    rows are in segment order already, so the first VALID row index is
    the value at each segment start after a (seg, ~valid, iota) sort."""
    bounds = _active_bounds(num_segments, bounds)
    n = seg_ids.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    big = jnp.int32(n)
    _, inv_s, iota_s = jax.lax.sort(
        (seg_ids, (~row_mask).astype(jnp.int32), iota), num_keys=3)
    # a segment whose first sorted row is invalid has NO valid rows
    vals = jnp.where(inv_s == 0, iota_s, big)
    if bounds is not None:
        return bounds.gather_first(vals, big)
    return _scatter_at(_starts_of(seg_ids), seg_ids, vals,
                       num_segments, big)


# -- segmented scans (window running frames) --------------------------------

def _seg_scan(values, starts, combine):
    """Inclusive segmented scan: resets at rows where ``starts`` is True."""

    def op(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, combine(va, vb)), fa | fb

    out, _ = jax.lax.associative_scan(op, (values, starts))
    return out


def seg_scan_sum(values, validity, starts):
    """Segmented inclusive running sum via global cumsum minus the
    segment-base (cumsum/cummax lower to compact reduce-windows; a
    generic associative_scan costs ~20s of XLA compile per instance on
    TPU — round-4 finding).  Integer wrap cancels exactly in the
    subtraction; float running sums lose at most the usual cancellation
    (tests compare approximately)."""
    n = values.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    first = jax.lax.cummax(jnp.where(starts, iota, 0))

    def seg_csum(x):
        cs = jnp.cumsum(x)
        return cs - (cs[first] - x[first])

    contrib = jnp.where(validity, values, jnp.zeros_like(values))
    if jnp.issubdtype(contrib.dtype, jnp.floating):
        # the cumsum-difference cancels catastrophically when another
        # segment holds huge values; floats keep the exact segmented scan
        total = _seg_scan(contrib, starts, lambda a, b: a + b)
    else:
        total = seg_csum(contrib)   # integer wrap cancels exactly
    return total, seg_csum(validity.astype(jnp.int64))


def seg_scan_min(values, validity, starts, is_float: bool):
    if is_float:
        ident = jnp.asarray(jnp.inf, values.dtype)
        nan = jnp.isnan(values)
        v = jnp.where(validity & ~nan, values, ident)
        m = _seg_scan(v, starts, jnp.minimum)
        seen_nonnan = _seg_scan((validity & ~nan).astype(jnp.int32), starts,
                                lambda a, b: a + b) > 0
        m = jnp.where(seen_nonnan, m, jnp.asarray(jnp.nan, values.dtype))
        seen = _seg_scan(validity.astype(jnp.int32), starts,
                         lambda a, b: a + b) > 0
        return m, seen
    ident = jnp.asarray(jnp.iinfo(values.dtype).max, values.dtype)
    v = jnp.where(validity, values, ident)
    m = _seg_scan(v, starts, jnp.minimum)
    seen = _seg_scan(validity.astype(jnp.int32), starts,
                     lambda a, b: a + b) > 0
    return m, seen


def seg_scan_max(values, validity, starts, is_float: bool):
    if is_float:
        ident = jnp.asarray(-jnp.inf, values.dtype)
        nan = jnp.isnan(values)
        v = jnp.where(validity & ~nan, values, ident)
        m = _seg_scan(v, starts, jnp.maximum)
        seen_nan = _seg_scan((validity & nan).astype(jnp.int32), starts,
                             lambda a, b: a + b) > 0
        m = jnp.where(seen_nan, jnp.asarray(jnp.nan, values.dtype), m)
        seen = _seg_scan(validity.astype(jnp.int32), starts,
                         lambda a, b: a + b) > 0
        return m, seen
    ident = jnp.asarray(jnp.iinfo(values.dtype).min, values.dtype)
    v = jnp.where(validity, values, ident)
    m = _seg_scan(v, starts, jnp.maximum)
    seen = _seg_scan(validity.astype(jnp.int32), starts,
                     lambda a, b: a + b) > 0
    return m, seen


def seg_fold(values, validity, seg_ids, num_segments: int, op, identity,
             bounds=None):
    """Segmented fold for non-min/max/sum combines (bit_and/or/xor): the
    pair-scan segmented fold + one end scatter.  associative_scan costs
    ~20s of XLA compile per instance on TPU, acceptable for these rare
    aggregates."""
    bounds = _active_bounds(num_segments, bounds)
    v = jnp.where(validity, values, jnp.asarray(identity, values.dtype))
    starts = _starts_of(seg_ids)
    run = _seg_scan(v, starts, op)
    if bounds is not None:
        out = bounds.gather_last(run, identity)
        has = bounds.counts(validity) > 0
        return out, has
    is_end = jnp.concatenate([starts[1:], jnp.ones(1, jnp.bool_)])
    out = _scatter_at(is_end, seg_ids, run, num_segments,
                      jnp.asarray(identity, values.dtype))
    has = jax.ops.segment_sum(validity.astype(jnp.int32), seg_ids,
                              num_segments=num_segments) > 0
    return out, has
