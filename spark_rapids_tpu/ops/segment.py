"""Segmented reductions/scans — the TPU groupby/window primitive.

Reference analog: cuDF's hash `Table.groupBy().aggregate()` kernels
(SURVEY.md §2.10 item 2).  TPU-first: group-by is sort-based — rows sorted by
key, equal-key runs become segments, and `jax.ops.segment_*` performs the
reduction in one pass.  Null/NaN semantics follow Spark SQL:

  * aggregates skip nulls; a group with zero valid inputs yields null
    (except count, which yields 0);
  * float min/max treat NaN as the greatest value (Spark total order).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def seg_sum(values, validity, seg_ids, num_segments: int):
    contrib = jnp.where(validity, values, jnp.zeros_like(values))
    if num_segments == 1:
        # global reduction: plain tree-reduce, no scatter
        return (jnp.sum(contrib, keepdims=True),
                jnp.sum(validity.astype(jnp.int64), keepdims=True) > 0)
    s = jax.ops.segment_sum(contrib, seg_ids, num_segments=num_segments)
    cnt = jax.ops.segment_sum(validity.astype(jnp.int64), seg_ids,
                              num_segments=num_segments)
    return s, cnt > 0


def seg_count(validity, seg_ids, num_segments: int):
    if num_segments == 1:
        return jnp.sum(validity.astype(jnp.int64), keepdims=True)
    return jax.ops.segment_sum(validity.astype(jnp.int64), seg_ids,
                               num_segments=num_segments)


def _seg_min_raw(v, seg_ids, num_segments: int):
    if num_segments == 1:
        return jnp.min(v, keepdims=True)
    return jax.ops.segment_min(v, seg_ids, num_segments=num_segments)


def _seg_max_raw(v, seg_ids, num_segments: int):
    if num_segments == 1:
        return jnp.max(v, keepdims=True)
    return jax.ops.segment_max(v, seg_ids, num_segments=num_segments)


def _seg_isum(v, seg_ids, num_segments: int):
    if num_segments == 1:
        return jnp.sum(v, keepdims=True)
    return jax.ops.segment_sum(v, seg_ids, num_segments=num_segments)


def seg_min(values, validity, seg_ids, num_segments: int, is_float: bool):
    if is_float:
        nan = jnp.isnan(values)
        big = jnp.asarray(jnp.inf, values.dtype)
        v = jnp.where(validity & ~nan, values, big)
        m = _seg_min_raw(v, seg_ids, num_segments)
        valid_nonnan = _seg_isum(
            (validity & ~nan).astype(jnp.int32), seg_ids, num_segments) > 0
        any_valid = _seg_isum(
            validity.astype(jnp.int32), seg_ids, num_segments) > 0
        # all-NaN group -> NaN (NaN is greatest, min falls back to NaN
        # only when nothing else exists)
        m = jnp.where(valid_nonnan, m, jnp.asarray(jnp.nan, values.dtype))
        return m, any_valid
    if values.dtype == jnp.bool_:
        v = jnp.where(validity, values, True)
        m = _seg_min_raw(v.astype(jnp.int32), seg_ids,
                         num_segments).astype(jnp.bool_)
    else:
        big = jnp.asarray(jnp.iinfo(values.dtype).max, values.dtype)
        v = jnp.where(validity, values, big)
        m = _seg_min_raw(v, seg_ids, num_segments)
    any_valid = _seg_isum(validity.astype(jnp.int32), seg_ids,
                          num_segments) > 0
    return m, any_valid


def seg_max(values, validity, seg_ids, num_segments: int, is_float: bool):
    if is_float:
        nan = jnp.isnan(values)
        small = jnp.asarray(-jnp.inf, values.dtype)
        v = jnp.where(validity & ~nan, values, small)
        m = _seg_max_raw(v, seg_ids, num_segments)
        has_nan = _seg_isum(
            (validity & nan).astype(jnp.int32), seg_ids, num_segments) > 0
        any_valid = _seg_isum(
            validity.astype(jnp.int32), seg_ids, num_segments) > 0
        m = jnp.where(has_nan, jnp.asarray(jnp.nan, values.dtype), m)
        return m, any_valid
    if values.dtype == jnp.bool_:
        v = jnp.where(validity, values, False)
        m = _seg_max_raw(v.astype(jnp.int32), seg_ids,
                         num_segments).astype(jnp.bool_)
    else:
        small = jnp.asarray(jnp.iinfo(values.dtype).min, values.dtype)
        v = jnp.where(validity, values, small)
        m = _seg_max_raw(v, seg_ids, num_segments)
    any_valid = _seg_isum(validity.astype(jnp.int32), seg_ids,
                          num_segments) > 0
    return m, any_valid


def seg_first_index(seg_ids, row_mask, num_segments: int):
    """Index of the first row of each segment (for group-key extraction)."""
    n = seg_ids.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    big = jnp.int32(n)
    v = jnp.where(row_mask, iota, big)
    return jax.ops.segment_min(v, seg_ids, num_segments=num_segments)


# -- segmented scans (window running frames) --------------------------------

def _seg_scan(values, starts, combine):
    """Inclusive segmented scan: resets at rows where ``starts`` is True."""

    def op(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, combine(va, vb)), fa | fb

    out, _ = jax.lax.associative_scan(op, (values, starts))
    return out


def seg_scan_sum(values, validity, starts):
    contrib = jnp.where(validity, values, jnp.zeros_like(values))
    total = _seg_scan(contrib, starts, lambda a, b: a + b)
    cnt = _seg_scan(validity.astype(jnp.int64), starts, lambda a, b: a + b)
    return total, cnt


def seg_scan_min(values, validity, starts, is_float: bool):
    if is_float:
        ident = jnp.asarray(jnp.inf, values.dtype)
        nan = jnp.isnan(values)
        v = jnp.where(validity & ~nan, values, ident)
        m = _seg_scan(v, starts, jnp.minimum)
        seen_nonnan = _seg_scan((validity & ~nan).astype(jnp.int32), starts,
                                lambda a, b: a + b) > 0
        m = jnp.where(seen_nonnan, m, jnp.asarray(jnp.nan, values.dtype))
        seen = _seg_scan(validity.astype(jnp.int32), starts,
                         lambda a, b: a + b) > 0
        return m, seen
    ident = jnp.asarray(jnp.iinfo(values.dtype).max, values.dtype)
    v = jnp.where(validity, values, ident)
    m = _seg_scan(v, starts, jnp.minimum)
    seen = _seg_scan(validity.astype(jnp.int32), starts,
                     lambda a, b: a + b) > 0
    return m, seen


def seg_scan_max(values, validity, starts, is_float: bool):
    if is_float:
        ident = jnp.asarray(-jnp.inf, values.dtype)
        nan = jnp.isnan(values)
        v = jnp.where(validity & ~nan, values, ident)
        m = _seg_scan(v, starts, jnp.maximum)
        seen_nan = _seg_scan((validity & nan).astype(jnp.int32), starts,
                             lambda a, b: a + b) > 0
        m = jnp.where(seen_nan, jnp.asarray(jnp.nan, values.dtype), m)
        seen = _seg_scan(validity.astype(jnp.int32), starts,
                         lambda a, b: a + b) > 0
        return m, seen
    ident = jnp.asarray(jnp.iinfo(values.dtype).min, values.dtype)
    v = jnp.where(validity, values, ident)
    m = _seg_scan(v, starts, jnp.maximum)
    seen = _seg_scan(validity.astype(jnp.int32), starts,
                     lambda a, b: a + b) > 0
    return m, seen
