"""Sort-key packing: turn arbitrary typed columns into int64 key words whose
ascending unsigned-ish order equals the requested SQL ordering.

This is the workhorse behind sort, sort-based group-by, sort-merge join and
window partitioning (the TPU answer to cuDF's `Table.orderBy` comparators,
SURVEY.md §2.10 item 4).  Techniques:

  * signed ints -> order-preserving by using them directly as signed keys;
    descending -> bitwise negation.
  * doubles -> IEEE-754 total order trick: flip sign bit for positives,
    flip all bits for negatives; NaN sorts greatest (Spark semantics).
  * strings (padded char matrix) -> big-endian packed int64 words, 8 chars
    per word; padding 0x00 orders shorter strings first, matching UTF-8
    byte order.
  * nulls -> a leading per-column null-flag key encodes NULLS FIRST/LAST.

`jax.lax.sort` then sorts the tuple of key words lexicographically
(num_keys=k) carrying a row-index payload; everything downstream gathers
through that permutation.
"""
from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn


@dataclasses.dataclass(frozen=True)
class SortSpec:
    """One ORDER BY term: column + direction + null ordering.

    Matches Spark's SortOrder (GpuSortOrder analog)."""

    ascending: bool = True
    nulls_first: bool = True  # Spark default: NULLS FIRST for ASC, LAST for DESC


def _float_total_order(bits: jax.Array) -> jax.Array:
    """IEEE bits (int64) -> monotone *signed* key.

    Positive floats: sign bit clear, bit pattern already ascends as signed.
    Negative floats: flip all value bits (keep the sign bit) so they stay in
    the signed-negative range with order reversed: -inf -> most negative key,
    -0.0 -> -1 (just below +0.0 at 0).  NaN (0x7FF8...) lands above +inf.
    """
    return jnp.where(bits < 0,
                     bits ^ jnp.int64(0x7FFFFFFFFFFFFFFF), bits)


def float_order_key(vals: jax.Array) -> jax.Array:
    """float array -> monotone int64 key with Spark normalization: -0.0
    keys with 0.0, every NaN bit pattern collapses to one canonical key
    (and sorts greatest).  Shared by sort-key packing and the window
    RANGE-frame searchsorted, which must match the physical sort order
    bit-for-bit."""
    d = vals.astype(jnp.float64)
    d = jnp.where(d == 0.0, 0.0, d)
    bits = d.view(jnp.int64)
    canonical_nan = jnp.int64(0x7FF8000000000000)
    bits = jnp.where(jnp.isnan(d), canonical_nan, bits)
    return _float_total_order(bits)


def _column_key_words(c: DeviceColumn) -> List[jax.Array]:
    """int64 key word list for ASC NULLS-handled-separately ordering."""
    dt = c.dtype
    if c.is_string:
        w = c.width
        words = []
        nwords = (w + 7) // 8
        for wi in range(nwords):
            acc = jnp.zeros(c.capacity, jnp.int64)
            for b in range(8):
                ci = wi * 8 + b
                byte = (c.chars[:, ci].astype(jnp.int64)
                        if ci < w else jnp.zeros(c.capacity, jnp.int64))
                acc = (acc << 8) | byte
            # big-endian packed; values are in [0, 2^64) but we only ever
            # shift in 8 bytes -> top bit may be set; rebias to signed order
            acc = acc ^ jnp.int64(-9223372036854775808)
            words.append(acc)
        return words
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        return [float_order_key(c.data)]
    if isinstance(dt, T.BooleanType):
        return [c.data.astype(jnp.int64)]
    if isinstance(dt, T.DecimalType) and dt.is_128:
        from spark_rapids_tpu.expr.decimal128 import key_words, unpack

        hi, lo = unpack(c.data)
        return list(key_words(hi, lo))
    return [c.data.astype(jnp.int64)]


def pack_sort_keys(cols: Sequence[DeviceColumn],
                   specs: Sequence[SortSpec],
                   row_mask: jax.Array) -> List[jax.Array]:
    """Build the list of int64 key vectors for lax.sort.

    ``row_mask`` marks logical rows; padding rows sort after everything
    (key word +inf) so they stay at the tail.
    """
    keys: List[jax.Array] = []
    pad_hi = jnp.int64(9223372036854775807)
    for c, spec in zip(cols, specs):
        null_key = jnp.where(c.validity,
                             0 if spec.nulls_first else 0,
                             -1 if spec.nulls_first else 1).astype(jnp.int64)
        if not spec.ascending:
            null_key = null_key  # null ordering is explicit, not flipped
        keys.append(jnp.where(row_mask, null_key, pad_hi))
        for wkey in _column_key_words(c):
            k = wkey if spec.ascending else ~wkey
            # null rows: neutral key so null group is stable/contiguous
            k = jnp.where(c.validity, k, 0)
            keys.append(jnp.where(row_mask, k, pad_hi))
    return keys


def sort_permutation(cols: Sequence[DeviceColumn],
                     specs: Sequence[SortSpec],
                     row_mask: jax.Array,
                     stable_iota: bool = True) -> jax.Array:
    """Returns the row permutation realizing the ordering."""
    n = row_mask.shape[0]
    keys = pack_sort_keys(cols, specs, row_mask)
    payload = jnp.arange(n, dtype=jnp.int32)
    operands = tuple(keys) + (payload,)
    out = jax.lax.sort(operands, num_keys=len(keys), is_stable=stable_iota)
    return out[-1]


def group_segments(sorted_key_words: Sequence[jax.Array],
                   row_mask_sorted: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Given key words already in sorted row order, return (segment_ids,
    num_groups) where equal consecutive keys share a segment id.

    Padding rows (mask False) all land in the last segment and are excluded
    from num_groups.
    """
    n = row_mask_sorted.shape[0]
    change = jnp.zeros(n, jnp.bool_)
    for k in sorted_key_words:
        prev = jnp.concatenate([k[:1], k[:-1]])
        change = change | (k != prev)
    change = change.at[0].set(True)
    seg = jnp.cumsum(change.astype(jnp.int32)) - 1
    num_groups = jnp.where(
        jnp.any(row_mask_sorted),
        seg[jnp.sum(row_mask_sorted.astype(jnp.int32)) - 1] + 1, 0)
    return seg, num_groups
