"""MXU-backed small-table gather: one_hot(idx, D) @ table.

Reference analog: cuDF's gather kernels scatter through HBM with native
random-access bandwidth; on TPU a random gather of N elements runs on the
VPU at ~1/20 of sequential bandwidth (~300ms for 20M rows even from a
VMEM-resident table — round-5 calibration), while the MXU contracts a
fused one_hot×table product in single-digit milliseconds.  For D-row
build tables (broadcast joins, dictionary decode) with D up to a few
thousand, XLA fuses the one-hot into the dot so the (N, D) selector is
never materialized.

Exactness: every output row selects exactly ONE table row (one-hot), so
each dot term is a single product with no accumulation — exact as long
as each operand survives the matmul input precision.  TPU matmuls run
bf16 passes at DEFAULT precision (8 mantissa bits), so payloads are
split into 8-bit limbs of their (unsigned) bit pattern and recombined
with integer shifts, making the gather bit-exact for every flat dtype
on both the CPU backend and the real chip.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn

# 8-bit limbs: one-hot rows have a single 1, so a dot term is a single
# product of 1.0 * limb.  TPU matmuls run at DEFAULT precision (bf16
# passes, 8 mantissa bits) — limbs must stay < 2^8 to survive bf16
# exactly (13-bit limbs decoded correctly on the CPU backend but
# truncated on the real chip: round-5 on-chip finding).
_LIMB_BITS = 8
_LIMB_MASK = (1 << _LIMB_BITS) - 1

MAX_TABLE_ROWS = 8192      # beyond this the one-hot contraction's N*D
#                            FLOPs stop being free; callers check


def _limbs_of(table: jax.Array) -> jax.Array:
    """(D,) integer/bool array -> (D, L) f32 limb matrix (bit pattern)."""
    if table.dtype == jnp.bool_:
        u = table.astype(jnp.uint32)
        nbits = 1
    else:
        nbits = table.dtype.itemsize * 8
        if nbits < 32:
            u = table.astype(jnp.int32).view(jnp.uint32) \
                & jnp.uint32((1 << nbits) - 1)
        else:
            u = table.view(jnp.uint32 if nbits == 32 else jnp.uint64)
    nl = -(-nbits // _LIMB_BITS)
    limbs = [((u >> (i * _LIMB_BITS)) & _LIMB_MASK).astype(jnp.float32)
             for i in range(nl)]
    return jnp.stack(limbs, axis=1)


def _recombine(out_f: jax.Array, dtype) -> jax.Array:
    """(N, L) f32 limb matrix -> (N,) array of dtype (bit pattern)."""
    if dtype == jnp.bool_:
        return out_f[:, 0] > 0.5
    nbits = jnp.dtype(dtype).itemsize * 8
    wide = jnp.uint32 if nbits <= 32 else jnp.uint64
    acc = jnp.zeros(out_f.shape[0], wide)
    for i in range(out_f.shape[1]):
        acc = acc | (out_f[:, i].astype(wide) << (i * _LIMB_BITS))
    if nbits < 32:
        # sign-extend sub-word types through int32
        acc32 = acc.astype(jnp.uint32)
        shifted = (acc32 << (32 - nbits)).view(jnp.int32) >> (32 - nbits)
        return shifted.astype(dtype)
    return acc.view(jnp.int32 if nbits == 32 else jnp.int64).astype(dtype) \
        if not jnp.issubdtype(dtype, jnp.floating) \
        else acc.view(dtype)


def mxu_gather(table: jax.Array, idx: jax.Array) -> jax.Array:
    """table[(idx,)] via the MXU; bit-exact for every flat dtype."""
    d = table.shape[0]
    oh = jax.nn.one_hot(idx, d, dtype=jnp.float32)
    if table.ndim == 2 and table.dtype == jnp.uint8:
        # char matrix: each byte column is its own (<256, bf16-exact) limb
        out = oh @ table.astype(jnp.float32)
        return jnp.round(out).astype(jnp.uint8)
    limbs = _limbs_of(table)
    out_f = oh @ limbs
    return _recombine(jnp.round(out_f), table.dtype)


def mxu_gather_col(c: DeviceColumn, idx: jax.Array):
    """DeviceColumn gather via the MXU, or None when the layout is not
    eligible (nested/array columns keep the VPU gather)."""
    if c.children is not None or c.elem_valid is not None:
        return None
    validity = mxu_gather(c.validity, idx)
    if c.chars is not None and c.chars.ndim == 2:
        chars = mxu_gather(c.chars, idx)
        lengths = mxu_gather(c.lengths, idx)
        return DeviceColumn(c.dtype, validity, chars=chars,
                            lengths=lengths)
    if c.data is None:
        return None
    if c.data.ndim == 1:
        return DeviceColumn(c.dtype, validity, data=mxu_gather(c.data, idx))
    if c.data.ndim == 2 and c.data.shape[1] == 2:      # decimal128
        hi = mxu_gather(c.data[:, 0], idx)
        lo = mxu_gather(c.data[:, 1], idx)
        return DeviceColumn(c.dtype, validity,
                            data=jnp.stack([hi, lo], axis=1))
    return None
