"""Per-tenant result-fragment cache (ISSUE 19 tentpole).

A plan-signature -> collected-rows cache for repeated dashboard
queries — the ``io/hot_cache.py`` fingerprint-keyed pattern one level
up: where the hot-table cache short-circuits the SCAN, this cache
short-circuits the whole collect (no planning, no compile, no device
work; "Accelerating Presto with GPUs", arXiv:2606.24647, finds serving
workloads dominated by exactly these repeats).

Keying and isolation: fragments are keyed by
``fingerprint(result_plan_key(root), session-conf items, tenant)``
(``compilecache/keys.py``).  ``result_plan_key`` is a VALUE-level plan
identity — per-node ``describe()`` strings (expressions and literals
printed), content digests for in-memory leaf data, file paths +
pushdown for file scans — because the telemetry plan *signature*
(node names only) would collide two queries that differ only in a
literal or in their data.  Plans carrying expressions the
compile-cache fingerprints call unsafe (UDFs, rand, clocks) are never
cached.  Entries additionally stamp the owning tenant — a lookup
under a different tenant MISSES even on a key collision, so
cross-tenant visibility of cached rows is structurally impossible
(the pinned zero-leak test).

Accounting: every fragment is charged to the PRODUCING query's
resource bill (ISSUE 18) as persistent bytes — like df.cache()
handles, intentionally retained beyond the query, excluded from the
residual leak gate — and released on eviction (the ledger's
late-charge/late-release paths keep settled bills truthful).

Eviction: LRU over ``serving.resultCache.maxBytes`` at insert;
``evict_to_bytes`` joins the governor's RED ladder next to the
hot-table cache (cached convenience data is the first ballast
overboard); ``drop_tenant`` at session close releases everything the
tenant owned.
"""
from __future__ import annotations

import hashlib
import sys
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from spark_rapids_tpu import perfcounters as PC


def _host_columns_digest(cols) -> str:
    """Content digest of in-memory leaf data (HostColumn buffers) —
    two create_dataframe leaves with the same schema but different
    values must never share a result fragment."""
    import numpy as np

    h = hashlib.sha1()
    for c in cols:
        h.update(str(c.dtype).encode("utf-8", "replace"))
        for buf in (c.validity, c.data, c.chars, c.lengths,
                    c.elem_valid):
            if buf is not None:
                h.update(np.ascontiguousarray(buf).tobytes())
        if c.children:
            h.update(_host_columns_digest(c.children).encode())
    return h.hexdigest()


def _node_has_unsafe_expr(node) -> bool:
    """Best-effort sweep for expressions whose value is not a function
    of the plan text (UDF callables, rand/uuid, clock captures) — the
    compile-cache ``_expr_unsafe`` verdict applied to every
    expression-looking attribute the node carries.  Caching such a
    plan's ROWS would freeze nondeterminism even harder than sharing
    its executable would."""
    from spark_rapids_tpu.compilecache.keys import _expr_unsafe

    try:
        attrs = vars(node).values()
    except TypeError:
        return False

    def scan(v) -> bool:
        if callable(getattr(v, "sql_string", None)):
            return _expr_unsafe(v)
        if isinstance(v, (list, tuple)):
            return any(scan(x) for x in v)
        return False

    return any(scan(v) for v in attrs)


def result_plan_key(root) -> Optional[tuple]:
    """Value-level identity of a planned exec tree, or None when the
    plan refuses one (the hot-cache scan_key discipline: shaky ground
    is never cached).  Per node: the ``describe()`` string — literals,
    expressions, join keys, and sort orders all print — plus a content
    digest for in-memory leaf data and paths + pushdown for file
    scans.  ``df.cache()`` nodes key on their NAME (describe() says
    hit/cold — execution state, not identity; the child subtree below
    them supplies the identity)."""
    from spark_rapids_tpu.exec.base import TpuExec

    parts: List[tuple] = []

    def walk(node, path: str) -> None:
        name = type(node).__name__
        if _node_has_unsafe_expr(node):
            raise ValueError(f"unsafe expression under {name}")
        desc = name if name == "TpuInMemoryTableScanExec" \
            else node.describe()
        parts.append((path, name, desc))
        hc = getattr(node, "host_columns", None)
        if hc is not None:
            parts.append((path, "data", _host_columns_digest(hc)))
        plan = getattr(node, "plan", None)
        if plan is not None and hasattr(plan, "paths"):
            parts.append((path, "files", tuple(plan.paths),
                          repr(getattr(plan, "pushed_filters", None)),
                          repr(getattr(plan, "options", None))))
        for i, c in enumerate(getattr(node, "children", ())):
            if isinstance(c, TpuExec):
                walk(c, f"{path}.{i}")

    try:
        walk(root, "0")
    # tpulint: disable=cancel-swallow (identity probe: a plan that
    # refuses a stable key falls through to the normal uncached
    # collect, which raises any real error with full context)
    except Exception:
        return None
    return tuple(parts)


def estimate_rows_bytes(rows: List[tuple]) -> int:
    """Cheap host-side size estimate: sample up to 64 rows' shallow +
    element sizes and scale.  An estimate is enough — the bound and the
    bills need proportionality, not byte exactness."""
    n = len(rows)
    if n == 0:
        return 64
    sample = rows[:64]
    per = 0
    for r in sample:
        per += sys.getsizeof(r)
        try:
            per += sum(sys.getsizeof(v) for v in r)
        except TypeError:
            pass
    return max(64, int(per / len(sample) * n))


class _Fragment:
    __slots__ = ("rows", "tenant", "owner_qid", "nbytes")

    def __init__(self, rows, tenant, owner_qid, nbytes):
        self.rows = rows
        self.tenant = tenant
        self.owner_qid = owner_qid
        self.nbytes = int(nbytes)


class ResultFragmentCache:
    """LRU host-rows cache; ``_lock`` is a leaf (order:
    _lock -> PC._LOCK / ledger._lock via the release helper)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Fragment]" = OrderedDict()
        self._bytes = 0

    # -- internals -------------------------------------------------------
    @staticmethod
    def _release_bill(frag: _Fragment) -> None:
        """Return the fragment's bytes to the owner's bill (late
        release on settled bills is supported)."""
        from spark_rapids_tpu.accounting import context as _acct

        if _acct.LEDGERS is not None:
            _acct.LEDGERS.release_device(
                frag.owner_qid, frag.nbytes, persistent=True)

    def _pop_lru_locked(self) -> Optional[_Fragment]:
        if not self._entries:
            return None
        _key, frag = self._entries.popitem(last=False)
        self._bytes -= frag.nbytes
        return frag

    # -- the cache -------------------------------------------------------
    def get(self, key: str, tenant: str) -> Optional[List[tuple]]:
        """The cached rows, or None.  The tenant stamp must match —
        a cross-tenant lookup is a MISS by construction."""
        with self._lock:
            frag = self._entries.get(key)
            if frag is None or frag.tenant != tenant:
                frag = None
            else:
                self._entries.move_to_end(key)
        if frag is None:
            PC.bump("result_cache_misses")
            return None
        PC.bump("result_cache_hits")
        return frag.rows

    def put(self, key: str, tenant: str, rows: List[tuple],
            owner_qid: Optional[str]) -> None:
        nbytes = estimate_rows_bytes(rows)
        if nbytes > self.max_bytes:
            return                       # would evict everything else
        evicted: List[_Fragment] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                evicted.append(old)
            frag = _Fragment(list(rows), tenant, owner_qid, nbytes)
            self._entries[key] = frag
            self._bytes += nbytes
            while self._bytes > self.max_bytes:
                victim = self._pop_lru_locked()
                if victim is None:
                    break
                evicted.append(victim)
        # bills + counters outside the lock
        from spark_rapids_tpu.accounting import context as _acct

        if _acct.LEDGERS is not None:
            _acct.LEDGERS.charge_device(owner_qid, nbytes, persistent=True)
        for frag in evicted:
            PC.bump("result_cache_evictions")
            self._release_bill(frag)

    def evict_to_bytes(self, target: int) -> int:
        """LRU-evict until at most ``target`` bytes remain (the
        governor's RED ladder); returns bytes evicted."""
        evicted: List[_Fragment] = []
        with self._lock:
            while self._bytes > max(0, int(target)):
                victim = self._pop_lru_locked()
                if victim is None:
                    break
                evicted.append(victim)
        for frag in evicted:
            PC.bump("result_cache_evictions")
            self._release_bill(frag)
        return sum(f.nbytes for f in evicted)

    def drop_tenant(self, tenant: str) -> int:
        """Release every fragment the tenant owns (session close);
        returns the count dropped."""
        dropped: List[_Fragment] = []
        with self._lock:
            for key in [k for k, f in self._entries.items()
                        if f.tenant == tenant]:
                frag = self._entries.pop(key)
                self._bytes -= frag.nbytes
                dropped.append(frag)
        for frag in dropped:
            self._release_bill(frag)
        return len(dropped)

    def clear(self) -> None:
        with self._lock:
            dropped = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
        for frag in dropped:
            self._release_bill(frag)

    # -- introspection ---------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            by_tenant: Dict[str, int] = {}
            for f in self._entries.values():
                by_tenant[f.tenant] = by_tenant.get(f.tenant, 0) + 1
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "by_tenant": by_tenant}

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted({f.tenant for f in self._entries.values()})
