"""Weighted fair-share admission policy (ISSUE 19 tentpole).

Replaces the FIFO admission order: when the serving tier is active it
installs a :class:`FairShareScheduler` into
``lifecycle/admission.SCHEDULER``, and the next free slot goes to the
eligible waiter whose tenant has the LOWEST normalized usage
(decayed usage units / weight) — classic stride scheduling over a
decaying usage account, the shape Theseus (arXiv:2508.05029) argues
decides whether an accelerated SQL platform serves or collapses.

Usage accounting:

* ``on_admit`` charges 1.0 unit at ADMISSION — never while waiting, so
  a rejected or timed-out query costs its tenant's share nothing (the
  ISSUE 19 retry_after_ms satellite's other half, pinned by test).
* ``note_query_end`` charges the query's wall seconds at lifecycle
  exit, so a tenant of few-but-heavy queries weighs the same as one of
  many-but-light queries.
* Both decay with half-life ``spark.rapids.tpu.serving.usageHalflifeS``
  so an idle tenant's history fades and it re-approaches its full
  share instead of being punished forever.

Quotas bound CONCURRENCY, not throughput: a tenant at its quota is
ineligible while any under-quota tenant waits, but the policy is
work-conserving — with only over-quota waiters the slot is still
granted (an idle device serves nobody).

Starvation-proofing falls out of the math: a light tenant's normalized
usage is always below a flooding tenant's, so its occasional queries
win every selection they enter — a heavy tenant at 10x submit rate
cannot push the light tenant's p95 past its SLO (the pinned
starved-tenant test).

Lock discipline: ``select``/``admissible``/``on_admit`` are called
while the admission controller holds its condition — ``_lock`` here is
a LEAF (dict/arithmetic only; order: admission._cond -> _lock).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Mapping, Optional


def parse_tenant_map(spec: str) -> Dict[str, float]:
    """Parse ``'tenantA:4,tenantB:1'`` (whitespace tolerated).  A bad
    entry raises ValueError at tier construction — a serving-conf typo
    must fail loudly, not silently grant default shares."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.rpartition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"bad tenant map entry {part!r} (want 'tenant:number')")
        try:
            out[name] = float(val)
        except ValueError:
            raise ValueError(
                f"bad tenant map entry {part!r} (want 'tenant:number')")
    return out


class FairShareScheduler:
    """Per-tenant decaying usage accounts + the selection policy."""

    def __init__(self, weights: Optional[Mapping[str, float]] = None,
                 quotas: Optional[Mapping[str, float]] = None,
                 halflife_s: float = 30.0):
        self._lock = threading.Lock()
        self._weights = {k: float(v) for k, v in (weights or {}).items()}
        self._quotas = {k: int(v) for k, v in (quotas or {}).items()}
        self._halflife_s = max(float(halflife_s), 1e-3)
        # tenant -> [decayed usage units, monotonic seconds of last touch]
        self._usage: Dict[str, list] = {}

    # -- static config ---------------------------------------------------
    def weight(self, tenant: str) -> float:
        return max(self._weights.get(tenant, 1.0), 1e-9)

    def quota(self, tenant: str) -> int:
        """Max concurrent running queries; 0 = unbounded."""
        return self._quotas.get(tenant, 0)

    # -- usage accounts --------------------------------------------------
    def _decayed_locked(self, tenant: str, now_s: float) -> float:
        row = self._usage.get(tenant)
        if row is None:
            return 0.0
        if now_s > row[1]:
            row[0] *= 0.5 ** ((now_s - row[1]) / self._halflife_s)
            row[1] = now_s
        return row[0]

    def charge(self, tenant: str, units: float) -> None:
        now = time.monotonic()
        with self._lock:
            val = self._decayed_locked(tenant, now)
            self._usage[tenant] = [val + float(units), now]

    def on_admit(self, tenant: str) -> None:
        """Charged at ADMISSION only — a query that waited and was
        rejected (queue timeout, shed) never reaches here, so its wait
        costs the tenant nothing."""
        self.charge(tenant, 1.0)

    def note_query_end(self, tenant: str, wall_ns: int) -> None:
        self.charge(tenant, wall_ns / 1e9)

    def normalized_usage(self, tenant: str) -> float:
        """The fair-share rank: decayed usage / weight (lower = more
        entitled to the next slot)."""
        now = time.monotonic()
        with self._lock:
            return self._decayed_locked(tenant, now) / self.weight(tenant)

    def usage_snapshot(self) -> Dict[str, float]:
        """tenant -> normalized usage (sampler / stress-harness
        surface)."""
        now = time.monotonic()
        with self._lock:
            return {t: self._decayed_locked(t, now) / self.weight(t)
                    for t in list(self._usage)}

    # -- admission policy (caller holds admission._cond) -----------------
    def admissible(self, tenant: str, running_by: Mapping[str, int]) -> bool:
        q = self.quota(tenant)
        return q <= 0 or int(running_by.get(tenant, 0)) < q

    def select(self, waiters: Iterable, running_by: Mapping[str, int]):
        """The fair-share pick among queued tickets (objects carrying
        ``.tenant``): under-quota tenants outrank over-quota ones, then
        lowest normalized usage, then FIFO arrival — deterministic and
        O(#waiters)."""
        now = time.monotonic()
        best = None
        best_key = None
        with self._lock:
            for idx, ticket in enumerate(waiters):
                t = ticket.tenant
                u = self._decayed_locked(t, now) / self.weight(t)
                key = (0 if self.admissible(t, running_by) else 1, u, idx)
                if best_key is None or key < best_key:
                    best, best_key = ticket, key
        return best

    # -- governor policy (tenant-aware shed / preempt) -------------------
    def most_starved(self, tenants: Iterable[str]) -> Optional[str]:
        """Among ``tenants`` (names with live demand), the one with the
        lowest normalized usage — the governor never sheds its
        queries."""
        now = time.monotonic()
        with self._lock:
            return min(
                tenants,
                key=lambda t: (self._decayed_locked(t, now)
                               / self.weight(t), t),
                default=None)

    def shed_decision(self, tenant: str,
                      running_by: Mapping[str, int],
                      demand: Iterable[str]) -> str:
        """Under RED: ``"never"`` for the most-starved tenant with
        demand (its queries pass through to the deadline predictor
        untouched is NOT enough — they are exempt from shedding
        entirely), ``"shed"`` for a tenant at/over its running quota
        (the over-quota tenant pays first), ``"maybe"`` otherwise (the
        deadline-aware predictor decides)."""
        names = set(demand)
        names.add(tenant)
        if self.most_starved(names) == tenant:
            return "never"
        q = self.quota(tenant)
        if q > 0 and int(running_by.get(tenant, 0)) >= q:
            return "shed"
        return "maybe"
