"""Multi-tenant serving tier (ISSUE 19 tentpole).

Composes the primitives the repo already has — admission + deadlines
(lifecycle/), SLO histograms (telemetry/), the overload governor
(governor/), resource bills (accounting/) — into a long-running
SERVICE: named tenant sessions with hard isolation, weighted fair-share
scheduling over the admission queue, tenant-aware shed/preempt, and a
per-tenant result-fragment cache.  "Accelerating Presto with GPUs"
(arXiv:2606.24647) is exactly this serving shape; Theseus
(arXiv:2508.05029) argues the scheduler layer is where accelerated SQL
platforms win or lose.

  * context.py      — the ambient TIER / RESULT_CACHE slots (one
                      module-attribute read per instrumented site).
  * fair_share.py   — FairShareScheduler: decaying per-tenant usage
                      accounts, weights, quotas, and the selection /
                      shed / preempt policies.
  * result_cache.py — ResultFragmentCache: plan-signature-keyed
                      collected rows, per-tenant scoped, bill-charged,
                      on the governor's RED eviction ladder.

Isolation contract (the pinned zero-cross-tenant-leak test): a tenant
session OWNS its conf (its own TpuSession/TpuConf — a set_conf never
leaks), its temp views (a plain per-session registry), its df.cache()
handles (tracked; unpersisted at close), and its result fragments
(tenant-stamped; dropped at close).  Cross-tenant visibility of any of
those is a bug, and an unclosed session or an orphaned fragment fails
the owning test through the conftest leak gate.

Disabled path: ``spark.rapids.tpu.serving.enabled`` defaults false;
nothing imports this package and no serving-module call is made
(cProfile-pinned).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from spark_rapids_tpu.serving import context as _ctx
from spark_rapids_tpu.serving.fair_share import (
    FairShareScheduler,
    parse_tenant_map,
)
from spark_rapids_tpu.serving.result_cache import ResultFragmentCache

_LOCK = threading.Lock()


class ServingSession:
    """One named tenant's isolated handle on the engine.

    Wraps a private ``TpuSession`` whose conf carries
    ``spark.rapids.tpu.serving.tenant=<name>`` — every collect's
    QueryContext, fair-share charge, SLO series, and governor decision
    attributes to this tenant.  Never shares conf, temp views, cache
    handles, or result fragments with any other session."""

    def __init__(self, tier: "ServingTier", tenant: str,
                 conf_overrides: Optional[dict] = None):
        from spark_rapids_tpu.session import TpuSession

        self.tenant = tenant
        self.closed = False
        self._tier = tier
        settings = dict(tier.base_settings)
        settings.update(conf_overrides or {})
        settings["spark.rapids.tpu.serving.tenant"] = tenant
        self._spark = TpuSession(settings)
        self._views: Dict[str, object] = {}
        self._cached: List[object] = []

    # -- the wrapped engine ----------------------------------------------
    @property
    def spark(self):
        """The underlying TpuSession (createDataFrame / read / conf)."""
        self._check_open()
        return self._spark

    def _check_open(self) -> None:
        if self.closed:
            raise RuntimeError(
                f"serving session '{self.tenant}' is closed")

    def set_conf(self, key: str, value) -> "ServingSession":
        """Session-scoped conf — lands on this tenant's private
        TpuSession only."""
        self._check_open()
        self._spark.set_conf(key, value)
        return self

    def get_conf(self, key: str) -> Optional[str]:
        self._check_open()
        return self._spark.conf.settings.get(key)

    # -- temp views (per-session registry; no cross-tenant lookup) -------
    def create_temp_view(self, name: str, df) -> None:
        self._check_open()
        self._views[name] = df

    def view(self, name: str):
        self._check_open()
        try:
            return self._views[name]
        except KeyError:
            raise KeyError(
                f"temp view '{name}' not found in serving session "
                f"'{self.tenant}' (views are session-scoped; another "
                f"tenant's views are never visible)") from None

    def temp_views(self) -> List[str]:
        self._check_open()
        return sorted(self._views)

    def drop_temp_view(self, name: str) -> bool:
        self._check_open()
        return self._views.pop(name, None) is not None

    # -- tracked df.cache() handles --------------------------------------
    def cache(self, df):
        """``df.cache()`` tracked by this session so close() releases
        the device batches even if the caller forgot unpersist()."""
        self._check_open()
        cached = df.cache()
        self._cached.append(cached)
        return cached

    # -- the serving collect (result-fragment cache) ---------------------
    def _result_key(self, df) -> Optional[str]:
        """fingerprint(value-level plan identity, session conf,
        tenant), or None when the plan refuses a stable key — shaky
        ground is never cached (the hot-cache scan_key discipline).
        ``result_plan_key`` (not the telemetry plan *signature*, which
        is node names only) so two queries differing in a literal or
        in their leaf data never share a fragment."""
        from spark_rapids_tpu.compilecache.keys import fingerprint
        from spark_rapids_tpu.serving.result_cache import result_plan_key

        try:
            root, _meta = df._planned()
        # tpulint: disable=cancel-swallow (planning probe: an unplannable
        # frame falls through to the normal collect path, which raises
        # the real error with full context)
        except Exception:
            return None
        parts = result_plan_key(root)
        if parts is None:
            return None
        conf_items = tuple(sorted(
            (str(k), str(v)) for k, v in df.session.conf.settings.items()))
        return fingerprint("serving-result", parts, conf_items, self.tenant)

    def collect(self, df) -> List[tuple]:
        """``df.collect()`` through the result-fragment cache: a repeat
        of a cached (plan, conf) returns the stored rows — no admission
        slot, no compile, no device work — and a miss stores the rows
        charged to the producing query's bill."""
        self._check_open()
        rc = _ctx.RESULT_CACHE
        key = self._result_key(df) if rc is not None else None
        if key is not None:
            rows = rc.get(key, self.tenant)
            if rows is not None:
                return list(rows)
        out = df.collect()
        if key is not None:
            from spark_rapids_tpu.lifecycle import last_query_stats

            stats = last_query_stats()
            owner = stats.get("query_id") if stats else None
            rc.put(key, self.tenant, out, owner)
        return out

    # -- teardown --------------------------------------------------------
    def close(self) -> None:
        """Release everything the session owns: cached device batches,
        temp views, and this tenant's result fragments.  Idempotent."""
        if self.closed:
            return
        self.closed = True
        while self._cached:
            df = self._cached.pop()
            try:
                df.unpersist()
            # tpulint: disable=cancel-swallow (session teardown: a
            # handle already closed by query cleanup is not an error)
            except Exception:
                pass
        self._views.clear()
        rc = _ctx.RESULT_CACHE
        if rc is not None:
            rc.drop_tenant(self.tenant)
        from spark_rapids_tpu import perfcounters as PC

        PC.bump("serving_sessions_closed")


class ServingTier:
    """The process-wide serving tier: the session registry, the
    fair-share scheduler, and the result-fragment cache."""

    def __init__(self, conf):
        from spark_rapids_tpu.config import (
            SERVING_QUOTAS,
            SERVING_USAGE_HALFLIFE_S,
            SERVING_WEIGHTS,
        )

        self.base_settings = dict(conf.settings)
        self.scheduler = FairShareScheduler(
            weights=parse_tenant_map(str(conf.get(SERVING_WEIGHTS) or "")),
            quotas=parse_tenant_map(str(conf.get(SERVING_QUOTAS) or "")),
            halflife_s=float(conf.get(SERVING_USAGE_HALFLIFE_S)))
        self._lock = threading.Lock()
        self._sessions: Dict[str, ServingSession] = {}

    # -- sessions --------------------------------------------------------
    def session(self, tenant: str,
                conf_overrides: Optional[dict] = None) -> ServingSession:
        """The tenant's open session, created on first use (named
        sessions: one live session per tenant name)."""
        if not tenant:
            raise ValueError("serving sessions require a tenant name")
        from spark_rapids_tpu import perfcounters as PC

        with self._lock:
            s = self._sessions.get(tenant)
            if s is not None and not s.closed:
                return s
            s = ServingSession(self, tenant, conf_overrides)
            self._sessions[tenant] = s
        PC.bump("serving_sessions_opened")
        return s

    def close_session(self, tenant: str) -> None:
        with self._lock:
            s = self._sessions.pop(tenant, None)
        if s is not None:
            s.close()

    def tenants(self) -> List[str]:
        """Tenants with an OPEN session."""
        with self._lock:
            return sorted(t for t, s in self._sessions.items()
                          if not s.closed)

    # -- leak gate surface -----------------------------------------------
    def leak_report(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            open_tenants = {t for t, s in self._sessions.items()
                            if not s.closed}
            for t in sorted(open_tenants):
                out.append(
                    f"LEAK: serving session '{t}' left open (its conf, "
                    "temp views, cache handles, and result fragments "
                    "are still live)")
        rc = _ctx.RESULT_CACHE
        if rc is not None:
            for t in rc.tenants():
                if t not in open_tenants:
                    out.append(
                        f"LEAK: result-cache fragments for tenant "
                        f"'{t}' outlive its session (close() must "
                        "drop them)")
        return out

    def shutdown(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.close()


# ---------------------------------------------------------------------------
# the ambient singleton (governor/__init__.py pattern)
# ---------------------------------------------------------------------------

def ensure_serving(conf) -> Optional[ServingTier]:
    """Build (idempotently) the serving tier when
    ``spark.rapids.tpu.serving.enabled`` is set; None when disabled.
    Installs the fair-share scheduler into the admission module and the
    result-fragment cache into its ambient slot."""
    from spark_rapids_tpu.config import (
        SERVING_ENABLED,
        SERVING_RESULT_CACHE_ENABLED,
        SERVING_RESULT_CACHE_MAX_BYTES,
    )

    if not bool(conf.get(SERVING_ENABLED)):
        return None
    with _LOCK:
        if _ctx.TIER is None:
            tier = ServingTier(conf)
            from spark_rapids_tpu.lifecycle import admission as _adm

            _adm.SCHEDULER = tier.scheduler
            if bool(conf.get(SERVING_RESULT_CACHE_ENABLED)):
                _ctx.RESULT_CACHE = ResultFragmentCache(
                    int(conf.get(SERVING_RESULT_CACHE_MAX_BYTES)))
            _ctx.TIER = tier
        return _ctx.TIER


def peek_serving() -> Optional[ServingTier]:
    """The tier if it exists — never creates one (sampler/governor
    discipline)."""
    return _ctx.TIER


def peek_result_cache() -> Optional[ResultFragmentCache]:
    return _ctx.RESULT_CACHE


def shutdown_serving() -> None:
    """Tear the tier down: close every session, uninstall the
    fair-share scheduler (admission reverts to FIFO), drop the result
    cache."""
    with _LOCK:
        tier = _ctx.TIER
        rc = _ctx.RESULT_CACHE
        _ctx.TIER = None
        _ctx.RESULT_CACHE = None
        from spark_rapids_tpu.lifecycle import admission as _adm

        _adm.SCHEDULER = None
    if tier is not None:
        tier.shutdown()
    if rc is not None:
        rc.clear()


def leak_report() -> List[str]:
    """Serving-side leak report for ``lifecycle.leak_report_all`` (one
    ambient check; empty while serving is off)."""
    tier = _ctx.TIER
    return tier.leak_report() if tier is not None else []


__all__ = [
    "FairShareScheduler", "ResultFragmentCache", "ServingSession",
    "ServingTier", "ensure_serving", "leak_report", "parse_tenant_map",
    "peek_result_cache", "peek_serving", "shutdown_serving",
]
