"""The ambient serving-tier slots (ISSUE 19).

Split from ``serving/__init__.py`` so hot paths can do ONE module
attribute read (``_SRV.TIER is None`` / ``_SRV.RESULT_CACHE is None``)
without importing any serving machinery — the governor/context.py
pattern.  Default sessions never create a tier, so the disabled path
makes zero serving-module calls (cProfile-pinned)."""
from __future__ import annotations

# The live ServingTier, or None while serving is disabled/shut down.
# Mutated only by serving.ensure_serving / serving.shutdown_serving
# under serving._LOCK.
TIER = None

# The live ResultFragmentCache — a separate slot so the governor's RED
# eviction ladder peeks it without walking the tier.
RESULT_CACHE = None
