"""TpuOverrides — the plan-rewrite registry (the product's core).

Reference analog: com/nvidia/spark/rapids/GpuOverrides.scala (~4,800 LoC):
a registry mapping every Catalyst expression / exec / scan / partitioning to
a replacement rule with a TypeSig, a tagging hook and a conversion; applied
as a Rule[SparkPlan].  The structure here is the same `expr()` / `exec()`
DSL over our plan nodes, and the apply() entry runs: wrap -> tag (accumulate
willNotWorkOnTpu reasons) -> convert (maximal TPU subtrees + transitions) ->
TpuTransitionOverrides (coalesce insertion + whole-stage fusion).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Type

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import (
    BATCH_SIZE_BYTES,
    ENABLE_CAST_STRING_TO_TIMESTAMP,
    MAX_READER_BATCH_SIZE_ROWS,
    TpuConf,
)
from spark_rapids_tpu.expr import arithmetic as A
from spark_rapids_tpu.expr import base as E
from spark_rapids_tpu.expr import cast as C
from spark_rapids_tpu.expr import collections as CL
from spark_rapids_tpu.expr import conditional as CO
from spark_rapids_tpu.expr import datetime as DT
from spark_rapids_tpu.expr import hashexprs as H
from spark_rapids_tpu.expr import complextypes as CT
from spark_rapids_tpu.expr import hof as HOF
from spark_rapids_tpu.expr import jsonexprs as J
from spark_rapids_tpu.expr import avroexprs as AV
from spark_rapids_tpu.expr import xmlexprs as XM
from spark_rapids_tpu.expr import xpath as XP
from spark_rapids_tpu.expr import mathfuncs as M
from spark_rapids_tpu.expr import misc as MI
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr import strings as S
from spark_rapids_tpu.expr import udf as U
from spark_rapids_tpu.overrides.meta import ExprMeta, SparkPlanMeta
from spark_rapids_tpu.plan import nodes as PN

# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ExprRule:
    type_sig: T.TypeSig
    extra_check: Optional[Callable[[ExprMeta], None]] = None
    desc: str = ""
    # array<string> (3-D char tensor) flows only through rules that opt in
    allow_string_arrays: bool = False
    # array<struct<flat|string...>> (the entries layout) opt-in
    allow_struct_entries: bool = False


@dataclasses.dataclass
class ExecRule:
    type_sig: T.TypeSig
    convert: Callable = None
    tag_exprs: Optional[Callable] = None
    extra_check: Optional[Callable[[SparkPlanMeta], None]] = None
    desc: str = ""
    allow_string_arrays: bool = False


_COMMON = (T.BOOLEAN_SIG + T.numeric + T.STRING_SIG + T.DATETIME_SIG
           + T.NULL_SIG)
_COMMON128 = _COMMON + T.DECIMAL_128_SIG.with_max_decimal(18)
# full 38-digit decimals (two-limb device columns, expr/decimal128.py)
_DEC128_FULL = _COMMON + T.DECIMAL_128_SIG
_NUM = T.numeric + T.NULL_SIG
_NUM128 = _NUM + T.DECIMAL_128_SIG
# arrays of primitive elements (padded list columns; element support is
# checked recursively by TypeSig.supports)
_ARRAY_SIG = T.TypeSig(frozenset({T.ArrayType}), 18)
_WITH_ARRAYS = _DEC128_FULL + _ARRAY_SIG


def _check_array_insert(meta: ExprMeta):
    e = meta.expr
    if e.pos_literal is None or int(e.pos_literal) == 0:
        meta.will_not_work_on_tpu(
            "array_insert position must be a non-zero literal on TPU "
            "(the output width bucket is a static shape)")


def _check_flatten(meta: ExprMeta):
    e = meta.expr
    if not (e._absorbed
            and all(isinstance(m.dataType, T.ArrayType)
                    for m in e.children)):
        meta.will_not_work_on_tpu(
            "flatten supports array(a1, a2, ...) of array columns on TPU "
            "(no general array<array> device layout)")


def _check_str_to_map(meta: ExprMeta):
    from spark_rapids_tpu.expr.base import Literal

    for d in meta.expr.children[1:]:
        if not isinstance(d, Literal):
            meta.will_not_work_on_tpu(
                "str_to_map delimiters must be string literals")
            break


def _check_schema_of_json(meta: ExprMeta):
    try:
        meta.expr._folded()
    except Exception as ex:  # non-literal / bad json: CPU raises instead
        meta.will_not_work_on_tpu(f"schema_of_json: {ex}")


def _check_hive_hash(meta: ExprMeta):
    for c in meta.expr.children:
        if isinstance(c.dataType, (T.DecimalType, T.TimestampType,
                                   T.ArrayType, T.MapType, T.StructType)):
            meta.will_not_work_on_tpu(
                f"hive_hash of {c.dataType.simpleString} is not supported "
                f"on TPU")
            break


def _check_xpath(meta: ExprMeta):
    from spark_rapids_tpu.expr.base import Literal

    p = meta.expr.children[1]
    if not (isinstance(p, Literal) and p.value is not None):
        meta.will_not_work_on_tpu("xpath path must be a string literal")


def _check_decimal_div(meta: ExprMeta):
    """Decimal divide computes numerator = l * 10^(s - ls + rs) in int64;
    operands whose numerator can exceed 18 digits fall back (reference:
    decimal_utils.cu 128-bit division; silent-null was a round-4 bug)."""
    e = meta.expr
    dt = e.dataType
    if not isinstance(dt, T.DecimalType):
        return
    lt = e.left.dataType
    rt = e.right.dataType
    shift = dt.scale - lt.scale + rt.scale
    if lt.precision + max(shift, 0) > 18 or rt.precision + max(-shift, 0) > 18:
        meta.will_not_work_on_tpu(
            "decimal divide intermediate exceeds 18 digits "
            "(128-bit division is not implemented on TPU)")


def _check_decimal_mult(meta: ExprMeta):
    """128x128 multiply needs 256-bit intermediates (reference caps at
    DECIMAL128 via decimal_utils.cu); operands above 18 digits fall back."""
    e = meta.expr
    for side in (e.left, e.right):
        dt = side._dataType
        if isinstance(dt, T.DecimalType) and dt.precision > 18:
            meta.will_not_work_on_tpu(
                "decimal multiply with an operand above 18 digits is not "
                "supported on TPU (needs 256-bit intermediates)")


def _check_decimal_addsub(meta: ExprMeta):
    """Reject results that Spark would rescale with precision loss (we only
    implement the exact <=38-digit path)."""
    e = meta.expr
    lt, rt = e.left._dataType, e.right._dataType
    if isinstance(lt, T.DecimalType) and isinstance(rt, T.DecimalType):
        s = max(lt.scale, rt.scale)
        p = max(lt.precision - lt.scale, rt.precision - rt.scale) + s + 1
        if p > 38:
            meta.will_not_work_on_tpu(
                "decimal add/subtract result exceeds 38 digits "
                "(precision-loss rescale not implemented on TPU)")


def _is_dec128(dt) -> bool:
    return isinstance(dt, T.DecimalType) and dt.precision > 18


def _check_cast(meta: ExprMeta):
    e: C.Cast = meta.expr
    src = e.child._dataType
    if src is None:
        return
    if not C.cast_supported(src, e.to):
        meta.will_not_work_on_tpu(
            f"cast from {src.simpleString} to {e.to.simpleString} is not "
            f"supported on TPU")
    if _is_dec128(src) or _is_dec128(e.to):
        # decimal128 limb paths implemented: dec<->dec, int->dec, dec->int,
        # dec->fp.  Everything else (string/fp->dec128, dec128->string)
        # falls back (reference: CastStrings 128-bit kernels, cast_string.cu)
        def kindof(t):
            if isinstance(t, T.DecimalType):
                return "dec"
            if isinstance(t, (T.ByteType, T.ShortType, T.IntegerType,
                              T.LongType)):
                return "int"
            if isinstance(t, (T.FloatType, T.DoubleType)):
                return "fp"
            return "other"

        pair = (kindof(src), kindof(e.to))
        if pair not in {("dec", "dec"), ("int", "dec"), ("dec", "int"),
                        ("dec", "fp")}:
            meta.will_not_work_on_tpu(
                f"cast {src.simpleString} -> {e.to.simpleString} above 18 "
                f"decimal digits is not supported on TPU")
    if isinstance(src, T.StringType) and isinstance(e.to, T.TimestampType):
        if not meta.conf.get(ENABLE_CAST_STRING_TO_TIMESTAMP):
            meta.will_not_work_on_tpu(
                "string->timestamp cast is disabled "
                "(spark.rapids.sql.castStringToTimestamp.enabled)")


def _check_like(meta: ExprMeta):
    e: S.Like = meta.expr
    pat = e.right
    if not isinstance(pat, E.Literal):
        meta.will_not_work_on_tpu("LIKE pattern must be a literal")
        return
    ok, compiled = S.try_compile_like(pat.value)
    if not ok:
        meta.will_not_work_on_tpu(
            f"LIKE pattern {pat.value!r} is not supported on TPU "
            f"(transpiler-reject path; see RegexParser analog)")
    elif compiled is not None:
        e._dfa = compiled  # reuse the tag-time compilation at eval


def _check_literal_pattern(meta: ExprMeta):
    if not isinstance(meta.expr.children[1], E.Literal):
        meta.will_not_work_on_tpu("pattern must be a literal")


def _check_rlike(meta: ExprMeta):
    """Transpile at tag time; reject -> CPU fallback (the reference's
    CudfRegexTranspiler-reject path, RegexParser.scala)."""
    from spark_rapids_tpu.regex import RegexUnsupported, compile_regex

    pat = meta.expr.children[1]
    if not isinstance(pat, E.Literal) or pat.value is None:
        meta.will_not_work_on_tpu("RLIKE pattern must be a non-null literal")
        return
    try:
        meta.expr._dfa = compile_regex(pat.value)
    except RegexUnsupported as ex:
        meta.will_not_work_on_tpu(str(ex))


def _check_literal_children(*ordinals, names="argument"):
    def check(meta: ExprMeta):
        for o in ordinals:
            ch = meta.expr.children[o]
            if not isinstance(ch, E.Literal) or ch.value is None:
                meta.will_not_work_on_tpu(
                    f"{names} (child {o}) must be a non-null literal on TPU")
    return check


def _check_time_format(meta: ExprMeta):
    """from_unixtime/date_format: literal pattern from the supported token
    subset (the transpiler-reject pattern applied to time formats)."""
    from spark_rapids_tpu.expr.datetime import parse_format

    fmt = meta.expr.children[1]
    if not isinstance(fmt, E.Literal) or fmt.value is None:
        meta.will_not_work_on_tpu("time format must be a non-null literal")
        return
    if parse_format(str(fmt.value)) is None:
        meta.will_not_work_on_tpu(
            f"time format {fmt.value!r} contains unsupported pattern "
            f"letters (supported: yyyy MM dd HH mm ss + separators)")


def _check_create_array(meta: ExprMeta):
    kids = meta.expr.children
    if not kids:
        meta.will_not_work_on_tpu("empty array() literal is not supported")
        return
    et = kids[0]._dataType
    if isinstance(et, (T.StringType, T.ArrayType, T.MapType, T.StructType)):
        meta.will_not_work_on_tpu(
            "array() of non-primitive elements is not supported on TPU")
        return
    for c in kids[1:]:
        if c._dataType != et:
            meta.will_not_work_on_tpu("array() elements must share one type")
            return


def _check_regexp_extract_all(meta: ExprMeta):
    """regexp_extract_all: span-safe literal pattern with bounded non-empty
    match length (static padded element matrix), idx 0 only."""
    from spark_rapids_tpu.regex import RegexUnsupported
    from spark_rapids_tpu.regex.spans import (compile_for_spans,
                                              match_length_bounds)

    e = meta.expr
    pat = e.children[1]
    if not isinstance(pat, E.Literal) or pat.value is None:
        meta.will_not_work_on_tpu("regexp pattern must be a non-null literal")
        return
    try:
        e._dfa = compile_for_spans(str(pat.value))
        lo, hi = match_length_bounds(str(pat.value))
    except RegexUnsupported as ex:
        meta.will_not_work_on_tpu(str(ex))
        return
    if lo < 1:
        meta.will_not_work_on_tpu(
            "regexp_extract_all: pattern can match the empty string")
    if hi is None or hi > e.MAX_MATCH_LEN:
        meta.will_not_work_on_tpu(
            f"regexp_extract_all: match length must be bounded by "
            f"{e.MAX_MATCH_LEN}")
    idx = e.children[2]
    if not isinstance(idx, E.Literal) or idx.value is None \
            or int(idx.value) != 0:
        meta.will_not_work_on_tpu(
            "regexp_extract_all with capture-group index needs a "
            "backtracking engine")


def _check_bround(meta: ExprMeta):
    ct = meta.expr.children[0]._dataType
    if isinstance(ct, T.DecimalType):
        meta.will_not_work_on_tpu(
            "bround over decimals (HALF_EVEN rescale) is not supported "
            "on TPU")


def _check_literal_fmt(meta: ExprMeta):
    if not isinstance(meta.expr.children[0], E.Literal) \
            or meta.expr.children[0].value is None:
        meta.will_not_work_on_tpu("format must be a non-null literal")


def _check_convert_timezone(meta: ExprMeta):
    from spark_rapids_tpu.tzdb import zone_tables

    e = meta.expr
    for tz in (e.source_tz, e.target_tz):
        try:
            zone_tables(tz)
        except Exception:
            meta.will_not_work_on_tpu(f"unknown timezone {tz!r}")


def _check_mask(meta: ExprMeta):
    for c in meta.expr.children[1:]:
        if not isinstance(c, E.Literal):
            meta.will_not_work_on_tpu(
                "mask replacement chars must be literals")
        elif c.value is not None and len(str(c.value)) != 1:
            meta.will_not_work_on_tpu(
                "mask replacements must be single characters")


def _check_regexp_span(meta: ExprMeta):
    from spark_rapids_tpu.regex import RegexUnsupported
    from spark_rapids_tpu.regex.spans import compile_for_spans

    e = meta.expr
    pat = e.children[1]
    if not isinstance(pat, E.Literal) or pat.value is None:
        meta.will_not_work_on_tpu("regexp pattern must be a non-null literal")
        return
    try:
        e._dfa = compile_for_spans(str(pat.value))
    except RegexUnsupported as ex:
        meta.will_not_work_on_tpu(str(ex))


def _check_split_part(meta: ExprMeta):
    d = meta.expr.children[1]
    if not isinstance(d, E.Literal) or not d.value:
        meta.will_not_work_on_tpu(
            "split_part delimiter must be a non-empty literal")
        return
    s = str(d.value)
    for k in range(1, len(s)):
        if s[k:] == s[:-k]:
            meta.will_not_work_on_tpu(
                "self-overlapping split_part delimiters are not supported "
                "on TPU (left-to-right scan ambiguity)")
            return


def _check_ilike(meta: ExprMeta):
    e = meta.expr
    pat = e.right
    if not isinstance(pat, E.Literal) or pat.value is None:
        meta.will_not_work_on_tpu(
            "ILIKE pattern must be a non-null literal")
        return
    ok, compiled = S.try_compile_like(str(pat.value).lower())
    if not ok:
        meta.will_not_work_on_tpu(
            "ILIKE pattern shape is not supported on TPU")
    else:
        e._compiled = compiled


def _check_regexp_spans(meta: ExprMeta):
    """regexp_replace/extract: literal pattern from the span-safe subset
    (regex/spans.py), literal replacement without $group refs / backslash,
    extract index 0 only (capture groups need backtracking)."""
    from spark_rapids_tpu.regex import RegexUnsupported
    from spark_rapids_tpu.regex.spans import compile_for_spans

    e = meta.expr
    pat = e.children[1]
    if not isinstance(pat, E.Literal) or pat.value is None:
        meta.will_not_work_on_tpu("regexp pattern must be a non-null literal")
        return
    try:
        e._dfa = compile_for_spans(str(pat.value))
    except RegexUnsupported as ex:
        meta.will_not_work_on_tpu(str(ex))
        return
    third = e.children[2]
    if not isinstance(third, E.Literal) or third.value is None:
        meta.will_not_work_on_tpu(
            "replacement/index must be a non-null literal")
        return
    if type(e).__name__ == "RegExpReplace":
        r = str(third.value)
        if "$" in r or "\\" in r:
            meta.will_not_work_on_tpu(
                "replacement with $group references or escapes is not "
                "supported on TPU")
    else:
        if int(third.value) != 0:
            meta.will_not_work_on_tpu(
                "regexp_extract group index != 0 needs capture groups "
                "(backtracking engine); falls back to CPU")


def _check_udf(meta: ExprMeta):
    """RapidsUDF/arrow-eval ladder: columnar UDFs fuse into the stage;
    plain python functions stay in the TPU plan via the arrow-eval host
    path (GpuArrowEvalPythonExec analog) unless disabled, in which case
    the stage falls back with the reference's explain wording."""
    from spark_rapids_tpu.expr.udf import supports_columnar

    if not supports_columnar(meta.expr.fn):
        from spark_rapids_tpu.config import ARROW_EVAL_ENABLED

        if not meta.conf.get(ARROW_EVAL_ENABLED):
            meta.will_not_work_on_tpu(
                f"UDF {meta.expr.name} does not implement "
                f"evaluate_columnar (TpuUDF); it will run row-based on "
                f"CPU")


def _check_substring_index(meta: ExprMeta):
    """Delimiter must be a literal without a self-overlap border (so left
    and right non-overlapping scans agree with Spark's byte scans)."""
    d = meta.expr.children[1]
    if not isinstance(d, E.Literal) or d.value is None:
        meta.will_not_work_on_tpu("substring_index delimiter must be a "
                                  "non-null literal")
        return
    s = str(d.value)
    for k in range(1, len(s)):
        if s[:k] == s[-k:]:
            meta.will_not_work_on_tpu(
                f"substring_index delimiter {s!r} is self-overlapping "
                f"(border of length {k}); occurrence counting may diverge")
            return


def _check_pad(meta: ExprMeta):
    _check_literal_children(1, 2, names="pad length/pad string")(meta)
    pad = meta.expr.children[2]
    if isinstance(pad, E.Literal) and pad.value == "":
        meta.will_not_work_on_tpu("empty pad string is not supported on TPU")


# structs of primitives/strings (device struct columns, columnar/column.py)
_STRUCT_SIG = (T.TypeSig(frozenset({T.StructType})) + T.BOOLEAN_SIG
               + T.INTEGRAL_SIG + T.FP_SIG + T.STRING_SIG
               + T.DATETIME_SIG + T.NULL_SIG)

_PRIM_ELEM = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
              T.LongType, T.FloatType, T.DoubleType, T.DateType,
              T.TimestampType)


def unsupported_nested_reason(dt, allow_string_elems=False,
                              allow_struct_entries=False) -> Optional[str]:
    """Why a nested type cannot live in device columns yet, or None.

    Array elements and map keys/values must be flat primitives (the padded
    list layout stores one numeric matrix); struct fields may additionally
    be strings.  TypeSig.supports recurses with the FULL kind set, which
    would wrongly admit array<string>, so every rule whose sig includes
    nested kinds routes through this check.  ``allow_struct_entries``
    admits array<struct<flat-or-string...>> — the entries layout
    (per-field array-column children) used by map_entries/arrays_zip."""
    if isinstance(dt, T.ArrayType):
        et = dt.elementType
        if allow_string_elems and isinstance(et, T.StringType):
            return None
        if isinstance(et, T.StructType):
            # the ENTRIES layout (per-field array-column children) is a
            # first-class representation: gather/compact/concat/host
            # conversions all handle it, so array<struct<flat|string>>
            # flows through any exec
            for f in et.fields:
                fd = f.dataType
                ok = isinstance(fd, (T.StringType,) + _PRIM_ELEM) or (
                    isinstance(fd, T.DecimalType) and not fd.is_128)
                if not ok:
                    return (f"{dt.simpleString}: entries-struct fields "
                            f"must be flat or string on TPU")
            return None
        if isinstance(et, T.DecimalType):
            return None if not et.is_128 else \
                f"{dt.simpleString}: decimal128 array elements"
        if not isinstance(et, _PRIM_ELEM):
            return (f"{dt.simpleString}: array elements must be flat "
                    f"primitives on TPU (array<string> needs a rule that "
                    f"opts in)")
        return None
    if isinstance(dt, T.MapType):
        for part, name in ((dt.keyType, "key"), (dt.valueType, "value")):
            if isinstance(part, T.DecimalType):
                if part.is_128:
                    return f"{dt.simpleString}: decimal128 map {name}s"
            elif not isinstance(part, _PRIM_ELEM):
                return (f"{dt.simpleString}: map {name}s must be flat "
                        f"primitives on TPU")
        return None
    if isinstance(dt, T.StructType):
        for f in dt.fields:
            if isinstance(f.dataType, (T.ArrayType, T.MapType,
                                       T.StructType)):
                return (f"{dt.simpleString}: nested field "
                        f"{f.name} inside a struct")
        return None
    return None


# maps with primitive keys/values (keys/values array-column pair)
_WITH_MAPS = (T.TypeSig(frozenset({T.MapType, T.ArrayType}))
              + T.BOOLEAN_SIG + T.INTEGRAL_SIG + T.FP_SIG
              + T.DATETIME_SIG + T.NULL_SIG).with_note(
    T.MapType, "primitive keys/values only (no strings yet)")


def _check_hof(meta: ExprMeta):
    """Tag the lambda body's expressions too (it is not a regular child)."""
    body_meta = wrap_expr(meta.expr.body, meta.conf)
    body_meta.tag_for_tpu()
    if not body_meta.can_run_with_children:
        for r in body_meta.all_reasons():
            meta.will_not_work_on_tpu(f"lambda body: {r}")


def _check_hof_agg(meta: ExprMeta):
    e = meta.expr
    merge_meta = wrap_expr(e.merge, meta.conf)
    merge_meta.tag_for_tpu()
    if not merge_meta.can_run_with_children:
        for r in merge_meta.all_reasons():
            meta.will_not_work_on_tpu(f"merge lambda: {r}")
    if e.finish is not None:
        fin_meta = wrap_expr(e.finish, meta.conf)
        fin_meta.tag_for_tpu()
        if not fin_meta.can_run_with_children:
            for r in fin_meta.all_reasons():
                meta.will_not_work_on_tpu(f"finish lambda: {r}")
    if e.merge.resolved and e.children[1].resolved \
            and type(e.merge.dataType) is not type(e.children[1].dataType):
        meta.will_not_work_on_tpu(
            "aggregate: merge result type must match the zero value type")
    if e.children[1].resolved and isinstance(
            e.children[1].dataType,
            (T.StringType, T.ArrayType, T.MapType, T.StructType)):
        meta.will_not_work_on_tpu(
            "aggregate: accumulator must be a flat primitive on TPU")


_SUPPORTED_CHARSETS = {"utf-8", "utf8", "us-ascii", "ascii", "iso-8859-1",
                       "utf-16", "utf-16be", "utf-16le"}


def _check_timezone(meta: ExprMeta):
    from spark_rapids_tpu.tzdb import is_known_zone

    tz = meta.expr.children[1]
    if not isinstance(tz, E.Literal):
        meta.will_not_work_on_tpu(
            "from/to_utc_timestamp: timezone must be a literal")
        return
    if not is_known_zone(tz.value):
        meta.will_not_work_on_tpu(
            f"unknown or unsupported timezone {tz.value!r}")


def _check_charset(meta: ExprMeta):
    cs = meta.expr.children[1]
    if not isinstance(cs, E.Literal):
        meta.will_not_work_on_tpu(
            "encode/decode: charset must be a literal")
        return
    if cs.value is None or str(cs.value).lower() not in _SUPPORTED_CHARSETS:
        meta.will_not_work_on_tpu(
            f"encode/decode: charset {cs.value!r} is not supported")


def _check_json_path(meta: ExprMeta):
    """Literal, non-wildcard JSON path (the reference's GpuGetJsonObject
    likewise falls back for non-literal paths)."""
    from spark_rapids_tpu.jsonpath import UnsupportedJsonPath, parse_json_path

    p = meta.expr.children[1]
    if not isinstance(p, E.Literal):
        meta.will_not_work_on_tpu(
            "get_json_object: only literal JSON paths are supported")
        return
    if p.value is None:
        return
    try:
        parse_json_path(p.value)
    except UnsupportedJsonPath as ex:
        meta.will_not_work_on_tpu(f"get_json_object: {ex} is not supported")


def _check_json_tuple(meta: ExprMeta):
    for k in meta.expr.children[1:]:
        if not isinstance(k, E.Literal):
            meta.will_not_work_on_tpu(
                "json_tuple: only literal field names are supported")
            return


_FLAT_STRUCT_OK = (T.StringType, T.BooleanType, T.ByteType, T.ShortType,
                   T.IntegerType, T.LongType, T.FloatType, T.DoubleType)


def _check_flat_struct(meta: ExprMeta, st, what: str):
    if not isinstance(st, T.StructType):
        meta.will_not_work_on_tpu(f"{what}: requires a struct schema")
        return
    for f in st.fields:
        if not isinstance(f.dataType, _FLAT_STRUCT_OK):
            meta.will_not_work_on_tpu(
                f"{what}: field {f.name} of type "
                f"{f.dataType.simpleString} is not supported (flat "
                "primitive/string structs only)")


def _check_from_json(meta: ExprMeta):
    _check_flat_struct(meta, meta.expr.schema, "from_json")


def _check_to_json(meta: ExprMeta):
    _check_flat_struct(meta, meta.expr.children[0]._dataType, "to_json")


def _check_to_binary(meta: ExprMeta):
    if meta.expr._fmt not in ("utf-8", "utf8", "hex", "base64"):
        meta.will_not_work_on_tpu(
            f"to_binary format '{meta.expr._fmt}' is not supported "
            "(utf-8/hex/base64; format must be a literal)")


def _check_sentences(meta: ExprMeta):
    meta.will_not_work_on_tpu(
        "sentences returns array<array<string>>, which has no padded "
        "device layout; always runs on CPU (the reference has no "
        "GpuSentences rule either)")


def _check_from_avro(meta: ExprMeta):
    e = meta.expr
    if e._avro_schema is None:
        meta.will_not_work_on_tpu(
            "from_avro: schema must be a literal json string")
        return
    _check_flat_struct(meta, e._dataType, "from_avro")


def _check_to_avro(meta: ExprMeta):
    _check_flat_struct(meta, meta.expr.children[0]._dataType, "to_avro")


def all_avro_sig():
    return (T.STRING_SIG + T.BINARY_SIG + T.numeric + T.BOOLEAN_SIG
            + T.NULL_SIG + T.TypeSig(frozenset({T.StructType})))


def _check_map_from_entries(meta: ExprMeta):
    at = meta.expr.children[0]._dataType
    if not (isinstance(at, T.ArrayType)
            and isinstance(at.elementType, T.StructType)
            and len(at.elementType.fields) == 2):
        meta.will_not_work_on_tpu(
            "map_from_entries requires array<struct<key,value>> input")
        return
    kt = at.elementType.fields[0].dataType
    if isinstance(kt, (T.ArrayType, T.MapType, T.StructType)):
        meta.will_not_work_on_tpu(
            "map_from_entries: nested key types are not supported on TPU")


def _check_map_sort(meta: ExprMeta):
    mt = meta.expr.children[0]._dataType
    if not isinstance(mt, T.MapType):
        meta.will_not_work_on_tpu("map_sort requires a map input")
        return
    if isinstance(mt.keyType, (T.StringType, T.ArrayType, T.MapType,
                               T.StructType, T.FloatType, T.DoubleType)):
        meta.will_not_work_on_tpu(
            "map_sort supports integral/date map keys on TPU")


def _check_shuffle(meta: ExprMeta):
    at = meta.expr.children[0]._dataType
    if isinstance(at, T.ArrayType) and isinstance(
            at.elementType, (T.ArrayType, T.MapType, T.StructType,
                             T.StringType)):
        meta.will_not_work_on_tpu(
            "shuffle supports flat-element arrays on TPU")


def _check_parse_to_datetime(meta: ExprMeta):
    fmt = meta.expr.fmt_literal
    if fmt is None:
        return
    ok = ("yyyy-MM-dd", "yyyy-MM-dd HH:mm:ss")
    if fmt is False or fmt not in ok:
        meta.will_not_work_on_tpu(
            f"to_date/to_timestamp format {fmt!r} is outside the "
            f"default-grammar subset {ok} supported on TPU")


def _check_number_format(meta: ExprMeta):
    if meta.expr._spec is None:
        meta.will_not_work_on_tpu(
            "to_number/to_char format must be a literal over the "
            "0/9/,/./$/S/MI subset")


def _check_from_xml(meta: ExprMeta):
    _check_flat_struct(meta, meta.expr.schema, "from_xml")


def _check_to_xml(meta: ExprMeta):
    _check_flat_struct(meta, meta.expr.children[0]._dataType, "to_xml")


def _check_extract(meta: ExprMeta):
    if getattr(meta.expr, "_delegate", None) is None:
        meta.will_not_work_on_tpu(
            "extract: field must be a literal among "
            + "/".join(sorted(DT._EXTRACT_FIELDS)))


EXPRESSIONS: Dict[Type, ExprRule] = {
    E.Literal: ExprRule(_WITH_ARRAYS, desc="constant literal", allow_string_arrays=True),
    E.BoundReference: ExprRule(_WITH_ARRAYS + _WITH_MAPS,
                               desc="column reference",
                               allow_string_arrays=True),
    E.AttributeReference: ExprRule(_WITH_ARRAYS + _WITH_MAPS,
                                   desc="column reference",
                                   allow_string_arrays=True),
    E.Alias: ExprRule(_WITH_ARRAYS + _WITH_MAPS
                      + T.TypeSig(frozenset({T.StructType})),
                      desc="alias", allow_string_arrays=True),
    A.Add: ExprRule(_NUM128, extra_check=_check_decimal_addsub),
    A.Subtract: ExprRule(_NUM128, extra_check=_check_decimal_addsub),
    A.Multiply: ExprRule(_NUM128, extra_check=_check_decimal_mult),
    A.Divide: ExprRule(_NUM, extra_check=_check_decimal_div),
    A.TryAdd: ExprRule(_NUM128, extra_check=_check_decimal_addsub,
                       desc="ANSI op, errors become null"),
    A.TrySubtract: ExprRule(_NUM128, extra_check=_check_decimal_addsub,
                            desc="ANSI op, errors become null"),
    A.TryMultiply: ExprRule(_NUM128, extra_check=_check_decimal_mult,
                            desc="ANSI op, errors become null"),
    A.TryDivide: ExprRule(_NUM, extra_check=_check_decimal_div,
                          desc="ANSI op, errors become null"),
    A.IntegralDivide: ExprRule(_NUM), A.Remainder: ExprRule(_NUM),
    A.Pmod: ExprRule(_NUM), A.UnaryMinus: ExprRule(_NUM),
    A.Abs: ExprRule(_NUM),
    P.EqualTo: ExprRule(_DEC128_FULL), P.LessThan: ExprRule(_DEC128_FULL),
    P.LessThanOrEqual: ExprRule(_DEC128_FULL),
    P.GreaterThan: ExprRule(_DEC128_FULL),
    P.GreaterThanOrEqual: ExprRule(_DEC128_FULL),
    P.EqualNullSafe: ExprRule(_DEC128_FULL),
    P.And: ExprRule(T.BOOLEAN_SIG + T.NULL_SIG),
    P.Or: ExprRule(T.BOOLEAN_SIG + T.NULL_SIG),
    P.Not: ExprRule(T.BOOLEAN_SIG + T.NULL_SIG),
    P.IsNull: ExprRule(_WITH_ARRAYS, allow_string_arrays=True),
    P.IsNotNull: ExprRule(_WITH_ARRAYS, allow_string_arrays=True),
    P.IsNaN: ExprRule(T.FP_SIG + T.BOOLEAN_SIG),
    P.In: ExprRule(_DEC128_FULL),
    CO.If: ExprRule(_COMMON128), CO.CaseWhen: ExprRule(_COMMON128),
    CO.Coalesce: ExprRule(_COMMON128), CO.Nvl: ExprRule(_COMMON128),
    CO.NaNvl: ExprRule(T.FP_SIG),
    CO.Greatest: ExprRule(_NUM + T.STRING_SIG),
    CO.Least: ExprRule(_NUM + T.STRING_SIG),
    C.Cast: ExprRule(_DEC128_FULL, extra_check=_check_cast),
    M.Sqrt: ExprRule(_NUM), M.Exp: ExprRule(_NUM), M.Log: ExprRule(_NUM),
    M.Log10: ExprRule(_NUM), M.Sin: ExprRule(_NUM), M.Cos: ExprRule(_NUM),
    M.Tan: ExprRule(_NUM), M.Asin: ExprRule(_NUM), M.Acos: ExprRule(_NUM),
    M.Atan: ExprRule(_NUM), M.Signum: ExprRule(_NUM), M.Pow: ExprRule(_NUM),
    M.Floor: ExprRule(_NUM), M.Ceil: ExprRule(_NUM), M.Round: ExprRule(_NUM),
    M.Sinh: ExprRule(_NUM), M.Cosh: ExprRule(_NUM), M.Tanh: ExprRule(_NUM),
    M.Asinh: ExprRule(_NUM), M.Acosh: ExprRule(_NUM),
    M.Atanh: ExprRule(_NUM), M.Cbrt: ExprRule(_NUM),
    M.Log2: ExprRule(_NUM), M.Log1p: ExprRule(_NUM),
    M.Expm1: ExprRule(_NUM), M.Rint: ExprRule(_NUM), M.Cot: ExprRule(_NUM),
    M.Csc: ExprRule(_NUM), M.Sec: ExprRule(_NUM),
    M.ToDegrees: ExprRule(_NUM), M.ToRadians: ExprRule(_NUM),
    M.Atan2: ExprRule(_NUM), M.Hypot: ExprRule(_NUM),
    M.Logarithm: ExprRule(_NUM),
    A.BitwiseAnd: ExprRule(T.INTEGRAL_SIG + T.NULL_SIG),
    A.BitwiseOr: ExprRule(T.INTEGRAL_SIG + T.NULL_SIG),
    A.BitwiseXor: ExprRule(T.INTEGRAL_SIG + T.NULL_SIG),
    A.BitwiseNot: ExprRule(T.INTEGRAL_SIG + T.NULL_SIG),
    A.ShiftLeft: ExprRule(T.INTEGRAL_SIG + T.NULL_SIG),
    A.ShiftRight: ExprRule(T.INTEGRAL_SIG + T.NULL_SIG),
    A.ShiftRightUnsigned: ExprRule(T.INTEGRAL_SIG + T.NULL_SIG),
    S.Length: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.Upper: ExprRule(T.STRING_SIG.with_note(
        T.StringType, "ASCII-only case conversion")),
    S.Lower: ExprRule(T.STRING_SIG.with_note(
        T.StringType, "ASCII-only case conversion")),
    S.Substring: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.Concat: ExprRule(T.STRING_SIG),
    S.StartsWith: ExprRule(T.STRING_SIG + T.BOOLEAN_SIG),
    S.EndsWith: ExprRule(T.STRING_SIG + T.BOOLEAN_SIG),
    S.Contains: ExprRule(T.STRING_SIG + T.BOOLEAN_SIG),
    S.StringTrim: ExprRule(T.STRING_SIG),
    S.Reverse: ExprRule(T.STRING_SIG.with_note(
        T.StringType, "byte-reverse; ASCII-only")),
    S.InitCap: ExprRule(T.STRING_SIG.with_note(
        T.StringType, "ASCII-only case conversion")),
    S.Ascii: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.Chr: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.StringReplace: ExprRule(
        T.STRING_SIG, extra_check=_check_literal_children(
            1, 2, names="search/replace")),
    S.StringTranslate: ExprRule(
        T.STRING_SIG, extra_check=_check_literal_children(
            1, 2, names="from/to")),
    S.StringInstr: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.StringLocate: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.StringLPad: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                           extra_check=_check_pad),
    S.StringRPad: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                           extra_check=_check_pad),
    S.StringRepeat: ExprRule(
        T.STRING_SIG + T.INTEGRAL_SIG,
        extra_check=_check_literal_children(1, names="repeat count")),
    S.ConcatWs: ExprRule(
        T.STRING_SIG, extra_check=_check_literal_children(
            0, names="separator")),
    S.OctetLength: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.BitLength: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.StringLeft: ExprRule(T.STRING_SIG.with_note(
        T.StringType, "byte-based; ASCII-exact") + T.INTEGRAL_SIG),
    S.StringRight: ExprRule(T.STRING_SIG.with_note(
        T.StringType, "byte-based; ASCII-exact") + T.INTEGRAL_SIG),
    S.SubstringIndex: ExprRule(
        T.STRING_SIG.with_note(T.StringType, "byte-based; ASCII-exact")
        + T.INTEGRAL_SIG,
        extra_check=_check_substring_index),
    S.StringSplit: ExprRule(
        _WITH_ARRAYS, allow_string_arrays=True,
        extra_check=_check_literal_children(1, names="split pattern"),
        desc="split into array<string> (host kernel + java-regex rules)"),
    S.ArrayJoin: ExprRule(_WITH_ARRAYS, allow_string_arrays=True),
    S.RegExpReplace: ExprRule(T.STRING_SIG,
                              extra_check=_check_regexp_spans),
    S.RegExpExtract: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                              extra_check=_check_regexp_spans),
    S.RegExpExtractAll: ExprRule(
        T.STRING_SIG + T.INTEGRAL_SIG + _ARRAY_SIG.with_note(
            T.ArrayType,
            f"bounded patterns; at most "
            f"{S.RegExpExtractAll.MAX_MATCHES} matches per row"),
        allow_string_arrays=True,
        extra_check=_check_regexp_extract_all),
    S.Overlay: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.FindInSet: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.Elt: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    S.StringSpace: ExprRule(
        T.STRING_SIG.with_note(
            T.StringType,
            f"length capped at {S.StringSpace.MAX_LEN}")
        + T.INTEGRAL_SIG),
    S.StringTrimLeft: ExprRule(T.STRING_SIG),
    S.StringTrimRight: ExprRule(T.STRING_SIG),
    M.BRound: ExprRule(_NUM, extra_check=_check_bround),
    M.WidthBucket: ExprRule(_NUM),
    M.Factorial: ExprRule(T.INTEGRAL_SIG),
    M.BitwiseCount: ExprRule(T.INTEGRAL_SIG + T.BOOLEAN_SIG),
    CO.Nvl2: ExprRule(_COMMON128),
    CO.NullIf: ExprRule(_COMMON128),
    S.Like: ExprRule(T.STRING_SIG + T.BOOLEAN_SIG, extra_check=_check_like),
    S.RLike: ExprRule(T.STRING_SIG + T.BOOLEAN_SIG,
                      extra_check=_check_rlike),
    DT.Year: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.Month: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.DayOfMonth: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.DayOfWeek: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.DayOfYear: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.Quarter: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.LastDay: ExprRule(T.DATETIME_SIG),
    DT.Hour: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.Minute: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.Second: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.DateAdd: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.DateSub: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.DateDiff: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.UnixTimestamp: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.WeekOfYear: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.AddMonths: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.MonthsBetween: ExprRule(T.DATETIME_SIG + T.FP_SIG),
    DT.TruncDate: ExprRule(
        T.DATETIME_SIG + T.STRING_SIG,
        extra_check=_check_literal_children(1, names="trunc format")),
    DT.NextDay: ExprRule(
        T.DATETIME_SIG + T.STRING_SIG,
        extra_check=_check_literal_children(1, names="day of week")),
    DT.FromUTCTimestamp: ExprRule(
        T.DATETIME_SIG + T.STRING_SIG, extra_check=_check_timezone,
        desc="tz offset via device transition tables (tzdb.py)"),
    DT.ToUTCTimestamp: ExprRule(
        T.DATETIME_SIG + T.STRING_SIG, extra_check=_check_timezone,
        desc="java.time gap/overlap resolution"),
    DT.FromUnixTime: ExprRule(
        T.DATETIME_SIG + T.INTEGRAL_SIG + T.STRING_SIG.with_note(
            T.StringType,
            "UTC session timezone; years 0001-9999 render correctly"),
        extra_check=_check_time_format),
    DT.DateFormat: ExprRule(
        T.DATETIME_SIG + T.STRING_SIG.with_note(
            T.StringType,
            "UTC session timezone; years 0001-9999 render correctly"),
        extra_check=_check_time_format),
    DT.ToUnixTimestamp: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.ToDate: ExprRule(
        T.DATETIME_SIG + T.STRING_SIG.with_note(
            T.StringType,
            "Spark stringToTimestamp subset; named timezones parse "
            "as null")),
    DT.ToTimestamp: ExprRule(
        T.DATETIME_SIG + T.STRING_SIG.with_note(
            T.StringType,
            "Spark stringToTimestamp subset; named timezones parse "
            "as null")),
    DT.WeekDay: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.MakeDate: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.MakeTimestamp: ExprRule(
        T.DATETIME_SIG + T.INTEGRAL_SIG + T.FP_SIG + T.DECIMAL_64_SIG),
    DT.CurrentDate: ExprRule(
        T.DATETIME_SIG.with_note(
            T.DateType, "captured once per query (UTC session timezone)")),
    DT.CurrentTimestamp: ExprRule(
        T.DATETIME_SIG.with_note(
            T.TimestampType,
            "captured once per query (UTC session timezone)")),
    DT.TimestampSeconds: ExprRule(
        T.DATETIME_SIG + T.INTEGRAL_SIG + T.FP_SIG),
    DT.TimestampMillis: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.TimestampMicros: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.UnixSeconds: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.UnixMillis: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.UnixMicros: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.UnixDate: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.DateFromUnixDate: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.TruncTimestamp: ExprRule(
        T.DATETIME_SIG + T.STRING_SIG,
        extra_check=_check_literal_fmt),
    DT.TimestampAdd: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.TimestampDiff: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    DT.ConvertTimezone: ExprRule(
        T.DATETIME_SIG, extra_check=_check_convert_timezone),
    DT.MonthName: ExprRule(T.DATETIME_SIG + T.STRING_SIG),
    DT.DayName: ExprRule(T.DATETIME_SIG + T.STRING_SIG),
    DT.LocalTimestamp: ExprRule(
        T.DATETIME_SIG.with_note(
            T.TimestampType,
            "captured once per query (UTC session timezone)")),
    DT.DatePart: ExprRule(T.DATETIME_SIG + T.INTEGRAL_SIG),
    MI.BitGet: ExprRule(T.INTEGRAL_SIG),
    MI.AssertTrue: ExprRule(T.BOOLEAN_SIG + T.NULL_SIG),
    MI.TypeOf: ExprRule(_WITH_ARRAYS + _WITH_MAPS,
                        allow_string_arrays=True,
                        desc="plan-time constant"),
    MI.UrlEncode: ExprRule(T.STRING_SIG, desc="host kernel"),
    MI.UrlDecode: ExprRule(T.STRING_SIG, desc="host kernel"),
    MI.JsonArrayLength: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                                 desc="host kernel"),
    MI.JsonObjectKeys: ExprRule(
        T.STRING_SIG + _ARRAY_SIG.with_note(
            T.ArrayType,
            f"first {MI.JsonObjectKeys.MAX_KEYS} keys, width "
            f"{MI.JsonObjectKeys.KEY_WIDTH}"),
        allow_string_arrays=True, desc="host kernel"),
    MI.FormatString: ExprRule(
        T.STRING_SIG + T.INTEGRAL_SIG + T.FP_SIG,
        extra_check=_check_literal_fmt, desc="host kernel"),
    MI.Uuid: ExprRule(
        T.STRING_SIG.with_note(
            T.StringType,
            "deterministic splitmix stream (reference marks uuid "
            "nondeterministic-incompat the same way)")),
    MI.Pi: ExprRule(T.FP_SIG),
    MI.EulerNumber: ExprRule(T.FP_SIG),
    MI.ToBinary: ExprRule(T.STRING_SIG, extra_check=_check_to_binary,
                          desc="host kernel (hex/base64); utf-8 on device"),
    MI.TryToBinary: ExprRule(T.STRING_SIG, extra_check=_check_to_binary,
                             desc="null instead of error on malformed"),
    MI.BitmapBitPosition: ExprRule(T.INTEGRAL_SIG),
    MI.BitmapBucketNumber: ExprRule(T.INTEGRAL_SIG),
    MI.BitmapCount: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG + T.BINARY_SIG,
                             desc="popcount over the binary blob"),
    MI.Randn: ExprRule(
        T.FP_SIG.with_note(
            T.DoubleType,
            "splitmix Box-Muller stream, not Spark's XORShiftRandom "
            "(reference marks rand nondeterministic-incompat the same "
            "way)")),
    MI.Sentences: ExprRule(
        T.STRING_SIG + _ARRAY_SIG, extra_check=_check_sentences,
        desc="always falls back (nested array<array<string>> layout)"),
    AV.AvroDataToCatalyst: ExprRule(
        all_avro_sig(), extra_check=_check_from_avro,
        desc="host-kernel row codec (from_avro); flat primitive records"),
    AV.CatalystDataToAvro: ExprRule(
        all_avro_sig(), extra_check=_check_to_avro,
        desc="host-kernel row codec (to_avro); flat primitive records"),
    S.Mask: ExprRule(T.STRING_SIG, extra_check=_check_mask),
    S.ILike: ExprRule(T.STRING_SIG + T.BOOLEAN_SIG,
                      extra_check=_check_ilike),
    S.RegExpCount: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                            extra_check=_check_regexp_span),
    S.RegExpInStr: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                            extra_check=_check_regexp_span),
    S.RegExpSubStr: ExprRule(T.STRING_SIG,
                             extra_check=_check_regexp_span),
    S.SplitPart: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                          extra_check=_check_split_part),
    CL.Get: ExprRule(_WITH_ARRAYS, allow_string_arrays=True),
    CL.ArraySize: ExprRule(_WITH_ARRAYS, allow_string_arrays=True),
    H.Murmur3Hash: ExprRule(_COMMON128, desc="Spark murmur3 hash"),
    H.XxHash64: ExprRule(_COMMON128, desc="Spark xxhash64"),
    H.HiveHash: ExprRule(_COMMON, extra_check=_check_hive_hash,
                         desc="Hive hash (31*h + colHash)"),
    H.BloomFilterMightContain: ExprRule(
        _COMMON128 + _ARRAY_SIG.with_note(
            T.ArrayType,
            "filter layout is the TPU word array, not Spark's sketch "
            "bytes"),
        desc="bloom filter probe (runtime-filter pushdown)"),
    CL.Size: ExprRule(_WITH_ARRAYS, allow_string_arrays=True),
    CL.Cardinality: ExprRule(_WITH_ARRAYS + _WITH_MAPS,
                             allow_string_arrays=True),
    CL.GetArrayItem: ExprRule(_WITH_ARRAYS, allow_string_arrays=True),
    CL.ElementAt: ExprRule(_WITH_ARRAYS + _WITH_MAPS,
                           allow_string_arrays=True),
    CL.TryElementAt: ExprRule(_WITH_ARRAYS + _WITH_MAPS,
                              allow_string_arrays=True),
    CL.MapFromEntries: ExprRule(_WITH_MAPS + _WITH_ARRAYS,
                                extra_check=_check_map_from_entries),
    CL.MapSort: ExprRule(_WITH_MAPS,
                         extra_check=_check_map_sort),
    CL.Shuffle: ExprRule(
        _WITH_ARRAYS.with_note(
            T.ArrayType,
            "splitmix permutation stream, not Spark's random sequence"),
        extra_check=_check_shuffle),
    DT.ParseToDate: ExprRule(T.DATETIME_SIG + T.STRING_SIG,
                             extra_check=_check_parse_to_datetime),
    DT.ParseToTimestamp: ExprRule(T.DATETIME_SIG + T.STRING_SIG,
                                  extra_check=_check_parse_to_datetime),
    DT.Extract: ExprRule(T.DATETIME_SIG + T.STRING_SIG + T.INTEGRAL_SIG,
                         extra_check=_check_extract),
    S.Luhn: ExprRule(T.STRING_SIG + T.BOOLEAN_SIG),
    S.Empty2Null: ExprRule(T.STRING_SIG),
    A.UnaryPositive: ExprRule(_NUM128),
    DT.TryToTimestamp: ExprRule(T.DATETIME_SIG + T.STRING_SIG,
                                extra_check=_check_parse_to_datetime),
    MI.ToNumber: ExprRule(
        T.STRING_SIG + T.DECIMAL_128_SIG,
        extra_check=_check_number_format,
        desc="host kernel; 0/9/,/./$/S/MI format subset"),
    MI.TryToNumber: ExprRule(
        T.STRING_SIG + T.DECIMAL_128_SIG,
        extra_check=_check_number_format,
        desc="null instead of error on mismatch"),
    MI.ToCharacter: ExprRule(
        T.STRING_SIG + _NUM128, extra_check=_check_number_format,
        desc="host kernel; 0/9/,/./$/S/MI format subset"),
    MI.InputFileName: ExprRule(
        T.STRING_SIG, desc="file path stamped by the scan execs"),
    XM.XmlToStructs: ExprRule(
        all_avro_sig(), extra_check=_check_from_xml,
        desc="host-kernel row codec (from_xml); flat structs"),
    XM.StructsToXml: ExprRule(
        all_avro_sig(), extra_check=_check_to_xml,
        desc="host-kernel row codec (to_xml); flat structs"),
    CL.ArrayContains: ExprRule(_WITH_ARRAYS),
    CL.CreateArray: ExprRule(_WITH_ARRAYS, extra_check=_check_create_array),
    CL.ArrayMin: ExprRule(_WITH_ARRAYS),
    CL.ArrayMax: ExprRule(_WITH_ARRAYS),
    CL.ArrayPosition: ExprRule(_WITH_ARRAYS),
    CL.ArrayRemove: ExprRule(_WITH_ARRAYS),
    CL.ArrayDistinct: ExprRule(_WITH_ARRAYS),
    CL.ArraysOverlap: ExprRule(_WITH_ARRAYS),
    CL.ArrayUnion: ExprRule(_WITH_ARRAYS),
    CL.ArrayIntersect: ExprRule(_WITH_ARRAYS),
    CL.ArrayExcept: ExprRule(_WITH_ARRAYS),
    CL.ArrayInsert: ExprRule(_WITH_ARRAYS,
                             extra_check=_check_array_insert,
                             allow_string_arrays=True),
    CL.Flatten: ExprRule(_WITH_ARRAYS, extra_check=_check_flatten,
                         allow_string_arrays=True),
    CL.StrToMap: ExprRule(T.STRING_SIG + T.NULL_SIG + T.TypeSig(
        frozenset({T.MapType, T.ArrayType})),
                          extra_check=_check_str_to_map,
                          desc="host kernel (split family)"),
    CL.MapEntries: ExprRule(
        _WITH_MAPS + T.TypeSig(frozenset({T.StructType})),
        allow_struct_entries=True, desc="entries layout"),
    CL.ArraysZip: ExprRule(
        _WITH_ARRAYS + T.TypeSig(frozenset({T.StructType})),
        allow_struct_entries=True, allow_string_arrays=True,
        desc="entries layout"),
    CL.Slice: ExprRule(_WITH_ARRAYS),
    CL.SortArray: ExprRule(
        _WITH_ARRAYS + T.BOOLEAN_SIG,
        extra_check=_check_literal_children(1, names="ascending flag")),
    CL.ArrayRepeat: ExprRule(
        _WITH_ARRAYS.with_note(
            T.ArrayType,
            f"element count capped at {CL.ArrayRepeat.MAX_ELEMENTS}")),
    CL.Sequence: ExprRule(
        _WITH_ARRAYS.with_note(
            T.ArrayType,
            f"sequence length capped at {CL.Sequence.MAX_ELEMENTS}")),
    HOF.ArrayTransform: ExprRule(_WITH_ARRAYS, extra_check=_check_hof),
    HOF.MapZipWith: ExprRule(_WITH_MAPS + T.STRING_SIG),
    HOF.ArrayFilter: ExprRule(_WITH_ARRAYS, extra_check=_check_hof),
    HOF.ArrayExists: ExprRule(
        _WITH_ARRAYS + T.BOOLEAN_SIG, extra_check=_check_hof),
    HOF.ArrayForAll: ExprRule(
        _WITH_ARRAYS + T.BOOLEAN_SIG, extra_check=_check_hof),
    HOF.ArrayAggregate: ExprRule(_WITH_ARRAYS, extra_check=_check_hof_agg),
    CL.CreateMap: ExprRule(_WITH_MAPS),
    CL.MapKeys: ExprRule(_WITH_MAPS),
    CL.MapValues: ExprRule(_WITH_MAPS),
    CL.GetMapValue: ExprRule(_WITH_MAPS),
    CL.MapFromArrays: ExprRule(_WITH_MAPS),
    CL.MapConcat: ExprRule(_WITH_MAPS),
    CL.MapContainsKey: ExprRule(_WITH_MAPS),
    CL.ArrayCompact: ExprRule(_WITH_ARRAYS),
    CL.ArrayAppend: ExprRule(_WITH_ARRAYS),
    CL.ArrayPrepend: ExprRule(_WITH_ARRAYS),
    HOF.TransformKeys: ExprRule(_WITH_MAPS, extra_check=_check_hof),
    HOF.TransformValues: ExprRule(_WITH_MAPS, extra_check=_check_hof),
    HOF.MapFilter: ExprRule(_WITH_MAPS + T.BOOLEAN_SIG,
                            extra_check=_check_hof),
    HOF.ZipWith: ExprRule(_WITH_ARRAYS, extra_check=_check_hof),
    U.UserDefinedExpression: ExprRule(
        _DEC128_FULL, extra_check=_check_udf,
        desc="TpuUDF (RapidsUDF analog): columnar jax kernel"),
    J.SchemaOfJson: ExprRule(T.STRING_SIG,
                            extra_check=_check_schema_of_json,
                            desc="plan-time constant fold"),
    XP.XPathList: ExprRule(T.STRING_SIG + T.NULL_SIG,
                           extra_check=_check_xpath,
                           allow_string_arrays=True,
                           desc="host kernel"),
    XP.XPathString: ExprRule(T.STRING_SIG + T.NULL_SIG,
                             extra_check=_check_xpath, desc="host kernel"),
    XP.XPathBoolean: ExprRule(T.STRING_SIG + T.NULL_SIG,
                              extra_check=_check_xpath,
                              desc="host kernel"),
    XP.XPathShort: ExprRule(T.STRING_SIG + T.NULL_SIG,
                            extra_check=_check_xpath, desc="host kernel"),
    XP.XPathInt: ExprRule(T.STRING_SIG + T.NULL_SIG,
                          extra_check=_check_xpath, desc="host kernel"),
    XP.XPathLong: ExprRule(T.STRING_SIG + T.NULL_SIG,
                           extra_check=_check_xpath, desc="host kernel"),
    XP.XPathFloat: ExprRule(T.STRING_SIG + T.NULL_SIG,
                            extra_check=_check_xpath, desc="host kernel"),
    XP.XPathDouble: ExprRule(T.STRING_SIG + T.NULL_SIG,
                             extra_check=_check_xpath, desc="host kernel"),
    J.GetJsonObject: ExprRule(
        T.STRING_SIG.with_note(
            T.StringType,
            "nested results are whitespace-compacted, not re-serialized"),
        extra_check=_check_json_path,
        desc="JSON path extraction (native host kernel)"),
    J.JsonTuple: ExprRule(
        T.STRING_SIG + _STRUCT_SIG,
        extra_check=_check_json_tuple,
        desc="json_tuple as a struct of string fields"),
    J.JsonToStructs: ExprRule(
        T.STRING_SIG + _STRUCT_SIG,
        extra_check=_check_from_json,
        desc="from_json (PERMISSIVE) into a flat struct"),
    J.StructsToJson: ExprRule(
        T.STRING_SIG + _STRUCT_SIG.with_note(
            T.StructType, "float fields may format differently than Spark"),
        extra_check=_check_to_json,
        desc="to_json of a flat struct"),
    CT.GetStructField: ExprRule(_STRUCT_SIG + _DEC128_FULL),
    CT.CreateNamedStruct: ExprRule(_STRUCT_SIG + _DEC128_FULL),
    MI.Md5: ExprRule(T.STRING_SIG, desc="md5 hex digest (host kernel)"),
    MI.Sha1: ExprRule(T.STRING_SIG),
    MI.Sha2: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                      extra_check=_check_literal_children(
                          1, names="bit length")),
    MI.Crc32: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    MI.Base64: ExprRule(T.STRING_SIG),
    MI.UnBase64: ExprRule(T.STRING_SIG.with_note(
        T.StringType, "binary output surfaces as the string column kind")),
    MI.Encode: ExprRule(T.STRING_SIG,
                        extra_check=_check_charset),
    MI.Decode: ExprRule(T.STRING_SIG, extra_check=_check_charset),
    MI.Hex: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    MI.Unhex: ExprRule(T.STRING_SIG),
    MI.Bin: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG),
    MI.Conv: ExprRule(T.STRING_SIG + T.INTEGRAL_SIG,
                      extra_check=_check_literal_children(
                          1, 2, names="bases")),
    MI.FormatNumber: ExprRule(
        _NUM + T.STRING_SIG.with_note(
            T.StringType, "HALF_EVEN rounding, US grouping")),
    MI.ParseUrl: ExprRule(T.STRING_SIG,
                          extra_check=_check_literal_children(
                              1, names="url part")),
    MI.Soundex: ExprRule(T.STRING_SIG.with_note(
        T.StringType, "ASCII letters only")),
    MI.Levenshtein: ExprRule(
        T.STRING_SIG.with_note(T.StringType, "byte-based; ASCII-exact")
        + T.INTEGRAL_SIG),
    MI.MonotonicallyIncreasingID: ExprRule(T.INTEGRAL_SIG),
    MI.SparkPartitionID: ExprRule(T.INTEGRAL_SIG),
    MI.Rand: ExprRule(T.FP_SIG.with_note(
        T.DoubleType,
        "deterministic threefry/splitmix stream, not Spark's "
        "XORShiftRandom sequence")),
    MI.RaiseError: ExprRule(T.STRING_SIG + T.NULL_SIG),
}


def wrap_expr(e: E.Expression, conf: TpuConf) -> ExprMeta:
    rule = EXPRESSIONS.get(type(e))
    return ExprMeta(e, conf, rule)


# ---------------------------------------------------------------------------
# Exec rules
# ---------------------------------------------------------------------------

_AGG_FUNCS_SUPPORTED = {"sum", "count", "count_star", "min", "max", "avg",
                        "first", "last", "var_pop", "var_samp", "stddev_pop",
                        "stddev_samp", "collect_list", "collect_set",
                        "count_if", "skewness", "kurtosis", "corr",
                        "covar_pop", "covar_samp", "percentile",
                        "approx_percentile", "approx_count_distinct",
                        "bloom_filter_agg",
                        # round 4: bool/bit/any_value/median + regr family
                        "bool_and", "bool_or", "bit_and", "bit_or",
                        "bit_xor", "any_value", "median",
                        "regr_count", "regr_avgx", "regr_avgy", "regr_sxx",
                        "regr_syy", "regr_sxy", "regr_slope",
                        "regr_intercept", "regr_r2"}

_NUMERIC_AGG_INPUT = (T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                      T.FloatType, T.DoubleType, T.DecimalType)


def _agg_extra_checks(meta: SparkPlanMeta, a) -> None:
    """Per-function input gates for the breadth aggregates."""
    ct = a.child._dataType if a.child is not None else None
    if a.func == "count_if" and not isinstance(ct, T.BooleanType):
        meta.will_not_work_on_tpu("count_if requires a boolean input")
    if a.func in ("skewness", "kurtosis", "percentile",
                  "approx_percentile") \
            and not isinstance(ct, _NUMERIC_AGG_INPUT):
        meta.will_not_work_on_tpu(
            f"{a.func} requires a numeric input")
    if a.func in PN.COVARIANCE_FUNCS or a.func in PN.REGR_FUNCS:
        c2 = a.child2._dataType if a.child2 is not None else None
        for part in (ct, c2):
            if not isinstance(part, _NUMERIC_AGG_INPUT):
                meta.will_not_work_on_tpu(
                    f"{a.func} requires numeric inputs")
                break
    if a.func in ("bool_and", "bool_or") \
            and not isinstance(ct, T.BooleanType):
        meta.will_not_work_on_tpu(f"{a.func} requires a boolean input")
    if a.func in ("bit_and", "bit_or", "bit_xor") \
            and not (ct is not None and ct.is_integral):
        meta.will_not_work_on_tpu(f"{a.func} requires an integral input")
    if a.func == "median" and not isinstance(ct, _NUMERIC_AGG_INPUT):
        meta.will_not_work_on_tpu("median requires a numeric input")
    if a.func == "median" and isinstance(ct, T.DecimalType) and ct.is_128:
        meta.will_not_work_on_tpu(
            "median over decimal128 is not supported on TPU")
    if a.func in ("percentile", "approx_percentile"):
        if not a.args or not (0.0 <= float(a.args[0]) <= 1.0):
            meta.will_not_work_on_tpu(
                f"{a.func}: percentage must be a literal in [0, 1]")
        if isinstance(ct, T.DecimalType) and ct.is_128:
            meta.will_not_work_on_tpu(
                f"{a.func} over decimal128 is not supported on TPU")
    if a.func in ("approx_count_distinct", "bloom_filter_agg"):
        if isinstance(ct, T.DecimalType) and ct.precision > 18:
            meta.will_not_work_on_tpu(
                f"{a.func}: decimal128 inputs are not supported (xxhash64 "
                "big-integer path missing)")
        if isinstance(ct, (T.ArrayType, T.MapType, T.StructType)):
            meta.will_not_work_on_tpu(
                f"{a.func} over nested inputs is not supported on TPU")
    if a.func == "bloom_filter_agg":
        if len(a.args) != 2 or int(a.args[1]) % 64 != 0 \
                or not (64 <= int(a.args[1]) <= (1 << 22)):
            meta.will_not_work_on_tpu(
                "bloom_filter_agg: num_bits must be a multiple of 64 in "
                "[64, 4194304]")
_WINDOW_FUNCS_SUPPORTED = {"row_number", "rank", "dense_rank", "sum", "count",
                           "min", "max", "avg", "lead", "lag", "ntile",
                           "percent_rank", "cume_dist", "first_value",
                           "last_value", "var_pop", "var_samp", "stddev_pop",
                           "stddev_samp"}
# frame-independent ranking/navigation functions
_WINDOW_RANK_FUNCS = {"row_number", "rank", "dense_rank", "ntile",
                      "percent_rank", "cume_dist", "lead", "lag"}
# bounded ROWS frames unroll shifted combines; cap the static window width
_MAX_BOUNDED_WINDOW = 256
_JOIN_TYPES_SUPPORTED = {PN.JoinType.INNER, PN.JoinType.LEFT_OUTER,
                         PN.JoinType.RIGHT_OUTER, PN.JoinType.FULL_OUTER,
                         PN.JoinType.LEFT_SEMI, PN.JoinType.LEFT_ANTI,
                         PN.JoinType.CROSS}


def _agg_check(meta: SparkPlanMeta):
    plan: PN.HashAggregate = meta.plan
    # the array-capable sig exists for collect_* OUTPUT columns only; array
    # grouping keys / array inputs to other aggregates have no TPU kernels
    for g in plan.grouping:
        if isinstance(g._dataType, T.ArrayType):
            meta.will_not_work_on_tpu(
                "grouping by an array column is not supported on TPU")
    for a in plan.aggregates:
        if (a.func not in ("collect_list", "collect_set")
                and a.child is not None
                and isinstance(a.child._dataType, T.ArrayType)):
            meta.will_not_work_on_tpu(
                f"{a.func} over an array column is not supported on TPU")
        if a.func not in _AGG_FUNCS_SUPPORTED:
            meta.will_not_work_on_tpu(
                f"aggregate function {a.func} is not supported on TPU")
        else:
            _agg_extra_checks(meta, a)
        if a.distinct:
            meta.will_not_work_on_tpu(
                "distinct aggregates are not supported on TPU yet")
        if a.func in ("collect_list", "collect_set") \
                and a.child is not None:
            et = a.child._dataType
            if isinstance(et, (T.StringType, T.ArrayType, T.MapType,
                               T.StructType)) or _is_dec128(et):
                meta.will_not_work_on_tpu(
                    f"{a.func} of {et.simpleString} elements is not "
                    f"supported on TPU (primitive elements only)")
        if (a.func in ("avg", "var_pop", "var_samp", "stddev_pop",
                       "stddev_samp")
                and a.child is not None and _is_dec128(a.child._dataType)):
            meta.will_not_work_on_tpu(
                f"{a.func} over decimals above 18 digits needs 128-bit "
                f"division; not supported on TPU yet")


def _join_check(meta: SparkPlanMeta):
    plan = meta.plan
    if plan.join_type not in _JOIN_TYPES_SUPPORTED:
        meta.will_not_work_on_tpu(
            f"join type {plan.join_type.value} is not supported on TPU")
    if plan.condition is not None and plan.join_type != PN.JoinType.INNER:
        meta.will_not_work_on_tpu(
            "non-inner join with residual condition is not supported on TPU")
    if not plan.left_keys and plan.join_type != PN.JoinType.CROSS:
        meta.will_not_work_on_tpu("equi-join keys required")
    for k in list(plan.left_keys) + list(plan.right_keys):
        if isinstance(k._dataType, (T.ArrayType, T.MapType, T.StructType)):
            meta.will_not_work_on_tpu(
                "join keys of nested types are not supported on TPU")


def _window_check(meta: SparkPlanMeta):
    """Tag-or-fallback for every (function, frame, type) combination the
    exec supports (GpuWindowExec tagging analog).  Anything rejected here is
    unreachable in exec/window.py — the RapidsMeta contract is that no
    NotImplementedError fires after conversion."""
    plan: PN.Window = meta.plan
    frame = plan.frame
    bounded = isinstance(frame, tuple)
    for f in plan.functions:
        if f.func not in _WINDOW_FUNCS_SUPPORTED:
            meta.will_not_work_on_tpu(
                f"window function {f.func} is not supported on TPU")
            continue
        if f.func in _WINDOW_RANK_FUNCS:
            continue
        ct = f.child._dataType if f.child is not None else None
        if ct is not None and isinstance(ct, (T.ArrayType, T.MapType,
                                              T.StructType)):
            meta.will_not_work_on_tpu(
                f"{f.func} over nested-typed window inputs is not "
                f"supported on TPU")
        if ct is not None and isinstance(ct, T.DecimalType) and ct.is_128 \
                and f.func != "count":
            meta.will_not_work_on_tpu(
                f"{f.func} over decimals above 18 digits in a window is "
                f"not supported on TPU")
        if ct is not None and isinstance(ct, T.DecimalType) \
                and (f.func == "avg" or f.func.startswith(("var", "stddev"))):
            meta.will_not_work_on_tpu(
                f"window {f.func} over decimals yields a decimal result "
                f"(needs decimal division); not supported on TPU")
        if isinstance(ct, T.StringType):
            if f.func in ("sum", "avg") or f.func.startswith(("var", "stddev")):
                meta.will_not_work_on_tpu(
                    f"{f.func} over strings is not valid")
            elif f.func in ("min", "max") and bounded:
                meta.will_not_work_on_tpu(
                    "string min/max over bounded window frames is not "
                    "supported on TPU (running/range/unbounded frames only)")
    if bounded:
        kind, a, b = frame
        if a < 0 or b < 0:
            meta.will_not_work_on_tpu(
                "bounded window frame offsets must be non-negative")
        elif kind == "rows" and a + b + 1 > _MAX_BOUNDED_WINDOW:
            meta.will_not_work_on_tpu(
                f"bounded window width {a + b + 1} exceeds the TPU unroll "
                f"cap ({_MAX_BOUNDED_WINDOW})")
        if kind == "range":
            if len(plan.order_by) != 1:
                meta.will_not_work_on_tpu(
                    "RANGE window frames require exactly one ORDER BY key")
            else:
                ot = plan.order_by[0][0]._dataType
                ok = (ot.is_integral
                      or isinstance(ot, (T.FloatType, T.DoubleType,
                                         T.DateType, T.TimestampType)))
                if not ok:
                    meta.will_not_work_on_tpu(
                        f"RANGE window frames over {ot.simpleString} order "
                        f"keys are not supported on TPU")
    if frame in ("range_running",) or (bounded and frame[0] == "range"):
        if not plan.order_by:
            meta.will_not_work_on_tpu(
                "RANGE window frames require an ORDER BY")


def _scan_check(meta: SparkPlanMeta):
    plan: PN.FileSourceScan = meta.plan
    fmt = plan.fmt
    key = {"parquet": "spark.rapids.sql.format.parquet.read.enabled",
           "csv": "spark.rapids.sql.format.csv.read.enabled",
           "json": "spark.rapids.sql.format.json.read.enabled",
           "orc": "spark.rapids.sql.format.orc.read.enabled",
           "avro": "spark.rapids.sql.format.avro.read.enabled"}.get(fmt)
    if key is None:
        meta.will_not_work_on_tpu(f"format {fmt} is not supported on TPU")
        return
    if str(meta.conf.settings.get(key, "true")).lower() == "false":
        meta.will_not_work_on_tpu(f"{fmt} reads disabled by {key}=false")


def _write_check(meta: SparkPlanMeta):
    """dataWriteCmds tagging (GpuOverrides.dataWriteCmds analog)."""
    plan = meta.plan
    if plan.fmt not in ("parquet", "orc", "csv", "json"):
        meta.will_not_work_on_tpu(
            f"write format {plan.fmt} is not supported on TPU")
        return
    key = f"spark.rapids.sql.format.{plan.fmt}.write.enabled"
    if str(meta.conf.settings.get(key, "true")).lower() == "false":
        meta.will_not_work_on_tpu(
            f"{plan.fmt} writes disabled by {key}=false")


def _exprs_of(plan) -> List[E.Expression]:
    if isinstance(plan, PN.Project):
        return list(plan.exprs)
    if isinstance(plan, PN.Filter):
        return [plan.condition]
    if isinstance(plan, PN.HashAggregate):
        out = list(plan.grouping)
        out += [a.child for a in plan.aggregates if a.child is not None]
        out += [a.child2 for a in plan.aggregates if a.child2 is not None]
        return out
    if isinstance(plan, PN._BaseJoin):
        out = list(plan.left_keys) + list(plan.right_keys)
        if plan.condition is not None:
            out.append(plan.condition)
        return out
    if isinstance(plan, PN.Sort):
        return [e for e, _ in plan.orders]
    if isinstance(plan, PN.Window):
        out = list(plan.partition_by) + [e for e, _ in plan.order_by]
        out += [f.child for f in plan.functions if f.child is not None]
        return out
    if isinstance(plan, PN.Exchange) and isinstance(
            plan.partitioning, PN.HashPartitioning):
        return list(plan.partitioning.keys)
    if isinstance(plan, PN.Generate):
        return [plan.gen_expr]
    if isinstance(plan, PN.Expand):
        return [e for ps in plan.projections for e in ps]
    return []


EXECS: Dict[Type, ExecRule] = {}


def _exec(cls, sig=_DEC128_FULL, tag_exprs=_exprs_of, extra=None, desc="",
          allow_string_arrays=False):
    EXECS[cls] = ExecRule(sig, tag_exprs=tag_exprs, extra_check=extra,
                          desc=desc,
                          allow_string_arrays=allow_string_arrays)


def _generate_check(meta: SparkPlanMeta):
    plan: PN.Generate = meta.plan
    dt = plan.gen_expr._dataType
    if not isinstance(dt, T.ArrayType):
        meta.will_not_work_on_tpu("explode input must be an array column")
    elif isinstance(dt.elementType, (T.ArrayType, T.MapType, T.StructType)):
        meta.will_not_work_on_tpu(
            "explode of nested array elements is not supported on TPU yet")


_BNLJ_TYPES = {PN.JoinType.INNER, PN.JoinType.CROSS, PN.JoinType.LEFT_OUTER,
               PN.JoinType.LEFT_SEMI, PN.JoinType.LEFT_ANTI}


def _bnlj_check(meta: SparkPlanMeta):
    plan: PN.BroadcastNestedLoopJoin = meta.plan
    if plan.join_type not in _BNLJ_TYPES:
        meta.will_not_work_on_tpu(
            f"nested-loop join type {plan.join_type.value} is not supported "
            f"on TPU (use an equi-join)")


def _exchange_check(meta: SparkPlanMeta):
    plan: PN.Exchange = meta.plan
    if isinstance(plan.partitioning, PN.HashPartitioning):
        for k in plan.partitioning.keys:
            if _is_dec128(k._dataType):
                meta.will_not_work_on_tpu(
                    "hash partitioning on decimals above 18 digits is not "
                    "supported on TPU (murmur3 big-integer path missing)")


_WITH_NESTED = _WITH_ARRAYS + T.TypeSig(
    frozenset({T.StructType, T.MapType}))

_exec(PN.LocalTableScan, sig=_WITH_NESTED, allow_string_arrays=True)
_exec(PN.CachedRelation, desc="GpuInMemoryTableScanExec analog")
_exec(PN.FileSourceScan, extra=_scan_check)
_exec(PN.InsertIntoHadoopFsRelation, extra=_write_check,
      desc="GpuDataWritingCommandExec analog")
_exec(PN.RangeNode)
_exec(PN.Sample, sig=_WITH_NESTED, allow_string_arrays=True,
      desc="deterministic splitmix sampler "
      "(GpuSampleExec analog; not Spark's XORShift sequence)")
_exec(PN.Project, sig=_WITH_NESTED, allow_string_arrays=True)
_exec(PN.Filter, sig=_WITH_NESTED, allow_string_arrays=True)
_exec(PN.HashAggregate, sig=_WITH_ARRAYS, extra=_agg_check)
_exec(PN.SortMergeJoin, sig=_WITH_ARRAYS, extra=_join_check,
      desc="converted to shuffled sorted join (GpuSortMergeJoinMeta analog)")
_exec(PN.ShuffledHashJoin, sig=_WITH_ARRAYS, extra=_join_check)
_exec(PN.BroadcastHashJoin, sig=_WITH_ARRAYS, extra=_join_check)
_exec(PN.Sort)
_exec(PN.Window, sig=_COMMON128, extra=_window_check)
_exec(PN.Generate, sig=_WITH_ARRAYS, extra=_generate_check,
      allow_string_arrays=True)
_exec(PN.Expand, sig=_WITH_ARRAYS)
_exec(PN.BroadcastNestedLoopJoin, extra=_bnlj_check)
_exec(PN.Exchange, extra=_exchange_check)
_exec(PN.BroadcastExchange)
_exec(PN.GlobalLimit, sig=_WITH_ARRAYS)
_exec(PN.LocalLimit, sig=_WITH_ARRAYS)
_exec(PN.Union, sig=_WITH_ARRAYS)


def wrap_plan(plan: PN.SparkPlan, conf: TpuConf) -> SparkPlanMeta:
    rule = EXECS.get(type(plan))
    return SparkPlanMeta(plan, conf, rule)


def wrap_plan_children(plan: PN.SparkPlan, conf: TpuConf):
    return [wrap_plan(c, conf) for c in plan.children]


# ---------------------------------------------------------------------------
# Conversion
# ---------------------------------------------------------------------------

def _convert_node(meta: SparkPlanMeta, tpu_children, ansi: bool):
    """Build the TpuExec for one convertible node."""
    from spark_rapids_tpu import exec as X
    from spark_rapids_tpu.exec.exchange import TpuBroadcastExchangeExec
    from spark_rapids_tpu.exec.join import TpuCartesianProductExec
    from spark_rapids_tpu.io.scan import TpuFileSourceScanExec

    plan = meta.plan
    if isinstance(plan, PN.LocalTableScan):
        from spark_rapids_tpu.config import TPU_SCAN_CACHE

        rows_cap = meta.conf.get(MAX_READER_BATCH_SIZE_ROWS)
        return X.TpuLocalTableScanExec(
            plan.host_columns, plan.output,
            target_batch_rows=rows_cap if rows_cap < 2147483647 else None,
            cache_device=meta.conf.get(TPU_SCAN_CACHE), cache_slot=plan)
    if isinstance(plan, PN.FileSourceScan):
        return TpuFileSourceScanExec(plan, meta.conf)
    if isinstance(plan, PN.RangeNode):
        return X.TpuRangeExec(plan.start, plan.end, plan.step)
    if isinstance(plan, PN.CachedRelation):
        return X.TpuInMemoryTableScanExec(tpu_children[0], plan.cache_slot)
    if isinstance(plan, PN.Project):
        return X.TpuProjectExec(plan.exprs, tpu_children[0], ansi)
    if isinstance(plan, PN.Filter):
        return X.TpuFilterExec(plan.condition, tpu_children[0], ansi)
    if isinstance(plan, PN.HashAggregate):
        return X.TpuHashAggregateExec(
            plan.grouping, plan.aggregates, plan.mode, tpu_children[0],
            plan.child.output, plan.output, ansi)
    if isinstance(plan, (PN.SortMergeJoin, PN.ShuffledHashJoin)):
        if plan.join_type == PN.JoinType.CROSS:
            return TpuCartesianProductExec(tpu_children[0], tpu_children[1],
                                           plan.output, plan.condition, ansi)
        shuffled = X.TpuShuffledSymmetricHashJoinExec(
            tpu_children[0], tpu_children[1], plan.left_keys, plan.right_keys,
            plan.join_type, plan.condition, plan.output, ansi,
            sub_partition_bytes=meta.conf.get(BATCH_SIZE_BYTES))
        # AQE: runtime join-strategy switch when both sides are planned
        # exchanges (spark.sql.adaptive.enabled, default on like Spark)
        from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
        from spark_rapids_tpu.exec.join import TpuAdaptiveJoinExec

        from spark_rapids_tpu.config import ADAPTIVE_ENABLED

        adaptive = meta.conf.get(ADAPTIVE_ENABLED)
        if adaptive and all(isinstance(c, TpuShuffleExchangeExec)
                            for c in shuffled.children):
            from spark_rapids_tpu.config import (
                AUTO_BROADCAST_JOIN_THRESHOLD,
            )

            return TpuAdaptiveJoinExec(
                shuffled, meta.conf.get(AUTO_BROADCAST_JOIN_THRESHOLD))
        return shuffled
    if isinstance(plan, PN.BroadcastHashJoin):
        return X.TpuBroadcastHashJoinExec(
            tpu_children[0], tpu_children[1], plan.left_keys, plan.right_keys,
            plan.join_type, plan.condition, plan.output, ansi,
            sub_partition_bytes=meta.conf.get(BATCH_SIZE_BYTES))
    if isinstance(plan, PN.Sample):
        from spark_rapids_tpu.exec.limit import TpuSampleExec

        return TpuSampleExec(plan.fraction, plan.seed, tpu_children[0])
    if isinstance(plan, PN.Sort):
        return X.TpuSortExec(plan.orders, plan.is_global, tpu_children[0],
                             ansi, ooc_bytes=meta.conf.get(BATCH_SIZE_BYTES))
    if isinstance(plan, PN.Window):
        return X.TpuWindowExec(plan.functions, plan.partition_by,
                               plan.order_by, tpu_children[0], plan.output,
                               plan.frame, ansi)
    if isinstance(plan, PN.Generate):
        from spark_rapids_tpu.exec.generate import TpuGenerateExec

        return TpuGenerateExec(plan.gen_expr, tpu_children[0],
                               plan.position, plan.outer, plan.output, ansi)
    if isinstance(plan, PN.Expand):
        from spark_rapids_tpu.exec.generate import TpuExpandExec

        return TpuExpandExec(plan.projections, tpu_children[0], plan.output,
                             ansi)
    if isinstance(plan, PN.BroadcastNestedLoopJoin):
        from spark_rapids_tpu.exec.generate import (
            TpuBroadcastNestedLoopJoinExec,
        )

        return TpuBroadcastNestedLoopJoinExec(
            tpu_children[0], tpu_children[1], plan.join_type,
            plan.condition, plan.output, ansi)
    if isinstance(plan, PN.Exchange):
        return X.TpuShuffleExchangeExec(plan.partitioning, tpu_children[0],
                                        ansi, conf=meta.conf)
    if isinstance(plan, PN.BroadcastExchange):
        return TpuBroadcastExchangeExec(tpu_children[0])
    if isinstance(plan, PN.GlobalLimit):
        return X.TpuGlobalLimitExec(plan.n, tpu_children[0])
    if isinstance(plan, PN.LocalLimit):
        return X.TpuLocalLimitExec(plan.n, tpu_children[0])
    if isinstance(plan, PN.Union):
        return X.TpuUnionExec(tpu_children)
    if isinstance(plan, PN.InsertIntoHadoopFsRelation):
        from spark_rapids_tpu.io.writer import TpuDataWritingCommandExec

        return TpuDataWritingCommandExec(
            plan.fmt, plan.path, plan.partition_cols, tpu_children[0],
            meta.conf, plan.mode)
    raise NotImplementedError(f"convert {meta.name}")


class CpuSubtree:
    """Marker: this subtree stays on CPU (executed by the oracle)."""

    def __init__(self, plan: PN.SparkPlan):
        self.plan = plan


def _rebuild_cpu_plan(meta: SparkPlanMeta, converted_children):
    """Child results may be TpuExec (need materialization node) or CPU plans."""
    from spark_rapids_tpu.exec.base import TpuExec
    from spark_rapids_tpu.overrides.transitions import TpuMaterializedScan

    new_children = []
    for cc in converted_children:
        if isinstance(cc, TpuExec):
            new_children.append(TpuMaterializedScan(cc))
        else:
            new_children.append(cc)
    return meta.plan.with_new_children(new_children)


def _walk_plan(plan: PN.SparkPlan):
    yield plan
    for c in plan.children:
        yield from _walk_plan(c)


class TpuOverrides:
    """The Rule[SparkPlan] entry point."""

    @staticmethod
    def apply(plan: PN.SparkPlan, conf: TpuConf):
        """Returns (root, meta): root is a TpuExec (possibly with embedded
        CPU subtrees) or a CPU plan (possibly with embedded TPU subtrees)."""
        from spark_rapids_tpu.exec.base import TpuExec
        from spark_rapids_tpu.exec.transitions import TpuRowToColumnarExec
        from spark_rapids_tpu.overrides.transitions import (
            TpuTransitionOverrides,
        )

        TpuOverrides._compile_udfs(plan, conf)
        meta = wrap_plan(plan, conf)
        meta.tag_for_tpu()
        TpuOverrides._apply_cost_optimizer(meta, conf)
        explain = conf.explain.upper()
        if explain in ("NOT_ON_GPU", "ALL"):
            txt = meta.explain(only_fallback=(explain == "NOT_ON_GPU"))
            if txt:
                print(txt)
        ansi = conf.ansi_enabled
        root = TpuOverrides._convert(meta, ansi)
        meta.stage_decisions = []
        if isinstance(root, TpuExec):
            from spark_rapids_tpu.overrides.transitions import (
                stage_decisions,
            )

            root = TpuTransitionOverrides.apply(root, conf)
            # transition-stage explain parity (VERDICT r4 Next #8): the
            # collective/fused stages report install/fallback like execs
            meta.stage_decisions = stage_decisions()
            if explain in ("NOT_ON_GPU", "ALL"):
                for name, installed, reason in meta.stage_decisions:
                    if installed and explain == "ALL":
                        print(f"  *stage* {name} will install")
                    elif not installed:
                        print(f"  !stage! {name} cannot install because "
                              f"{reason}")
        return root, meta

    @staticmethod
    def _apply_cost_optimizer(meta: SparkPlanMeta, conf: TpuConf):
        """CostBasedOptimizer analog (SURVEY.md §2.2, default OFF like the
        reference): keeps a plan on CPU when the device round-trip cannot
        pay for itself — the transition cost (2 transfers + compile) of a
        tiny input exceeds any kernel win."""
        from spark_rapids_tpu.config import (
            OPTIMIZER_ENABLED,
            OPTIMIZER_SMALL_PLAN_BYTES,
        )

        if not conf.get(OPTIMIZER_ENABLED) or not meta.can_this_run:
            return
        from spark_rapids_tpu.session import _estimated_plan_bytes

        threshold = conf.get(OPTIMIZER_SMALL_PLAN_BYTES)
        size = _estimated_plan_bytes(meta.plan)
        if size is not None and size < threshold:
            meta.will_not_work_on_tpu(
                f"not worth accelerating (cost-based optimizer: input "
                f"~{size}B below spark.rapids.sql.optimizer."
                f"smallPlanBytes={threshold})")

    @staticmethod
    def _compile_udfs(plan: PN.SparkPlan, conf: TpuConf):
        """udf-compiler pass (the reference's logical-rule analog): trace
        plain-python UDFs in Project/Filter into expression trees so they
        fuse into the compiled stage; untranslatable UDFs keep arrow-eval.

        Runs pre-tagging; differential tests still compare against the
        oracle executing the ORIGINAL python function."""
        from spark_rapids_tpu.expr.cast import Cast
        from spark_rapids_tpu.expr.udf import (
            UserDefinedExpression,
            supports_columnar,
        )
        from spark_rapids_tpu.udf_compiler import try_compile

        from spark_rapids_tpu.config import UDF_COMPILER_ENABLED

        if not conf.get(UDF_COMPILER_ENABLED):
            return

        def make_sub(schema):
            def sub(e):
                if isinstance(e, UserDefinedExpression) \
                        and not supports_columnar(e.fn):
                    compiled = try_compile(e.fn, e.children)
                    if compiled is not None:
                        try:
                            out = Cast(compiled, e.dataType)
                            out.resolve(schema)
                            return out
                        except Exception:
                            return e
                return e

            return sub

        import copy

        def has_plain_udf(x):
            return bool(x.collect(
                lambda y: isinstance(y, UserDefinedExpression)
                and not supports_columnar(y.fn)))

        for node in _walk_plan(plan):
            # substitution works on DEEP COPIES: the logical plan is the
            # user's object and re-plans with the compiler (or the rewrite)
            # disabled must still see the original python UDF
            if isinstance(node, PN.Project):
                sub = make_sub(node.child.output)
                node.exprs = [
                    copy.deepcopy(x).transform_up(sub)
                    if has_plain_udf(x) else x for x in node.exprs]
            elif isinstance(node, PN.Filter):
                if has_plain_udf(node.condition):
                    sub = make_sub(node.child.output)
                    node.condition = copy.deepcopy(
                        node.condition).transform_up(sub)

    @staticmethod
    def _convert(meta: SparkPlanMeta, ansi: bool):
        from spark_rapids_tpu.exec.base import TpuExec
        from spark_rapids_tpu.exec.transitions import TpuRowToColumnarExec

        converted = [TpuOverrides._convert(m, ansi) for m in meta.child_metas]
        if meta.can_this_run:
            tpu_children = []
            for cc, cm in zip(converted, meta.child_metas):
                if isinstance(cc, TpuExec):
                    tpu_children.append(cc)
                else:
                    # CPU child under a TPU parent: row->columnar transition
                    tpu_children.append(TpuRowToColumnarExec(cc, ansi))
            node = _convert_node(meta, tpu_children, ansi)
            # the fault domain's runtime CPU fallback + circuit-breaker
            # keying map an exec back to its plan-node twin
            node._origin_plan = meta.plan
            return node
        # node stays on CPU; TPU children materialize through transitions
        return _rebuild_cpu_plan(meta, converted)
