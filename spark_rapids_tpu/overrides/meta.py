"""RapidsMeta analog — the tagging tree over the physical plan.

Reference analog: com/nvidia/spark/rapids/RapidsMeta.scala (RapidsMeta,
SparkPlanMeta, BaseExprMeta, DataFromReplacementRule): every plan node and
expression is wrapped in a meta object; ``tag_for_tpu`` marks it
TPU-capable or records human-readable reasons via ``will_not_work_on_tpu``;
``convert_to_tpu`` builds the TPU operator.  The accumulated reasons feed
``spark.rapids.sql.explain=NOT_ON_GPU`` -style output — the reference's
signature debuggability feature, reproduced verbatim here.
"""
from __future__ import annotations

from typing import Callable, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.expr.base import Expression


class BaseMeta:
    def __init__(self, conf: TpuConf):
        self.conf = conf
        self.cannot_run_reasons: List[str] = []
        self.child_metas: List[BaseMeta] = []

    def will_not_work_on_tpu(self, reason: str):
        if reason not in self.cannot_run_reasons:
            self.cannot_run_reasons.append(reason)

    @property
    def can_this_run(self) -> bool:
        return not self.cannot_run_reasons

    @property
    def can_run_with_children(self) -> bool:
        return self.can_this_run and all(
            m.can_run_with_children for m in self.child_metas)

    def tag_for_tpu(self):
        raise NotImplementedError


class ExprMeta(BaseMeta):
    """Meta for one expression node (BaseExprMeta analog)."""

    def __init__(self, expr: Expression, conf: TpuConf, rule):
        super().__init__(conf)
        self.expr = expr
        self.rule = rule
        from spark_rapids_tpu.overrides.overrides import wrap_expr

        self.child_metas = [wrap_expr(c, conf) for c in expr.children]

    @property
    def name(self) -> str:
        return type(self.expr).__name__

    def tag_for_tpu(self):
        for m in self.child_metas:
            m.tag_for_tpu()
        if self.rule is None:
            self.will_not_work_on_tpu(
                f"expression {self.name} is not supported on TPU")
            return
        if not self.conf.is_op_enabled(self.name, "expression"):
            self.will_not_work_on_tpu(
                f"expression {self.name} has been disabled by "
                f"spark.rapids.sql.expression.{self.name}=false")
        sig: T.TypeSig = self.rule.type_sig
        dt = self.expr._dataType
        if dt is not None and not sig.supports(dt):
            self.will_not_work_on_tpu(
                f"expression {self.name} produces an unsupported type: "
                + sig.reason_not_supported(dt))
        for c in self.expr.children:
            cdt = c._dataType
            if cdt is not None and not sig.supports(cdt) \
                    and not isinstance(cdt, T.NullType):
                self.will_not_work_on_tpu(
                    f"expression {self.name} input: "
                    + sig.reason_not_supported(cdt))
        # nested-type element constraints (TypeSig recursion is too loose:
        # it would admit array<string> because StringType is in the sig)
        from spark_rapids_tpu.overrides.overrides import (
            unsupported_nested_reason,
        )

        allow_sa = getattr(self.rule, "allow_string_arrays", False)
        allow_se = getattr(self.rule, "allow_struct_entries", False)
        for d in [dt] + [c._dataType for c in self.expr.children]:
            if d is None:
                continue
            reason = unsupported_nested_reason(d, allow_sa, allow_se)
            if reason:
                self.will_not_work_on_tpu(
                    f"expression {self.name}: {reason}")
                break
        if self.rule.extra_check is not None:
            self.rule.extra_check(self)

    def all_reasons(self) -> List[str]:
        out = list(self.cannot_run_reasons)
        for m in self.child_metas:
            out.extend(m.all_reasons())
        return out

    @property
    def can_run_with_children(self) -> bool:
        return self.can_this_run and all(
            m.can_run_with_children for m in self.child_metas)


class SparkPlanMeta(BaseMeta):
    """Meta for one plan node (SparkPlanMeta analog)."""

    def __init__(self, plan, conf: TpuConf, rule):
        super().__init__(conf)
        self.plan = plan
        self.rule = rule
        from spark_rapids_tpu.overrides.overrides import wrap_plan_children

        self.child_metas = wrap_plan_children(plan, conf)
        self.expr_metas: List[ExprMeta] = []

    @property
    def name(self) -> str:
        return type(self.plan).__name__

    def add_expr_metas(self, exprs):
        from spark_rapids_tpu.overrides.overrides import wrap_expr

        for e in exprs:
            if e is not None:
                self.expr_metas.append(wrap_expr(e, self.conf))

    def tag_for_tpu(self):
        for m in self.child_metas:
            m.tag_for_tpu()
        if self.rule is None:
            self.will_not_work_on_tpu(
                f"exec {self.name} is not supported on TPU")
            return
        if not self.conf.is_op_enabled(self.name, "exec"):
            self.will_not_work_on_tpu(
                f"exec {self.name} has been disabled by "
                f"spark.rapids.sql.exec.{self.name}=false")
        # output type check
        sig: T.TypeSig = self.rule.type_sig
        from spark_rapids_tpu.overrides.overrides import (
            unsupported_nested_reason,
        )

        for f in self.plan.output.fields:
            if not sig.supports(f.dataType):
                self.will_not_work_on_tpu(
                    f"exec {self.name} output column '{f.name}': "
                    + sig.reason_not_supported(f.dataType))
            else:
                reason = unsupported_nested_reason(
                    f.dataType,
                    getattr(self.rule, "allow_string_arrays", False))
                if reason:
                    self.will_not_work_on_tpu(
                        f"exec {self.name} output column '{f.name}': "
                        + reason)
        # expression checks
        if self.rule.tag_exprs is not None:
            self.add_expr_metas(self.rule.tag_exprs(self.plan))
        for em in self.expr_metas:
            em.tag_for_tpu()
            if not em.can_run_with_children:
                for r in em.all_reasons():
                    self.will_not_work_on_tpu(r)
        # host-kernel expressions (pure_callback) only run in the eager
        # Project/Filter stage path; every other exec jits its expressions
        # into one XLA program, where no host-callback channel exists
        if self.name not in ("Project", "Filter"):
            from spark_rapids_tpu.expr.base import contains_host_kernel

            for em in self.expr_metas:
                if contains_host_kernel(em.expr):
                    self.will_not_work_on_tpu(
                        f"exec {self.name}: host-kernel expression "
                        f"{em.expr.sql_string()} must sit under a Project")
                    break
        if self.rule.extra_check is not None:
            self.rule.extra_check(self)
        # circuit breaker (resilience/breaker.py): stages that failed
        # deterministically at runtime are routed to the CPU oracle at
        # plan time until their TTL expires (half-open probe re-admits)
        from spark_rapids_tpu.resilience.breaker import consult_plan

        reason = consult_plan(self.plan, self.conf)
        if reason:
            from spark_rapids_tpu import perfcounters as PC

            PC.bump("breaker_plan_fallbacks")
            self.will_not_work_on_tpu(reason)
        # qualification advisory (profiling/advisor.py, ISSUE 8): an
        # operator class the accumulated profile shows as persistently
        # fallback-heavy is routed to its native/CPU placement at plan
        # time — opt-in (off-by-default conf), every other class keeps
        # its default placement; the conf gate keeps the disabled path
        # free of profiling-module calls
        from spark_rapids_tpu.config import PROFILE_ADVISOR_ENABLED

        if self.conf.get(PROFILE_ADVISOR_ENABLED):
            from spark_rapids_tpu.profiling.advisor import (
                consult_plan_advisor,
            )

            reason = consult_plan_advisor(self.plan, self.conf)
            if reason:
                from spark_rapids_tpu import perfcounters as PC

                PC.bump("advisor_plan_fallbacks")
                self.will_not_work_on_tpu(reason)

    # ------------------------------------------------------------------
    def explain(self, indent: int = 0, only_fallback: bool = True) -> str:
        lines = []
        pad = "  " * indent
        if self.can_this_run:
            if not only_fallback:
                lines.append(f"{pad}*{self.name} will run on TPU")
        else:
            reasons = "; ".join(self.cannot_run_reasons)
            lines.append(f"{pad}!{self.name} cannot run on TPU because "
                         f"{reasons}")
        for m in self.child_metas:
            sub = m.explain(indent + 1, only_fallback)
            if sub:
                lines.append(sub)
        return "\n".join(l for l in lines if l)
