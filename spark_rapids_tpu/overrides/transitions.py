"""TpuTransitionOverrides — post-conversion plan fixups.

Reference analog: com/nvidia/spark/rapids/GpuTransitionOverrides.scala:
inserts transitions at CPU<->GPU boundaries, adds GpuCoalesceBatches /
GpuShuffleCoalesceExec after shuffles, and validates the final plan.  Here
the boundary transitions are inserted during conversion (overrides.py); this
pass adds:

  * TpuCoalesceBatchesExec after every shuffle exchange (the
    GpuShuffleCoalesceExec role: concat per-partition slices to the goal
    size — and on TPU, re-bucket shapes to bound recompiles);
  * Sort+Limit -> TpuTopNExec rewrite (GpuTopN);
  * whole-stage fusion of adjacent project/filter stages (TPU-specific).
"""
from __future__ import annotations

from typing import List

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import BATCH_SIZE_BYTES, TPU_WHOLESTAGE_FUSION, TpuConf
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.exec.basic import TpuStageExec, fuse_stages
from spark_rapids_tpu.exec.coalesce import CoalesceGoal, TpuCoalesceBatchesExec
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.exec.limit import TpuGlobalLimitExec, TpuLocalLimitExec
from spark_rapids_tpu.exec.sort import TpuSortExec, TpuTopNExec
from spark_rapids_tpu.plan.nodes import SparkPlan


class TpuMaterializedScan(SparkPlan):
    """CPU plan node backed by a TPU subtree: the columnar->row boundary.

    Reference analog: GpuColumnarToRowExec feeding a CPU operator."""

    def __init__(self, tpu_child: TpuExec):
        super().__init__([])
        self.tpu_child = tpu_child

    @property
    def output(self):
        return self.tpu_child.output

    def describe(self):
        return f"ColumnarToRow <- {self.tpu_child.describe()}"

    def materialize_cpu(self):
        from spark_rapids_tpu.cpu.oracle import CpuCol
        from spark_rapids_tpu.exec.transitions import TpuColumnarToRowExec

        c2r = TpuColumnarToRowExec(self.tpu_child)
        host = c2r.collect_host()
        cols = [CpuCol.from_host(h) for h in host]
        n = cols[0].n if cols else 0
        return cols, n


def _mesh_stage_on(conf: TpuConf, switch) -> bool:
    """The shared 4-condition guard of every ICI stage rewrite: mesh mode
    on, the per-stage kill switch on, shuffle mode ICI, >1 device."""
    return _mesh_stage_reason(conf, switch) is None


def _mesh_stage_reason(conf: TpuConf, switch):
    """None when the mesh stage may install; otherwise the fallback reason
    (which of the 4 guard conditions failed), for explain parity."""
    import jax

    from spark_rapids_tpu.config import MESH_ENABLED, SHUFFLE_MODE

    if not conf.get(MESH_ENABLED):
        return f"{MESH_ENABLED.key} is false"
    if not conf.get(switch):
        return f"{switch.key} is false"
    if str(conf.get(SHUFFLE_MODE)).upper() != "ICI":
        return (f"{SHUFFLE_MODE.key}={conf.get(SHUFFLE_MODE)} "
                "(mesh stages need ICI)")
    if len(jax.devices()) <= 1:
        return "single device (no mesh to distribute over)"
    return None


# ---------------------------------------------------------------------------
# Stage rules: the taggable registry of transition-installed execs
# (VERDICT r4 Next #8).  The reference registers every exec in
# GpuOverrides.execs with per-exec explain/fallback; the collective (ICI)
# and fused stages here are installed by plan REWRITE rather than node
# conversion, so they get their own registry + per-apply decision ledger
# that the explain output and docs generator read.
# ---------------------------------------------------------------------------

import dataclasses as _dc
import threading as _threading


@_dc.dataclass(frozen=True)
class StageRule:
    name: str           # installed exec class name
    conf_key: str       # kill-switch conf
    desc: str           # what the stage collapses / replaces


def _stage_rules():
    from spark_rapids_tpu import config as C

    return {r.name: r for r in [
        StageRule("TpuIciShuffleAggExec", C.MESH_AGG_ENABLED.key,
                  "Final<-Exchange<-Partial aggregate as one SPMD "
                  "collective program (all-to-all over ICI)"),
        StageRule("TpuIciShuffleJoinExec", C.MESH_JOIN_ENABLED.key,
                  "shuffled equi-join as mesh all-to-all both sides + "
                  "per-device sorted probe"),
        StageRule("TpuIciSortExec", C.MESH_SORT_ENABLED.key,
                  "global sort as sampled range exchange + per-device "
                  "sort + ordered emit"),
        StageRule("TpuIciWindowExec", C.MESH_WINDOW_ENABLED.key,
                  "partitioned window as hash all-to-all on PARTITION BY "
                  "+ per-device window"),
        StageRule("TpuIciRepartitionExec", C.MESH_REPARTITION_ENABLED.key,
                  "remaining hash/round-robin exchanges as the generic "
                  "mesh all-to-all"),
        StageRule("TpuJoinAggFusedExec", C.JOIN_AGG_FUSION.key,
                  "aggregate over unconditioned INNER/LEFT broadcast "
                  "equi-join fused into one program"),
        StageRule("TpuWindowChainFusedExec", C.WINDOW_CHAIN_FUSION.key,
                  "window over complete-agg (and trailing stage ops) "
                  "fused into one program"),
        StageRule("TpuAdaptiveShuffleReaderExec",
                  C.ADAPTIVE_ENABLED.key,
                  "stats-driven shuffle-read partition coalescing "
                  "(GpuCustomShuffleReaderExec analog)"),
        StageRule("TpuFusedPipelineExec", C.FUSION_ENABLED.key,
                  "maximal pipeline-able operator chains (stage/expand) "
                  "compiled as ONE jitted program, split at predicted-"
                  "oversized HBM boundaries (manifest ∩ cost model)"),
    ]}


STAGE_RULES = None      # populated lazily (config import cycle)


def stage_rules():
    global STAGE_RULES
    if STAGE_RULES is None:
        STAGE_RULES = _stage_rules()
    return STAGE_RULES


_STAGE_LOG = _threading.local()


def _stage_log_reset() -> None:
    _STAGE_LOG.entries = []


def stage_decisions():
    """[(exec_name, installed: bool, reason: Optional[str])] for the most
    recent TpuTransitionOverrides.apply on this thread."""
    return list(getattr(_STAGE_LOG, "entries", []))


def _record(name: str, installed: bool, reason=None) -> None:
    entries = getattr(_STAGE_LOG, "entries", None)
    if entries is not None:
        entries.append((name, installed, reason))


class TpuTransitionOverrides:
    @staticmethod
    def apply(root: TpuExec, conf: TpuConf) -> TpuExec:
        from spark_rapids_tpu.exec.partition_sizing import (
            size_exchange_partitions,
        )

        _stage_log_reset()
        # size-aware partition counts FIRST (ISSUE 10): exchanges whose
        # estimated input exceeds the per-partition pool budget grow
        # their counts and become exempt from the single-device collapse
        # (out-of-core schedule, not parallelism)
        root = size_exchange_partitions(root, conf)
        root = TpuTransitionOverrides._coalesce_single_device_shuffle(
            root, conf)
        root = TpuTransitionOverrides._insert_coalesce(root, conf)
        root = TpuTransitionOverrides._collapse_complete_agg(root, conf)
        root = TpuTransitionOverrides._rewrite_topn(root)
        if conf.get(TPU_WHOLESTAGE_FUSION):
            root = fuse_stages(root)
        # after stage fusion so Agg(Stage(Join)) has become Agg(Join) with
        # the stage ops absorbed as the aggregate's pre_ops
        root = TpuTransitionOverrides._fuse_join_agg(root, conf)
        root = TpuTransitionOverrides._fuse_window_chain(root, conf)
        # whole-plan pipeline fusion (ISSUE 17) after the specialized
        # join-agg / window-chain fusions so they keep first claim on
        # their patterns; remaining stage/expand chains compile into one
        # program each, split at predicted-oversized HBM boundaries
        from spark_rapids_tpu.exec.fusion import fuse_pipelines

        root = fuse_pipelines(root, conf)
        root = TpuTransitionOverrides._rewrite_ici_agg(root, conf)
        root = TpuTransitionOverrides._rewrite_ici_join(root, conf)
        root = TpuTransitionOverrides._rewrite_ici_sort(root, conf)
        root = TpuTransitionOverrides._rewrite_ici_window(root, conf)
        root = TpuTransitionOverrides._rewrite_ici_repartition(root, conf)
        return root

    @staticmethod
    def _collapse_complete_agg(node: TpuExec, conf: TpuConf) -> TpuExec:
        """Single-device exchange elision for two-phase aggregates:
        Final <- [Coalesce] <- Exchange <- Partial  =>  Complete.

        The exchange exists to co-locate keys across devices; with one
        device (or the mesh path disabled) it only adds program launches.
        The COMPLETE aggregate runs ONE fused XLA program for a
        single-batch input and falls back to the exact two-phase pipeline
        (buffer-form merges) for multi-batch — see
        TpuHashAggregateExec._execute_complete.  Reference analog: AQE's
        single-partition shuffle elision (SURVEY.md §2.2)."""
        import jax

        from spark_rapids_tpu.config import (
            COMPLETE_AGG_COLLAPSE,
            MESH_AGG_ENABLED,
            MESH_ENABLED,
            SHUFFLE_MODE,
        )
        from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
        from spark_rapids_tpu.plan.nodes import AggregateMode

        node.children = [
            TpuTransitionOverrides._collapse_complete_agg(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        if not conf.get(COMPLETE_AGG_COLLAPSE):
            return node
        if _mesh_stage_on(conf, MESH_AGG_ENABLED):
            return node  # the ICI collective rewrite owns this pattern
        if not (isinstance(node, TpuHashAggregateExec)
                and node.mode == AggregateMode.FINAL):
            return node
        from spark_rapids_tpu.exec.exchange import (
            TpuAdaptiveShuffleReaderExec,
        )

        mid = node.children[0]
        if isinstance(mid, (TpuCoalesceBatchesExec,
                            TpuAdaptiveShuffleReaderExec)):
            mid = mid.children[0]
        if not isinstance(mid, TpuShuffleExchangeExec):
            return node
        partial = mid.children[0]
        if not (isinstance(partial, TpuHashAggregateExec)
                and partial.mode == AggregateMode.PARTIAL):
            return node
        comp = TpuHashAggregateExec(
            partial.grouping, partial.aggregates, AggregateMode.COMPLETE,
            partial.children[0], partial.child_schema, node.output,
            node.ansi)
        comp.pre_ops = partial.pre_ops
        comp.input_schema = partial.input_schema
        return comp

    @staticmethod
    def _fuse_join_agg(node: TpuExec, conf: TpuConf) -> TpuExec:
        """Aggregate directly above an unconditioned INNER/LEFT equi-join
        fuses into TpuJoinAggFusedExec (exec/fused.py)."""
        from spark_rapids_tpu.config import JOIN_AGG_FUSION
        from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
        from spark_rapids_tpu.exec.fused import TpuJoinAggFusedExec
        from spark_rapids_tpu.exec.join import TpuBroadcastHashJoinExec
        from spark_rapids_tpu.plan.nodes import AggregateMode, JoinType

        node.children = [
            TpuTransitionOverrides._fuse_join_agg(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        if not (isinstance(node, TpuHashAggregateExec)
                and node.mode in (AggregateMode.COMPLETE,
                                  AggregateMode.PARTIAL)
                and not node._has_collect):
            return node
        join = node.children[0]
        if not (isinstance(join, TpuBroadcastHashJoinExec)
                and join.condition is None
                and join.join_type in (JoinType.INNER, JoinType.LEFT_OUTER)
                and join.left_keys):
            return node
        if not conf.get(JOIN_AGG_FUSION):
            _record("TpuJoinAggFusedExec", False,
                    f"{JOIN_AGG_FUSION.key} is false")
            return node
        _record("TpuJoinAggFusedExec", True)
        # the agg keeps the join as its child (used by the oversized-build
        # fallback); the fused exec replaces it in the surrounding tree
        return TpuJoinAggFusedExec(node, join)

    @staticmethod
    def _fuse_window_chain(node: TpuExec, conf: TpuConf) -> TpuExec:
        """[Stage(]Window([CompleteAgg(x)])[)] -> TpuWindowChainFusedExec.

        Non-ANSI only (the fused program carries no error-flag channel);
        ANSI chains keep their per-operator programs."""
        from spark_rapids_tpu.config import WINDOW_CHAIN_FUSION
        from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
        from spark_rapids_tpu.exec.basic import TpuStageExec
        from spark_rapids_tpu.exec.fused import TpuWindowChainFusedExec
        from spark_rapids_tpu.exec.window import TpuWindowExec
        from spark_rapids_tpu.plan.nodes import AggregateMode

        from spark_rapids_tpu.config import MESH_WINDOW_ENABLED

        mesh_claims = _mesh_stage_on(conf, MESH_WINDOW_ENABLED)
        # match TOP-DOWN so the longest chain (stage+window+agg) wins over
        # the inner window+agg pair, then recurse into the result
        post_ops, post_schema = None, None
        window = node
        if isinstance(node, TpuStageExec) and not node.ansi \
                and not node._has_host_kernels() \
                and isinstance(node.children[0], TpuWindowExec):
            window = node.children[0]
            post_ops, post_schema = node.ops, node.output
        if (isinstance(window, TpuWindowExec) and not window.ansi
                # partitioned windows belong to the ICI window rewrite
                # in mesh mode; partition-less ones still fuse
                and not (mesh_claims and window.partition_by)):
            pre_agg = None
            child = window.children[0]
            if (isinstance(child, TpuHashAggregateExec)
                    and child.mode == AggregateMode.COMPLETE
                    and not child._has_collect and not child.ansi):
                pre_agg = child
            if pre_agg is not None or post_ops is not None:
                if conf.get(WINDOW_CHAIN_FUSION):
                    _record("TpuWindowChainFusedExec", True)
                    node = TpuWindowChainFusedExec(window, pre_agg,
                                                   post_ops, post_schema)
                else:
                    _record("TpuWindowChainFusedExec", False,
                            f"{WINDOW_CHAIN_FUSION.key} is false")
        node.children = [
            TpuTransitionOverrides._fuse_window_chain(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        return node

    @staticmethod
    def _rewrite_ici_sort(node: TpuExec, conf: TpuConf) -> TpuExec:
        """ICI mesh mode: a global TpuSortExec becomes the distributed
        range-exchange sort (sampled global splitters + all-to-all +
        per-device sort + ordered emit — exec/ici.TpuIciSortExec)."""
        import jax

        from spark_rapids_tpu.config import (MESH_ENABLED, MESH_EPOCH_BYTES,
                                             SHUFFLE_MODE)
        from spark_rapids_tpu.exec.ici import TpuIciSortExec

        from spark_rapids_tpu.config import MESH_SORT_ENABLED

        node.children = [
            TpuTransitionOverrides._rewrite_ici_sort(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        if not (isinstance(node, TpuSortExec) and node.is_global):
            return node
        reason = _mesh_stage_reason(conf, MESH_SORT_ENABLED)
        if reason is not None:
            _record("TpuIciSortExec", False, reason)
            return node
        from spark_rapids_tpu.config import MESH_DEVICES as _MD
        from spark_rapids_tpu.parallel.mesh import make_mesh

        _record("TpuIciSortExec", True)
        return TpuIciSortExec(node, make_mesh(conf.get(_MD) or None),
                              epoch_bytes=conf.get(MESH_EPOCH_BYTES))

    @staticmethod
    def _rewrite_ici_agg(node: TpuExec, conf: TpuConf) -> TpuExec:
        """ICI mesh mode: collapse Final<-[Coalesce]<-Exchange<-Partial into
        one SPMD collective program (exec/ici.py).

        Runs after fuse_stages so the partial aggregate already carries its
        fused scan-side filter/project ops into the per-device program."""
        import jax

        from spark_rapids_tpu.config import (MESH_AGG_ENABLED,
                                             MESH_ENABLED, SHUFFLE_MODE)
        from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
        from spark_rapids_tpu.exec.ici import TpuIciShuffleAggExec
        from spark_rapids_tpu.plan.nodes import AggregateMode

        node.children = [
            TpuTransitionOverrides._rewrite_ici_agg(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        if not (isinstance(node, TpuHashAggregateExec)
                and node.mode == AggregateMode.FINAL):
            return node
        from spark_rapids_tpu.exec.exchange import (
            TpuAdaptiveShuffleReaderExec,
        )

        mid = node.children[0]
        if isinstance(mid, (TpuCoalesceBatchesExec,
                            TpuAdaptiveShuffleReaderExec)):
            mid = mid.children[0]
        if not isinstance(mid, TpuShuffleExchangeExec):
            return node
        partial = mid.children[0]
        if not (isinstance(partial, TpuHashAggregateExec)
                and partial.mode == AggregateMode.PARTIAL):
            return node
        reason = _mesh_stage_reason(conf, MESH_AGG_ENABLED)
        if reason is not None:
            _record("TpuIciShuffleAggExec", False, reason)
            return node
        from spark_rapids_tpu.config import MESH_DEVICES, MESH_EPOCH_BYTES
        from spark_rapids_tpu.parallel.mesh import make_mesh

        _record("TpuIciShuffleAggExec", True)
        return TpuIciShuffleAggExec(
            partial, node, make_mesh(conf.get(MESH_DEVICES) or None),
            epoch_bytes=conf.get(MESH_EPOCH_BYTES))

    @staticmethod
    def _rewrite_ici_join(node: TpuExec, conf: TpuConf) -> TpuExec:
        """ICI mesh mode: Join <- (Exchange, Exchange) becomes one pair of
        SPMD programs — all-to-all both sides over ICI, local sorted-probe
        join per device (exec/ici.TpuIciShuffleJoinExec)."""
        import jax

        from spark_rapids_tpu.config import MESH_ENABLED, SHUFFLE_MODE
        from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
        from spark_rapids_tpu.exec.ici import TpuIciShuffleJoinExec
        from spark_rapids_tpu.exec.join import (
            TpuAdaptiveJoinExec,
            TpuShuffledSymmetricHashJoinExec,
        )
        from spark_rapids_tpu.plan.nodes import JoinType

        from spark_rapids_tpu.config import MESH_JOIN_ENABLED

        node.children = [
            TpuTransitionOverrides._rewrite_ici_join(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        join = node
        if isinstance(join, TpuAdaptiveJoinExec):
            # the collective plan replaces the AQE wrapper: a mesh
            # all-to-all already is the "shuffle" it would avoid
            join = join.shuffled
        if not isinstance(join, TpuShuffledSymmetricHashJoinExec):
            return node
        reason = _mesh_stage_reason(conf, MESH_JOIN_ENABLED)
        if reason is None and join.join_type not in (
                JoinType.INNER, JoinType.LEFT_OUTER, JoinType.LEFT_SEMI,
                JoinType.LEFT_ANTI, JoinType.RIGHT_OUTER,
                JoinType.FULL_OUTER):
            reason = (f"join type {join.join_type.value} has no mesh "
                      "materialization")
        if reason is None and join.condition is not None \
                and join.join_type != JoinType.INNER:
            # non-inner residual conditions are tag-time fallbacks anyway
            reason = ("residual join condition is only supported for "
                      "INNER mesh joins")
        if reason is None and not all(
                isinstance(c, TpuShuffleExchangeExec)
                for c in join.children):
            reason = "join inputs are not both shuffle exchanges"
        if reason is not None:
            _record("TpuIciShuffleJoinExec", False, reason)
            return node
        from spark_rapids_tpu.config import MESH_DEVICES
        from spark_rapids_tpu.parallel.mesh import make_mesh

        from spark_rapids_tpu.config import MESH_EPOCH_BYTES as _MEB

        _record("TpuIciShuffleJoinExec", True)
        return TpuIciShuffleJoinExec(
            join, join.children[0].children[0],
            join.children[1].children[0],
            make_mesh(conf.get(MESH_DEVICES) or None),
            epoch_bytes=conf.get(_MEB))

    @staticmethod
    def _rewrite_ici_window(node: TpuExec, conf: TpuConf) -> TpuExec:
        """ICI mesh mode: a partitioned TpuWindowExec becomes the
        distributed mesh window (hash all-to-all on PARTITION BY +
        single-chip window per device — exec/ici.TpuIciWindowExec).
        Partition-less windows keep the single-chip exec (a global window
        is one ordered scan; there is nothing to co-locate)."""
        from spark_rapids_tpu.config import (MESH_DEVICES,
                                             MESH_EPOCH_BYTES,
                                             MESH_WINDOW_ENABLED)
        from spark_rapids_tpu.exec.ici import (
            TpuIciWindowExec,
            mesh_exchange_schema_supported,
        )
        from spark_rapids_tpu.exec.window import TpuWindowExec

        node.children = [
            TpuTransitionOverrides._rewrite_ici_window(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        if not (isinstance(node, TpuWindowExec) and node.partition_by):
            return node
        reason = _mesh_stage_reason(conf, MESH_WINDOW_ENABLED)
        if reason is None and not mesh_exchange_schema_supported(
                node.children[0].output):
            reason = ("input schema has nested/unsupported columns for "
                      "the mesh exchange")
        if reason is not None:
            _record("TpuIciWindowExec", False, reason)
            return node
        from spark_rapids_tpu.parallel.mesh import make_mesh

        _record("TpuIciWindowExec", True)
        return TpuIciWindowExec(
            node, make_mesh(conf.get(MESH_DEVICES) or None),
            epoch_bytes=conf.get(MESH_EPOCH_BYTES))

    @staticmethod
    def _rewrite_ici_repartition(node: TpuExec, conf: TpuConf) -> TpuExec:
        """ICI mesh mode, LAST of the mesh rewrites: any remaining hash /
        round-robin shuffle exchange (not claimed by the agg/join/sort/
        window stages above) lowers to the generic mesh all-to-all
        repartition (exec/ici.TpuIciRepartitionExec)."""
        from spark_rapids_tpu.config import (MESH_DEVICES,
                                             MESH_EPOCH_BYTES,
                                             MESH_REPARTITION_ENABLED)
        from spark_rapids_tpu.exec.ici import (
            TpuIciRepartitionExec,
            mesh_exchange_schema_supported,
        )
        from spark_rapids_tpu.plan.nodes import (HashPartitioning,
                                                 RoundRobinPartitioning)

        node.children = [
            TpuTransitionOverrides._rewrite_ici_repartition(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        if not (isinstance(node, TpuShuffleExchangeExec)
                and isinstance(node.partitioning,
                               (HashPartitioning, RoundRobinPartitioning))):
            return node
        reason = _mesh_stage_reason(conf, MESH_REPARTITION_ENABLED)
        if reason is None and not mesh_exchange_schema_supported(
                node.output):
            reason = ("output schema has nested/unsupported columns for "
                      "the mesh exchange")
        if reason is not None:
            _record("TpuIciRepartitionExec", False, reason)
            return node
        from spark_rapids_tpu.config import ICI_CROSS_SLICE_HOSTS
        from spark_rapids_tpu.parallel.mesh import make_mesh

        _record("TpuIciRepartitionExec", True)
        return TpuIciRepartitionExec(
            node, make_mesh(conf.get(MESH_DEVICES) or None),
            epoch_bytes=conf.get(MESH_EPOCH_BYTES),
            cross_hosts=conf.get(ICI_CROSS_SLICE_HOSTS))

    @staticmethod
    def _coalesce_single_device_shuffle(node: TpuExec,
                                        conf: TpuConf) -> TpuExec:
        """AQE-style shuffle partition coalescing for one device: hash/
        round-robin exchanges repartition for parallelism that a single
        chip does not have, and every extra partition costs a program
        launch (and, on a compile-tunnel platform, potentially a compile).
        Collapse them to a single partition; results are unchanged
        (aggs/joins are partition-count independent)."""
        import jax

        from spark_rapids_tpu.config import SINGLE_DEVICE_SHUFFLE_COALESCE
        from spark_rapids_tpu.plan.nodes import (HashPartitioning,
                                                 RoundRobinPartitioning,
                                                 SinglePartitioning)

        node.children = [
            TpuTransitionOverrides._coalesce_single_device_shuffle(c, conf)
            if isinstance(c, TpuExec) else c for c in node.children]
        if not conf.get(SINGLE_DEVICE_SHUFFLE_COALESCE):
            return node
        if len(jax.devices()) > 1:
            return node
        from spark_rapids_tpu.config import DISTRIBUTED_ENABLED

        if isinstance(node, TpuShuffleExchangeExec) and isinstance(
                node.partitioning,
                (HashPartitioning, RoundRobinPartitioning)) \
                and not getattr(node, "_ooc_sized", False) \
                and not conf.get(DISTRIBUTED_ENABLED):
            # sized exchanges keep their partitions: on one chip they
            # are the out-of-core schedule, not elidable parallelism.
            # Distributed exchanges (ISSUE 14) keep them too: reduce
            # partitions are the unit of cross-host placement — with
            # one local chip and N remote workers, collapsing would
            # collapse the cluster to one worker
            node.partitioning = SinglePartitioning()
        return node

    @staticmethod
    def _insert_coalesce(node: TpuExec, conf: TpuConf) -> TpuExec:
        from spark_rapids_tpu.config import (
            ADAPTIVE_ENABLED,
            EXCHANGE_COALESCE_SMALL_BYTES,
        )
        from spark_rapids_tpu.exec.exchange import (
            TpuAdaptiveShuffleReaderExec,
        )

        node.children = [
            TpuTransitionOverrides._insert_coalesce(c, conf)
            if isinstance(c, TpuExec) else c
            for c in node.children]
        # overload governor (ISSUE 13): plan-time batch-size goals
        # shrink under YELLOW/RED so newly planned queries start with
        # smaller working sets (one ambient check when disabled)
        from spark_rapids_tpu.governor import context as _GOV

        _gov = _GOV.GOVERNOR
        goal_bytes = conf.get(BATCH_SIZE_BYTES)
        if _gov is not None:
            goal_bytes = _gov.degraded_goal(goal_bytes)
        new_children = []
        for c in node.children:
            if isinstance(c, TpuShuffleExchangeExec):
                if conf.get(ADAPTIVE_ENABLED):
                    # general AQE: the reader RECORDS per-partition
                    # rows/bytes and coalesces on the measured stats
                    # (GpuCustomShuffleReaderExec analog)
                    _record("TpuAdaptiveShuffleReaderExec", True)
                    new_children.append(TpuAdaptiveShuffleReaderExec(
                        c, goal_bytes,
                        small_bytes=conf.get(
                            EXCHANGE_COALESCE_SMALL_BYTES)))
                else:
                    _record("TpuAdaptiveShuffleReaderExec", False,
                            f"{ADAPTIVE_ENABLED.key} is false")
                    goal = CoalesceGoal(goal_bytes)
                    new_children.append(TpuCoalesceBatchesExec(goal, c))
            else:
                new_children.append(c)
        node.children = new_children
        return node

    @staticmethod
    def _rewrite_topn(node: TpuExec) -> TpuExec:
        node.children = [TpuTransitionOverrides._rewrite_topn(c)
                         if isinstance(c, TpuExec) else c
                         for c in node.children]
        if isinstance(node, (TpuGlobalLimitExec, TpuLocalLimitExec)):
            child = node.children[0]
            # Limit(Sort) or Limit(Coalesce(Exchange(Sort)))
            if isinstance(child, TpuSortExec):
                return TpuTopNExec(node.n, child.orders, child.children[0],
                                   child.ansi)
        return node
