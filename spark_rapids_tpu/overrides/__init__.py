from spark_rapids_tpu.overrides.overrides import TpuOverrides  # noqa: F401
