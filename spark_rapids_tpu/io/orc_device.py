"""Device-side ORC column assembly (GpuOrcScan's device half).

Walks stripes via io/orc_native.py, slices each column's PRESENT/DATA
streams, expands RLEv2 runs on device (Pallas bit-unpack for DIRECT
payloads), and scatters present values back to row positions — the same
assembly shape as the parquet device reader."""
from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    DEFAULT_ROW_BUCKETS,
    DeviceColumn,
    round_up_bucket,
)
from spark_rapids_tpu.io.orc_native import (
    K_DATE,
    K_DOUBLE,
    K_FLOAT,
    K_INT,
    K_LONG,
    K_SHORT,
    S_DATA,
    S_PRESENT,
    _decompress_stream,
    _pb_fields,
    _one,
    expand_present,
    expand_rlev2,
    read_orc_meta,
    split_rlev2_runs,
)
from spark_rapids_tpu.io.parquet_native import _Unsupported

_INT_KINDS = {K_SHORT, K_INT, K_LONG, K_DATE}
_FLOAT_KINDS = {K_FLOAT: np.float32, K_DOUBLE: np.float64}

_OK = {
    K_SHORT: (T.ShortType, T.IntegerType, T.LongType),
    K_INT: (T.IntegerType, T.LongType),
    K_LONG: (T.LongType,),
    K_DATE: (T.DateType,),
    K_FLOAT: (T.FloatType,),
    K_DOUBLE: (T.DoubleType,),
}


def read_orc_device(path: str, schema: T.StructType,
                    row_buckets=DEFAULT_ROW_BUCKETS) -> ColumnarBatch:
    """Escaping errors carry ``file=<path>`` context (io/faults.py)."""
    from spark_rapids_tpu.io.faults import file_context

    with file_context(path, "orc", "device"):
        return _read_orc_device(path, schema, row_buckets)


def _read_orc_device(path: str, schema: T.StructType,
                     row_buckets=DEFAULT_ROW_BUCKETS) -> ColumnarBatch:
    with open(path, "rb") as f:
        data = f.read()
    cols_meta, stripes, compression, total = read_orc_meta(data)
    by_name = {c.name: c for c in cols_meta}
    for f_ in schema.fields:
        c = by_name.get(f_.name)
        if c is None:
            raise _Unsupported(f"orc column {f_.name} missing")
        ok = _OK.get(c.kind)
        if ok is None or not isinstance(f_.dataType, ok):
            raise _Unsupported(
                f"orc column {f_.name}: kind {c.kind} as "
                f"{f_.dataType.simpleString}")
    cap = round_up_bucket(max(total, 1), row_buckets)
    per_field_vals: List[List] = [[] for _ in schema.fields]
    per_field_valid: List[List] = [[] for _ in schema.fields]
    for st in stripes:
        sf_raw = data[st.offset + st.index_len + st.data_len:
                      st.offset + st.index_len + st.data_len
                      + st.footer_len]
        sf = _pb_fields(_decompress_stream(sf_raw, compression))
        streams = [_pb_fields(s) for s in sf.get(1, [])]
        encodings = [_pb_fields(e) for e in sf.get(2, [])]
        # stream byte ranges: consecutive from the stripe start
        pos = st.offset
        located = []  # (kind, column, start, length)
        for s in streams:
            kind = _one(s, 1, 0)
            col = _one(s, 2, 0)
            ln = _one(s, 3, 0)
            located.append((kind, col, pos, ln))
            pos += ln
        for fi, f_ in enumerate(schema.fields):
            cm = by_name[f_.name]
            enc = _one(encodings[cm.col_id], 1, 0) \
                if cm.col_id < len(encodings) else 0
            present = None
            vbuf = None
            for kind, col, start, ln in located:
                if col != cm.col_id:
                    continue
                if kind == S_PRESENT:
                    present = _decompress_stream(data[start:start + ln],
                                                 compression)
                elif kind == S_DATA:
                    vbuf = _decompress_stream(data[start:start + ln],
                                              compression)
            if vbuf is None:
                raise _Unsupported(f"orc column {f_.name}: no DATA stream")
            if present is not None:
                defined_np = expand_present(present, st.num_rows)
                ndef = int(defined_np.sum())
            else:
                defined_np = np.ones(st.num_rows, np.bool_)
                ndef = st.num_rows
            defined = jnp.asarray(defined_np)
            sdt = T.storage_dtype(f_.dataType)
            if cm.kind in _INT_KINDS:
                if enc != 2:  # DIRECT_V2 only
                    raise _Unsupported(f"orc int encoding {enc}")
                runs = split_rlev2_runs(vbuf, signed=True, total=ndef)
                vals = expand_rlev2(runs, signed=True, total=ndef)
            else:
                np_dt = _FLOAT_KINDS[cm.kind]
                vals = jnp.asarray(np.frombuffer(vbuf, np_dt, count=ndef))
            from spark_rapids_tpu.io.parquet_device import scatter_present

            vals = scatter_present(vals.astype(sdt), defined, ndef,
                                   st.num_rows)
            per_field_vals[fi].append(vals)
            per_field_valid[fi].append(defined)
    cols = []
    for fi, f_ in enumerate(schema.fields):
        vals = (jnp.concatenate(per_field_vals[fi])
                if len(per_field_vals[fi]) > 1 else per_field_vals[fi][0])
        valid = (jnp.concatenate(per_field_valid[fi])
                 if len(per_field_valid[fi]) > 1
                 else per_field_valid[fi][0])
        sdt = T.storage_dtype(f_.dataType)
        data_arr = jnp.zeros(cap, sdt).at[:vals.shape[0]].set(vals)
        valid_arr = jnp.zeros(cap, jnp.bool_).at[:valid.shape[0]].set(valid)
        cols.append(DeviceColumn(f_.dataType, valid_arr, data=data_arr))
    return ColumnarBatch(cols, total, schema)
