"""Merge-on-read assembly: parquet files minus deleted row indices.

Shared by the Iceberg position-delete reader and the Delta deletion-vector
reader (the reference applies these inside its GPU parquet readers; here
per-file row positions do not survive the concatenating scan, so the take
happens while building the batch).

I/O fault domain (ISSUE 5): each data file reads under the same per-file
classify/tolerate path as the plain scan — a corrupt or vanished data
file listed by a stale manifest/log skips (with counters + quarantine)
when the ignoreCorruptFiles/ignoreMissingFiles confs allow, and otherwise
raises a file-attributed fault instead of an anonymous pyarrow error."""
from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple


def read_parquet_minus_rows(session, files, schema):
    """files: [(path, deleted_row_indices_or_None)] -> DataFrame."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.columnar.column import HostColumn
    from spark_rapids_tpu.io import faults as IOF
    from spark_rapids_tpu.io.scan import read_parquet_file
    from spark_rapids_tpu.plan.nodes import LocalTableScan
    from spark_rapids_tpu.session import DataFrame

    conf = session.conf
    tol = IOF.scan_tolerance(conf)
    names = [f.name for f in schema.fields]
    tables = []
    for path, gone in files:
        try:
            with IOF.file_context(path, "parquet", "MOR"):
                t = read_parquet_file(path, names)
        except Exception as e:
            IOF.handle_scan_error(e, path, "parquet", "MOR", tol, conf)
            continue
        if gone:
            keep = np.setdiff1d(np.arange(t.num_rows),
                                np.asarray(sorted(gone), dtype=np.int64))
            t = t.take(pa.array(keep))
        tables.append(t)
    if tables:
        tbl = pa.concat_tables(tables)
        cols = [HostColumn.from_arrow(tbl.column(f.name), f.dataType)
                for f in schema.fields]
    else:
        # every file tolerated away: an empty table of the right schema
        cols = [HostColumn.from_pylist([], f.dataType)
                for f in schema.fields]
    return DataFrame(LocalTableScan(cols, schema), session)
