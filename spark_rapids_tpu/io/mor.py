"""Merge-on-read assembly: parquet files minus deleted row indices.

Shared by the Iceberg position-delete reader and the Delta deletion-vector
reader (the reference applies these inside its GPU parquet readers; here
per-file row positions do not survive the concatenating scan, so the take
happens while building the batch)."""
from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple


def read_parquet_minus_rows(session, files, schema):
    """files: [(path, deleted_row_indices_or_None)] -> DataFrame."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.columnar.column import HostColumn
    from spark_rapids_tpu.plan.nodes import LocalTableScan
    from spark_rapids_tpu.session import DataFrame

    names = [f.name for f in schema.fields]
    tables = []
    for path, gone in files:
        t = pq.read_table(path, columns=names)
        if gone:
            keep = np.setdiff1d(np.arange(t.num_rows),
                                np.asarray(sorted(gone), dtype=np.int64))
            t = t.take(pa.array(keep))
        tables.append(t)
    tbl = pa.concat_tables(tables)
    cols = [HostColumn.from_arrow(tbl.column(f.name), f.dataType)
            for f in schema.fields]
    return DataFrame(LocalTableScan(cols, schema), session)
