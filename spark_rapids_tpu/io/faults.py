"""I/O fault domain — per-FILE scan-error classification and tolerance.

Reference analog (SURVEY.md §2.6): GpuMultiFileReader inherits Spark's
``spark.sql.files.ignoreCorruptFiles`` / ``ignoreMissingFiles`` semantics —
one truncated footer in a million-file data lake skips ONE file, not the
query.  PR 1's stage fault domain is too coarse for that: a deterministic
scan failure would CPU-fallback (and re-fail: the oracle reads the same
bytes) or kill the stage.  This module is the finer-grained layer every
reader routes escaping errors through:

  * classification — :func:`to_scan_fault` walks the exception chain
    (``resilience/classify.exception_chain``) and maps file-attributable
    decode errors to :class:`CorruptFile` / :class:`TruncatedFile` /
    :class:`MissingFile` / :class:`SchemaMismatch`, each carrying the
    path, format, reader mode, and (when a parser recorded one) the byte
    offset.  Non-file faults (ANSI errors, cancellation, OOM, transient
    infrastructure) are never classified — they keep their PR 1 semantics.
  * tolerance — :func:`scan_tolerance` reads the Spark confs (and their
    ``spark.rapids.tpu.files.*`` tri-state aliases, which win when set);
    :func:`is_tolerated` decides skip vs fail-fast per fault class.
  * accounting — :func:`record_skip` bumps ``files_skipped_corrupt`` /
    ``files_skipped_missing``, emits an ``io_fault`` diagnostics event,
    and appends the file to the per-query quarantine manifest
    (``quarantine-<query_id>.json`` next to the event log).
  * attribution — :func:`annotate_scan_error` / :func:`file_context` tag
    any OTHER error escaping a reader with ``file=<path>`` and the reader
    mode via ``__notes__``, so chaos/stress logs are attributable even
    for faults the classifier refuses to own.
"""
from __future__ import annotations

import errno as _errno
import json
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional, Type

from spark_rapids_tpu.resilience import classify as CL


# ---------------------------------------------------------------------------
# fault classes
# ---------------------------------------------------------------------------

class ScanFault(Exception):
    """A file-attributable scan failure.  ``kind`` is the quarantine /
    counter class; classified DETERMINISTIC by resilience/classify (the
    fallthrough default), so an untolerated fault escalates to the stage
    fault domain with full file context in its message."""

    kind = "corrupt"

    def __init__(self, path: str, detail: str = "", fmt: str = "",
                 reader_mode: str = "", offset: Optional[int] = None):
        self.path = path
        self.detail = detail
        self.fmt = fmt
        self.reader_mode = reader_mode
        self.offset = offset
        at = f" near byte {offset}" if offset is not None else ""
        mode = f" reader={reader_mode}" if reader_mode else ""
        super().__init__(
            f"{self.kind} file={path}{at} fmt={fmt or '?'}{mode}"
            + (f": {detail}" if detail else ""))


class CorruptFile(ScanFault):
    kind = "corrupt"


class TruncatedFile(CorruptFile):
    """Corrupt subclass: the file ends early (footer/postscript/sync
    marker missing).  Tolerated by the same conf as CorruptFile; the
    distinction survives into the quarantine manifest."""

    kind = "truncated"


class MissingFile(ScanFault):
    kind = "missing"


class SchemaMismatch(CorruptFile):
    """The file's physical schema drifted from the scan schema (renamed /
    missing column).  Treated as a corrupt-class fault: Spark's
    FileScanRDD likewise skips any per-file read error under
    ignoreCorruptFiles."""

    kind = "schema_mismatch"


# ---------------------------------------------------------------------------
# tolerance confs
# ---------------------------------------------------------------------------

class ScanTolerance:
    __slots__ = ("ignore_corrupt", "ignore_missing")

    def __init__(self, ignore_corrupt: bool, ignore_missing: bool):
        self.ignore_corrupt = bool(ignore_corrupt)
        self.ignore_missing = bool(ignore_missing)


def _tri_state(conf, alias_entry, base_entry) -> bool:
    """spark.rapids.tpu.files.* alias wins when set; unset defers to the
    Spark conf."""
    raw = conf.get(alias_entry)
    if raw is None or str(raw).strip() == "":
        return bool(conf.get(base_entry))
    return str(raw).strip().lower() in ("true", "1", "yes")


def scan_tolerance(conf) -> ScanTolerance:
    from spark_rapids_tpu.config import (
        IGNORE_CORRUPT_FILES,
        IGNORE_MISSING_FILES,
        TPU_IGNORE_CORRUPT_FILES,
        TPU_IGNORE_MISSING_FILES,
    )

    return ScanTolerance(
        _tri_state(conf, TPU_IGNORE_CORRUPT_FILES, IGNORE_CORRUPT_FILES),
        _tri_state(conf, TPU_IGNORE_MISSING_FILES, IGNORE_MISSING_FILES))


def is_tolerated(fault: ScanFault, tol: ScanTolerance) -> bool:
    if isinstance(fault, MissingFile):
        return tol.ignore_missing
    return tol.ignore_corrupt


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

# pyarrow / parser messages that mean "this file's schema drifted"
_SCHEMA_MARKERS = (
    "No match for FieldRef",
    "Invalid column selected",
    "Schema at index",
    "schema mismatch",
    "field not found",
)

# messages that mean "the file ends early" (vs generic corruption)
_TRUNCATION_MARKERS = (
    "truncat", "postscript", "Unexpected end", "unexpected end",
    "ends early", "sync marker mismatch", "footer missing",
)

# messages that mean "the bytes are not a valid file of this format"
_CORRUPT_MARKERS = (
    "Is this a 'parquet' file",
    "Could not open Parquet input source",
    "Parquet magic bytes not found",
    "not a parquet file",
    "not an ORC file",
    "not an Avro object container",
    "corrupt", "Corrupt",
    "StripeFooter",
    "Couldn't deserialize thrift",
    "CRC",
    "invalid checksum",
    "decompress",
    "snappy",
)

# python-level parse wreckage inside a file-decode region: these types are
# only mapped when no marker matched AND the error came from a decode
# context (the wrap sites only cover the per-file read region)
_DECODE_ERROR_TYPES = (struct.error, UnicodeDecodeError, EOFError,
                       IndexError, zlib.error)

# chaos-injected corruption (resilience/faults.py file_corrupt kind),
# matched by name to keep this module import-light
_INJECTED_CORRUPT_NAMES = ("InjectedFileCorruption",)


def _looks_truncated(path: str, fmt: str) -> bool:
    """Cheap tail sniff: a parquet file whose last 4 bytes are not PAR1
    (or any file the message-markers flagged) was cut short."""
    try:
        if fmt == "parquet" and os.path.isfile(path):
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size < 8:
                    return True
                f.seek(-4, os.SEEK_END)
                return f.read(4) != b"PAR1"
    except OSError:
        pass
    return False


def to_scan_fault(exc: BaseException, path: str, fmt: str = "",
                  reader_mode: str = "") -> Optional[ScanFault]:
    """Map ``exc`` (raised while reading ``path``) to a typed scan fault,
    or None when the error is not file-attributable (semantic errors,
    cancellation, OOM, transient infrastructure, plain bugs) — those keep
    their resilience class and are only annotated with file context."""
    if CL.classify_failure(exc) != CL.DETERMINISTIC:
        return None
    offset = None
    for link in CL.exception_chain(exc):
        if isinstance(link, ScanFault):
            return link
        if offset is None:
            offset = getattr(link, "srt_offset", None)

    def build(cls: Type[ScanFault], detail_exc: BaseException) -> ScanFault:
        detail = f"{type(detail_exc).__name__}: {detail_exc}"
        return cls(path, detail[:300], fmt, reader_mode, offset)

    for link in CL.exception_chain(exc):
        msg = str(link)
        if isinstance(link, FileNotFoundError) \
                or (isinstance(link, OSError)
                    and link.errno == _errno.ENOENT) \
                or (isinstance(link, OSError)
                    and "No such file or directory" in msg):
            return build(MissingFile, link)
        if type(link).__name__ in _INJECTED_CORRUPT_NAMES:
            return build(CorruptFile, link)
        # message-marker sniffing is restricted to PARSER-origin types
        # (ValueError covers pyarrow ArrowInvalid and the native
        # parsers; OSError covers pyarrow IO errors): an engine error —
        # e.g. Spark's FAILFAST RuntimeError, which interpolates the raw
        # ROW into its message — must never classify from user data
        # that happens to contain 'corrupt'/'CRC'/...
        if isinstance(link, (ValueError, OSError)):
            if any(m in msg for m in _SCHEMA_MARKERS):
                return build(SchemaMismatch, link)
            if any(m in msg for m in _TRUNCATION_MARKERS):
                return build(TruncatedFile, link)
            if any(m in msg for m in _CORRUPT_MARKERS):
                return build(TruncatedFile if _looks_truncated(path, fmt)
                             else CorruptFile, link)
        if isinstance(link, _DECODE_ERROR_TYPES):
            return build(TruncatedFile if _looks_truncated(path, fmt)
                         else CorruptFile, link)
        if isinstance(link, OSError) and link.errno is None:
            # pyarrow surfaces decode failures as bare OSError with no
            # errno ("Unknown compression type", "bad StripeFooter", …);
            # real OS-level errors always carry one
            return build(TruncatedFile if _looks_truncated(path, fmt)
                         else CorruptFile, link)
    return None


# ---------------------------------------------------------------------------
# attribution (__notes__ on python 3.10: set the list by hand)
# ---------------------------------------------------------------------------

def annotate_scan_error(exc: BaseException, path: str,
                        reader_mode: str = "") -> BaseException:
    """Attach ``file=<path> reader=<mode>`` to an escaping error so
    chaos/stress logs can attribute it — idempotent per path."""
    note = f"file={path}" + (f" reader={reader_mode}" if reader_mode else "")
    notes = getattr(exc, "__notes__", None)
    if notes is None:
        try:
            exc.__notes__ = [note]
        # tpulint: disable=cancel-swallow (guards a setattr on the
        # exception object; nothing cancellable runs in the try)
        except Exception:
            pass
    elif not any(f"file={path}" in n for n in notes):
        notes.append(note)
    if getattr(exc, "srt_file", None) is None:
        try:
            exc.srt_file = path
        # tpulint: disable=cancel-swallow (setattr guard, as above)
        except Exception:
            pass
    return exc


class file_context:
    """``with file_context(path, fmt, reader):`` — annotate anything that
    escapes the per-file decode region with the file path + reader mode.
    Typed ScanFaults pass through untouched (they already carry it)."""

    def __init__(self, path: str, fmt: str = "", reader_mode: str = ""):
        self.path = path
        self.fmt = fmt
        self.reader_mode = reader_mode

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is None or isinstance(exc, ScanFault) \
                or isinstance(exc, (GeneratorExit, KeyboardInterrupt,
                                    SystemExit)):
            return False
        annotate_scan_error(exc, self.path, self.reader_mode)
        return False


# ---------------------------------------------------------------------------
# skip accounting: counters, io_fault event, quarantine manifest
# ---------------------------------------------------------------------------

_Q_LOCK = threading.Lock()
# query_id -> [entry]; bounded per query (a pathological million-file
# skip storm cannot hold GBs of dicts) AND across queries (a long-lived
# serving process evicts the oldest query's records once
# _MAX_QUARANTINE_QUERIES are retained)
_QUARANTINE: Dict[str, List[dict]] = {}
_DONE_QIDS: set = set()          # flushed (query ended) — evict these first
_MAX_QUARANTINE_ENTRIES = 10000
_MAX_QUARANTINE_QUERIES = 64
_LAST_QID: List[Optional[str]] = [None]


def quarantine_entries(query_id: Optional[str] = None) -> List[dict]:
    """The quarantine records of one query — default: the current query,
    or (outside one) the most recent query that quarantined anything."""
    with _Q_LOCK:
        if query_id is None:
            from spark_rapids_tpu.lifecycle.context import current

            ctx = current()
            query_id = ctx.query_id if ctx is not None else _LAST_QID[0]
        return list(_QUARANTINE.get(query_id, []))


def reset_quarantine() -> None:
    with _Q_LOCK:
        _QUARANTINE.clear()
        _DONE_QIDS.clear()
        _LAST_QID[0] = None


def _flush_manifest(conf, qid: str, entries: List[dict]) -> Optional[str]:
    """Atomic rewrite of the per-query quarantine manifest next to the
    diagnostics event log.  No eventLogDir conf -> in-memory only."""
    from spark_rapids_tpu.config import DIAGNOSTICS_EVENT_LOG_DIR

    log_dir = conf.get(DIAGNOSTICS_EVENT_LOG_DIR)
    if not log_dir:
        return None
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, f"quarantine-{qid}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"query_id": qid, "files": entries}, f, indent=1)
    os.replace(tmp, path)
    return path


def flush_quarantine(conf, qid: str) -> None:
    """Write ``qid``'s manifest now (idempotent; OSError-silent — the
    manifest is diagnostics, never query-fatal).  Marks the query done:
    its retained records become first in line for cross-query
    eviction."""
    with _Q_LOCK:
        snapshot = list(_QUARANTINE.get(qid, []))
        _DONE_QIDS.add(qid)
    if not snapshot:
        return
    try:
        _flush_manifest(conf, qid, snapshot)
    except OSError:
        pass


def record_skip(fault: ScanFault, conf) -> None:
    """One tolerated-away file: counter + io_fault event + quarantine.

    The manifest flushes ONCE per query — on the first skip a lifecycle
    cleanup hook is registered, so a 10k-file skip storm costs one
    write, and a query killed mid-scan still flushes on unwind.
    Outside a query context (eager MOR assembly) each skip flushes
    immediately."""
    import time

    from spark_rapids_tpu import perfcounters as PC
    from spark_rapids_tpu.diagnostics import context as DIAG_CTX
    from spark_rapids_tpu.lifecycle.context import current

    PC.bump("files_skipped_missing" if isinstance(fault, MissingFile)
            else "files_skipped_corrupt")
    rec = DIAG_CTX.RECORDER
    if rec is not None:
        rec.io_fault(fault.kind, fault.path, fault.fmt,
                     detail=fault.detail)
    ctx = current()
    qid = ctx.query_id if ctx is not None else "-"
    entry = {
        "path": fault.path,
        "class": fault.kind,
        "offset": fault.offset,
        "fmt": fault.fmt,
        "reader": fault.reader_mode,
        "error": fault.detail,
        "ts": time.time(),
    }
    with _Q_LOCK:
        _LAST_QID[0] = qid
        lst = _QUARANTINE.get(qid)
        if lst is None:
            # bound retained queries: evict FLUSHED (ended) queries
            # first so a still-running query's records survive to its
            # end-of-query flush; only if every retained query is still
            # live does the hard bound evict the oldest regardless
            while len(_QUARANTINE) >= _MAX_QUARANTINE_QUERIES:
                victim = next((k for k in _QUARANTINE
                               if k in _DONE_QIDS),
                              next(iter(_QUARANTINE)))
                _QUARANTINE.pop(victim)
                _DONE_QIDS.discard(victim)
            lst = _QUARANTINE[qid] = []
        if len(lst) >= _MAX_QUARANTINE_ENTRIES:
            return
        lst.append(entry)
        first = len(lst) == 1
    if ctx is not None:
        if first:
            ctx.add_cleanup(lambda: flush_quarantine(conf, qid))
    else:
        flush_quarantine(conf, qid)


# ---------------------------------------------------------------------------
# the per-file error handler every reader mode routes through
# ---------------------------------------------------------------------------

def handle_scan_error(exc: BaseException, path: str, fmt: str,
                      reader_mode: str, tol: ScanTolerance, conf,
                      count_skips: bool = True) -> bool:
    """Classify + resolve one per-file error.  Returns True when the
    file was tolerated away (caller skips it and re-drives the surviving
    set); raises the typed fault (untolerated, file-attributed) or the
    annotated original (unclassifiable) otherwise."""
    fault = to_scan_fault(exc, path, fmt, reader_mode)
    if fault is not None:
        if is_tolerated(fault, tol):
            if count_skips:
                record_skip(fault, conf)
            return True
        if fault is exc:
            raise fault
        raise fault from exc
    annotate_scan_error(exc, path, reader_mode)
    raise exc
