"""Iceberg table scan (read path) — the GpuIcebergParquetReader analog.

Reference analog: iceberg/ module (SURVEY.md §2.8, MED): the reference
accelerates Iceberg's parquet data-file reads.  This module walks the open
Iceberg v1/v2 table metadata directly: ``metadata/version-hint.text`` (or
the highest ``vN.metadata.json``), current snapshot -> manifest LIST
(Avro) -> manifests (Avro) -> live parquet data files; the engine's
regular parquet scan reads the data.

Supported subset: parquet data files, flat primitive schemas, v2
position deletes (file_path/pos parquet files, applied while assembling
the scan) and equality deletes (applied as device ANTI joins against the
delete rows).  Limits: equality deletes apply to the whole snapshot —
sequence-number scoping (re-inserts after a delete) is not implemented
and such tables read incorrectly (undetected); null values in equality
delete rows raise (the anti join cannot match null==null).
"""
from __future__ import annotations

import json
import os
import re
from typing import List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.avro import read_avro_file

_PRIMS = {
    "boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG, "float": T.FLOAT,
    "double": T.DOUBLE, "string": T.STRING, "date": T.DATE,
    "timestamp": T.TIMESTAMP, "timestamptz": T.TIMESTAMP,
    "binary": T.BINARY,
}


def _field_type(t) -> T.DataType:
    if isinstance(t, str):
        if t in _PRIMS:
            return _PRIMS[t]
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", t)
        if m:
            return T.DecimalType(int(m.group(1)), int(m.group(2)))
        raise ValueError(f"unsupported iceberg type {t!r}")
    if isinstance(t, dict) and t.get("type") == "list":
        return T.ArrayType(_field_type(t["element"]),
                           not t.get("element-required", False))
    raise ValueError(f"unsupported iceberg type {t!r}")


def _schema_from_metadata(meta: dict) -> T.StructType:
    schemas = meta.get("schemas")
    if schemas:
        sid = meta.get("current-schema-id", 0)
        schema = next((s for s in schemas if s.get("schema-id") == sid),
                      schemas[-1])
    else:
        schema = meta["schema"]  # v1 single-schema layout
    return T.StructType([
        T.StructField(f["name"], _field_type(f["type"]),
                      not f.get("required", False))
        for f in schema["fields"]])


def _resolve(table_path: str, p: str) -> str:
    """Manifest paths may be absolute file URIs or table-relative."""
    if p.startswith("file://"):
        return p[len("file://"):]
    if os.path.isabs(p):
        return p
    return os.path.join(table_path, p)


def _latest_metadata(table_path: str) -> str:
    mdir = os.path.join(table_path, "metadata")
    hint = os.path.join(mdir, "version-hint.text")
    if os.path.isfile(hint):
        with open(hint) as f:
            v = f.read().strip()
        cand = os.path.join(mdir, f"v{v}.metadata.json")
        if os.path.isfile(cand):
            return cand
    best: Tuple[int, Optional[str]] = (-1, None)
    for name in os.listdir(mdir):
        m = re.match(r"v(\d+)\.metadata\.json$", name)
        if m and int(m.group(1)) > best[0]:
            best = (int(m.group(1)), os.path.join(mdir, name))
    if best[1] is None:
        raise FileNotFoundError(
            f"{table_path}: no iceberg metadata json found")
    return best[1]


def _field_id_names(meta: dict) -> dict:
    schemas = meta.get("schemas")
    if schemas:
        sid = meta.get("current-schema-id", 0)
        schema = next((s for s in schemas if s.get("schema-id") == sid),
                      schemas[-1])
    else:
        schema = meta["schema"]
    return {f["id"]: f["name"] for f in schema["fields"] if "id" in f}


def iceberg_data_files(table_path: str,
                       snapshot_id: Optional[int] = None):
    """-> (live data paths, position-delete paths, equality deletes as
    (path, [column names]) pairs, table schema)."""
    with open(_latest_metadata(table_path)) as f:
        meta = json.load(f)
    schema = _schema_from_metadata(meta)
    id_names = _field_id_names(meta)
    snaps = meta.get("snapshots", [])
    if not snaps:
        return [], [], [], schema
    sid = snapshot_id if snapshot_id is not None \
        else meta.get("current-snapshot-id")
    snap = next((s for s in snaps if s.get("snapshot-id") == sid),
                snaps[-1])
    mlist = _resolve(table_path, snap["manifest-list"])
    _, entries = read_avro_file(mlist)
    paths: List[str] = []
    pos_deletes: List[str] = []
    eq_deletes: List[Tuple[str, List[str]]] = []
    for entry in entries:
        mpath = _resolve(table_path, entry["manifest_path"])
        _, files = read_avro_file(mpath)
        for fe in files:
            status = fe.get("status", 1)
            if status == 2:  # DELETED
                continue
            df = fe["data_file"]
            fmt = (df.get("file_format") or "PARQUET")
            if str(fmt).upper() != "PARQUET":
                raise ValueError(f"iceberg {fmt} data files not supported")
            content = df.get("content") or 0
            fp = _resolve(table_path, df["file_path"])
            if content == 0:
                paths.append(fp)
            elif content == 1:  # position deletes
                pos_deletes.append(fp)
            elif content == 2:  # equality deletes
                ids = df.get("equality_ids") or []
                names = [id_names[i] for i in ids if i in id_names]
                if not names:
                    raise ValueError(
                        "iceberg equality delete without resolvable "
                        "equality_ids")
                eq_deletes.append((fp, names))
            else:
                raise ValueError(f"iceberg delete content {content}")
    # manifests replay newest-first; drop duplicates, keep order
    def uniq(seq):
        seen = set()
        out = []
        for x in seq:
            key = x if isinstance(x, str) else x[0]
            if key not in seen:
                seen.add(key)
                out.append(x)
        return out

    return uniq(paths), uniq(pos_deletes), uniq(eq_deletes), schema


def _apply_position_deletes(session, paths, pos_delete_paths, schema):
    import pyarrow.parquet as pq

    from spark_rapids_tpu.io.mor import read_parquet_minus_rows

    dropped = {}
    for dp in pos_delete_paths:
        t = pq.read_table(dp)
        for fp, pos in zip(t.column("file_path").to_pylist(),
                           t.column("pos").to_pylist()):
            dropped.setdefault(_norm_path(fp), set()).add(int(pos))
    return read_parquet_minus_rows(
        session, [(p, dropped.get(_norm_path(p))) for p in paths], schema)


def _norm_path(p: str) -> str:
    return p[len("file://"):] if p.startswith("file://") else p


def read_iceberg(session, table_path: str,
                 snapshot_id: Optional[int] = None):
    paths, pos_del, eq_del, schema = iceberg_data_files(
        table_path, snapshot_id)
    if not paths:
        return session.create_dataframe(
            {f.name: [] for f in schema.fields}, schema)
    if pos_del:
        df = _apply_position_deletes(session, paths, pos_del, schema)
    else:
        df = session.read.schema(schema).parquet(*paths)
    # equality deletes: device ANTI join against the delete rows (the
    # engine-join design Delta MERGE uses)
    for dp, names in eq_del:
        import pyarrow.parquet as pq

        t = pq.read_table(dp, columns=names)
        dschema = T.StructType(
            [f for f in schema.fields if f.name in names])
        data = {f.name: t.column(f.name).to_pylist()
                for f in dschema.fields}
        if any(v is None for vals in data.values() for v in vals):
            # the spec matches null==null in equality deletes; the anti
            # join cannot, so reject rather than silently keep the rows
            raise ValueError(
                "iceberg equality deletes with null values are not "
                "supported")
        ddf = session.create_dataframe(data, dschema)
        df = df.join(ddf, on=names, how="left_anti")
    return df
