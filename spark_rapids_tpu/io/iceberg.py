"""Iceberg table scan (read path) — the GpuIcebergParquetReader analog.

Reference analog: iceberg/ module (SURVEY.md §2.8, MED): the reference
accelerates Iceberg's parquet data-file reads.  This module walks the open
Iceberg v1/v2 table metadata directly: ``metadata/version-hint.text`` (or
the highest ``vN.metadata.json``), current snapshot -> manifest LIST
(Avro) -> manifests (Avro) -> live parquet data files; the engine's
regular parquet scan reads the data.

Supported subset: parquet data files, append-only tables (no position /
equality deletes — those raise), flat primitive schemas.
"""
from __future__ import annotations

import json
import os
import re
from typing import List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.avro import read_avro_file

_PRIMS = {
    "boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG, "float": T.FLOAT,
    "double": T.DOUBLE, "string": T.STRING, "date": T.DATE,
    "timestamp": T.TIMESTAMP, "timestamptz": T.TIMESTAMP,
    "binary": T.BINARY,
}


def _field_type(t) -> T.DataType:
    if isinstance(t, str):
        if t in _PRIMS:
            return _PRIMS[t]
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", t)
        if m:
            return T.DecimalType(int(m.group(1)), int(m.group(2)))
        raise ValueError(f"unsupported iceberg type {t!r}")
    if isinstance(t, dict) and t.get("type") == "list":
        return T.ArrayType(_field_type(t["element"]),
                           not t.get("element-required", False))
    raise ValueError(f"unsupported iceberg type {t!r}")


def _schema_from_metadata(meta: dict) -> T.StructType:
    schemas = meta.get("schemas")
    if schemas:
        sid = meta.get("current-schema-id", 0)
        schema = next((s for s in schemas if s.get("schema-id") == sid),
                      schemas[-1])
    else:
        schema = meta["schema"]  # v1 single-schema layout
    return T.StructType([
        T.StructField(f["name"], _field_type(f["type"]),
                      not f.get("required", False))
        for f in schema["fields"]])


def _resolve(table_path: str, p: str) -> str:
    """Manifest paths may be absolute file URIs or table-relative."""
    if p.startswith("file://"):
        return p[len("file://"):]
    if os.path.isabs(p):
        return p
    return os.path.join(table_path, p)


def _latest_metadata(table_path: str) -> str:
    mdir = os.path.join(table_path, "metadata")
    hint = os.path.join(mdir, "version-hint.text")
    if os.path.isfile(hint):
        with open(hint) as f:
            v = f.read().strip()
        cand = os.path.join(mdir, f"v{v}.metadata.json")
        if os.path.isfile(cand):
            return cand
    best: Tuple[int, Optional[str]] = (-1, None)
    for name in os.listdir(mdir):
        m = re.match(r"v(\d+)\.metadata\.json$", name)
        if m and int(m.group(1)) > best[0]:
            best = (int(m.group(1)), os.path.join(mdir, name))
    if best[1] is None:
        raise FileNotFoundError(
            f"{table_path}: no iceberg metadata json found")
    return best[1]


def iceberg_data_files(table_path: str,
                       snapshot_id: Optional[int] = None
                       ) -> Tuple[List[str], T.StructType]:
    """-> (live parquet data file paths, table schema)."""
    with open(_latest_metadata(table_path)) as f:
        meta = json.load(f)
    schema = _schema_from_metadata(meta)
    snaps = meta.get("snapshots", [])
    if not snaps:
        return [], schema
    sid = snapshot_id if snapshot_id is not None \
        else meta.get("current-snapshot-id")
    snap = next((s for s in snaps if s.get("snapshot-id") == sid),
                snaps[-1])
    mlist = _resolve(table_path, snap["manifest-list"])
    _, entries = read_avro_file(mlist)
    paths: List[str] = []
    for entry in entries:
        content = entry.get("content", 0)
        if content not in (None, 0):
            raise ValueError(
                "iceberg delete manifests are not supported (append-only "
                "tables)")
        mpath = _resolve(table_path, entry["manifest_path"])
        _, files = read_avro_file(mpath)
        for fe in files:
            status = fe.get("status", 1)
            if status == 2:  # DELETED
                continue
            df = fe["data_file"]
            if isinstance(df.get("content"), int) and df["content"] != 0:
                raise ValueError("iceberg delete files are not supported")
            fmt = (df.get("file_format") or "PARQUET")
            if str(fmt).upper() != "PARQUET":
                raise ValueError(f"iceberg {fmt} data files not supported")
            paths.append(_resolve(table_path, df["file_path"]))
    # manifests replay newest-first; drop duplicates, keep order
    seen = set()
    uniq = []
    for p in paths:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq, schema


def read_iceberg(session, table_path: str,
                 snapshot_id: Optional[int] = None):
    paths, schema = iceberg_data_files(table_path, snapshot_id)
    if not paths:
        return session.create_dataframe(
            {f.name: [] for f in schema.fields}, schema)
    return session.read.schema(schema).parquet(*paths)
