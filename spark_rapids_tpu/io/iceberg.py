"""Iceberg table scan (read path) — the GpuIcebergParquetReader analog.

Reference analog: iceberg/ module (SURVEY.md §2.8, MED): the reference
accelerates Iceberg's parquet data-file reads.  This module walks the open
Iceberg v1/v2 table metadata directly: ``metadata/version-hint.text`` (or
the highest ``vN.metadata.json``), current snapshot -> manifest LIST
(Avro) -> manifests (Avro) -> live parquet data files; the engine's
regular parquet scan reads the data.

Supported subset: parquet data files, flat primitive schemas, v2
position deletes (file_path/pos parquet files, applied while assembling
the scan) and equality deletes (applied as device ANTI joins against the
delete rows).  Limits: equality deletes apply to the whole snapshot —
sequence-number scoping (re-inserts after a delete) is not implemented
and such tables read incorrectly (undetected); null values in equality
delete rows raise (the anti join cannot match null==null).
"""
from __future__ import annotations

import json
import os
import re
from typing import List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.avro import read_avro_file

_PRIMS = {
    "boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG, "float": T.FLOAT,
    "double": T.DOUBLE, "string": T.STRING, "date": T.DATE,
    "timestamp": T.TIMESTAMP, "timestamptz": T.TIMESTAMP,
    "binary": T.BINARY,
}


def _field_type(t) -> T.DataType:
    if isinstance(t, str):
        if t in _PRIMS:
            return _PRIMS[t]
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", t)
        if m:
            return T.DecimalType(int(m.group(1)), int(m.group(2)))
        raise ValueError(f"unsupported iceberg type {t!r}")
    if isinstance(t, dict) and t.get("type") == "list":
        return T.ArrayType(_field_type(t["element"]),
                           not t.get("element-required", False))
    raise ValueError(f"unsupported iceberg type {t!r}")


def _schema_from_metadata(meta: dict) -> T.StructType:
    schemas = meta.get("schemas")
    if schemas:
        sid = meta.get("current-schema-id", 0)
        schema = next((s for s in schemas if s.get("schema-id") == sid),
                      schemas[-1])
    else:
        schema = meta["schema"]  # v1 single-schema layout
    return T.StructType([
        T.StructField(f["name"], _field_type(f["type"]),
                      not f.get("required", False))
        for f in schema["fields"]])


def _resolve(table_path: str, p: str) -> str:
    """Manifest paths may be absolute file URIs or table-relative."""
    if p.startswith("file://"):
        return p[len("file://"):]
    if os.path.isabs(p):
        return p
    return os.path.join(table_path, p)


def _latest_metadata(table_path: str) -> str:
    mdir = os.path.join(table_path, "metadata")
    hint = os.path.join(mdir, "version-hint.text")
    if os.path.isfile(hint):
        with open(hint) as f:
            v = f.read().strip()
        cand = os.path.join(mdir, f"v{v}.metadata.json")
        if os.path.isfile(cand):
            return cand
    best: Tuple[int, Optional[str]] = (-1, None)
    for name in os.listdir(mdir):
        m = re.match(r"v(\d+)\.metadata\.json$", name)
        if m and int(m.group(1)) > best[0]:
            best = (int(m.group(1)), os.path.join(mdir, name))
    if best[1] is None:
        raise FileNotFoundError(
            f"{table_path}: no iceberg metadata json found")
    return best[1]


def _field_id_names(meta: dict) -> dict:
    schemas = meta.get("schemas")
    if schemas:
        sid = meta.get("current-schema-id", 0)
        schema = next((s for s in schemas if s.get("schema-id") == sid),
                      schemas[-1])
    else:
        schema = meta["schema"]
    return {f["id"]: f["name"] for f in schema["fields"] if "id" in f}


def iceberg_data_files(table_path: str,
                       snapshot_id: Optional[int] = None):
    """-> (live data paths, position-delete paths, equality deletes as
    (path, [column names]) pairs, table schema)."""
    with open(_latest_metadata(table_path)) as f:
        meta = json.load(f)
    schema = _schema_from_metadata(meta)
    id_names = _field_id_names(meta)
    snaps = meta.get("snapshots", [])
    if not snaps:
        return [], [], [], schema
    sid = snapshot_id if snapshot_id is not None \
        else meta.get("current-snapshot-id")
    snap = next((s for s in snaps if s.get("snapshot-id") == sid),
                snaps[-1])
    from spark_rapids_tpu.io.faults import file_context

    mlist = _resolve(table_path, snap["manifest-list"])
    with file_context(mlist, "avro", "iceberg-manifest-list"):
        _, entries = read_avro_file(mlist)
    paths: List[str] = []
    pos_deletes: List[str] = []
    eq_deletes: List[Tuple[str, List[str]]] = []
    for entry in entries:
        mpath = _resolve(table_path, entry["manifest_path"])
        # metadata corruption is never tolerated away (skipping a
        # manifest silently drops an unknowable file set) — it only
        # gains file attribution here
        with file_context(mpath, "avro", "iceberg-manifest"):
            _, files = read_avro_file(mpath)
        for fe in files:
            status = fe.get("status", 1)
            if status == 2:  # DELETED
                continue
            df = fe["data_file"]
            fmt = (df.get("file_format") or "PARQUET")
            if str(fmt).upper() != "PARQUET":
                raise ValueError(f"iceberg {fmt} data files not supported")
            content = df.get("content") or 0
            fp = _resolve(table_path, df["file_path"])
            if content == 0:
                paths.append(fp)
            elif content == 1:  # position deletes
                pos_deletes.append(fp)
            elif content == 2:  # equality deletes
                ids = df.get("equality_ids") or []
                names = [id_names[i] for i in ids if i in id_names]
                if not names:
                    raise ValueError(
                        "iceberg equality delete without resolvable "
                        "equality_ids")
                eq_deletes.append((fp, names))
            else:
                raise ValueError(f"iceberg delete content {content}")
    # manifests replay newest-first; drop duplicates, keep order
    def uniq(seq):
        seen = set()
        out = []
        for x in seq:
            key = x if isinstance(x, str) else x[0]
            if key not in seen:
                seen.add(key)
                out.append(x)
        return out

    return uniq(paths), uniq(pos_deletes), uniq(eq_deletes), schema


def _apply_position_deletes(session, paths, pos_delete_paths, schema):
    import pyarrow.parquet as pq

    from spark_rapids_tpu.io.mor import read_parquet_minus_rows

    from spark_rapids_tpu.io.faults import file_context

    dropped = {}
    for dp in pos_delete_paths:
        # delete files are MOR metadata: never tolerated away (skipping
        # one would resurrect deleted rows) — attributed only
        with file_context(dp, "parquet", "iceberg-position-deletes"):
            t = pq.read_table(dp)
        for fp, pos in zip(t.column("file_path").to_pylist(),
                           t.column("pos").to_pylist()):
            dropped.setdefault(_norm_path(fp), set()).add(int(pos))
    return read_parquet_minus_rows(
        session, [(p, dropped.get(_norm_path(p))) for p in paths], schema)


def _norm_path(p: str) -> str:
    return p[len("file://"):] if p.startswith("file://") else p


def read_iceberg(session, table_path: str,
                 snapshot_id: Optional[int] = None):
    paths, pos_del, eq_del, schema = iceberg_data_files(
        table_path, snapshot_id)
    if not paths:
        return session.create_dataframe(
            {f.name: [] for f in schema.fields}, schema)
    if pos_del:
        df = _apply_position_deletes(session, paths, pos_del, schema)
    else:
        df = session.read.schema(schema).parquet(*paths)
    # equality deletes: device ANTI join against the delete rows (the
    # engine-join design Delta MERGE uses)
    for dp, names in eq_del:
        import pyarrow.parquet as pq

        from spark_rapids_tpu.io.faults import file_context

        with file_context(dp, "parquet", "iceberg-equality-deletes"):
            t = pq.read_table(dp, columns=names)
        dschema = T.StructType(
            [f for f in schema.fields if f.name in names])
        data = {f.name: t.column(f.name).to_pylist()
                for f in dschema.fields}
        if any(v is None for vals in data.values() for v in vals):
            # the spec matches null==null in equality deletes; the anti
            # join cannot, so reject rather than silently keep the rows
            raise ValueError(
                "iceberg equality deletes with null values are not "
                "supported")
        ddf = session.create_dataframe(data, dschema)
        df = df.join(ddf, on=names, how="left_anti")
    return df


# ---------------------------------------------------------------------------
# Write path (VERDICT r3 Next #7).  Reference analog: the reference's
# Iceberg module is read-only too in most branches; Spark's Iceberg writes
# go through the iceberg-spark runtime (SURVEY.md §2.8 Iceberg).  This
# implements format-version-2 append/overwrite commits from scratch:
# data parquet files + manifest avro + manifest-list avro + metadata json,
# all round-tripping through this module's own reader and avro codec.
# ---------------------------------------------------------------------------

_ICEBERG_TYPE = {
    "BooleanType": "boolean", "IntegerType": "int", "LongType": "long",
    "FloatType": "float", "DoubleType": "double", "StringType": "string",
    "DateType": "date", "TimestampType": "timestamptz",
    "ByteType": "int", "ShortType": "int",
}


def _type_to_iceberg(dt) -> str:
    if isinstance(dt, T.DecimalType):
        return f"decimal({dt.precision}, {dt.scale})"
    name = type(dt).__name__
    if name not in _ICEBERG_TYPE:
        raise ValueError(f"iceberg write: unsupported type {dt.simpleString}")
    return _ICEBERG_TYPE[name]


def _schema_json(schema: T.StructType) -> dict:
    return {"type": "struct", "schema-id": 0,
            "fields": [{"id": i + 1, "name": f.name,
                        "required": not f.nullable,
                        "type": _type_to_iceberg(f.dataType)}
                       for i, f in enumerate(schema.fields)]}


_MANIFEST_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
            ]}},
    ]}

_MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "added_snapshot_id", "type": "long"},
    ]}


def write_iceberg(df, table_path: str, mode: str = "error",
                  partition_by=None) -> int:
    """Write a DataFrame as an iceberg v2 commit; returns the snapshot id.

    modes: error/ignore/append/overwrite.  ``partition_by`` uses identity
    transforms; data files land under data/<col>=<value>/ and the spec is
    recorded in the metadata (the scan reads files regardless of
    partition layout)."""
    import time
    import uuid

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.delta.table import _df_to_arrow
    from spark_rapids_tpu.io.avro import write_avro_file

    mdir = os.path.join(table_path, "metadata")
    ddir = os.path.join(table_path, "data")
    exists = os.path.isdir(mdir) and any(
        re.match(r"v(\d+)\.metadata\.json$", n)
        for n in os.listdir(mdir)) if os.path.isdir(mdir) else False
    if exists and mode in ("error", "errorifexists"):
        raise FileExistsError(f"iceberg table already exists: {table_path}")
    if exists and mode == "ignore":
        with open(_latest_metadata(table_path)) as f:
            return json.load(f).get("current-snapshot-id", -1)
    os.makedirs(mdir, exist_ok=True)
    os.makedirs(ddir, exist_ok=True)

    meta = None
    version = 0
    if exists:
        mpath = _latest_metadata(table_path)
        version = int(re.match(r"v(\d+)\.metadata\.json$",
                               os.path.basename(mpath)).group(1))
        with open(mpath) as f:
            meta = json.load(f)

    tbl = _df_to_arrow(df)
    snapshot_id = int(uuid.uuid4().int % (1 << 62))
    now_ms = int(time.time() * 1000)
    part_cols = list(partition_by or [])

    # -- data files (hive-style dirs for identity partitions) ----------
    data_files = []

    def _write_part(sub_tbl, subdir):
        os.makedirs(subdir, exist_ok=True)
        fp = os.path.join(subdir, f"{uuid.uuid4().hex}.parquet")
        pq.write_table(sub_tbl, fp)
        data_files.append({
            "status": 1, "snapshot_id": snapshot_id,
            "data_file": {
                "content": 0, "file_path": fp,
                "file_format": "PARQUET",
                "record_count": sub_tbl.num_rows,
                "file_size_in_bytes": os.path.getsize(fp)}})

    if tbl.num_rows:
        if part_cols:
            import pyarrow.compute as pc

            keys = [tbl.column(c) for c in part_cols]
            combos = {tuple(row) for row in zip(
                *[k.to_pylist() for k in keys])}
            for combo in sorted(combos, key=lambda t: tuple(map(str, t))):
                mask = None
                for c, v in zip(part_cols, combo):
                    m = (pc.is_null(tbl.column(c)) if v is None
                         else pc.equal(tbl.column(c), pa.scalar(v)))
                    mask = m if mask is None else pc.and_(mask, m)
                sub = tbl.filter(mask)
                subdir = os.path.join(ddir, *[
                    f"{c}={'null' if v is None else v}"
                    for c, v in zip(part_cols, combo)])
                _write_part(sub, subdir)
        else:
            _write_part(tbl, ddir)

    # -- manifest + manifest list --------------------------------------
    manifest_path = os.path.join(
        mdir, f"manifest-{uuid.uuid4().hex}.avro")
    write_avro_file(manifest_path, _MANIFEST_SCHEMA, data_files)
    manifests = [{"manifest_path": manifest_path,
                  "manifest_length": os.path.getsize(manifest_path),
                  "partition_spec_id": 0,
                  "added_snapshot_id": snapshot_id}]
    if meta is not None and mode == "append":
        cur = next((s for s in meta.get("snapshots", [])
                    if s.get("snapshot-id")
                    == meta.get("current-snapshot-id")), None)
        if cur is not None:
            old_list = _resolve(table_path, cur["manifest-list"])
            _, old = read_avro_file(old_list)
            manifests = list(old) + manifests
    mlist_path = os.path.join(
        mdir, f"snap-{snapshot_id}-{uuid.uuid4().hex}.avro")
    write_avro_file(mlist_path, _MANIFEST_LIST_SCHEMA, manifests)

    # -- metadata json v2 ----------------------------------------------
    schema_json = _schema_json(df.schema)
    name_to_id = {f["name"]: f["id"] for f in schema_json["fields"]}
    spec = {"spec-id": 0, "fields": [
        {"name": c, "transform": "identity",
         "source-id": name_to_id[c], "field-id": 1000 + i}
        for i, c in enumerate(part_cols)]}
    snapshot = {"snapshot-id": snapshot_id,
                "timestamp-ms": now_ms,
                "sequence-number": (meta or {}).get(
                    "last-sequence-number", 0) + 1,
                "summary": {"operation":
                            "append" if mode == "append" else "overwrite"},
                "manifest-list": mlist_path,
                "schema-id": 0}
    snapshots = list((meta or {}).get("snapshots", [])) \
        if mode == "append" and meta is not None else []
    if meta is not None and mode == "overwrite":
        snapshots = list(meta.get("snapshots", []))
    snapshots.append(snapshot)
    new_meta = {
        "format-version": 2,
        "table-uuid": (meta or {}).get("table-uuid",
                                       str(uuid.uuid4())),
        "location": table_path,
        "last-sequence-number": snapshot["sequence-number"],
        "last-updated-ms": now_ms,
        "last-column-id": len(schema_json["fields"]),
        "schemas": [schema_json],
        "current-schema-id": 0,
        "partition-specs": [spec],
        "default-spec-id": 0,
        "last-partition-id": 999 + len(part_cols),
        "sort-orders": [{"order-id": 0, "fields": []}],
        "default-sort-order-id": 0,
        "properties": {},
        "snapshots": snapshots,
        "current-snapshot-id": snapshot_id,
    }
    out_path = os.path.join(mdir, f"v{version + 1}.metadata.json")
    with open(out_path, "w") as f:
        json.dump(new_meta, f)
    with open(os.path.join(mdir, "version-hint.text"), "w") as f:
        f.write(str(version + 1))
    return snapshot_id
