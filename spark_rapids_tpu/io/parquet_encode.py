"""Device-side Parquet ENCODE — the decode pipeline's mirror.

Reference analog: GpuParquetFileFormat writes through cuDF's
``Table.writeParquetChunked`` — pages are ENCODED on device and the host
only assembles headers/footer (SURVEY.md §2.6 Writers, §2.10 item 9).
TPU replacement, same split:

  device (jitted kernels, feed the perf counters):
    * dictionary build: one sort + boundary pass -> padded unique values
      + count (``device_dict_build``);
    * index computation + k-bit packing: merge-rank positions into the
      dictionary, then the RLE/bit-packed hybrid's bit-packed body as a
      pure reshape/shift/matmul kernel (``device_bitpack``) — no per-row
      host loop anywhere;
    * def-levels for nullable columns: validity -> 1-bit packed run.

  host (this module): thrift compact page headers + footer, the snappy
    framing through the C compressor twin (native.snappy_compress), and
    file layout.  The host never touches row data — only the already
    -packed byte buffers that come back from the device.

Scope: flat INT32/INT64/FLOAT/DOUBLE/date columns (PLAIN or
RLE_DICTIONARY) and BYTE_ARRAY strings (PLAIN, device-computed lengths +
offsets).  io/writer.py routes eligible tables here when
``spark.rapids.sql.format.parquet.encode.device`` is on; anything else
keeps the pyarrow host encode.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.parquet_native import (CODEC_SNAPPY,
                                                CODEC_UNCOMPRESSED,
                                                ENC_PLAIN, ENC_RLE,
                                                ENC_RLE_DICT, PAGE_DATA,
                                                PAGE_DICT, TYPE_BYTE_ARRAY,
                                                TYPE_DOUBLE, TYPE_FLOAT,
                                                TYPE_INT32, TYPE_INT64)
from spark_rapids_tpu.perfcounters import tpu_jit

# thrift compact type nibbles
_CT_TRUE, _CT_FALSE, _CT_BYTE = 1, 2, 3
_CT_I16, _CT_I32, _CT_I64, _CT_DOUBLE = 4, 5, 6, 7
_CT_BINARY, _CT_LIST, _CT_SET, _CT_MAP, _CT_STRUCT = 8, 9, 10, 11, 12


class _TW:
    """Minimal thrift compact-protocol WRITER (the reader's inverse)."""

    def __init__(self):
        self.buf = bytearray()

    def varint(self, v: int):
        while True:
            b = v & 0x7F
            v >>= 7
            self.buf.append(b | 0x80 if v else b)
            if not v:
                return

    def zigzag(self, v: int):
        self.varint((v << 1) ^ (v >> 63) if v < 0 else (v << 1))

    def field(self, fid: int, last: int, ctype: int) -> int:
        delta = fid - last
        if 0 < delta < 16:
            self.buf.append((delta << 4) | ctype)
        else:
            self.buf.append(ctype)
            self.zigzag(fid)
        return fid

    def write_i(self, fid: int, last: int, v: int, ctype=_CT_I64) -> int:
        last = self.field(fid, last, ctype)
        self.zigzag(v)
        return last

    def write_bin(self, fid: int, last: int, v: bytes) -> int:
        last = self.field(fid, last, _CT_BINARY)
        self.varint(len(v))
        self.buf += v
        return last

    def write_list_header(self, fid: int, last: int, n: int,
                          etype: int) -> int:
        last = self.field(fid, last, _CT_LIST)
        if n < 15:
            self.buf.append((n << 4) | etype)
        else:
            self.buf.append(0xF0 | etype)
            self.varint(n)
        return last

    def stop(self):
        self.buf.append(0)


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

@tpu_jit
def _k_bitpack_bits(bits):
    """(n8, 8) bool -> (n8,) uint8, little-endian bit order (parquet
    RLE/bit-packed little-endian convention) — one matmul-shaped dot."""
    w = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.int32)
    return jnp.sum(bits.astype(jnp.int32) * w[None, :],
                   axis=1).astype(jnp.uint8)


def device_bitpack(values, bit_width: int) -> jax.Array:
    """k-bit little-endian pack of (n,) nonneg ints on device."""
    n = values.shape[0]
    if bit_width == 0:
        return jnp.zeros(0, jnp.uint8)
    shifts = jnp.arange(bit_width, dtype=values.dtype)
    bits = ((values[:, None] >> shifts[None, :]) & 1).astype(jnp.bool_)
    flat = bits.reshape(-1)
    pad = (-flat.shape[0]) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.bool_)])
    return _k_bitpack_bits(flat.reshape(-1, 8))


def _dict_build_fn(data, n_valid_mask):
    """sorted uniques (padded with last value) + count."""
    big = jnp.iinfo(jnp.int64).max
    key = jnp.where(n_valid_mask, data.astype(jnp.int64), big)
    s = jnp.sort(key)
    nv = jnp.sum(n_valid_mask.astype(jnp.int32))
    bnd = jnp.zeros(s.shape[0], jnp.bool_).at[0].set(True)
    bnd = bnd.at[1:].set(s[1:] != s[:-1])
    in_valid = jnp.arange(s.shape[0]) < nv
    is_new = bnd & in_valid
    uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    n_uniq = jnp.sum(is_new.astype(jnp.int32))
    # compact the uniques to the front (stable sort by ~is_new); the
    # tail pads with int64-max so searchsorted stays correct over the
    # full static-width array
    order = jnp.argsort(~is_new, stable=True)
    uniques = jnp.where(jnp.arange(s.shape[0]) < n_uniq, s[order], big)
    return uniques, n_uniq


_dict_build_jit = tpu_jit(_dict_build_fn)


def _dict_indices_fn(data, mask, uniques, n_uniq):
    pos = jnp.searchsorted(uniques[:], jnp.where(
        mask, data.astype(jnp.int64), uniques[0]))
    pos = jnp.clip(pos, 0, jnp.maximum(n_uniq - 1, 0))
    return pos.astype(jnp.int32)


_dict_indices_jit = tpu_jit(_dict_indices_fn)


# ---------------------------------------------------------------------------
# host assembly
# ---------------------------------------------------------------------------

def _hybrid_bitpacked(packed: bytes, n_values: int, bw: int) -> bytes:
    """One bit-packed run of the RLE/bit-packed hybrid."""
    groups = -(-n_values // 8)
    header = bytearray()
    v = (groups << 1) | 1
    while True:
        b = v & 0x7F
        v >>= 7
        header.append(b | 0x80 if v else b)
        if not v:
            break
    need = groups * bw
    body = packed[:need] if len(packed) >= need else \
        packed + b"\0" * (need - len(packed))
    return bytes(header) + body


def _rle_run(value: int, count: int, bw: int) -> bytes:
    out = bytearray()
    v = count << 1
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            break
    nbytes = (bw + 7) // 8
    out += int(value).to_bytes(nbytes, "little") if nbytes else b""
    return bytes(out)


_PHYS = {T.IntegerType: TYPE_INT32, T.DateType: TYPE_INT32,
         T.LongType: TYPE_INT64, T.FloatType: TYPE_FLOAT,
         T.DoubleType: TYPE_DOUBLE, T.StringType: TYPE_BYTE_ARRAY,
         T.TimestampType: TYPE_INT64}


def supported_schema(schema: T.StructType) -> bool:
    return all(type(f.dataType) in _PHYS for f in schema.fields)


def _page_header(page_type: int, usize: int, csize: int, n_values: int,
                 encoding: int, def_encoding: int = ENC_RLE) -> bytes:
    tw = _TW()
    last = 0
    last = tw.write_i(1, last, page_type, _CT_I32)
    last = tw.write_i(2, last, usize, _CT_I32)
    last = tw.write_i(3, last, csize, _CT_I32)
    if page_type == PAGE_DATA:
        last = tw.field(5, last, _CT_STRUCT)    # data_page_header
        l2 = 0
        l2 = tw.write_i(1, l2, n_values, _CT_I32)
        l2 = tw.write_i(2, l2, encoding, _CT_I32)
        l2 = tw.write_i(3, l2, def_encoding, _CT_I32)   # def level enc
        l2 = tw.write_i(4, l2, ENC_RLE, _CT_I32)        # rep level enc
        tw.stop()
    else:                                       # dictionary page
        last = tw.field(7, last, _CT_STRUCT)    # dictionary_page_header
        l2 = 0
        l2 = tw.write_i(1, l2, n_values, _CT_I32)
        l2 = tw.write_i(2, l2, ENC_PLAIN, _CT_I32)
        tw.stop()
    tw.stop()
    return bytes(tw.buf)


def _compress(codec: int, payload: bytes) -> bytes:
    if codec == CODEC_SNAPPY:
        from spark_rapids_tpu.native import snappy_compress

        return snappy_compress(payload)
    return payload


class _ChunkMeta:
    __slots__ = ("name", "phys", "n", "encodings", "codec",
                 "data_off", "dict_off", "csize", "usize", "dict_usize")


def _encode_column(f: T.StructField, col, n: int, codec: int,
                   use_dict: bool):
    """One column chunk -> (pages bytes, _ChunkMeta).  ``col`` is the
    device HostColumn-like carrier (validity + data or chars/lengths)."""
    phys = _PHYS[type(f.dataType)]
    nullable = bool(f.nullable)
    validity = col.validity[:n]
    mask = jnp.asarray(np.asarray(validity))

    # ---- def levels (nullable): 1-bit packed on device ----
    def_bytes = b""
    if nullable:
        packed = np.asarray(device_bitpack(
            jnp.asarray(np.asarray(validity).astype(np.int32)), 1))
        def_bytes = _hybrid_bitpacked(packed.tobytes(), n, 1)
        def_bytes = struct.pack("<I", len(def_bytes)) + def_bytes

    pages = bytearray()
    meta = _ChunkMeta()
    meta.name = f.name
    meta.phys = phys
    meta.n = n
    meta.codec = codec
    meta.dict_off = None

    if phys == TYPE_BYTE_ARRAY:
        # PLAIN byte-array: (len, bytes) interleave built with vectorized
        # scatters over device-computed lengths/offsets — no per-row loop
        chars = np.asarray(col.chars[:n])
        valid_np = np.asarray(validity)
        lens = np.where(valid_np,
                        np.asarray(col.lengths[:n]).astype(np.int64), 0)
        keep = valid_np if nullable else np.ones(n, np.bool_)
        klens = lens[keep]
        k = len(klens)
        starts = np.zeros(k, np.int64)
        if k:
            starts[1:] = np.cumsum(klens + 4)[:-1]
        total = int((klens + 4).sum())
        payload_arr = np.zeros(total, np.uint8)
        for b in range(4):      # 4 vectorized prefix scatters
            payload_arr[starts + b] = ((klens >> (8 * b)) & 0xFF)
        total_chars = int(klens.sum())
        if total_chars:
            row_ids = np.repeat(np.arange(k), klens)
            cum_excl = np.concatenate([[0], np.cumsum(klens)[:-1]])
            within = np.arange(total_chars) - np.repeat(cum_excl, klens)
            kchars = chars[keep]
            payload_arr[np.repeat(starts + 4, klens) + within] = \
                kchars[row_ids, within]
        payload = def_bytes + payload_arr.tobytes()
        meta.encodings = [ENC_PLAIN, ENC_RLE]
        enc = ENC_PLAIN
    elif use_dict:
        data = col.data[:n]
        uniques, n_uniq = _dict_build_jit(
            jnp.asarray(np.asarray(data)).astype(jnp.int64), mask)
        n_uniq = int(n_uniq)                      # one sync per chunk
        bw = max((n_uniq - 1).bit_length(), 1)
        idx = _dict_indices_jit(jnp.asarray(np.asarray(data)), mask,
                                uniques, jnp.int32(n_uniq))
        if nullable:
            # v1 data pages hold only the DEFINED values
            idx = jnp.asarray(np.asarray(idx)[np.asarray(validity)])
        n_defined = int(idx.shape[0])
        packed = np.asarray(device_bitpack(idx, bw))
        uvals = np.asarray(uniques)[:n_uniq]
        if phys == TYPE_INT32:
            dict_payload = uvals.astype("<i4").tobytes()
        elif phys == TYPE_INT64:
            dict_payload = uvals.astype("<i8").tobytes()
        else:
            raise ValueError("dict encode: int types only")
        cdict = _compress(codec, dict_payload)
        meta.dict_off = True
        dict_header = _page_header(PAGE_DICT, len(dict_payload),
                                   len(cdict), n_uniq, ENC_PLAIN)
        pages += dict_header
        pages += cdict
        meta.dict_usize = len(dict_header) + len(dict_payload)
        body = bytes([bw]) + _hybrid_bitpacked(packed.tobytes(),
                                               n_defined, bw)
        payload = def_bytes + body
        meta.encodings = [ENC_RLE_DICT, ENC_PLAIN, ENC_RLE]
        enc = ENC_RLE_DICT
    else:
        data = np.asarray(col.data[:n])
        if nullable:
            # parquet PLAIN pages hold only the DEFINED values
            data = data[np.asarray(validity)]
        wire = {TYPE_INT32: "<i4", TYPE_INT64: "<i8",
                TYPE_FLOAT: "<f4", TYPE_DOUBLE: "<f8"}[phys]
        payload = def_bytes + data.astype(wire).tobytes()
        meta.encodings = [ENC_PLAIN, ENC_RLE]
        enc = ENC_PLAIN

    cpayload = _compress(codec, bytes(payload))
    header = _page_header(PAGE_DATA, len(payload), len(cpayload), n, enc)
    data_page_pos = len(pages)
    pages += header
    pages += cpayload
    # total_uncompressed_size = page headers + UNCOMPRESSED payloads
    meta.usize = (getattr(meta, "dict_usize", 0) + len(header)
                  + len(payload))
    meta.csize = len(pages)
    meta.data_off = data_page_pos
    return bytes(pages), meta


def write_parquet_device(path: str, schema: T.StructType, cols, n: int,
                         compression: str = "snappy",
                         use_dict: bool = True) -> Dict[str, int]:
    """Write one parquet file with device-encoded pages.  ``cols`` are
    host-materializable column carriers (HostColumn or DeviceColumn
    fetched once).  Returns stats for tests/metrics."""
    codec = CODEC_SNAPPY if compression == "snappy" \
        else CODEC_UNCOMPRESSED
    out = bytearray(b"PAR1")
    chunk_metas: List[Tuple[_ChunkMeta, int]] = []
    for f, c in zip(schema.fields, cols):
        can_dict = (use_dict
                    and _PHYS[type(f.dataType)] in (TYPE_INT32,
                                                    TYPE_INT64))
        pages, meta = _encode_column(f, c, n, codec, can_dict)
        chunk_metas.append((meta, len(out)))
        out += pages

    # ---- footer ----
    tw = _TW()
    last = 0
    last = tw.write_i(1, last, 1, _CT_I32)               # version
    # schema: root + one element per field
    last = tw.write_list_header(2, last, 1 + len(schema.fields),
                                _CT_STRUCT)
    root = _TW()
    r_last = 0
    r_last = root.write_bin(4, r_last, b"schema")
    r_last = root.write_i(5, r_last, len(schema.fields), _CT_I32)
    tw.buf += root.buf
    tw.stop()
    for f in schema.fields:
        el = _TW()
        e_last = 0
        e_last = el.write_i(1, e_last, _PHYS[type(f.dataType)], _CT_I32)
        e_last = el.write_i(3, e_last, 1 if f.nullable else 0, _CT_I32)
        e_last = el.write_bin(4, e_last, f.name.encode())
        if isinstance(f.dataType, T.DateType):
            e_last = el.write_i(6, e_last, 6, _CT_I32)   # DATE converted
        if isinstance(f.dataType, T.TimestampType):
            e_last = el.write_i(6, e_last, 10, _CT_I32)  # TIMESTAMP_MICROS
        if isinstance(f.dataType, T.StringType):
            e_last = el.write_i(6, e_last, 0, _CT_I32)   # UTF8
        tw.buf += el.buf
        tw.stop()
    last = tw.write_i(3, last, n, _CT_I64)               # num_rows
    # one row group
    last = tw.write_list_header(4, last, 1, _CT_STRUCT)
    rg = _TW()
    g_last = 0
    g_last = rg.write_list_header(1, g_last, len(chunk_metas), _CT_STRUCT)
    total = 0
    for meta, base in chunk_metas:
        cc = _TW()
        c_last = 0
        c_last = cc.write_i(2, c_last, base, _CT_I64)    # file_offset
        c_last = cc.field(3, c_last, _CT_STRUCT)         # meta_data
        m = _TW()
        m_last = 0
        m_last = m.write_i(1, m_last, meta.phys, _CT_I32)
        m_last = m.write_list_header(2, m_last, len(meta.encodings),
                                     _CT_I32)
        for e in meta.encodings:
            m.zigzag(e)
        m_last = m.write_list_header(3, m_last, 1, _CT_BINARY)
        m.varint(len(meta.name.encode()))
        m.buf += meta.name.encode()
        m_last = m.write_i(4, m_last, meta.codec, _CT_I32)
        m_last = m.write_i(5, m_last, meta.n, _CT_I64)   # num_values
        m_last = m.write_i(6, m_last, meta.usize, _CT_I64)
        m_last = m.write_i(7, m_last, meta.csize, _CT_I64)
        m_last = m.write_i(9, m_last, base + meta.data_off, _CT_I64)
        if meta.dict_off is not None:
            # field 11: dictionary_page_offset (10 is index_page_offset)
            m_last = m.write_i(11, m_last, base, _CT_I64)
        cc.buf += m.buf
        cc.buf.append(0)        # end meta_data struct
        rg.buf += cc.buf
        rg.buf.append(0)        # end column chunk struct
        total += meta.csize
    g_last = rg.write_i(2, g_last, total, _CT_I64)       # total_byte_size
    g_last = rg.write_i(3, g_last, n, _CT_I64)           # num_rows
    tw.buf += rg.buf
    tw.stop()                                            # end row group
    last = tw.write_bin(6, last, b"spark-rapids-tpu device encoder")
    tw.stop()                                            # end FileMetaData

    footer = bytes(tw.buf)
    out += footer
    out += struct.pack("<I", len(footer))
    out += b"PAR1"
    with open(path, "wb") as fh:
        fh.write(out)
    return {"bytes": len(out), "columns": len(chunk_metas)}
