"""File writers — Parquet/ORC/CSV output with dynamic partitioning.

Reference analogs (SURVEY.md §2.6 Writers): ColumnarOutputWriter,
GpuParquetFileFormat / GpuOrcFileFormat, GpuFileFormatDataWriter and
GpuDynamicPartitionDataConcurrentWriter: device batches are encoded and
written without a row-by-row pass; dynamic partitioning splits each batch by
the partition-column values and appends to per-partition files;
``spark.sql.files.maxRecordsPerFile`` rolls files over.

TPU adaptation: the encode step is pyarrow (host) after a device->host
columnar copy; partition splitting happens device-side (one compaction per
partition value) before the host copy, mirroring how the reference slices
batches on device before writing.

Commit protocol (ISSUE 5, GpuFileFormatDataWriter / Spark task-commit
analog): every part file is written into a ``_temporary/<query-uuid>``
staging dir under the output path and atomically renamed into place on
commit (optionally fsync'd — files, partition dirs, and the ``_SUCCESS``
marker — via ``spark.rapids.tpu.files.fsyncOnCommit``); ``_SUCCESS`` is
written only after every rename landed.  Overwrite mode deletes the OLD
output at commit time, not before the write, so any failure or cancel
BEFORE commit leaves the previous data intact (the commit's own
clear+rename pass keeps Spark's residual non-atomic window — a
disk-full mid-commit can still mix old and new).  Failure or a
CancelToken trip
deletes the staging dir — registered both in the writer's own unwind path
and as a lifecycle cleanup hook — so readers can never observe partial
output.  Staging dirs are tracked process-wide; a leftover one fails the
owning test through the conftest leak gate.
"""
from __future__ import annotations

import os
import shutil
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.config import conf
from spark_rapids_tpu.exec.base import TpuExec

MAX_RECORDS_PER_FILE = conf("spark.sql.files.maxRecordsPerFile").doc(
    "Roll output files over after this many records (0 = unlimited)."
).long_conf(0)

PARQUET_WRITE_COMPRESSION = conf(
    "spark.sql.parquet.compression.codec").doc(
    "Parquet write codec: snappy, zstd, gzip, none.").string_conf("snappy")

_EXT = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv", "json": ".json"}

TEMP_DIR_NAME = "_temporary"

# process-wide registry of live (uncommitted, unaborted) staging dirs —
# the conftest leak gate reads it through lifecycle.leak_report_all
_STAGING_LOCK = threading.Lock()
_LIVE_STAGING: set = set()


def staging_leak_report() -> List[str]:
    with _STAGING_LOCK:
        dirs = sorted(_LIVE_STAGING)
    return [f"LEAK: writer staging dir never committed/aborted: {d}"
            for d in dirs if os.path.isdir(d)]


def reset_leaked_staging() -> None:
    """Remove leftover staging dirs (leak-gate recovery path)."""
    with _STAGING_LOCK:
        dirs = list(_LIVE_STAGING)
        _LIVE_STAGING.clear()
    for d in dirs:
        shutil.rmtree(d, ignore_errors=True)
        _prune_temp_root(os.path.dirname(d))


def _prune_temp_root(temp_root: str) -> None:
    """Drop the _temporary parent once its last staging dir is gone."""
    try:
        if os.path.basename(temp_root) == TEMP_DIR_NAME \
                and not os.listdir(temp_root):
            os.rmdir(temp_root)
    except OSError:
        pass


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class TaskCommit:
    """One write's staging/commit lifecycle (Spark's
    FileCommitProtocol task-commit analog, single-task form).

    Files are written under ``<out>/_temporary/<query-uuid>/<reldir>``;
    :meth:`commit` renames each into ``<out>/<reldir>`` (atomic per
    file — readers see a part file fully or not at all, and ``_SUCCESS``
    only after all of them), :meth:`abort` deletes the staging dir.
    Both are idempotent; abort is also registered as a lifecycle cleanup
    hook so a CancelToken trip mid-write cleans up even if the writer's
    own unwind path never runs."""

    def __init__(self, final_dir: str):
        from spark_rapids_tpu.lifecycle.context import current

        self.final = final_dir
        ctx = current()
        qid = f"{ctx.query_id}-" if ctx is not None else ""
        self.staging = os.path.join(
            final_dir, TEMP_DIR_NAME, f"{qid}{uuid.uuid4().hex[:12]}")
        os.makedirs(self.staging)
        self._done = False
        with _STAGING_LOCK:
            _LIVE_STAGING.add(self.staging)
        if ctx is not None:
            ctx.add_cleanup(self.abort)

    def stage_dir(self, reldir: str = "") -> str:
        d = os.path.join(self.staging, reldir) if reldir else self.staging
        os.makedirs(d, exist_ok=True)
        return d

    def commit(self, fsync: bool = False,
               clear_existing: bool = False) -> List[str]:
        """Atomically publish every staged file; returns final paths.

        ``clear_existing`` implements overwrite semantics HERE rather
        than before the write started: a failed or cancelled overwrite
        leaves the OLD data intact (only a successful write replaces
        it)."""
        if self._done:
            return []
        if clear_existing:
            for entry in os.listdir(self.final):
                if entry == TEMP_DIR_NAME:
                    continue
                full = os.path.join(self.final, entry)
                if os.path.isdir(full):
                    shutil.rmtree(full)
                else:
                    os.remove(full)
        moved: List[str] = []
        dest_dirs = []
        for root, _dirs, files in os.walk(self.staging):
            rel = os.path.relpath(root, self.staging)
            dest_dir = (self.final if rel == "."
                        else os.path.join(self.final, rel))
            os.makedirs(dest_dir, exist_ok=True)
            dest_dirs.append(dest_dir)
            for fn in files:
                src = os.path.join(root, fn)
                if fsync:
                    _fsync_file(src)
                dst = os.path.join(dest_dir, fn)
                os.replace(src, dst)
                moved.append(dst)
        # _SUCCESS is the commit marker: written only after every part
        # file landed (Spark parity — and the reader-visible guarantee)
        success = os.path.join(self.final, "_SUCCESS")
        open(success, "w").close()
        if fsync:
            # durability covers the rename targets too: every directory
            # a part file landed in (partition subdirs included), the
            # commit marker, and the output root
            _fsync_file(success)
            for d in dict.fromkeys(dest_dirs + [self.final]):
                _fsync_file(d)
        self._finish()
        return moved

    def abort(self) -> None:
        if self._done:
            return
        shutil.rmtree(self.staging, ignore_errors=True)
        self._finish()

    def _finish(self) -> None:
        self._done = True
        with _STAGING_LOCK:
            _LIVE_STAGING.discard(self.staging)
        shutil.rmtree(self.staging, ignore_errors=True)
        _prune_temp_root(os.path.dirname(self.staging))


def _hive_part_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    import datetime

    if isinstance(v, bool):
        return str(v).lower()
    if isinstance(v, (datetime.date, datetime.datetime)):
        return str(v)
    s = str(v)
    # minimal escaping of path-hostile chars (Spark escapes a larger set)
    for ch, esc in (("/", "%2F"), (":", "%3A"), ("=", "%3D"), (" ", "%20")):
        s = s.replace(ch, esc)
    return s


def write_arrow_table(tbl, fmt: str, directory: str, basename: str,
                      compression: str = "snappy") -> str:
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, basename + _EXT[fmt])
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(tbl, path,
                       compression=None if compression == "none"
                       else compression)
    elif fmt == "orc":
        import pyarrow.orc as paorc

        paorc.write_table(tbl, path)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(tbl, path)
    elif fmt == "json":
        import json as _json

        rows = tbl.to_pylist()
        with open(path, "w") as f:
            for r in rows:
                f.write(_json.dumps(r, default=str) + "\n")
    else:
        raise NotImplementedError(fmt)
    return path


class _FileRoller:
    """Applies maxRecordsPerFile + emits sequential part files."""

    def __init__(self, fmt: str, directory: str, task_id: int,
                 max_records: int, compression: str):
        self.fmt = fmt
        self.directory = directory
        self.task_id = task_id
        self.max_records = max_records
        self.compression = compression
        self.seq = 0
        self.files: List[str] = []

    def write(self, tbl) -> None:
        import pyarrow as pa

        chunks = [tbl]
        if self.max_records and tbl.num_rows > self.max_records:
            chunks = [tbl.slice(i, self.max_records)
                      for i in range(0, tbl.num_rows, self.max_records)]
        for c in chunks:
            base = (f"part-{self.task_id:05d}-{self.seq:04d}-"
                    f"{uuid.uuid4().hex[:12]}")
            self.files.append(write_arrow_table(
                c, self.fmt, self.directory, base, self.compression))
            self.seq += 1


def _take_host(h: HostColumn, idx) -> HostColumn:
    """Row selection on a host column (flat/string kinds — the device
    -encode schemas)."""
    if h.chars is not None:
        return HostColumn(h.dtype, h.validity[idx], chars=h.chars[idx],
                          lengths=h.lengths[idx])
    return HostColumn(h.dtype, h.validity[idx], data=h.data[idx])


def batch_to_arrow(batch: ColumnarBatch):
    import pyarrow as pa

    host = batch.to_host_columns()
    arrays = [h.to_arrow() for h in host]
    names = batch.schema.field_names()
    return pa.table(dict(zip(names, arrays)))


class TpuDataWritingCommandExec(TpuExec):
    """GpuFileFormatDataWriter analog: consumes the child's device batches
    and writes them; dynamic partitioning splits on device first."""

    EXTRA_METRICS = {"writeTime": "MODERATE"}

    def __init__(self, fmt: str, path: str, partition_cols: List[str],
                 child: TpuExec, tpu_conf, mode: str = "overwrite"):
        super().__init__([child])
        self.fmt = fmt
        self.path = path
        self.partition_cols = partition_cols
        self.conf = tpu_conf
        self.mode = mode

    @property
    def output(self):
        return T.StructType([])

    def describe(self):
        p = f" partitionBy={self.partition_cols}" if self.partition_cols else ""
        return f"TpuDataWritingCommand {self.fmt} {self.path}{p}"

    def execute_columnar(self):
        self.run_write()
        return iter(())

    def _device_encode_on(self) -> bool:
        from spark_rapids_tpu.config import PARQUET_DEVICE_ENCODE
        from spark_rapids_tpu.io.parquet_encode import supported_schema

        if self.fmt != "parquet" \
                or not self.conf.get(PARQUET_DEVICE_ENCODE):
            return False
        out_fields = [f for f in self.children[0].output.fields
                      if f.name not in self.partition_cols]
        if not supported_schema(T.StructType(out_fields)):
            return False
        return self.conf.get(PARQUET_WRITE_COMPRESSION) in ("snappy",
                                                            "none")

    def run_write(self) -> None:
        from spark_rapids_tpu.config import FSYNC_ON_COMMIT

        # overwrite deletes the OLD output at COMMIT time (TaskCommit
        # clear_existing), not here: a failed/cancelled overwrite must
        # leave the previous data intact, never an emptied directory
        os.makedirs(self.path, exist_ok=True)
        max_records = self.conf.get(MAX_RECORDS_PER_FILE)
        compression = self.conf.get(PARQUET_WRITE_COMPRESSION)
        device_encode = self._device_encode_on()
        commit = TaskCommit(self.path)
        rollers: Dict[str, _FileRoller] = {}
        seqs: Dict[str, int] = {}
        try:
            for task_id, batch in enumerate(
                    self.children[0].execute_columnar()):
                with self.metric("writeTime").timed():
                    if device_encode:
                        from spark_rapids_tpu.io.parquet_encode import (
                            write_parquet_device,
                        )

                        for reldir, schema, cols, nrows in \
                                self._split_batch_host(batch, max_records):
                            directory = commit.stage_dir(reldir)
                            seq = seqs.get(reldir, 0)
                            seqs[reldir] = seq + 1
                            base = (f"part-{task_id:05d}-{seq:04d}-"
                                    f"{uuid.uuid4().hex[:12]}.parquet")
                            write_parquet_device(
                                os.path.join(directory, base), schema,
                                cols, nrows, compression)
                        continue
                    for reldir, tbl in self._split_batch(batch):
                        roller = rollers.get(reldir)
                        if roller is None:
                            # rolled (maxRecordsPerFile) part files stage
                            # under the same commit protocol as everything
                            # else — no direct-to-destination writes left
                            roller = rollers[reldir] = _FileRoller(
                                self.fmt, commit.stage_dir(reldir),
                                task_id, max_records, compression)
                        roller.write(tbl)
            # empty input still commits: directory + _SUCCESS (Spark
            # parity); the rename pass is then a no-op
            commit.commit(fsync=bool(self.conf.get(FSYNC_ON_COMMIT)),
                          clear_existing=(self.mode == "overwrite"))
        except BaseException:
            # failure or CancelToken trip: readers must never observe
            # partial output (the lifecycle cleanup hook is the backstop
            # when this frame never unwinds)
            commit.abort()
            raise
        self.metrics["numOutputRows"]  # touch for metric presence

    def _split_batch_host(self, batch: ColumnarBatch, max_records: int):
        """Device-encode path: yield (reldir, schema, host columns, n)
        per partition (and per maxRecordsPerFile roll)."""
        import numpy as np

        names = batch.schema.field_names()
        host = batch.to_host_columns()
        n = batch.num_rows

        def rolls(reldir, schema, cols, nrows):
            if max_records and nrows > max_records:
                for s in range(0, nrows, max_records):
                    ln = min(max_records, nrows - s)
                    yield (reldir, schema,
                           [c.slice_rows(s, s + ln) for c in cols], ln)
            else:
                yield reldir, schema, cols, nrows

        if not self.partition_cols:
            schema = T.StructType(list(batch.schema.fields))
            yield from rolls("", schema,
                             [h.slice_rows(0, n) for h in host], n)
            return
        pidx = [names.index(c) for c in self.partition_cols]
        didx = [i for i in range(len(names)) if i not in pidx]
        schema = T.StructType([batch.schema.fields[i] for i in didx])
        part_vals = [host[i].to_pylist()[:n] for i in pidx]
        keys = list(zip(*part_vals))
        uniq = sorted(set(keys), key=lambda t: tuple(str(x) for x in t))
        keys_arr = np.array([str(k) for k in keys])
        for u in uniq:
            mask = keys_arr == str(u)
            idx = np.nonzero(mask)[0]
            cols = [_take_host(host[i], idx) for i in didx]
            reldir = "/".join(
                f"{c}={_hive_part_value(v)}"
                for c, v in zip(self.partition_cols, u))
            yield from rolls(reldir, schema, cols, len(idx))

    def _split_batch(self, batch: ColumnarBatch):
        """Yield (relative_partition_dir, arrow_table_without_part_cols)."""
        if not self.partition_cols:
            yield "", batch_to_arrow(batch)
            return
        import numpy as np

        names = batch.schema.field_names()
        pidx = [names.index(c) for c in self.partition_cols]
        didx = [i for i in range(len(names)) if i not in pidx]
        host = batch.to_host_columns()
        part_vals = [host[i].to_pylist() for i in pidx]
        tbl = batch_to_arrow(batch)
        keys = list(zip(*part_vals)) if part_vals else []
        uniq = sorted(set(keys), key=lambda t: tuple(str(x) for x in t))
        keys_arr = np.array([str(k) for k in keys])
        for u in uniq:
            mask = keys_arr == str(u)
            sub = tbl.filter(mask).select([names[i] for i in didx])
            reldir = "/".join(
                f"{c}={_hive_part_value(v)}"
                for c, v in zip(self.partition_cols, u))
            yield reldir, sub


def cpu_write(plan, ansi: bool) -> None:
    """CPU-oracle write path (the differential baseline for write tests)."""
    import pyarrow as pa

    from spark_rapids_tpu.cpu.oracle import execute_cpu_plan

    child = plan.children[0]
    cols, n = execute_cpu_plan(child, ansi)
    arrays = [c.to_host().to_arrow() for c in cols]
    names = child.output.field_names()
    tbl = pa.table(dict(zip(names, arrays)))
    os.makedirs(plan.path, exist_ok=True)
    # the oracle write runs the SAME staging/commit protocol: the
    # differential write tests must compare like with like, and a failed
    # oracle write must not leave partial output either
    commit = TaskCommit(plan.path)
    try:
        if plan.partition_cols:
            import numpy as np

            pidx = [names.index(c) for c in plan.partition_cols]
            didx = [i for i in range(len(names)) if i not in pidx]
            part_vals = [tbl.column(names[i]).to_pylist() for i in pidx]
            keys = list(zip(*part_vals))
            uniq = sorted(set(keys), key=lambda t: tuple(str(x) for x in t))
            keys_arr = np.array([str(k) for k in keys])
            for u in uniq:
                mask = keys_arr == str(u)
                sub = tbl.filter(mask).select([names[i] for i in didx])
                reldir = "/".join(f"{c}={_hive_part_value(v)}"
                                  for c, v in zip(plan.partition_cols, u))
                base = f"part-00000-0000-{uuid.uuid4().hex[:12]}"
                write_arrow_table(sub, plan.fmt, commit.stage_dir(reldir),
                                  base)
        else:
            base = f"part-00000-0000-{uuid.uuid4().hex[:12]}"
            write_arrow_table(tbl, plan.fmt, commit.stage_dir(), base)
        from spark_rapids_tpu.config import FSYNC_ON_COMMIT, get_conf

        commit.commit(fsync=bool(get_conf().get(FSYNC_ON_COMMIT)),
                      clear_existing=(plan.mode == "overwrite"))
    except BaseException:
        commit.abort()
        raise
