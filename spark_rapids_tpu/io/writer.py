"""File writers — Parquet/ORC/CSV output with dynamic partitioning.

Reference analogs (SURVEY.md §2.6 Writers): ColumnarOutputWriter,
GpuParquetFileFormat / GpuOrcFileFormat, GpuFileFormatDataWriter and
GpuDynamicPartitionDataConcurrentWriter: device batches are encoded and
written without a row-by-row pass; dynamic partitioning splits each batch by
the partition-column values and appends to per-partition files;
``spark.sql.files.maxRecordsPerFile`` rolls files over.

TPU adaptation: the encode step is pyarrow (host) after a device->host
columnar copy; partition splitting happens device-side (one compaction per
partition value) before the host copy, mirroring how the reference slices
batches on device before writing.
"""
from __future__ import annotations

import os
import uuid
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.config import conf
from spark_rapids_tpu.exec.base import TpuExec

MAX_RECORDS_PER_FILE = conf("spark.sql.files.maxRecordsPerFile").doc(
    "Roll output files over after this many records (0 = unlimited)."
).long_conf(0)

PARQUET_WRITE_COMPRESSION = conf(
    "spark.sql.parquet.compression.codec").doc(
    "Parquet write codec: snappy, zstd, gzip, none.").string_conf("snappy")

_EXT = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv", "json": ".json"}


def _hive_part_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    import datetime

    if isinstance(v, bool):
        return str(v).lower()
    if isinstance(v, (datetime.date, datetime.datetime)):
        return str(v)
    s = str(v)
    # minimal escaping of path-hostile chars (Spark escapes a larger set)
    for ch, esc in (("/", "%2F"), (":", "%3A"), ("=", "%3D"), (" ", "%20")):
        s = s.replace(ch, esc)
    return s


def write_arrow_table(tbl, fmt: str, directory: str, basename: str,
                      compression: str = "snappy") -> str:
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, basename + _EXT[fmt])
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(tbl, path,
                       compression=None if compression == "none"
                       else compression)
    elif fmt == "orc":
        import pyarrow.orc as paorc

        paorc.write_table(tbl, path)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(tbl, path)
    elif fmt == "json":
        import json as _json

        rows = tbl.to_pylist()
        with open(path, "w") as f:
            for r in rows:
                f.write(_json.dumps(r, default=str) + "\n")
    else:
        raise NotImplementedError(fmt)
    return path


class _FileRoller:
    """Applies maxRecordsPerFile + emits sequential part files."""

    def __init__(self, fmt: str, directory: str, task_id: int,
                 max_records: int, compression: str):
        self.fmt = fmt
        self.directory = directory
        self.task_id = task_id
        self.max_records = max_records
        self.compression = compression
        self.seq = 0
        self.files: List[str] = []

    def write(self, tbl) -> None:
        import pyarrow as pa

        chunks = [tbl]
        if self.max_records and tbl.num_rows > self.max_records:
            chunks = [tbl.slice(i, self.max_records)
                      for i in range(0, tbl.num_rows, self.max_records)]
        for c in chunks:
            base = (f"part-{self.task_id:05d}-{self.seq:04d}-"
                    f"{uuid.uuid4().hex[:12]}")
            self.files.append(write_arrow_table(
                c, self.fmt, self.directory, base, self.compression))
            self.seq += 1


def _take_host(h: HostColumn, idx) -> HostColumn:
    """Row selection on a host column (flat/string kinds — the device
    -encode schemas)."""
    if h.chars is not None:
        return HostColumn(h.dtype, h.validity[idx], chars=h.chars[idx],
                          lengths=h.lengths[idx])
    return HostColumn(h.dtype, h.validity[idx], data=h.data[idx])


def batch_to_arrow(batch: ColumnarBatch):
    import pyarrow as pa

    host = batch.to_host_columns()
    arrays = [h.to_arrow() for h in host]
    names = batch.schema.field_names()
    return pa.table(dict(zip(names, arrays)))


class TpuDataWritingCommandExec(TpuExec):
    """GpuFileFormatDataWriter analog: consumes the child's device batches
    and writes them; dynamic partitioning splits on device first."""

    EXTRA_METRICS = {"writeTime": "MODERATE"}

    def __init__(self, fmt: str, path: str, partition_cols: List[str],
                 child: TpuExec, tpu_conf, mode: str = "overwrite"):
        super().__init__([child])
        self.fmt = fmt
        self.path = path
        self.partition_cols = partition_cols
        self.conf = tpu_conf
        self.mode = mode

    @property
    def output(self):
        return T.StructType([])

    def describe(self):
        p = f" partitionBy={self.partition_cols}" if self.partition_cols else ""
        return f"TpuDataWritingCommand {self.fmt} {self.path}{p}"

    def execute_columnar(self):
        self.run_write()
        return iter(())

    def _device_encode_on(self) -> bool:
        from spark_rapids_tpu.config import PARQUET_DEVICE_ENCODE
        from spark_rapids_tpu.io.parquet_encode import supported_schema

        if self.fmt != "parquet" \
                or not self.conf.get(PARQUET_DEVICE_ENCODE):
            return False
        out_fields = [f for f in self.children[0].output.fields
                      if f.name not in self.partition_cols]
        if not supported_schema(T.StructType(out_fields)):
            return False
        return self.conf.get(PARQUET_WRITE_COMPRESSION) in ("snappy",
                                                            "none")

    def run_write(self) -> None:
        import shutil

        if self.mode == "overwrite" and os.path.exists(self.path):
            shutil.rmtree(self.path)
        os.makedirs(self.path, exist_ok=True)
        max_records = self.conf.get(MAX_RECORDS_PER_FILE)
        compression = self.conf.get(PARQUET_WRITE_COMPRESSION)
        device_encode = self._device_encode_on()
        rollers: Dict[str, _FileRoller] = {}
        seqs: Dict[str, int] = {}
        names = None
        for task_id, batch in enumerate(
                self.children[0].execute_columnar()):
            names = batch.schema.field_names()
            with self.metric("writeTime").timed():
                if device_encode:
                    from spark_rapids_tpu.io.parquet_encode import (
                        write_parquet_device,
                    )

                    for reldir, schema, cols, nrows in \
                            self._split_batch_host(batch, max_records):
                        directory = os.path.join(self.path, reldir) \
                            if reldir else self.path
                        os.makedirs(directory, exist_ok=True)
                        seq = seqs.get(reldir, 0)
                        seqs[reldir] = seq + 1
                        base = (f"part-{task_id:05d}-{seq:04d}-"
                                f"{uuid.uuid4().hex[:12]}.parquet")
                        write_parquet_device(
                            os.path.join(directory, base), schema, cols,
                            nrows, compression)
                    continue
                for reldir, tbl in self._split_batch(batch):
                    directory = os.path.join(self.path, reldir) \
                        if reldir else self.path
                    roller = rollers.get(reldir)
                    if roller is None:
                        roller = rollers[reldir] = _FileRoller(
                            self.fmt, directory, task_id, max_records,
                            compression)
                    roller.write(tbl)
        # empty input: still create the directory + _SUCCESS (Spark parity)
        open(os.path.join(self.path, "_SUCCESS"), "w").close()
        self.metrics["numOutputRows"]  # touch for metric presence

    def _split_batch_host(self, batch: ColumnarBatch, max_records: int):
        """Device-encode path: yield (reldir, schema, host columns, n)
        per partition (and per maxRecordsPerFile roll)."""
        import numpy as np

        names = batch.schema.field_names()
        host = batch.to_host_columns()
        n = batch.num_rows

        def rolls(reldir, schema, cols, nrows):
            if max_records and nrows > max_records:
                for s in range(0, nrows, max_records):
                    ln = min(max_records, nrows - s)
                    yield (reldir, schema,
                           [c.slice_rows(s, s + ln) for c in cols], ln)
            else:
                yield reldir, schema, cols, nrows

        if not self.partition_cols:
            schema = T.StructType(list(batch.schema.fields))
            yield from rolls("", schema,
                             [h.slice_rows(0, n) for h in host], n)
            return
        pidx = [names.index(c) for c in self.partition_cols]
        didx = [i for i in range(len(names)) if i not in pidx]
        schema = T.StructType([batch.schema.fields[i] for i in didx])
        part_vals = [host[i].to_pylist()[:n] for i in pidx]
        keys = list(zip(*part_vals))
        uniq = sorted(set(keys), key=lambda t: tuple(str(x) for x in t))
        keys_arr = np.array([str(k) for k in keys])
        for u in uniq:
            mask = keys_arr == str(u)
            idx = np.nonzero(mask)[0]
            cols = [_take_host(host[i], idx) for i in didx]
            reldir = "/".join(
                f"{c}={_hive_part_value(v)}"
                for c, v in zip(self.partition_cols, u))
            yield from rolls(reldir, schema, cols, len(idx))

    def _split_batch(self, batch: ColumnarBatch):
        """Yield (relative_partition_dir, arrow_table_without_part_cols)."""
        if not self.partition_cols:
            yield "", batch_to_arrow(batch)
            return
        import numpy as np

        names = batch.schema.field_names()
        pidx = [names.index(c) for c in self.partition_cols]
        didx = [i for i in range(len(names)) if i not in pidx]
        host = batch.to_host_columns()
        part_vals = [host[i].to_pylist() for i in pidx]
        tbl = batch_to_arrow(batch)
        keys = list(zip(*part_vals)) if part_vals else []
        uniq = sorted(set(keys), key=lambda t: tuple(str(x) for x in t))
        keys_arr = np.array([str(k) for k in keys])
        for u in uniq:
            mask = keys_arr == str(u)
            sub = tbl.filter(mask).select([names[i] for i in didx])
            reldir = "/".join(
                f"{c}={_hive_part_value(v)}"
                for c, v in zip(self.partition_cols, u))
            yield reldir, sub


def cpu_write(plan, ansi: bool) -> None:
    """CPU-oracle write path (the differential baseline for write tests)."""
    import pyarrow as pa

    from spark_rapids_tpu.cpu.oracle import execute_cpu_plan

    child = plan.children[0]
    cols, n = execute_cpu_plan(child, ansi)
    arrays = [c.to_host().to_arrow() for c in cols]
    names = child.output.field_names()
    tbl = pa.table(dict(zip(names, arrays)))
    import shutil

    if plan.mode == "overwrite" and os.path.exists(plan.path):
        shutil.rmtree(plan.path)
    os.makedirs(plan.path, exist_ok=True)
    writer = TpuDataWritingCommandExec.__new__(TpuDataWritingCommandExec)
    # reuse the partition-splitting logic host-side
    if plan.partition_cols:
        import numpy as np

        pidx = [names.index(c) for c in plan.partition_cols]
        didx = [i for i in range(len(names)) if i not in pidx]
        part_vals = [tbl.column(names[i]).to_pylist() for i in pidx]
        keys = list(zip(*part_vals))
        uniq = sorted(set(keys), key=lambda t: tuple(str(x) for x in t))
        keys_arr = np.array([str(k) for k in keys])
        for u in uniq:
            mask = keys_arr == str(u)
            sub = tbl.filter(mask).select([names[i] for i in didx])
            reldir = "/".join(f"{c}={_hive_part_value(v)}"
                              for c, v in zip(plan.partition_cols, u))
            base = f"part-00000-0000-{uuid.uuid4().hex[:12]}"
            write_arrow_table(sub, plan.fmt,
                              os.path.join(plan.path, reldir), base)
    else:
        base = f"part-00000-0000-{uuid.uuid4().hex[:12]}"
        write_arrow_table(tbl, plan.fmt, plan.path, base)
    open(os.path.join(plan.path, "_SUCCESS"), "w").close()
