"""Pure-python Avro Object Container File reader/writer.

Reference analog: GpuAvroScan (SURVEY.md §2.6 Avro read) — the reference
decodes Avro blocks on the GPU via cuDF.  On TPU, Avro (like CSV/JSON) is a
host-parse format (SURVEY.md §2.10 item 10); no third-party Avro library is
available in the image, so the container format + binary encoding are
implemented here from the Avro 1.11 spec.  This module also powers the
Iceberg manifest reader (manifests are Avro files).

Supported: records of null/boolean/int/long/float/double/bytes/string,
nullable unions ["null", T], arrays of primitives, logicalTypes
date / timestamp-millis / timestamp-micros / decimal(bytes); codecs
null + deflate.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# Binary primitives
# ---------------------------------------------------------------------------

def zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: bytearray, n: int):
    z = zigzag_encode(n) & 0xFFFFFFFFFFFFFFFF
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read_long(self) -> int:
        shift, acc = 0, 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                return zigzag_decode(acc)
            shift += 7

    def read_bytes(self) -> bytes:
        n = self.read_long()
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_fixed(self, n: int) -> bytes:
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out


# ---------------------------------------------------------------------------
# Schema-driven value codec
# ---------------------------------------------------------------------------

def _decode_value(r: _Reader, schema) -> Any:
    if isinstance(schema, list):  # union
        idx = r.read_long()
        return _decode_value(r, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {f["name"]: _decode_value(r, f["type"])
                    for f in schema["fields"]}
        if t == "array":
            out = []
            while True:
                count = r.read_long()
                if count == 0:
                    return out
                if count < 0:
                    r.read_long()  # block byte size (skipped)
                    count = -count
                for _ in range(count):
                    out.append(_decode_value(r, schema["items"]))
        if t == "map":
            out = {}
            while True:
                count = r.read_long()
                if count == 0:
                    return out
                if count < 0:
                    r.read_long()
                    count = -count
                for _ in range(count):
                    k = r.read_bytes().decode("utf-8")
                    out[k] = _decode_value(r, schema["values"])
        if t == "enum":
            return schema["symbols"][r.read_long()]
        if t == "fixed":
            return r.read_fixed(schema["size"])
        return _decode_value(r, t)  # {"type": "int", "logicalType": ...}
    if schema == "null":
        return None
    if schema == "boolean":
        b = r.read_fixed(1)
        return b != b"\x00"
    if schema in ("int", "long"):
        return r.read_long()
    if schema == "float":
        return struct.unpack("<f", r.read_fixed(4))[0]
    if schema == "double":
        return struct.unpack("<d", r.read_fixed(8))[0]
    if schema == "bytes":
        return r.read_bytes()
    if schema == "string":
        return r.read_bytes().decode("utf-8")
    raise ValueError(f"unsupported avro type: {schema!r}")


def _encode_value(buf: bytearray, schema, v):
    if isinstance(schema, list):  # union: pick first matching branch
        for i, branch in enumerate(schema):
            if (v is None) == (branch == "null"):
                write_long(buf, i)
                _encode_value(buf, branch, v)
                return
        raise ValueError(f"no union branch for {v!r} in {schema!r}")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                _encode_value(buf, f["type"], v[f["name"]])
            return
        if t == "array":
            if v:
                write_long(buf, len(v))
                for x in v:
                    _encode_value(buf, schema["items"], x)
            write_long(buf, 0)
            return
        if t == "map":
            if v:
                write_long(buf, len(v))
                for k, x in v.items():
                    kb = k.encode("utf-8")
                    write_long(buf, len(kb))
                    buf.extend(kb)
                    _encode_value(buf, schema["values"], x)
            write_long(buf, 0)
            return
        if t == "enum":
            write_long(buf, schema["symbols"].index(v))
            return
        if t == "fixed":
            buf.extend(v)
            return
        _encode_value(buf, t, v)
        return
    if schema == "null":
        return
    if schema == "boolean":
        buf.append(1 if v else 0)
        return
    if schema in ("int", "long"):
        write_long(buf, int(v))
        return
    if schema == "float":
        buf.extend(struct.pack("<f", v))
        return
    if schema == "double":
        buf.extend(struct.pack("<d", v))
        return
    if schema == "bytes":
        write_long(buf, len(v))
        buf.extend(v)
        return
    if schema == "string":
        b = v.encode("utf-8")
        write_long(buf, len(b))
        buf.extend(b)
        return
    raise ValueError(f"unsupported avro type: {schema!r}")


# ---------------------------------------------------------------------------
# Container files
# ---------------------------------------------------------------------------

def read_avro_file(path: str) -> Tuple[dict, List[dict]]:
    """-> (parsed schema json, records as dicts).  Decode errors carry
    the byte offset reached (io/faults.py quarantine context)."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    r = _Reader(data)
    try:
        return _read_avro_blocks(r, data, path)
    except (IndexError, struct.error, zlib.error, KeyError,
            json.JSONDecodeError) as e:
        err = ValueError(
            f"{path}: corrupt avro container near byte {r.pos} "
            f"({type(e).__name__}: {e})")
        err.srt_offset = r.pos
        raise err from e


def _read_avro_blocks(r: "_Reader", data: bytes,
                      path: str) -> Tuple[dict, List[dict]]:
    r.pos = 4
    meta: Dict[str, bytes] = {}
    while True:
        count = r.read_long()
        if count == 0:
            break
        if count < 0:
            r.read_long()
            count = -count
        for _ in range(count):
            k = r.read_bytes().decode("utf-8")
            meta[k] = r.read_bytes()
    sync = r.read_fixed(16)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    records: List[dict] = []
    while r.pos < len(data):
        count = r.read_long()
        size = r.read_long()
        block = r.read_fixed(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec: {codec}")
        br = _Reader(block)
        for _ in range(count):
            records.append(_decode_value(br, schema))
        marker = r.read_fixed(16)
        if marker != sync:
            raise ValueError(f"{path}: sync marker mismatch")
    return schema, records


def write_avro_file(path: str, schema: dict, records: List[dict],
                    codec: str = "null", sync: Optional[bytes] = None):
    sync = sync or os.urandom(16)
    buf = bytearray(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8")}
    write_long(buf, len(meta))
    for k, v in meta.items():
        kb = k.encode("utf-8")
        write_long(buf, len(kb))
        buf.extend(kb)
        write_long(buf, len(v))
        buf.extend(v)
    write_long(buf, 0)
    buf.extend(sync)
    body = bytearray()
    for rec in records:
        _encode_value(body, schema, rec)
    block = bytes(body)
    if codec == "deflate":
        c = zlib.compressobj(9, zlib.DEFLATED, -15)
        block = c.compress(block) + c.flush()
    elif codec != "null":
        raise ValueError(f"unsupported avro codec: {codec}")
    write_long(buf, len(records))
    write_long(buf, len(block))
    buf.extend(block)
    buf.extend(sync)
    with open(path, "wb") as f:
        f.write(bytes(buf))


# ---------------------------------------------------------------------------
# Schema mapping to the engine's type system
# ---------------------------------------------------------------------------

def avro_schema_to_struct(schema: dict):
    """Avro record schema -> StructType (logicalTypes honored)."""
    from spark_rapids_tpu import types as T

    def field_type(s) -> Tuple[Any, bool]:
        nullable = False
        if isinstance(s, list):
            branches = [b for b in s if b != "null"]
            nullable = len(branches) < len(s)
            if len(branches) != 1:
                raise ValueError(f"unsupported avro union: {s!r}")
            s = branches[0]
        if isinstance(s, dict):
            lt = s.get("logicalType")
            t = s["type"]
            if lt == "date" and t == "int":
                return T.DATE, nullable
            if lt in ("timestamp-micros", "timestamp-millis") and t == "long":
                return T.TIMESTAMP, nullable
            if lt == "decimal":
                return T.DecimalType(s.get("precision", 38),
                                     s.get("scale", 0)), nullable
            if t == "array":
                et, en = field_type(s["items"])
                return T.ArrayType(et, containsNull=en), nullable
            if t == "record":
                inner = avro_schema_to_struct(s)
                return inner, nullable
            s = t
        prim = {"boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG,
                "float": T.FLOAT, "double": T.DOUBLE, "string": T.STRING,
                "bytes": T.BINARY}
        if s in prim:
            return prim[s], nullable
        raise ValueError(f"unsupported avro type: {s!r}")

    fields = []
    for f in schema["fields"]:
        dt, nullable = field_type(f["type"])
        fields.append(T.StructField(f["name"], dt, nullable))
    return T.StructType(fields)


def _convert_cell(v, s):
    """Avro-decoded value -> engine python value for HostColumn."""
    import datetime as _dt
    from decimal import Decimal

    from spark_rapids_tpu import types as T

    if v is None:
        return None
    if isinstance(s, T.DateType):
        return _dt.date(1970, 1, 1) + _dt.timedelta(days=v)
    if isinstance(s, T.DecimalType):
        unscaled = int.from_bytes(v, "big", signed=True)
        return Decimal(unscaled).scaleb(-s.scale)
    return v


def read_avro_columns(path: str, schema_struct=None):
    """Read an Avro file into (HostColumns, StructType).

    Timestamp-millis values are normalized to microseconds."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.column import HostColumn

    avro_schema, records = read_avro_file(path)
    struct = schema_struct or avro_schema_to_struct(avro_schema)
    # detect millis fields for normalization
    millis = set()
    for f in avro_schema["fields"]:
        s = f["type"]
        if isinstance(s, list):
            s = next((b for b in s if b != "null"), None)
        if isinstance(s, dict) and s.get("logicalType") == "timestamp-millis":
            millis.add(f["name"])
    cols = []
    for f in struct.fields:
        vals = []
        for rec in records:
            v = _convert_cell(rec.get(f.name), f.dataType)
            if v is not None and f.name in millis:
                v = v * 1000
            vals.append(v)
        cols.append(HostColumn.from_pylist(vals, f.dataType))
    return cols, struct
