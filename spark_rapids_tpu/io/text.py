"""Spark-strict CSV / JSON-lines parsing.

Reference analog: GpuTextBasedPartitionReader + GpuCSVScan / GpuJsonScan
(SURVEY.md §2.6 CSV/JSON row): the reference reproduces Spark's Univocity/
Jackson parse semantics in cuDF kernels; here the host parse (sanctioned by
SURVEY §2.10 item 10 — "host parse -> device, then incremental Pallas")
reproduces them in one place shared by the device pipeline and the CPU
oracle, with pinned-expectation tests guarding the semantics.

Supported semantics (the PERMISSIVE core):

  * modes: PERMISSIVE (default), DROPMALFORMED, FAILFAST
  * ``columnNameOfCorruptRecord`` (default ``_corrupt_record``): when that
    column appears in the schema, malformed records land there as the raw
    line while successfully-converted fields keep their values (Spark
    PERMISSIVE keeps partial rows)
  * CSV: header/sep/quote options; a record is malformed when its token
    count differs from the schema or any field fails conversion; empty
    tokens (== ``nullValue``, default "") are null
  * CSV field conversion is Spark-strict: integers reject decimals and
    wrap-only values, booleans are true/false (case-insensitive), date/
    timestamp use the cast grammar (expr/cast.py twin _str_to_date_py /
    _str_to_ts_py), decimals HALF_UP-quantize and range-check
  * JSON lines: a record is malformed when the line is not a JSON object;
    missing fields are null; a present field of the wrong JSON type is
    null (numbers render into string fields like Spark's literal-text
    coercion); nested values into scalar fields are null
"""
from __future__ import annotations

import json as _json
import math
from decimal import ROUND_HALF_UP, Decimal, InvalidOperation
from typing import List, Optional

from spark_rapids_tpu import types as T

DEFAULT_CORRUPT_COL = "_corrupt_record"

_I_RANGE = {T.ByteType: (-2**7, 2**7 - 1), T.ShortType: (-2**15, 2**15 - 1),
            T.IntegerType: (-2**31, 2**31 - 1),
            T.LongType: (-2**63, 2**63 - 1)}


class _FieldError(Exception):
    pass


def _convert_csv_field(tok: Optional[str], dt: T.DataType,
                       null_value: str):
    """One CSV token -> python storage value (or None); raises _FieldError
    on a Spark-invalid token."""
    if tok is None or tok == null_value:
        return None
    if isinstance(dt, T.StringType):
        return tok
    if isinstance(dt, T.BooleanType):
        low = tok.strip().lower()
        if low == "true":
            return True
        if low == "false":
            return False
        raise _FieldError(tok)
    s = tok.strip()
    if not s:
        return None
    if dt.is_integral:
        body = s[1:] if s[:1] in "+-" else s
        if not body.isdigit():
            raise _FieldError(tok)
        v = int(s)
        lo, hi = _I_RANGE[type(dt)]
        if not lo <= v <= hi:
            raise _FieldError(tok)
        return v
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        try:
            return float(s)
        except ValueError:
            raise _FieldError(tok)
    if isinstance(dt, T.DecimalType):
        try:
            d = Decimal(s)
            # inf/nan parse as Decimal but quantize raises — malformed
            scaled = int(d.scaleb(dt.scale).quantize(
                Decimal(1), rounding=ROUND_HALF_UP))
        except InvalidOperation:
            raise _FieldError(tok)
        if abs(scaled) >= 10 ** dt.precision:
            raise _FieldError(tok)
        return scaled
    if isinstance(dt, T.DateType):
        from spark_rapids_tpu.cpu.oracle import _str_to_date_py

        days = _str_to_date_py(s)
        if days is None:
            raise _FieldError(tok)
        return days
    if isinstance(dt, T.TimestampType):
        from spark_rapids_tpu.cpu.oracle import _str_to_ts_py

        micros = _str_to_ts_py(s)
        if micros is None:
            raise _FieldError(tok)
        return micros
    raise _FieldError(f"unsupported CSV type {dt.simpleString}")


def _finish(rows, schema: T.StructType):
    """rows: list of per-field python value lists -> HostColumns.

    Decimal fields hold SCALED int64 values here (the converters return
    unscaled-integer representation); from_pylist expects true numeric
    values and rescales, so wrap them back into exact Decimals first —
    round-4 differential fuzzing caught the double-scaling."""
    from spark_rapids_tpu.columnar.column import HostColumn

    cols = []
    for i, f in enumerate(schema.fields):
        vals = [r[i] for r in rows]
        if isinstance(f.dataType, T.DecimalType):
            vals = [None if v is None
                    else Decimal(v).scaleb(-f.dataType.scale)
                    for v in vals]
        cols.append(HostColumn.from_pylist(vals, f.dataType))
    return cols, len(rows)


def _classify_tokens(toks_u, dt: T.DataType, null_value: str):
    """Vectorized Spark-strict classification of one CSV column's tokens.

    Returns (values, validity, uncertain): rows where ``uncertain`` is
    True could not be decided by a vectorized rule (exotic grammar,
    unicode digits, rounding decimals, timestamps...) and must re-run
    through the strict per-row loop — a row the vectorizer does claim
    always agrees with ``_convert_csv_field``.
    """
    import numpy as np

    n = len(toks_u)
    is_null = toks_u == null_value
    uncertain = np.zeros(n, np.bool_)
    if isinstance(dt, T.StringType):
        return toks_u, ~is_null, uncertain
    s = np.char.strip(toks_u)
    if isinstance(dt, T.BooleanType):
        low = np.char.lower(s)
        vals = low == "true"
        known = is_null | vals | (low == "false")
        return vals, ~is_null & known, ~known
    empty = s == ""
    is_null = is_null | empty
    first = s.astype("U1")
    signed = (first == "+") | (first == "-")
    body = np.where(signed, np.char.lstrip(s, "+-"), s)
    slen = np.char.str_len(s)
    blen = np.char.str_len(body)
    clean_sign = slen - blen <= 1     # exactly one sign char was stripped
    _DIGITS = str.maketrans("", "", "0123456789")
    ascii_digits = (np.char.translate(body, _DIGITS) == "") & (body != "")
    if dt.is_integral:
        lo, hi = _I_RANGE[type(dt)]
        cand = ~is_null & ascii_digits & (blen <= 18) & clean_sign
        vals = np.zeros(n, np.int64)
        if cand.any():
            vals[cand] = s[cand].astype(np.int64)
        in_range = (vals >= lo) & (vals <= hi)
        uncertain = ~is_null & ~(cand & in_range)
        return vals, cand & in_range, uncertain
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        _FCHARS = str.maketrans("", "", "0123456789+-.eE")
        cand = ~is_null & (np.char.translate(s, _FCHARS) == "")
        vals = np.zeros(n, np.float64)
        if cand.any():
            try:
                vals[cand] = s[cand].astype(np.float64)
            except ValueError:
                return vals, np.zeros(n, np.bool_), ~is_null
        return vals, cand, ~is_null & ~cand
    if isinstance(dt, T.DecimalType):
        # exact-scale fast case: [sign]digits[.digits] with frac digits
        # <= scale (no HALF_UP rounding) and no int64 overflow possible
        parts = np.char.partition(body, ".")
        intpart, dot, frac = parts[:, 0], parts[:, 1], parts[:, 2]
        digits_only = ((np.char.translate(intpart, _DIGITS) == "")
                       & (np.char.translate(frac, _DIGITS) == ""))
        flen = np.char.str_len(frac)
        ilen = np.char.str_len(intpart)
        cand = (~is_null & digits_only & clean_sign & (ilen + flen > 0)
                & (flen <= dt.scale) & (ilen + dt.scale <= 18)
                & ~((dot == ".") & (flen == 0) & (ilen == 0)))
        vals = np.zeros(n, np.int64)
        if cand.any():
            mant_s = np.char.add(np.where(ilen == 0, "0", intpart), frac)
            mant = np.zeros(n, np.int64)
            mant[cand] = mant_s[cand].astype(np.int64)
            exp = np.minimum(dt.scale - flen, 18)
            scale_up = np.power(10, np.maximum(exp, 0)).astype(np.int64)
            vals = mant * scale_up
            vals = np.where(first == "-", -vals, vals)
        in_range = np.abs(vals) < 10 ** dt.precision
        ok = cand & in_range
        return vals, ok, ~is_null & ~ok
    if isinstance(dt, T.DateType):
        vals = np.zeros(n, np.int64)
        ok = np.zeros(n, np.bool_)
        cand = ~is_null & (slen == 10)
        if cand.any():
            c = np.ascontiguousarray(s[cand].astype("U10"))
            ch = c.view(np.uint32).reshape(-1, 10)
            d0 = ord("0")
            dig = (ch >= d0) & (ch <= d0 + 9)
            shape_ok = (dig[:, [0, 1, 2, 3, 5, 6, 8, 9]].all(axis=1)
                        & (ch[:, 4] == ord("-")) & (ch[:, 7] == ord("-")))
            y = ((ch[:, 0] - d0) * 1000 + (ch[:, 1] - d0) * 100
                 + (ch[:, 2] - d0) * 10 + (ch[:, 3] - d0)).astype(np.int64)
            m = ((ch[:, 5] - d0) * 10 + (ch[:, 6] - d0)).astype(np.int64)
            d = ((ch[:, 8] - d0) * 10 + (ch[:, 9] - d0)).astype(np.int64)
            leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
            dim = np.array([0, 31, 28, 31, 30, 31, 30, 31, 31, 30, 31,
                            30, 31], np.int64)[np.clip(m, 0, 12)]
            dim = np.where((m == 2) & leap, 29, dim)
            valid_ymd = shape_ok & (y >= 1) & (m >= 1) & (m <= 12) \
                & (d >= 1) & (d <= dim)
            # days_from_civil (proleptic Gregorian, epoch 1970-01-01)
            yy = y - (m <= 2)
            era = np.floor_divide(yy, 400)
            yoe = yy - era * 400
            doy = (153 * (m + np.where(m > 2, -3, 9)) + 2) // 5 + d - 1
            doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
            days = era * 146097 + doe - 719468
            idx = np.flatnonzero(cand)
            vals[idx[valid_ymd]] = days[valid_ymd]
            ok[idx[valid_ymd]] = True
        return vals, ok, ~is_null & ~ok
    # timestamps and anything else: strict loop decides
    return np.zeros(n, np.int64), np.zeros(n, np.bool_), ~is_null


def _read_csv_fast(path: str, schema: T.StructType, options: dict):
    """Vectorized CSV fast path (VERDICT r3 Next #5): pyarrow tokenizes
    (quote-aware splitting at C speed), numpy bulk-converts each column
    with Spark-strict semantics, and every row a vectorized rule cannot
    decide re-runs through the strict loop — so results are identical to
    the per-row reference parse below.  Returns None when preconditions
    fail (ragged rows, parse errors, exotic options); the caller then
    uses the strict loop for the whole file."""
    import numpy as np

    try:
        import pyarrow as pa
        import pyarrow.csv as pacsv
    except ImportError:
        return None
    mode = str(options.get("mode", "PERMISSIVE")).upper()
    header = str(options.get("header", "false")).lower() == "true"
    sep = str(options.get("sep", options.get("delimiter", ",")))
    quote = str(options.get("quote", '"')) or '"'
    null_value = str(options.get("nullValue", ""))
    corrupt_col = str(options.get("columnNameOfCorruptRecord",
                                  DEFAULT_CORRUPT_COL))
    if len(sep) != 1 or len(quote) != 1:
        return None
    fields = schema.fields
    data_idx = [i for i, f in enumerate(fields) if f.name != corrupt_col]
    corrupt_idx = next((i for i, f in enumerate(fields)
                        if f.name == corrupt_col), None)
    names = [f"c{j}" for j in range(len(data_idx))]

    def _arrow_type(dt):
        """The arrow type whose CSV parse agrees with Spark wherever it
        SUCCEEDS (probe-verified: every divergence raises ArrowInvalid,
        falling back a tier — it never silently differs).  Booleans are
        excluded (arrow accepts 1/0/True), timestamps too (session-tz
        grammar); both classify from strings instead."""
        if isinstance(dt, T.StringType):
            return pa.string()
        if dt.is_integral:
            return {T.ByteType: pa.int8(), T.ShortType: pa.int16(),
                    T.IntegerType: pa.int32(),
                    T.LongType: pa.int64()}[type(dt)]
        if isinstance(dt, (T.FloatType, T.DoubleType)):
            # FloatType parses as f64 then downcasts — the strict loop's
            # python float() + f32 storage double-rounds identically
            return pa.float64()
        if isinstance(dt, T.DateType):
            return pa.date32()
        if isinstance(dt, T.DecimalType) and not dt.is_128:
            return pa.decimal128(dt.precision, dt.scale)
        return None

    def _read(types_map):
        return pacsv.read_csv(
            path,
            read_options=pacsv.ReadOptions(
                column_names=names, skip_rows=1 if header else 0,
                use_threads=False),
            parse_options=pacsv.ParseOptions(
                delimiter=sep, quote_char=quote),
            convert_options=pacsv.ConvertOptions(
                column_types=types_map,
                null_values=[null_value],
                strings_can_be_null=True))

    typed_map = {}
    typed_cols = set()
    for j, fi in enumerate(data_idx):
        at = _arrow_type(fields[fi].dataType)
        if at is not None:
            typed_map[names[j]] = at
            typed_cols.add(fi)
        else:
            typed_map[names[j]] = pa.string()
    tbl = None
    try:
        tbl = _read(typed_map)
    except (pa.ArrowInvalid, pa.ArrowKeyError, OSError):
        typed_cols = set()
        try:
            # tier 2: tokenize only; numpy classifies, python decides
            # leftovers.  NOTE null_values=[] here — the classifiers see
            # the raw tokens
            tbl = pacsv.read_csv(
                path,
                read_options=pacsv.ReadOptions(
                    column_names=names, skip_rows=1 if header else 0,
                    use_threads=False),
                parse_options=pacsv.ParseOptions(
                    delimiter=sep, quote_char=quote),
                convert_options=pacsv.ConvertOptions(
                    column_types={nm: pa.string() for nm in names},
                    null_values=[], strings_can_be_null=False))
        except (pa.ArrowInvalid, pa.ArrowKeyError, OSError):
            return None  # ragged rows etc: the strict loop owns them
    n = tbl.num_rows
    if n == 0:
        return _finish([], schema)
    from spark_rapids_tpu.columnar.column import HostColumn

    out_vals = {}
    out_valid = {}
    arrow_cols = {}
    uncertain = np.zeros(n, np.bool_)
    for j, fi in enumerate(data_idx):
        col = tbl.column(names[j]).combine_chunks()
        dt = fields[fi].dataType
        if fi in typed_cols:
            if isinstance(dt, T.FloatType):
                validity = np.asarray(col.is_valid())
                vals = np.asarray(col.fill_null(0.0), np.float64).astype(
                    np.float32)
                arrow_cols[fi] = HostColumn(dt, validity, data=vals)
            else:
                hc = HostColumn.from_arrow(col, dt)
                if isinstance(dt, T.DateType) and len(hc.data):
                    lo_days, hi_days = -719162, 2932896  # 0001..9999
                    d_ = hc.data[hc.validity]
                    if len(d_) and (int(d_.min()) < lo_days
                                    or int(d_.max()) > hi_days):
                        return None  # strict loop owns out-of-grammar years
                arrow_cols[fi] = hc
            continue
        # tier-1 reads classify-columns as arrow string with null_values
        # matching; restore the raw token (exactly null_value) so the
        # classifier sees what the strict loop would
        toks_u = np.asarray(col.fill_null(null_value).to_numpy(
            zero_copy_only=False), dtype="U")
        vals, valid, unc = _classify_tokens(toks_u, dt, null_value)
        out_vals[fi] = (vals, toks_u)
        out_valid[fi] = valid
        uncertain |= unc
    malformed = np.zeros(n, np.bool_)
    fb_rows = np.flatnonzero(uncertain)
    fb_out = {}
    if len(fb_rows):
        for r in fb_rows:
            # typed columns already parsed whole-column clean; only the
            # string-classified columns can be uncertain
            row_out = [None] * len(fields)
            bad = False
            for j, fi in enumerate(data_idx):
                if fi not in out_vals:
                    continue
                tok = str(out_vals[fi][1][r])
                try:
                    row_out[fi] = _convert_csv_field(
                        tok, fields[fi].dataType, null_value)
                except _FieldError:
                    bad = True
            fb_out[int(r)] = row_out
            malformed[r] = bad
    raw_lines = None
    if malformed.any() and (mode == "FAILFAST" or mode == "PERMISSIVE"
                            and corrupt_idx is not None):
        with open(path, "rb") as fh:
            data = fh.read()
        if quote.encode() in data:
            return None  # raw-record mapping unsafe with quoting: strict
        lines = [ln.rstrip(b"\r").decode("utf-8", "replace")
                 for ln in data.split(b"\n")]
        lines = [ln for ln in lines[(1 if header else 0):] if ln != ""]
        if len(lines) != n:
            return None
        raw_lines = lines
        if mode == "FAILFAST":
            r = int(np.flatnonzero(malformed)[0])
            raise RuntimeError(
                f"Malformed CSV record (FAILFAST): {raw_lines[r]!r}")
    keep = ~malformed if mode == "DROPMALFORMED" else np.ones(n, np.bool_)
    from spark_rapids_tpu.columnar.column import HostColumn

    cols = []
    for fi, f in enumerate(fields):
        if fi == corrupt_idx:
            vals = [None] * n
            if raw_lines is not None:
                for r in np.flatnonzero(malformed):
                    vals[int(r)] = raw_lines[int(r)]
            cols.append(HostColumn.from_pylist(
                [v for v, k in zip(vals, keep) if k], f.dataType))
            continue
        dt = f.dataType
        if fi in arrow_cols:
            hc = arrow_cols[fi]
            if bool(keep.all()):
                cols.append(hc)
            elif hc.chars is not None:
                cols.append(HostColumn(dt, hc.validity[keep],
                                       chars=hc.chars[keep],
                                       lengths=hc.lengths[keep]))
            else:
                cols.append(HostColumn(dt, hc.validity[keep],
                                       data=hc.data[keep]))
            continue
        vals, toks_u = out_vals[fi]
        valid = out_valid[fi]
        if isinstance(dt, T.StringType):
            py = [str(t) if v else None for t, v in zip(toks_u, valid)]
            for r, row_out in fb_out.items():
                py[r] = row_out[fi]
            cols.append(HostColumn.from_pylist(
                [v for v, k in zip(py, keep) if k], dt))
            continue
        sd = T.storage_dtype(dt)
        arr = vals.astype(sd)
        validity = valid.copy()
        for r, row_out in fb_out.items():
            v = row_out[fi]
            if v is None:
                validity[r] = False
            else:
                arr[r] = np.asarray(v).astype(sd)
                validity[r] = True
        cols.append(HostColumn.from_numpy(arr[keep], dt, validity[keep]))
    return cols, int(keep.sum())


def read_csv_spark(path: str, schema: T.StructType, options: dict):
    """Spark-semantic CSV read -> (HostColumns, row count).  Escaping
    errors are annotated with ``file=<path>`` (io/faults.py) — FAILFAST
    parse errors keep their type (PROPAGATE semantics), unreadable bytes
    classify as corrupt at the scan layer."""
    from spark_rapids_tpu.io.faults import file_context

    with file_context(path, "csv", "host"):
        return _read_csv_spark(path, schema, options)


def _read_csv_spark(path: str, schema: T.StructType, options: dict):
    import csv as _csv

    if str(options.get("tpuFastParse", "true")).lower() != "false":
        try:
            fast = _read_csv_fast(path, schema, options)
        except RuntimeError:
            raise       # FAILFAST surfaced by the fast path
        except Exception as e:
            from spark_rapids_tpu.resilience import classify as _CL

            if _CL.classify_failure(e) == _CL.PROPAGATE:
                # QueryCancelled / deadline / ANSI errors are the
                # query's correct observable behavior — retrying the
                # strict loop would swallow a cancellation (ISSUE 9)
                raise
            fast = None  # any fast-path surprise: the strict loop decides
        if fast is not None:
            return fast

    mode = str(options.get("mode", "PERMISSIVE")).upper()
    header = str(options.get("header", "false")).lower() == "true"
    sep = str(options.get("sep", options.get("delimiter", ",")))
    quote = str(options.get("quote", '"')) or '"'
    null_value = str(options.get("nullValue", ""))
    corrupt_col = str(options.get("columnNameOfCorruptRecord",
                                  DEFAULT_CORRUPT_COL))
    fields = schema.fields
    data_idx = [i for i, f in enumerate(fields) if f.name != corrupt_col]
    corrupt_idx = next((i for i, f in enumerate(fields)
                        if f.name == corrupt_col), None)
    rows = []

    class _RawTee:
        """Line iterator that records what csv.reader consumed, so the
        corrupt column stores the RAW record (quoting intact), not a
        re-join of the parsed tokens."""

        def __init__(self, fh):
            self.fh = fh
            self.buf = []

        def __iter__(self):
            return self

        def __next__(self):
            line = next(self.fh)
            self.buf.append(line)
            return line

        def take_raw(self):
            raw = "".join(self.buf).rstrip("\r\n")
            self.buf = []
            return raw

    with open(path, "r", encoding="utf-8", newline="") as fh:
        tee = _RawTee(fh)
        reader = _csv.reader(tee, delimiter=sep, quotechar=quote)
        for li, toks in enumerate(reader):
            raw = tee.take_raw()
            if header and li == 0:
                continue
            if not toks:
                continue  # Spark drops blank lines
            out = [None] * len(fields)
            bad = len(toks) != len(data_idx)
            for j, fi in enumerate(data_idx):
                tok = toks[j] if j < len(toks) else None
                try:
                    out[fi] = _convert_csv_field(
                        tok, fields[fi].dataType, null_value)
                except _FieldError:
                    bad = True
            if bad:
                if mode == "FAILFAST":
                    raise RuntimeError(
                        f"Malformed CSV record (FAILFAST): {raw!r}")
                if mode == "DROPMALFORMED":
                    continue
                if corrupt_idx is not None:
                    out[corrupt_idx] = raw
            rows.append(out)
    return _finish(rows, schema)


def _convert_json_value(v, dt: T.DataType):
    """One parsed JSON value -> python storage value (None on mismatch)."""
    if v is None:
        return None
    if isinstance(dt, T.StringType):
        if isinstance(v, str):
            return v
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (int, float)):
            # Spark keeps the literal number text; json round-trip is the
            # closest faithful rendering here
            return _json.dumps(v)
        return None
    if isinstance(dt, T.BooleanType):
        return v if isinstance(v, bool) else None
    if dt.is_integral:
        if isinstance(v, bool) or not isinstance(v, int):
            return None
        lo, hi = _I_RANGE[type(dt)]
        return v if lo <= v <= hi else None
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return float(v)
    if isinstance(dt, T.DecimalType):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        if isinstance(v, float) and not math.isfinite(v):
            return None
        try:
            scaled = int(Decimal(str(v)).scaleb(dt.scale).quantize(
                Decimal(1), rounding=ROUND_HALF_UP))
        except InvalidOperation:
            return None
        return scaled if abs(scaled) < 10 ** dt.precision else None
    if isinstance(dt, T.DateType):
        from spark_rapids_tpu.cpu.oracle import _str_to_date_py

        return _str_to_date_py(v) if isinstance(v, str) else None
    if isinstance(dt, T.TimestampType):
        from spark_rapids_tpu.cpu.oracle import _str_to_ts_py

        return _str_to_ts_py(v) if isinstance(v, str) else None
    return None


def _read_json_fast(path: str, schema: T.StructType, options: dict):
    """Vectorized JSON-lines fast path: pyarrow's NDJSON reader parses
    typed columns at C speed for the clean common case.  Every Spark/
    arrow semantic divergence (type coercion to null, number-to-string
    literal text, malformed lines, out-of-range...) makes arrow RAISE,
    so the strict loop still decides those files; integral range checks
    (Spark nulls out-of-range) run in numpy on the int64 parse."""
    import numpy as np

    try:
        import pyarrow as pa
        import pyarrow.json as pajson
    except ImportError:
        return None
    corrupt_col = str(options.get("columnNameOfCorruptRecord",
                                  DEFAULT_CORRUPT_COL))
    fields = schema.fields
    if any(f.name == corrupt_col for f in fields):
        return None     # malformed-record capture needs the strict loop

    def _arrow_type(dt):
        if isinstance(dt, T.StringType):
            return pa.string()
        if dt.is_integral:
            return pa.int64()   # range-checked to null below (Spark)
        if isinstance(dt, T.DoubleType):
            return pa.float64()
        if isinstance(dt, T.FloatType):
            return pa.float64()
        if isinstance(dt, T.BooleanType):
            return pa.bool_()
        return None             # date/ts/decimal/nested: strict loop

    atypes = [_arrow_type(f.dataType) for f in fields]
    if any(t is None for t in atypes):
        return None
    try:
        tbl = pajson.read_json(
            path,
            parse_options=pajson.ParseOptions(
                explicit_schema=pa.schema(
                    [(f.name, t) for f, t in zip(fields, atypes)]),
                unexpected_field_behavior="ignore"))
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, OSError):
        return None
    from spark_rapids_tpu.columnar.column import HostColumn

    cols = []
    for f in fields:
        col = tbl.column(f.name).combine_chunks()
        dt = f.dataType
        if dt.is_integral and not isinstance(dt, T.LongType):
            validity = np.asarray(col.is_valid())
            vals = np.asarray(col.fill_null(0), np.int64)
            lo, hi = _I_RANGE[type(dt)]
            validity = validity & (vals >= lo) & (vals <= hi)
            cols.append(HostColumn(
                dt, validity,
                data=np.where(validity, vals, 0).astype(
                    T.storage_dtype(dt))))
        elif isinstance(dt, T.FloatType):
            validity = np.asarray(col.is_valid())
            vals = np.asarray(col.fill_null(0.0), np.float64).astype(
                np.float32)
            cols.append(HostColumn(dt, validity, data=vals))
        else:
            cols.append(HostColumn.from_arrow(col, dt))
    return cols, tbl.num_rows


def read_json_spark(path: str, schema: T.StructType, options: dict):
    """Spark-semantic JSON-lines read; file-context annotated like the
    CSV twin."""
    from spark_rapids_tpu.io.faults import file_context

    with file_context(path, "json", "host"):
        return _read_json_spark(path, schema, options)


def _read_json_spark(path: str, schema: T.StructType, options: dict):
    """Spark-semantic JSON-lines read -> (HostColumns, row count)."""
    if str(options.get("tpuFastParse", "true")).lower() != "false":
        try:
            fast = _read_json_fast(path, schema, options)
        except Exception as e:
            from spark_rapids_tpu.resilience import classify as _CL

            if _CL.classify_failure(e) == _CL.PROPAGATE:
                # a tripped CancelToken (or ANSI-mode error) must
                # unwind, not silently degrade to the strict loop
                raise
            fast = None
        if fast is not None:
            return fast
    mode = str(options.get("mode", "PERMISSIVE")).upper()
    corrupt_col = str(options.get("columnNameOfCorruptRecord",
                                  DEFAULT_CORRUPT_COL))
    fields = schema.fields
    corrupt_idx = next((i for i, f in enumerate(fields)
                        if f.name == corrupt_col), None)
    rows = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            raw = line.rstrip("\n")
            if not raw.strip():
                continue
            out = [None] * len(fields)
            try:
                obj = _json.loads(raw)
                bad = not isinstance(obj, dict)
            except ValueError:
                obj, bad = None, True
            if not bad:
                for i, f in enumerate(fields):
                    if i == corrupt_idx:
                        continue
                    out[i] = _convert_json_value(obj.get(f.name),
                                                 f.dataType)
            if bad:
                if mode == "FAILFAST":
                    raise RuntimeError(
                        f"Malformed JSON record (FAILFAST): {raw!r}")
                if mode == "DROPMALFORMED":
                    continue
                if corrupt_idx is not None:
                    out[corrupt_idx] = raw
            rows.append(out)
    return _finish(rows, schema)
