"""Spark-strict CSV / JSON-lines parsing.

Reference analog: GpuTextBasedPartitionReader + GpuCSVScan / GpuJsonScan
(SURVEY.md §2.6 CSV/JSON row): the reference reproduces Spark's Univocity/
Jackson parse semantics in cuDF kernels; here the host parse (sanctioned by
SURVEY §2.10 item 10 — "host parse -> device, then incremental Pallas")
reproduces them in one place shared by the device pipeline and the CPU
oracle, with pinned-expectation tests guarding the semantics.

Supported semantics (the PERMISSIVE core):

  * modes: PERMISSIVE (default), DROPMALFORMED, FAILFAST
  * ``columnNameOfCorruptRecord`` (default ``_corrupt_record``): when that
    column appears in the schema, malformed records land there as the raw
    line while successfully-converted fields keep their values (Spark
    PERMISSIVE keeps partial rows)
  * CSV: header/sep/quote options; a record is malformed when its token
    count differs from the schema or any field fails conversion; empty
    tokens (== ``nullValue``, default "") are null
  * CSV field conversion is Spark-strict: integers reject decimals and
    wrap-only values, booleans are true/false (case-insensitive), date/
    timestamp use the cast grammar (expr/cast.py twin _str_to_date_py /
    _str_to_ts_py), decimals HALF_UP-quantize and range-check
  * JSON lines: a record is malformed when the line is not a JSON object;
    missing fields are null; a present field of the wrong JSON type is
    null (numbers render into string fields like Spark's literal-text
    coercion); nested values into scalar fields are null
"""
from __future__ import annotations

import json as _json
import math
from decimal import ROUND_HALF_UP, Decimal, InvalidOperation
from typing import List, Optional

from spark_rapids_tpu import types as T

DEFAULT_CORRUPT_COL = "_corrupt_record"

_I_RANGE = {T.ByteType: (-2**7, 2**7 - 1), T.ShortType: (-2**15, 2**15 - 1),
            T.IntegerType: (-2**31, 2**31 - 1),
            T.LongType: (-2**63, 2**63 - 1)}


class _FieldError(Exception):
    pass


def _convert_csv_field(tok: Optional[str], dt: T.DataType,
                       null_value: str):
    """One CSV token -> python storage value (or None); raises _FieldError
    on a Spark-invalid token."""
    if tok is None or tok == null_value:
        return None
    if isinstance(dt, T.StringType):
        return tok
    if isinstance(dt, T.BooleanType):
        low = tok.strip().lower()
        if low == "true":
            return True
        if low == "false":
            return False
        raise _FieldError(tok)
    s = tok.strip()
    if not s:
        return None
    if dt.is_integral:
        body = s[1:] if s[:1] in "+-" else s
        if not body.isdigit():
            raise _FieldError(tok)
        v = int(s)
        lo, hi = _I_RANGE[type(dt)]
        if not lo <= v <= hi:
            raise _FieldError(tok)
        return v
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        try:
            return float(s)
        except ValueError:
            raise _FieldError(tok)
    if isinstance(dt, T.DecimalType):
        try:
            d = Decimal(s)
        except InvalidOperation:
            raise _FieldError(tok)
        scaled = int(d.scaleb(dt.scale).quantize(
            Decimal(1), rounding=ROUND_HALF_UP))
        if abs(scaled) >= 10 ** dt.precision:
            raise _FieldError(tok)
        return scaled
    if isinstance(dt, T.DateType):
        from spark_rapids_tpu.cpu.oracle import _str_to_date_py

        days = _str_to_date_py(s)
        if days is None:
            raise _FieldError(tok)
        return days
    if isinstance(dt, T.TimestampType):
        from spark_rapids_tpu.cpu.oracle import _str_to_ts_py

        micros = _str_to_ts_py(s)
        if micros is None:
            raise _FieldError(tok)
        return micros
    raise _FieldError(f"unsupported CSV type {dt.simpleString}")


def _finish(rows, schema: T.StructType):
    """rows: list of per-field python value lists -> HostColumns."""
    from spark_rapids_tpu.columnar.column import HostColumn

    cols = []
    for i, f in enumerate(schema.fields):
        vals = [r[i] for r in rows]
        cols.append(HostColumn.from_pylist(vals, f.dataType))
    return cols, len(rows)


def read_csv_spark(path: str, schema: T.StructType, options: dict):
    """Spark-semantic CSV read -> (HostColumns, row count)."""
    import csv as _csv

    mode = str(options.get("mode", "PERMISSIVE")).upper()
    header = str(options.get("header", "false")).lower() == "true"
    sep = str(options.get("sep", options.get("delimiter", ",")))
    quote = str(options.get("quote", '"')) or '"'
    null_value = str(options.get("nullValue", ""))
    corrupt_col = str(options.get("columnNameOfCorruptRecord",
                                  DEFAULT_CORRUPT_COL))
    fields = schema.fields
    data_idx = [i for i, f in enumerate(fields) if f.name != corrupt_col]
    corrupt_idx = next((i for i, f in enumerate(fields)
                        if f.name == corrupt_col), None)
    rows = []

    class _RawTee:
        """Line iterator that records what csv.reader consumed, so the
        corrupt column stores the RAW record (quoting intact), not a
        re-join of the parsed tokens."""

        def __init__(self, fh):
            self.fh = fh
            self.buf = []

        def __iter__(self):
            return self

        def __next__(self):
            line = next(self.fh)
            self.buf.append(line)
            return line

        def take_raw(self):
            raw = "".join(self.buf).rstrip("\r\n")
            self.buf = []
            return raw

    with open(path, "r", encoding="utf-8", newline="") as fh:
        tee = _RawTee(fh)
        reader = _csv.reader(tee, delimiter=sep, quotechar=quote)
        for li, toks in enumerate(reader):
            raw = tee.take_raw()
            if header and li == 0:
                continue
            if not toks:
                continue  # Spark drops blank lines
            out = [None] * len(fields)
            bad = len(toks) != len(data_idx)
            for j, fi in enumerate(data_idx):
                tok = toks[j] if j < len(toks) else None
                try:
                    out[fi] = _convert_csv_field(
                        tok, fields[fi].dataType, null_value)
                except _FieldError:
                    bad = True
            if bad:
                if mode == "FAILFAST":
                    raise RuntimeError(
                        f"Malformed CSV record (FAILFAST): {raw!r}")
                if mode == "DROPMALFORMED":
                    continue
                if corrupt_idx is not None:
                    out[corrupt_idx] = raw
            rows.append(out)
    return _finish(rows, schema)


def _convert_json_value(v, dt: T.DataType):
    """One parsed JSON value -> python storage value (None on mismatch)."""
    if v is None:
        return None
    if isinstance(dt, T.StringType):
        if isinstance(v, str):
            return v
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (int, float)):
            # Spark keeps the literal number text; json round-trip is the
            # closest faithful rendering here
            return _json.dumps(v)
        return None
    if isinstance(dt, T.BooleanType):
        return v if isinstance(v, bool) else None
    if dt.is_integral:
        if isinstance(v, bool) or not isinstance(v, int):
            return None
        lo, hi = _I_RANGE[type(dt)]
        return v if lo <= v <= hi else None
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return float(v)
    if isinstance(dt, T.DecimalType):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        if isinstance(v, float) and not math.isfinite(v):
            return None
        try:
            scaled = int(Decimal(str(v)).scaleb(dt.scale).quantize(
                Decimal(1), rounding=ROUND_HALF_UP))
        except InvalidOperation:
            return None
        return scaled if abs(scaled) < 10 ** dt.precision else None
    if isinstance(dt, T.DateType):
        from spark_rapids_tpu.cpu.oracle import _str_to_date_py

        return _str_to_date_py(v) if isinstance(v, str) else None
    if isinstance(dt, T.TimestampType):
        from spark_rapids_tpu.cpu.oracle import _str_to_ts_py

        return _str_to_ts_py(v) if isinstance(v, str) else None
    return None


def read_json_spark(path: str, schema: T.StructType, options: dict):
    """Spark-semantic JSON-lines read -> (HostColumns, row count)."""
    mode = str(options.get("mode", "PERMISSIVE")).upper()
    corrupt_col = str(options.get("columnNameOfCorruptRecord",
                                  DEFAULT_CORRUPT_COL))
    fields = schema.fields
    corrupt_idx = next((i for i, f in enumerate(fields)
                        if f.name == corrupt_col), None)
    rows = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            raw = line.rstrip("\n")
            if not raw.strip():
                continue
            out = [None] * len(fields)
            try:
                obj = _json.loads(raw)
                bad = not isinstance(obj, dict)
            except ValueError:
                obj, bad = None, True
            if not bad:
                for i, f in enumerate(fields):
                    if i == corrupt_idx:
                        continue
                    out[i] = _convert_json_value(obj.get(f.name),
                                                 f.dataType)
            if bad:
                if mode == "FAILFAST":
                    raise RuntimeError(
                        f"Malformed JSON record (FAILFAST): {raw!r}")
                if mode == "DROPMALFORMED":
                    continue
                if corrupt_idx is not None:
                    out[corrupt_idx] = raw
            rows.append(out)
    return _finish(rows, schema)
