"""Device-resident hot-table cache — repeated scans skip the link.

Reference analog: the serving-tier observation in "Accelerating Presto
with GPUs" (arXiv:2606.24647) — dashboard workloads re-scan the same
slowly-changing tables — combined with the reference's
ParquetCachedBatchSerializer stance: once decoded columns sit in
accelerator memory, a repeat query should pay zero transfer.

A completed file scan registers its device batches here keyed by a
``compilecache.keys.fingerprint`` over everything that could change the
bytes produced: the file set WITH per-file (size, mtime_ns) fingerprints
(a rewritten file misses naturally), the projected column set, the
pushed-down filters, the snapshot id recorded in the scan options
(iceberg/delta MOR scans), and the reader chunking conf.  A second scan
with the same key yields the cached batches — zero H2D bytes
(``hot_cache_hits``; the tier-1 pin asserts the zero).

Memory discipline: every cached batch is registered with the spill
framework as a PERSISTENT spillable (the df.cache() semantics — it
outlives its query), so HBM pressure migrates cold entries down-tier
through the existing LRU machinery instead of OOMing, and the leak
accounting knows about every byte.  The cache itself enforces
``spark.rapids.tpu.scan.hotTableCache.maxBytes`` by closing
least-recently-used entries (``hot_cache_evictions``).
``TpuSession.close()`` (and ``clear()``) drops everything — the
session-shutdown leak gate sees an empty framework afterwards.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import List, Optional, Tuple


class _Entry:
    __slots__ = ("handles", "paths", "nbytes")

    def __init__(self, handles, paths, nbytes: int):
        self.handles = handles          # List[SpillableColumnarBatch]
        self.paths = paths              # List[str] (stamp source per batch)
        self.nbytes = nbytes


class HotTableCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0

    # -- keying ---------------------------------------------------------
    @staticmethod
    def scan_key(fmt: str, paths, columns, pushed_repr: str, options,
                 max_rows: int) -> Optional[str]:
        """Fingerprint of everything that could change scan output; None
        when a file vanished mid-keying (no caching on shaky ground)."""
        from spark_rapids_tpu.compilecache.keys import fingerprint

        stats = []
        for p in paths:
            try:
                st = os.stat(p)
                stats.append((p, st.st_size, st.st_mtime_ns))
            except OSError:
                return None
        return fingerprint(
            "hot_table_scan", fmt, tuple(stats), tuple(columns),
            pushed_repr,
            tuple(sorted((str(k), str(v))
                         for k, v in (options or {}).items())),
            int(max_rows))

    # -- lookup / insert ------------------------------------------------
    def get(self, key: str) -> Optional[List[Tuple[object, str]]]:
        """Cached (batch, path) pairs, LRU-touched; None on miss.
        Materializing may unspill (that transfer is counted normally).
        An entry racing a concurrent eviction (handle closed between
        the lock release and materialization) degrades to a miss."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            handles = list(e.handles)
            paths = list(e.paths)
        out = []
        for h, p in zip(handles, paths):
            if h.closed:
                return None
            b = h.get_batch()
            if b is None:
                return None
            out.append((b, p))
        return out

    def put(self, key: str, batches: List[Tuple[object, str]],
            max_bytes: int) -> bool:
        """Register a completed scan's batches; False when it exceeds
        ``max_bytes`` on its own (not cached)."""
        from spark_rapids_tpu import perfcounters as PC
        from spark_rapids_tpu.memory.spill import get_spill_framework

        total = sum(b.nbytes() for b, _ in batches)
        if not batches or total > max_bytes:
            return False
        # the spill framework's host tier round-trips flat + string
        # columns only: a nested (array/struct) batch would lose its
        # element buffers on a device->host migration, so such scans
        # stay uncached
        for b, _ in batches:
            for c in b.columns:
                if c.is_array or c.is_struct or c.is_string_array:
                    return False
        fw = get_spill_framework()
        handles = [fw.track(b, persistent=True) for b, _ in batches]
        paths = [p for _, p in batches]
        with self._lock:
            old = self._entries.pop(key, None)
            victims = [old] if old is not None else []
            if old is not None:
                self._bytes -= old.nbytes
            while self._bytes + total > max_bytes and self._entries:
                k, v = self._entries.popitem(last=False)
                self._bytes -= v.nbytes
                victims.append(v)
                PC.bump("hot_cache_evictions")
            self._entries[key] = _Entry(handles, paths, total)
            self._bytes += total
        for v in victims:
            for h in v.handles:
                try:
                    h.close()
                # tpulint: disable=cancel-swallow (best-effort close of
                # evicted spill handles on the non-cancellable put path)
                except Exception:
                    pass
        return True

    # -- maintenance ----------------------------------------------------
    def evict_to_bytes(self, target_bytes: int) -> int:
        """Evict LRU entries until total cached bytes <= target (the
        governor's RED-entry ballast drop, ISSUE 13); returns how many
        entries were evicted.  Handle closes happen outside the lock,
        like :meth:`put`'s eviction path."""
        from spark_rapids_tpu import perfcounters as PC

        target = max(int(target_bytes), 0)
        with self._lock:
            victims = []
            while self._bytes > target and self._entries:
                _k, v = self._entries.popitem(last=False)
                self._bytes -= v.nbytes
                victims.append(v)
                PC.bump("hot_cache_evictions")
        for v in victims:
            for h in v.handles:
                try:
                    h.close()
                # tpulint: disable=cancel-swallow (best-effort close of
                # evicted spill handles on the pressure-eviction path)
                except Exception:
                    pass
        return len(victims)

    def clear(self) -> int:
        with self._lock:
            victims = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
        n = 0
        for v in victims:
            for h in v.handles:
                n += 1
                try:
                    h.close()
                # tpulint: disable=cancel-swallow (best-effort close at
                # clear/session shutdown; must not abort the sweep)
                except Exception:
                    pass
        return n

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes}


_lock = threading.Lock()
_cache: Optional[HotTableCache] = None


def get_hot_cache() -> HotTableCache:
    global _cache
    with _lock:
        if _cache is None:
            _cache = HotTableCache()
        return _cache


def peek_hot_cache() -> Optional[HotTableCache]:
    return _cache


def clear_hot_cache() -> int:
    """Drop every cached table (session shutdown / tests)."""
    c = _cache
    return c.clear() if c is not None else 0
