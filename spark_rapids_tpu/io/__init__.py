from spark_rapids_tpu.io.scan import TpuFileSourceScanExec  # noqa: F401
