"""TPU file scans — Parquet/CSV/JSON readers with the reference's 3 modes.

Reference analog (SURVEY.md §2.6): GpuParquetScan + GpuMultiFileReader with
PERFILE / COALESCING / MULTITHREADED reader types, host-side footer parsing
and row-group pruning with predicate pushdown, then device decode.

TPU adaptation: the host decode stage uses pyarrow (footer parse, row-group
pruning, predicate pushdown, dictionary/RLE decode) on background threads —
playing the role of the reference's host-side fetch+filter threads — and the
"device decode" step is the host->HBM upload into padded columns.  A Pallas
on-device Parquet decode (dictionary/RLE/bit-pack) is the planned follow-up,
mirroring how the reference moved decode from host to cuDF kernels
(BASELINE north-star note in SURVEY.md §2.10 item 9).

Reader mode selection matches the reference:
  * PERFILE       — one file at a time, simple.
  * COALESCING    — many small files/row-groups stitched into one batch
                    before upload (fewer, larger HBM transfers).
  * MULTITHREADED — a host thread pool fetches/decodes files ahead while
    the device consumes (cloud-storage latency hiding).
  * AUTO          — MULTITHREADED for >1 file else COALESCING.

I/O fault domain (ISSUE 5, io/faults.py): every per-file read routes its
escaping errors through per-FILE classification — corrupt / truncated /
missing / schema-drifted files are skipped (with counters, an io_fault
event, and a quarantine-manifest entry) when the
``spark.sql.files.ignoreCorruptFiles`` / ``ignoreMissingFiles`` confs (or
their ``spark.rapids.tpu.files.*`` aliases) say so, and the COALESCING /
MULTITHREADED modes re-drive the surviving file set instead of aborting
the batch stitch.  A DEVICE-decode failure on one file retries that file
only on the native (host) decoder (``file_decoder_fallbacks``), and a
systematically-failing device decoder trips a per-format circuit-breaker
entry that routes the whole scan to the native decoder at plan time.
"""
from __future__ import annotations

import concurrent.futures as cf
from struct import error as struct_error
from typing import Iterator, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.config import (
    MAX_READER_BATCH_SIZE_ROWS,
    PARQUET_DEVICE_DECODE,
    PARQUET_MULTITHREAD_READ_NUM_THREADS,
    PARQUET_READER_TYPE,
    TpuConf,
)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.io import faults as IOF
from spark_rapids_tpu.plan.nodes import FileSourceScan
from spark_rapids_tpu.resilience import faults as chaos


def _filters_to_arrow(pushed) -> Optional[list]:
    """Convert pushed-down predicates to pyarrow filter tuples (row-group
    pruning; ParquetFileFilterHandler analog).  Conservative: only simple
    col-op-literal comparisons are pushed; everything else is re-checked by
    the TpuFilterExec above the scan anyway."""
    from spark_rapids_tpu.expr import base as E
    from spark_rapids_tpu.expr import predicates as P

    out = []
    for f in pushed or []:
        try:
            op_map = {P.EqualTo: "==", P.LessThan: "<",
                      P.LessThanOrEqual: "<=", P.GreaterThan: ">",
                      P.GreaterThanOrEqual: ">="}
            op = op_map.get(type(f))
            if op is None:
                continue
            l, r = f.children
            if isinstance(l, E.AttributeReference) and isinstance(r, E.Literal):
                out.append((l.colname, op, r.value))
        except Exception:
            continue
    return out or None


def read_parquet_file(path: str, columns, filters=None):
    """Single-FILE parquet read (shared with the CPU oracle and the MOR
    reader).  Without pushdown filters it bypasses pyarrow's dataset
    layer: dataset discovery infers hive partitioning from ``k=1/`` path
    segments and then fails to merge a partition column that ALSO exists
    in the file (the iceberg/delta identity-partition layout).  Missing
    columns raise a typed SchemaMismatch (ParquetFile.read would silently
    drop them)."""
    import pyarrow.parquet as pq

    if filters is not None:
        # filters need the dataset reader; partitioning=None keeps the
        # hive inference off for this single-file path too
        return pq.read_table(path, columns=columns, filters=filters,
                             partitioning=None)
    pf = pq.ParquetFile(path)
    have = set(pf.schema_arrow.names)
    missing = [c for c in (columns or []) if c not in have]
    if missing:
        raise IOF.SchemaMismatch(
            path, f"columns {missing} not in file schema "
                  f"{sorted(have)[:8]}", "parquet")
    return pf.read(columns=columns)


def _decode_breaker_key(fmt: str):
    """Per-FORMAT breaker key for the device decoder: a decoder that
    fails file after file (a systematic kernel/parser bug, not one bad
    file) should stop being tried at all — plan-time consult routes the
    format to the native decoder until the TTL re-probe."""
    return ("TpuFileSourceScanExec.deviceDecode", fmt)


class TpuFileSourceScanExec(TpuExec):
    # GpuFileSourceScanExec metric set (bufferTime/gpuDecodeTime)
    EXTRA_METRICS = {"bufferTime": "MODERATE",
                     "gpuDecodeTime": "MODERATE"}

    def __init__(self, plan: FileSourceScan, conf: TpuConf):
        super().__init__([])
        self.plan = plan
        self.conf = conf
        self.reader_type = conf.get(PARQUET_READER_TYPE).upper()
        self.num_threads = conf.get(PARQUET_MULTITHREAD_READ_NUM_THREADS)
        self.max_rows = conf.get(MAX_READER_BATCH_SIZE_ROWS)

    @property
    def output(self):
        return self.plan.output

    def describe(self):
        return (f"TpuFileSourceScan {self.plan.fmt} "
                f"{len(self.plan.paths)} files mode={self._mode()}")

    def _mode(self) -> str:
        if self.reader_type != "AUTO":
            return self.reader_type
        return "MULTITHREADED" if len(self.plan.paths) > 1 else "COALESCING"

    # -- device decode (Pallas) -----------------------------------------
    def _device_decode_conf_on(self) -> bool:
        from spark_rapids_tpu.config import ORC_DEVICE_DECODE

        if self.plan.fmt == "parquet":
            return bool(self.conf.get(PARQUET_DEVICE_DECODE))
        if self.plan.fmt == "orc":
            return bool(self.conf.get(ORC_DEVICE_DECODE))
        return False

    def _decode_breaker_open(self) -> bool:
        """True when the per-format decode breaker holds this scan on the
        native decoder (the plan-time trip of a systematically-failing
        device decoder)."""
        from spark_rapids_tpu.config import RESILIENCE_BREAKER_TTL_SEC
        from spark_rapids_tpu.resilience.breaker import get_breaker

        breaker = get_breaker()
        if not breaker.has_entries():
            return False
        why = breaker.consult(
            _decode_breaker_key(self.plan.fmt),
            float(self.conf.get(RESILIENCE_BREAKER_TTL_SEC)))
        if why is not None:
            self._log_decode_fallback("(all files)",
                                      f"decode breaker: {why}")
            return True
        return False

    def _log_decode_fallback(self, path: str, why: str) -> None:
        from spark_rapids_tpu.config import DECODE_LOG_FALLBACK

        if self.conf.get(DECODE_LOG_FALLBACK):
            import sys

            print(f"[spark-rapids-tpu] device decode fallback for "
                  f"{path}: {why}", file=sys.stderr)

    def _try_device_decode(self, path: str, file_index: int = 0,
                           blocked: bool = False):
        """Pallas decode path; None -> retry THIS FILE on the native
        (host) decoder.  An error outside the expected unsupported-subset
        set counts as a decoder failure (``file_decoder_fallbacks``) and
        feeds the per-format decode breaker; it never escalates to the
        stage fault domain — the host decoder owns the file from here.
        ``blocked`` is the per-SCAN breaker decision (consulted once in
        execute_columnar, not per file)."""
        import os

        if blocked or os.path.isdir(path):
            return None
        if not self._device_decode_conf_on():
            return None
        from spark_rapids_tpu import perfcounters as PC
        from spark_rapids_tpu.config import RESILIENCE_BREAKER_THRESHOLD
        from spark_rapids_tpu.io.parquet_native import _Unsupported
        from spark_rapids_tpu.io.parquet_device import read_parquet_device
        from spark_rapids_tpu.resilience import classify as CL
        from spark_rapids_tpu.resilience.breaker import get_breaker

        key = _decode_breaker_key(self.plan.fmt)
        try:
            chaos.check_decode_fault(self.node_name, file_index)
            with self.metric("gpuDecodeTime").timed():
                if self.plan.fmt == "orc":
                    from spark_rapids_tpu.io.orc_device import (
                        read_orc_device)

                    out = read_orc_device(path, self.plan.output)
                else:
                    out = read_parquet_device(path, self.plan.output)
        except (_Unsupported, KeyError, ValueError, IndexError,
                struct_error) as ex:
            # the documented unsupported-subset fallback: expected,
            # silent, not a decoder failure
            self._log_decode_fallback(path, f"{type(ex).__name__}: {ex}")
            return None
        except Exception as ex:
            kind = CL.classify_failure(ex)
            if kind == CL.PROPAGATE:
                raise
            if kind in (CL.TRANSIENT, CL.DEVICE_OOM):
                # infrastructure pressure, not a decoder bug: the native
                # decoder still reads this file, but the event must not
                # feed the per-format breaker or misreport a
                # systematically-failing decoder
                self._log_decode_fallback(
                    path, f"{kind} during device decode "
                          f"({type(ex).__name__}: {ex}); using native "
                          f"decoder for this file")
                return None
            if IOF.to_scan_fault(ex, path, self.plan.fmt) is not None:
                # a vanished/corrupt/drifted FILE is not a decoder
                # failure: the host path re-derives the fault and the
                # tolerance confs own it — bad data must not indict the
                # decoder (or trip its breaker)
                return None
            PC.bump("file_decoder_fallbacks")
            self.metric("fileDecoderFallbacks").add(1)
            if get_breaker().record_failure(
                    key,
                    int(self.conf.get(RESILIENCE_BREAKER_THRESHOLD)),
                    reason=f"device decode: {type(ex).__name__}: {ex}"):
                PC.bump("breaker_trips")
            self._log_decode_fallback(
                path, f"decoder FAILURE {type(ex).__name__}: {ex} "
                      f"(retrying on native decoder)")
            return None
        if get_breaker().has_entries():
            get_breaker().record_success(key)
        return out

    # -- host decode ----------------------------------------------------
    def _read_file_host(self, path: str):
        import pyarrow as pa

        import os

        with self.metric("bufferTime").timed():
            if os.path.isdir(path):
                # hive-partitioned directory: dataset read (partition
                # columns materialize from the directory names)
                import pyarrow.dataset as ds

                dset = ds.dataset(path, format=self.plan.fmt,
                                  partitioning="hive",
                                  exclude_invalid_files=True)
                tbl = dset.to_table(
                    columns=[f.name for f in self.plan.output.fields])
            elif self.plan.fmt == "parquet":
                cols = [f.name for f in self.plan.output.fields]
                tbl = read_parquet_file(
                    path, cols,
                    filters=_filters_to_arrow(self.plan.pushed_filters))
            elif self.plan.fmt == "orc":
                import pyarrow.orc as paorc

                tbl = paorc.ORCFile(path).read(
                    columns=[f.name for f in self.plan.output.fields])
            elif self.plan.fmt in ("csv", "json"):
                # Spark-strict parse (PERMISSIVE/_corrupt_record etc.) —
                # io/text.py, shared with the CPU oracle
                from spark_rapids_tpu.io.text import (read_csv_spark,
                                                      read_json_spark)

                rd = (read_csv_spark if self.plan.fmt == "csv"
                      else read_json_spark)
                cols, _ = rd(path, self.plan.output, self.plan.options)
                tbl = pa.table(
                    {f.name: c.to_arrow()
                     for f, c in zip(self.plan.output.fields, cols)})
            elif self.plan.fmt == "avro":
                from spark_rapids_tpu.io.avro import read_avro_columns

                cols, struct = read_avro_columns(path, self.plan.output)
                tbl = pa.table(
                    {f.name: c.to_arrow()
                     for f, c in zip(struct.fields, cols)})
            else:
                raise NotImplementedError(self.plan.fmt)
        return tbl

    def _read_host_checked(self, path: str, file_index: int, mode: str):
        """One per-file host read under the I/O fault domain: the chaos
        ``file_corrupt`` hook fires here, and every escaping error is
        wrapped/annotated with the file path + reader mode."""
        with IOF.file_context(path, self.plan.fmt, mode):
            chaos.check_file_fault(self.node_name, file_index, path)
            return self._read_file_host(path)

    def _table_or_skip(self, thunk, path: str, mode: str,
                       tol: IOF.ScanTolerance):
        """Run ``thunk`` (a per-file read, or a future's result) under
        the tolerate/skip contract: -> arrow table, or None when the
        file was tolerated away (counted, quarantined); raises the
        typed/annotated fault otherwise."""
        try:
            return thunk()
        except Exception as e:
            # handle_scan_error returns True (tolerated) or raises
            IOF.handle_scan_error(e, path, self.plan.fmt, mode, tol,
                                  self.conf)
            self.metric("filesSkipped").add(1)
            return None

    def _host_table_or_skip(self, path: str, file_index: int, mode: str,
                            tol: IOF.ScanTolerance):
        return self._table_or_skip(
            lambda: self._read_host_checked(path, file_index, mode),
            path, mode, tol)

    def _table_to_host_cols(self, tbl) -> List[HostColumn]:
        return [HostColumn.from_arrow(tbl.column(f.name), f.dataType)
                for f in self.plan.output.fields]

    def _upload(self, tbl) -> ColumnarBatch:
        with self.metric("gpuDecodeTime").timed():  # name kept for parity
            cols = self._table_to_host_cols(tbl)
            names = self.plan.output.field_names()
            return ColumnarBatch.from_host_columns(cols, names)

    # -- modes ----------------------------------------------------------
    @staticmethod
    def _stamp(batch: ColumnarBatch, path: str) -> ColumnarBatch:
        """Record the source file on the batch and in the process-wide
        holder (InputFileName reads them — Spark's InputFileBlockHolder
        analog; pull execution processes each batch before the next
        yield, so the holder tracks the right file)."""
        from spark_rapids_tpu.expr.misc import CURRENT_INPUT_FILE

        batch.input_file = path
        CURRENT_INPUT_FILE[0] = path
        return batch

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        mode = self._mode()
        tol = IOF.scan_tolerance(self.conf)
        # ONE breaker consult per scan (an open breaker would otherwise
        # be re-consulted and re-logged for every one of N files)
        dev_blocked = (self._device_decode_conf_on()
                       and self._decode_breaker_open())
        if mode == "PERFILE":
            for i, p in enumerate(self.plan.paths):
                dev = self._try_device_decode(p, i, dev_blocked)
                if dev is not None:
                    yield self._stamp(self._count_output(dev), p)
                    continue
                tbl = self._host_table_or_skip(p, i, mode, tol)
                if tbl is None:
                    continue
                yield self._stamp(self._count_output(
                    self._upload(tbl)), p)
        elif mode == "COALESCING":
            import pyarrow as pa

            host_paths = []
            for i, p in enumerate(self.plan.paths):
                dev = self._try_device_decode(p, i, dev_blocked)
                if dev is not None:
                    yield self._stamp(self._count_output(dev), p)
                else:
                    host_paths.append((i, p))
            # the batch stitch re-drives the SURVIVING file set: a
            # tolerated-away file drops out of the concat instead of
            # aborting it
            tbls = []
            surviving = []
            for i, p in host_paths:
                tbl = self._host_table_or_skip(p, i, mode, tol)
                if tbl is not None:
                    tbls.append(tbl)
                    surviving.append(p)
            if not tbls:
                return
            tbl = pa.concat_tables(tbls)
            one = surviving[0] if len(surviving) == 1 else ""
            for chunk in self._row_chunks(tbl):
                yield self._stamp(
                    self._count_output(self._upload(chunk)), one)
        else:  # MULTITHREADED
            with cf.ThreadPoolExecutor(self.num_threads) as pool:
                # device decode is a single-threaded device pipeline; host
                # fallbacks keep the thread pool
                host_futs = []  # (index, path, future) — dups preserved
                for i, p in enumerate(self.plan.paths):
                    dev = self._try_device_decode(p, i, dev_blocked)
                    if dev is not None:
                        yield self._stamp(self._count_output(dev), p)
                    else:
                        host_futs.append(
                            (i, p,
                             pool.submit(self._read_host_checked,
                                         p, i, mode)))
                for i, p, fut in host_futs:
                    # the pyarrow struct_error that named no file now
                    # does: the wrap happened on the pool thread, the
                    # tolerate/raise decision happens here
                    tbl = self._table_or_skip(fut.result, p, mode, tol)
                    if tbl is None:
                        continue
                    for chunk in self._row_chunks(tbl):
                        yield self._stamp(self._count_output(
                            self._upload(chunk)), p)

    def _row_chunks(self, tbl):
        n = tbl.num_rows
        if n <= self.max_rows:
            yield tbl
            return
        start = 0
        while start < n:
            yield tbl.slice(start, self.max_rows)
            start += self.max_rows
