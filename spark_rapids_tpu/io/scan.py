"""TPU file scans — Parquet/CSV/JSON readers with the reference's 3 modes.

Reference analog (SURVEY.md §2.6): GpuParquetScan + GpuMultiFileReader with
PERFILE / COALESCING / MULTITHREADED reader types, host-side footer parsing
and row-group pruning with predicate pushdown, then device decode.

TPU adaptation: the host decode stage uses pyarrow (footer parse, row-group
pruning, predicate pushdown, dictionary/RLE decode) on background threads —
playing the role of the reference's host-side fetch+filter threads — and the
"device decode" step is the host->HBM upload into padded columns.  The
Pallas on-device Parquet decode (io/parquet_device.py) replaces that upload
with a COMPRESSED-page transfer where eligible (decompress + decode at HBM
bandwidth; ``spark.rapids.sql.format.parquet.transfer.compressed``),
mirroring how the reference moved decode from host to cuDF kernels.

Transport-aware pipeline (ISSUE 6):

  * an async double-buffered H2D prefetch ring
    (``spark.rapids.tpu.scan.prefetch.depth``) overlaps the upload of
    batch N+1 with query compute on batch N for the COALESCING and
    MULTITHREADED modes — ``bytes_h2d_overlapped`` / ``prefetch_stall_ns``
    and the ``scan_prefetch`` diagnostics event expose the overlap;
  * a device-resident hot-table cache
    (``spark.rapids.tpu.scan.hotTableCache.enabled``, io/hot_cache.py)
    lets a repeated query over an unchanged table skip the
    read+decode+transfer entirely (spill-integrated, dropped at session
    close).

Reader mode selection matches the reference:
  * PERFILE       — one file at a time, simple.
  * COALESCING    — many small files/row-groups stitched into one batch
                    before upload (fewer, larger HBM transfers).
  * MULTITHREADED — a host thread pool fetches/decodes files ahead while
    the device consumes (cloud-storage latency hiding).
  * AUTO          — MULTITHREADED for >1 file else COALESCING.

I/O fault domain (ISSUE 5, io/faults.py): every per-file read routes its
escaping errors through per-FILE classification — corrupt / truncated /
missing / schema-drifted files are skipped (with counters, an io_fault
event, and a quarantine-manifest entry) when the
``spark.sql.files.ignoreCorruptFiles`` / ``ignoreMissingFiles`` confs (or
their ``spark.rapids.tpu.files.*`` aliases) say so, and the COALESCING /
MULTITHREADED modes re-drive the surviving file set instead of aborting
the batch stitch.  A DEVICE-decode failure on one file retries that file
only on the native (host) decoder (``file_decoder_fallbacks``), and a
systematically-failing device decoder trips a per-format circuit-breaker
entry that routes the whole scan to the native decoder at plan time.
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
import time
from struct import error as struct_error
from typing import Iterator, List, Optional

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.config import (
    MAX_READER_BATCH_SIZE_ROWS,
    PARQUET_DEVICE_DECODE,
    PARQUET_MULTITHREAD_READ_NUM_THREADS,
    PARQUET_READER_TYPE,
    SCAN_HOT_CACHE,
    SCAN_HOT_CACHE_MAX_BYTES,
    SCAN_PREFETCH_DEPTH,
    TpuConf,
)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.io import faults as IOF
from spark_rapids_tpu.plan.nodes import FileSourceScan
from spark_rapids_tpu.resilience import faults as chaos


def _filters_to_arrow(pushed) -> Optional[list]:
    """Convert pushed-down predicates to pyarrow filter tuples (row-group
    pruning; ParquetFileFilterHandler analog).  Conservative: only simple
    col-op-literal comparisons are pushed; everything else is re-checked by
    the TpuFilterExec above the scan anyway."""
    from spark_rapids_tpu.expr import base as E
    from spark_rapids_tpu.expr import predicates as P

    out = []
    for f in pushed or []:
        try:
            op_map = {P.EqualTo: "==", P.LessThan: "<",
                      P.LessThanOrEqual: "<=", P.GreaterThan: ">",
                      P.GreaterThanOrEqual: ">="}
            op = op_map.get(type(f))
            if op is None:
                continue
            l, r = f.children
            if isinstance(l, E.AttributeReference) and isinstance(r, E.Literal):
                out.append((l.colname, op, r.value))
        # tpulint: disable=cancel-swallow (pure expression translation;
        # an untranslatable predicate is re-checked by the filter above)
        except Exception:
            continue
    return out or None


def read_parquet_file(path: str, columns, filters=None):
    """Single-FILE parquet read (shared with the CPU oracle and the MOR
    reader).  Without pushdown filters it bypasses pyarrow's dataset
    layer: dataset discovery infers hive partitioning from ``k=1/`` path
    segments and then fails to merge a partition column that ALSO exists
    in the file (the iceberg/delta identity-partition layout).  Missing
    columns raise a typed SchemaMismatch (ParquetFile.read would silently
    drop them)."""
    import pyarrow.parquet as pq

    if filters is not None:
        # filters need the dataset reader; partitioning=None keeps the
        # hive inference off for this single-file path too
        return pq.read_table(path, columns=columns, filters=filters,
                             partitioning=None)
    pf = pq.ParquetFile(path)
    have = set(pf.schema_arrow.names)
    missing = [c for c in (columns or []) if c not in have]
    if missing:
        raise IOF.SchemaMismatch(
            path, f"columns {missing} not in file schema "
                  f"{sorted(have)[:8]}", "parquet")
    return pf.read(columns=columns)


def _decode_breaker_key(fmt: str):
    """Per-FORMAT breaker key for the device decoder: a decoder that
    fails file after file (a systematic kernel/parser bug, not one bad
    file) should stop being tried at all — plan-time consult routes the
    format to the native decoder until the TTL re-probe."""
    return ("TpuFileSourceScanExec.deviceDecode", fmt)


class TpuFileSourceScanExec(TpuExec):
    # GpuFileSourceScanExec metric set (bufferTime/gpuDecodeTime), plus
    # the ISSUE 6 transport-pipeline signals surfaced per-operator so
    # explain("analyze") shows them as per-query deltas like the other
    # operator metrics (ISSUE 7 satellite): hot-cache hit/miss,
    # overlapped H2D bytes, prefetch stall wall, and per-chunk
    # compressed->decoded decode fallbacks
    EXTRA_METRICS = {"bufferTime": "MODERATE",
                     "gpuDecodeTime": "MODERATE",
                     "hotCacheHits": "MODERATE",
                     "hotCacheMisses": "MODERATE",
                     "bytesH2DOverlapped": "MODERATE",
                     "prefetchStallTime": "MODERATE",
                     "chunkDecodeFallbacks": "MODERATE"}

    def __init__(self, plan: FileSourceScan, conf: TpuConf):
        super().__init__([])
        self.plan = plan
        self.conf = conf
        self.reader_type = conf.get(PARQUET_READER_TYPE).upper()
        self.num_threads = conf.get(PARQUET_MULTITHREAD_READ_NUM_THREADS)
        self.max_rows = conf.get(MAX_READER_BATCH_SIZE_ROWS)

    @property
    def output(self):
        return self.plan.output

    def describe(self):
        return (f"TpuFileSourceScan {self.plan.fmt} "
                f"{len(self.plan.paths)} files mode={self._mode()}")

    def _mode(self) -> str:
        if self.reader_type != "AUTO":
            return self.reader_type
        return "MULTITHREADED" if len(self.plan.paths) > 1 else "COALESCING"

    # -- device decode (Pallas) -----------------------------------------
    def _device_decode_conf_on(self) -> bool:
        from spark_rapids_tpu.config import ORC_DEVICE_DECODE

        if self.plan.fmt == "parquet":
            return bool(self.conf.get(PARQUET_DEVICE_DECODE))
        if self.plan.fmt == "orc":
            return bool(self.conf.get(ORC_DEVICE_DECODE))
        return False

    def _decode_breaker_open(self) -> bool:
        """True when the per-format decode breaker holds this scan on the
        native decoder (the plan-time trip of a systematically-failing
        device decoder)."""
        from spark_rapids_tpu.config import RESILIENCE_BREAKER_TTL_SEC
        from spark_rapids_tpu.resilience.breaker import get_breaker

        breaker = get_breaker()
        if not breaker.has_entries():
            return False
        why = breaker.consult(
            _decode_breaker_key(self.plan.fmt),
            float(self.conf.get(RESILIENCE_BREAKER_TTL_SEC)))
        if why is not None:
            self._log_decode_fallback("(all files)",
                                      f"decode breaker: {why}")
            return True
        return False

    def _log_decode_fallback(self, path: str, why: str) -> None:
        from spark_rapids_tpu.config import DECODE_LOG_FALLBACK

        if self.conf.get(DECODE_LOG_FALLBACK):
            import sys

            print(f"[spark-rapids-tpu] device decode fallback for "
                  f"{path}: {why}", file=sys.stderr)

    def _try_device_decode(self, path: str, file_index: int = 0,
                           blocked: bool = False):
        """Pallas decode path; None -> retry THIS FILE on the native
        (host) decoder.  An error outside the expected unsupported-subset
        set counts as a decoder failure (``file_decoder_fallbacks``) and
        feeds the per-format decode breaker; it never escalates to the
        stage fault domain — the host decoder owns the file from here.
        ``blocked`` is the per-SCAN breaker decision (consulted once in
        execute_columnar, not per file)."""
        import os

        if blocked or os.path.isdir(path):
            return None
        if not self._device_decode_conf_on():
            return None
        from spark_rapids_tpu import perfcounters as PC
        from spark_rapids_tpu.config import RESILIENCE_BREAKER_THRESHOLD
        from spark_rapids_tpu.io.parquet_native import _Unsupported
        from spark_rapids_tpu.io.parquet_device import read_parquet_device
        from spark_rapids_tpu.resilience import classify as CL
        from spark_rapids_tpu.resilience.breaker import get_breaker

        key = _decode_breaker_key(self.plan.fmt)
        # per-chunk compressed->decoded fallbacks happen inside
        # parquet_device without operator context; the counter delta
        # across this file's decode attributes them to this scan
        # (advisory under concurrent scans, like every TpuMetric)
        pre_chunk_falls = PC.COUNTERS.get("chunk_decode_fallbacks", 0)
        try:
            chaos.check_decode_fault(self.node_name, file_index)
            with self.metric("gpuDecodeTime").timed():
                if self.plan.fmt == "orc":
                    from spark_rapids_tpu.io.orc_device import (
                        read_orc_device)

                    out = read_orc_device(path, self.plan.output)
                else:
                    out = read_parquet_device(path, self.plan.output)
        except (_Unsupported, KeyError, ValueError, IndexError,
                struct_error) as ex:
            # the documented unsupported-subset fallback: expected,
            # silent, not a decoder failure
            self._log_decode_fallback(path, f"{type(ex).__name__}: {ex}")
            return None
        except Exception as ex:
            kind = CL.classify_failure(ex)
            if kind == CL.PROPAGATE:
                raise
            if kind in (CL.TRANSIENT, CL.DEVICE_OOM):
                # infrastructure pressure, not a decoder bug: the native
                # decoder still reads this file, but the event must not
                # feed the per-format breaker or misreport a
                # systematically-failing decoder
                self._log_decode_fallback(
                    path, f"{kind} during device decode "
                          f"({type(ex).__name__}: {ex}); using native "
                          f"decoder for this file")
                return None
            if IOF.to_scan_fault(ex, path, self.plan.fmt) is not None:
                # a vanished/corrupt/drifted FILE is not a decoder
                # failure: the host path re-derives the fault and the
                # tolerance confs own it — bad data must not indict the
                # decoder (or trip its breaker)
                return None
            PC.bump("file_decoder_fallbacks")
            self.metric("fileDecoderFallbacks").add(1)
            if get_breaker().record_failure(
                    key,
                    int(self.conf.get(RESILIENCE_BREAKER_THRESHOLD)),
                    reason=f"device decode: {type(ex).__name__}: {ex}"):
                PC.bump("breaker_trips")
            self._log_decode_fallback(
                path, f"decoder FAILURE {type(ex).__name__}: {ex} "
                      f"(retrying on native decoder)")
            return None
        falls = PC.COUNTERS.get("chunk_decode_fallbacks", 0) \
            - pre_chunk_falls
        if falls > 0:
            self.metric("chunkDecodeFallbacks").add(falls)
        if get_breaker().has_entries():
            get_breaker().record_success(key)
        return out

    # -- host decode ----------------------------------------------------
    def _read_file_host(self, path: str):
        import pyarrow as pa

        import os

        with self.metric("bufferTime").timed():
            if os.path.isdir(path):
                # hive-partitioned directory: dataset read (partition
                # columns materialize from the directory names)
                import pyarrow.dataset as ds

                dset = ds.dataset(path, format=self.plan.fmt,
                                  partitioning="hive",
                                  exclude_invalid_files=True)
                tbl = dset.to_table(
                    columns=[f.name for f in self.plan.output.fields])
            elif self.plan.fmt == "parquet":
                cols = [f.name for f in self.plan.output.fields]
                tbl = read_parquet_file(
                    path, cols,
                    filters=_filters_to_arrow(self.plan.pushed_filters))
            elif self.plan.fmt == "orc":
                import pyarrow.orc as paorc

                tbl = paorc.ORCFile(path).read(
                    columns=[f.name for f in self.plan.output.fields])
            elif self.plan.fmt in ("csv", "json"):
                # Spark-strict parse (PERMISSIVE/_corrupt_record etc.) —
                # io/text.py, shared with the CPU oracle
                from spark_rapids_tpu.io.text import (read_csv_spark,
                                                      read_json_spark)

                rd = (read_csv_spark if self.plan.fmt == "csv"
                      else read_json_spark)
                cols, _ = rd(path, self.plan.output, self.plan.options)
                tbl = pa.table(
                    {f.name: c.to_arrow()
                     for f, c in zip(self.plan.output.fields, cols)})
            elif self.plan.fmt == "avro":
                from spark_rapids_tpu.io.avro import read_avro_columns

                cols, struct = read_avro_columns(path, self.plan.output)
                tbl = pa.table(
                    {f.name: c.to_arrow()
                     for f, c in zip(struct.fields, cols)})
            else:
                raise NotImplementedError(self.plan.fmt)
        return tbl

    def _read_host_checked(self, path: str, file_index: int, mode: str):
        """One per-file host read under the I/O fault domain: the chaos
        ``file_corrupt`` hook fires here, and every escaping error is
        wrapped/annotated with the file path + reader mode."""
        with IOF.file_context(path, self.plan.fmt, mode):
            chaos.check_file_fault(self.node_name, file_index, path)
            return self._read_file_host(path)

    def _table_or_skip(self, thunk, path: str, mode: str,
                       tol: IOF.ScanTolerance):
        """Run ``thunk`` (a per-file read, or a future's result) under
        the tolerate/skip contract: -> arrow table, or None when the
        file was tolerated away (counted, quarantined); raises the
        typed/annotated fault otherwise."""
        try:
            return thunk()
        except Exception as e:
            # handle_scan_error returns True (tolerated) or raises
            IOF.handle_scan_error(e, path, self.plan.fmt, mode, tol,
                                  self.conf)
            self.metric("filesSkipped").add(1)
            return None

    def _host_table_or_skip(self, path: str, file_index: int, mode: str,
                            tol: IOF.ScanTolerance):
        return self._table_or_skip(
            lambda: self._read_host_checked(path, file_index, mode),
            path, mode, tol)

    def _table_to_host_cols(self, tbl) -> List[HostColumn]:
        return [HostColumn.from_arrow(tbl.column(f.name), f.dataType)
                for f in self.plan.output.fields]

    def _upload(self, tbl) -> ColumnarBatch:
        with self.metric("gpuDecodeTime").timed():  # name kept for parity
            cols = self._table_to_host_cols(tbl)
            names = self.plan.output.field_names()
            # transfer-wall attribution (ISSUE 6 satellite): time the
            # pad+device_put only — the arrow->HostColumn conversion
            # above is host decode, not link time
            t0 = time.perf_counter_ns()
            out = ColumnarBatch.from_host_columns(cols, names)
            PC.bump("scan_transfer_ns", time.perf_counter_ns() - t0)
            return out

    # -- modes ----------------------------------------------------------
    @staticmethod
    def _stamp(batch: ColumnarBatch, path: str) -> ColumnarBatch:
        """Record the source file on the batch and in the process-wide
        holder (InputFileName reads them — Spark's InputFileBlockHolder
        analog; pull execution processes each batch before the next
        yield, so the holder tracks the right file)."""
        from spark_rapids_tpu.expr.misc import CURRENT_INPUT_FILE

        batch.input_file = path
        CURRENT_INPUT_FILE[0] = path
        return batch

    # -- hot-table cache (ISSUE 6) --------------------------------------
    def _hot_cache_key(self) -> Optional[str]:
        from spark_rapids_tpu.io.hot_cache import HotTableCache

        return HotTableCache.scan_key(
            self.plan.fmt, self.plan.paths,
            [f.name for f in self.plan.output.fields],
            repr(_filters_to_arrow(self.plan.pushed_filters)),
            self.plan.options, self.max_rows)

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        mode = self._mode()
        tol = IOF.scan_tolerance(self.conf)
        # ONE breaker consult per scan (an open breaker would otherwise
        # be re-consulted and re-logged for every one of N files)
        dev_blocked = (self._device_decode_conf_on()
                       and self._decode_breaker_open())
        cache = key = None
        collected: Optional[list] = None
        cacheable = [True]
        if self.conf.get(SCAN_HOT_CACHE):
            from spark_rapids_tpu.io.hot_cache import get_hot_cache

            key = self._hot_cache_key()
            if key is not None:
                cache = get_hot_cache()
                hit = cache.get(key)
                if hit is not None:
                    PC.bump("hot_cache_hits")
                    self.metric("hotCacheHits").add(1)
                    for b, p in hit:
                        yield self._stamp(self._count_output(b), p)
                    return
                PC.bump("hot_cache_misses")
                self.metric("hotCacheMisses").add(1)
                collected = []

        def note_skip():
            # a tolerated-away file means this scan's output is a
            # conf-dependent SUBSET of the key's file set — never cache
            cacheable[0] = False

        for b, p in self._execute_scan(mode, tol, dev_blocked,
                                       note_skip):
            if collected is not None:
                collected.append((b, p))
            yield self._stamp(self._count_output(b), p)
        # reached ONLY on full completion: an abandoned generator
        # (limit) or an escaping fault must not publish partial output
        if cache is not None and collected is not None and cacheable[0]:
            cache.put(key, collected,
                      int(self.conf.get(SCAN_HOT_CACHE_MAX_BYTES)))

    def _execute_scan(self, mode: str, tol: IOF.ScanTolerance,
                      dev_blocked: bool, note_skip):
        """Per-mode read pipeline yielding (batch, source-path) pairs
        (stamping/caching happen in execute_columnar)."""
        if mode == "PERFILE":
            for i, p in enumerate(self.plan.paths):
                dev = self._try_device_decode(p, i, dev_blocked)
                if dev is not None:
                    yield dev, p
                    continue
                tbl = self._host_table_or_skip(p, i, mode, tol)
                if tbl is None:
                    note_skip()
                    continue
                yield self._upload(tbl), p
        elif mode == "COALESCING":
            import pyarrow as pa

            host_paths = []
            for i, p in enumerate(self.plan.paths):
                dev = self._try_device_decode(p, i, dev_blocked)
                if dev is not None:
                    yield dev, p
                else:
                    host_paths.append((i, p))
            # the batch stitch re-drives the SURVIVING file set: a
            # tolerated-away file drops out of the concat instead of
            # aborting it
            tbls = []
            surviving = []
            for i, p in host_paths:
                tbl = self._host_table_or_skip(p, i, mode, tol)
                if tbl is not None:
                    tbls.append(tbl)
                    surviving.append(p)
                else:
                    note_skip()
            if not tbls:
                return
            tbl = pa.concat_tables(tbls)
            one = surviving[0] if len(surviving) == 1 else ""

            def jobs():
                for chunk in self._row_chunks(tbl):
                    yield (lambda ch=chunk: [(self._upload(ch), one)])

            yield from self._prefetched(jobs())
        else:  # MULTITHREADED
            with cf.ThreadPoolExecutor(self.num_threads) as pool:
                # device decode is a single-threaded device pipeline; host
                # fallbacks keep the thread pool
                host_futs = []  # (index, path, future) — dups preserved
                for i, p in enumerate(self.plan.paths):
                    dev = self._try_device_decode(p, i, dev_blocked)
                    if dev is not None:
                        yield dev, p
                    else:
                        host_futs.append(
                            (i, p,
                             pool.submit(self._read_host_checked,
                                         p, i, mode)))

                def jobs():
                    for i, p, fut in host_futs:
                        # the pyarrow struct_error that named no file
                        # now does: the wrap happened on the pool
                        # thread, the tolerate/raise decision happens
                        # here.  ONE upload job per CHUNK — a per-file
                        # job would materialize whole files in HBM and
                        # defeat the bounded ring
                        tbl = self._table_or_skip(fut.result, p, mode,
                                                  tol)
                        if tbl is None:
                            note_skip()
                            continue
                        for chunk in self._row_chunks(tbl):
                            yield (lambda ch=chunk, pp=p:
                                   [(self._upload(ch), pp)])

                yield from self._prefetched(jobs())

    # -- async H2D prefetch ring (ISSUE 6) ------------------------------
    def _prefetched(self, jobs):
        """Bounded staging ring: run up to ``prefetch.depth`` upload
        jobs ahead on a staging thread so the transfer of batch N+1
        overlaps the query's compute on batch N.  Each job returns a
        list of (batch, path) pairs.  CancelToken-aware: the consumer
        wait polls the query's cooperative cancel; overlap efficiency
        lands in ``bytes_h2d_overlapped`` / ``prefetch_stall_ns`` and a
        ``scan_prefetch`` diagnostics event."""
        depth = int(self.conf.get(SCAN_PREFETCH_DEPTH))
        if depth <= 0:
            for job in jobs:
                yield from job()
            return
        from spark_rapids_tpu.diagnostics import context as DIAG_CTX
        from spark_rapids_tpu.lifecycle import check_cancel
        from spark_rapids_tpu.lifecycle.context import current as _cur
        from spark_rapids_tpu.progress import context as PROG_CTX

        stats = {"batches": 0, "overlapped_bytes": 0, "stall_ns": 0}
        ring: collections.deque = collections.deque()
        pool = cf.ThreadPoolExecutor(
            1, thread_name_prefix="srt-scan-prefetch")
        jobs_it = iter(jobs)
        # progress attribution (ISSUE 12): the owning query id is
        # captured HERE on the query thread — the staging thread has no
        # query contextvar of its own, and its decode+upload wall must
        # show up under this query, not nowhere
        _ctx = _cur()
        owner_qid = _ctx.query_id if _ctx is not None else None

        def run_job(job):
            if PROG_CTX.TRACKER is None or owner_qid is None:
                return job()
            t0 = time.perf_counter_ns()
            out = job()
            PROG_CTX.TRACKER.add_background(
                owner_qid, "scan_prefetch",
                time.perf_counter_ns() - t0)
            return out

        from spark_rapids_tpu.governor import context as _GOV

        def fill():
            # overload governor (ISSUE 13): under YELLOW/RED the ring
            # stops running ahead — speculative uploads spend exactly
            # the HBM pressure needs back; in-flight jobs still drain
            # and remaining jobs run inline on the consumer thread
            gov = _GOV.GOVERNOR
            if gov is not None and gov.pause_background():
                return
            while len(ring) < depth:
                try:
                    job = next(jobs_it)
                except StopIteration:
                    return
                ring.append(pool.submit(run_job, job))

        try:
            fill()
            while True:
                if ring:
                    fut = ring.popleft()
                    fill()
                    overlapped = fut.done()
                    if not overlapped:
                        t0 = time.perf_counter_ns()
                        while True:
                            check_cancel()
                            try:
                                items = fut.result(timeout=0.05)
                                break
                            except cf.TimeoutError:
                                continue
                        stall = time.perf_counter_ns() - t0
                        PC.bump("prefetch_stall_ns", stall)
                        self.metric("prefetchStallTime").add(stall)
                        stats["stall_ns"] += stall
                    else:
                        items = fut.result()
                else:
                    # ring empty: either the governor paused run-ahead
                    # or every job is consumed — run the next inline
                    try:
                        job = next(jobs_it)
                    except StopIteration:
                        break
                    check_cancel()
                    overlapped = False
                    items = run_job(job)
                for b, p in items:
                    stats["batches"] += 1
                    if overlapped:
                        nb = b.nbytes()
                        PC.bump("bytes_h2d_overlapped", nb)
                        self.metric("bytesH2DOverlapped").add(nb)
                        stats["overlapped_bytes"] += nb
                    yield b, p
                fill()
        finally:
            for f in ring:
                f.cancel()
            pool.shutdown(wait=True)
            rec = DIAG_CTX.RECORDER
            if rec is not None:
                rec.scan_prefetch(depth, stats["batches"],
                                  stats["overlapped_bytes"],
                                  stats["stall_ns"])

    def _row_chunks(self, tbl):
        n = tbl.num_rows
        if n <= self.max_rows:
            yield tbl
            return
        start = 0
        while start < n:
            yield tbl.slice(start, self.max_rows)
            start += self.max_rows
