"""TPU file scans — Parquet/CSV/JSON readers with the reference's 3 modes.

Reference analog (SURVEY.md §2.6): GpuParquetScan + GpuMultiFileReader with
PERFILE / COALESCING / MULTITHREADED reader types, host-side footer parsing
and row-group pruning with predicate pushdown, then device decode.

TPU adaptation: the host decode stage uses pyarrow (footer parse, row-group
pruning, predicate pushdown, dictionary/RLE decode) on background threads —
playing the role of the reference's host-side fetch+filter threads — and the
"device decode" step is the host->HBM upload into padded columns.  A Pallas
on-device Parquet decode (dictionary/RLE/bit-pack) is the planned follow-up,
mirroring how the reference moved decode from host to cuDF kernels
(BASELINE north-star note in SURVEY.md §2.10 item 9).

Reader mode selection matches the reference:
  * PERFILE       — one file at a time, simple.
  * COALESCING    — many small files/row-groups stitched into one batch
                    before upload (fewer, larger HBM transfers).
  * MULTITHREADED — a host thread pool fetches/decodes files ahead while
    the device consumes (cloud-storage latency hiding).
  * AUTO          — MULTITHREADED for >1 file else COALESCING.
"""
from __future__ import annotations

import concurrent.futures as cf
from struct import error as struct_error
from typing import Iterator, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.config import (
    MAX_READER_BATCH_SIZE_ROWS,
    PARQUET_DEVICE_DECODE,
    PARQUET_MULTITHREAD_READ_NUM_THREADS,
    PARQUET_READER_TYPE,
    TpuConf,
)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.plan.nodes import FileSourceScan


def _filters_to_arrow(pushed) -> Optional[list]:
    """Convert pushed-down predicates to pyarrow filter tuples (row-group
    pruning; ParquetFileFilterHandler analog).  Conservative: only simple
    col-op-literal comparisons are pushed; everything else is re-checked by
    the TpuFilterExec above the scan anyway."""
    from spark_rapids_tpu.expr import base as E
    from spark_rapids_tpu.expr import predicates as P

    out = []
    for f in pushed or []:
        try:
            op_map = {P.EqualTo: "==", P.LessThan: "<",
                      P.LessThanOrEqual: "<=", P.GreaterThan: ">",
                      P.GreaterThanOrEqual: ">="}
            op = op_map.get(type(f))
            if op is None:
                continue
            l, r = f.children
            if isinstance(l, E.AttributeReference) and isinstance(r, E.Literal):
                out.append((l.colname, op, r.value))
        except Exception:
            continue
    return out or None


class TpuFileSourceScanExec(TpuExec):
    # GpuFileSourceScanExec metric set (bufferTime/gpuDecodeTime)
    EXTRA_METRICS = {"bufferTime": "MODERATE",
                     "gpuDecodeTime": "MODERATE"}

    def __init__(self, plan: FileSourceScan, conf: TpuConf):
        super().__init__([])
        self.plan = plan
        self.conf = conf
        self.reader_type = conf.get(PARQUET_READER_TYPE).upper()
        self.num_threads = conf.get(PARQUET_MULTITHREAD_READ_NUM_THREADS)
        self.max_rows = conf.get(MAX_READER_BATCH_SIZE_ROWS)

    @property
    def output(self):
        return self.plan.output

    def describe(self):
        return (f"TpuFileSourceScan {self.plan.fmt} "
                f"{len(self.plan.paths)} files mode={self._mode()}")

    def _mode(self) -> str:
        if self.reader_type != "AUTO":
            return self.reader_type
        return "MULTITHREADED" if len(self.plan.paths) > 1 else "COALESCING"

    # -- device decode (Pallas) -----------------------------------------
    def _try_device_decode(self, path: str):
        """Pallas decode path; None -> fall back to the host decode."""
        import os

        from spark_rapids_tpu.config import ORC_DEVICE_DECODE

        if os.path.isdir(path):
            return None
        if self.plan.fmt == "parquet":
            if not self.conf.get(PARQUET_DEVICE_DECODE):
                return None
        elif self.plan.fmt == "orc":
            if not self.conf.get(ORC_DEVICE_DECODE):
                return None
        else:
            return None
        from spark_rapids_tpu.config import DECODE_LOG_FALLBACK
        from spark_rapids_tpu.io.parquet_native import _Unsupported
        from spark_rapids_tpu.io.parquet_device import read_parquet_device

        try:
            with self.metric("gpuDecodeTime").timed():
                if self.plan.fmt == "orc":
                    from spark_rapids_tpu.io.orc_device import (
                        read_orc_device)

                    return read_orc_device(path, self.plan.output)
                return read_parquet_device(path, self.plan.output)
        except (_Unsupported, KeyError, ValueError, IndexError,
                struct_error) as ex:
            if self.conf.get(DECODE_LOG_FALLBACK):
                import sys

                print(f"[spark-rapids-tpu] device decode fallback for "
                      f"{path}: {type(ex).__name__}: {ex}",
                      file=sys.stderr)
            return None

    # -- host decode ----------------------------------------------------
    def _read_file_host(self, path: str):
        import pyarrow as pa

        import os

        with self.metric("bufferTime").timed():
            if os.path.isdir(path):
                # hive-partitioned directory: dataset read (partition
                # columns materialize from the directory names)
                import pyarrow.dataset as ds

                dset = ds.dataset(path, format=self.plan.fmt,
                                  partitioning="hive",
                                  exclude_invalid_files=True)
                tbl = dset.to_table(
                    columns=[f.name for f in self.plan.output.fields])
            elif self.plan.fmt == "parquet":
                import pyarrow.parquet as pq

                cols = [f.name for f in self.plan.output.fields]
                tbl = pq.read_table(
                    path, columns=cols,
                    filters=_filters_to_arrow(self.plan.pushed_filters))
            elif self.plan.fmt == "orc":
                import pyarrow.orc as paorc

                tbl = paorc.ORCFile(path).read(
                    columns=[f.name for f in self.plan.output.fields])
            elif self.plan.fmt in ("csv", "json"):
                # Spark-strict parse (PERMISSIVE/_corrupt_record etc.) —
                # io/text.py, shared with the CPU oracle
                from spark_rapids_tpu.io.text import (read_csv_spark,
                                                      read_json_spark)

                rd = (read_csv_spark if self.plan.fmt == "csv"
                      else read_json_spark)
                cols, _ = rd(path, self.plan.output, self.plan.options)
                tbl = pa.table(
                    {f.name: c.to_arrow()
                     for f, c in zip(self.plan.output.fields, cols)})
            elif self.plan.fmt == "avro":
                from spark_rapids_tpu.io.avro import read_avro_columns

                cols, struct = read_avro_columns(path, self.plan.output)
                tbl = pa.table(
                    {f.name: c.to_arrow()
                     for f, c in zip(struct.fields, cols)})
            else:
                raise NotImplementedError(self.plan.fmt)
        return tbl

    def _table_to_host_cols(self, tbl) -> List[HostColumn]:
        return [HostColumn.from_arrow(tbl.column(f.name), f.dataType)
                for f in self.plan.output.fields]

    def _upload(self, tbl) -> ColumnarBatch:
        with self.metric("gpuDecodeTime").timed():  # name kept for parity
            cols = self._table_to_host_cols(tbl)
            names = self.plan.output.field_names()
            return ColumnarBatch.from_host_columns(cols, names)

    # -- modes ----------------------------------------------------------
    @staticmethod
    def _stamp(batch: ColumnarBatch, path: str) -> ColumnarBatch:
        """Record the source file on the batch and in the process-wide
        holder (InputFileName reads them — Spark's InputFileBlockHolder
        analog; pull execution processes each batch before the next
        yield, so the holder tracks the right file)."""
        from spark_rapids_tpu.expr.misc import CURRENT_INPUT_FILE

        batch.input_file = path
        CURRENT_INPUT_FILE[0] = path
        return batch

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        mode = self._mode()
        if mode == "PERFILE":
            for p in self.plan.paths:
                dev = self._try_device_decode(p)
                if dev is not None:
                    yield self._stamp(self._count_output(dev), p)
                else:
                    yield self._stamp(self._count_output(
                        self._upload(self._read_file_host(p))), p)
        elif mode == "COALESCING":
            import pyarrow as pa

            host_paths = []
            for p in self.plan.paths:
                dev = self._try_device_decode(p)
                if dev is not None:
                    yield self._stamp(self._count_output(dev), p)
                else:
                    host_paths.append(p)
            tbls = [self._read_file_host(p) for p in host_paths]
            if not tbls:
                return
            tbl = pa.concat_tables(tbls)
            one = host_paths[0] if len(host_paths) == 1 else ""
            for chunk in self._row_chunks(tbl):
                yield self._stamp(
                    self._count_output(self._upload(chunk)), one)
        else:  # MULTITHREADED
            with cf.ThreadPoolExecutor(self.num_threads) as pool:
                # device decode is a single-threaded device pipeline; host
                # fallbacks keep the thread pool
                host_futs = []  # (path, future) — duplicates preserved
                for p in self.plan.paths:
                    dev = self._try_device_decode(p)
                    if dev is not None:
                        yield self._stamp(self._count_output(dev), p)
                    else:
                        host_futs.append(
                            (p, pool.submit(self._read_file_host, p)))
                for p, fut in host_futs:
                    tbl = fut.result()
                    for chunk in self._row_chunks(tbl):
                        yield self._stamp(self._count_output(
                            self._upload(chunk)), p)

    def _row_chunks(self, tbl):
        n = tbl.num_rows
        if n <= self.max_rows:
            yield tbl
            return
        start = 0
        while start < n:
            yield tbl.slice(start, self.max_rows)
            start += self.max_rows
