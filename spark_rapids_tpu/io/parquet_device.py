"""Device-side Parquet column assembly.

Reference analog: SURVEY.md §3.4's device half — the reference hands
host-stitched row-group bytes to cuDF's decode kernels; here the host half
(io/parquet_native.py) parses footers/page headers/run headers and the
Pallas kernels (pallas/decode.py) unpack bits, expand runs, and gather
dictionaries on device.  Unsupported features raise _Unsupported and the
scan silently falls back to the pyarrow host decode (the reference's
hybrid-scan stance).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    DEFAULT_ROW_BUCKETS,
    DeviceColumn,
    round_up_bucket,
)
from spark_rapids_tpu.io.parquet_native import (
    CODEC_SNAPPY,
    CODEC_UNCOMPRESSED,
    ENC_PLAIN,
    ENC_PLAIN_DICT,
    ENC_RLE_DICT,
    TYPE_BOOLEAN,
    TYPE_BYTE_ARRAY,
    TYPE_FLOAT,
    TYPE_INT32,
    TYPE_INT64,
    _PLAIN_DTYPES,
    _Unsupported,
    read_column_pages,
    read_footer,
    split_hybrid_runs,
)
from spark_rapids_tpu.pallas.decode import (
    MAX_BIT_WIDTH,
    expand_runs,
    expand_runs_dev,
    expand_runs_host,
    unpack_bitpacked,
    unpack_bitpacked_dev,
)
from spark_rapids_tpu.pallas.decompress import (
    TooFragmented,
    raw_to_device,
    snappy_to_device,
)


class _CompressedUnsupported(Exception):
    """Page/chunk outside the compressed-transfer subset: the caller
    re-decodes the CHUNK through the decoded-transfer device path
    (``chunk_decode_fallbacks``) — correctness is identical, only the
    link bytes differ."""

_OK_TYPES = {
    TYPE_INT32: (T.IntegerType, T.DateType, T.ByteType, T.ShortType,
                 T.DecimalType),
    TYPE_INT64: (T.LongType, T.TimestampType, T.DecimalType),
    TYPE_FLOAT: (T.FloatType,),
    5: (T.DoubleType,),          # TYPE_DOUBLE
    TYPE_BOOLEAN: (T.BooleanType,),
    TYPE_BYTE_ARRAY: (T.StringType,),
}


def _check_field(info, dt: T.DataType):
    ok = _OK_TYPES.get(info.ptype)
    if ok is None or not isinstance(dt, ok):
        raise _Unsupported(
            f"column {info.name}: parquet type {info.ptype} as "
            f"{dt.simpleString}")
    if isinstance(dt, T.DecimalType) and dt.is_128:
        raise _Unsupported("decimal128 device decode")


def expand_defined(page):
    """Definition levels -> (defined bool array, ndef) — host expansion of
    the tiny 1-bit streams (shared by numeric + string pages and the ORC
    reader's PRESENT handling)."""
    from spark_rapids_tpu.perfcounters import count_h2d

    n = page.num_values
    if page.def_runs is not None:
        levels = expand_runs_host(page.def_runs, page.def_buf, n, 1)
        defined_np = levels.astype(np.bool_)
        count_h2d(defined_np.nbytes)
        return jnp.asarray(defined_np), int(defined_np.sum())
    return jnp.ones(n, jnp.bool_), n


def _page_dev_region(page) -> jax.Array:
    """Ship the page's STORED bytes across the link and return the
    decompressed region as a device uint8 array (the compressed-transfer
    entry point).  Raises for codecs outside the device-decompressible
    subset (zstd) or streams whose gather resolution has no transport
    win — the chunk then falls back to the decoded-transfer path."""
    if page.raw_values is None:
        raise _CompressedUnsupported("no stored-page bytes recorded")
    if page.raw_codec == CODEC_UNCOMPRESSED:
        return raw_to_device(page.raw_values)
    if page.raw_codec == CODEC_SNAPPY:
        # what the decoded-transfer path would ship for this page: the
        # value payload plus (when the levels live inside the region)
        # the expanded definition-level bool vector
        decoded_cost = len(page.value_buf) + (
            page.num_values if page.def_off is not None else 0)
        return snappy_to_device(page.raw_values, decoded_cost)
    raise _CompressedUnsupported(
        f"codec {page.raw_codec} has no device decompressor")


def _expand_defined_dev(page, dev_region):
    """Compressed-path twin of :func:`expand_defined`: the 1-bit levels
    expand from the DEVICE-resident decompressed region (v1 pages carry
    them inside it), so no decoded bool vector crosses the link.  The
    defined COUNT comes from the host-parsed runs — the host already
    holds the decompressed structure, so this costs neither a transfer
    nor a device sync."""
    from spark_rapids_tpu.perfcounters import count_h2d

    n = page.num_values
    if page.def_runs is None:
        return jnp.ones(n, jnp.bool_), n
    levels = expand_runs_host(page.def_runs, page.def_buf, n, 1)
    ndef = int(levels.astype(np.bool_).sum())
    if page.def_off is not None:
        lv = expand_runs_dev(page.def_runs, dev_region, page.def_off,
                             n, 1)
        return lv.astype(jnp.bool_), ndef
    # v2: levels sit uncompressed OUTSIDE the values region — host
    # expansion, decoded bool vector on the link (counted)
    defined_np = levels.astype(np.bool_)
    count_h2d(defined_np.nbytes)
    return jnp.asarray(defined_np), ndef


def scatter_present(vals, defined, ndef, n):
    """Compacted present values -> row positions (null rows zero-filled)."""
    if ndef == n:
        return vals
    pos = jnp.cumsum(defined.astype(jnp.int32)) - 1
    safe = jnp.clip(pos, 0, max(ndef - 1, 0))
    return jnp.where(defined, vals[safe],
                     jnp.zeros((), vals.dtype))


def _decode_string_page(page, cp, ndict):
    """Dictionary-encoded BYTE_ARRAY page -> (row dict indices, validity).

    The small dict page parsed on host; the per-ROW index stream expands
    on device and the chars gather happens once per file (TPU-shaped: a
    dense (rows, width) gather from the resident dict matrix)."""
    n = page.num_values
    if page.encoding not in (ENC_PLAIN_DICT, ENC_RLE_DICT):
        raise _Unsupported("PLAIN byte_array data page (host-walk only)")
    defined, ndef = expand_defined(page)
    if page.index_bit_width > MAX_BIT_WIDTH:
        raise _Unsupported(f"dictionary index width {page.index_bit_width}")
    runs = split_hybrid_runs(page.value_buf, page.index_bit_width, ndef)
    idx = expand_runs(runs, page.value_buf, ndef, page.index_bit_width)
    idx = jnp.clip(idx.astype(jnp.int32), 0, max(ndict - 1, 0))
    return scatter_present(idx, defined, ndef, n), defined


def _decode_string_page_compressed(page, cp, ndict):
    """Compressed-transfer twin of :func:`_decode_string_page`: the
    index stream expands from the device-decompressed page region.
    PLAIN byte_array pages stay outside the subset (their interleaved
    lengths force the host walk) — the chunk falls back."""
    n = page.num_values
    if page.encoding not in (ENC_PLAIN_DICT, ENC_RLE_DICT):
        raise _CompressedUnsupported(
            "PLAIN byte_array page (host-walk only)")
    if page.index_bit_width > MAX_BIT_WIDTH:
        raise _Unsupported(f"dictionary index width {page.index_bit_width}")
    dev = _page_dev_region(page)
    defined, ndef = _expand_defined_dev(page, dev)
    runs = split_hybrid_runs(page.value_buf, page.index_bit_width, ndef)
    idx = expand_runs_dev(runs, dev, page.value_off, ndef,
                          page.index_bit_width)
    idx = jnp.clip(idx.astype(jnp.int32), 0, max(ndict - 1, 0))
    return scatter_present(idx, defined, ndef, n), defined


def _decode_plain_string_page(page):
    """PLAIN BYTE_ARRAY page -> (chars matrix, lens, identity indices,
    validity) — VERDICT r3 Next #4.  The interleaved (len, bytes) layout
    forces a sequential length walk (C kernel, host_kernels.cpp); the
    char gather into the padded matrix is one vectorized numpy pass and
    the matrix uploads once like a page-local dictionary."""
    from spark_rapids_tpu.native import plain_byte_array_lens

    n = page.num_values
    defined, ndef = expand_defined(page)
    lens = plain_byte_array_lens(page.value_buf, ndef)
    buf_np = np.frombuffer(page.value_buf, np.uint8)
    starts = (4 * (np.arange(ndef, dtype=np.int64) + 1)
              + np.concatenate([[0], np.cumsum(lens[:-1], dtype=np.int64)])
              if ndef else np.zeros(0, np.int64))
    w = max(int(lens.max()) if ndef else 1, 1)
    pos = starts[:, None] + np.arange(w, dtype=np.int64)[None, :]
    inside = np.arange(w, dtype=np.int32)[None, :] < lens[:, None]
    chars = np.where(inside,
                     buf_np[np.clip(pos, 0, max(len(buf_np) - 1, 0))],
                     0).astype(np.uint8)
    idx = scatter_present(jnp.arange(max(ndef, 1), dtype=jnp.int32)[:ndef]
                          if ndef else jnp.zeros(0, jnp.int32),
                          defined, ndef, n)
    return chars, lens, idx, defined


def _decode_page(page, info, dt: T.DataType, dictionary):
    """One data page -> (values (n,), validity (n,)) device arrays."""
    from spark_rapids_tpu.perfcounters import count_h2d

    n = page.num_values
    defined, ndef = expand_defined(page)
    sdt = T.storage_dtype(dt)
    if page.encoding in (ENC_PLAIN_DICT, ENC_RLE_DICT):
        if dictionary is None:
            raise _Unsupported("dictionary page missing")
        if page.index_bit_width > MAX_BIT_WIDTH:
            raise _Unsupported(
                f"dictionary index width {page.index_bit_width}")
        runs = split_hybrid_runs(page.value_buf, page.index_bit_width,
                                 ndef)
        idx = expand_runs(runs, page.value_buf, ndef,
                          page.index_bit_width)
        count_h2d(dictionary.nbytes)
        dict_dev = jnp.asarray(dictionary)
        vals = dict_dev[jnp.clip(idx.astype(jnp.int32), 0,
                                 max(len(dictionary) - 1, 0))]
    elif page.encoding == ENC_PLAIN:
        if info.ptype == TYPE_BOOLEAN:
            vals = unpack_bitpacked(
                np.frombuffer(page.value_buf, np.uint8), 1, ndef)
        else:
            np_dt = _PLAIN_DTYPES[info.ptype]
            host_vals = np.frombuffer(page.value_buf, np_dt, count=ndef)
            count_h2d(host_vals.nbytes)
            vals = jnp.asarray(host_vals)
    else:
        raise _Unsupported(f"encoding {page.encoding}")
    vals = vals.astype(sdt)
    return scatter_present(vals, defined, ndef, n), defined


def _decode_page_compressed(page, info, dt: T.DataType, dictionary):
    """Compressed-transfer twin of :func:`_decode_page`: the page's
    STORED bytes cross the link, decompress on device
    (pallas/decompress.py), and the value stream decodes from the
    device-resident region — bit-unpack + run expansion via the Pallas
    kernels, PLAIN numerics via a device bitcast."""
    from spark_rapids_tpu.perfcounters import count_h2d

    n = page.num_values
    dev = _page_dev_region(page)
    defined, ndef = _expand_defined_dev(page, dev)
    sdt = T.storage_dtype(dt)
    if page.encoding in (ENC_PLAIN_DICT, ENC_RLE_DICT):
        if dictionary is None:
            raise _Unsupported("dictionary page missing")
        if page.index_bit_width > MAX_BIT_WIDTH:
            raise _Unsupported(
                f"dictionary index width {page.index_bit_width}")
        runs = split_hybrid_runs(page.value_buf, page.index_bit_width,
                                 ndef)
        idx = expand_runs_dev(runs, dev, page.value_off, ndef,
                              page.index_bit_width)
        count_h2d(dictionary.nbytes)
        dict_dev = jnp.asarray(dictionary)
        vals = dict_dev[jnp.clip(idx.astype(jnp.int32), 0,
                                 max(len(dictionary) - 1, 0))]
    elif page.encoding == ENC_PLAIN:
        if info.ptype == TYPE_BOOLEAN:
            vals = unpack_bitpacked_dev(
                dev[page.value_off:], 1, ndef)
        else:
            np_dt = _PLAIN_DTYPES[info.ptype]
            isz = np.dtype(np_dt).itemsize
            lo = page.value_off
            region = dev[lo:lo + ndef * isz]
            if int(region.shape[0]) < ndef * isz:
                raise _Unsupported("PLAIN value region short")
            vals = jax.lax.bitcast_convert_type(
                region.reshape(ndef, isz) if ndef else
                region.reshape(0, isz), np_dt)
    else:
        raise _Unsupported(f"encoding {page.encoding}")
    vals = vals.astype(sdt)
    return scatter_present(vals, defined, ndef, n), defined


def read_parquet_device(path: str, schema: T.StructType,
                        row_buckets=DEFAULT_ROW_BUCKETS) -> ColumnarBatch:
    """One file -> one padded device batch via the Pallas decode path.
    Escaping errors carry ``file=<path>`` context (io/faults.py) so a
    decoder failure in a multi-file scan is attributable."""
    from spark_rapids_tpu.io.faults import file_context

    with file_context(path, "parquet", "device"):
        return _read_parquet_device(path, schema, row_buckets)


def _decode_string_chunk(f, cp, use_compressed: bool):
    """One string column chunk -> (vals, valids, dicts).

    dict-encoded pages share the row group's dictionary; PLAIN pages
    (incl. parquet's dict-overflow spill) carry page-local char matrices
    — entries appended in row order so the assembly's base offsets line
    up."""
    vals: List = []
    valids: List = []
    dicts: List = []
    pending_dict_rows = 0
    for page in cp.pages:
        if page.encoding in (ENC_PLAIN_DICT, ENC_RLE_DICT):
            if cp.dict_chars is None:
                raise _Unsupported(
                    f"column {cp.info.name}: dictionary page missing")
            ndict = cp.dict_chars.shape[0]
            if use_compressed:
                idx, ok = _decode_string_page_compressed(page, cp, ndict)
            else:
                idx, ok = _decode_string_page(page, cp, ndict)
            pending_dict_rows += page.num_values
        elif page.encoding == ENC_PLAIN:
            if use_compressed:
                raise _CompressedUnsupported(
                    "PLAIN byte_array page (host-walk only)")
            if pending_dict_rows:
                dicts.append((cp.dict_chars, cp.dict_lens,
                              pending_dict_rows))
                pending_dict_rows = 0
            chars, lens2, idx, ok = _decode_plain_string_page(page)
            dicts.append((chars, lens2, page.num_values))
        else:
            raise _Unsupported(f"byte_array encoding {page.encoding}")
        vals.append(idx)
        valids.append(ok)
    if pending_dict_rows:
        dicts.append((cp.dict_chars, cp.dict_lens, pending_dict_rows))
    return vals, valids, dicts


def _decode_numeric_chunk(f, info, cp, use_compressed: bool):
    vals: List = []
    valids: List = []
    for page in cp.pages:
        if use_compressed:
            v, ok = _decode_page_compressed(page, info, f.dataType,
                                            cp.dictionary)
        else:
            v, ok = _decode_page(page, info, f.dataType, cp.dictionary)
        vals.append(v)
        valids.append(ok)
    return vals, valids


def _compressed_transfer_on() -> bool:
    from spark_rapids_tpu.config import (PARQUET_COMPRESSED_TRANSFER,
                                         get_conf)

    return bool(get_conf().get(PARQUET_COMPRESSED_TRANSFER))


def _chunk_compressed_eligible(cp, is_string: bool) -> bool:
    """Metadata pre-pass: every page of the chunk must sit inside the
    compressed-transfer subset BEFORE any bytes ship — a mid-chunk
    unsupported page discovered after uploading its predecessors would
    pay the link twice (once compressed, once decoded on the retry)."""
    for page in cp.pages:
        if page.raw_values is None:
            return False
        if page.raw_codec not in (CODEC_UNCOMPRESSED, CODEC_SNAPPY):
            return False
        if is_string and page.encoding not in (ENC_PLAIN_DICT,
                                               ENC_RLE_DICT):
            return False
    return True


def _read_parquet_device(path: str, schema: T.StructType,
                         row_buckets=DEFAULT_ROW_BUCKETS) -> ColumnarBatch:
    from spark_rapids_tpu import perfcounters as PC

    with open(path, "rb") as f:
        data = f.read()
    groups, names = read_footer(data)
    wanted = schema.field_names()
    for w in wanted:
        if w not in names:
            raise _Unsupported(f"column {w} missing from file")
    total = sum(g.num_rows for g in groups)
    cap = round_up_bucket(max(total, 1), row_buckets)
    compressed = _compressed_transfer_on()
    per_field_vals: List[List] = [[] for _ in wanted]
    per_field_valid: List[List] = [[] for _ in wanted]
    # string columns: dict char matrices per (field, row-group)
    per_field_dicts: List[List] = [[] for _ in wanted]
    for g in groups:
        by_name = {c.name: c for c in g.columns}
        for fi, f in enumerate(schema.fields):
            info = by_name.get(f.name)
            if info is None:
                raise _Unsupported(f"column {f.name} missing in row group")
            _check_field(info, f.dataType)
            cp = read_column_pages(data, info, g.num_rows)
            # compressed transfer first, falling back PER CHUNK to the
            # decoded-transfer path when any page sits outside the
            # device-decompressible subset (zstd, PLAIN byte_array,
            # no-transport-win streams) — same bits, heavier link.
            # Statically-knowable ineligibility (codec/encoding) is
            # decided from the page headers before any bytes ship; the
            # try/except handles the data-dependent cases
            # (no-transport-win snappy streams)
            is_str = isinstance(f.dataType, T.StringType)
            use_comp = compressed and _chunk_compressed_eligible(
                cp, is_str)
            if compressed and not use_comp:
                PC.bump("chunk_decode_fallbacks")
            while True:
                try:
                    if isinstance(f.dataType, T.StringType):
                        vals, valids, dicts = _decode_string_chunk(
                            f, cp, use_comp)
                        per_field_dicts[fi].extend(dicts)
                    else:
                        vals, valids = _decode_numeric_chunk(
                            f, info, cp, use_comp)
                    break
                except (_CompressedUnsupported, TooFragmented):
                    if not use_comp:
                        raise
                    use_comp = False
                    PC.bump("chunk_decode_fallbacks")
            per_field_vals[fi].extend(vals)
            per_field_valid[fi].extend(valids)
    cols = []
    for fi, f in enumerate(schema.fields):
        vals = jnp.concatenate(per_field_vals[fi]) \
            if len(per_field_vals[fi]) > 1 else per_field_vals[fi][0]
        valid = jnp.concatenate(per_field_valid[fi]) \
            if len(per_field_valid[fi]) > 1 else per_field_valid[fi][0]
        valid_arr = jnp.zeros(cap, jnp.bool_).at[:valid.shape[0]].set(valid)
        if isinstance(f.dataType, T.StringType):
            cols.append(_assemble_string_col(
                f.dataType, per_field_dicts[fi], vals, valid_arr, cap))
            continue
        sdt = T.storage_dtype(f.dataType)
        data_arr = jnp.zeros(cap, sdt).at[:vals.shape[0]].set(vals)
        cols.append(DeviceColumn(f.dataType, valid_arr, data=data_arr))
    return ColumnarBatch(cols, total, schema)


def _assemble_string_col(dt, dicts, idx, valid_arr, cap):
    """Row dict-indices + per-row-group dictionaries -> one padded string
    column: stack the dictionaries (offsetting indices per row group) and
    gather the char matrix on device."""
    from spark_rapids_tpu.columnar.column import (DEFAULT_WIDTH_BUCKETS,
                                                  round_up_bucket)

    from spark_rapids_tpu.perfcounters import count_h2d

    w = round_up_bucket(
        max(max(d[0].shape[1] for d in dicts), 1), DEFAULT_WIDTH_BUCKETS)
    parts = []
    lens = []
    base = 0
    bases = []
    for chars, ln, nrows in dicts:
        padded = np.zeros((chars.shape[0], w), np.uint8)
        padded[:, :chars.shape[1]] = chars
        parts.append(padded)
        lens.append(ln)
        bases.append((base, nrows))
        base += chars.shape[0]
    chars_np = np.concatenate(parts, axis=0)
    lens_np = np.concatenate(lens)
    count_h2d(chars_np.nbytes + lens_np.nbytes)
    all_chars = jnp.asarray(chars_np)
    all_lens = jnp.asarray(lens_np)
    # offset each row group's indices into the stacked dictionary
    offs = np.zeros(int(idx.shape[0]), np.int32)
    pos = 0
    for b, nrows in bases:
        offs[pos:pos + nrows] = b
        pos += nrows
    count_h2d(4 * int(idx.shape[0]))
    gidx = idx + jnp.asarray(offs[: int(idx.shape[0])])
    full_idx = jnp.zeros(cap, jnp.int32).at[: gidx.shape[0]].set(gidx)
    chars = all_chars[full_idx]
    lengths = jnp.where(valid_arr, all_lens[full_idx], 0).astype(jnp.int32)
    return DeviceColumn(dt, valid_arr, chars=chars, lengths=lengths)
