"""Native ORC stripe reader — host metadata parse + device RLEv2 decode.

Reference analog: GpuOrcScan (SURVEY.md §2.6): the reference parses ORC
footers on the host and decodes stripes with cuDF kernels.  This module is
the TPU twin: a minimal protobuf reader walks PostScript/Footer/
StripeFooter, the host splits RLEv2 runs (O(#runs)), and the values decode
on device — DIRECT runs ride the SAME Pallas bit-unpack kernel as parquet
(ORC packs MSB-first: bytes bit-reverse on the host via one vectorized
table lookup, the kernel unpacks LSB-first, and the W-bit values
bit-reverse back on device), DELTA runs unpack + cumsum on device,
SHORT_REPEAT runs are device fills.

Supported subset (else _Unsupported -> silent pyarrow host fallback, the
parquet reader's stance): flat INT/SHORT/LONG/DATE (RLEv2 signed),
FLOAT/DOUBLE (raw IEEE) columns with optional PRESENT streams,
UNCOMPRESSED or ZLIB compression, DIRECT widths <= 24, no PATCHED_BASE,
no strings/timestamps/booleans/nested types, no dictionary encodings.
"""
from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.io.parquet_native import _Unsupported

MAGIC = b"ORC"

# protobuf wire types
_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5

# ORC type kinds
K_BOOLEAN, K_BYTE, K_SHORT, K_INT, K_LONG = 0, 1, 2, 3, 4
K_FLOAT, K_DOUBLE, K_STRING, K_BINARY, K_TIMESTAMP = 5, 6, 7, 8, 9
K_STRUCT, K_DATE = 12, 15

# stream kinds
S_PRESENT, S_DATA, S_LENGTH, S_DICT = 0, 1, 2, 3

# RLEv2 direct-width code table (spec fig.)
_WIDTH_TABLE = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16,
                17, 18, 19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48,
                56, 64]

_BYTE_REV = np.array(
    [int(f"{b:08b}"[::-1], 2) for b in range(256)], np.uint8)


def _pb_fields(buf: bytes):
    """One protobuf message -> {field: value-or-list} (uint varints;
    length-delimited as raw bytes)."""
    out: Dict[int, list] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wt = key >> 3, key & 7
        if wt == _WT_VARINT:
            v = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
        elif wt == _WT_LEN:
            ln = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == _WT_I64:
            v = struct.unpack_from("<q", buf, pos)[0]
            pos += 8
        elif wt == _WT_I32:
            v = struct.unpack_from("<i", buf, pos)[0]
            pos += 4
        else:
            raise _Unsupported(f"protobuf wire type {wt}")
        out.setdefault(field, []).append(v)
    return out


def _one(fields, k, default=None):
    v = fields.get(k)
    return v[0] if v else default


def _varints(fields, k) -> List[int]:
    """Repeated uint field: plain varints and/or PACKED blobs."""
    out: List[int] = []
    for v in fields.get(k, []):
        if isinstance(v, int):
            out.append(v)
        else:
            pos = 0
            while pos < len(v):
                x, pos = _varint(v, pos)
                out.append(x)
    return out


def _decompress_stream(buf: bytes, compression: int) -> bytes:
    """ORC stream bytes -> decompressed (3-byte chunk headers for zlib)."""
    if compression == 0:  # NONE
        return buf
    if compression != 1:  # 1 = ZLIB
        raise _Unsupported(f"orc compression kind {compression}")
    out = bytearray()
    pos = 0
    while pos + 3 <= len(buf):
        h = buf[pos] | (buf[pos + 1] << 8) | (buf[pos + 2] << 16)
        pos += 3
        ln = h >> 1
        original = h & 1
        chunk = buf[pos:pos + ln]
        pos += ln
        out += chunk if original else zlib.decompress(chunk, -15)
    return bytes(out)


@dataclasses.dataclass
class OrcColumn:
    name: str
    kind: int
    col_id: int


@dataclasses.dataclass
class OrcStripe:
    offset: int
    index_len: int
    data_len: int
    footer_len: int
    num_rows: int


def read_orc_meta(data: bytes):
    """-> (columns, stripes, compression, num_rows)."""
    if not data.startswith(MAGIC):
        raise _Unsupported("not an ORC file")
    if len(data) < 4:
        err = ValueError("ORC file truncated (no postscript)")
        err.srt_offset = len(data)
        raise err
    try:
        return _read_orc_meta(data)
    except (IndexError, struct.error) as e:
        # byte-offset context for the fault classifier / quarantine
        err = ValueError(
            f"corrupt ORC postscript/footer near byte {len(data) - 1} "
            f"({type(e).__name__}: {e})")
        err.srt_offset = len(data) - 1
        raise err from e


def _read_orc_meta(data: bytes):
    ps_len = data[-1]
    ps = _pb_fields(data[-1 - ps_len:-1])
    footer_len = _one(ps, 1, 0)
    compression = _one(ps, 2, 0)
    footer_raw = data[-1 - ps_len - footer_len:-1 - ps_len]
    footer = _pb_fields(_decompress_stream(footer_raw, compression))
    types = [
        _pb_fields(t) for t in footer.get(4, [])]
    if not types or _one(types[0], 1, -1) != K_STRUCT:
        raise _Unsupported("orc root type is not a struct")
    root = types[0]
    sub = _varints(root, 2)
    names = [n.decode() for n in root.get(3, [])]
    if len(sub) != len(names):
        raise _Unsupported("orc schema shape")
    cols = [OrcColumn(nm, _one(types[cid], 1, -1), cid)
            for nm, cid in zip(names, sub)]
    stripes = [OrcStripe(_one(s, 1, 0), _one(s, 2, 0), _one(s, 3, 0),
                         _one(s, 4, 0), _one(s, 5, 0))
               for s in (_pb_fields(raw) for raw in footer.get(3, []))]
    return cols, stripes, compression, _one(footer, 6, 0)


# ---------------------------------------------------------------------------
# RLEv2 run splitting (host, O(#runs))
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RleV2Run:
    kind: str            # "repeat" | "direct" | "delta"
    count: int
    value: int = 0       # repeat value (already sign-decoded)
    width: int = 0       # packed width (direct / delta remainder)
    payload: bytes = b""
    base: int = 0        # delta
    delta0: int = 0      # delta


def _varint(buf, pos):
    v = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


def _zz(v):
    return (v >> 1) ^ -(v & 1)


def split_rlev2_runs(buf: bytes, signed: bool,
                     total: int) -> List[RleV2Run]:
    runs: List[RleV2Run] = []
    pos = 0
    got = 0
    while got < total and pos < len(buf):
        h = buf[pos]
        enc = h >> 6
        if enc == 0:  # SHORT_REPEAT
            nbytes = ((h >> 3) & 0x7) + 1
            cnt = (h & 0x7) + 3
            pos += 1
            v = int.from_bytes(buf[pos:pos + nbytes], "big")
            pos += nbytes
            if signed:
                v = _zz(v)
            runs.append(RleV2Run("repeat", cnt, value=v))
            got += cnt
        elif enc == 1:  # DIRECT
            w = _WIDTH_TABLE[(h >> 1) & 0x1F]
            cnt = (((h & 1) << 8) | buf[pos + 1]) + 1
            pos += 2
            nbytes = (cnt * w + 7) // 8
            runs.append(RleV2Run("direct", cnt, width=w,
                                 payload=buf[pos:pos + nbytes]))
            pos += nbytes
            got += cnt
        elif enc == 3:  # DELTA
            wcode = (h >> 1) & 0x1F
            w = 0 if wcode == 0 else _WIDTH_TABLE[wcode]
            cnt = (((h & 1) << 8) | buf[pos + 1]) + 1
            pos += 2
            b, pos = _varint(buf, pos)
            base = _zz(b) if signed else b
            d0, pos = _varint(buf, pos)
            delta0 = _zz(d0)
            nbytes = 0
            payload = b""
            if w and cnt > 2:
                nbytes = ((cnt - 2) * w + 7) // 8
                payload = buf[pos:pos + nbytes]
                pos += nbytes
            runs.append(RleV2Run("delta", cnt, width=w, payload=payload,
                                 base=base, delta0=delta0))
            got += cnt
        else:  # PATCHED_BASE
            raise _Unsupported("rlev2 PATCHED_BASE")
    return runs


def _unpack_direct(payload: bytes, width: int, count: int):
    """MSB-first W-bit packed payload -> (count,) int64 on device.

    Widths <= 24 ride the parquet Pallas kernel (byte bit-reverse on host,
    W-bit value reverse on device); byte-aligned wide widths (32/40/48/
    56/64) assemble big-endian bytes with one XLA weighted sum; the odd
    wide widths (26/28/30) fall back."""
    import jax.numpy as jnp

    from spark_rapids_tpu.pallas.decode import MAX_BIT_WIDTH, unpack_bitpacked

    if width <= MAX_BIT_WIDTH:
        rev = _BYTE_REV[np.frombuffer(payload, np.uint8)]
        raw = unpack_bitpacked(rev, width, count)
        v = jnp.zeros_like(raw)
        for k in range(width):
            v = v | (((raw >> jnp.uint32(k)) & jnp.uint32(1))
                     << jnp.uint32(width - 1 - k))
        return v.astype(jnp.uint64)
    if width % 8:
        raise _Unsupported(f"rlev2 direct width {width}")
    nb = width // 8
    buf = np.zeros(count * nb, np.uint8)
    raw = np.frombuffer(payload, np.uint8)
    buf[:min(len(raw), len(buf))] = raw[:len(buf)]
    mat = jnp.asarray(buf).reshape(count, nb).astype(jnp.uint64)
    acc = jnp.zeros(count, jnp.uint64)
    for k in range(nb):  # big-endian bytes
        acc = (acc << jnp.uint64(8)) | mat[:, k]
    return acc


def _zz_device(u):
    """Zigzag decode in uint64 space (logical shift), then reinterpret."""
    import jax.numpy as jnp

    dec = (u >> jnp.uint64(1)) ^ (jnp.uint64(0) - (u & jnp.uint64(1)))
    return dec.view(jnp.int64)


def expand_rlev2(runs: List[RleV2Run], signed: bool, total: int):
    """Runs -> (total,) int64 device array.

    DIRECT payloads bit-reverse per byte on the host (one vectorized table
    lookup) so the parquet LSB-first Pallas kernel applies; the W-bit
    values bit-reverse back on device."""
    import jax.numpy as jnp

    from spark_rapids_tpu.pallas.decode import MAX_BIT_WIDTH, unpack_bitpacked

    parts = []
    got = 0
    for r in runs:
        take = min(r.count, total - got)
        if take <= 0:
            break
        if r.kind == "repeat":
            parts.append(jnp.full(take, r.value, jnp.int64))
        elif r.kind == "direct":
            u = _unpack_direct(r.payload, r.width, r.count)[:take]
            parts.append(_zz_device(u) if signed else u.view(jnp.int64))
        else:  # delta
            if r.width > MAX_BIT_WIDTH:
                raise _Unsupported(f"rlev2 delta width {r.width}")
            sign = 1 if r.delta0 >= 0 else -1
            if r.count <= 1:
                parts.append(jnp.full(take, r.base, jnp.int64))
                got += take
                continue
            if r.width:
                deltas = _unpack_direct(
                    r.payload, r.width, r.count - 2).view(jnp.int64) * sign
            else:
                deltas = jnp.full(r.count - 2, r.delta0, jnp.int64)
            seq = jnp.concatenate([
                jnp.asarray([r.base, r.base + r.delta0], jnp.int64),
                jnp.asarray([r.base + r.delta0], jnp.int64)
                + jnp.cumsum(deltas)])
            parts.append(seq[:take])
        got += take
    import jax.numpy as jnp2

    if not parts:
        return jnp2.zeros(total, jnp2.int64)
    out = jnp2.concatenate(parts) if len(parts) > 1 else parts[0]
    if out.shape[0] < total:
        out = jnp2.concatenate(
            [out, jnp2.zeros(total - out.shape[0], jnp2.int64)])
    return out[:total]


def expand_present(buf: bytes, total: int) -> np.ndarray:
    """Byte-RLE boolean PRESENT stream -> (total,) bool (host: tiny)."""
    bits = []
    pos = 0
    need_bytes = (total + 7) // 8
    while pos < len(buf) and len(bits) < need_bytes:
        h = buf[pos]
        pos += 1
        if h < 128:  # run of h+3 copies of next byte
            bits.extend([buf[pos]] * (h + 3))
            pos += 1
        else:  # 256-h literal bytes
            n = 256 - h
            bits.extend(buf[pos:pos + n])
            pos += n
    arr = np.array(bits[:need_bytes], np.uint8)
    return np.unpackbits(arr, bitorder="big")[:total].astype(np.bool_)
